"""Checkpoint / resume for training state and plan artifacts.

The reference has no checkpointing at all (SURVEY.md §5 "Checkpoint / resume —
Absent"; its planner output is an ephemeral stdout ranking).  Two durable
artifacts live here:

1. **The chosen plan** — ``PlanArtifact`` (execution.mesh) serialized next to
   the weights.  A plan is the "checkpoint of the search": re-planning is
   cheap, but the artifact pins exactly which mesh/shardings a run used, so
   resume never silently retrains under a different layout.
2. **Training state** — params + optax state + step via orbax, the TPU-native
   checkpointer: sharded arrays are written per-shard (each host/device
   writes its own slice — no gather through host 0) and restored directly
   onto the target ``NamedSharding``s, so a checkpoint written on one mesh
   restores onto another (e.g. elastic re-plan after a topology change,
   planner/replan.py) without a resharding pass through host memory.
"""
from __future__ import annotations

import hashlib
import json
import shutil
from dataclasses import dataclass, field
from pathlib import Path

import jax
import numpy as np
import orbax.checkpoint as ocp
from jax.sharding import Mesh, NamedSharding

from metis_tpu.core.errors import CheckpointCorruptError, CheckpointWriteError
from metis_tpu.execution.mesh import PlanArtifact
from metis_tpu.execution.train import TrainState

_STATE_DIR = "state"
_PLAN_FILE = "plan.json"
_META_FILE = "meta.json"


@dataclass(frozen=True)
class CheckpointMeta:
    """Sidecar metadata — enough to sanity-check a resume.

    ``block_layout`` records the physical ordering of the stacked block
    axis: "canonical", or "interleaved:<pp>x<vs>" for the interleaved pipeline
    schedule's device-major chunk permutation
    (``execution.pipeline.interleave_block_order``) — restoring a permuted
    checkpoint under a different schedule would silently scramble the
    layers, so resume must compare this field.

    ``digests`` maps each state-tree leaf path to a sha256 of its logical
    content (shape + dtype + bytes, mesh-independent — a cross-mesh restore
    of the same values verifies clean).  Restore recomputes and compares;
    a mismatch raises :class:`CheckpointCorruptError` and triggers the
    ``.prev`` fallback instead of silently training on garbage."""

    step: int
    mesh_axes: tuple[str, ...]
    mesh_shape: tuple[int, ...]
    block_layout: str = "canonical"
    digests: dict[str, str] = field(default_factory=dict)

    def to_json(self) -> str:
        return json.dumps({
            "step": self.step,
            "mesh_axes": list(self.mesh_axes),
            "mesh_shape": list(self.mesh_shape),
            "block_layout": self.block_layout,
            "digests": self.digests,
        }, indent=2)

    @staticmethod
    def from_json(payload: str) -> "CheckpointMeta":
        d = json.loads(payload)
        return CheckpointMeta(
            step=d["step"],
            mesh_axes=tuple(d["mesh_axes"]),
            mesh_shape=tuple(d["mesh_shape"]),
            block_layout=d.get("block_layout", "canonical"),
            digests=dict(d.get("digests", {})),
        )


def _tree_digests(tree) -> dict[str, str]:
    """Leaf path -> sha256 of (shape, dtype, content bytes) for every array
    leaf.  Gathers each array to host (``device_get``), so the digest is a
    property of the logical value, not its sharding.  Multi-host runs skip
    digests (non-addressable shards cannot be gathered here) — single-host
    is where CI drills and the corruption-fallback story live."""
    if jax.process_count() > 1:
        return {}
    out: dict[str, str] = {}
    leaves, _ = jax.tree_util.tree_flatten_with_path(tree)
    for path, leaf in leaves:
        arr = np.asarray(jax.device_get(leaf))
        h = hashlib.sha256()
        h.update(str(arr.shape).encode())
        h.update(str(arr.dtype).encode())
        h.update(np.ascontiguousarray(arr).tobytes())
        out[jax.tree_util.keystr(path)] = h.hexdigest()
    return out


def _verify_digests(directory: Path, tree, meta: CheckpointMeta) -> None:
    """Raise :class:`CheckpointCorruptError` when a restored leaf's content
    digest disagrees with the one recorded at save.  Checkpoints without
    recorded digests (older, or multi-host saves) verify vacuously."""
    if not meta.digests:
        return
    actual = _tree_digests(tree)
    if not actual:  # multi-host restore: digests not computable here
        return
    bad = sorted(k for k, v in meta.digests.items() if actual.get(k) != v)
    if bad:
        shown = ", ".join(bad[:3]) + ("..." if len(bad) > 3 else "")
        raise CheckpointCorruptError(
            f"checkpoint {directory}: content digest mismatch for "
            f"{len(bad)} leaf/leaves ({shown}) — the checkpoint on disk "
            "is corrupt")


def save_checkpoint(
    directory: str | Path,
    state: TrainState,
    mesh: Mesh,
    plan: PlanArtifact | None = None,
    block_layout: str = "canonical",
    keep_prev: bool = False,
) -> Path:
    """Write state (+ optional plan artifact) under ``directory``.

    Crash-safe overwrite: the new checkpoint is fully written into a ``.tmp``
    sibling first, the previous checkpoint is parked at ``.prev`` during the
    swap, and ``restore_checkpoint``/``load_meta`` fall back to ``.prev`` if a
    crash leaves the primary missing — at every instant one complete
    checkpoint is on disk.  Synchronous — returns when the swap is done."""
    directory = Path(directory).absolute()
    # Multi-host: the tmp-dir (re)creation, the meta/plan writes, and the
    # final swap are plain filesystem surgery on the shared directory — one
    # host performs each, fenced by barriers (no host enters the orbax save
    # before tmp exists; none returns mid-swap).  Ordering invariant: never
    # delete the only complete checkpoint — .prev is cleared early only when
    # the primary exists (to make room for the park), and cleared finally
    # only after the new primary is in place.
    tmp, prev, multi_host = _prepare_tmp(directory)
    tree = _state_tree(state)
    with ocp.PyTreeCheckpointer() as ckptr:
        ckptr.save(tmp / _STATE_DIR, tree, force=True)
    _write_meta_and_plan(
        tmp, _mesh_meta(state, mesh, block_layout, _tree_digests(tree)), plan)
    _swap_tmp_into_place(directory, tmp, prev, multi_host,
                         keep_prev=keep_prev)
    return directory


def _state_tree(state: TrainState) -> dict:
    """THE serialized schema — shared by the sync and async writers so the
    restore path always matches."""
    return {"params": state.params, "opt_state": state.opt_state,
            "step": state.step}


def _write_meta_and_plan(tmp: Path, meta: CheckpointMeta,
                         plan: PlanArtifact | None) -> None:
    if jax.process_index() != 0:
        return
    (tmp / _META_FILE).write_text(meta.to_json())
    if plan is not None:
        (tmp / _PLAN_FILE).write_text(plan.to_json())


def _mesh_meta(state: TrainState, mesh: Mesh,
               block_layout: str = "canonical",
               digests: dict[str, str] | None = None) -> CheckpointMeta:
    return CheckpointMeta(
        step=int(state.step),
        mesh_axes=tuple(mesh.axis_names),
        mesh_shape=tuple(mesh.devices.shape),
        block_layout=block_layout,
        digests=digests or {},
    )


def _prepare_tmp(directory: Path) -> tuple[Path, Path, bool]:
    """(tmp, prev, multi_host) with tmp freshly (re)created and all hosts
    fenced behind its existence."""
    tmp = directory.with_name(directory.name + ".tmp")
    prev = directory.with_name(directory.name + ".prev")
    multi_host = jax.process_count() > 1
    if jax.process_index() == 0:
        if tmp.exists():
            shutil.rmtree(tmp)
        tmp.mkdir(parents=True)
    if multi_host:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices("metis_ckpt_tmp_ready")
    return tmp, prev, multi_host


def _swap_tmp_into_place(directory: Path, tmp: Path, prev: Path,
                         multi_host: bool, keep_prev: bool = False) -> None:
    """The crash-safe primary swap (see ``save_checkpoint`` ordering
    invariant); fenced so no host returns mid-swap.  ``keep_prev`` retains
    the displaced checkpoint as a rollback generation — slice-controller
    saves need it: each slice saves independently, and a crash between two
    slices' saves leaves them at different steps; the behind slice's step
    is then only reachable by the ahead slice through its ``.prev``
    (``execution/multihost2.py`` rollback handshake)."""
    if multi_host:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices("metis_ckpt_pre_swap")
    if jax.process_index() == 0:
        if directory.exists():
            if prev.exists():
                shutil.rmtree(prev)
            directory.rename(prev)
        tmp.rename(directory)
        if prev.exists() and not keep_prev:
            shutil.rmtree(prev)
    if multi_host:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices("metis_ckpt_post_swap")


class AsyncCheckpointWriter:
    """Checkpoint writes overlapped with training.

    ``save`` snapshots the state with orbax's ``AsyncCheckpointer`` (device
    arrays are copied out, serialization runs on background threads) and
    returns immediately; the crash-safe ``.tmp``/``.prev`` swap of
    ``save_checkpoint`` is deferred until the write completes — performed by
    ``wait()``, or automatically at the start of the next ``save``.  Until a
    pending write is swapped, the previous complete checkpoint remains the
    primary, so a crash mid-write loses at most the in-flight checkpoint.

    Usage::

        writer = AsyncCheckpointWriter()
        for step in ...:
            state, loss = train_step(state, ...)
            if step % interval == 0:
                writer.save(ckpt_dir, state, mesh, plan)  # non-blocking
        writer.close()                                    # flush + swap

    ``keep_prev`` retains the displaced checkpoint as a ``.prev`` rollback
    generation on every swap (``_swap_tmp_into_place``) — the corruption
    fallback ``restore_checkpoint`` restores from when the latest fails
    digest verification.
    """

    def __init__(self, keep_prev: bool = False):
        self._ckptr = ocp.AsyncCheckpointer(ocp.PyTreeCheckpointHandler())
        self._pending: tuple[Path, Path, Path, bool] | None = None
        self._keep_prev = keep_prev

    def save(
        self,
        directory: str | Path,
        state: TrainState,
        mesh: Mesh,
        plan: PlanArtifact | None = None,
        block_layout: str = "canonical",
    ) -> None:
        self.wait()  # finish + swap any previous write first
        directory = Path(directory).absolute()
        tmp, prev, multi_host = _prepare_tmp(directory)
        tree = _state_tree(state)
        # digests are computed from the live state at enqueue time (the
        # same snapshot the async serializer copies out), so the meta
        # describes exactly the bytes the background write will land
        digests = _tree_digests(tree)
        self._ckptr.save(tmp / _STATE_DIR, tree, force=True)
        _write_meta_and_plan(
            tmp, _mesh_meta(state, mesh, block_layout, digests), plan)
        self._pending = (directory, tmp, prev, multi_host)

    def wait(self) -> None:
        """Block until the in-flight write (if any) is durable and swapped
        into place as the primary checkpoint.

        A failure of the background save surfaces HERE, re-raised as
        :class:`CheckpointWriteError` naming the checkpoint path — the
        write was dispatched steps ago, so without the path the traceback
        points at an unrelated train-loop line.  The failed write's
        ``.tmp`` is left unswapped: the previous complete checkpoint
        remains the primary."""
        if self._pending is None:
            return
        directory, tmp, prev, multi_host = self._pending
        self._pending = None
        try:
            self._ckptr.wait_until_finished()
        except Exception as e:
            raise CheckpointWriteError(
                f"async checkpoint write to {directory} failed: "
                f"{type(e).__name__}: {e}") from e
        _swap_tmp_into_place(directory, tmp, prev, multi_host,
                             keep_prev=self._keep_prev)

    def close(self) -> None:
        """Flush + swap the in-flight write, then release the checkpointer.

        An in-flight write failure is surfaced (as
        :class:`CheckpointWriteError`), never swallowed — but the
        underlying orbax checkpointer is always closed, so a failed final
        checkpoint does not also leak its background threads.  Orbax's own
        ``close()`` re-joins the background commit and re-raises its error
        raw — when ``wait()`` already surfaced the failure, that second
        raise is suppressed so the typed, path-carrying error propagates."""
        try:
            self.wait()
        except BaseException:
            try:
                self._ckptr.close()
            except Exception:  # noqa: BLE001 — wait()'s error is primary
                pass
            raise
        else:
            self._ckptr.close()

    def __enter__(self) -> "AsyncCheckpointWriter":
        return self

    def __exit__(self, exc_type, *exc) -> None:
        if exc_type is not None:
            # the body is already unwinding — don't let a secondary flush
            # failure mask the original error
            try:
                self.close()
            except Exception:  # noqa: BLE001 — reported path is the body's
                pass
        else:
            self.close()


def _resolve_dir(directory: str | Path) -> Path:
    """The primary checkpoint dir, or its ``.prev`` backup if a crash
    interrupted the last save mid-swap."""
    directory = Path(directory).absolute()
    if directory.exists():
        return directory
    prev = directory.with_name(directory.name + ".prev")
    if prev.exists():
        return prev
    return directory


def load_meta(directory: str | Path) -> CheckpointMeta:
    return CheckpointMeta.from_json(
        (_resolve_dir(directory) / _META_FILE).read_text())


def load_plan(directory: str | Path) -> PlanArtifact | None:
    p = _resolve_dir(directory) / _PLAN_FILE
    return PlanArtifact.from_json(p.read_text()) if p.exists() else None


def _as_restore(leaf):
    # Mesh-sharded leaves restore straight onto the reference's (target-mesh)
    # NamedSharding — orbax reshards on read, so the checkpoint's own mesh
    # never needs to exist in this process (elastic resume onto a smaller
    # device set).  Every other leaf — e.g. a scalar step counter whose
    # reference carries a SingleDeviceSharding — restores as a host numpy
    # array: pinning it to its reference's single device would commit it to
    # device 0 and make the next jitted step over a multi-device mesh raise
    # "Received incompatible devices", while restoring it "as saved" would
    # need the checkpoint's (possibly gone) device set.  An uncommitted host
    # value is placed by the compiled step like any other donation-free input.
    if isinstance(leaf, jax.Array) and \
            isinstance(getattr(leaf, "sharding", None), NamedSharding):
        return ocp.ArrayRestoreArgs(
            sharding=leaf.sharding, global_shape=leaf.shape,
            dtype=leaf.dtype)
    return ocp.RestoreArgs(restore_type=np.ndarray)


def _restore_tree(directory: Path, ref: dict) -> dict:
    """Restore the state tree shaped/sharded like ``ref`` (orbax reshards
    onto the reference leaves' NamedShardings on read).

    Raises ``FileNotFoundError`` when ``directory`` holds no checkpoint at
    all (the "fresh start" signal callers branch on) but a typed
    :class:`CheckpointCorruptError` for everything else — a truncated array
    file, a missing array inside an otherwise-present store, a garbage
    metadata blob — so callers can fall back to ``.prev`` instead of dying
    on a raw deserialization traceback."""
    state_dir = directory / _STATE_DIR
    if not state_dir.exists():
        raise FileNotFoundError(f"no checkpoint state at {state_dir}")
    restore_args = jax.tree.map(_as_restore, ref)
    try:
        with ocp.PyTreeCheckpointer() as ckptr:
            return ckptr.restore(
                state_dir,
                args=ocp.args.PyTreeRestore(
                    item=ref, restore_args=restore_args))
    except Exception as e:
        raise CheckpointCorruptError(
            f"checkpoint {directory} is unreadable: "
            f"{type(e).__name__}: {e}") from e


def _load_meta_if_present(directory: Path) -> CheckpointMeta | None:
    p = directory / _META_FILE
    if not p.exists():
        return None
    try:
        return CheckpointMeta.from_json(p.read_text())
    except Exception as e:
        raise CheckpointCorruptError(
            f"checkpoint {directory} has an unreadable {_META_FILE}: "
            f"{type(e).__name__}: {e}") from e


def _restore_verified(directory: Path, ref: dict) -> dict:
    """Restore ``ref``-shaped state from ``directory`` and verify it against
    the per-leaf content digests its own ``meta.json`` recorded at save
    (checkpoints without digests verify vacuously)."""
    tree = _restore_tree(directory, ref)
    meta = _load_meta_if_present(directory)
    if meta is not None:
        _verify_digests(directory, tree, meta)
    return tree


def _restore_candidates(directory: str | Path) -> list[Path]:
    """Checkpoint generations to try, newest first: the resolved primary,
    then the retained ``.prev`` rollback generation (when it exists and is
    not already what the primary resolved to)."""
    directory = Path(directory).absolute()
    primary = _resolve_dir(directory)
    prev = directory.with_name(directory.name + ".prev")
    out = [primary]
    if prev.exists() and prev != primary:
        out.append(prev)
    return out


def _restore_with_fallback(directory: str | Path, ref: dict) -> dict:
    """Digest-verified restore with automatic fallback: if the latest
    checkpoint is corrupt (unreadable store OR digest mismatch) and a
    ``.prev`` generation is retained, restore that instead.  Only when
    every generation fails does an error propagate; a missing checkpoint
    altogether stays ``FileNotFoundError``, but corruption anywhere wins
    over a missing fallback — callers must not mistake "the checkpoint is
    garbage" for "fresh start"."""
    errors: list[Exception] = []
    for cand in _restore_candidates(directory):
        try:
            return _restore_verified(cand, ref)
        except (CheckpointCorruptError, FileNotFoundError) as e:
            errors.append(e)
    for e in errors:
        if isinstance(e, CheckpointCorruptError):
            raise e
    raise errors[0]


def block_layouts_compatible(meta: CheckpointMeta, expected: str) -> bool:
    """Whether a checkpoint's recorded block layout matches ``expected``.

    Handles the legacy "interleaved:<vs>" format (before pp was encoded in
    the string): it is accepted iff the vs matches AND the checkpoint's own
    recorded mesh pp extent equals the expected pp — the permutation
    (``interleave_block_order``) depends on both, so a same-vs checkpoint
    from a different pp must still be refused."""
    if meta.block_layout == expected:
        return True
    if (meta.block_layout.startswith("interleaved:")
            and "x" not in meta.block_layout
            and expected.startswith("interleaved:")
            and "x" in expected):
        exp_pp, _, exp_vs = expected[len("interleaved:"):].partition("x")
        legacy_vs = meta.block_layout[len("interleaved:"):]
        try:
            meta_pp = meta.mesh_shape[meta.mesh_axes.index("pp")]
        except ValueError:
            meta_pp = 1
        return legacy_vs == exp_vs and str(meta_pp) == exp_pp
    return False


def restore_checkpoint(
    directory: str | Path,
    reference_state: TrainState,
    expected_block_layout: str | None = None,
) -> TrainState:
    """Restore a TrainState shaped/sharded like ``reference_state`` (built
    with ``build_train_state`` on the *target* mesh — which may differ from
    the mesh the checkpoint was written on; orbax reshards on read).

    ``expected_block_layout``: when given, refuse a checkpoint whose
    recorded ``CheckpointMeta.block_layout`` differs — restoring a permuted
    (interleaved-schedule) checkpoint under a different layout silently
    scrambles the layers.

    The restore is digest-verified against the checkpoint's recorded
    content digests, with automatic fallback to the retained ``.prev``
    generation when the latest is corrupt (``_restore_with_fallback``)."""
    if expected_block_layout is not None:
        meta = load_meta(directory)
        if not block_layouts_compatible(meta, expected_block_layout):
            raise ValueError(
                f"checkpoint {directory} was written with block layout "
                f"'{meta.block_layout}', expected '{expected_block_layout}' "
                "— refusing to restore (a layout mismatch silently "
                "scrambles the stacked block axis)")
    tree = _restore_with_fallback(directory, _state_tree(reference_state))
    step = tree["step"]
    if not isinstance(step, jax.Array):
        step = jax.numpy.asarray(np.asarray(step))
    return TrainState(params=tree["params"], opt_state=tree["opt_state"],
                      step=step)


# ---------------------------------------------------------------------------
# hetero (multi-mesh) checkpoints: one per-stage state list, one directory
# ---------------------------------------------------------------------------


def _hetero_tree(state: list, step) -> dict:
    return {
        "stages": [{"params": p, "opt_state": o} for p, o in state],
        "step": step,
    }


def _pad_empty(tree):
    """orbax refuses zero-size arrays; a hetero stage holding only the
    embed/head pseudo-layer has empty block-param leaves.  Swap them for
    1-element placeholders at save; the restore side grafts the reference's
    (identical, correctly sharded) empties back."""
    import jax.numpy as jnp

    return jax.tree.map(
        lambda a: (jnp.zeros((1,), getattr(a, "dtype", jnp.float32))
                   if getattr(a, "size", 1) == 0 else a),
        tree)


def save_hetero_checkpoint(
    directory: str | Path,
    state: list,
    step: int,
    plan: PlanArtifact | None = None,
    keep_prev: bool = False,
) -> Path:
    """Checkpoint the multi-mesh hetero executor's state — a list of
    per-stage ``[params, opt_state]`` pairs, each living on its own stage
    mesh (``execution.hetero.make_hetero_train_step``).  Same crash-safe
    swap as ``save_checkpoint``; the meta records the stage count in place
    of a mesh shape.  Digests cover the PADDED tree (the bytes actually on
    disk) — the restore side verifies before grafting empties back."""
    import jax.numpy as jnp

    directory = Path(directory).absolute()
    tmp, prev, multi_host = _prepare_tmp(directory)
    tree = _pad_empty(_hetero_tree(state, jnp.asarray(step, jnp.int32)))
    with ocp.PyTreeCheckpointer() as ckptr:
        ckptr.save(tmp / _STATE_DIR, tree, force=True)
    _write_meta_and_plan(
        tmp, CheckpointMeta(step=int(step), mesh_axes=("stage",),
                            mesh_shape=(len(state),),
                            digests=_tree_digests(tree)), plan)
    _swap_tmp_into_place(directory, tmp, prev, multi_host,
                         keep_prev=keep_prev)
    return directory


def restore_hetero_checkpoint(
    directory: str | Path,
    reference_state: list,
) -> list:
    """Restore a per-stage state list shaped/sharded like
    ``reference_state`` (a fresh ``init_fn(key)`` of the SAME plan — stage
    structure must match; shardings are taken from the reference leaves).
    Digest-verified, with ``.prev`` fallback like ``restore_checkpoint``."""
    import jax.numpy as jnp

    ref = _hetero_tree(reference_state, jnp.zeros((), jnp.int32))
    tree = _restore_with_fallback(directory, _pad_empty(ref))
    # graft the reference's empty leaves back over their saved placeholders
    tree = jax.tree.map(
        lambda r, g: r if getattr(r, "size", 1) == 0 else g, ref, tree)
    return [[s["params"], s["opt_state"]] for s in tree["stages"]]
