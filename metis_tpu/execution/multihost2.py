"""Per-slice-controller hetero execution — the multi-controller shape of the
multi-mesh executor (SURVEY.md §7 hard part 3; VERDICT r3 next-step 5b).

``execution.hetero`` runs a non-uniform plan single-controller: one process
owns every stage's mesh and moves boundary activations with
``jax.device_put``.  On the north-star deployment (v4-32 + v5e-16) that is
impossible — the slices are DIFFERENT jax backends (different chip
generations cannot join one runtime), so the real topology is one
CONTROLLER PER SLICE: each controller owns one stage group's mesh, feeds its
own stage, and the boundary activations/cotangents flow host-to-host over
DCN.  This module realizes that slice with two plain OS processes:

- each worker owns stage ``i``'s devices ONLY (its own jax runtime — no
  ``jax.distributed``: the stages never share a collective, which is the
  whole point; a v4 and a v5e slice could not share one anyway);
- the stage programs are the SAME jitted closures the single-controller
  executor builds (``hetero._make_stage_fn`` + per-stage vjp) — this module
  adds transport, not math;
- boundary tensors move over a TCP socket pair (host-mediated, exactly how
  a DCN transfer between incompatible slices is realized);
- the schedule mirrors the single-controller executor tick for tick:
  forward fill (all microbatches, storing only boundary inputs), backward
  drain in reverse, one optimizer step per stage — so the loss stream is
  numerically IDENTICAL to ``make_hetero_train_step`` on the same plan
  (pinned by tests/test_multihost2.py).

The worker entry: ``python -m metis_tpu.execution.multihost2 <stage_id>
<num_stages> <port>`` — tests spawn one worker per stage.
"""
from __future__ import annotations

import json
import socket
import struct
import sys

import numpy as np


# ---------------------------------------------------------------------------
# boundary transport: length-framed numpy arrays over TCP
# ---------------------------------------------------------------------------


def send_array(sock: socket.socket, arr: np.ndarray) -> None:
    """Length-framed array with an explicit dtype/shape header — ``np.save``
    silently degrades ml_dtypes (bfloat16 round-trips as raw '|V2'), and
    boundary tensors on the bf16 execution path must arrive as bf16."""
    arr = np.asarray(arr)
    meta = json.dumps({"dtype": str(arr.dtype),
                       "shape": list(arr.shape)}).encode()
    payload = np.ascontiguousarray(arr).tobytes()
    sock.sendall(struct.pack("<QQ", len(meta), len(payload))
                 + meta + payload)


def recv_array(sock: socket.socket) -> np.ndarray:
    header = _recv_exact(sock, 16)
    n_meta, n_payload = struct.unpack("<QQ", header)
    meta = json.loads(_recv_exact(sock, n_meta))
    dtype = np.dtype(meta["dtype"])  # ml_dtypes names resolve once imported
    return np.frombuffer(
        _recv_exact(sock, n_payload), dtype=dtype).reshape(meta["shape"])


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    while n:
        chunk = sock.recv(min(n, 1 << 20))
        if not chunk:
            raise ConnectionError("boundary peer closed the socket")
        chunks.append(chunk)
        n -= len(chunk)
    return b"".join(chunks)


def _connect_ring_addrs(stage_id: int, num_stages: int,
                        link_addrs, timeout_s: float = 120.0):
    """(to_prev, to_next) sockets for this stage over explicit link
    addresses: ``link_addrs[i] = (host, port)`` is the boundary link between
    stage i and stage i+1 — stage i LISTENS there (bind on all interfaces at
    that port), stage i+1 DIALS it.  ``num_stages - 1`` links total."""
    to_prev = to_next = None
    if stage_id < num_stages - 1:
        # bind the link's OWN host (loopback stays loopback; a multi-homed
        # box pins the interface the operator named) — never 0.0.0.0
        srv = socket.create_server(
            (link_addrs[stage_id][0], link_addrs[stage_id][1]))
        srv.settimeout(timeout_s)
        to_next, _ = srv.accept()
        srv.close()
    if stage_id > 0:
        host, port = link_addrs[stage_id - 1]
        deadline = timeout_s
        while True:
            try:
                to_prev = socket.create_connection((host, port), timeout=2.0)
                break
            except OSError:
                deadline -= 0.2
                if deadline <= 0:
                    raise
                import time

                time.sleep(0.2)
    # boundary transfers must BLOCK: the peer may sit in a minutes-long
    # first-call XLA compile before its first send — a lingering
    # connect/accept timeout on the socket would kill the run
    for s in (to_prev, to_next):
        if s is not None:
            s.settimeout(None)
    return to_prev, to_next


def _connect_ring(stage_id: int, num_stages: int, base_port: int,
                  timeout_s: float = 60.0):
    """Localhost ring on consecutive ports (the fixed-workload/test shape)."""
    return _connect_ring_addrs(
        stage_id, num_stages,
        [("127.0.0.1", base_port + i) for i in range(num_stages - 1)],
        timeout_s)


# ---------------------------------------------------------------------------
# the fixed 2-stage workload (shared with the single-controller parity leg)
# ---------------------------------------------------------------------------

WORKLOAD = dict(vocab_size=256, seq_len=16, hidden=64, num_heads=4,
                num_blocks=3, ffn_multiplier=2)
PARTITION = (0, 2, 5)   # profile layers: stage0 = embed+1 block, stage1 = 2 blocks+head
STRATEGIES = ({"dp": 2, "tp": 1}, {"dp": 1, "tp": 2})
GBS, MICROBATCHES, STEPS = 8, 2, 3


def workload_plan(cfg=None):
    """(cfg, stage_specs) for the fixed parity workload."""
    import jax.numpy as jnp

    from metis_tpu.execution.hetero import stage_specs_from_plan
    from metis_tpu.models import GPTConfig

    if cfg is None:
        cfg = GPTConfig(dtype=jnp.float32, **WORKLOAD)
    return cfg, stage_specs_from_plan(PARTITION, STRATEGIES, cfg)


def workload_batches():
    """Deterministic [steps][M, rows, seq] token microbatches — every
    controller derives the same schedule from the same seed (the
    multi-controller feeding contract, execution/multihost.py)."""
    import jax

    toks = jax.random.randint(
        jax.random.PRNGKey(17),
        (STEPS, MICROBATCHES, GBS // MICROBATCHES, WORKLOAD["seq_len"]),
        0, WORKLOAD["vocab_size"])
    return np.asarray(toks)


def run_single_controller_losses() -> list[float]:
    """The identical run under the single-process multi-mesh executor — the
    numeric parity oracle (needs >= 4 local devices)."""
    import jax

    from metis_tpu.execution.hetero import make_hetero_train_step

    cfg, stages = workload_plan()
    init_fn, step = make_hetero_train_step(cfg, stages)
    state = init_fn(jax.random.PRNGKey(0))
    losses = []
    for toks in workload_batches():
        state, loss = step(state, toks, toks)
        losses.append(float(loss))
    return losses


# ---------------------------------------------------------------------------
# the per-stage controller
# ---------------------------------------------------------------------------


def _run_stage_loop(cfg, stages, stage_id, connect, batch_iter_factory,
                    microbatches, restore_hook=None, save_hook=None,
                    rollback_hook=None, checkpoint_every: int = 0) -> dict:
    """The per-stage controller loop shared by the fixed-workload worker and
    the plan-artifact worker: build this stage's mesh/params/closures, then
    per step run the forward fill (storing only boundary inputs), the
    reversed backward drain, and one optimizer update — mirroring
    ``make_hetero_train_step`` tick for tick so the loss stream is
    numerically identical to the single-controller executor on the same
    plan (tests/test_multihost2.py, tests/test_cli.py).

    ``connect()`` returns the (to_prev, to_next) sockets;
    ``batch_iter_factory(start_step)`` yields microbatch-major
    ``(tok_mbs, tgt_mbs)`` pairs of shape ``[M, rows, seq]``, identically
    derived on every controller from the shared data schedule, fast-
    forwarded past ``start_step`` consumed batches on resume (the
    multi-controller feeding contract, ``execution/multihost.py``).

    ``restore_hook(params, opt_state, mesh) -> (params, opt_state,
    start_step)`` and ``save_hook(params, opt_state, step, mesh)`` bolt
    per-slice checkpointing on: each controller persists ONLY its stage's
    state.  After the ring connects, neighbors exchange their
    ``start_step`` and refuse a mismatch — slices resuming from different
    steps would silently walk different batch schedules."""
    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from metis_tpu.execution.hetero import (
        _make_stage_fn,
        _slice_stage_params,
        _stage_param_specs,
    )
    from metis_tpu.execution.mesh import DP, EP, SP, TP
    from metis_tpu.execution.train import build_optimizer
    from metis_tpu.models import family_ops, resolve_attention

    num_stages = len(stages)
    spec = stages[stage_id]
    devs = jax.devices()[: spec.devices]
    if len(devs) < spec.devices:
        raise RuntimeError(
            f"stage {stage_id} needs {spec.devices} devices, "
            f"have {len(jax.devices())}")
    if spec.ep > 1:
        mesh = Mesh(np.array(devs).reshape(spec.dp // spec.ep, spec.ep,
                                           spec.tp), (DP, EP, TP))
    elif spec.cp > 1:
        mesh = Mesh(np.array(devs).reshape(spec.dp, spec.cp, spec.tp),
                    (DP, SP, TP))
    else:
        mesh = Mesh(np.array(devs).reshape(spec.dp, spec.tp), (DP, TP))

    # identical init to the single-controller executor: one full init from
    # the shared seed, slice this stage's leaves.  A function: the rollback
    # path re-derives step-0 state without holding a pristine copy live.
    optimizer = build_optimizer()
    specs = _stage_param_specs(spec, cfg)

    def init_state():
        full = family_ops(cfg)[3](jax.random.PRNGKey(0), cfg)
        p0 = jax.tree.map(
            lambda x, sp: jax.device_put(x, NamedSharding(mesh, sp)),
            _slice_stage_params(full, spec), specs)
        with mesh:
            return p0, optimizer.init(p0)

    params, opt_state = init_state()

    total_blocks = max(cfg.num_blocks, 1)
    fn = _make_stage_fn(spec, cfg, resolve_attention(cfg),
                        aux_weight=spec.num_blocks / total_blocks)
    is_first, is_last = stage_id == 0, stage_id == num_stages - 1
    M = microbatches

    def _in_mesh(f):
        def run(*args):
            with mesh:
                return f(*args)
        return run

    if is_last:
        def lg(params, x_in, tgt):
            loss, grads = jax.value_and_grad(fn, argnums=(0, 1))(
                params, x_in, tgt)
            return loss, grads[0], grads[1]
        lossgrad = _in_mesh(jax.jit(lg))
    elif is_first:
        fwd = _in_mesh(jax.jit(fn))

        def bw(params, tok, ct):
            _, pull = jax.vjp(lambda p: fn(p, tok), params)
            return pull(ct)[0]
        bwd = _in_mesh(jax.jit(bw))
    else:
        fwd = _in_mesh(jax.jit(fn))

        def bw_mid(params, x_in, ct):
            _, pull = jax.vjp(fn, params, x_in)
            return pull(ct)
        bwd = _in_mesh(jax.jit(bw_mid))

    def upd(params, opt_state, acc):
        grads = jax.tree.map(lambda g: g / M, acc)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state
    apply_upd = _in_mesh(jax.jit(upd, donate_argnums=(0, 1, 2)))
    add = _in_mesh(jax.jit(
        lambda a, g: jax.tree.map(jnp.add, a, g), donate_argnums=(0,)))

    start_step = 0
    if restore_hook is not None:
        params, opt_state, start_step = restore_hook(params, opt_state, mesh)

    boundary_spec = NamedSharding(mesh, P(None, None, None))
    to_prev, to_next = connect()
    # Resume agreement: slices resuming from different steps would silently
    # feed different batch schedules.  Saves on different controllers are
    # uncoordinated, so a crash in the inter-slice save window legitimately
    # leaves neighbors at different steps — the chain agrees on the GLOBAL
    # minimum (num_stages-1 rounds of neighbor-min propagation) and any
    # slice ahead of it rolls back through its retained ``.prev``
    # generation (rollback_hook); only an unrecoverable gap raises.
    def _exchange_min(step):
        for sock in (to_prev, to_next):
            if sock is not None:
                send_array(sock, np.asarray([step], np.int64))
        for sock in (to_prev, to_next):
            if sock is not None:
                step = min(step, int(recv_array(sock)[0]))
        return step

    agreed = start_step
    for _ in range(max(num_stages - 1, 1)):
        agreed = _exchange_min(agreed)
    if agreed != start_step:
        if agreed == 0:
            # step 0 needs no checkpoint: re-derive the fresh init
            params, opt_state = init_state()
            rolled = (params, opt_state, 0)
        else:
            rolled = (rollback_hook(agreed, params, opt_state, mesh)
                      if rollback_hook is not None else None)
        if rolled is None:
            raise RuntimeError(
                f"stage {stage_id} resumes at step {start_step} but the "
                f"slice chain agrees on {agreed}, and no rollback "
                f"generation reaches it — slice checkpoints are out of "
                "sync (same --checkpoint-dir on every controller?)")
        params, opt_state, start_step = rolled

    losses: list[float] = []
    steps = 0
    for tok, tgt in batch_iter_factory(start_step):
        steps += 1
        x_in: list = [None] * M
        # ---- forward fill (boundary inputs only, as the single-controller
        # executor stores them)
        for m in range(M):
            if is_first:
                x = fwd(params, tok[m])
                send_array(to_next, jax.device_get(x))
            else:
                x_in[m] = jax.device_put(recv_array(to_prev), boundary_spec)
                if not is_last:
                    x = fwd(params, x_in[m])
                    send_array(to_next, jax.device_get(x))
        # ---- backward drain, reversed (same accumulation order)
        acc = None
        step_losses = []
        for m in reversed(range(M)):
            if is_last:
                loss, g, ct = lossgrad(params, x_in[m], tgt[m])
                step_losses.append(float(jax.device_get(loss)))
                send_array(to_prev, jax.device_get(ct))
            elif is_first:
                ct = jax.device_put(recv_array(to_next), boundary_spec)
                g = bwd(params, tok[m], ct)
            else:
                ct = jax.device_put(recv_array(to_next), boundary_spec)
                g, ct_prev = bwd(params, x_in[m], ct)
                send_array(to_prev, jax.device_get(ct_prev))
            acc = g if acc is None else add(acc, g)
        params, opt_state = apply_upd(params, opt_state, acc)
        if is_last:
            losses.append(float(np.mean(step_losses)))
        if (save_hook is not None and checkpoint_every
                and steps % checkpoint_every == 0):
            save_hook(params, opt_state, start_step + steps, mesh)

    if save_hook is not None and not (
            checkpoint_every and steps % checkpoint_every == 0):
        save_hook(params, opt_state, start_step + steps, mesh)
    for sock in (to_prev, to_next):
        if sock is not None:
            sock.close()
    return {
        "stage": stage_id,
        "stages": num_stages,
        "local_devices": len(jax.devices()),
        "steps": steps,
        "start_step": start_step,
        "losses": losses,  # non-last stages report []
    }


def run_stage_worker(stage_id: int, num_stages: int, base_port: int) -> dict:
    """One controller owning stage ``stage_id``'s mesh: runs the FIXED
    2-stage parity workload with boundary tensors over sockets (the gate /
    test entry; the CLI route is :func:`run_artifact_stage_worker`)."""
    if num_stages != 2:
        raise ValueError(
            f"the fixed parity workload has exactly 2 stages, "
            f"got num_stages={num_stages}")
    import jax.numpy as jnp

    cfg, stages = workload_plan()

    def batch_iter_factory(start_step):
        def gen():
            for toks in workload_batches():
                t = jnp.asarray(toks)
                yield t, t
        return gen()

    return _run_stage_loop(
        cfg, stages, stage_id,
        lambda: _connect_ring(stage_id, num_stages, base_port),
        batch_iter_factory, MICROBATCHES)


def run_artifact_stage_worker(
    artifact,
    model,
    stage_id: int,
    link_addrs,
    steps: int,
    data_path: str | None = None,
    seed: int = 0,
    checkpoint_dir: str | None = None,
    checkpoint_every: int = 0,
) -> dict:
    """One slice controller running stage ``stage_id`` of a REAL plan
    artifact — the CLI-drivable per-slice-controller topology (VERDICT r4
    weak #5: it was gate-only).  Any number of stages: first / middle /
    last roles, a middle stage relaying activations forward and
    input-cotangents backward.  Batches flow through the SAME deterministic
    input pipeline as the single-controller train CLI (shared ``seed`` /
    ``data_path``), so every controller derives the identical schedule.
    With ``checkpoint_dir`` each controller checkpoints/resumes ITS stage
    under ``<dir>/slice{stage_id}/`` (crash-safe swap, the data schedule
    fast-forwarded on resume; the ring handshake refuses out-of-sync
    neighbors).

    Refused plan shapes (explicit errors beat silent divergence):

    - MoE: the router aux-loss couples stages through the head loss, which
      the socket transport does not carry;
    - non-gpipe schedules / virtual stages: the loop implements the
      fill-drain schedule only, and a plan priced as 1f1b/interleaved must
      not silently execute as something else (the schedule is a searched,
      priced axis — ``cost/schedule.py``);
    - mixed-device-type stages (uneven data-balancer rows / per-type
      groups): one slice controller owns ONE jax runtime, which cannot
      span device types — such stages only exist in the single-runtime
      executor (callers pass ``stage_replica_rows`` from
      ``plan_replica_rows`` to detect this; the CLI does)."""
    from metis_tpu.data.pipeline import (
        TokenDataset,
        make_input_pipeline,
        synthetic_run_dataset,
    )
    from metis_tpu.execution.hetero import stage_specs_from_plan
    from metis_tpu.execution.pipeline import microbatch_split
    from metis_tpu.models import config_for_model_spec
    from metis_tpu.models.moe import MoEConfig

    cfg = config_for_model_spec(model)
    if isinstance(cfg, MoEConfig):
        raise ValueError(
            "slice-controller execution supports dense plans only (the MoE "
            "router aux couples stages through the head loss)")
    if getattr(artifact, "schedule", "gpipe") != "gpipe":
        # virtual_stages is NOT checked: it only shapes the interleaved
        # schedule (resolve_schedule defaults it to 2 even for gpipe
        # plans, where the executors ignore it)
        raise ValueError(
            f"slice-controller execution implements the gpipe fill-drain "
            f"schedule only; this plan was priced as "
            f"schedule={artifact.schedule!r} — running it as gpipe would "
            "invalidate the planner's cost basis")
    stages = stage_specs_from_plan(
        artifact.layer_partition, artifact.strategies, cfg)
    num_stages = len(stages)
    if num_stages < 2:
        raise ValueError("slice-controller execution needs >= 2 stages")
    if not 0 <= stage_id < num_stages:
        raise ValueError(f"stage {stage_id} out of range 0..{num_stages - 1}")
    if len(link_addrs) != num_stages - 1:
        raise ValueError(
            f"{num_stages} stages need {num_stages - 1} boundary links, "
            f"got {len(link_addrs)}")

    import jax.numpy as jnp

    M = artifact.microbatches
    # the SAME deterministic feeding as the single-controller train CLI
    # (planner/cli.py hetero route): dataset -> [gbs, seq] batches ->
    # microbatch_split — every controller walks the identical schedule
    if data_path:
        toks_src = (np.load(data_path, mmap_mode="r")
                    if data_path.endswith(".npy")
                    else np.memmap(data_path, dtype=np.int32, mode="r"))
        dataset = TokenDataset(toks_src, model.sequence_length)
    else:
        dataset = synthetic_run_dataset(
            model.vocab_size, artifact.gbs, model.sequence_length, seed=seed)

    def batch_iter_factory(start_step):
        # fast-forward the deterministic schedule past the batches the
        # resumed steps already consumed (same rule as the train CLI)
        batches = make_input_pipeline(dataset, artifact.gbs, epochs=None,
                                      skip_batches=start_step)

        def gen():
            for _ in range(steps):
                toks_g, tgts_g = next(batches)
                yield (microbatch_split(jnp.asarray(toks_g), M),
                       microbatch_split(jnp.asarray(tgts_g), M))
        return gen()

    restore_hook = save_hook = rollback_hook = None
    if checkpoint_dir is not None:
        # each controller persists ONLY its stage: <dir>/slice{stage_id}/
        # (next to the top-level plan.json the CLI pins); the loop's ring
        # handshake agrees on the chain-min step and rolls ahead slices
        # back through their retained .prev generation
        from pathlib import Path

        from metis_tpu.execution.checkpoint import (
            load_meta,
            restore_checkpoint,
            save_checkpoint,
        )
        from metis_tpu.execution.train import TrainState

        sdir = Path(checkpoint_dir) / f"slice{stage_id}"

        def restore_hook(params, opt_state, mesh):
            try:
                meta = load_meta(sdir)
            except FileNotFoundError:
                return params, opt_state, 0
            restored = restore_checkpoint(
                sdir, TrainState(params=params, opt_state=opt_state,
                                 step=jnp.zeros((), jnp.int32)))
            return restored.params, restored.opt_state, meta.step

        def save_hook(params, opt_state, step, mesh):
            # keep_prev: saves on different controllers are uncoordinated —
            # the retained generation is the rollback target when a crash
            # lands between two slices' saves (rollback_hook below)
            save_checkpoint(
                sdir,
                TrainState(params=params, opt_state=opt_state,
                           step=jnp.asarray(step, jnp.int32)),
                mesh, plan=artifact, keep_prev=True)

        def rollback_hook(target_step, params, opt_state, mesh):
            """(params, opt_state, target_step) from a checkpoint at
            EXACTLY target_step — the primary if it matches, else the
            retained .prev generation; None when neither reaches it."""
            prev = sdir.with_name(sdir.name + ".prev")
            for d in (sdir, prev):
                try:
                    if load_meta(d).step != target_step:
                        continue
                except (FileNotFoundError, OSError):
                    continue
                restored = restore_checkpoint(
                    d, TrainState(params=params, opt_state=opt_state,
                                  step=jnp.zeros((), jnp.int32)))
                return restored.params, restored.opt_state, target_step
            return None  # target 0 is served by the loop's fresh re-init

    return _run_stage_loop(
        cfg, stages, stage_id,
        lambda: _connect_ring_addrs(stage_id, num_stages, link_addrs),
        batch_iter_factory, M, restore_hook=restore_hook,
        save_hook=save_hook, rollback_hook=rollback_hook,
        checkpoint_every=checkpoint_every)


def parse_link_addrs(peers: str) -> list[tuple[str, int]]:
    """``host:port,host:port,...`` -> [(host, port), ...] — one entry per
    boundary link (stage i listens on entry i; stage i+1 dials it)."""
    out = []
    for part in peers.split(","):
        part = part.strip()
        if not part:
            continue
        host, _, port = part.rpartition(":")
        if not host or not port.isdigit():
            raise ValueError(f"bad link address {part!r} (want host:port)")
        out.append((host, int(port)))
    if not out:
        raise ValueError("no boundary link addresses given")
    return out


def spawn_hetero_workers(base_port: int, timeout_s: float = 420.0
                         ) -> list[dict]:
    """Spawn one controller process per stage of the fixed workload and
    return their reports.  Each worker sees ONLY its stage's device count
    (xla_force_host_platform_device_count) — there is no shared runtime to
    fall back on, so passing the parity test genuinely demonstrates the
    per-slice-controller topology."""
    import os
    import subprocess

    _, stages = _plan_shape()
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    procs = []
    for i, ndev in enumerate(stages):
        env = {**os.environ,
               "JAX_PLATFORMS": "cpu",
               "XLA_FLAGS": f"--xla_force_host_platform_device_count={ndev}",
               "PYTHONPATH": repo}
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "metis_tpu.execution.multihost2",
             str(i), str(len(stages)), str(base_port)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env, cwd=repo))
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=timeout_s)
            if p.returncode != 0:
                raise RuntimeError(f"hetero worker failed:\n{err[-1500:]}")
            outs.append(json.loads(out.strip().splitlines()[-1]))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    return outs


def _plan_shape() -> tuple[tuple, list[int]]:
    """(strategies, per-stage device counts) without touching a backend —
    the spawner must not initialize jax in the parent."""
    counts = [s["dp"] * s["tp"] for s in STRATEGIES]
    return STRATEGIES, counts


if __name__ == "__main__":
    _stage, _n, _port = int(sys.argv[1]), int(sys.argv[2]), int(sys.argv[3])
    import jax as _jax

    _jax.config.update("jax_platforms", "cpu")
    print(json.dumps(run_stage_worker(_stage, _n, _port)), flush=True)
