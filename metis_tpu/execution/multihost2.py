"""Per-slice-controller hetero execution — the multi-controller shape of the
multi-mesh executor (SURVEY.md §7 hard part 3; VERDICT r3 next-step 5b).

``execution.hetero`` runs a non-uniform plan single-controller: one process
owns every stage's mesh and moves boundary activations with
``jax.device_put``.  On the north-star deployment (v4-32 + v5e-16) that is
impossible — the slices are DIFFERENT jax backends (different chip
generations cannot join one runtime), so the real topology is one
CONTROLLER PER SLICE: each controller owns one stage group's mesh, feeds its
own stage, and the boundary activations/cotangents flow host-to-host over
DCN.  This module realizes that slice with two plain OS processes:

- each worker owns stage ``i``'s devices ONLY (its own jax runtime — no
  ``jax.distributed``: the stages never share a collective, which is the
  whole point; a v4 and a v5e slice could not share one anyway);
- the stage programs are the SAME jitted closures the single-controller
  executor builds (``hetero._make_stage_fn`` + per-stage vjp) — this module
  adds transport, not math;
- boundary tensors move over a TCP socket pair (host-mediated, exactly how
  a DCN transfer between incompatible slices is realized);
- the schedule mirrors the single-controller executor tick for tick:
  forward fill (all microbatches, storing only boundary inputs), backward
  drain in reverse, one optimizer step per stage — so the loss stream is
  numerically IDENTICAL to ``make_hetero_train_step`` on the same plan
  (pinned by tests/test_multihost2.py).

The worker entry: ``python -m metis_tpu.execution.multihost2 <stage_id>
<num_stages> <port>`` — tests spawn one worker per stage.
"""
from __future__ import annotations

import io
import json
import socket
import struct
import sys

import numpy as np


# ---------------------------------------------------------------------------
# boundary transport: length-framed numpy arrays over TCP
# ---------------------------------------------------------------------------


def send_array(sock: socket.socket, arr: np.ndarray) -> None:
    buf = io.BytesIO()
    np.save(buf, np.asarray(arr), allow_pickle=False)
    payload = buf.getvalue()
    sock.sendall(struct.pack("<Q", len(payload)) + payload)


def recv_array(sock: socket.socket) -> np.ndarray:
    header = _recv_exact(sock, 8)
    (n,) = struct.unpack("<Q", header)
    return np.load(io.BytesIO(_recv_exact(sock, n)), allow_pickle=False)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    while n:
        chunk = sock.recv(min(n, 1 << 20))
        if not chunk:
            raise ConnectionError("boundary peer closed the socket")
        chunks.append(chunk)
        n -= len(chunk)
    return b"".join(chunks)


def _connect_ring(stage_id: int, num_stages: int, base_port: int,
                  timeout_s: float = 60.0):
    """(to_prev, to_next) sockets for this stage.  Link ``i`` (ports
    base_port + i) joins stage i (listener) and stage i+1 (dialer)."""
    to_prev = to_next = None
    if stage_id < num_stages - 1:
        srv = socket.create_server(("127.0.0.1", base_port + stage_id))
        srv.settimeout(timeout_s)
        to_next, _ = srv.accept()
        srv.close()
    if stage_id > 0:
        deadline = timeout_s
        while True:
            try:
                to_prev = socket.create_connection(
                    ("127.0.0.1", base_port + stage_id - 1), timeout=2.0)
                break
            except OSError:
                deadline -= 0.2
                if deadline <= 0:
                    raise
                import time

                time.sleep(0.2)
    # boundary transfers must BLOCK: the peer may sit in a minutes-long
    # first-call XLA compile before its first send — a lingering
    # connect/accept timeout on the socket would kill the run
    for s in (to_prev, to_next):
        if s is not None:
            s.settimeout(None)
    return to_prev, to_next


# ---------------------------------------------------------------------------
# the fixed 2-stage workload (shared with the single-controller parity leg)
# ---------------------------------------------------------------------------

WORKLOAD = dict(vocab_size=256, seq_len=16, hidden=64, num_heads=4,
                num_blocks=3, ffn_multiplier=2)
PARTITION = (0, 2, 5)   # profile layers: stage0 = embed+1 block, stage1 = 2 blocks+head
STRATEGIES = ({"dp": 2, "tp": 1}, {"dp": 1, "tp": 2})
GBS, MICROBATCHES, STEPS = 8, 2, 3


def workload_plan(cfg=None):
    """(cfg, stage_specs) for the fixed parity workload."""
    import jax.numpy as jnp

    from metis_tpu.execution.hetero import stage_specs_from_plan
    from metis_tpu.models import GPTConfig

    if cfg is None:
        cfg = GPTConfig(dtype=jnp.float32, **WORKLOAD)
    return cfg, stage_specs_from_plan(PARTITION, STRATEGIES, cfg)


def workload_batches():
    """Deterministic [steps][M, rows, seq] token microbatches — every
    controller derives the same schedule from the same seed (the
    multi-controller feeding contract, execution/multihost.py)."""
    import jax

    toks = jax.random.randint(
        jax.random.PRNGKey(17),
        (STEPS, MICROBATCHES, GBS // MICROBATCHES, WORKLOAD["seq_len"]),
        0, WORKLOAD["vocab_size"])
    return np.asarray(toks)


def run_single_controller_losses() -> list[float]:
    """The identical run under the single-process multi-mesh executor — the
    numeric parity oracle (needs >= 4 local devices)."""
    import jax

    from metis_tpu.execution.hetero import make_hetero_train_step

    cfg, stages = workload_plan()
    init_fn, step = make_hetero_train_step(cfg, stages)
    state = init_fn(jax.random.PRNGKey(0))
    losses = []
    for toks in workload_batches():
        state, loss = step(state, toks, toks)
        losses.append(float(loss))
    return losses


# ---------------------------------------------------------------------------
# the per-stage controller
# ---------------------------------------------------------------------------


def run_stage_worker(stage_id: int, num_stages: int, base_port: int) -> dict:
    """One controller owning stage ``stage_id``'s mesh: runs the shared
    workload with boundary tensors over sockets.  Returns a report dict.

    The slice implements exactly TWO stages (first + last roles; a middle
    stage would need a forward relay and an input-cotangent path this
    worker does not have) — matching the fixed 2-stage workload."""
    if num_stages != 2:
        raise ValueError(
            f"the per-slice-controller slice implements exactly 2 stages, "
            f"got num_stages={num_stages}")
    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from metis_tpu.execution.hetero import (
        _make_stage_fn,
        _slice_stage_params,
        _stage_param_specs,
    )
    from metis_tpu.execution.mesh import DP, TP
    from metis_tpu.execution.train import build_optimizer
    from metis_tpu.models import init_params
    from metis_tpu.models.gpt import default_attention

    cfg, stages = workload_plan()
    spec = stages[stage_id]
    devs = jax.devices()[: spec.devices]
    if len(devs) < spec.devices:
        raise RuntimeError(
            f"stage {stage_id} needs {spec.devices} devices, "
            f"have {len(jax.devices())}")
    mesh = Mesh(np.array(devs).reshape(spec.dp, spec.tp), (DP, TP))

    # identical init to the single-controller executor: one full
    # init_params from the shared seed, slice this stage's leaves
    full = init_params(jax.random.PRNGKey(0), cfg)
    specs = _stage_param_specs(spec, cfg)
    params = jax.tree.map(
        lambda x, sp: jax.device_put(x, NamedSharding(mesh, sp)),
        _slice_stage_params(full, spec), specs)
    optimizer = build_optimizer()
    with mesh:
        opt_state = optimizer.init(params)

    total_blocks = max(cfg.num_blocks, 1)
    fn = _make_stage_fn(spec, cfg, default_attention(cfg),
                        aux_weight=spec.num_blocks / total_blocks)
    is_first = stage_id == 0
    is_last = stage_id == num_stages - 1

    def _in_mesh(f):
        def run(*args):
            with mesh:
                return f(*args)
        return run

    if is_last:
        def lg(params, x_in, tgt):
            loss, grads = jax.value_and_grad(fn, argnums=(0, 1))(
                params, x_in, tgt)
            return loss, grads[0], grads[1]
        lossgrad = _in_mesh(jax.jit(lg))
    else:
        fwd = _in_mesh(jax.jit(fn))

        def bw(params, tok, ct):
            _, pull = jax.vjp(lambda p: fn(p, tok), params)
            return pull(ct)[0]
        bwd = _in_mesh(jax.jit(bw))

    def upd(params, opt_state, acc):
        grads = jax.tree.map(lambda g: g / MICROBATCHES, acc)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state
    apply_upd = _in_mesh(jax.jit(upd, donate_argnums=(0, 1, 2)))

    add = _in_mesh(jax.jit(
        lambda a, g: jax.tree.map(jnp.add, a, g), donate_argnums=(0,)))

    to_prev, to_next = _connect_ring(stage_id, num_stages, base_port)
    batches = workload_batches()
    losses: list[float] = []
    M = MICROBATCHES
    for toks in batches:
        # ---- forward fill (boundary inputs only, as the single-controller
        # executor stores them)
        x_in: list = [None] * M
        for m in range(M):
            if is_first:
                x = fwd(params, jnp.asarray(toks[m]))
                send_array(to_next, jax.device_get(x))
            else:
                x_in[m] = jax.device_put(
                    recv_array(to_prev),
                    NamedSharding(mesh, P(None, None, None)))
        # ---- backward drain, reversed (same accumulation order)
        acc = None
        step_losses = []
        for m in reversed(range(M)):
            if is_last:
                loss, g, ct = lossgrad(params, x_in[m], jnp.asarray(toks[m]))
                step_losses.append(float(jax.device_get(loss)))
                send_array(to_prev, jax.device_get(ct))
            else:
                ct = jax.device_put(
                    recv_array(to_next),
                    NamedSharding(mesh, P(None, None, None)))
                g = bwd(params, jnp.asarray(toks[m]), ct)
            acc = g if acc is None else add(acc, g)
        params, opt_state = apply_upd(params, opt_state, acc)
        if is_last:
            losses.append(float(np.mean(step_losses)))

    for s in (to_prev, to_next):
        if s is not None:
            s.close()
    return {
        "stage": stage_id,
        "stages": num_stages,
        "local_devices": len(jax.devices()),
        "losses": losses,  # non-last stages report []
    }


def spawn_hetero_workers(base_port: int, timeout_s: float = 420.0
                         ) -> list[dict]:
    """Spawn one controller process per stage of the fixed workload and
    return their reports.  Each worker sees ONLY its stage's device count
    (xla_force_host_platform_device_count) — there is no shared runtime to
    fall back on, so passing the parity test genuinely demonstrates the
    per-slice-controller topology."""
    import os
    import subprocess

    _, stages = _plan_shape()
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    procs = []
    for i, ndev in enumerate(stages):
        env = {**os.environ,
               "JAX_PLATFORMS": "cpu",
               "XLA_FLAGS": f"--xla_force_host_platform_device_count={ndev}",
               "PYTHONPATH": repo}
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "metis_tpu.execution.multihost2",
             str(i), str(len(stages)), str(base_port)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env, cwd=repo))
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=timeout_s)
            if p.returncode != 0:
                raise RuntimeError(f"hetero worker failed:\n{err[-1500:]}")
            outs.append(json.loads(out.strip().splitlines()[-1]))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    return outs


def _plan_shape() -> tuple[tuple, list[int]]:
    """(strategies, per-stage device counts) without touching a backend —
    the spawner must not initialize jax in the parent."""
    counts = [s["dp"] * s["tp"] for s in STRATEGIES]
    return STRATEGIES, counts


if __name__ == "__main__":
    _stage, _n, _port = int(sys.argv[1]), int(sys.argv[2]), int(sys.argv[3])
    import jax as _jax

    _jax.config.update("jax_platforms", "cpu")
    print(json.dumps(run_stage_worker(_stage, _n, _port)), flush=True)
