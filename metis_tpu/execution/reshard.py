"""Live plan migration: reshard running state between two plans in place.

Every replan used to imply drain -> checkpoint -> rebuild -> digest-verified
restore — a filesystem round-trip whose cost dominates elastic recovery
(the PR-10 fleet drill bottoms out at goodput 0.6283 per event).  Following
the cross-mesh resharding half of arXiv 2211.05322, this module moves the
state over the device fabric instead:

1. **Delta** — :func:`plan_reshard` compares the source state's per-leaf
   shardings against a reference state initialized under the destination
   plan and keeps only the leaves whose layout actually changes (the
   minimal-transfer set; resident leaves are adopted as-is).
2. **Transfer** — :func:`execute_reshard` re-lays each moved leaf onto its
   destination sharding with ``jax.device_put`` (XLA lowers a cross-mesh
   device_put to the all-to-all / ppermute collective program over the
   surviving device intersection; ``execution.mesh.shard_params`` is the
   same primitive at init).  Each leaf transfer consults the
   ``reshard_send`` fault point and is retry-wrapped
   (``resilience/retry.RetryPolicy``) so transient fabric hiccups don't
   abort the migration.
3. **Verify** — the same sha256 per-leaf content digests the checkpoint
   path records (``execution.checkpoint._tree_digests`` — shape + dtype +
   bytes, sharding-independent) are computed on the source before and the
   destination after; any mismatch (or an injected ``reshard_verify``
   fault) raises :class:`~metis_tpu.core.errors.MigrationError`, and the
   caller degrades to checkpoint-restore — a failed migration never loses
   state, it just costs the old path.

The analytic half (:func:`stage_layout`, :func:`layout_moved_bytes`,
:func:`price_migration_ms`) prices a prospective switch from plan artifacts
alone — the same moved-bytes rule ``cost/estimator.py`` charges as the
additive ``migration`` term (``SearchConfig.migrate_from``), so the planner,
the serve daemon's replan notes, and the supervisor's go/no-go decision all
agree on what a switch costs before any state moves.
"""
from __future__ import annotations

import time
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from metis_tpu.core.errors import MigrationError
from metis_tpu.core.events import EventLog, NULL_LOG
from metis_tpu.execution.checkpoint import _tree_digests
from metis_tpu.execution.mesh import PP, PlanArtifact
from metis_tpu.resilience.faults import NULL_INJECTOR, FaultInjector
from metis_tpu.resilience.retry import RetryPolicy


# ---------------------------------------------------------------------------
# analytic layout delta + pricing (shared with cost/estimator.py)
# ---------------------------------------------------------------------------


def stage_layout(artifact: PlanArtifact,
                 num_layers: int | None = None) -> tuple:
    """Canonical per-stage layout of a plan artifact: one
    ``(tp, layer_start, layer_end)`` triple per pipeline stage — the
    ``SearchConfig.migrate_from`` encoding the migration cost term prices
    against.  Uniform artifacts (one strategy, pp in the mesh shape) are
    expanded to per-stage triples; artifacts without a recorded layer
    partition rebuild the canonical even split from ``num_layers``."""
    strategies = [dict(s) for s in artifact.strategies]
    if artifact.mesh_shape and PP in artifact.mesh_axes:
        pp = artifact.mesh_shape[artifact.mesh_axes.index(PP)]
    else:
        pp = len(strategies)
    if len(strategies) == 1 and pp > 1:
        strategies = strategies * pp
    bounds = tuple(artifact.layer_partition)
    if not bounds:
        if num_layers is None:
            raise ValueError(
                "artifact records no layer partition — pass num_layers to "
                "rebuild the canonical even split")
        from metis_tpu.cost.estimator import uniform_layer_split

        counts = uniform_layer_split(num_layers, pp)
        acc = [0]
        for c in counts:
            acc.append(acc[-1] + c)
        bounds = tuple(acc)
    return tuple((int(s["tp"]), int(bounds[i]), int(bounds[i + 1]))
                 for i, s in enumerate(strategies))


def layout_moved_bytes(old_layout: tuple, new_layout: tuple,
                       volume) -> float:
    """Parameter bytes a switch from ``old_layout`` to ``new_layout`` must
    move: every layer the new layout does NOT already hold at the same tp
    under some old stage transfers its (new-tp-sharded) parameter bytes.
    The identical rule ``cost/estimator._migration_ms`` amortizes — kept
    in lockstep so the priced term and the live transfer agree."""
    old_tp: dict[int, int] = {}
    for tp, start, end in old_layout:
        for layer in range(start, end):
            old_tp[layer] = tp
    moved = 0.0
    for tp, start, end in new_layout:
        per = volume.parameter_bytes_per_layer(tp)
        for layer in range(start, end):
            if old_tp.get(layer) != tp:
                moved += per[layer]
    return moved


def price_migration_ms(old_layout: tuple, new_layout: tuple, volume,
                       bw_gbps: float = 100.0) -> float:
    """One-time live-transfer cost of the switch, in ms (decimal GB/s —
    the native bandwidth convention).  This is the UN-amortized figure the
    supervisor compares against the measured checkpoint-restore time; the
    cost model divides the same bytes by ``migration_amortize_steps`` to
    make it a per-step term."""
    return layout_moved_bytes(old_layout, new_layout, volume) / (bw_gbps * 1e6)


def device_sets_intersect(old_cluster, new_cluster) -> bool:
    """Whether any device survives a topology change — the cheap first
    gate of migration eligibility (a live reshard needs a surviving
    intersection to move state over; a wholesale fleet swap does not
    have one and must go through the checkpoint)."""
    types = ({n.device_type for n in old_cluster.nodes}
             | {n.device_type for n in new_cluster.nodes})
    return any(
        min(old_cluster.num_devices_by_type(t),
            new_cluster.num_devices_by_type(t)) > 0
        for t in types)


def migration_eligible(old_kind: str, new_kind: str,
                       old_block_layout: str, new_block_layout: str,
                       devices_intersect: bool) -> tuple[bool, str]:
    """(eligible, reason) for a live in-memory reshard between two built
    executables.  Shape-compatibility is structural: the gspmd route's
    state is mesh-independent full logical arrays (always migratable to
    another gspmd plan), the pipeline route stacks blocks per stage (same
    recorded block layout required — a pp or schedule change alters leaf
    shapes), and the multi-mesh hetero route's per-stage state lists have
    no cross-plan adapter yet (documented limitation — checkpoint-restore
    handles it, as before)."""
    if not devices_intersect:
        return False, "old and new device sets are disjoint"
    if old_kind == "hetero" or new_kind == "hetero":
        return False, "hetero per-stage state has no live-reshard adapter"
    if old_kind != new_kind:
        return False, (f"state shapes differ across executors "
                       f"({old_kind} -> {new_kind})")
    if old_kind == "pipeline" and old_block_layout != new_block_layout:
        return False, (f"pipeline block layouts differ "
                       f"({old_block_layout} -> {new_block_layout})")
    return True, "ok"


# ---------------------------------------------------------------------------
# the live transfer
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class ReshardReport:
    """What one executed migration did."""

    leaves: int          # total state leaves
    moved: int           # leaves actually transferred
    moved_bytes: int     # bytes of the transferred leaves
    stall_ms: float      # wall-clock transfer + verify time
    verified: bool       # digest check ran and passed


def _leaf_nbytes(leaf) -> int:
    size = getattr(leaf, "size", 0)
    itemsize = getattr(getattr(leaf, "dtype", None), "itemsize", 0)
    return int(size) * int(itemsize)


def _shardings_match(src, dst) -> bool:
    """Whether a leaf is already laid out as the destination wants it —
    conservative: anything uncertain counts as a move (a redundant
    device_put of an already-placed array is cheap and correct)."""
    s = getattr(src, "sharding", None)
    d = getattr(dst, "sharding", None)
    if s is None or d is None:
        return False
    try:
        if s.device_set != d.device_set:
            return False
        return s.is_equivalent_to(d, src.ndim)
    except Exception:  # noqa: BLE001 — unknown sharding kinds just move
        return False


def plan_reshard(src_state, dst_reference) -> tuple[list, int, int]:
    """The minimal-transfer set: ``(moved_indices, total_leaves,
    moved_bytes)`` over the flattened state.  Raises
    :class:`MigrationError` when the two states are not the same logical
    state (tree structure or any leaf shape/dtype differs) — that is a
    checkpoint-restore job, not a reshard."""
    src_leaves, src_def = jax.tree_util.tree_flatten(src_state)
    dst_leaves, dst_def = jax.tree_util.tree_flatten(dst_reference)
    if src_def != dst_def:
        raise MigrationError(
            "src and dst state trees differ structurally — the plans do "
            "not share a state schema, reshard cannot apply")
    moved: list[int] = []
    moved_bytes = 0
    for i, (s, d) in enumerate(zip(src_leaves, dst_leaves)):
        if (getattr(s, "shape", None) != getattr(d, "shape", None)
                or getattr(s, "dtype", None) != getattr(d, "dtype", None)):
            raise MigrationError(
                f"state leaf {i} changes shape/dtype across the plans "
                f"({getattr(s, 'shape', None)}/{getattr(s, 'dtype', None)}"
                f" -> {getattr(d, 'shape', None)}/"
                f"{getattr(d, 'dtype', None)}) — reshard cannot apply")
        if not _shardings_match(s, d):
            moved.append(i)
            moved_bytes += _leaf_nbytes(s)
    return moved, len(src_leaves), moved_bytes


def execute_reshard(
    src_state,
    dst_reference,
    *,
    step: int | None = None,
    events: EventLog = NULL_LOG,
    faults: FaultInjector = NULL_INJECTOR,
    retry: RetryPolicy | None = None,
    sleep=time.sleep,
    verify: bool = True,
):
    """Reshard ``src_state`` onto ``dst_reference``'s layout and return
    ``(new_state, ReshardReport)``.

    ``dst_reference`` is a freshly initialized state under the destination
    plan — only its tree structure and leaf shardings are read; its values
    are discarded in favor of the source's.  Emits ``reshard_plan`` once,
    ``reshard_step`` per transferred leaf, and ``migration_complete`` on
    verified success.  Any failure — structural mismatch, exhausted
    ``reshard_send`` retries, digest mismatch, injected ``reshard_verify``
    fault — raises :class:`MigrationError` (or
    :class:`~metis_tpu.core.errors.RetryExhaustedError`) with the source
    state untouched, so the caller can fall back to checkpoint-restore.
    """
    t0 = time.perf_counter()
    src_digests = _tree_digests(src_state) if verify else {}
    moved, total, moved_bytes = plan_reshard(src_state, dst_reference)
    events.emit("reshard_plan", leaves=total, moved=len(moved),
                moved_bytes=moved_bytes, step=step)
    moved_set = set(moved)
    src_leaves, src_def = jax.tree_util.tree_flatten(src_state)
    dst_leaves, _ = jax.tree_util.tree_flatten(dst_reference)
    paths, _ = jax.tree_util.tree_flatten_with_path(src_state)
    policy = retry if retry is not None else RetryPolicy()

    out: list = []
    for i, (s, d) in enumerate(zip(src_leaves, dst_leaves)):
        if i not in moved_set:
            out.append(s)
            continue
        leaf_path = jax.tree_util.keystr(paths[i][0])

        def transfer(s=s, d=d):
            spec = faults.check("reshard_send", step)
            if spec is not None:
                raise OSError(
                    f"injected reshard_send fault (arg={spec.arg})")
            if not getattr(d, "_committed", True):
                # the destination executable left this leaf's placement to
                # the runtime (scalar opt-state counters and the like);
                # committing it to the reference's single device would pin
                # a device assignment the destination jit then rejects —
                # hand back an equally uncommitted copy instead
                return jnp.asarray(np.asarray(jax.device_get(s)))
            # stage through the canonical logical value (device_get's view
            # — the same bytes the checkpoint digests).  A direct
            # src->dst.sharding device_put lets XLA gather from ANY shard
            # claiming a logical index, and shards that claim to replicate
            # an index can drift on long-running dp ranks — the assembled
            # bytes would then depend on replica choice and fail the
            # digest check nondeterministically.
            return jax.device_put(np.asarray(jax.device_get(s)), d.sharding)

        out.append(policy.call(transfer, op=f"reshard_send:{leaf_path}",
                               events=events, sleep=sleep))
        events.emit("reshard_step", leaf=leaf_path,
                    bytes=_leaf_nbytes(s), step=step)
    new_state = jax.tree_util.tree_unflatten(src_def, out)

    verified = False
    if verify:
        if faults.check("reshard_verify", step) is not None:
            raise MigrationError(
                "injected reshard_verify fault: post-transfer digest "
                "mismatch")
        dst_digests = _tree_digests(new_state)
        if src_digests or dst_digests:
            bad = sorted(k for k, v in src_digests.items()
                         if dst_digests.get(k) != v)
            if bad:
                shown = ", ".join(bad[:3]) + ("..." if len(bad) > 3 else "")
                raise MigrationError(
                    f"reshard digest mismatch for {len(bad)} leaf/leaves "
                    f"({shown}) — state diverged in flight")
            verified = True
    stall_ms = (time.perf_counter() - t0) * 1000.0
    events.emit("migration_complete", leaves=total, moved=len(moved),
                moved_bytes=moved_bytes, stall_ms=round(stall_ms, 3),
                step=step)
    return new_state, ReshardReport(
        leaves=total, moved=len(moved), moved_bytes=moved_bytes,
        stall_ms=stall_ms, verified=verified)
