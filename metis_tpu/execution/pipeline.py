"""Pipeline-parallel training: GPipe schedule over a "pp" mesh axis.

JAX has no built-in pipeline parallelism (SURVEY.md §7 "Hard parts") — this
implements it SPMD-style with ``shard_map``: every device runs the same
program; the stacked block parameters are sharded along the layer axis over
"pp" so each pipeline rank physically holds only its stage's layers;
activations rotate stage-to-stage with ``lax.ppermute`` each tick.  With M
microbatches and S stages the schedule runs M + S - 1 ticks — exactly the
GPipe fill-drain the planner's cost model prices as
``(M - 1) * max_stage + sum(stages)`` (``cost/estimator.py``), closing the
predicted-vs-executed loop.

Inside ``shard_map`` GSPMD does not apply, so tensor parallelism here is
explicit Megatron-style SPMD: column-parallel qkv/mlp-in (per-head shards),
row-parallel proj/mlp-out followed by ``psum`` over "tp", vocab-parallel
embedding and cross-entropy.  Data parallelism shards the microbatch batch
dim.  Gradient reductions are NOT manual: with vma checking on (the
default), autodiff transposes the forward collectives exactly — grads arrive
reduced over "dp" and correctly replicated over "pp"/"tp" for invariant
leaves; adding manual psums double-counts (pinned by the grad-parity test).
"""
from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from metis_tpu.execution.mesh import DP, PP, TP, gpt_param_specs, shard_params
from metis_tpu.models.gpt import GPTConfig, _layer_norm, causal_attention, init_params

# ---------------------------------------------------------------------------
# Megatron-style manual-collective layers (for use inside shard_map)
# ---------------------------------------------------------------------------


def tp_embed(params: dict, tokens: jnp.ndarray, cfg: GPTConfig,
             tp_axis: str = TP) -> jnp.ndarray:
    """Vocab-parallel embedding: each tp rank holds a vocab slice, looks up
    in-range tokens, and the psum assembles full embeddings."""
    table = params["embed"]["tok"]          # local [V/t, h]
    v_local = table.shape[0]
    base = jax.lax.axis_index(tp_axis) * v_local
    local_ids = jnp.clip(tokens - base, 0, v_local - 1)
    in_range = (tokens >= base) & (tokens < base + v_local)
    emb = table.astype(cfg.dtype)[local_ids] * in_range[..., None].astype(cfg.dtype)
    emb = jax.lax.psum(emb, tp_axis)
    pos = params["embed"]["pos"].astype(cfg.dtype)[: tokens.shape[1]]
    return emb + pos[None, :, :]


def tp_block_forward(x: jnp.ndarray, layer: dict, cfg: GPTConfig,
                     tp_axis: str = TP) -> jnp.ndarray:
    """One transformer block with explicit tensor-parallel collectives.
    x: [b, s, h] replicated across tp; weight leaves are local tp shards."""
    dt = cfg.dtype
    hd = cfg.head_dim

    y = _layer_norm(x, layer["ln1_scale"], layer["ln1_bias"])
    # column-parallel qkv: local out dim h/t = (nh/t) heads
    qkv = jnp.einsum("bsh,chk->cbsk", y, layer["qkv"].astype(dt),
                     preferred_element_type=jnp.float32)
    qkv = (qkv + layer["qkv_bias"][:, None, None, :]).astype(dt)
    q, k, v = qkv[0], qkv[1], qkv[2]

    def heads(t):
        b, s, k_local = t.shape
        return t.reshape(b, s, k_local // hd, hd).transpose(0, 2, 1, 3)

    ctx = causal_attention(heads(q), heads(k), heads(v))
    b, nh_local, s, _ = ctx.shape
    ctx = ctx.transpose(0, 2, 1, 3).reshape(b, s, nh_local * hd)
    # row-parallel proj: partial sums -> psum
    attn_out = jnp.einsum("bsk,kh->bsh", ctx, layer["proj"].astype(dt),
                          preferred_element_type=jnp.float32)
    attn_out = jax.lax.psum(attn_out, tp_axis)
    x = x + (attn_out + layer["proj_bias"]).astype(dt)

    y = _layer_norm(x, layer["ln2_scale"], layer["ln2_bias"])
    z = jnp.einsum("bsh,hf->bsf", y, layer["mlp_in"].astype(dt),
                   preferred_element_type=jnp.float32)
    z = jax.nn.gelu((z + layer["mlp_in_bias"]).astype(jnp.float32)).astype(dt)
    z = jnp.einsum("bsf,fh->bsh", z, layer["mlp_out"].astype(dt),
                   preferred_element_type=jnp.float32)
    z = jax.lax.psum(z, tp_axis)
    return x + (z + layer["mlp_out_bias"]).astype(dt)


def tp_head_loss(params: dict, x: jnp.ndarray, targets: jnp.ndarray,
                 cfg: GPTConfig, tp_axis: str = TP) -> jnp.ndarray:
    """Vocab-parallel cross-entropy (Megatron-style): local logits slice,
    global max via pmax, normalizer and target logit via psum."""
    y = _layer_norm(x, params["head"]["ln_scale"], params["head"]["ln_bias"])
    w = params["head"]["out"]               # local [h, V/t]
    logits = jnp.einsum("bsh,hv->bsv", y, w.astype(cfg.dtype),
                        preferred_element_type=jnp.float32)
    v_local = logits.shape[-1]
    base = jax.lax.axis_index(tp_axis) * v_local

    # stability shift only — stop_gradient keeps pmax out of the VJP (it has
    # no differentiation rule, and the shift cancels in the loss anyway)
    gmax = jax.lax.stop_gradient(
        jax.lax.pmax(jax.lax.stop_gradient(logits).max(-1), tp_axis))
    sumexp = jax.lax.psum(
        jnp.exp(logits - gmax[..., None]).sum(-1), tp_axis)

    local_t = jnp.clip(targets - base, 0, v_local - 1)
    in_range = (targets >= base) & (targets < base + v_local)
    t_logit = jnp.take_along_axis(logits, local_t[..., None], axis=-1)[..., 0]
    t_logit = jax.lax.psum(jnp.where(in_range, t_logit, 0.0), tp_axis)

    nll = jnp.log(sumexp) + gmax - t_logit
    return nll.mean()


# ---------------------------------------------------------------------------
# GPipe schedule
# ---------------------------------------------------------------------------


def _pipeline_loss_local(
    params: dict,
    tokens_mbs: jnp.ndarray,   # [M, mbs_local, S]
    targets_mbs: jnp.ndarray,
    cfg: GPTConfig,
) -> jnp.ndarray:
    """Per-device GPipe body (inside shard_map over (pp, dp, tp))."""
    num_stages = jax.lax.axis_size(PP)
    stage = jax.lax.axis_index(PP)
    M = tokens_mbs.shape[0]
    ticks = M + num_stages - 1
    mbs_local, seq = tokens_mbs.shape[1], tokens_mbs.shape[2]

    fwd_perm = [(i, i + 1) for i in range(num_stages - 1)]

    def blocks_local(x):
        def step(carry, layer):
            return tp_block_forward(carry, layer, cfg), None
        out, _ = jax.lax.scan(step, x, params["blocks"])
        return out

    def tick(carry, t):
        buf, loss_sum = carry
        feed_idx = jnp.clip(t, 0, M - 1)
        tok = jax.lax.dynamic_index_in_dim(tokens_mbs, feed_idx, 0, False)
        x0 = tp_embed(params, tok, cfg)
        x_in = jnp.where(stage == 0, x0, buf)
        x_out = blocks_local(x_in)

        out_idx = jnp.clip(t - (num_stages - 1), 0, M - 1)
        tgt = jax.lax.dynamic_index_in_dim(targets_mbs, out_idx, 0, False)
        mb_loss = tp_head_loss(params, x_out, tgt, cfg)
        is_emitting = (stage == num_stages - 1) & (t >= num_stages - 1)
        loss_sum = loss_sum + jnp.where(is_emitting, mb_loss, 0.0)

        buf_next = (
            jax.lax.ppermute(x_out, PP, fwd_perm)
            if num_stages > 1 else x_out)
        return (buf_next, loss_sum), None

    # initial carries are replicated values but become device-varying inside
    # the loop (ppermute over pp, data over dp) — cast them up front so the
    # scan carry types match under the vma checker
    buf0 = jax.lax.pcast(
        jnp.zeros((mbs_local, seq, cfg.hidden), cfg.dtype), (PP, DP), to='varying')
    loss0 = jax.lax.pcast(jnp.zeros((), jnp.float32), (PP, DP), to='varying')
    (_, loss_sum), _ = jax.lax.scan(tick, (buf0, loss0), jnp.arange(ticks))

    # loss lives on the last stage; share it, and average over dp shards
    loss = jax.lax.psum(loss_sum, PP) / M
    return jax.lax.pmean(loss, DP)


def make_pipeline_train_step(
    cfg: GPTConfig,
    mesh: Mesh,
    num_microbatches: int,
    optimizer=None,
):
    """Jitted GPipe train step over a (pp, dp, tp) mesh.

    Requires ``cfg.num_blocks %% pp == 0`` (uniform stages — the stacked
    layer axis shards evenly; non-uniform stages are a planned extension).
    Returns (init_fn, step_fn): ``init_fn(key) -> (params, opt_state)`` on
    mesh; ``step_fn(params, opt_state, tokens, targets) -> (params,
    opt_state, loss)`` with tokens/targets [gbs_local..., seq] already
    microbatch-major: [M, batch, seq].
    """
    pp = mesh.shape[PP]
    if cfg.num_blocks % pp:
        raise ValueError(
            f"num_blocks={cfg.num_blocks} must divide evenly into pp={pp} "
            "stages for the uniform pipeline")
    optimizer = optimizer or optax.adamw(1e-4)
    specs = gpt_param_specs(cfg, tp_axis=TP, pp_axis=PP)
    data_spec = P(None, DP, None)  # [M, batch, seq]

    loss_local = partial(_pipeline_loss_local, cfg=cfg)

    # With vma checking on, autodiff through the manual collectives (tp
    # psums, the pp loss psum, the dp pmean) transposes exactly: gradients
    # arrive correctly reduced over dp and correctly replicated over pp for
    # the pipeline-replicated embed/head leaves.  No manual grad collectives
    # — adding them double-counts (caught by the grad-parity test).
    sharded_step = jax.shard_map(
        jax.value_and_grad(loss_local), mesh=mesh,
        in_specs=(specs, data_spec, data_spec),
        out_specs=(P(), specs),
    )

    def step_fn(params, opt_state, tokens_mbs, targets_mbs):
        if tokens_mbs.shape[0] != num_microbatches:
            raise ValueError(
                f"expected {num_microbatches} microbatches, got "
                f"{tokens_mbs.shape[0]} (use microbatch_split)")
        loss, grads = sharded_step(params, tokens_mbs, targets_mbs)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    with mesh:
        jitted = jax.jit(step_fn, donate_argnums=(0, 1))

    def init_fn(key):
        params = shard_params(init_params(key, cfg), mesh, specs)
        opt_state = optimizer.init(params)
        return params, opt_state

    def run(params, opt_state, tokens_mbs, targets_mbs):
        with mesh:
            return jitted(params, opt_state, tokens_mbs, targets_mbs)

    return init_fn, run


def microbatch_split(tokens: jnp.ndarray, num_microbatches: int) -> jnp.ndarray:
    """[gbs, seq] -> [M, gbs/M, seq] (microbatch-major layout the pipeline
    step consumes)."""
    gbs, seq = tokens.shape
    if gbs % num_microbatches:
        raise ValueError(f"gbs={gbs} not divisible into {num_microbatches} microbatches")
    return tokens.reshape(num_microbatches, gbs // num_microbatches, seq)
