"""Pipeline-parallel training: GPipe schedule over a "pp" mesh axis.

JAX has no built-in pipeline parallelism (SURVEY.md §7 "Hard parts") — this
implements it SPMD-style with ``shard_map``: every device runs the same
program; the stacked block parameters are sharded along the layer axis over
"pp" so each pipeline rank physically holds only its stage's layers;
activations rotate stage-to-stage with ``lax.ppermute`` each tick.  With M
microbatches and S stages the schedule runs M + S - 1 ticks — exactly the
GPipe fill-drain the planner's cost model prices as
``(M - 1) * max_stage + sum(stages)`` (``cost/estimator.py``), closing the
predicted-vs-executed loop.

**Communication overlap** (``overlap=True``, the default): the gpipe/1f1b
tick bodies are double-buffered — the scan carry holds the previous tick's
UNPERMUTED boundary send and its ``ppermute`` is issued at the TOP of the
next tick's body, where it has no data dependency on that tick's embed (or,
for the 1f1b cotangent ring, the whole forward slot), so XLA's async
collective scheduler can run the transfer under compute.  The manual-
backward schedules additionally chunk the dp gradient all-reduce
(``execution.train.chunked_pmean``) so it pipelines against the backward
tail and the optimizer step.  Both transformations are value-identical to
the lockstep schedule: tick t still consumes the permute of tick t-1's
output (zeros permute to zeros at t=0), and pmean is elementwise so
chunking is exact — pinned by the overlapped-vs-lockstep grad-parity
tests.  The cost model prices the exposed remainder accordingly
(``SearchConfig.use_overlap_model``).

Inside ``shard_map`` GSPMD does not apply, so tensor parallelism here is
explicit Megatron-style SPMD: column-parallel qkv/mlp-in (per-head shards),
row-parallel proj/mlp-out followed by ``psum`` over "tp", vocab-parallel
embedding and cross-entropy.  Data parallelism shards the microbatch batch
dim.  Gradient reductions are NOT manual: with vma checking on (the
default), autodiff transposes the forward collectives exactly — grads arrive
reduced over "dp" and correctly replicated over "pp"/"tp" for invariant
leaves; adding manual psums double-counts (pinned by the grad-parity test).
"""
from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from metis_tpu.core.compat import axis_size, pcast, shard_map, vma_of
from metis_tpu.core.events import EventLog, NULL_LOG
from metis_tpu.core.trace import Tracer
from metis_tpu.execution import train as _train
from metis_tpu.execution.mesh import DP, PP, TP, gpt_param_specs, shard_params
from metis_tpu.models.gpt import (
    GPTConfig, _layer_norm, default_attention, init_params)

# ---------------------------------------------------------------------------
# Megatron-style manual-collective layers (for use inside shard_map)
# ---------------------------------------------------------------------------


def tp_embed(params: dict, tokens: jnp.ndarray, cfg: GPTConfig,
             tp_axis: str = TP) -> jnp.ndarray:
    """Vocab-parallel embedding: each tp rank holds a vocab slice, looks up
    in-range tokens, and the psum assembles full embeddings."""
    table = params["embed"]["tok"]          # local [V/t, h]
    v_local = table.shape[0]
    base = jax.lax.axis_index(tp_axis) * v_local
    local_ids = jnp.clip(tokens - base, 0, v_local - 1)
    in_range = (tokens >= base) & (tokens < base + v_local)
    emb = table.astype(cfg.dtype)[local_ids] * in_range[..., None].astype(cfg.dtype)
    emb = jax.lax.psum(emb, tp_axis)
    pos = params["embed"]["pos"].astype(cfg.dtype)[: tokens.shape[1]]
    return emb + pos[None, :, :]


def tp_block_forward(x: jnp.ndarray, layer: dict, cfg: GPTConfig,
                     tp_axis: str = TP) -> jnp.ndarray:
    """One transformer block with explicit tensor-parallel collectives.
    x: [b, s, h] replicated across tp; weight leaves are local tp shards."""
    dt = cfg.dtype
    hd = cfg.head_dim

    y = _layer_norm(x, layer["ln1_scale"], layer["ln1_bias"])
    # column-parallel qkv: local out dim h/t = (nh/t) heads
    qkv = jnp.einsum("bsh,chk->cbsk", y, layer["qkv"].astype(dt),
                     preferred_element_type=jnp.float32)
    qkv = (qkv + layer["qkv_bias"][:, None, None, :]).astype(dt)
    q, k, v = qkv[0], qkv[1], qkv[2]

    def heads(t):
        b, s, k_local = t.shape
        return t.reshape(b, s, k_local // hd, hd).transpose(0, 2, 1, 3)

    # cfg.attn-resolved (dense or flash) — heads are tp-local here, so the
    # kernel sees [b, nh/t, s, hd] and tiles per shard
    ctx = default_attention(cfg)(heads(q), heads(k), heads(v))
    b, nh_local, s, _ = ctx.shape
    ctx = ctx.transpose(0, 2, 1, 3).reshape(b, s, nh_local * hd)
    # row-parallel proj: partial sums -> psum
    attn_out = jnp.einsum("bsk,kh->bsh", ctx, layer["proj"].astype(dt),
                          preferred_element_type=jnp.float32)
    attn_out = jax.lax.psum(attn_out, tp_axis)
    x = x + (attn_out + layer["proj_bias"]).astype(dt)

    y = _layer_norm(x, layer["ln2_scale"], layer["ln2_bias"])
    z = jnp.einsum("bsh,hf->bsf", y, layer["mlp_in"].astype(dt),
                   preferred_element_type=jnp.float32)
    z = jax.nn.gelu((z + layer["mlp_in_bias"]).astype(jnp.float32)).astype(dt)
    z = jnp.einsum("bsf,fh->bsh", z, layer["mlp_out"].astype(dt),
                   preferred_element_type=jnp.float32)
    z = jax.lax.psum(z, tp_axis)
    return x + (z + layer["mlp_out_bias"]).astype(dt)


def tp_head_loss(params: dict, x: jnp.ndarray, targets: jnp.ndarray,
                 cfg: GPTConfig, tp_axis: str = TP) -> jnp.ndarray:
    """Vocab-parallel cross-entropy (Megatron-style): local logits slice,
    global max via pmax, normalizer and target logit via psum."""
    y = _layer_norm(x, params["head"]["ln_scale"], params["head"]["ln_bias"])
    w = params["head"]["out"]               # local [h, V/t]
    logits = jnp.einsum("bsh,hv->bsv", y, w.astype(cfg.dtype),
                        preferred_element_type=jnp.float32)
    v_local = logits.shape[-1]
    base = jax.lax.axis_index(tp_axis) * v_local

    # stability shift only — stop_gradient keeps pmax out of the VJP (it has
    # no differentiation rule, and the shift cancels in the loss anyway)
    gmax = jax.lax.stop_gradient(
        jax.lax.pmax(jax.lax.stop_gradient(logits).max(-1), tp_axis))
    sumexp = jax.lax.psum(
        jnp.exp(logits - gmax[..., None]).sum(-1), tp_axis)

    local_t = jnp.clip(targets - base, 0, v_local - 1)
    in_range = (targets >= base) & (targets < base + v_local)
    t_logit = jnp.take_along_axis(logits, local_t[..., None], axis=-1)[..., 0]
    t_logit = jax.lax.psum(jnp.where(in_range, t_logit, 0.0), tp_axis)

    nll = jnp.log(sumexp) + gmax - t_logit
    return nll.mean()


# ---------------------------------------------------------------------------
# shared vma/reduction helpers for the manual-backward schedules
# ---------------------------------------------------------------------------


def _varying(x, axes=(PP, DP)):
    """Cast up to varying over ``axes``, skipping axes the value already
    varies over (param-derived zeros inherit the shards' vma)."""
    need = tuple(a for a in axes if a not in vma_of(x))
    return pcast(x, need, to='varying') if need else x


def _match_vma(ct, primal):
    """A cotangent must carry the primal output's exact vma."""
    need = tuple(a for a in vma_of(primal)
                 if a not in vma_of(ct))
    return pcast(ct, need, to='varying') if need else ct


def _vary_params_for_manual_vjp(params):
    """Mark every param leaf varying over (pp, dp) BEFORE a per-stage vjp:
    for a leaf the vjp sees as pp/dp-INVARIANT it would insert the
    invariance-restoring psum itself (each stage is mid-backward on a
    DIFFERENT microbatch, so that reduction both mixes microbatches and
    double-counts against the explicit psum/pmean of
    ``_reduce_pipeline_grads``).  Leaves stay tp-invariant where they are
    tp-replicated — the vjp's automatic tp reduction of their gradients is
    exactly Megatron's grad psum."""
    return jax.tree.map(lambda x: _varying(x, (PP, DP)), params)


def _gated_embed(params, tok, x_in, is_first, cfg):
    """tp_embed only where this device's current unit is the model's first
    (the predicate varies over pp but is tp-invariant, so the embed psum
    inside the taken branch is collective-safe); elsewhere the boundary
    input passes through untouched — the vocab lookup + psum is skipped,
    not just masked."""
    return jax.lax.cond(
        is_first,
        # branch outputs must agree in vma; the boundary input always
        # carries >= the embed's (it crossed pp rings), so cast up to it
        lambda xi: _match_vma(tp_embed(params, tok, cfg), xi),
        lambda xi: xi,
        x_in)


def _gated_head_loss(params, x_out, tgt, is_last, cfg):
    """tp_head_loss only on the model's last unit — the [hidden, vocab]
    projection + softmax rivals a whole block at realistic vocab sizes, so
    computing it on every stage/tick (as a masked-SPMD where would) wastes
    S x (or vs*S x, interleaved) its cost.  The zero branch carries the
    loss's (pp, dp) vma so cond types match."""
    return jax.lax.cond(
        is_last,
        lambda xo: tp_head_loss(params, xo, tgt, cfg),
        lambda xo: _varying(jnp.zeros((), jnp.float32), (PP, DP)),
        x_out)


def _reduce_pipeline_grads(gacc, loss_sum, M, dp_chunk_elems=None):
    """Final reductions shared by the manual-backward schedules: loss and
    grads average over microbatches and dp; pipeline-replicated leaves
    (embed/head) live on one stage each — psum over pp rebuilds the
    replicated gradient (contributions elsewhere are exactly zero).

    ``dp_chunk_elems`` (overlap schedule): chunk the dp all-reduce so the
    collectives pipeline against the backward tail and the optimizer step
    — exactly equal values, pmean is elementwise."""
    loss = jax.lax.psum(loss_sum, PP) / M
    loss = jax.lax.pmean(loss, DP)
    scaled = jax.tree.map(lambda g: g / M, gacc)
    if dp_chunk_elems is None:
        grads = jax.tree.map(lambda g: jax.lax.pmean(g, DP), scaled)
    else:
        grads = _train.chunked_pmean(scaled, DP, dp_chunk_elems)
    grads = {
        "embed": jax.tree.map(lambda g: jax.lax.psum(g, PP), grads["embed"]),
        "blocks": grads["blocks"],
        "head": jax.tree.map(lambda g: jax.lax.psum(g, PP), grads["head"]),
    }
    return loss, grads


# ---------------------------------------------------------------------------
# Schedules
#
# Three pipeline schedules share the Megatron-style TP layers above:
#
# - **GPipe** (``_pipeline_loss_local``): forward-only scan over
#   M + S - 1 ticks, loss out, gradients by autodiff through the scan.  XLA
#   stores every tick's residuals (all block internals), so peak activation
#   memory grows with the microbatch count M.
# - **1F1B, memory-bounded** (``_pipeline_1f1b_local``): each tick runs one
#   forward slot and one backward slot; backwards start as soon as the first
#   microbatch reaches the last stage, so at most ``min(M, 2(S-1)+1)``
#   boundary activations are live per stage — peak activation memory is
#   O(S), independent of M.  The backward slot recomputes its stage forward
#   from the saved boundary input (stage-granular rematerialization), the
#   standard memory/FLOPs trade.  Step time obeys the same fill-drain
#   formula the cost model prices (the bubble fraction (S-1)/(M+S-1) is
#   unchanged; ticks = M + 2(S-1) of fwd+bwd work vs GPipe's two passes).
# - **Interleaved virtual stages** (``_pipeline_interleaved_local``): each
#   device owns ``vs`` model chunks in the device-major interleaved layout;
#   microbatches run in groups of S over a vs*S-deep chunk pipeline with
#   wraparound rings, remat per unit.  The fill/drain exposes chunk units,
#   so the per-group bubble is (S-1)/(vs*S + S - 1) — smaller than GPipe's
#   when M is below ~vs*S (groups drain between themselves).
# ---------------------------------------------------------------------------


def _masked_blocks_scan(params, x, cfg, mask):
    """Stage-local block scan shared by the gpipe/1f1b bodies.

    ``mask`` (local [per_stage] bool, sharded over pp) marks which stacked
    slots hold REAL blocks — uneven layer partitions pad every stage to the
    largest stage's count with zero layers (``pad_blocks_for_partition``)
    and a padded slot passes the activation through unchanged.  The select
    form (not lax.cond) keeps the scan autodiff-safe on the gpipe path.
    COST: the schedule is lockstep (ppermute barriers), so every tick costs
    the LARGEST stage's block count on every device whether slots are
    padded or not — the planner prices uneven 1f1b plans with leveled
    max(lens) per stage accordingly (cost/estimator.py)."""
    if mask is None:
        def step(carry, layer):
            return tp_block_forward(carry, layer, cfg), None
        out, _ = jax.lax.scan(step, x, params["blocks"])
        return out

    def step(carry, layer_m):
        layer, m = layer_m
        out = tp_block_forward(carry, layer, cfg)
        return jnp.where(m, out, carry), None

    out, _ = jax.lax.scan(step, x, (params["blocks"], mask))
    return out


def _pipeline_loss_local(
    params: dict,
    tokens_mbs: jnp.ndarray,   # [M, mbs_local, S]
    targets_mbs: jnp.ndarray,
    mask=None,                 # local [per_stage] bool, or None (even split)
    *,
    cfg: GPTConfig,
    overlap: bool = False,
) -> jnp.ndarray:
    """Per-device GPipe body (inside shard_map over (pp, dp, tp)).

    ``overlap``: double-buffer the boundary send — the carry holds the
    previous tick's UNPERMUTED output and its ``ppermute`` is issued at the
    top of the body, before the embed it has no dependency on, so the
    transfer can run under that compute.  Tick t still consumes the permute
    of tick t-1's output either way (zeros permute to zeros at t=0), so
    loss and gradients are identical to lockstep."""
    num_stages = axis_size(PP)
    stage = jax.lax.axis_index(PP)
    M = tokens_mbs.shape[0]
    ticks = M + num_stages - 1
    mbs_local, seq = tokens_mbs.shape[1], tokens_mbs.shape[2]

    fwd_perm = [(i, i + 1) for i in range(num_stages - 1)]

    def blocks_local(x):
        return _masked_blocks_scan(params, x, cfg, mask)

    def tick(carry, t):
        buf, loss_sum = carry
        if overlap and num_stages > 1:
            # previous tick's unpermuted output: rotate it now, while the
            # embed below (which does not read it) can run concurrently
            buf = jax.lax.ppermute(buf, PP, fwd_perm)
        feed_idx = jnp.clip(t, 0, M - 1)
        tok = jax.lax.dynamic_index_in_dim(tokens_mbs, feed_idx, 0, False)
        # NOTE masked (where), not cond-gated like the manual-vjp schedules:
        # autodiff of cond+collectives through this whole-scan
        # value_and_grad path aborts inside XLA (runtime CHECK), so GPipe
        # keeps the compute-everywhere-select-one form
        x0 = tp_embed(params, tok, cfg)
        x_in = jnp.where(stage == 0, x0, buf)
        x_out = blocks_local(x_in)

        out_idx = jnp.clip(t - (num_stages - 1), 0, M - 1)
        tgt = jax.lax.dynamic_index_in_dim(targets_mbs, out_idx, 0, False)
        mb_loss = tp_head_loss(params, x_out, tgt, cfg)
        is_emitting = (stage == num_stages - 1) & (t >= num_stages - 1)
        loss_sum = loss_sum + jnp.where(is_emitting, mb_loss, 0.0)

        buf_next = (
            x_out if overlap or num_stages == 1
            else jax.lax.ppermute(x_out, PP, fwd_perm))
        return (buf_next, loss_sum), None

    # initial carries are replicated values but become device-varying inside
    # the loop (ppermute over pp, data over dp) — cast them up front so the
    # scan carry types match under the vma checker
    buf0 = pcast(
        jnp.zeros((mbs_local, seq, cfg.hidden), cfg.dtype), (PP, DP), to='varying')
    loss0 = pcast(jnp.zeros((), jnp.float32), (PP, DP), to='varying')
    (_, loss_sum), _ = jax.lax.scan(tick, (buf0, loss0), jnp.arange(ticks))

    # loss lives on the last stage; share it, and average over dp shards
    loss = jax.lax.psum(loss_sum, PP) / M
    return jax.lax.pmean(loss, DP)


def _pipeline_1f1b_local(
    params: dict,
    tokens_mbs: jnp.ndarray,   # [M, mbs_local, S]
    targets_mbs: jnp.ndarray,
    mask=None,                 # local [per_stage] bool, or None (even split)
    *,
    cfg: GPTConfig,
    overlap: bool = False,
) -> tuple[jnp.ndarray, dict]:
    """Per-device memory-bounded 1F1B body: returns ``(loss, grads)``.

    ``overlap``: the carries hold the previous tick's UNPERMUTED sends and
    both rings rotate at the top of the body — the cotangent permute is
    then in flight during the entire forward slot (and the activation
    permute during the stage-0 embed) instead of barriering the tick; the
    final dp gradient all-reduce additionally runs chunked
    (``execution.train.chunked_pmean``).  Values identical to lockstep.

    Schedule (global tick t, stage s, S stages, M microbatches):

    - forward slot: microbatch ``mf = t - s`` (GPipe forward timing);
    - backward slot: microbatch ``mb = t - (2(S-1) - s)`` — the last stage
      runs a microbatch's backward in the same tick as its forward, each
      earlier stage one tick later, so in-flight microbatches per stage
      never exceed ``2(S-1-s) + 1``.

    The stage's boundary input is saved in a ring of ``R = min(M, 2(S-1)+1)``
    slots; the backward slot recomputes the stage forward from the saved
    input with ``jax.vjp``.  Slot reuse is safe: a slot written by forward
    microbatch ``mb + R`` at tick ``s + mb + R`` is read by backward ``mb``
    at tick ``2(S-1) - s + mb``, and ``s + R >= 2(S-1) - s`` for every
    stage; within a tick the forward write precedes the backward read (the
    two coincide only on the last stage, where the same microbatch's input
    is written then immediately consumed).

    Gradients accumulate in the scan carry: the loss cotangent is seeded
    only on the last stage, the embed branch transposes to zero off stage 0,
    so per-leaf contributions live on their owning stage; the caller psums
    pipeline-replicated leaves over "pp" and pmeans everything over "dp".
    """
    num_stages = axis_size(PP)
    stage = jax.lax.axis_index(PP)
    M, mbs_local, seq = tokens_mbs.shape
    S = num_stages
    R = min(M, 2 * (S - 1) + 1)
    ticks = M + 2 * (S - 1)
    fwd_perm = [(i, i + 1) for i in range(S - 1)]
    bwd_perm = [(i + 1, i) for i in range(S - 1)]

    params = _vary_params_for_manual_vjp(params)

    def blocks_local(p, x):
        return _masked_blocks_scan(p, x, cfg, mask)

    def stage_fn(p, x_in, tok, tgt):
        """Uniform per-stage program: embed on stage 0, blocks, head loss on
        the last stage (loss cotangent seeded there only); embed/head run
        under lax.cond so the other stages skip their compute entirely."""
        x = _gated_embed(p, tok, x_in, stage == 0, cfg)
        x_out = blocks_local(p, x)
        loss = _gated_head_loss(p, x_out, tgt, stage == S - 1, cfg)
        return x_out, loss

    def tick(carry, t):
        buf_fwd, buf_ct, ring, gacc, loss_sum = carry
        if overlap and S > 1:
            # previous tick's unpermuted sends: rotate both rings now —
            # buf_ct is not read until the backward slot, so its transfer
            # runs under the whole forward slot's compute
            buf_fwd = jax.lax.ppermute(buf_fwd, PP, fwd_perm)
            buf_ct = jax.lax.ppermute(buf_ct, PP, bwd_perm)

        # ---- forward slot: microbatch t - stage
        mf = t - stage
        active_f = (mf >= 0) & (mf < M)
        mf_c = jnp.clip(mf, 0, M - 1)
        tok_f = jax.lax.dynamic_index_in_dim(tokens_mbs, mf_c, 0, False)
        x_in = _gated_embed(params, tok_f, buf_fwd, stage == 0, cfg)
        # save the boundary input (masked in-place: an inactive slot keeps
        # its old value — mf_c clips onto live slots, so a blind write would
        # clobber them)
        slot_f = mf_c % R
        old = jax.lax.dynamic_index_in_dim(ring, slot_f, 0, False)
        ring = jax.lax.dynamic_update_index_in_dim(
            ring, jnp.where(active_f, x_in, old), slot_f, 0)
        x_out = blocks_local(params, x_in)

        # ---- backward slot: microbatch t - (2(S-1) - stage)
        mb = t - (2 * (S - 1) - stage)
        active_b = (mb >= 0) & (mb < M)
        mb_c = jnp.clip(mb, 0, M - 1)
        tok_b = jax.lax.dynamic_index_in_dim(tokens_mbs, mb_c, 0, False)
        tgt_b = jax.lax.dynamic_index_in_dim(targets_mbs, mb_c, 0, False)
        x_saved = jax.lax.dynamic_index_in_dim(ring, mb_c % R, 0, False)

        is_last = stage == S - 1
        (x_p, loss_p), pull = jax.vjp(
            lambda p, x: stage_fn(p, x, tok_b, tgt_b), params, x_saved)
        # cotangents: boundary ct from the next stage, except the last
        # stage, which seeds the loss instead
        ct_x = _match_vma(jnp.where(is_last, jnp.zeros_like(buf_ct), buf_ct),
                          x_p)
        ct_loss = _match_vma(
            jnp.where(is_last & active_b, 1.0, 0.0).astype(loss_p.dtype),
            loss_p)
        g_params, g_x = pull((ct_x, ct_loss))
        gacc = jax.tree.map(
            lambda a, g: a + jnp.where(active_b, g, jnp.zeros_like(g)),
            gacc, g_params)
        loss_sum = loss_sum + jnp.where(active_b & is_last, loss_p, 0.0)

        # ---- rotate: activations forward, cotangents backward (overlap:
        # carry the unpermuted sends, the next tick rotates them at its top)
        ct_send = jnp.where(active_b, g_x, jnp.zeros_like(g_x))
        if overlap or S == 1:
            buf_fwd, buf_ct = x_out, ct_send
        else:
            buf_fwd = jax.lax.ppermute(x_out, PP, fwd_perm)
            buf_ct = jax.lax.ppermute(ct_send, PP, bwd_perm)
        return (buf_fwd, buf_ct, ring, gacc, loss_sum), None

    act = jnp.zeros((mbs_local, seq, cfg.hidden), cfg.dtype)
    carry0 = (
        _varying(act),                       # buf_fwd
        _varying(act),                       # buf_ct
        _varying(jnp.zeros((R,) + act.shape, cfg.dtype)),  # ring
        jax.tree.map(                        # gacc: local grad shards
            lambda p: _varying(jnp.zeros_like(p, dtype=jnp.float32)), params),
        _varying(jnp.zeros((), jnp.float32)),  # loss_sum
    )
    (_, _, _, gacc, loss_sum), _ = jax.lax.scan(
        tick, carry0, jnp.arange(ticks))
    return _reduce_pipeline_grads(
        gacc, loss_sum, M,
        dp_chunk_elems=_train.DP_CHUNK_ELEMS if overlap else None)


def uneven_pad_indices(block_counts) -> list[int]:
    """Padded stacked-axis layout for an uneven layer partition: stage ``s``
    owns slots ``[s*per_stage, (s+1)*per_stage)`` with its ``counts[s]``
    real blocks first (global block order preserved) and ``-1`` pad slots
    after — the contiguous pp sharding of ``gpt_param_specs`` then lands
    each device exactly its stage's blocks."""
    per_stage = max(block_counts)
    idx: list[int] = []
    off = 0
    for c in block_counts:
        idx += list(range(off, off + c)) + [-1] * (per_stage - c)
        off += c
    return idx


def pad_blocks_for_partition(blocks, block_counts):
    """Reorder + zero-pad the stacked block leaves per
    ``uneven_pad_indices`` (pad layers are zeros — never applied: the
    schedule bodies mask them to identity)."""
    idx = uneven_pad_indices(block_counts)

    def pad_leaf(a):
        z = jnp.zeros_like(a[:1])
        return jnp.concatenate(
            [a[i:i + 1] if i >= 0 else z for i in idx], axis=0)

    return jax.tree.map(pad_leaf, blocks)


def unpad_blocks_for_partition(blocks, block_counts):
    """Inverse of ``pad_blocks_for_partition``: drop pad slots and restore
    the canonical global block order (for export/inspection)."""
    idx = uneven_pad_indices(block_counts)
    keep = [i for i, b in enumerate(idx) if b >= 0]

    def unpad_leaf(a):
        return jnp.concatenate([a[i:i + 1] for i in keep], axis=0)

    return jax.tree.map(unpad_leaf, blocks)


def interleave_block_order(num_blocks: int, pp: int, vs: int) -> list[int]:
    """Block permutation for the interleaved schedule: device ``s`` owns
    virtual chunks ``v`` covering global blocks ``(v*pp + s)*K .. +K`` with
    ``K = num_blocks // (pp * vs)``; the stacked block axis is reordered
    device-major (s, v, k) so the contiguous pp sharding of
    ``gpt_param_specs`` lands each device exactly its chunks."""
    K = num_blocks // (pp * vs)
    return [(v * pp + s) * K + k
            for s in range(pp) for v in range(vs) for k in range(K)]


def _pipeline_interleaved_local(
    params: dict,
    tokens_mbs: jnp.ndarray,   # [M, mbs_local, S]
    targets_mbs: jnp.ndarray,
    cfg: GPTConfig,
    vs: int,
    overlap: bool = False,
) -> tuple[jnp.ndarray, dict]:
    """Per-device interleaved-pipeline body: returns ``(loss, grads)``.

    ``overlap`` here chunks the final dp gradient all-reduce only — the
    wraparound chunk rings stay lockstep: their permute result feeds the
    ring-slot bookkeeping at the top of the next tick, so hoisting them
    buys no scheduling freedom (unlike gpipe/1f1b, whose hoisted permute
    is independent of the next tick's embed/forward slot).

    Each device holds ``vs`` virtual chunks of ``K = L/(S*vs)`` blocks
    (device-major interleaved layout, ``interleave_block_order``); a
    microbatch traverses chunk 0 across all stages, wraps around the ring,
    then chunk 1, and so on.  Microbatches run in groups of S:

    - forward tick t: unit ``(g, v)`` with ``g + v*S = t - s`` (unique
      decomposition, so each device runs exactly one chunk-unit per tick);
      activations move stage s -> s+1, wrapping S-1 -> 0 into the next
      chunk; ticks per group = vs*S + S - 1;
    - backward mirrors it reversed (``t' = vs*S + S - 2 - (g + v*S + s)``),
      cotangents move s -> s-1 wrapping 0 -> S-1, each unit recomputing its
      chunk forward from the saved boundary input (stage-level remat, as
      the 1f1b schedule).

    The pipeline fill/drain exposes only CHUNK units (K layers), so the
    bubble is ~1/vs of GPipe's per group — the interleaved schedule's
    point (at the price of S x more frequent, S x smaller boundary sends,
    which ride the same links).  Peak boundary storage is vs*S inputs per
    device per group.
    """
    S = axis_size(PP)
    stage = jax.lax.axis_index(PP)
    M, mbs_local, seq = tokens_mbs.shape
    if M % S:
        raise ValueError(
            f"interleaved schedule needs microbatches ({M}) divisible by "
            f"pp ({S}) — microbatches run in groups of S")
    groups = M // S
    VS = vs * S
    ticks = VS + S - 1
    fwd_perm = [(i, (i + 1) % S) for i in range(S)]
    bwd_perm = [(i, (i - 1) % S) for i in range(S)]
    local_blocks = jax.tree.leaves(params["blocks"])[0].shape[0]
    K = local_blocks // vs

    params = _vary_params_for_manual_vjp(params)

    def chunk_fwd(p, x, v):
        chunk = jax.tree.map(
            lambda a: jax.lax.dynamic_slice_in_dim(a, v * K, K, 0),
            p["blocks"])

        def step(carry, layer):
            return tp_block_forward(carry, layer, cfg), None
        out, _ = jax.lax.scan(step, x, chunk)
        return out

    def unit_fn(p, x_in, tok, tgt, v):
        """One (chunk, stage) unit; embed on the first unit, head loss on
        the last (its cotangent is seeded only there); both gated under
        lax.cond so every other unit skips the compute."""
        x = _gated_embed(p, tok, x_in, (v == 0) & (stage == 0), cfg)
        x_out = chunk_fwd(p, x, v)
        loss = _gated_head_loss(
            p, x_out, tgt, (v == vs - 1) & (stage == S - 1), cfg)
        return x_out, loss

    act = jnp.zeros((mbs_local, seq, cfg.hidden), cfg.dtype)

    def run_group(carry, grp):
        gacc, loss_sum = carry
        toks = jax.lax.dynamic_slice_in_dim(tokens_mbs, grp * S, S, 0)
        tgts = jax.lax.dynamic_slice_in_dim(targets_mbs, grp * S, S, 0)

        # ---- forward fill: save every unit's boundary input
        def ftick(fc, t):
            buf, ring = fc
            u = t - stage
            active = (u >= 0) & (u < VS)
            u_c = jnp.clip(u, 0, VS - 1)
            v, g = u_c // S, u_c % S
            tok = jax.lax.dynamic_index_in_dim(toks, g, 0, False)
            x_in = _gated_embed(
                params, tok, buf, (v == 0) & (stage == 0), cfg)
            old = jax.lax.dynamic_index_in_dim(ring, u_c, 0, False)
            ring = jax.lax.dynamic_update_index_in_dim(
                ring, jnp.where(active, x_in, old), u_c, 0)
            x_out = chunk_fwd(params, x_in, v)
            buf = jax.lax.ppermute(x_out, PP, fwd_perm) if S > 1 else x_out
            return (buf, ring), None

        ring0 = _varying(jnp.zeros((VS,) + act.shape, cfg.dtype))
        (_, ring), _ = jax.lax.scan(
            ftick, (_varying(act), ring0), jnp.arange(ticks))

        # ---- backward drain: reversed order, remat per unit
        def btick(bc, tb):
            gacc, loss_sum, buf_ct = bc
            u = (VS + S - 2) - stage - tb      # g + v*S
            active = (u >= 0) & (u < VS)
            u_c = jnp.clip(u, 0, VS - 1)
            v, g = u_c // S, u_c % S
            tok = jax.lax.dynamic_index_in_dim(toks, g, 0, False)
            tgt = jax.lax.dynamic_index_in_dim(tgts, g, 0, False)
            x_saved = jax.lax.dynamic_index_in_dim(ring, u_c, 0, False)
            is_last = (v == vs - 1) & (stage == S - 1)
            (x_p, loss_p), pull = jax.vjp(
                lambda p, x: unit_fn(p, x, tok, tgt, v), params, x_saved)
            ct_x = _match_vma(
                jnp.where(is_last, jnp.zeros_like(buf_ct), buf_ct), x_p)
            ct_loss = _match_vma(
                jnp.where(is_last & active, 1.0, 0.0).astype(loss_p.dtype),
                loss_p)
            g_params, g_x = pull((ct_x, ct_loss))
            gacc = jax.tree.map(
                lambda a, gr: a + jnp.where(active, gr, jnp.zeros_like(gr)),
                gacc, g_params)
            loss_sum = loss_sum + jnp.where(active & is_last, loss_p, 0.0)
            ct_send = jnp.where(active, g_x, jnp.zeros_like(g_x))
            buf_ct = (jax.lax.ppermute(ct_send, PP, bwd_perm)
                      if S > 1 else ct_send)
            return (gacc, loss_sum, buf_ct), None

        (gacc, loss_sum, _), _ = jax.lax.scan(
            btick, (gacc, loss_sum, _varying(act)), jnp.arange(ticks))
        return (gacc, loss_sum), None

    gacc0 = jax.tree.map(
        lambda p: _varying(jnp.zeros_like(p, dtype=jnp.float32)), params)
    (gacc, loss_sum), _ = jax.lax.scan(
        run_group, (gacc0, _varying(jnp.zeros((), jnp.float32))),
        jnp.arange(groups))
    return _reduce_pipeline_grads(
        gacc, loss_sum, M,
        dp_chunk_elems=_train.DP_CHUNK_ELEMS if overlap else None)


def make_pipeline_train_step(
    cfg: GPTConfig,
    mesh: Mesh,
    num_microbatches: int,
    optimizer=None,
    schedule: str = "gpipe",
    virtual_stages: int = 2,
    block_counts=None,
    events: EventLog = NULL_LOG,
    overlap: bool = True,
):
    """Jitted pipeline train step over a (pp, dp, tp) mesh.

    ``overlap`` (default on) runs the communication-overlap schedule:
    double-buffered boundary ``ppermute`` (gpipe/1f1b — the send is issued
    at the top of the next tick's body, under compute it has no dependency
    on) and chunked dp gradient all-reduce (manual-backward schedules,
    ``execution.train.chunked_pmean``).  Loss and gradients are identical
    to the lockstep schedule (``overlap=False``) — the transformations
    only reorder when collectives are issued; emits one
    ``pipeline_overlap`` event when active.

    ``schedule`` picks "gpipe" (forward scan + autodiff backward; activation
    memory grows with the microbatch count), "1f1b" (memory-bounded
    one-forward-one-backward with stage-level rematerialization; peak
    boundary activations O(pp)), or "interleaved" (each device owns
    ``virtual_stages`` model chunks in the device-major interleaved layout;
    microbatches run in groups of pp with a bubble of
    (pp-1)/(virtual_stages*pp + pp - 1) per group — smaller than GPipe's
    when the microbatch count is below ~virtual_stages*pp, since this
    implementation drains between groups rather than overlapping them).
    All produce identical losses and gradients (pinned by the parity
    tests).  NOTE the interleaved layout also changes the physical block
    order of params/checkpoints (``interleave_block_order``) — resume
    compares ``CheckpointMeta.block_layout``.

    ``events`` (optional ``core.events.EventLog``): phase observability via
    the flight recorder — ``pipeline_init`` and ``pipeline_first_step``
    spans time the on-mesh parameter initialization and the first (XLA
    compile-dominated) step invocation, so a trace distinguishes compile
    time from the steady-state step times the cost-model accuracy ledger
    scores (``obs/ledger.AccuracyMonitor`` skips those compile steps).

    ``block_counts`` (optional, len == pp, sum == ``cfg.num_blocks``): an
    UNEVEN per-stage block partition for the gpipe/1f1b schedules.  Every
    stage is padded to the largest stage's count with zero layers that the
    schedule bodies mask to identity (``pad_blocks_for_partition``), so the
    stacked layer axis still shards evenly.  The schedule stays lockstep,
    so each tick costs the largest stage's count on every device — the
    value of an uneven split is FEASIBILITY (running partitions the even
    split can't express at all), and the planner prices it with leveled
    max-stage lens (cost/estimator.py).  Without it,
    ``cfg.num_blocks %% pp == 0`` is required (the interleaved schedule
    always requires the even split — its chunk permutation has no pad
    concept; fully per-stage-custom plans run on the multi-mesh executor
    in ``execution.hetero``).
    Returns (init_fn, step_fn): ``init_fn(key) -> (params, opt_state)`` on
    mesh; ``step_fn(params, opt_state, tokens, targets) -> (params,
    opt_state, loss)`` with tokens/targets [gbs_local..., seq] already
    microbatch-major: [M, batch, seq].
    """
    pp = mesh.shape[PP]
    counts = None
    if block_counts is not None:
        counts = tuple(int(c) for c in block_counts)
        if (len(counts) != pp or sum(counts) != cfg.num_blocks
                or min(counts) < 1):
            raise ValueError(
                f"block_counts={counts} must have one entry >= 1 per "
                f"pp={pp} stage summing to num_blocks={cfg.num_blocks}")
        if len(set(counts)) == 1:
            counts = None  # even: the unpadded fast path
    if counts is None:
        if cfg.num_blocks % pp:
            raise ValueError(
                f"num_blocks={cfg.num_blocks} must divide evenly into "
                f"pp={pp} stages for the uniform pipeline (pass "
                "block_counts for an uneven gpipe/1f1b split)")
    elif schedule == "interleaved":
        raise ValueError(
            "interleaved schedule requires an even block split "
            f"(got block_counts={counts})")
    if schedule not in ("gpipe", "1f1b", "interleaved"):
        raise ValueError(f"unknown pipeline schedule {schedule!r}")
    if schedule == "interleaved":
        if virtual_stages < 1:
            raise ValueError(
                f"virtual_stages={virtual_stages} must be >= 1")
        if cfg.num_blocks % (pp * virtual_stages):
            raise ValueError(
                f"interleaved schedule needs num_blocks={cfg.num_blocks} "
                f"divisible by pp*virtual_stages={pp * virtual_stages}")
        if num_microbatches % pp:
            raise ValueError(
                f"interleaved schedule runs microbatches in groups of "
                f"pp={pp}; {num_microbatches} microbatches don't divide")
    optimizer = optimizer or optax.adamw(1e-4)
    specs = gpt_param_specs(cfg, tp_axis=TP, pp_axis=PP)
    data_spec = P(None, DP, None)  # [M, batch, seq]

    # With vma checking on, autodiff through the manual collectives (tp
    # psums, the pp loss psum, the dp pmean) transposes exactly: gradients
    # arrive correctly reduced over dp and correctly replicated over pp for
    # the pipeline-replicated embed/head leaves.  No manual grad collectives
    # — adding them double-counts (caught by the grad-parity test).
    if schedule == "gpipe":
        local = jax.value_and_grad(
            partial(_pipeline_loss_local, cfg=cfg, overlap=overlap))
    elif schedule == "1f1b":
        local = partial(_pipeline_1f1b_local, cfg=cfg, overlap=overlap)
    else:
        local = partial(_pipeline_interleaved_local, cfg=cfg,
                        vs=virtual_stages, overlap=overlap)
    if overlap:
        # gpipe's dp reduction is autodiff-inserted (the loss pmean
        # transposes), so only the manual-backward schedules chunk it
        events.emit(
            "pipeline_overlap", schedule=schedule,
            dp_chunk_elems=(0 if schedule == "gpipe"
                            else _train.DP_CHUNK_ELEMS))
    # uneven split: the per-slot real-block mask rides along as an extra
    # sharded operand (a closure capture would be pp-replicated; the mask
    # must vary per stage)
    mask_global = (jnp.asarray([b >= 0 for b in uneven_pad_indices(counts)])
                   if counts is not None else None)
    mask_specs = (P(PP),) if counts is not None else ()
    sharded_step = shard_map(
        local, mesh=mesh,
        in_specs=(specs, data_spec, data_spec) + mask_specs,
        out_specs=(P(), specs),
    )

    def step_fn(params, opt_state, tokens_mbs, targets_mbs):
        if tokens_mbs.shape[0] != num_microbatches:
            raise ValueError(
                f"expected {num_microbatches} microbatches, got "
                f"{tokens_mbs.shape[0]} (use microbatch_split)")
        extra = (mask_global,) if mask_global is not None else ()
        loss, grads = sharded_step(params, tokens_mbs, targets_mbs, *extra)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    with mesh:
        jitted = jax.jit(step_fn, donate_argnums=(0, 1))

    tracer = Tracer(events)

    def init_fn(key):
        with tracer.span("pipeline_init", schedule=schedule, pp=pp,
                         microbatches=num_microbatches):
            return _init(key)

    def _init(key):
        full = init_params(key, cfg)
        if schedule == "interleaved":
            # reorder the stacked block axis device-major so the contiguous
            # pp sharding gives device s its virtual chunks (the optimizer,
            # grads, and checkpoints all live in this layout consistently)
            order = jnp.asarray(interleave_block_order(
                cfg.num_blocks, pp, virtual_stages))
            full = {**full,
                    "blocks": jax.tree.map(lambda a: a[order], full["blocks"])}
        elif counts is not None:
            # uneven split: pad each stage's slice to the largest stage's
            # count with masked zero layers (params/opt_state/checkpoints
            # all live in this padded layout consistently)
            full = {**full, "blocks": pad_blocks_for_partition(
                full["blocks"], counts)}
        params = shard_params(full, mesh, specs)
        opt_state = optimizer.init(params)
        return params, opt_state

    first_step = [True]

    def run(params, opt_state, tokens_mbs, targets_mbs):
        if first_step[0]:
            # the compile-dominated first invocation gets its own span so a
            # trace (and the accuracy ledger's skip_steps) can separate XLA
            # compile time from the steady-state steps the planner priced
            first_step[0] = False
            with tracer.span("pipeline_first_step", schedule=schedule,
                             pp=pp, microbatches=num_microbatches):
                with mesh:
                    out = jitted(params, opt_state, tokens_mbs, targets_mbs)
                if tracer.enabled:
                    jax.block_until_ready(out[2])  # loss — bound the span
                return out
        with mesh:
            return jitted(params, opt_state, tokens_mbs, targets_mbs)

    return init_fn, run


def microbatch_split(tokens: jnp.ndarray, num_microbatches: int) -> jnp.ndarray:
    """[gbs, seq] -> [M, gbs/M, seq] (microbatch-major layout the pipeline
    step consumes)."""
    gbs, seq = tokens.shape
    if gbs % num_microbatches:
        raise ValueError(f"gbs={gbs} not divisible into {num_microbatches} microbatches")
    return tokens.reshape(num_microbatches, gbs // num_microbatches, seq)
