"""Plan -> jax.sharding.Mesh + NamedSharding emission.

The planner's output becomes an executable artifact here (SURVEY.md §7 step 8
— replaces the reference's printed Megatron rank tuples,
``cost_het_cluster.py:43-45``): a uniform plan maps to a ("pp", "dp", "tp")
device mesh; parameters get Megatron-style PartitionSpecs (column-parallel
qkv/mlp-in, row-parallel proj/mlp-out, vocab-parallel embedding/head); the
batch shards over dp.  Everything below is GSPMD-first: specs + sharding
constraints, XLA inserts the collectives over ICI.
"""
from __future__ import annotations

import json
from dataclasses import dataclass

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from metis_tpu.core.types import UniformPlan
from metis_tpu.models.gpt import GPTConfig

PP, DP, TP, SP, EP = "pp", "dp", "tp", "sp", "ep"


def mesh_for_uniform_plan(plan: UniformPlan, devices=None) -> Mesh:
    """(pp, dp, tp) mesh over the device list (row-major, matching the
    planner's linear rank placement)."""
    devs = np.asarray(devices if devices is not None else jax.devices())
    need = plan.pp * plan.dp * plan.tp
    if devs.size < need:
        raise ValueError(f"plan needs {need} devices, have {devs.size}")
    grid = devs.flatten()[:need].reshape(plan.pp, plan.dp, plan.tp)
    return Mesh(grid, (PP, DP, TP))


def mesh_dp_tp(dp: int, tp: int, devices=None) -> Mesh:
    """(dp, tp) mesh for non-pipelined execution."""
    devs = np.asarray(devices if devices is not None else jax.devices())
    if devs.size < dp * tp:
        raise ValueError(f"mesh needs {dp * tp} devices, have {devs.size}")
    grid = devs.flatten()[: dp * tp].reshape(dp, tp)
    return Mesh(grid, (DP, TP))


def gpt_param_specs(cfg: GPTConfig, tp_axis: str = TP, pp_axis: str | None = None) -> dict:
    """PartitionSpec tree matching models.gpt.init_params.

    ``pp_axis`` shards the stacked block-layer axis (pipeline stages own
    contiguous layer slices); requires num_blocks % pp == 0.
    """
    t, p = tp_axis, pp_axis
    return {
        "embed": {
            "tok": P(t, None),      # vocab-parallel embedding
            "pos": P(),
        },
        "blocks": {
            "ln1_scale": P(p, None),
            "ln1_bias": P(p, None),
            "qkv": P(p, None, None, t),  # column-parallel (per-head)
            "qkv_bias": P(p, None, t),
            "proj": P(p, t, None),      # row-parallel
            "proj_bias": P(p, None),
            "ln2_scale": P(p, None),
            "ln2_bias": P(p, None),
            "mlp_in": P(p, None, t),    # column-parallel
            "mlp_in_bias": P(p, t),
            "mlp_out": P(p, t, None),   # row-parallel
            "mlp_out_bias": P(p, None),
        },
        "head": {
            "ln_scale": P(),
            "ln_bias": P(),
            "out": P(None, t),      # vocab-parallel head
        },
    }


def moe_param_specs(
    cfg, tp_axis: str = TP, ep_axis: str = EP, pp_axis: str | None = None
) -> dict:
    """PartitionSpec tree matching models.moe.init_moe_params.

    Expert weights shard their leading num_experts axis over ``ep_axis`` —
    GSPMD then inserts the token all-to-alls around the expert einsums
    (models.moe docstring); dense weights follow the Megatron TP layout of
    ``gpt_param_specs``.
    """
    t, p, e = tp_axis, pp_axis, ep_axis
    specs = gpt_param_specs(cfg, tp_axis=tp_axis, pp_axis=pp_axis)
    blocks = dict(specs["blocks"])
    for key in ("mlp_in", "mlp_in_bias", "mlp_out", "mlp_out_bias"):
        del blocks[key]
    blocks.update({
        "router": P(p, None, None),
        "expert_in": P(p, e, None, t),       # column-parallel within expert
        "expert_in_bias": P(p, e, t),
        "expert_out": P(p, e, t, None),      # row-parallel within expert
        "expert_out_bias": P(p, e, None),
    })
    return {**specs, "blocks": blocks}


def batch_spec(dp_axis: str = DP, seq_axis: str | None = None) -> P:
    """Sharding for [batch, seq] token arrays."""
    return P(dp_axis, seq_axis)


def shard_params(params: dict, mesh: Mesh, specs: dict) -> dict:
    """Place a parameter pytree onto the mesh with the given specs."""
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), params, specs)


@dataclass(frozen=True)
class PlanArtifact:
    """Serializable chosen plan — the bridge from search to execution (and
    the 'checkpoint' of the search, SURVEY.md §5 Checkpoint/resume)."""

    mesh_axes: tuple[str, ...]
    mesh_shape: tuple[int, ...]
    layer_partition: tuple[int, ...]
    strategies: tuple[dict, ...]
    gbs: int
    microbatches: int

    def to_json(self) -> str:
        return json.dumps({
            "mesh_axes": list(self.mesh_axes),
            "mesh_shape": list(self.mesh_shape),
            "layer_partition": list(self.layer_partition),
            "strategies": list(self.strategies),
            "gbs": self.gbs,
            "microbatches": self.microbatches,
        }, indent=2)

    @staticmethod
    def from_json(payload: str) -> "PlanArtifact":
        d = json.loads(payload)
        return PlanArtifact(
            mesh_axes=tuple(d["mesh_axes"]),
            mesh_shape=tuple(d["mesh_shape"]),
            layer_partition=tuple(d["layer_partition"]),
            strategies=tuple(d["strategies"]),
            gbs=d["gbs"],
            microbatches=d["microbatches"],
        )

    @staticmethod
    def from_uniform_plan(plan: UniformPlan) -> "PlanArtifact":
        return PlanArtifact(
            mesh_axes=(PP, DP, TP),
            mesh_shape=(plan.pp, plan.dp, plan.tp),
            layer_partition=(),
            strategies=({"dp": plan.dp, "tp": plan.tp},),
            gbs=plan.gbs,
            microbatches=plan.num_microbatches,
        )
