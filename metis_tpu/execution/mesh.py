"""Plan -> jax.sharding.Mesh + NamedSharding emission.

The planner's output becomes an executable artifact here (SURVEY.md §7 step 8
— replaces the reference's printed Megatron rank tuples,
``cost_het_cluster.py:43-45``): a uniform plan maps to a ("pp", "dp", "tp")
device mesh; parameters get Megatron-style PartitionSpecs (column-parallel
qkv/mlp-in, row-parallel proj/mlp-out, vocab-parallel embedding/head); the
batch shards over dp.  Everything below is GSPMD-first: specs + sharding
constraints, XLA inserts the collectives over ICI.
"""
from __future__ import annotations

import json
from dataclasses import dataclass
from pathlib import Path

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from metis_tpu.core.types import UniformPlan
from metis_tpu.models.gpt import GPTConfig

PP, DP, TP, SP, EP = "pp", "dp", "tp", "sp", "ep"


def _grid(shape: tuple[int, ...], axes: tuple[str, ...], devices=None) -> Mesh:
    """Shared mesh construction: default device list, size check, row-major
    reshape (matching the planner's linear rank placement)."""
    devs = np.asarray(devices if devices is not None else jax.devices())
    need = int(np.prod(shape))
    if devs.size < need:
        raise ValueError(
            f"mesh {axes}={shape} needs {need} devices, have {devs.size}")
    return Mesh(devs.flatten()[:need].reshape(shape), axes)


def mesh_for_uniform_plan(plan: UniformPlan, devices=None) -> Mesh:
    """(pp, dp, tp) mesh over the device list."""
    return _grid((plan.pp, plan.dp, plan.tp), (PP, DP, TP), devices)


def mesh_dp_tp(dp: int, tp: int, devices=None) -> Mesh:
    """(dp, tp) mesh for non-pipelined execution."""
    return _grid((dp, tp), (DP, TP), devices)


def gpt_param_specs(cfg: GPTConfig, tp_axis: str = TP, pp_axis: str | None = None) -> dict:
    """PartitionSpec tree matching models.gpt.init_params.

    ``pp_axis`` shards the stacked block-layer axis (pipeline stages own
    contiguous layer slices); requires num_blocks % pp == 0.
    """
    t, p = tp_axis, pp_axis
    return {
        "embed": {
            "tok": P(t, None),      # vocab-parallel embedding
            "pos": P(),
        },
        "blocks": {
            "ln1_scale": P(p, None),
            "ln1_bias": P(p, None),
            "qkv": P(p, None, None, t),  # column-parallel (per-head)
            "qkv_bias": P(p, None, t),
            "proj": P(p, t, None),      # row-parallel
            "proj_bias": P(p, None),
            "ln2_scale": P(p, None),
            "ln2_bias": P(p, None),
            "mlp_in": P(p, None, t),    # column-parallel
            "mlp_in_bias": P(p, t),
            "mlp_out": P(p, t, None),   # row-parallel
            "mlp_out_bias": P(p, None),
        },
        "head": {
            "ln_scale": P(),
            "ln_bias": P(),
            "out": P(None, t),      # vocab-parallel head
        },
    }


def moe_param_specs(
    cfg, tp_axis: str = TP, ep_axis: str = EP, pp_axis: str | None = None
) -> dict:
    """PartitionSpec tree matching models.moe.init_moe_params.

    Expert weights shard their leading num_experts axis over ``ep_axis`` —
    GSPMD then inserts the token all-to-alls around the expert einsums
    (models.moe docstring); dense weights follow the Megatron TP layout of
    ``gpt_param_specs``.
    """
    t, p, e = tp_axis, pp_axis, ep_axis
    specs = gpt_param_specs(cfg, tp_axis=tp_axis, pp_axis=pp_axis)
    blocks = dict(specs["blocks"])
    for key in ("mlp_in", "mlp_in_bias", "mlp_out", "mlp_out_bias"):
        del blocks[key]
    blocks.update({
        "router": P(p, None, None),
        "expert_in": P(p, e, None, t),       # column-parallel within expert
        "expert_in_bias": P(p, e, t),
        "expert_out": P(p, e, t, None),      # row-parallel within expert
        "expert_out_bias": P(p, e, None),
    })
    return {**specs, "blocks": blocks}


def llama_param_specs(
    cfg, tp_axis: str = TP, pp_axis: str | None = None, tp_size: int = 1
) -> dict:
    """PartitionSpec tree matching models.llama.init_llama_params.

    Megatron layout: wq/w_gate/w_up column-parallel, wo/w_down row-parallel,
    norms replicated, vocab-parallel embed/head.  The KV projection is
    column-parallel only when the KV head count divides ``tp_size`` shards
    evenly (GQA with few KV heads otherwise replicates K/V — the standard
    fallback, since a head cannot be split across ranks without changing
    attention math)."""
    t, p = tp_axis, pp_axis
    kv_t = t if tp_size <= 1 or cfg.kv_heads % tp_size == 0 else None
    return {
        "embed": {"tok": P(t, None)},
        "blocks": {
            "attn_norm": P(p, None),
            "wq": P(p, None, t),
            "wkv": P(p, None, None, kv_t),
            "wo": P(p, t, None),
            "ffn_norm": P(p, None),
            "w_gate": P(p, None, t),
            "w_up": P(p, None, t),
            "w_down": P(p, t, None),
        },
        "head": {
            "norm": P(),
            "out": P(None, t),
        },
    }


def batch_spec(dp_axis: str = DP, seq_axis: str | None = None) -> P:
    """Sharding for [batch, seq] token arrays."""
    return P(dp_axis, seq_axis)


def shard_params(params: dict, mesh: Mesh, specs: dict) -> dict:
    """Place a parameter pytree onto the mesh with the given specs."""
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), params, specs)


@dataclass(frozen=True)
class PlanArtifact:
    """Serializable chosen plan — the bridge from search to execution (and
    the 'checkpoint' of the search, SURVEY.md §5 Checkpoint/resume)."""

    mesh_axes: tuple[str, ...]
    mesh_shape: tuple[int, ...]
    layer_partition: tuple[int, ...]
    strategies: tuple[dict, ...]
    gbs: int
    microbatches: int
    # hetero extras (empty for uniform plans): device-type placement order and
    # per-stage device counts — non-rectangular stages don't form one mesh
    node_sequence: tuple[str, ...] = ()
    device_groups: tuple[int, ...] = ()
    # pipeline schedule the plan was PRICED with (a searched axis,
    # cost/schedule.py) — the executable must run what the planner costed
    schedule: str = "gpipe"
    virtual_stages: int = 1

    def to_json(self) -> str:
        return json.dumps({
            "mesh_axes": list(self.mesh_axes),
            "mesh_shape": list(self.mesh_shape),
            "layer_partition": list(self.layer_partition),
            "strategies": list(self.strategies),
            "gbs": self.gbs,
            "microbatches": self.microbatches,
            "node_sequence": list(self.node_sequence),
            "device_groups": list(self.device_groups),
            "schedule": self.schedule,
            "virtual_stages": self.virtual_stages,
        }, indent=2)

    @staticmethod
    def from_json(payload: str) -> "PlanArtifact":
        d = json.loads(payload)
        return PlanArtifact(
            mesh_axes=tuple(d["mesh_axes"]),
            mesh_shape=tuple(d["mesh_shape"]),
            layer_partition=tuple(d["layer_partition"]),
            strategies=tuple(d["strategies"]),
            gbs=d["gbs"],
            microbatches=d["microbatches"],
            node_sequence=tuple(d.get("node_sequence", ())),
            device_groups=tuple(d.get("device_groups", ())),
            schedule=d.get("schedule", "gpipe"),
            virtual_stages=d.get("virtual_stages", 1),
        )

    def save(self, path) -> None:
        Path(path).write_text(self.to_json())

    @staticmethod
    def load(path) -> "PlanArtifact":
        return PlanArtifact.from_json(Path(path).read_text())

    def build_mesh(self, devices=None) -> Mesh:
        """Reconstruct the mesh for a rectangular (uniform-stage) artifact."""
        if not self.mesh_shape:
            raise ValueError(
                "artifact has non-uniform stages; build per-stage meshes from "
                "device_groups/strategies instead")
        return _grid(self.mesh_shape, self.mesh_axes, devices)

    @staticmethod
    def from_uniform_plan(plan: UniformPlan) -> "PlanArtifact":
        return PlanArtifact(
            mesh_axes=(PP, DP, TP),
            mesh_shape=(plan.pp, plan.dp, plan.tp),
            layer_partition=(),
            strategies=({"dp": plan.dp, "tp": plan.tp},),
            gbs=plan.gbs,
            microbatches=plan.num_microbatches,
        )

    @staticmethod
    def from_ranked_plan(ranked) -> "PlanArtifact":
        """Capture a hetero planner result (planner.api.RankedPlan).  When
        every stage shares one strategy shape the artifact is rectangular
        with every plan axis named honestly — (pp, dp, ep, sp, tp), trivial
        axes kept at size 1 — so consumers shard the batch over (dp, ep),
        run ring attention over sp, and shard experts over ep, exactly as
        costed.  Otherwise mesh fields stay empty and per-stage data drives
        execution."""
        from dataclasses import asdict

        inter, intra = ranked.inter, ranked.intra
        strategies = tuple(asdict(s) for s in intra.strategies)
        uniform = len(
            {(s.dp, s.tp, s.cp, s.ep) for s in intra.strategies}) == 1
        s0 = intra.strategies[0]
        return PlanArtifact(
            mesh_axes=(PP, DP, EP, SP, TP) if uniform else (),
            mesh_shape=(
                (inter.num_stages, s0.dp // s0.ep, s0.ep, s0.cp, s0.tp)
                if uniform else ()),
            layer_partition=tuple(intra.layer_partition),
            strategies=strategies,
            gbs=inter.gbs,
            microbatches=inter.batches,
            node_sequence=tuple(inter.node_sequence),
            device_groups=tuple(inter.device_groups),
            schedule=getattr(intra, "schedule", "gpipe"),
            virtual_stages=getattr(intra, "virtual_stages", 1),
        )
