"""Non-uniform hetero plan execution: one GSPMD program per pipeline stage.

The planner's flagship output — hetero plans with non-uniform layer
partitions and per-stage ``(dp, tp)`` strategies (≅ the reference's printed
plan tuple, ``cost_het_cluster.py:43-45``) — cannot run as one SPMD program:
stages differ in layer count, mesh shape, and (on real clusters) hardware
platform, so one ``shard_map`` cannot express them.  This executor is the
TPU-native answer (SURVEY.md §7 "Heterogeneous multi-slice execution"):

- **one mesh + one jitted program per stage** — each stage is a plain GSPMD
  ``(dp, tp)`` program over its own device slice; XLA inserts the TP
  collectives per stage, exactly as the per-stage cost terms price them;
- **boundary activations move between meshes with ``jax.device_put``** — on
  a real deployment that transfer rides DCN between slices, matching the
  cost model's inter-stage p2p term;
- **backward is stitched manually across stages** with per-stage
  ``jax.vjp`` closures: each stage's backward *recomputes its forward*
  (stage-granular rematerialization — the standard TPU memory/FLOPs trade),
  so only boundary activations are stored between the forward and backward
  passes, the GPipe activation footprint the planner's memory model charges;
- **uneven hetero-DP microbatches** (Metis's signature feature, reference
  ``load_balancer.py:155-179``): a stage whose replicas get unequal row
  counts pads each replica to the max count with a static gather, shards the
  padded batch over dp, and inverse-gathers back to the canonical row order
  at the stage boundary.  Transformer blocks mix nothing across batch rows,
  so pad rows contribute exactly zero gradient — the padding is invisible to
  the math and the boundary contract stays canonical.

Losses average per-microbatch means; gradients accumulate across
microbatches on each stage's mesh and divide by the microbatch count at the
optimizer step, so the result is identical to the single-program global-mean
loss (pinned by the parity test).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from metis_tpu.execution.mesh import DP, EP, SP, TP
from metis_tpu.execution.train import (
    build_optimizer,
    fsdp_wrap_specs,
    param_specs_for,
)
from metis_tpu.models import family_ops
from metis_tpu.models import resolve_attention
from metis_tpu.models.gpt import GPTConfig
from metis_tpu.models.moe import MoEConfig


@dataclass(frozen=True)
class StageSpec:
    """One pipeline stage of a hetero plan, execution-ready.

    ``blocks`` is the [lo, hi) transformer-block range (converted from the
    planner's profile-layer boundaries — profile layer 0 is the embedding
    pseudo-layer, layer ``num_blocks + 1`` the LM head, matching
    ``GPTConfig.num_profile_layers``).  ``replica_rows`` carries the uneven
    per-replica microbatch rows from the data balancer (None = even split).
    ``replica_groups`` (sizes in replicas, summing to ``dp``) splits a
    MIXED-device-type stage into per-type sub-meshes: each group runs its
    own GSPMD program on its own real row count — no padding, and an MoE
    group's expert capacity derives from its own tokens (capacity
    proportional to the group's real-token share, the lever that makes
    uneven hetero-DP an actual win for MoE stages; VERDICT r3 next-step 7).
    """

    blocks: tuple[int, int]
    has_embed: bool
    has_head: bool
    dp: int
    tp: int
    zero: int = 0
    ep: int = 1  # expert parallelism rides inside dp (MoE stages only)
    cp: int = 1  # context parallelism over a dedicated axis
    cp_mode: str = "ring"  # "ring" (K/V rotation) or "a2a" (Ulysses)
    replica_rows: tuple[int, ...] | None = None
    replica_groups: tuple[int, ...] | None = None

    @property
    def devices(self) -> int:
        return self.dp * self.cp * self.tp

    @property
    def num_blocks(self) -> int:
        return self.blocks[1] - self.blocks[0]


def stage_specs_from_plan(
    layer_partition: Sequence[int],
    strategies: Sequence,
    cfg: GPTConfig,
    stage_replica_rows: Sequence[Sequence[int] | None] | None = None,
    stage_replica_groups: Sequence[Sequence[int] | None] | None = None,
) -> tuple[StageSpec, ...]:
    """Convert planner output (profile-layer boundaries + per-stage
    strategies) into executable StageSpecs.

    ``strategies`` entries may be ``core.types.Strategy`` objects or the
    dicts a ``PlanArtifact`` stores.
    """
    bounds = list(layer_partition)
    n_profile = cfg.num_profile_layers
    if bounds[0] != 0 or bounds[-1] != n_profile:
        raise ValueError(
            f"layer_partition {bounds} must span [0, {n_profile}] "
            f"(= num_blocks + embed + head profile layers)")
    if len(bounds) != len(strategies) + 1:
        raise ValueError(
            f"{len(strategies)} strategies need {len(strategies) + 1} "
            f"partition boundaries, got {len(bounds)}")

    out = []
    for s, strat in enumerate(strategies):
        if isinstance(strat, dict):
            dp, tp = strat["dp"], strat["tp"]
            zero = strat.get("zero", 0)
            cp, ep = strat.get("cp", 1), strat.get("ep", 1)
            cp_mode = strat.get("cp_mode", "ring")
        else:
            dp, tp, zero = strat.dp, strat.tp, strat.zero
            cp, ep = strat.cp, strat.ep
            cp_mode = strat.cp_mode
        is_moe = isinstance(cfg, MoEConfig)
        if cp > 1 and is_moe:
            raise NotImplementedError(
                f"stage {s}: cp+MoE stages have no execution path "
                "(ring attention composes with dense families)")
        if cp > 1 and cfg.seq_len % cp:
            raise ValueError(
                f"stage {s}: cp={cp} must divide seq_len={cfg.seq_len}")
        if ep > 1 and not is_moe:
            raise ValueError(f"stage {s}: ep={ep} needs an MoE config")
        if ep > 1 and (dp % ep or cfg.num_experts % ep):
            raise ValueError(
                f"stage {s}: ep={ep} must divide dp={dp} and "
                f"num_experts={cfg.num_experts}")
        lo, hi = bounds[s], bounds[s + 1]
        rows = None
        if stage_replica_rows is not None and stage_replica_rows[s] is not None:
            rows = tuple(stage_replica_rows[s])
            if len(rows) != dp:
                raise ValueError(
                    f"stage {s}: {len(rows)} replica rows for dp={dp}")
        groups = None
        if (stage_replica_groups is not None
                and stage_replica_groups[s] is not None):
            groups = tuple(stage_replica_groups[s])
            if sum(groups) != dp:
                raise ValueError(
                    f"stage {s}: replica_groups {groups} must sum to dp={dp}")
        out.append(StageSpec(
            blocks=(max(lo - 1, 0), min(hi - 1, cfg.num_blocks)),
            has_embed=lo == 0,
            has_head=hi == n_profile,
            dp=dp, tp=tp, zero=zero, ep=ep, cp=cp, cp_mode=cp_mode,
            replica_rows=rows, replica_groups=groups))
    return tuple(out)


def _slice_stage_params(params: dict, spec: StageSpec) -> dict:
    lo, hi = spec.blocks
    out = {"blocks": jax.tree.map(lambda a: a[lo:hi], params["blocks"])}
    if spec.has_embed:
        out["embed"] = params["embed"]
    if spec.has_head:
        out["head"] = params["head"]
    return out


def _stage_param_specs(spec: StageSpec, cfg: GPTConfig) -> dict:
    full = param_specs_for(cfg, tp_axis=TP, tp_size=spec.tp,
                           ep_axis=EP if spec.ep > 1 else None)
    out = {"blocks": full["blocks"]}
    if spec.has_embed:
        out["embed"] = full["embed"]
    if spec.has_head:
        out["head"] = full["head"]
    return out


def _pad_maps(replica_rows: Sequence[int]) -> tuple[np.ndarray, np.ndarray]:
    """Static gather maps realizing an uneven per-replica split.

    Returns ``(to_padded, to_canonical)``: ``x[to_padded]`` lays the
    canonical batch out as ``dp * max_rows`` rows (each replica's share
    padded with duplicates of row 0 — masked out by the inverse gather), and
    ``padded[to_canonical]`` restores canonical order.
    """
    mx = max(replica_rows)
    to_padded, to_canonical = [], []
    start = 0
    for r in replica_rows:
        slot0 = len(to_padded)
        to_padded += list(range(start, start + r)) + [0] * (mx - r)
        to_canonical += list(range(slot0, slot0 + r))
        start += r
    return np.asarray(to_padded, np.int32), np.asarray(to_canonical, np.int32)


def _make_stage_fn(spec: StageSpec, cfg: GPTConfig, attn_impl,
                   aux_weight: float = 0.0):
    """The stage's pure forward: params + boundary input -> boundary output
    (or loss, on the last stage).  Signature varies by role:

    - first stage:        f(params, tokens)            -> x
    - middle stage:       f(params, x)                 -> x
    - last stage:         f(params, x, targets)        -> loss
    - single-stage plan:  f(params, tokens, targets)   -> loss

    MoE stages additionally expose their router load-balance auxiliary:
    non-head stages return ``(x, aux_mean)`` and the head stage folds
    ``aux_loss_coef * aux_weight * aux_mean`` into its loss, where
    ``aux_weight`` is the stage's share of the model's blocks — summed
    across stages this reproduces the single-program
    ``moe_next_token_loss`` (coef x mean over ALL blocks) exactly.
    """
    pad = spec.replica_rows is not None and len(set(spec.replica_rows)) > 1
    is_moe = isinstance(cfg, MoEConfig)
    pad_mask = None
    if pad:
        to_padded, to_canonical = _pad_maps(spec.replica_rows)
        if is_moe:
            # routed experts compete for capacity across the whole token
            # batch, so a duplicate pad row claiming an expert slot would
            # displace a real token.  The router takes a validity mask
            # (models/moe.moe_ffn): pad tokens never enter routing,
            # capacity, or the aux statistics — uneven hetero-DP (Metis's
            # signature feature) is sound for MoE stages with it (exact
            # below capacity pressure; see moe_ffn on the drop-set
            # approximation when capacity binds).  Per-ROW vector; the
            # router broadcasts over seq.
            pad_mask = np.zeros(len(to_padded), np.float32)
            pad_mask[to_canonical] = 1.0
    batch_axes = (DP, EP) if spec.ep > 1 else DP
    seq_axis = SP if spec.cp > 1 else None
    batch_sharded = P(batch_axes, seq_axis, None)

    embed, run_blocks, head_logits, _ = family_ops(cfg)

    def run(params, first_in, targets=None):
        x_or_tok = first_in
        if pad:
            x_or_tok = x_or_tok[to_padded]
        if spec.has_embed:
            x = embed(params, x_or_tok, cfg)
        else:
            x = x_or_tok
        x = jax.lax.with_sharding_constraint(x, batch_sharded)
        aux = None
        if is_moe:
            if spec.num_blocks == 0:
                # embed-/head-only stage: a zero-length scan's aux mean
                # would be NaN; there are no routers here, aux is zero
                aux = jnp.zeros((), jnp.float32)
            else:
                mask = (jnp.asarray(pad_mask)
                        if pad_mask is not None else None)
                x, aux = run_blocks(params, x, cfg, attn_impl,
                                    valid_mask=mask)
        else:
            x = run_blocks(params, x, cfg, attn_impl)
        if pad:
            x = x[to_canonical]
        if not spec.has_head:
            return (x, aux) if is_moe else x
        logits = head_logits(params, x, cfg)
        logp = jax.nn.log_softmax(logits, axis=-1)
        picked = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
        loss = -picked.mean()
        if is_moe:
            loss = loss + cfg.aux_loss_coef * aux_weight * aux
        return loss

    return run


def make_hetero_train_step(
    cfg: GPTConfig,
    stages: Sequence[StageSpec],
    devices: Sequence | None = None,
    optimizer=None,
    attn_impl=None,
):
    """Build the multi-mesh executor for a non-uniform hetero plan.

    Returns ``(init_fn, step_fn)``:

    - ``init_fn(key) -> state`` — a list of per-stage ``(params, opt_state)``
      pairs, each placed on its stage's mesh (params sliced from one full
      ``init_params`` call so results match the single-device model bit-for-
      bit at fp32);
    - ``step_fn(state, tokens_mbs, targets_mbs) -> (state, loss)`` with
      tokens/targets microbatch-major ``[M, rows, seq]``; runs all forward
      microbatches (storing only boundary activations), then the stitched
      backward, then one optimizer step per stage.
    """
    stages = tuple(stages)
    devs = list(devices if devices is not None else jax.devices())
    need = sum(s.devices for s in stages)
    if len(devs) < need:
        raise ValueError(f"plan needs {need} devices, have {len(devs)}")
    optimizer = optimizer or build_optimizer()
    attn = attn_impl or resolve_attention(cfg)

    meshes: list[Mesh] = []
    off = 0
    for s in stages:
        chips = devs[off:off + s.devices]
        if s.ep > 1:
            grid = np.array(chips).reshape(s.dp // s.ep, s.ep, s.tp)
            meshes.append(Mesh(grid, (DP, EP, TP)))
        elif s.cp > 1:
            grid = np.array(chips).reshape(s.dp, s.cp, s.tp)
            meshes.append(Mesh(grid, (DP, SP, TP)))
        else:
            grid = np.array(chips).reshape(s.dp, s.tp)
            meshes.append(Mesh(grid, (DP, TP)))
        off += s.devices

    S = len(stages)
    is_moe = isinstance(cfg, MoEConfig)
    total_blocks = max(cfg.num_blocks, 1)
    # per-stage share of the global aux mean (see _make_stage_fn docstring)
    aux_w = [s.num_blocks / total_blocks for s in stages]

    # -- per-type sub-mesh groups (StageSpec.replica_groups) --------------
    # A mixed-type stage splits into one GSPMD program per device-type
    # group: each group computes ONLY its real rows (no padding — the
    # pad/mask path charges every replica the padded batch, and an MoE
    # group's expert capacity now derives from its own token count).
    # Gradients are summed across groups on the stage's primary mesh, the
    # optimizer runs there once, and params mirror back out per step — the
    # state/checkpoint contract is unchanged.
    import dataclasses as _dc

    units: list[list[dict] | None] = []
    off_u = 0
    for i, s in enumerate(stages):
        eligible = (s.replica_groups is not None and len(s.replica_groups) > 1
                    and s.zero == 0 and s.cp == 1 and s.ep == 1)
        if not eligible:
            units.append(None)
            off_u += s.devices
            continue
        us = []
        dev_off = off_u
        rep_off = 0
        for dp_g in s.replica_groups:
            chips = devs[dev_off: dev_off + dp_g * s.tp]
            mesh_u = Mesh(np.array(chips).reshape(dp_g, s.tp), (DP, TP))
            rows_g = (tuple(s.replica_rows[rep_off: rep_off + dp_g])
                      if s.replica_rows is not None else None)
            sub = _dc.replace(
                s, dp=dp_g, replica_groups=None,
                replica_rows=(rows_g if rows_g is not None
                              and len(set(rows_g)) > 1 else None))
            # weight: the group's share of the microbatch rows (static:
            # either from the balancer's split or the even dp fraction)
            w_g = (sum(rows_g) / sum(s.replica_rows)
                   if s.replica_rows is not None else dp_g / s.dp)
            us.append({"mesh": mesh_u, "spec": sub, "dp": dp_g,
                       "rows": rows_g, "w": w_g,
                       "fn": _make_stage_fn(sub, cfg, attn,
                                            aux_weight=aux_w[i])})
            dev_off += dp_g * s.tp
            rep_off += dp_g
        units.append(us)
        off_u += s.devices

    fns = []
    for i, s in enumerate(stages):
        stage_attn = attn
        if s.cp > 1:
            # context parallelism over the stage's dedicated sp axis;
            # positions stay global (embed/rope run on the GSPMD-global
            # array).  Mode per the plan: ring K/V rotation or Ulysses a2a.
            if s.cp_mode == "a2a":
                from metis_tpu.ops.ulysses import make_ulysses_attention

                stage_attn = make_ulysses_attention(
                    meshes[i], SP, head_axes=(TP,))
            else:
                from metis_tpu.ops.ring_attention import make_ring_attention

                stage_attn = make_ring_attention(meshes[i], SP)
        fns.append(_make_stage_fn(s, cfg, stage_attn, aux_weight=aux_w[i]))

    def _in_mesh(mesh: Mesh, fn):
        # bare-PartitionSpec constraints inside the stage programs resolve
        # against the mesh context at trace time, so every call enters the
        # stage's mesh
        def run(*args):
            with mesh:
                return fn(*args)
        return run

    # per-stage jitted programs, run in the stage's mesh context
    fwd, bwd, lossgrad, add_grads, apply_upd = [], [], [], [], []
    for s in range(S):
        spec, mesh, f = stages[s], meshes[s], fns[s]
        is_first, is_last = s == 0, s == S - 1

        if is_last:
            if is_first:  # single-stage plan: loss of (params, tokens)
                def lg(params, tok, tgt, _f=f):
                    return jax.value_and_grad(_f)(params, tok, tgt)
            else:
                def lg(params, x_in, tgt, _f=f):
                    # d(loss)/d(params), d(loss)/d(boundary input)
                    (loss, grads) = jax.value_and_grad(
                        _f, argnums=(0, 1))(params, x_in, tgt)
                    return loss, grads[0], grads[1]
            lossgrad.append(_in_mesh(mesh, jax.jit(lg)))
            fwd.append(None)
            bwd.append(None)
        else:
            fwd.append(_in_mesh(mesh, jax.jit(f)))
            # MoE stages emit (x, aux); the backward seeds the aux cotangent
            # with its loss weight directly — aux_s depends only on this
            # stage's params and input, so no aux value crosses a boundary
            aux_seed = cfg.aux_loss_coef * aux_w[s] if is_moe else None
            if is_first:
                def bw(params, tok, ct, _f=f, _as=aux_seed):
                    # tokens are ints — pull back to params only
                    _, pull = jax.vjp(lambda p: _f(p, tok), params)
                    if _as is not None:
                        ct = (ct, jnp.asarray(_as, jnp.float32))
                    return pull(ct)[0]
            else:
                def bw(params, x_in, ct, _f=f, _as=aux_seed):
                    _, pull = jax.vjp(_f, params, x_in)
                    if _as is not None:
                        ct = (ct, jnp.asarray(_as, jnp.float32))
                    return pull(ct)
            bwd.append(_in_mesh(mesh, jax.jit(bw)))
            lossgrad.append(None)

        add_grads.append(_in_mesh(mesh, jax.jit(
            lambda acc, g: jax.tree.map(jnp.add, acc, g),
            donate_argnums=(0,))))

        def upd(params, opt_state, acc, M, _opt=optimizer):
            grads = jax.tree.map(lambda g: g / M, acc)
            updates, opt_state = _opt.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state
        apply_upd.append(_in_mesh(mesh, jax.jit(
            upd, static_argnums=(3,), donate_argnums=(0, 1, 2))))

    # per-unit jitted programs for grouped stages (mirrors the per-stage
    # closures above, with the loss/cotangent scaled by the group's row
    # share so the summed loss reproduces the global batch mean)
    stage_specs_cache = [_stage_param_specs(s, cfg) for s in stages]
    for i, us in enumerate(units):
        if us is None:
            continue
        is_first, is_last = i == 0, i == S - 1
        for u in us:
            fn_u, w_u = u["fn"], u["w"]
            mesh_u = u["mesh"]

            def _in_u(f, _m=mesh_u):
                def run(*args):
                    with _m:
                        return f(*args)
                return run

            if is_last:
                if is_first:  # single-stage plan: tokens in, params grad only
                    def lg(params, tok, tgt, _f=fn_u, _w=w_u):
                        loss, g = jax.value_and_grad(
                            lambda p: _w * _f(p, tok, tgt))(params)
                        return loss, g, None
                else:
                    def lg(params, x_in, tgt, _f=fn_u, _w=w_u):
                        loss, grads = jax.value_and_grad(
                            lambda p, x: _w * _f(p, x, tgt),
                            argnums=(0, 1))(params, x_in)
                        return loss, grads[0], grads[1]
                u["lossgrad"] = _in_u(jax.jit(lg))
            else:
                u["fwd"] = _in_u(jax.jit(fn_u))
                aux_seed_u = (cfg.aux_loss_coef * aux_w[i] * w_u
                              if is_moe else None)
                if is_first:
                    def bw(params, tok, ct, _f=fn_u, _as=aux_seed_u):
                        _, pull = jax.vjp(lambda p: _f(p, tok), params)
                        if _as is not None:
                            ct = (ct, jnp.asarray(_as, jnp.float32))
                        return pull(ct)[0]
                else:
                    def bw(params, x_in, ct, _f=fn_u, _as=aux_seed_u):
                        _, pull = jax.vjp(_f, params, x_in)
                        if _as is not None:
                            ct = (ct, jnp.asarray(_as, jnp.float32))
                        return pull(ct)
                u["bwd"] = _in_u(jax.jit(bw))

    def _put(x, s: int, spec: P):
        return jax.device_put(x, NamedSharding(meshes[s], spec))

    def _put_rep(x, mesh: Mesh):
        # replicated on the unit mesh (a raw single-device put would clash
        # with the mesh-sharded params inside the unit's jit)
        return jax.device_put(
            x, NamedSharding(mesh, P(*([None] * x.ndim))))

    def _put_tree(tree, mesh: Mesh, specs):
        return jax.tree.map(
            lambda a, sp: jax.device_put(a, NamedSharding(mesh, sp)),
            tree, specs)

    def _unit_bounds(s: int, rows: int) -> list[int]:
        """Canonical row offsets of stage ``s``'s groups."""
        bounds = [0]
        for u in units[s]:
            r = (sum(u["rows"]) if u["rows"] is not None
                 else rows * u["dp"] // stages[s].dp)
            bounds.append(bounds[-1] + r)
        return bounds

    def _boundary_spec(s: int, rows: int) -> P:
        # activations shard over dp when rows divide evenly, else replicate
        # (the in-stage pad/gather re-shards anyway); grouped stages take
        # the canonical array replicated and slice per group
        return (P(DP, None, None)
                if units[s] is None and rows % stages[s].dp == 0
                else P(None, None, None))

    def init_fn(key):
        full = family_ops(cfg)[3](key, cfg)
        state = []
        for s, (spec, mesh) in enumerate(zip(stages, meshes)):
            specs = _stage_param_specs(spec, cfg)
            sliced = _slice_stage_params(full, spec)
            if spec.zero >= 3:
                specs = fsdp_wrap_specs(specs, sliced, DP,
                                        axis_size=mesh.shape[DP])
            params = jax.tree.map(
                lambda x, sp: jax.device_put(x, NamedSharding(mesh, sp)),
                sliced, specs)
            with mesh:
                opt_state = optimizer.init(params)
            if spec.zero in (1, 2):
                # ZeRO-1/2 per stage: optimizer state shards over the
                # stage's dp ranks, params stay replicated across them
                from metis_tpu.execution.train import opt_state_specs_by_shape

                wrapped = fsdp_wrap_specs(specs, sliced, DP,
                                          axis_size=mesh.shape[DP])
                opt_specs = opt_state_specs_by_shape(
                    opt_state, sliced, wrapped)
                opt_state = jax.tree.map(
                    lambda x, sp: jax.device_put(x, NamedSharding(mesh, sp)),
                    opt_state, opt_specs)
            state.append([params, opt_state])
        return state

    def step_fn(state, tokens_mbs, targets_mbs):
        M, rows = tokens_mbs.shape[0], tokens_mbs.shape[1]
        for spec in stages:
            if spec.replica_rows is not None and sum(spec.replica_rows) != rows:
                raise ValueError(
                    f"replica_rows {spec.replica_rows} must sum to the "
                    f"microbatch size {rows}")

        # grouped stages: mirror the stage's params onto each group mesh
        # once per step (the canonical copy — state, optimizer, checkpoints
        # — stays on the primary mesh)
        unit_params = [None] * S
        for s in range(S):
            if units[s] is not None:
                unit_params[s] = [
                    _put_tree(state[s][0], u["mesh"], stage_specs_cache[s])
                    for u in units[s]]

        # ---- forward fill: store only boundary inputs per (stage, mb)
        toks = [_put(tokens_mbs[m], 0, P(None, None)) for m in range(M)]
        tgts = [_put(targets_mbs[m], S - 1, P(None, None)) for m in range(M)]
        x_in = [[None] * M for _ in range(S)]  # boundary input of stage s
        aux_vals = []  # MoE: non-head stages' weighted aux means
        for m in range(M):
            x = None
            for s in range(S - 1):
                src = toks[m] if s == 0 else x
                if units[s] is None:
                    x = fwd[s](state[s][0], src)
                    if is_moe:
                        # keep aux on device; one fetch at the end (a
                        # per-(stage, mb) device_get here would serialize
                        # the forward fill)
                        x, aux = x
                        aux_vals.append(cfg.aux_loss_coef * aux_w[s] * aux)
                else:
                    ub = _unit_bounds(s, rows)
                    parts = []
                    for g, u in enumerate(units[s]):
                        if ub[g + 1] == ub[g]:
                            continue  # balancer gave this type 0 rows
                        src_u = _put_rep(src[ub[g]:ub[g + 1]], u["mesh"])
                        out_u = u["fwd"](unit_params[s][g], src_u)
                        if is_moe:
                            out_u, aux = out_u
                            aux_vals.append(cfg.aux_loss_coef * aux_w[s]
                                            * u["w"] * aux)
                        parts.append(out_u)
                    nxt = NamedSharding(meshes[s + 1], P(None, None, None))
                    with meshes[s + 1]:
                        x = jnp.concatenate(
                            [jax.device_put(p, nxt) for p in parts], axis=0)
                    x_in[s + 1][m] = x
                    continue
                x_in[s + 1][m] = x = _put(x, s + 1, _boundary_spec(s + 1, rows))

        # ---- backward drain: per-stage grad accumulation across mbs
        accs = [None] * S
        losses = []

        def _acc(s, g):
            accs[s] = g if accs[s] is None else add_grads[s](accs[s], g)

        for m in reversed(range(M)):
            if units[-1] is not None:
                # grouped last stage: per-group loss/grad, losses and
                # cotangents already scaled by the group's row share
                ub = _unit_bounds(S - 1, rows)
                src_last = toks[m] if S == 1 else x_in[-1][m]
                ct_parts, loss_sum = [], None
                dev0 = meshes[-1].devices.flat[0]
                for g, u in enumerate(units[-1]):
                    if ub[g + 1] == ub[g]:
                        continue  # 0-row group: no loss, no grads
                    x_u = _put_rep(src_last[ub[g]:ub[g + 1]], u["mesh"])
                    t_u = _put_rep(tgts[m][ub[g]:ub[g + 1]], u["mesh"])
                    loss_u, g_u, ct_u = u["lossgrad"](
                        unit_params[-1][g], x_u, t_u)
                    # sum on the primary mesh's device — an async scalar
                    # transfer, NOT a blocking device_get in the drain (the
                    # forward fill avoids per-(stage, mb) host syncs for
                    # the same reason)
                    loss_dev = jax.device_put(loss_u, dev0)
                    loss_sum = (loss_dev if loss_sum is None
                                else loss_sum + loss_dev)
                    if ct_u is not None:
                        ct_parts.append(ct_u)
                    _acc(S - 1, _put_tree(g_u, meshes[-1],
                                          stage_specs_cache[-1]))
                losses.append(loss_sum)
                ct = ct_parts  # list, re-assembled at the next _put below
            elif S == 1:
                loss, g = lossgrad[-1](state[0][0], toks[m], tgts[m])
                ct = None
                losses.append(loss)
                _acc(0, g)
            else:
                loss, g, ct = lossgrad[-1](state[-1][0], x_in[-1][m], tgts[m])
                losses.append(loss)
                _acc(S - 1, g)
            for s in range(S - 2, -1, -1):
                if isinstance(ct, list):
                    spec_s = NamedSharding(meshes[s], P(None, None, None))
                    with meshes[s]:
                        ct = jnp.concatenate(
                            [jax.device_put(p, spec_s) for p in ct], axis=0)
                else:
                    ct = _put(ct, s, _boundary_spec(s, rows))
                if units[s] is None:
                    if s == 0:
                        g = bwd[0](state[0][0], toks[m], ct)
                        _acc(0, g)
                    else:
                        g, ct = bwd[s](state[s][0], x_in[s][m], ct)
                        _acc(s, g)
                else:
                    ub = _unit_bounds(s, rows)
                    ct_parts = []
                    for gi, u in enumerate(units[s]):
                        if ub[gi + 1] == ub[gi]:
                            continue  # 0-row group
                        ct_u = _put_rep(ct[ub[gi]:ub[gi + 1]], u["mesh"])
                        if s == 0:
                            tok_u = _put_rep(
                                toks[m][ub[gi]:ub[gi + 1]], u["mesh"])
                            g_u = u["bwd"](unit_params[s][gi], tok_u, ct_u)
                        else:
                            x_u = _put_rep(
                                x_in[s][m][ub[gi]:ub[gi + 1]], u["mesh"])
                            g_u, ct_x = u["bwd"](
                                unit_params[s][gi], x_u, ct_u)
                            ct_parts.append(ct_x)
                        _acc(s, _put_tree(g_u, meshes[s],
                                          stage_specs_cache[s]))
                    ct = ct_parts if ct_parts else None

        # ---- optimizer step per stage
        for s in range(S):
            params, opt_state = apply_upd[s](
                state[s][0], state[s][1], accs[s], M)
            state[s] = [params, opt_state]
        loss = float(np.mean([jax.device_get(l) for l in losses]))
        if aux_vals:
            # upstream stages' weighted aux terms (the head stage already
            # folded its own): mean over microbatches, summed over stages
            loss += float(np.sum(jax.device_get(aux_vals))) / M
        return state, loss

    return init_fn, step_fn


def plan_replica_groups(
    inter,
    strategies: Sequence,
    cluster,
) -> list[tuple[int, ...] | None]:
    """Per-stage device-TYPE group sizes (in replicas) for the sub-mesh
    split of mixed-type stages (``StageSpec.replica_groups``).  Homogeneous
    stages — and mixed stages carrying zero/cp/ep axes, which the grouped
    path doesn't support — return None (single program)."""
    from metis_tpu.balance.data import replica_chunks
    from metis_tpu.balance.stage_perf import rank_device_types

    ranks = rank_device_types(cluster, inter.node_sequence)
    out: list[tuple[int, ...] | None] = []
    for stage_id, strat in enumerate(strategies):
        start, end = inter.stage_rank_range(stage_id)
        types = ranks[start:end]
        zero = getattr(strat, "zero", 0)
        cp = getattr(strat, "cp", 1)
        ep = getattr(strat, "ep", 1)
        if len(set(types)) == 1 or zero or cp > 1 or ep > 1:
            out.append(None)
            continue
        rep_types = [c[0] for c in replica_chunks(types, strat.dp)]
        groups: list[int] = []
        prev = None
        for t in rep_types:
            if t == prev:
                groups[-1] += 1
            else:
                groups.append(1)
                prev = t
        out.append(tuple(groups) if len(groups) > 1 else None)
    return out


def plan_replica_rows(
    inter,
    strategies: Sequence,
    cluster,
    profiles,
) -> list[tuple[int, ...] | None]:
    """Per-stage uneven replica row counts from the data balancer — the
    execution-side consumer of Metis's signature feature (reference
    ``partition_data``, ``load_balancer.py:155-179``).  Homogeneous stages
    return None (even GSPMD sharding needs no padding)."""
    from metis_tpu.balance.data import DataBalancer
    from metis_tpu.balance.stage_perf import rank_device_types

    balancer = DataBalancer(profiles)
    ranks = rank_device_types(cluster, inter.node_sequence)
    mb = inter.gbs // inter.batches
    out: list[tuple[int, ...] | None] = []
    for stage_id, strat in enumerate(strategies):
        start, end = inter.stage_rank_range(stage_id)
        types = ranks[start:end]
        if len(set(types)) == 1:
            out.append(None)
        else:
            out.append(tuple(balancer.partition(types, strat.dp, strat.tp, mb)))
    return out


def make_hetero_train_step_from_artifact(
    cfg: GPTConfig,
    artifact,
    devices: Sequence | None = None,
    optimizer=None,
    stage_replica_rows: Sequence[Sequence[int] | None] | None = None,
):
    """PlanArtifact -> executable hetero step (the plan-to-execution bridge
    for non-rectangular plans; rectangular plans may still prefer the
    single-program paths in execution.train / execution.pipeline)."""
    stages = stage_specs_from_plan(
        artifact.layer_partition, artifact.strategies, cfg,
        stage_replica_rows=stage_replica_rows)
    groups = tuple(artifact.device_groups)
    if groups and groups != tuple(s.devices for s in stages):
        raise ValueError(
            f"device_groups {groups} disagree with strategies "
            f"{tuple(s.devices for s in stages)}")
    return make_hetero_train_step(
        cfg, stages, devices=devices, optimizer=optimizer)
