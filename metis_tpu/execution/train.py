"""GSPMD training step: dp x tp (x ring-attention sp) without pipelining.

The single-program path for plans with pp=1 (and the per-stage building block
for the pipelined path): parameters and batch carry NamedShardings, the loss
is computed under jit with sharding constraints, and XLA inserts the
all-reduces (gradients over dp, activations over tp) on the ICI mesh.
"""
from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from metis_tpu.core.events import NULL_LOG
from metis_tpu.execution.mesh import (
    DP,
    EP,
    TP,
    batch_spec,
    gpt_param_specs,
    llama_param_specs,
    moe_param_specs,
    shard_params,
)
from metis_tpu.models.gpt import GPTConfig, init_params, next_token_loss
from metis_tpu.models.moe import (
    MoEConfig,
    init_moe_params,
    moe_next_token_loss,
)
from metis_tpu.ops.ring_attention import make_ring_attention


def param_specs_for(cfg: GPTConfig, tp_axis: str = TP, ep_axis: str = EP,
                    pp_axis: str | None = None, tp_size: int = 1) -> dict:
    """Model-family dispatch: MoE configs get expert sharding specs, LLaMA
    configs the RMSNorm/RoPE/GQA layout (``tp_size`` gates GQA KV-projection
    sharding)."""
    from metis_tpu.models.llama import LlamaConfig

    if isinstance(cfg, MoEConfig):
        return moe_param_specs(cfg, tp_axis=tp_axis, ep_axis=ep_axis,
                               pp_axis=pp_axis)
    if isinstance(cfg, LlamaConfig):
        return llama_param_specs(cfg, tp_axis=tp_axis, pp_axis=pp_axis,
                                 tp_size=tp_size)
    return gpt_param_specs(cfg, tp_axis=tp_axis, pp_axis=pp_axis)


def init_params_for(key: jax.Array, cfg: GPTConfig) -> dict:
    from metis_tpu.models.llama import LlamaConfig, init_llama_params

    if isinstance(cfg, MoEConfig):
        return init_moe_params(key, cfg)
    if isinstance(cfg, LlamaConfig):
        return init_llama_params(key, cfg)
    return init_params(key, cfg)


def fsdp_wrap_specs(specs: dict, params: dict, dp_axis: str = DP,
                    axis_size: int = 1) -> dict:
    """ZeRO-3/FSDP on TPU is a sharding, not a wrapper: shard each >=2D
    parameter's largest still-unsharded dim over ``dp_axis``.  Optimizer
    state mirrors the param pytree, so optax state (and the fp32 Adam
    moments — the bulk of training memory) shards with it; GSPMD inserts the
    forward/backward all-gathers (planning model: cost/zero.py).  Only dims
    divisible by ``axis_size`` are eligible (XLA rejects uneven named
    shardings at placement); 1D leaves and leaves with no eligible dim stay
    replicated — negligible bytes for biases/norms.
    """
    def wrap(spec: P, leaf) -> P:
        shape = leaf.shape
        if len(shape) < 2:
            return spec
        parts = list(spec) + [None] * (len(shape) - len(spec))
        free = [i for i in range(len(shape))
                if parts[i] is None and shape[i] % max(axis_size, 1) == 0]
        if not free:
            return spec
        parts[max(free, key=lambda j: shape[j])] = dp_axis
        return P(*parts)

    return jax.tree.map(wrap, specs, params,
                        is_leaf=lambda x: isinstance(x, P))


def opt_state_specs_by_shape(opt_state, params, wrapped_specs) -> object:
    """PartitionSpec tree for an optax state, by shape/dtype-matching its
    leaves to the parameter leaves.

    Optimizer moments (Adam mu/nu) mirror the param tree leaf-for-leaf but
    live inside optax NamedTuples whose structure differs from the param
    pytree, so specs can't be tree-mapped across directly.  Leaves whose
    (shape, dtype) matches a parameter take that parameter's wrapped spec;
    scalars and unmatched leaves replicate; ambiguous shapes (two params of
    equal shape with different wrapped specs) fall back to replicated rather
    than guessing."""
    shape_to_spec: dict = {}
    p_leaves = jax.tree.leaves(params)
    s_leaves = jax.tree.leaves(wrapped_specs,
                               is_leaf=lambda x: isinstance(x, P))
    for leaf, spec in zip(p_leaves, s_leaves):
        key = (tuple(leaf.shape), jnp.dtype(leaf.dtype))
        if key in shape_to_spec and shape_to_spec[key] != spec:
            shape_to_spec[key] = P()
        else:
            shape_to_spec[key] = spec
    return jax.tree.map(
        lambda l: shape_to_spec.get(
            (tuple(l.shape), jnp.dtype(l.dtype)), P()),
        opt_state)


def loss_fn_for(cfg: GPTConfig):
    from metis_tpu.models.llama import LlamaConfig, llama_next_token_loss

    if isinstance(cfg, MoEConfig):
        return moe_next_token_loss
    if isinstance(cfg, LlamaConfig):
        return llama_next_token_loss
    return next_token_loss


# Elements per chunk of the manual dp gradient all-reduce when the overlap
# pipeline schedule is on (execution/pipeline._reduce_pipeline_grads): 2^20
# f32 elements = 4 MB per collective.  Module-level so tests and benches can
# monkeypatch the granularity.
DP_CHUNK_ELEMS = 1 << 20


def chunked_pmean(tree, axis: str, chunk_elems: int = 0):
    """``pmean`` every leaf of ``tree`` over ``axis`` in flat chunks of at
    most ``chunk_elems`` elements (``<= 0`` uses ``DP_CHUNK_ELEMS``).

    pmean is elementwise, so the chunked result EQUALS the whole-leaf
    pmean bit-for-bit; the point is scheduling — splitting large leaves
    into several smaller all-reduces lets XLA start reducing early grads
    while the backward tail still computes and start the optimizer update
    on reduced chunks' leaves instead of waiting on one monolithic
    collective per leaf.
    """
    if chunk_elems <= 0:
        chunk_elems = DP_CHUNK_ELEMS

    def reduce_leaf(g):
        n = g.size
        if n <= chunk_elems:
            return jax.lax.pmean(g, axis)
        flat = g.reshape(-1)
        parts = [jax.lax.pmean(flat[i:i + chunk_elems], axis)
                 for i in range(0, n, chunk_elems)]
        return jnp.concatenate(parts).reshape(g.shape)

    return jax.tree.map(reduce_leaf, tree)


@jax.tree_util.register_dataclass
@dataclass
class TrainState:
    params: dict
    opt_state: object
    step: jnp.ndarray


class StepTimer:
    """Per-step train-loop telemetry -> EventLog ``train_step`` events.

    ``record()`` once per completed step: wall-clock step time, cumulative
    elapsed, and tokens/sec derived from ``tokens_per_step`` ride on every
    emitted event alongside the caller's fields (loss etc.).  Caveat: JAX
    dispatch is async — a step's wall time is honest only when the caller
    synchronizes (fetching the loss does); between syncs the per-step times
    are dispatch times and only the synced steps' values are load-bearing.
    A disabled log records for free.

    ``monitor`` (optional ``obs.ledger.AccuracyMonitor``): every SYNCED
    step (``loss`` passed — the fetch forced the sync that makes the wall
    time honest) is also fed to the cost-model accuracy ledger, which
    emits ``accuracy_sample`` events and raises the drift alarm when the
    estimator's prediction stops matching the hardware.  Use
    ``--log-every 1`` for per-step accuracy; sparser syncs fold the
    un-synced steps' dispatch lag into the synced step's time."""

    def __init__(self, events=None, tokens_per_step: int = 0,
                 start_step: int = 0, monitor=None):
        import time as _time

        self.events = events if events is not None else NULL_LOG
        self.tokens_per_step = tokens_per_step
        self.step_idx = start_step
        self.monitor = monitor
        self._clock = _time.perf_counter
        self._t0 = self._clock()
        self._last = self._t0

    def record(self, loss: float | None = None, emit: bool = True,
               **fields) -> dict:
        now = self._clock()
        step_ms = (now - self._last) * 1e3
        self._last = now
        self.step_idx += 1
        rec: dict = {"step": self.step_idx,
                     "step_ms": round(step_ms, 3),
                     "elapsed_s": round(now - self._t0, 3)}
        if self.tokens_per_step and step_ms > 0:
            rec["tokens_per_s"] = round(
                self.tokens_per_step / (step_ms / 1e3))
        if loss is not None:
            rec["loss"] = loss
        rec.update(fields)
        if emit:
            self.events.emit("train_step", **rec)
        if self.monitor is not None and loss is not None:
            self.monitor.observe(step_ms, step=self.step_idx)
        return rec


class LossAnomalyDetector:
    """Step-loss sanity guard for the training supervisor
    (``resilience/supervisor.py``).

    ``observe(loss, step)`` classifies each synced step loss:

    - ``"nan"`` — non-finite (NaN/inf).  The state is already poisoned;
      the only safe answer is a rollback to the last checkpoint.
    - ``"spike"`` — finite but > ``spike_factor`` x the rolling mean of the
      last ``window`` healthy losses (once ``min_history`` of them exist —
      the first steps of a fresh run are legitimately wild).  Reported but
      survivable: spikes usually anneal away.
    - ``None`` — healthy; the loss joins the rolling window.

    Anomalous losses never enter the window, so one spike does not raise
    the baseline that judges the next."""

    def __init__(self, spike_factor: float = 10.0, window: int = 8,
                 min_history: int = 3):
        if spike_factor <= 1.0:
            raise ValueError("spike_factor must exceed 1.0")
        if window < 1 or min_history < 1:
            raise ValueError("window and min_history must be >= 1")
        from collections import deque

        self.spike_factor = spike_factor
        self.min_history = min_history
        self._healthy: deque = deque(maxlen=window)

    def observe(self, loss: float, step: int | None = None) -> str | None:
        import math

        loss = float(loss)
        if not math.isfinite(loss):
            return "nan"
        if len(self._healthy) >= self.min_history:
            mean = sum(self._healthy) / len(self._healthy)
            if mean > 0 and loss > self.spike_factor * mean:
                return "spike"
        self._healthy.append(loss)
        return None

    def reset(self) -> None:
        """Forget history — call after a rollback: the restored state's
        losses should be judged fresh, not against the poisoned run-up."""
        self._healthy.clear()


def build_optimizer(lr: float = 1e-4, weight_decay: float = 0.01):
    return optax.adamw(lr, weight_decay=weight_decay)


def build_train_state(
    key: jax.Array,
    cfg: GPTConfig,
    mesh: Mesh,
    optimizer=None,
    tp_axis: str = TP,
    ep_axis: str | None = None,
    fsdp_axis: str | None = None,
    zero: int = 0,
    zero_axis: str = DP,
) -> tuple[TrainState, dict]:
    """Initialize params on-mesh (sharded from the start) and the matching
    optimizer state.  Returns (state, param_specs).  ``ep_axis`` shards MoE
    expert weights (ignored for dense configs; None replicates experts);
    ``fsdp_axis`` additionally shards params + optimizer state ZeRO-3 style
    (usually the dp axis).

    ``zero`` consumes the planner's ``Strategy.zero`` field directly (the
    cost model's memory-relief claim, ``cost/zero.py``, is now delivered by
    execution): 1/2 shard the optimizer state over ``zero_axis`` while
    params stay replicated over data ranks (gradient sharding within the
    update is XLA's to schedule — on TPU there is no separate "ZeRO-2"
    persistent-grad buffer to shard); 3 shards params + state FSDP-style
    (same as passing ``fsdp_axis``)."""
    optimizer = optimizer or build_optimizer()
    if zero >= 3 and fsdp_axis is None:
        fsdp_axis = zero_axis
    specs = param_specs_for(cfg, tp_axis=tp_axis, ep_axis=ep_axis,
                            tp_size=dict(mesh.shape).get(tp_axis, 1))
    host_params = init_params_for(key, cfg)
    if fsdp_axis is not None:
        specs = fsdp_wrap_specs(specs, host_params, fsdp_axis,
                                axis_size=mesh.shape[fsdp_axis])
    params = shard_params(host_params, mesh, specs)
    opt_state = optimizer.init(params)
    if zero in (1, 2) and fsdp_axis is None:
        wrapped = fsdp_wrap_specs(specs, host_params, zero_axis,
                                  axis_size=mesh.shape[zero_axis])
        opt_specs = opt_state_specs_by_shape(opt_state, host_params, wrapped)
        opt_state = jax.tree.map(
            lambda x, sp: jax.device_put(x, NamedSharding(mesh, sp)),
            opt_state, opt_specs)
    return TrainState(params=params, opt_state=opt_state,
                      step=jnp.zeros((), jnp.int32)), specs


def make_train_step(
    cfg: GPTConfig,
    mesh: Mesh,
    optimizer=None,
    attn_impl=None,
    seq_axis: str | None = None,
    dp_axis: str = DP,
    megatron_sp: bool = False,
    tp_axis: str = TP,
    cp_mode: str = "ring",
) -> Callable:
    """Jitted (state, tokens, targets) -> (state, loss).

    ``seq_axis``: shard the sequence over this mesh axis (context
    parallelism) — ``cp_mode`` picks ring attention (K/V rotation) or the
    Ulysses all-to-all head re-shard ("a2a", ops/ulysses.py).  Without it,
    full attention runs locally and tp sharding is handled entirely by
    GSPMD.  ``megatron_sp`` sequence-shards the residual stream over
    ``tp_axis`` (Megatron sequence parallelism: the non-matmul regions'
    activations divide by tp; XLA turns the TP all-reduces into
    reduce-scatter + all-gather pairs around them).
    """
    optimizer = optimizer or build_optimizer()
    if seq_axis is not None and attn_impl is None:
        if cp_mode == "a2a":
            from metis_tpu.ops.ulysses import make_ulysses_attention

            attn_impl = make_ulysses_attention(
                mesh, seq_axis, head_axes=(tp_axis,))
        else:
            attn_impl = make_ring_attention(mesh, seq_axis)

    tok_sharding = NamedSharding(mesh, batch_spec(dp_axis, seq_axis))

    resid_fn = None
    if megatron_sp:
        # [batch, seq, hidden]; with context parallelism active the seq dim
        # is already sharded over seq_axis — sp subdivides it by tp rather
        # than replacing it (dropping seq_axis here would all-gather the cp
        # shards at every block and blow the planned activation budget)
        seq_shard = (seq_axis, tp_axis) if seq_axis is not None else tp_axis
        resid_spec = P(dp_axis, seq_shard, None)
        resid_fn = lambda x: jax.lax.with_sharding_constraint(x, resid_spec)  # noqa: E731

    loss_fn = loss_fn_for(cfg)

    def step(state: TrainState, tokens: jnp.ndarray, targets: jnp.ndarray):
        tokens = jax.lax.with_sharding_constraint(tokens, tok_sharding.spec)
        targets = jax.lax.with_sharding_constraint(targets, tok_sharding.spec)
        loss, grads = jax.value_and_grad(loss_fn)(
            state.params, tokens, targets, cfg, attn_impl, resid_fn)
        updates, opt_state = optimizer.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        return TrainState(params=params, opt_state=opt_state,
                          step=state.step + 1), loss

    with mesh:
        jitted = jax.jit(step, donate_argnums=(0,))

    def run(state, tokens, targets):
        with mesh:
            return jitted(state, tokens, targets)

    return run


def make_forward(cfg: GPTConfig, mesh: Mesh | None = None, attn_impl=None):
    """Jittable forward (params, tokens) -> logits for inference checks and
    the driver's compile entry.  With ``mesh``, compilation runs in that mesh
    context so sharded params keep their layouts."""
    from metis_tpu.models.gpt import forward

    fn = jax.jit(partial(forward, cfg=cfg, attn_impl=attn_impl))
    if mesh is None:
        return fn

    def run(params, tokens):
        with mesh:
            return fn(params, tokens)

    return run
