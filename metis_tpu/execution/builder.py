"""PlanArtifact -> executable training step: the plan-to-execution contract.

One entry point, ``build_executable``, routes a chosen plan to the execution
path that realizes it (the reference prints plan tuples and stops,
``cost_het_cluster.py:73-77``; here the artifact runs):

- **GSPMD single-program** (``execution.train``) for pp=1 rectangular plans —
  dp/ep batch sharding, tp via GSPMD, cp via ring attention over the "sp"
  mesh axis, Megatron SP via residual constraints, ZeRO via state sharding;
- **shard_map pipeline** (``execution.pipeline``) for pp>1 rectangular
  plans with one (dp, tp) strategy and zero=0 — the fastest
  single-program pipeline (GPipe or memory-bounded 1F1B via
  ``schedule=``).  Even layer splits always; 1f1b additionally takes
  UNEVEN block partitions (stages padded to the largest stage's count
  with masked identity layers);
- **multi-mesh per-stage** (``execution.hetero``) for everything else a
  hetero planner emits: non-uniform layer partitions, per-stage strategies,
  uneven hetero-DP microbatches, ZeRO under pipelining, MoE/ep stages, and
  cp (ring attention) stages (each stage is a GSPMD program, so state
  sharding and per-stage mesh axes compose — the configuration the ADVICE
  r1 medium finding flagged as cost-model-only).

Every path is normalized to ``(init, step)`` with
``init(key) -> state`` and ``step(state, tokens, targets) -> (state, loss)``
on full-batch ``[gbs, seq]`` token arrays (microbatch splitting happens
inside, per the plan's microbatch count).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Sequence

import jax

from metis_tpu.execution.hetero import (
    make_hetero_train_step,
    plan_replica_groups,
    plan_replica_rows,
    stage_specs_from_plan,
)
from metis_tpu.execution.mesh import DP, EP, PP, SP, TP, PlanArtifact
from metis_tpu.execution.pipeline import (
    make_pipeline_train_step,
    microbatch_split,
)
from metis_tpu.execution.train import (
    TrainState,
    build_train_state,
    make_train_step,
)
from metis_tpu.models.gpt import GPTConfig
from metis_tpu.models.moe import MoEConfig


@dataclass(frozen=True)
class Executable:
    """A plan realized: which path runs it, plus the normalized step API."""

    kind: str  # "gspmd" | "pipeline" | "hetero"
    init: Callable
    step: Callable


def pipeline_block_counts(artifact: PlanArtifact, cfg: GPTConfig,
                          pp: int) -> tuple[int, ...] | None:
    """Per-stage transformer-BLOCK counts implied by the artifact's
    layer partition (profile layers include the embed/head pseudo-layers on
    the first/last stages), or None when no partition is recorded (implicit
    even split)."""
    bounds = artifact.layer_partition
    if not bounds:
        return None
    blocks = []
    for i in range(len(bounds) - 1):
        lo, hi = bounds[i], bounds[i + 1]
        blocks.append(min(hi - 1, cfg.num_blocks) - max(lo - 1, 0))
    return tuple(blocks)


def _uniform_block_split(artifact: PlanArtifact, cfg: GPTConfig,
                         pp: int) -> bool:
    """True when the layer partition gives every stage the same BLOCK count
    (the shard_map pipeline's contract: the stacked layer axis shards
    evenly over pp).  Counted in transformer blocks, not profile layers:
    the canonical even split (``uniform_layer_split``) gives the first/last
    stages +1 profile layer for the embed/head pseudo-layers while their
    block counts stay equal — exactly the partition the schedule families
    emit, which must route here, not to the hetero executor."""
    blocks = pipeline_block_counts(artifact, cfg, pp)
    if blocks is None:
        return cfg.num_blocks % max(pp, 1) == 0
    return (len(set(blocks)) == 1 and blocks[0] > 0
            and cfg.num_blocks % len(blocks) == 0)


def _uneven_1f1b_split(artifact: PlanArtifact, cfg: GPTConfig, pp: int,
                       schedule: str) -> tuple[int, ...] | None:
    """An uneven block partition the shard_map pipeline can still realize
    (1f1b pads stages to the largest stage's count with masked identity
    layers — ``execution.pipeline.pad_blocks_for_partition``); None when
    the plan must route elsewhere."""
    if schedule != "1f1b":
        return None
    blocks = pipeline_block_counts(artifact, cfg, pp)
    if (blocks is not None and len(blocks) == pp
            and len(set(blocks)) > 1
            and min(blocks) >= 1 and sum(blocks) == cfg.num_blocks):
        return blocks
    return None


def resolve_schedule(
    artifact: PlanArtifact,
    schedule: str | None = None,
    virtual_stages: int | None = None,
) -> tuple[str, int]:
    """One resolution rule for the (schedule, virtual_stages) a plan runs
    with: explicit arguments win, else the artifact's priced values (with
    the historical default of 2 chunks when an explicit interleaved request
    meets an artifact that never recorded a vs).  Shared by
    ``build_executable`` and the CLI so the checkpoint layout string always
    describes what actually executes."""
    if schedule is None:
        schedule = artifact.schedule
    if virtual_stages is None:
        virtual_stages = (artifact.virtual_stages
                          if artifact.virtual_stages > 1 else 2)
    return schedule, virtual_stages


def exec_state_to_train_state(kind: str, state, step: int,
                              mesh=None, replicate_step: bool = False
                              ) -> TrainState:
    """Adapt an executable's state to the checkpointable ``TrainState``.

    The gspmd route's state IS a TrainState; the pipeline route's is a
    ``(params, opt_state)`` tuple whose step lives outside the state — wrap
    it with ``step`` as an int32 scalar.  ``replicate_step`` (multi-host):
    orbax refuses host-local arrays in a multi-controller run, so the step
    scalar is replicated over ``mesh``.  Hetero per-stage state lists have
    their own save/restore pair (``save_hetero_checkpoint``) and do not
    adapt."""
    if kind == "gspmd":
        return state
    if kind == "hetero":
        raise ValueError(
            "hetero state lists checkpoint via save_hetero_checkpoint, "
            "not TrainState")
    import jax.numpy as jnp

    params, opt_state = state
    step_arr = jnp.asarray(step, jnp.int32)
    if replicate_step and mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec

        step_arr = jax.device_put(
            step_arr, NamedSharding(mesh, PartitionSpec()))
    return TrainState(params=params, opt_state=opt_state, step=step_arr)


def train_state_to_exec_state(kind: str, ts: TrainState):
    """Inverse of ``exec_state_to_train_state`` — unwrap a (restored)
    TrainState back into the shape ``Executable.step`` consumes."""
    if kind == "gspmd":
        return ts
    if kind == "hetero":
        raise ValueError("hetero state lists do not adapt to TrainState")
    return (ts.params, ts.opt_state)


def checkpoint_block_layout(
    artifact: PlanArtifact,
    cfg: GPTConfig,
    exe_kind: str,
    schedule: str,
    virtual_stages: int,
) -> str:
    """The ``CheckpointMeta.block_layout`` string describing how this
    (plan, executable, schedule) physically orders the stacked block axis.

    The interleaved schedule permutes the block order
    (``execution.pipeline.interleave_block_order``) as a function of BOTH
    pp and virtual_stages; an uneven 1f1b split pads/reorders it too
    (``pad_blocks_for_partition``).  Restore compares this string and
    refuses a mismatch — a silent mismatch would scramble the layers."""
    if exe_kind != "pipeline":
        return "canonical"
    if artifact.mesh_shape and PP in artifact.mesh_axes:
        pp = artifact.mesh_shape[artifact.mesh_axes.index(PP)]
    else:
        pp = 1
    if schedule == "interleaved":
        return f"interleaved:{pp}x{virtual_stages}"
    counts = _uneven_1f1b_split(artifact, cfg, pp, schedule)
    if counts is not None:
        return f"uneven:{pp}x" + "-".join(str(c) for c in counts)
    return "canonical"


def build_executable(
    cfg: GPTConfig,
    artifact: PlanArtifact,
    devices: Sequence | None = None,
    optimizer=None,
    cluster=None,
    profiles=None,
    schedule: str | None = None,
    virtual_stages: int | None = None,
    events=None,
    overlap: bool = True,
) -> Executable:
    """Route ``artifact`` to the execution path that realizes it.

    ``cluster`` + ``profiles`` (optional) enable the data balancer's uneven
    per-replica microbatches on mixed-type hetero stages.  ``schedule``
    selects the single-program pipeline schedule ("gpipe", the
    memory-bounded "1f1b", or "interleaved" with ``virtual_stages`` model
    chunks per device — smaller fill/drain bubble when the microbatch
    count is below ~virtual_stages*pp; it drains between microbatch
    groups) and applies only when the plan routes to the
    shard_map pipeline; the gspmd route has no pipeline and the hetero
    route is already stage-granular-remat with boundary-only storage.
    ``None`` (default) runs the schedule the ARTIFACT was priced with —
    the planner searches the schedule as a plan axis (cost/schedule.py,
    including 1f1b's remat overhead and true activation peak) and the
    executable must realize what was costed; pass explicitly to override.

    ``events`` (optional ``core.events.EventLog``): forwarded to the
    pipeline route for build/first-step-compile phase spans via the flight
    recorder (``execution/pipeline.py``).

    ``overlap`` (default on, pipeline route only): the communication-
    overlap schedule — double-buffered boundary ppermute + chunked dp
    gradient all-reduce; gradients identical to lockstep
    (``execution/pipeline.py``).  False forces the lockstep schedule."""
    schedule, virtual_stages = resolve_schedule(
        artifact, schedule, virtual_stages)
    if schedule not in ("gpipe", "1f1b", "interleaved"):
        raise ValueError(f"unknown pipeline schedule {schedule!r}")
    if schedule == "interleaved" and virtual_stages < 1:
        raise ValueError(f"virtual_stages={virtual_stages} must be >= 1")
    strategies = [dict(s) for s in artifact.strategies]
    for s in strategies:
        s.setdefault("cp", 1)
        s.setdefault("ep", 1)
        s.setdefault("zero", 0)
        s.setdefault("sp", False)
        s.setdefault("cp_mode", "ring")
    # uniform artifacts carry ONE strategy with pp encoded in the mesh shape
    # (PlanArtifact.from_uniform_plan); hetero artifacts carry one per stage
    if artifact.mesh_shape and PP in artifact.mesh_axes:
        pp = artifact.mesh_shape[artifact.mesh_axes.index(PP)]
    else:
        pp = len(strategies)
    if len(strategies) == 1 and pp > 1:
        strategies = strategies * pp
    uniform = len({(s["dp"], s["tp"], s["cp"], s["ep"], s["zero"], s["sp"])
                   for s in strategies}) == 1
    s0 = strategies[0]

    if artifact.mesh_shape and pp == 1:
        return _gspmd_executable(cfg, artifact, s0, devices, optimizer)

    if (artifact.mesh_shape and uniform and s0["zero"] == 0
            and not s0["sp"] and s0["cp"] == 1 and s0["ep"] == 1):
        if _uniform_block_split(artifact, cfg, pp):
            return _pipeline_executable(
                cfg, artifact, s0, pp, devices, optimizer,
                schedule, virtual_stages, events=events, overlap=overlap)
        counts = _uneven_1f1b_split(artifact, cfg, pp, schedule)
        if counts is not None:
            return _pipeline_executable(
                cfg, artifact, s0, pp, devices, optimizer,
                schedule, virtual_stages, block_counts=counts,
                events=events, overlap=overlap)

    return _hetero_executable(
        cfg, artifact, strategies, devices, optimizer, cluster, profiles)


def _gspmd_executable(cfg, artifact, s0, devices, optimizer) -> Executable:
    mesh = artifact.build_mesh(devices)
    is_moe = isinstance(cfg, MoEConfig)
    seq_axis = SP if s0["cp"] > 1 else None
    dp_axis = (DP, EP) if s0["ep"] > 1 else DP

    def init(key):
        state, _ = build_train_state(
            key, cfg, mesh, optimizer=optimizer, tp_axis=TP,
            ep_axis=EP if is_moe else None,
            zero=s0["zero"], zero_axis=DP)
        return state

    step = make_train_step(
        cfg, mesh, optimizer=optimizer, seq_axis=seq_axis, dp_axis=dp_axis,
        megatron_sp=bool(s0["sp"]), tp_axis=TP,
        cp_mode=s0.get("cp_mode", "ring"))
    return Executable(kind="gspmd", init=init, step=step)


def _pipeline_executable(cfg, artifact, s0, pp, devices,
                         optimizer, schedule="gpipe",
                         virtual_stages=2, block_counts=None,
                         events=None, overlap=True) -> Executable:
    import numpy as np
    from jax.sharding import Mesh

    devs = list(devices if devices is not None else jax.devices())
    need = pp * s0["dp"] * s0["tp"]
    if len(devs) < need:
        raise ValueError(f"plan needs {need} devices, have {len(devs)}")
    mesh = Mesh(
        np.array(devs[:need]).reshape(pp, s0["dp"], s0["tp"]), (PP, DP, TP))
    from metis_tpu.core.events import NULL_LOG

    init_fn, raw_step = make_pipeline_train_step(
        cfg, mesh, artifact.microbatches, optimizer=optimizer,
        schedule=schedule, virtual_stages=virtual_stages,
        block_counts=block_counts,
        events=events if events is not None else NULL_LOG,
        overlap=overlap)

    def init(key):
        return init_fn(key)

    def step(state, tokens, targets):
        params, opt_state = state
        tok = microbatch_split(tokens, artifact.microbatches)
        tgt = microbatch_split(targets, artifact.microbatches)
        params, opt_state, loss = raw_step(params, opt_state, tok, tgt)
        return (params, opt_state), loss

    return Executable(kind="pipeline", init=init, step=step)


def _hetero_executable(cfg, artifact, strategies, devices, optimizer, cluster,
                       profiles) -> Executable:
    pp = len(strategies)
    rows = groups = None
    if (cluster is not None and profiles is not None
            and artifact.node_sequence):
        # mixed-type stages split into per-type sub-meshes, each computing
        # only its data balancer share (no padding; an MoE group's expert
        # capacity derives from its own tokens — hetero.StageSpec docs)
        from metis_tpu.core.types import InterStagePlan, Strategy

        inter = InterStagePlan(
            node_sequence=tuple(artifact.node_sequence),
            device_groups=tuple(artifact.device_groups),
            batches=artifact.microbatches, gbs=artifact.gbs)
        strats = [Strategy(dp=s["dp"], tp=s["tp"]) for s in strategies]
        rows = plan_replica_rows(inter, strats, cluster, profiles)
        groups = plan_replica_groups(inter, strats, cluster)
    bounds = artifact.layer_partition
    if not bounds:
        # rectangular artifacts drop the canonical even split; rebuild it
        per = cfg.num_profile_layers // pp
        bounds = tuple(per * i for i in range(pp)) + (cfg.num_profile_layers,)
    stages = stage_specs_from_plan(
        bounds, strategies, cfg, stage_replica_rows=rows,
        stage_replica_groups=groups)
    init_fn, raw_step = make_hetero_train_step(
        cfg, stages, devices=devices, optimizer=optimizer)

    def step(state, tokens, targets):
        tok = microbatch_split(tokens, artifact.microbatches)
        tgt = microbatch_split(targets, artifact.microbatches)
        return raw_step(state, tok, tgt)

    return Executable(kind="hetero", init=init_fn, step=step)
