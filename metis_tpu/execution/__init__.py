from metis_tpu.execution.mesh import (
    DP,
    PP,
    SP,
    TP,
    PlanArtifact,
    batch_spec,
    gpt_param_specs,
    mesh_dp_tp,
    mesh_for_uniform_plan,
    shard_params,
)
from metis_tpu.execution.train import (
    TrainState,
    build_optimizer,
    build_train_state,
    make_forward,
    make_train_step,
)
from metis_tpu.execution.pipeline import (
    make_pipeline_train_step,
    microbatch_split,
    tp_block_forward,
    tp_embed,
    tp_head_loss,
)

__all__ = [
    "DP", "PP", "SP", "TP",
    "PlanArtifact",
    "batch_spec",
    "gpt_param_specs",
    "mesh_dp_tp",
    "mesh_for_uniform_plan",
    "shard_params",
    "TrainState",
    "build_optimizer",
    "build_train_state",
    "make_forward",
    "make_train_step",
    "make_pipeline_train_step",
    "microbatch_split",
    "tp_block_forward",
    "tp_embed",
    "tp_head_loss",
]
