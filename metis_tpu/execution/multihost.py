"""Multi-host (multi-controller) execution — SURVEY.md §7 hard part 3.

JAX's multi-controller runtime: every host runs the SAME program against a
global device set (``jax.distributed.initialize`` wires the coordination
service; ``jax.devices()`` then spans all hosts, ``jax.local_devices()``
this host's chips).  jit-compiled computations over a global
``jax.sharding.Mesh`` are single-program-multiple-data across hosts — XLA
inserts the cross-host collectives (ICI within a slice, DCN between slices
on real TPU deployments; Gloo on the CPU fake backend the tests use).

What this module adds over plain jax:

- ``initialize_multihost`` — init with the platform pinned FIRST (a wedged
  remote-TPU tunnel hangs any backend touch, so the pin must precede the
  distributed handshake), returning a summary the caller can assert on;
- ``global_batch_pipeline`` — per-host data feeding: every host computes
  the batch schedule deterministically (same seed), but only materializes
  and transfers the shards its own devices own
  (``jax.make_array_from_callback`` slices the host batch per addressable
  device).  The GSPMD and pipeline executors consume the resulting global
  arrays unchanged — the same ``make_train_step``/
  ``make_pipeline_train_step`` run single- or multi-controller.

How the HETERO (multi-mesh) executor maps to multi-host — the design note
VERDICT r2 asked for: ``execution/hetero.py`` is deliberately
single-controller.  Its per-stage programs live on disjoint device sets
and exchange boundary activations with ``jax.device_put`` — on a
multi-slice TPU deployment each stage's mesh is one slice, and the
boundary ``device_put`` between stages is exactly a DCN transfer
(host-mediated unless ``jax.transfer_guard``-free direct DCN paths exist
for the pair).  Scaling that to multiple CONTROLLERS means each slice's
host feeds its own stage and the boundary tensors flow host-to-host;
the uniform GSPMD/pipeline paths in this module are the multi-controller
story, and a hetero deployment runs one controller per stage group with
this module's primitives inside each stage.

The CLI test path: ``python -m metis_tpu.execution.multihost <proc_id>
<num_procs> <port> <mode>`` runs one worker (mode "gspmd" or "pipeline")
— tests/test_multihost.py spawns two of them over 4 virtual CPU devices
each and checks cross-process loss agreement AND numeric parity with the
identical single-process 8-device run.
"""
from __future__ import annotations

import json
import sys
from dataclasses import dataclass


@dataclass(frozen=True)
class MultihostInfo:
    process_index: int
    process_count: int
    global_device_count: int
    local_device_count: int


def initialize_multihost(
    coordinator_address: str,
    num_processes: int,
    process_id: int,
    platform: str | None = None,
) -> MultihostInfo:
    """``jax.distributed.initialize`` with the platform pinned first.

    ``platform``: pin via jax.config BEFORE any backend touch (plugin
    backends override the JAX_PLATFORMS env var at import; a wedged
    remote-TPU tunnel then hangs init — the round-1/2 failure mode)."""
    import jax

    if platform is not None:
        jax.config.update("jax_platforms", platform)
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    return MultihostInfo(
        process_index=jax.process_index(),
        process_count=jax.process_count(),
        global_device_count=len(jax.devices()),
        local_device_count=len(jax.local_devices()),
    )


def global_batch_pipeline(
    dataset,
    gbs: int,
    mesh,
    dp_axis="dp",
    seq_axis=None,
    shuffle_seed: int | None = 0,
    epochs: int | None = None,
    skip_batches: int = 0,
):
    """Iterator of GLOBAL ``(tokens, targets)`` arrays for multi-controller
    training: every host walks the same deterministic batch schedule, but
    only its addressable shards are materialized on devices.

    The batch schedule must be identical on every host (same dataset,
    seed, and skip) — global arrays are assembled from per-host shards, so
    divergent schedules would silently mix batches."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from metis_tpu.data.pipeline import _host_batches

    sharding = NamedSharding(mesh, P(dp_axis, seq_axis))

    def to_global(arr):
        return jax.make_array_from_callback(
            arr.shape, sharding, lambda idx: arr[idx])

    for toks, tgts in _host_batches(dataset, gbs, shuffle_seed, epochs,
                                    skip=skip_batches):
        yield to_global(toks), to_global(tgts)


def spawn_workers(
    mode: str,
    port: int,
    num_procs: int = 2,
    devices_per_process: int = 4,
    timeout_s: float = 300.0,
) -> list[dict]:
    """Spawn ``num_procs`` multihost workers (this module's ``__main__``)
    and return their parsed JSON reports.  ALWAYS reaps every child —
    a failed or timed-out worker must not leave its peers blocked in the
    coordinator handshake holding the port (they would poison every later
    run on the same port)."""
    import os
    import subprocess

    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    env = {**os.environ,
           "JAX_PLATFORMS": "cpu",
           "XLA_FLAGS":
               f"--xla_force_host_platform_device_count={devices_per_process}",
           "PYTHONPATH": repo}
    procs = [
        subprocess.Popen(
            [sys.executable, "-m", "metis_tpu.execution.multihost",
             str(i), str(num_procs), str(port), mode],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env, cwd=repo)
        for i in range(num_procs)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=timeout_s)
            if p.returncode != 0:
                raise RuntimeError(
                    f"multihost worker failed:\n{err[-1500:]}")
            outs.append(json.loads(out.strip().splitlines()[-1]))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    return outs


# ---------------------------------------------------------------------------
# worker entry (spawned by tests / dryrun_multihost)
# ---------------------------------------------------------------------------


def _worker_main(argv: list[str]) -> int:
    proc_id, num_procs, port, mode = (
        int(argv[0]), int(argv[1]), int(argv[2]), argv[3])
    info = initialize_multihost(
        f"127.0.0.1:{port}", num_procs, proc_id, platform="cpu")

    import numpy as np

    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from metis_tpu.data.pipeline import TokenDataset
    from metis_tpu.execution.mesh import DP, PP, TP
    from metis_tpu.execution.pipeline import (
        make_pipeline_train_step,
        microbatch_split,
    )
    from metis_tpu.execution.train import build_train_state, make_train_step
    from metis_tpu.models import GPTConfig

    devs = jax.devices()
    cfg = GPTConfig(vocab_size=512, seq_len=16, hidden=64, num_heads=4,
                    num_blocks=2, ffn_multiplier=2, dtype=jnp.float32)
    gbs, steps = 8, 2
    dataset = TokenDataset.synthetic(
        cfg.vocab_size, gbs * cfg.seq_len * (steps + 2) + 1, cfg.seq_len)

    losses = []
    if mode == "gspmd":
        mesh = Mesh(np.array(devs).reshape(len(devs) // 2, 2), (DP, TP))
        state, _ = build_train_state(jax.random.PRNGKey(0), cfg, mesh)
        step = make_train_step(cfg, mesh)
        batches = global_batch_pipeline(dataset, gbs, mesh, dp_axis=DP)
        for _ in range(steps):
            toks, tgts = next(batches)
            state, loss = step(state, toks, tgts)
            losses.append(float(jax.device_get(loss)))
    elif mode == "pipeline":
        pp, tp = 2, 2
        dp = len(devs) // (pp * tp)
        mesh = Mesh(np.array(devs).reshape(pp, dp, tp), (PP, DP, TP))
        M = 2
        init_fn, step = make_pipeline_train_step(cfg, mesh, M)
        params, opt_state = init_fn(jax.random.PRNGKey(1))
        # microbatch-major [M, gbs/M, seq] global arrays: feed per host
        # through the same callback-sharded path (dp shards dim 1)
        from jax.sharding import NamedSharding, PartitionSpec as P

        from metis_tpu.data.pipeline import _host_batches

        data_sharding = NamedSharding(mesh, P(None, DP, None))

        def to_global(arr):
            return jax.make_array_from_callback(
                arr.shape, data_sharding, lambda idx: arr[idx])

        host = _host_batches(dataset, gbs, 0, None, skip=0)
        for _ in range(steps):
            toks, tgts = next(host)
            tok_mbs = to_global(np.asarray(microbatch_split(
                jnp.asarray(toks), M)))
            tgt_mbs = to_global(np.asarray(microbatch_split(
                jnp.asarray(tgts), M)))
            params, opt_state, loss = step(params, opt_state, tok_mbs,
                                           tgt_mbs)
            losses.append(float(jax.device_get(loss)))
    else:
        raise SystemExit(f"unknown mode {mode!r}")

    print(json.dumps({
        "process": info.process_index,
        "processes": info.process_count,
        "global_devices": info.global_device_count,
        "local_devices": info.local_device_count,
        "mode": mode,
        "losses": losses,
    }), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(_worker_main(sys.argv[1:]))
