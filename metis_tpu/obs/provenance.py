"""Plan provenance: the decision log — the system's flight data recorder.

PR 16 made every request and metric observable, but nothing durable
answered *why is the fleet running this plan?* — the drift→replan→push→
migrate loop mutates served plans with no queryable record of what
triggered each change, what the runner-up was, or how much the cost model
could be trusted at the margin.  This module closes that gap:

- :class:`DecisionRecord` — one plan decision (cold search, cache-hit
  serve, drift replan, cluster-delta replan, fleet re-partition, tenant
  replan, migration choice, autoscale delta) with its query/plan
  fingerprints, the trigger cause, a **causal parent seq**, the trace_id,
  config/calibration/profile digests, the additive ``CostBreakdown``, the
  exact-backend ``Certificate`` when one exists, the runner-up plan and
  its margin, and per-component residual stats as model-confidence
  context for that margin.
- :class:`DecisionLog` — an append-only, sequence-numbered JSONL file.
  Reopening an existing log resumes the sequence (a daemon restart never
  resets seq numbering), and every append also emits a ``decision_record``
  event into the regular event stream so traces and decisions join.
- :func:`diff_plans` — attributes a decision change per
  ``CostBreakdown`` component (the additive deltas sum exactly to the
  total_ms delta) and per decision axis (stages / dp / tp / cp /
  placement / layer-cut / schedule).
- :func:`causal_chain` / :func:`render_chain` — walk parent seqs back to
  the root trigger (e.g. ``preemption → cluster_delta →
  fleet_repartition → tenant_replan → migration_decision``) and render
  the chain with the attributed diff at each hop — what
  ``metis-tpu why`` prints.

The log is durable state like the accuracy ledger, not telemetry: it is
never rotated, and ``tools/check_decisions_schema.py`` validates its
invariants (seq monotonicity, resolvable parents, additive breakdowns)
in tier-1.
"""
from __future__ import annotations

import hashlib
import json
import threading
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import IO, Any, Sequence

from metis_tpu.core.events import EventLog, NULL_LOG
from metis_tpu.core.types import COST_COMPONENTS, CostBreakdown

# Every kind a DecisionRecord may carry — one per way the system picks
# (or re-picks) a plan.  ``cluster_delta`` is the capacity-change root
# decision the per-tenant / per-query replans hang off; ``autoscale_delta``
# is the same root when a predictive autoscaler (inference/replay.py)
# issued the delta.
DECISION_KINDS = (
    "cold_search",       # cache miss -> full (or warm-state) search
    "cache_hit",         # served straight from the plan cache
    "drift_replan",      # accuracy drift alarm -> re-search
    "cluster_delta",     # capacity changed (eviction / return / manual)
    "autoscale_delta",   # capacity changed by a forecast-driven policy
    "delta_replan",      # per-query re-search after a cluster delta
    "fleet_repartition", # multi-tenant carve re-scored (sched/fleet.py)
    "tenant_replan",     # one tenant's carve changed -> new plan
    "migration_decision",# migrate-vs-checkpoint-restore choice
    "profile_transfer",  # roofline transfer to an unprofiled device type
)


@dataclass(frozen=True)
class DecisionRecord:
    """One plan decision, as the decision log persists it.

    ``parent_seq`` is the causal edge: the seq of the decision that
    *caused* this one (a cache hit's parent is the cold search that
    filled the entry; a tenant replan's parent is the fleet re-partition;
    the re-partition's parent is the cluster delta).  ``None`` marks a
    causal root.  ``margin_ms`` is ``runner_up.total_ms - total_ms`` —
    how close the ranking was — and ``confidence`` carries the ledger's
    per-component residual stats so the margin can be judged against the
    model's demonstrated error ("runner-up was 3.1 ms away; p95 compute
    residual alone is 4.2 ms").
    """

    seq: int
    ts: float
    kind: str
    plan_fingerprint: str = ""
    query_fingerprint: str = ""
    cause: str = ""
    parent_seq: int | None = None
    trace_id: str | None = None
    tenant: str | None = None
    total_ms: float | None = None
    breakdown: dict | None = None       # CostBreakdown.to_json_dict()
    certificate: dict | None = None     # Certificate.to_json_dict()
    runner_up: dict | None = None       # {"plan_fingerprint", "total_ms"}
    margin_ms: float | None = None
    confidence: dict | None = None      # component -> residual stats
    digests: dict = field(default_factory=dict)  # config/calibration/profiles
    detail: dict = field(default_factory=dict)   # kind-specific extras

    def to_json_dict(self) -> dict:
        d = {"seq": self.seq, "ts": self.ts, "kind": self.kind,
             "plan_fingerprint": self.plan_fingerprint}
        if self.query_fingerprint:
            d["query_fingerprint"] = self.query_fingerprint
        if self.cause:
            d["cause"] = self.cause
        if self.parent_seq is not None:
            d["parent_seq"] = self.parent_seq
        if self.trace_id:
            d["trace_id"] = self.trace_id
        if self.tenant:
            d["tenant"] = self.tenant
        if self.total_ms is not None:
            d["total_ms"] = self.total_ms
        if self.breakdown is not None:
            d["breakdown"] = self.breakdown
        if self.certificate is not None:
            d["certificate"] = self.certificate
        if self.runner_up is not None:
            d["runner_up"] = self.runner_up
        if self.margin_ms is not None:
            d["margin_ms"] = self.margin_ms
        if self.confidence:
            d["confidence"] = self.confidence
        if self.digests:
            d["digests"] = self.digests
        if self.detail:
            d["detail"] = self.detail
        return d

    @staticmethod
    def from_json_dict(d: dict) -> "DecisionRecord":
        return DecisionRecord(
            seq=int(d["seq"]),
            ts=float(d.get("ts", 0.0)),
            kind=d["kind"],
            plan_fingerprint=d.get("plan_fingerprint", ""),
            query_fingerprint=d.get("query_fingerprint", ""),
            cause=d.get("cause", ""),
            parent_seq=(int(d["parent_seq"])
                        if d.get("parent_seq") is not None else None),
            trace_id=d.get("trace_id"),
            tenant=d.get("tenant"),
            total_ms=d.get("total_ms"),
            breakdown=d.get("breakdown"),
            certificate=d.get("certificate"),
            runner_up=d.get("runner_up"),
            margin_ms=d.get("margin_ms"),
            confidence=d.get("confidence"),
            digests=dict(d.get("digests", {})),
            detail=dict(d.get("detail", {})),
        )


class DecisionLog:
    """Append-only, sequence-numbered decision JSONL.

    ``DecisionLog(None)`` keeps decisions in memory only (tests, NULL
    wiring).  Opening an existing path reloads every record and resumes
    the sequence where the previous process left off — restart-safe seq
    continuity is the contract ``GET /decisions?since=N`` subscribers
    rely on.  Thread-safe; the append is a single buffered line write,
    cheap enough to ride the cached-hit serve path (bench ``provenance``
    section pins the overhead ≤ 2%).
    """

    def __init__(self, path: str | Path | None = None,
                 events: EventLog = NULL_LOG):
        self.path = Path(path) if path is not None else None
        self.events = events
        self._fh: IO[str] | None = None
        self._lock = threading.RLock()
        self._records: list[DecisionRecord] = []
        self._by_seq: dict[int, DecisionRecord] = {}
        self._last_seq = 0
        if self.path is not None and self.path.exists():
            self._load()

    def _load(self) -> None:
        for line in self.path.read_text().splitlines():
            if not line.strip():
                continue
            try:
                rec = DecisionRecord.from_json_dict(json.loads(line))
            except (json.JSONDecodeError, KeyError, TypeError, ValueError):
                continue  # the schema checker reports corruption; keep going
            self._records.append(rec)
            self._by_seq[rec.seq] = rec
            self._last_seq = max(self._last_seq, rec.seq)

    @property
    def last_seq(self) -> int:
        with self._lock:
            return self._last_seq

    def resume_seq(self, seq: int) -> None:
        """Fast-forward the sequence cursor to at least ``seq`` (never
        backwards).  A durable log resumes from its own file on open;
        this covers the in-memory case, where a restored daemon snapshot
        remembers the seq the previous process reached — ``GET
        /decisions?since=N`` subscribers rely on seq numbers never being
        reissued across a restart."""
        with self._lock:
            self._last_seq = max(self._last_seq, int(seq))

    def __len__(self) -> int:
        with self._lock:
            return len(self._records)

    def record(self, kind: str, plan_fingerprint: str = "",
               **fields: Any) -> DecisionRecord:
        """Append one decision; returns the record with its seq assigned.

        ``fields`` are DecisionRecord fields (query_fingerprint, cause,
        parent_seq, trace_id, tenant, total_ms, breakdown, certificate,
        runner_up, margin_ms, confidence, digests, detail).
        """
        with self._lock:
            self._last_seq += 1
            rec = DecisionRecord(
                seq=self._last_seq, ts=time.time(), kind=kind,
                plan_fingerprint=plan_fingerprint, **fields)
            self._records.append(rec)
            self._by_seq[rec.seq] = rec
            if self.path is not None:
                if self._fh is None:
                    self._fh = open(self.path, "a", buffering=1)
                self._fh.write(
                    json.dumps(rec.to_json_dict(), default=str) + "\n")
        ev = {"seq": rec.seq, "kind": kind, "fingerprint": plan_fingerprint}
        if rec.trace_id:
            ev["trace_id"] = rec.trace_id
        self.events.emit("decision_record", **ev)
        return rec

    def records(self, since: int = 0) -> list[DecisionRecord]:
        """Records with ``seq > since``, oldest first."""
        with self._lock:
            return [r for r in self._records if r.seq > since]

    def get(self, seq: int) -> DecisionRecord | None:
        with self._lock:
            return self._by_seq.get(seq)

    def find(self, plan_fingerprint: str | None = None,
             tenant: str | None = None,
             kind: str | None = None) -> DecisionRecord | None:
        """The LATEST record matching every given criterion, or None."""
        with self._lock:
            for rec in reversed(self._records):
                if plan_fingerprint is not None \
                        and rec.plan_fingerprint != plan_fingerprint:
                    continue
                if tenant is not None and rec.tenant != tenant:
                    continue
                if kind is not None and rec.kind != kind:
                    continue
                return rec
        return None

    def chain(self, leaf: DecisionRecord | int) -> list[DecisionRecord]:
        """Causal chain root..leaf (see :func:`causal_chain`)."""
        with self._lock:
            return causal_chain(self._records, leaf)

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None

    def __enter__(self) -> "DecisionLog":
        return self

    def __exit__(self, *exc: object) -> bool:
        self.close()
        return False


NULL_DECISIONS = DecisionLog(None)


# ---------------------------------------------------------------------------
# planner-result helpers
# ---------------------------------------------------------------------------


def artifact_digest(obj) -> str:
    """12-hex sha1 of any JSON-serializable object (canonical form) — the
    generic digest ``DecisionRecord.digests`` values use."""
    payload = json.dumps(obj, sort_keys=True, separators=(",", ":"),
                         default=str)
    return hashlib.sha1(payload.encode()).hexdigest()[:12]


def profile_store_digest(profiles) -> str:
    """Identity of a ``profiles.store.ProfileStore``'s pricing-relevant
    content: per-(type, tp, bs) total layer time plus the attention stamp
    and layer count.  Two stores that would price every candidate
    identically digest identically; "" when the store is not digestable."""
    try:
        return artifact_digest({
            "configs": {
                f"{t}/tp{tp}/bs{bs}": round(
                    profiles.get(t, tp, bs).total_time_ms, 6)
                for (t, tp, bs) in profiles.configs()},
            "attn": getattr(profiles, "attn", None),
            "num_layers": profiles.model.num_layers,
        })
    except Exception:
        return ""


def fingerprint_plan_dict(d: dict) -> str:
    """Plan fingerprint of a serialized plan dict (a ``dump_ranked_plans``
    entry or ``RankedPlan.to_json_dict()``): reuses an embedded
    ``plan_fingerprint`` when present, recomputes from the structural
    fields otherwise, and returns "" when neither is possible."""
    from metis_tpu.obs.ledger import plan_fingerprint as _fp

    if d.get("plan_fingerprint"):
        return d["plan_fingerprint"]
    if "layer_partition" in d and "strategies" in d:
        return _fp(
            layer_partition=d.get("layer_partition", ()),
            strategies=d.get("strategies", ()),
            gbs=d.get("gbs", 0),
            microbatches=d.get("batches", 0),
            node_sequence=d.get("node_sequence", ()),
            device_groups=d.get("device_groups", ()),
            schedule=d.get("schedule", "gpipe"),
            virtual_stages=d.get("virtual_stages", 1),
        )
    return ""


def planner_decision_fields(result) -> dict:
    """DecisionRecord fields extracted from a ``planner.api``
    PlannerResult: best plan fingerprint + breakdown, the runner-up and
    margin, and the exact-backend certificate when one was attached.
    Returns {} for an infeasible result (no best plan)."""
    from metis_tpu.obs.ledger import fingerprint_ranked_plan

    best = result.best
    if best is None:
        return {}
    fields: dict = {"plan_fingerprint": fingerprint_ranked_plan(best),
                    "total_ms": best.cost.total_ms}
    if best.breakdown is not None:
        fields["breakdown"] = best.breakdown.to_json_dict()
    if len(result.plans) > 1:
        ru = result.plans[1]
        fields["runner_up"] = {
            "plan_fingerprint": fingerprint_ranked_plan(ru),
            "total_ms": ru.cost.total_ms,
        }
        fields["margin_ms"] = ru.cost.total_ms - best.cost.total_ms
    if result.certificate is not None:
        fields["certificate"] = result.certificate.to_json_dict()
    return fields


def record_planner_decision(decisions: "DecisionLog | None", result,
                            kind: str = "cold_search",
                            **fields: Any) -> DecisionRecord | None:
    """Record one planner-search decision into ``decisions`` (None or an
    infeasible result record nothing): the :func:`planner_decision_fields`
    extraction plus any caller fields (cause, parent_seq, trace_id,
    tenant, digests, detail...).  The one call the offline entry points
    (``planner.api.plan_hetero``, ``planner.replan``) thread through."""
    if decisions is None:
        return None
    extracted = planner_decision_fields(result)
    if not extracted:
        return None
    fp = extracted.pop("plan_fingerprint", "")
    return decisions.record(kind, plan_fingerprint=fp,
                            **{**extracted, **fields})


# ---------------------------------------------------------------------------
# plan diff engine
# ---------------------------------------------------------------------------

# The decision axes a diff reports: what structurally changed between two
# plans, independent of the cost attribution.
DIFF_AXES = ("stages", "dp", "tp", "cp", "placement", "layer_cut",
             "schedule", "virtual_stages", "batches", "gbs")


def _plan_dict(obj) -> dict:
    """Normalize a diffable object to a plan JSON dict: accepts a live
    ``RankedPlan``, a ``RankedPlan.to_json_dict()`` / ``dump_ranked_plans``
    entry, or a ``DecisionRecord`` (whose breakdown carries the cost but
    no structural axes — those stay empty)."""
    if isinstance(obj, DecisionRecord):
        d: dict = {"plan_fingerprint": obj.plan_fingerprint}
        if obj.breakdown is not None:
            d["breakdown"] = obj.breakdown
        if obj.total_ms is not None:
            d["cost_ms"] = obj.total_ms
        return d
    if isinstance(obj, dict):
        return obj
    if hasattr(obj, "to_json_dict"):  # RankedPlan
        return obj.to_json_dict()
    raise TypeError(f"cannot diff a {type(obj).__name__}")


def plan_axes(plan: dict) -> dict:
    """Decision-axis view of one plan dict (missing axes omitted)."""
    axes: dict = {}
    if "device_groups" in plan or "num_stages" in plan:
        axes["stages"] = plan.get("num_stages",
                                  len(plan.get("device_groups", ())))
    strategies = plan.get("strategies")
    if strategies:
        axes["dp"] = [int(s.get("dp", 1)) for s in strategies]
        axes["tp"] = [int(s.get("tp", 1)) for s in strategies]
        axes["cp"] = [int(s.get("cp", 1)) for s in strategies]
    if "node_sequence" in plan:
        axes["placement"] = list(plan["node_sequence"])
    if "layer_partition" in plan:
        axes["layer_cut"] = list(plan["layer_partition"])
    for key in ("schedule", "virtual_stages", "batches", "gbs"):
        if key in plan:
            axes[key] = plan[key]
    return axes


@dataclass(frozen=True)
class PlanDiff:
    """Attributed difference between two plans (b relative to a).

    ``component_deltas`` decompose ``total_delta_ms`` exactly — the
    additive contract ``CostBreakdown`` pins (components sum to total_ms
    on each side, so their per-component differences sum to the total
    difference).  ``axis_changes`` lists every decision axis whose value
    moved; ``decisive`` names the component carrying the largest share
    of the delta."""

    fingerprint_a: str
    fingerprint_b: str
    total_a_ms: float | None
    total_b_ms: float | None
    total_delta_ms: float | None
    component_deltas: dict[str, float]
    decisive: tuple[str, float] | None
    axis_changes: dict[str, dict]
    axes_a: dict
    axes_b: dict

    @property
    def component_delta_sum_ms(self) -> float:
        return sum(self.component_deltas.values())

    def to_json_dict(self) -> dict:
        return {
            "fingerprint_a": self.fingerprint_a,
            "fingerprint_b": self.fingerprint_b,
            "total_a_ms": self.total_a_ms,
            "total_b_ms": self.total_b_ms,
            "total_delta_ms": self.total_delta_ms,
            "component_deltas": dict(self.component_deltas),
            "decisive": ({"component": self.decisive[0],
                          "delta_ms": self.decisive[1]}
                         if self.decisive else None),
            "axis_changes": {k: dict(v)
                             for k, v in self.axis_changes.items()},
        }

    def render(self) -> str:
        """Human table: per-component attribution + axis changes."""
        lines: list[str] = []
        if self.component_deltas:
            keys = [k for k in COST_COMPONENTS
                    if abs(self.component_deltas.get(k, 0.0)) > 1e-12]
            keys += [k for k in self.component_deltas
                     if k not in keys
                     and abs(self.component_deltas[k]) > 1e-12]
            width = max([len("component")] + [len(k) for k in keys])
            lines.append(f"{'component'.ljust(width)}  delta (b-a) ms")
            lines.append(f"{'-' * width}  --------------")
            for k in keys:
                lines.append(
                    f"{k.ljust(width)}  {self.component_deltas[k]:+.3f}")
            if self.total_delta_ms is not None:
                lines.append(
                    f"{'total'.ljust(width)}  {self.total_delta_ms:+.3f}")
            if self.decisive is not None:
                name, d = self.decisive
                lines.append("")
                lines.append(f"decisive: {name} ({d:+.3f} ms)")
        if self.axis_changes:
            lines.append("")
            lines.append("axis changes:")
            for axis, ch in self.axis_changes.items():
                lines.append(f"  {axis}: {ch['a']!r} -> {ch['b']!r}")
        elif self.axes_a and self.axes_b:
            lines.append("")
            lines.append("axis changes: none (identical decision axes)")
        return "\n".join(lines)


def diff_plans(a, b) -> PlanDiff:
    """Attribute the decision change from plan ``a`` to plan ``b``.

    Accepts live ``RankedPlan``s, serialized plan dicts
    (``dump_ranked_plans`` entries), or ``DecisionRecord``s in any
    combination.  Component deltas are computed through
    ``CostBreakdown.delta`` (b − a), so they sum exactly to the
    breakdown total delta by additivity; when either side lacks a
    breakdown the cost attribution is empty and only axis changes are
    reported."""
    da, db = _plan_dict(a), _plan_dict(b)
    fp_of = fingerprint_plan_dict
    bd_a = (CostBreakdown.from_json_dict(da["breakdown"])
            if da.get("breakdown") else None)
    bd_b = (CostBreakdown.from_json_dict(db["breakdown"])
            if db.get("breakdown") else None)
    component_deltas: dict[str, float] = {}
    decisive = None
    total_a = bd_a.total_ms if bd_a else da.get("cost_ms")
    total_b = bd_b.total_ms if bd_b else db.get("cost_ms")
    if bd_a is not None and bd_b is not None:
        component_deltas = bd_a.delta(bd_b)
        decisive = bd_a.decisive_component(bd_b)
    total_delta = (total_b - total_a
                   if total_a is not None and total_b is not None else None)
    axes_a, axes_b = plan_axes(da), plan_axes(db)
    axis_changes = {
        axis: {"a": axes_a[axis], "b": axes_b[axis]}
        for axis in DIFF_AXES
        if axis in axes_a and axis in axes_b and axes_a[axis] != axes_b[axis]
    }
    return PlanDiff(
        fingerprint_a=fp_of(da), fingerprint_b=fp_of(db),
        total_a_ms=total_a, total_b_ms=total_b, total_delta_ms=total_delta,
        component_deltas=component_deltas, decisive=decisive,
        axis_changes=axis_changes, axes_a=axes_a, axes_b=axes_b)


# ---------------------------------------------------------------------------
# causal chain reconstruction
# ---------------------------------------------------------------------------


def causal_chain(records: Sequence[DecisionRecord],
                 leaf: DecisionRecord | int) -> list[DecisionRecord]:
    """Walk ``parent_seq`` edges from ``leaf`` back to the causal root;
    returns root..leaf order.  A dangling parent reference ends the walk
    (the schema checker flags it); a cycle cannot occur because parents
    always have smaller seqs, but the walk guards anyway."""
    by_seq = {r.seq: r for r in records}
    rec = by_seq.get(leaf) if isinstance(leaf, int) else leaf
    if rec is None:
        return []
    chain = [rec]
    seen = {rec.seq}
    while rec.parent_seq is not None:
        parent = by_seq.get(rec.parent_seq)
        if parent is None or parent.seq in seen:
            break
        chain.append(parent)
        seen.add(parent.seq)
        rec = parent
    chain.reverse()
    return chain


def render_chain(chain: Sequence[DecisionRecord],
                 with_diffs: bool = True) -> str:
    """Render a causal chain root-first, one hop per block, with the
    attributed plan diff at every hop whose adjacent decisions both carry
    a breakdown."""
    if not chain:
        return "no matching decision"
    lines: list[str] = []
    prev: DecisionRecord | None = None
    for depth, rec in enumerate(chain):
        head = f"[seq {rec.seq}] {rec.kind}"
        if rec.cause:
            head += f" (cause: {rec.cause})"
        if rec.tenant:
            head += f" tenant={rec.tenant}"
        if rec.plan_fingerprint:
            head += f" plan={rec.plan_fingerprint}"
        if rec.total_ms is not None:
            head += f" {rec.total_ms:.3f} ms"
        lines.append(("  " * depth) + ("-> " if depth else "") + head)
        # risk posture (uncertainty layer): how this plan was ranked —
        # point (default, unannotated), tail-quantile, or CVaR — and
        # whether it was priced off transferred (unprofiled) profiles
        detail = rec.detail or {}
        ranking = detail.get("ranking")
        transferred = detail.get("transferred_profiles")
        if ranking or transferred:
            bits = []
            if ranking == "quantile":
                bits.append("quantile-ranked "
                            f"(q={detail.get('risk_quantile')})")
            elif ranking == "cvar":
                bits.append(f"CVaR-ranked (alpha={detail.get('cvar_alpha')})")
            elif ranking == "point" and detail.get("risk_requested"):
                bits.append("point-ranked (risk requested; ledger too "
                            "thin to fit)")
            elif ranking:
                bits.append(f"{ranking}-ranked")
            if transferred:
                bits.append("transferred profiles: "
                            + ", ".join(transferred))
            lines.append(("  " * depth) + "   risk: " + "; ".join(bits))
        if rec.margin_ms is not None and rec.runner_up is not None:
            conf = ""
            if rec.confidence:
                worst = max(
                    ((k, v.get("p95_abs_ms")) for k, v in
                     rec.confidence.items()
                     if isinstance(v, dict)
                     and v.get("p95_abs_ms") is not None),
                    key=lambda kv: kv[1], default=None)
                if worst is not None:
                    conf = (f"; p95 {worst[0]} residual alone is "
                            f"{worst[1]:.1f} ms")
            lines.append(
                ("  " * depth) + f"   runner-up "
                f"{rec.runner_up.get('plan_fingerprint', '?')} was "
                f"{rec.margin_ms:.1f} ms away{conf}")
        if rec.trace_id:
            lines.append(("  " * depth) + f"   trace={rec.trace_id}")
        if (with_diffs and prev is not None
                and prev.breakdown and rec.breakdown
                and prev.plan_fingerprint != rec.plan_fingerprint):
            diff = diff_plans(prev, rec)
            for dl in diff.render().splitlines():
                lines.append(("  " * depth) + "   | " + dl)
        prev = rec
    return "\n".join(lines)


def chain_json(chain: Sequence[DecisionRecord]) -> dict:
    """Machine-readable chain (``metis-tpu why --json``): the records
    root..leaf plus the attributed diff at each breakdown-carrying hop."""
    hops: list[dict] = []
    prev: DecisionRecord | None = None
    for rec in chain:
        hop: dict = {"record": rec.to_json_dict()}
        if (prev is not None and prev.breakdown and rec.breakdown
                and prev.plan_fingerprint != rec.plan_fingerprint):
            hop["diff"] = diff_plans(prev, rec).to_json_dict()
        hops.append(hop)
        prev = rec
    return {"depth": len(chain), "hops": hops,
            "root_cause": chain[0].cause or chain[0].kind if chain else None}
