"""Cost-model accuracy ledger: predicted-vs-measured drift tracking.

Metis is, at its core, a cost model — the plan is only as good as the
estimator's fidelity (PAPER.md §0), yet until this module nothing ever
checked a plan's predicted step time against what ``execution/`` measures.
This closes the loop:

- :func:`plan_fingerprint` gives every plan a stable identity computed
  identically from a planner ``RankedPlan`` and an execution
  ``PlanArtifact``, so predictions written at search time join with
  measurements written steps (or days) later.
- :class:`AccuracyLedger` persists both sides as append-only JSONL
  (``prediction`` and ``measurement`` records) and computes the summary
  stats — MAPE, signed error (systematic bias), error percentiles,
  per-plan and per-stage residuals — that ``metis-tpu accuracy`` renders.
- :class:`DriftDetector` turns the rolling error into an alarm with
  hysteresis: one ``drift_alarm`` event per excursion above the band, no
  re-fire until the error drops below the clear threshold — the signal
  :func:`metis_tpu.planner.replan.replan_on_drift` keys on.
- :class:`AccuracyMonitor` is the train-loop composition of all three
  (``execution/train.StepTimer`` feeds it one measured step at a time).

The ledger file is shareable state, not telemetry: committing one per
deployment gives the next planner run (and ``cost/calibration.
fit_ledger_correction``) the residuals to refit against.
"""
from __future__ import annotations

import dataclasses
import hashlib
import json
import math
import time
from collections import deque
from dataclasses import dataclass
from pathlib import Path
from typing import IO, Any, Iterable, Sequence

from metis_tpu.core.events import EventLog, NULL_LOG

# ---------------------------------------------------------------------------
# plan fingerprints
# ---------------------------------------------------------------------------

# Strategy keys with their defaults: both sides of the join (planner
# Strategy dataclasses, artifact dicts that may predate newer axes) expand
# to the same canonical form before hashing.
_STRATEGY_DEFAULTS = {
    "dp": 1, "tp": 1, "sp": False, "cp": 1, "ep": 1, "zero": 0,
    "cp_mode": "ring",
}


def _canonical_strategies(strategies: Iterable) -> list[dict]:
    out = []
    for s in strategies:
        d = dict(s) if isinstance(s, dict) else dataclasses.asdict(s)
        out.append({k: d.get(k, default)
                    for k, default in sorted(_STRATEGY_DEFAULTS.items())})
    return out


def plan_fingerprint(
    *,
    layer_partition: Sequence[int],
    strategies: Iterable,
    gbs: int,
    microbatches: int,
    node_sequence: Sequence[str] = (),
    device_groups: Sequence[int] = (),
    schedule: str = "gpipe",
    virtual_stages: int = 1,
    extra: dict | None = None,
) -> str:
    """Stable 12-hex identity of a plan's execution-relevant shape.

    Hashes the canonical JSON of the fields that determine what actually
    runs; cosmetic fields (cost, rank, search accounting) are excluded so
    the same plan found by two searches — or round-tripped through a
    ``PlanArtifact`` — fingerprints identically.
    """
    canonical = {
        "layer_partition": list(layer_partition),
        "strategies": _canonical_strategies(strategies),
        "gbs": int(gbs),
        "microbatches": int(microbatches),
        "node_sequence": list(node_sequence),
        "device_groups": list(device_groups),
        "schedule": schedule,
        "virtual_stages": int(virtual_stages),
    }
    if extra:
        canonical.update(extra)
    payload = json.dumps(canonical, sort_keys=True, separators=(",", ":"))
    return hashlib.sha1(payload.encode()).hexdigest()[:12]


def fingerprint_ranked_plan(ranked) -> str:
    """Fingerprint of a ``planner.api`` RankedPlan (hetero search output)."""
    inter, intra = ranked.inter, ranked.intra
    return plan_fingerprint(
        layer_partition=intra.layer_partition,
        strategies=intra.strategies,
        gbs=inter.gbs,
        microbatches=inter.batches,
        node_sequence=inter.node_sequence,
        device_groups=inter.device_groups,
        schedule=intra.schedule,
        virtual_stages=intra.virtual_stages,
    )


def fingerprint_uniform_plan(plan) -> str:
    """Fingerprint of a ``core.types`` UniformPlan — matches
    ``fingerprint_artifact(PlanArtifact.from_uniform_plan(plan))``."""
    return plan_fingerprint(
        layer_partition=(),
        strategies=({"dp": plan.dp, "tp": plan.tp},),
        gbs=plan.gbs,
        microbatches=plan.num_microbatches,
        extra={"pp": plan.pp},
    )


def fingerprint_artifact(art) -> str:
    """Fingerprint of an ``execution.mesh`` PlanArtifact.

    Matches ``fingerprint_ranked_plan`` for artifacts captured with
    ``from_ranked_plan`` and ``fingerprint_uniform_plan`` for
    ``from_uniform_plan`` ones (whose pp lives only in the mesh shape —
    hetero artifacts carry it in ``device_groups`` instead).
    """
    extra = None
    if not art.device_groups and not art.layer_partition and art.mesh_shape:
        axes = tuple(art.mesh_axes)
        if "pp" in axes:
            extra = {"pp": int(art.mesh_shape[axes.index("pp")])}
    return plan_fingerprint(
        layer_partition=art.layer_partition,
        strategies=art.strategies,
        gbs=art.gbs,
        microbatches=art.microbatches,
        node_sequence=art.node_sequence,
        device_groups=art.device_groups,
        schedule=art.schedule,
        virtual_stages=art.virtual_stages,
        extra=extra,
    )


# ---------------------------------------------------------------------------
# query fingerprints (serve-layer cache keys)
# ---------------------------------------------------------------------------

# SearchConfig fields that cannot change the ranked result, only how fast
# (or how verbosely) it is computed: the parallel worker count and the
# heartbeat cadence.  Byte-identity across these is the contract the
# serial/parallel parity tests already pin, so two queries differing only
# here may share a cache entry.  Every OTHER field — including the cost-
# model toggles ``use_overlap_model``/``use_batch_eval`` — is hashed, so a
# config flip can never return a stale cached plan.
_RESULT_NEUTRAL_CONFIG_FIELDS = frozenset({"workers", "progress_every"})


def calibration_fingerprint(calibration) -> str | None:
    """12-hex identity of a ``cost.calibration.CollectiveCalibration``'s
    pricing-relevant content (fitted curves, not raw samples); None for
    None.  Two calibrations that price collectives identically — same
    platform/device/group-size fits — fingerprint identically."""
    if calibration is None:
        return None
    if hasattr(calibration, "to_json_dict"):
        d = dict(calibration.to_json_dict())
        d.pop("samples", None)
    else:  # already a plain dict (e.g. loaded JSON)
        d = {k: v for k, v in dict(calibration).items() if k != "samples"}
    payload = json.dumps(d, sort_keys=True, separators=(",", ":"),
                         default=str)
    return hashlib.sha1(payload.encode()).hexdigest()[:12]


def query_fingerprint(model, cluster, config, *, calibration=None,
                      workload=None, extra: dict | None = None) -> str:
    """Stable 12-hex identity of a plan *query*: model × cluster × gbs ×
    every cost-relevant ``SearchConfig`` field × calibration identity ×
    workload kind.

    This is the serve-layer cache key (``serve/cache.PlanCache``), distinct
    from :func:`plan_fingerprint` on purpose: a plan fingerprint identifies
    a search *result*'s execution shape (it must stay stable across cost-
    model changes so predictions join with measurements), while a query
    fingerprint identifies a search *input* — flip any knob that could
    change the ranking and the key must change.  sha1 over canonical JSON,
    not ``hash()``, so the key is stable across processes and restarts.

    ``workload`` (an ``inference.workload.InferenceWorkload``, or None for
    training) is hashed structurally: a training query hashes the literal
    string "training" while an inference query hashes its kind tag plus
    every SLO/traffic field, so a cached training plan can never alias an
    inference query for the same model/cluster — nor can two inference
    queries differing in any SLO field alias each other.
    """
    cfg = dataclasses.asdict(config)
    for name in _RESULT_NEUTRAL_CONFIG_FIELDS:
        cfg.pop(name, None)
    canonical = {
        "model": dataclasses.asdict(model),
        "cluster": {
            "nodes": [[n.device_type, int(n.num_devices)]
                      for n in cluster.nodes],
            "devices": {
                name: dataclasses.asdict(dev)
                for name, dev in sorted(cluster.devices.items())
            },
        },
        "config": cfg,
        "calibration": calibration_fingerprint(calibration),
        "workload": ("training" if workload is None
                     else {"kind": "inference",
                           **dataclasses.asdict(workload)}),
    }
    if extra:
        canonical.update(extra)
    payload = json.dumps(canonical, sort_keys=True, separators=(",", ":"),
                         default=str)
    return hashlib.sha1(payload.encode()).hexdigest()[:12]


# ---------------------------------------------------------------------------
# ledger records
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class AccuracySample:
    """One measured step joined against its plan's prediction (if any).

    ``components`` holds measured per-``CostBreakdown``-component times
    when the measurement was component-resolved (empty otherwise — the
    residual decomposition then falls back to proportional attribution);
    ``device_type`` labels which hardware measured the step, so residual
    distributions can be grouped per device type."""

    fingerprint: str
    measured_ms: float
    predicted_ms: float | None = None
    step: int | None = None
    source: str = "train"
    stage_ms: tuple[float, ...] = ()
    components: dict[str, float] = dataclasses.field(default_factory=dict)
    device_type: str = ""

    @property
    def error_pct(self) -> float | None:
        """Signed (predicted - measured) / measured, percent; None when the
        plan was never predicted (or measured zero)."""
        if self.predicted_ms is None or self.measured_ms <= 0:
            return None
        return (self.predicted_ms - self.measured_ms) / self.measured_ms * 100

    @property
    def abs_error_pct(self) -> float | None:
        e = self.error_pct
        return None if e is None else abs(e)


@dataclass(frozen=True)
class LedgerSummary:
    """Aggregate accuracy stats over a ledger (``metis-tpu accuracy``)."""

    n_samples: int
    n_matched: int            # samples with a joined prediction
    n_plans: int              # distinct fingerprints measured
    mape_pct: float | None
    signed_error_pct: float | None   # mean signed error — systematic bias
    p50_abs_pct: float | None
    p90_abs_pct: float | None
    max_abs_pct: float | None
    worst: tuple[dict, ...] = ()          # worst samples, most wrong first
    by_plan: dict[str, dict] = dataclasses.field(default_factory=dict)
    stage_residuals: tuple[dict, ...] = ()  # per stage idx, where measurable

    def to_json_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["worst"] = list(self.worst)
        d["stage_residuals"] = list(self.stage_residuals)
        return d


def _percentile(sorted_vals: list[float], q: float) -> float:
    if not sorted_vals:
        raise ValueError("empty")
    idx = min(int(round(q * (len(sorted_vals) - 1))), len(sorted_vals) - 1)
    return sorted_vals[idx]


class AccuracyLedger:
    """Append-only JSONL of predicted-vs-measured records, keyed by plan
    fingerprint.

    Two record kinds share the file: ``{"kind": "prediction", fingerprint,
    predicted_ms, components, stage_ms, ...}`` written once per planned
    run, and ``{"kind": "measurement", fingerprint, measured_ms, step,
    source, stage_ms}`` written per measured step (train) or per validated
    plan (validate).  Opening an existing path loads both sides and re-joins
    them, so the file round-trips; ``AccuracyLedger(None)`` is an in-memory
    ledger (nothing persisted).

    Loading is fault-hardened the same way ``serve.persist.Oplog`` is:
    a torn trailing line (crash mid-append), a record with NaN/inf
    times, or a measurement missing its value is SKIPPED and counted
    (``n_skipped``; one ``ledger_skip`` event with the per-reason
    tally) instead of crashing the open or poisoning residual fits.
    """

    def __init__(self, path: str | Path | None = None,
                 events: EventLog = NULL_LOG):
        self.path = Path(path) if path is not None else None
        self.events = events
        self._fh: IO[str] | None = None
        self.predictions: dict[str, dict] = {}
        self.samples: list[AccuracySample] = []
        self.n_skipped = 0
        if self.path is not None and self.path.exists():
            self._load()

    @staticmethod
    def _finite(v) -> bool:
        return (isinstance(v, (int, float))
                and math.isfinite(v))

    def _load(self) -> None:
        skipped: dict[str, int] = {}
        for line in self.path.read_text().splitlines():
            if not line.strip():
                continue
            reason = None
            try:
                rec = json.loads(line)
                kind = rec.get("kind")
                if kind == "prediction":
                    fp = rec["fingerprint"]
                    if not self._finite(rec.get("predicted_ms")):
                        reason = "non_finite"
                    else:
                        self.predictions[fp] = rec
                elif kind == "measurement":
                    rec["fingerprint"]
                    m = rec.get("measured_ms")
                    if m is None:
                        # predicted-only / valueless measurement row
                        reason = "missing_measurement"
                    elif not self._finite(m):
                        reason = "non_finite"
                    else:
                        self.samples.append(self._join(rec))
            except json.JSONDecodeError:
                reason = "torn_line"
            except (KeyError, TypeError, ValueError):
                reason = "bad_record"
            if reason is not None:
                skipped[reason] = skipped.get(reason, 0) + 1
        if skipped:
            self.n_skipped = sum(skipped.values())
            self.events.emit("ledger_skip", n_skipped=self.n_skipped,
                             reasons=dict(sorted(skipped.items())))

    def _join(self, rec: dict) -> AccuracySample:
        pred = self.predictions.get(rec["fingerprint"])
        return AccuracySample(
            fingerprint=rec["fingerprint"],
            measured_ms=rec["measured_ms"],
            predicted_ms=pred["predicted_ms"] if pred else None,
            step=rec.get("step"),
            source=rec.get("source", "train"),
            stage_ms=tuple(rec.get("stage_ms", ())),
            components=dict(rec.get("components") or {}),
            device_type=rec.get("device_type", ""),
        )

    def _append(self, rec: dict) -> None:
        if self.path is None:
            return
        if self._fh is None:
            self._fh = open(self.path, "a", buffering=1)
        self._fh.write(json.dumps(rec, default=str) + "\n")

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None

    def __enter__(self) -> "AccuracyLedger":
        return self

    def __exit__(self, *exc: object) -> bool:
        self.close()
        return False

    # -- writes ------------------------------------------------------------
    def record_prediction(
        self,
        fingerprint: str,
        predicted_ms: float,
        components: dict[str, float] | None = None,
        stage_ms: Sequence[float] = (),
        **meta: Any,
    ) -> dict:
        rec = {
            "kind": "prediction", "ts": time.time(),
            "fingerprint": fingerprint, "predicted_ms": predicted_ms,
            "components": dict(components or {}),
            "stage_ms": list(stage_ms), **meta,
        }
        self.predictions[fingerprint] = rec
        self._append(rec)
        return rec

    def record_measurement(
        self,
        fingerprint: str,
        measured_ms: float,
        step: int | None = None,
        stage_ms: Sequence[float] = (),
        source: str = "train",
        **extra: Any,
    ) -> AccuracySample:
        rec = {
            "kind": "measurement", "ts": time.time(),
            "fingerprint": fingerprint, "measured_ms": measured_ms,
            "step": step, "source": source, "stage_ms": list(stage_ms),
            **extra,
        }
        self._append(rec)
        sample = self._join(rec)
        self.samples.append(sample)
        return sample

    # -- stats -------------------------------------------------------------
    def summary(self, fingerprint: str | None = None,
                worst_k: int = 5) -> LedgerSummary:
        samples = [s for s in self.samples
                   if fingerprint is None or s.fingerprint == fingerprint]
        matched = [s for s in samples if s.error_pct is not None]
        abs_errs = sorted(s.abs_error_pct for s in matched)
        by_plan: dict[str, dict] = {}
        for s in samples:
            d = by_plan.setdefault(s.fingerprint, {
                "n": 0, "n_matched": 0, "abs_errs": [], "signed": [],
                "predicted_ms": (self.predictions.get(s.fingerprint) or {})
                .get("predicted_ms"),
            })
            d["n"] += 1
            if s.error_pct is not None:
                d["n_matched"] += 1
                d["abs_errs"].append(s.abs_error_pct)
                d["signed"].append(s.error_pct)
        for fp, d in by_plan.items():
            errs, signed = d.pop("abs_errs"), d.pop("signed")
            d["mape_pct"] = (round(sum(errs) / len(errs), 3)
                             if errs else None)
            d["signed_error_pct"] = (round(sum(signed) / len(signed), 3)
                                     if signed else None)
        worst = tuple(
            {"fingerprint": s.fingerprint, "step": s.step,
             "source": s.source, "predicted_ms": s.predicted_ms,
             "measured_ms": s.measured_ms,
             "error_pct": round(s.error_pct, 3)}
            for s in sorted(matched, key=lambda s: -s.abs_error_pct)[:worst_k]
        )
        return LedgerSummary(
            n_samples=len(samples),
            n_matched=len(matched),
            n_plans=len(by_plan),
            mape_pct=(round(sum(abs_errs) / len(abs_errs), 3)
                      if abs_errs else None),
            signed_error_pct=(round(
                sum(s.error_pct for s in matched) / len(matched), 3)
                if matched else None),
            p50_abs_pct=(round(_percentile(abs_errs, 0.5), 3)
                         if abs_errs else None),
            p90_abs_pct=(round(_percentile(abs_errs, 0.9), 3)
                         if abs_errs else None),
            max_abs_pct=round(abs_errs[-1], 3) if abs_errs else None,
            worst=worst,
            by_plan=by_plan,
            stage_residuals=self._stage_residuals(samples),
        )

    def _stage_residuals(
            self, samples: Sequence[AccuracySample]) -> tuple[dict, ...]:
        """Per-stage signed residuals, for samples whose measurement AND
        prediction both carry per-stage times (the multi-controller /
        per-stage executors); empty when neither side is stage-resolved."""
        acc: dict[int, list[float]] = {}
        for s in samples:
            pred = self.predictions.get(s.fingerprint)
            if not s.stage_ms or not pred or not pred.get("stage_ms"):
                continue
            for i, (p, m) in enumerate(zip(pred["stage_ms"], s.stage_ms)):
                if m > 0:
                    acc.setdefault(i, []).append((p - m) / m * 100)
        return tuple(
            {"stage": i, "n": len(errs),
             "signed_error_pct": round(sum(errs) / len(errs), 3),
             "mape_pct": round(sum(abs(e) for e in errs) / len(errs), 3)}
            for i, errs in sorted(acc.items())
        )

    def component_residuals(
            self, fingerprint: str | None = None,
            by_device: bool = False) -> dict[str, dict]:
        """Per-``CostBreakdown``-component residual distributions in ms.

        For every matched sample whose prediction carries ``components``:
        a component-resolved measurement (``record_measurement(...,
        components={...})``) yields the exact residual ``predicted_c -
        measured_c`` per component both sides carry (a component absent
        from the measurement is skipped for that sample — e.g.
        ``migration`` appears only on migrated plans); an unresolved
        measurement attributes the total residual proportionally to the
        predicted component shares, so the per-component residuals still
        sum to the total residual by additivity.

        Returns ``{component: {n, mean_ms, var_ms, p50_abs_ms,
        p95_abs_ms}}`` — or, with ``by_device=True``, the same keyed by
        device type first (samples without a ``device_type`` group under
        ``""``).  Empty dict when nothing is component-attributable.
        This is the model-confidence context ``DecisionRecord.confidence``
        carries for the ranking margin (``metis-tpu accuracy
        --components`` renders it)."""
        acc: dict[tuple[str, str], list[float]] = {}
        for s in self.samples:
            if fingerprint is not None and s.fingerprint != fingerprint:
                continue
            pred = self.predictions.get(s.fingerprint)
            if not pred or not pred.get("components"):
                continue
            pcomps = pred["components"]
            ptotal = pred.get("predicted_ms") or sum(pcomps.values())
            dev = s.device_type or pred.get("device_type", "") or ""
            for comp, pv in pcomps.items():
                if s.components:
                    if comp not in s.components:
                        continue
                    r = pv - s.components[comp]
                elif ptotal > 0 and s.measured_ms > 0:
                    r = pv / ptotal * (ptotal - s.measured_ms)
                else:
                    continue
                acc.setdefault((dev, comp), []).append(r)

        def stats(residuals: list[float]) -> dict:
            n = len(residuals)
            mean = sum(residuals) / n
            var = max(sum(r * r for r in residuals) / n - mean * mean, 0.0)
            abs_sorted = sorted(abs(r) for r in residuals)
            return {"n": n, "mean_ms": round(mean, 4),
                    "var_ms": round(var, 4),
                    "p50_abs_ms": round(_percentile(abs_sorted, 0.5), 4),
                    "p95_abs_ms": round(_percentile(abs_sorted, 0.95), 4)}

        if by_device:
            out: dict[str, dict] = {}
            for (dev, comp), residuals in sorted(acc.items()):
                out.setdefault(dev, {})[comp] = stats(residuals)
            return out
        merged: dict[str, list[float]] = {}
        for (_dev, comp), residuals in acc.items():
            merged.setdefault(comp, []).extend(residuals)
        return {comp: stats(residuals)
                for comp, residuals in sorted(merged.items())}


# ---------------------------------------------------------------------------
# drift detection
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class DriftStatus:
    """Snapshot of a DriftDetector — the replan-trigger contract
    (``planner.replan.replan_on_drift`` keys on ``in_drift``)."""

    in_drift: bool
    rolling_mape_pct: float | None
    n: int
    alarms: int
    band_pct: float


class DriftDetector:
    """Rolling-MAPE drift alarm with hysteresis.

    ``observe(error_pct)`` per accuracy sample; when the rolling window's
    MAPE exceeds ``band_pct`` (with at least ``min_samples`` observed) the
    detector enters drift, emits exactly ONE ``drift_alarm`` event, and
    stays armed-off until the rolling MAPE falls below ``clear_pct``
    (default band/2) — so a run hovering at the band cannot spam alarms.
    """

    def __init__(self, band_pct: float = 20.0, min_samples: int = 5,
                 window: int = 32, clear_pct: float | None = None,
                 events: EventLog = NULL_LOG,
                 fingerprint: str | None = None):
        self.band_pct = band_pct
        self.min_samples = max(int(min_samples), 1)
        self.clear_pct = band_pct / 2 if clear_pct is None else clear_pct
        self.events = events
        self.fingerprint = fingerprint
        self._errors: deque[float] = deque(maxlen=max(int(window), 1))
        self.in_drift = False
        self.alarms = 0

    @property
    def n(self) -> int:
        return len(self._errors)

    @property
    def rolling_mape_pct(self) -> float | None:
        if not self._errors:
            return None
        return sum(self._errors) / len(self._errors)

    def observe(self, error_pct: float) -> bool:
        """Feed one signed error; True exactly when the alarm fires."""
        self._errors.append(abs(error_pct))
        mape = self.rolling_mape_pct
        if self.in_drift:
            if mape < self.clear_pct:
                self.in_drift = False  # re-armed: a new excursion can fire
            return False
        if self.n >= self.min_samples and mape > self.band_pct:
            self.in_drift = True
            self.alarms += 1
            fields = {"mape_pct": round(mape, 3), "band_pct": self.band_pct,
                      "n": self.n}
            if self.fingerprint is not None:
                fields["fingerprint"] = self.fingerprint
            self.events.emit("drift_alarm", **fields)
            return True
        return False

    def status(self) -> DriftStatus:
        return DriftStatus(
            in_drift=self.in_drift,
            rolling_mape_pct=self.rolling_mape_pct,
            n=self.n,
            alarms=self.alarms,
            band_pct=self.band_pct,
        )


class AccuracyMonitor:
    """Train-loop composition: ledger + events + drift detector.

    One ``observe(measured_ms)`` per measured step writes the measurement
    record, emits an ``accuracy_sample`` event (when the plan has a
    prediction to compare against), and feeds the drift detector — which
    emits at most one ``drift_alarm`` per excursion.  ``skip_steps``
    swallows the first N steps (compilation dominates them; charging the
    cost model for XLA compile time would be a false alarm generator).
    """

    def __init__(self, ledger: AccuracyLedger, fingerprint: str,
                 events: EventLog = NULL_LOG, band_pct: float = 20.0,
                 min_samples: int = 5, skip_steps: int = 1,
                 source: str = "train"):
        self.ledger = ledger
        self.fingerprint = fingerprint
        self.events = events
        self.source = source
        self.skip_steps = skip_steps
        self._skipped = 0
        self.detector = DriftDetector(
            band_pct=band_pct, min_samples=min_samples, events=events,
            fingerprint=fingerprint)

    def observe(self, measured_ms: float, step: int | None = None,
                stage_ms: Sequence[float] = ()) -> AccuracySample | None:
        if self._skipped < self.skip_steps:
            self._skipped += 1
            return None
        sample = self.ledger.record_measurement(
            self.fingerprint, measured_ms, step=step, stage_ms=stage_ms,
            source=self.source)
        err = sample.error_pct
        if err is not None:
            self.events.emit(
                "accuracy_sample", fingerprint=self.fingerprint,
                predicted_ms=sample.predicted_ms, measured_ms=measured_ms,
                error_pct=round(err, 3), step=step, source=self.source)
            self.detector.observe(err)
        return sample

    def status(self) -> DriftStatus:
        return self.detector.status()
