from metis_tpu.obs.ledger import (
    AccuracyLedger,
    AccuracyMonitor,
    AccuracySample,
    DriftDetector,
    DriftStatus,
    LedgerSummary,
    fingerprint_artifact,
    fingerprint_ranked_plan,
    fingerprint_uniform_plan,
    plan_fingerprint,
)

__all__ = [
    "AccuracyLedger",
    "AccuracyMonitor",
    "AccuracySample",
    "DriftDetector",
    "DriftStatus",
    "LedgerSummary",
    "fingerprint_artifact",
    "fingerprint_ranked_plan",
    "fingerprint_uniform_plan",
    "plan_fingerprint",
]
