"""Dependency-free metrics plane: counters, gauges, histograms, rates.

The flight recorder (:mod:`metis_tpu.core.trace`) answers *what happened
on one run* — spans, heartbeats, counter totals drained to a JSONL file.
This module answers *how is the system doing right now*: latency
distributions, ratios, and rates a long-lived daemon exposes on
``GET /metrics`` in Prometheus text exposition format, stdlib only.

Four instrument kinds, all thread-safe and all registered in a
:class:`MetricsRegistry`:

- :class:`Counter` — monotonic, float-valued (device-hours accumulate in
  fractions).
- :class:`Gauge` — set/inc/dec a point-in-time value.
- :class:`Histogram` — log-bucketed streaming distribution.  Buckets are
  geometric (``_BUCKET_FACTOR`` per step, ~12%/bucket), so
  :meth:`Histogram.quantile` is exact to within one bucket's relative
  width at any scale from microseconds to hours, with O(buckets) memory
  regardless of sample count.  Mergeable across processes like
  ``core.trace.Counters.merge`` — the parallel-search workers' dict
  round-trip (:meth:`Histogram.to_dict` / :meth:`Histogram.merge_dict`)
  is associative and commutative, so shard accounting folds in any
  order.
- :class:`RateMeter` — rolling-window event rate (the dashboard's qps),
  rendered as a gauge.

``render_prometheus`` / ``parse_exposition`` are inverse enough that the
``metis-tpu top`` dashboard and ``tools/check_metrics_names.py`` both
consume the daemon's own scrape output rather than reaching into
process state.  ``METRIC_CATALOG`` is the documented contract: every
metric any subsystem exports, checked bidirectionally against the
README "Metrics" table by tools/check_metrics_names.py.
"""
from __future__ import annotations

import bisect
import math
import re
import threading
import time
from typing import Any, Iterable

# ---------------------------------------------------------------------------
# log-spaced default buckets: 20 per decade over 1e-6 .. 1e9 (any latency
# from nanoseconds-in-ms-units up to days-in-seconds lands in-range), so a
# quantile estimate is within one bucket = within ~12% relative error
# ---------------------------------------------------------------------------

_BUCKETS_PER_DECADE = 20
_BUCKET_FACTOR = 10.0 ** (1.0 / _BUCKETS_PER_DECADE)
_BUCKET_LO_EXP = -6
_BUCKET_HI_EXP = 9


def _default_bounds() -> tuple[float, ...]:
    n = (_BUCKET_HI_EXP - _BUCKET_LO_EXP) * _BUCKETS_PER_DECADE + 1
    return tuple(10.0 ** (_BUCKET_LO_EXP + i / _BUCKETS_PER_DECADE)
                 for i in range(n))


DEFAULT_BOUNDS = _default_bounds()


class Counter:
    """Monotonic counter.  ``inc`` with a negative amount raises — the
    monotonicity is what lets scrape deltas be trusted as rates."""

    __slots__ = ("_value", "_lock")

    def __init__(self) -> None:
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError(f"counter increment must be >= 0, got {n}")
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge:
    """Point-in-time value: set / inc / dec."""

    __slots__ = ("_value", "_lock")

    def __init__(self) -> None:
        self._value = 0.0
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    def dec(self, n: float = 1.0) -> None:
        with self._lock:
            self._value -= n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Histogram:
    """Log-bucketed streaming histogram.

    ``observe(v)`` is a bisect + two adds under a lock — cheap enough for
    per-request recording on the serve daemon's cached-hit path.  Values
    at or below the smallest bound land in bucket 0; values above the
    largest land in the overflow (``+Inf``) bucket.  Exact ``count``,
    ``sum``, ``min``, ``max`` ride alongside the buckets, so quantile
    estimates can be clamped to the observed range (a constant sample's
    p50 is exact, not a bucket edge)."""

    __slots__ = ("bounds", "_counts", "count", "sum", "min", "max", "_lock")

    def __init__(self, bounds: Iterable[float] | None = None):
        self.bounds: tuple[float, ...] = (tuple(bounds) if bounds is not None
                                          else DEFAULT_BOUNDS)
        if list(self.bounds) != sorted(self.bounds) or not self.bounds:
            raise ValueError("histogram bounds must be sorted and non-empty")
        self._counts = [0] * (len(self.bounds) + 1)  # last = overflow/+Inf
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        v = float(value)
        i = bisect.bisect_left(self.bounds, v)
        with self._lock:
            self._counts[i] += 1
            self.count += 1
            self.sum += v
            if v < self.min:
                self.min = v
            if v > self.max:
                self.max = v

    # -- quantiles ----------------------------------------------------------
    def quantile(self, q: float) -> float | None:
        """Nearest-rank quantile estimate, exact to within one bucket's
        relative width (``numpy.quantile(..., method="inverted_cdf")`` is
        the test oracle).  None on an empty histogram."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile must be in [0, 1], got {q}")
        with self._lock:
            n = self.count
            if n == 0:
                return None
            target = max(1, math.ceil(q * n))
            cum = 0
            idx = len(self._counts) - 1
            for i, c in enumerate(self._counts):
                cum += c
                if cum >= target:
                    idx = i
                    break
            lo = self.bounds[idx - 1] if idx > 0 else self.min
            hi = self.bounds[idx] if idx < len(self.bounds) else self.max
            # geometric midpoint suits log buckets; clamp to the observed
            # range so degenerate samples stay exact
            if lo > 0 and hi > 0 and math.isfinite(lo) and math.isfinite(hi):
                est = math.sqrt(lo * hi)
            else:
                est = hi if math.isfinite(hi) else lo
            return min(max(est, self.min), self.max)

    # -- merging (Counters.merge-style, associative + commutative) ----------
    def merge(self, other: "Histogram") -> None:
        """Fold another histogram (same bounds) into this one."""
        if other.bounds != self.bounds:
            raise ValueError("cannot merge histograms with different bounds")
        with other._lock:
            counts = list(other._counts)
            count, total = other.count, other.sum
            mn, mx = other.min, other.max
        self._merge_parts(counts, count, total, mn, mx)

    def merge_dict(self, d: dict) -> None:
        """Fold a :meth:`to_dict` snapshot (how a worker process ships its
        shard's distribution home, like ``Counters.merge``)."""
        counts = [0] * (len(self.bounds) + 1)
        for i, c in d.get("counts", {}).items():
            counts[int(i)] = int(c)
        self._merge_parts(counts, int(d.get("count", 0)),
                          float(d.get("sum", 0.0)),
                          float(d.get("min", math.inf)),
                          float(d.get("max", -math.inf)))

    def _merge_parts(self, counts, count, total, mn, mx) -> None:
        with self._lock:
            for i, c in enumerate(counts):
                self._counts[i] += c
            self.count += count
            self.sum += total
            if mn < self.min:
                self.min = mn
            if mx > self.max:
                self.max = mx

    def to_dict(self) -> dict:
        """JSON-safe sparse snapshot (bucket index -> count)."""
        with self._lock:
            return {
                "counts": {str(i): c for i, c in enumerate(self._counts)
                           if c},
                "count": self.count,
                "sum": self.sum,
                "min": self.min if self.count else None,
                "max": self.max if self.count else None,
            }

    def cumulative_buckets(self) -> list[tuple[float, int]]:
        """``(le_bound, cumulative_count)`` pairs for exposition: only
        buckets where the cumulative count changes, plus the ``+Inf``
        terminator (valid Prometheus histogram — every rendered bucket is
        cumulative and ``+Inf`` equals ``count``)."""
        out: list[tuple[float, int]] = []
        with self._lock:
            cum = 0
            for i, c in enumerate(self._counts[:-1]):
                cum += c
                if c:
                    out.append((self.bounds[i], cum))
            out.append((math.inf, self.count))
        return out


class RateMeter:
    """Rolling-window event rate.

    ``mark(n)`` buckets events into fixed time slots; :meth:`rate` sums
    the slots still inside the window and divides by the window actually
    covered (so a meter younger than its window reports an honest rate
    instead of diluting by unlived time)."""

    __slots__ = ("window_s", "_slot_s", "_counts", "_epochs", "_t0",
                 "total", "_lock")

    def __init__(self, window_s: float = 60.0, slots: int = 15):
        if window_s <= 0 or slots < 1:
            raise ValueError("window_s must be > 0 and slots >= 1")
        self.window_s = float(window_s)
        self._slot_s = self.window_s / slots
        self._counts = [0.0] * slots
        self._epochs = [-1] * slots
        self._t0 = time.monotonic()
        self.total = 0.0
        self._lock = threading.Lock()

    def mark(self, n: float = 1.0) -> None:
        now = time.monotonic()
        epoch = int(now / self._slot_s)
        i = epoch % len(self._counts)
        with self._lock:
            if self._epochs[i] != epoch:
                self._epochs[i] = epoch
                self._counts[i] = 0.0
            self._counts[i] += n
            self.total += n

    def rate(self) -> float:
        now = time.monotonic()
        epoch = int(now / self._slot_s)
        horizon = epoch - len(self._counts) + 1
        with self._lock:
            live = sum(c for c, e in zip(self._counts, self._epochs)
                       if e >= horizon)
        covered = min(self.window_s, max(now - self._t0, self._slot_s))
        return live / covered


class _NullInstrument:
    """Shared no-op standing in for every instrument kind on a disabled
    registry, so instrumented call sites never guard."""

    __slots__ = ()

    def inc(self, n: float = 1.0) -> None:
        pass

    def dec(self, n: float = 1.0) -> None:
        pass

    def set(self, v: float) -> None:
        pass

    def observe(self, value: float) -> None:
        pass

    def mark(self, n: float = 1.0) -> None:
        pass

    def quantile(self, q: float) -> None:
        return None

    def rate(self) -> float:
        return 0.0

    @property
    def value(self) -> float:
        return 0.0


NULL_INSTRUMENT = _NullInstrument()


# ---------------------------------------------------------------------------
# the documented contract: every metric the codebase exports.
# tools/check_metrics_names.py enforces README table == this catalog and
# scraped /metrics names ⊆ this catalog.
# ---------------------------------------------------------------------------

METRIC_CATALOG: dict[str, tuple[str, str, tuple[str, ...]]] = {
    # name -> (type, help, label names)
    "metis_serve_requests_total": (
        "counter", "HTTP requests completed, per endpoint", ("endpoint",)),
    "metis_serve_errors_total": (
        "counter", "HTTP responses with status >= 400, per endpoint",
        ("endpoint",)),
    "metis_serve_request_latency_ms": (
        "histogram", "wall time per HTTP request, per endpoint",
        ("endpoint",)),
    "metis_serve_qps": (
        "gauge", "rolling 60s request rate across all endpoints", ()),
    "metis_serve_inflight_requests": (
        "gauge", "HTTP requests currently executing", ()),
    "metis_serve_queue_depth": (
        "gauge", "threads holding or waiting on the search lock", ()),
    "metis_serve_coalesced_waits_total": (
        "counter", "plan queries that waited behind a single-flight "
                   "leader instead of searching", ()),
    "metis_serve_coalesced_wait_ms": (
        "histogram", "time followers spent waiting for the single-flight "
                     "leader's search", ()),
    "metis_serve_cache_hits_total": (
        "counter", "plan cache lookups answered from cache", ()),
    "metis_serve_cache_misses_total": (
        "counter", "plan cache lookups that missed", ()),
    "metis_serve_cache_hit_ratio": (
        "gauge", "hits / (hits + misses) over daemon lifetime", ()),
    "metis_serve_cache_entries": (
        "gauge", "plan cache occupancy (entries)", ()),
    "metis_serve_cache_capacity": (
        "gauge", "plan cache capacity (entries)", ()),
    "metis_serve_cache_evictions_total": (
        "counter", "plan cache LRU evictions", ()),
    "metis_serve_cache_invalidations_total": (
        "counter", "plan cache entries dropped by drift alarms, deltas, "
                   "or explicit invalidation", ()),
    "metis_serve_cache_shard_lock_wait_ms": (
        "histogram", "time blocked acquiring one plan-cache shard lock "
                     "(uncontended acquires are not timed)", ("shard",)),
    "metis_serve_keepalive_reuse_total": (
        "counter", "HTTP requests served on an already-open keep-alive "
                   "connection (2nd and later request per connection)",
        ()),
    "metis_serve_pool_threads": (
        "gauge", "handler worker-pool size", ()),
    "metis_serve_pool_busy_threads": (
        "gauge", "handler pool threads currently serving a connection",
        ()),
    "metis_serve_pool_backlog": (
        "gauge", "accepted connections queued for a free pool thread",
        ()),
    "metis_serve_pool_queue_wait_ms": (
        "histogram", "time an accepted connection waited in the backlog "
                     "before a pool thread picked it up", ()),
    "metis_serve_overload_total": (
        "counter", "connections shed with 503 + Retry-After because the "
                   "worker pool and its backlog were both full", ()),
    "metis_search_pool_workers": (
        "gauge", "resident cold-search worker processes (0 = pool off or "
                 "closed)", ()),
    "metis_search_pool_inflight": (
        "gauge", "searches currently executing on the worker pool", ()),
    "metis_serve_warm_states": (
        "gauge", "retained warm search states", ()),
    "metis_serve_notes_backlog": (
        "gauge", "notifications held for long-poll subscribers", ()),
    "metis_serve_uptime_seconds": (
        "gauge", "seconds since the daemon booted", ()),
    "metis_serve_tenants": (
        "gauge", "registered tenants", ()),
    "metis_snapshot_age_seconds": (
        "gauge", "seconds since the last durable state snapshot was "
                 "written (staleness = the periodic snapshotter is "
                 "failing)", ()),
    "metis_snapshot_size_bytes": (
        "gauge", "size of the last written state snapshot", ()),
    "metis_oplog_appends_total": (
        "counter", "state-mutation ops appended to the oplog", ()),
    "metis_standby_oplog_lag": (
        "gauge", "ops the standby still trails the primary by "
                 "(0 once caught up or promoted)", ()),
    "metis_search_duration_seconds": (
        "histogram", "end-to-end search time per cold plan query",
        ("kind",)),
    "metis_search_phase_seconds": (
        "histogram", "serial hetero search phase durations "
                     "(setup/enumeration/intra_stage/costing/ranking)",
        ("phase",)),
    "metis_fleet_utilization_frac": (
        "gauge", "devices allocated / fleet devices, last fleet plan", ()),
    "metis_fleet_objective": (
        "gauge", "priority-weighted utility objective of the last fleet "
                 "plan", ()),
    "metis_fleet_tenant_utilization_frac": (
        "gauge", "per-tenant utility vs full-fleet baseline, last fleet "
                 "plan", ("tenant",)),
    "metis_fleet_tenant_devices": (
        "gauge", "devices carved to the tenant in the last fleet plan",
        ("tenant",)),
    "metis_fleet_preemptions_total": (
        "counter", "capacity-change shrinks of a tenant's carve",
        ("tenant",)),
    "metis_replay_slo_attainment": (
        "gauge", "request-weighted SLO attainment of the running traffic "
                 "replay", ("policy",)),
    "metis_replay_device_hours_total": (
        "counter", "provisioned device-hours accumulated by the traffic "
                   "replay", ("policy",)),
    "metis_replay_ticks_total": (
        "counter", "traffic-replay ticks simulated", ("policy",)),
    "metis_plan_confidence_p": (
        "gauge", "confidence p of the last exact-backend certificate "
                 "(probability the certified plan is truly optimal "
                 "under the ledger-fit residual model)", ()),
    "metis_transfer_scale_factor": (
        "gauge", "roofline time-scale factor applied to transferred "
                 "(unprofiled-device) profiles", ("target_type",)),
}


_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")


def _escape_label(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


class MetricsRegistry:
    """Instrument registry + Prometheus text renderer.

    ``registry.counter(name, **labels)`` returns the one Counter for that
    (name, labels) pair, creating it on first use — call sites fetch and
    record in one line, and repeat fetches are a dict lookup.  A name is
    permanently bound to one instrument kind (mixing kinds under one name
    raises).  ``MetricsRegistry(enabled=False)`` (or :data:`NULL_METRICS`)
    returns shared no-op instruments so instrumented code costs nothing
    when telemetry is off — the bench's metrics-overhead baseline."""

    def __init__(self, enabled: bool = True):
        self.enabled = enabled
        self._lock = threading.Lock()
        self._metrics: dict[tuple[str, tuple], Any] = {}
        self._kinds: dict[str, str] = {}
        self._help: dict[str, str] = {}

    # -- instrument accessors -----------------------------------------------
    def _get(self, kind: str, name: str, help_text: str, factory,
             labels: dict[str, str]):
        if not self.enabled:
            return NULL_INSTRUMENT
        if not _NAME_RE.match(name):
            raise ValueError(f"bad metric name: {name!r}")
        for k in labels:
            if not _LABEL_RE.match(k):
                raise ValueError(f"bad label name: {k!r}")
        key = (name, tuple(sorted((k, str(v)) for k, v in labels.items())))
        with self._lock:
            inst = self._metrics.get(key)
            if inst is not None:
                if self._kinds[name] != kind:
                    raise ValueError(
                        f"metric {name!r} is a {self._kinds[name]}, "
                        f"not a {kind}")
                return inst
            prev = self._kinds.get(name)
            if prev is not None and prev != kind:
                raise ValueError(
                    f"metric {name!r} is a {prev}, not a {kind}")
            inst = factory()
            self._metrics[key] = inst
            self._kinds[name] = kind
            if name not in self._help:
                cat = METRIC_CATALOG.get(name)
                self._help[name] = help_text or (cat[1] if cat else "")
            return inst

    def counter(self, name: str, help: str = "", **labels: str) -> Counter:
        return self._get("counter", name, help, Counter, labels)

    def gauge(self, name: str, help: str = "", **labels: str) -> Gauge:
        return self._get("gauge", name, help, Gauge, labels)

    def histogram(self, name: str, help: str = "",
                  bounds: Iterable[float] | None = None,
                  **labels: str) -> Histogram:
        return self._get("histogram", name, help,
                         lambda: Histogram(bounds=bounds), labels)

    def rate(self, name: str, help: str = "", window_s: float = 60.0,
             **labels: str) -> RateMeter:
        # rendered as a gauge: the sample is the instantaneous rate
        return self._get("rate", name, help,
                         lambda: RateMeter(window_s=window_s), labels)

    # -- introspection ------------------------------------------------------
    def names(self) -> set[str]:
        with self._lock:
            return set(self._kinds)

    def snapshot(self) -> dict:
        """Nested JSON-safe dump: name -> list of {labels, ...values}."""
        out: dict[str, list] = {}
        with self._lock:
            items = list(self._metrics.items())
            kinds = dict(self._kinds)
        for (name, labelkey), inst in items:
            kind = kinds[name]
            entry: dict[str, Any] = {"labels": dict(labelkey)}
            if kind == "histogram":
                entry.update(inst.to_dict())
                entry.pop("counts", None)
                for q in (0.5, 0.95, 0.99):
                    entry[f"p{int(q * 100)}"] = inst.quantile(q)
            elif kind == "rate":
                entry["rate"] = inst.rate()
                entry["total"] = inst.total
            else:
                entry["value"] = inst.value
            out.setdefault(name, []).append(entry)
        return out

    def merge(self, other: "MetricsRegistry") -> None:
        """Fold another registry's counters and histograms into this one
        (gauges/rates are point-in-time — last write wins, like
        ``Counters.merge`` folding worker shards)."""
        with other._lock:
            items = list(other._metrics.items())
            kinds = dict(other._kinds)
            helps = dict(other._help)
        for (name, labelkey), inst in items:
            kind = kinds[name]
            labels = dict(labelkey)
            if kind == "counter":
                self.counter(name, helps.get(name, ""), **labels).inc(
                    inst.value)
            elif kind == "histogram":
                mine = self.histogram(name, helps.get(name, ""),
                                      bounds=inst.bounds, **labels)
                mine.merge(inst)
            elif kind == "gauge":
                self.gauge(name, helps.get(name, ""), **labels).set(
                    inst.value)
            # rates cannot be meaningfully merged across processes

    # -- exposition ---------------------------------------------------------
    def render(self) -> str:
        """Prometheus text exposition (format version 0.0.4)."""
        with self._lock:
            items = sorted(self._metrics.items())
            kinds = dict(self._kinds)
            helps = dict(self._help)
        by_name: dict[str, list] = {}
        for (name, labelkey), inst in items:
            by_name.setdefault(name, []).append((dict(labelkey), inst))
        lines: list[str] = []
        for name in sorted(by_name):
            kind = kinds[name]
            exposed_type = "gauge" if kind == "rate" else kind
            if helps.get(name):
                lines.append(f"# HELP {name} {helps[name]}")
            lines.append(f"# TYPE {name} {exposed_type}")
            for labels, inst in by_name[name]:
                base = _label_str(labels)
                if kind == "histogram":
                    for le, cum in inst.cumulative_buckets():
                        lab = _label_str({**labels, "le": _fmt(le)})
                        lines.append(f"{name}_bucket{lab} {cum}")
                    lines.append(f"{name}_sum{base} {_fmt(inst.sum)}")
                    lines.append(f"{name}_count{base} {inst.count}")
                elif kind == "rate":
                    lines.append(f"{name}{base} {_fmt(inst.rate())}")
                else:
                    lines.append(f"{name}{base} {_fmt(inst.value)}")
        return "\n".join(lines) + "\n"


def _label_str(labels: dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape_label(str(v))}"'
                     for k, v in sorted(labels.items()))
    return "{" + inner + "}"


NULL_METRICS = MetricsRegistry(enabled=False)


# ---------------------------------------------------------------------------
# exposition parsing — the dashboard and the checker consume the scrape
# text itself, not process state
# ---------------------------------------------------------------------------

_SAMPLE_RE = re.compile(
    r'^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)'
    r'(?:\{(?P<labels>[^}]*)\})?'
    r'\s+(?P<value>[^\s]+)\s*$')
_LABEL_PAIR_RE = re.compile(
    r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _unescape_label(v: str) -> str:
    return (v.replace("\\n", "\n").replace('\\"', '"')
            .replace("\\\\", "\\"))


def _parse_value(s: str) -> float:
    if s == "+Inf":
        return math.inf
    if s == "-Inf":
        return -math.inf
    if s == "NaN":
        return math.nan
    return float(s)


def parse_exposition(text: str) -> dict[str, dict]:
    """Parse Prometheus text exposition into::

        {family: {"type": str|None, "help": str|None,
                  "samples": [(sample_name, labels_dict, value)]}}

    ``_bucket``/``_sum``/``_count`` samples group under their histogram's
    family name.  Raises ValueError on a malformed line."""
    out: dict[str, dict] = {}
    declared: set[str] = set()

    def family(name: str) -> str:
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix) and name[:-len(suffix)] in declared:
                return name[:-len(suffix)]
        return name

    for lineno, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            parts = line[len("# HELP "):].split(" ", 1)
            fam = out.setdefault(parts[0], {"type": None, "help": None,
                                            "samples": []})
            fam["help"] = parts[1] if len(parts) > 1 else ""
            declared.add(parts[0])
            continue
        if line.startswith("# TYPE "):
            parts = line[len("# TYPE "):].split(" ", 1)
            fam = out.setdefault(parts[0], {"type": None, "help": None,
                                            "samples": []})
            fam["type"] = parts[1].strip() if len(parts) > 1 else ""
            declared.add(parts[0])
            continue
        if line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            raise ValueError(f"line {lineno}: malformed sample: {line!r}")
        labels = {k: _unescape_label(v) for k, v in
                  _LABEL_PAIR_RE.findall(m.group("labels") or "")}
        value = _parse_value(m.group("value"))
        fam_name = family(m.group("name"))
        fam = out.setdefault(fam_name, {"type": None, "help": None,
                                        "samples": []})
        fam["samples"].append((m.group("name"), labels, value))
    return out


def quantile_from_buckets(buckets: list[tuple[float, float]],
                          q: float) -> float | None:
    """Nearest-rank quantile from scraped cumulative ``(le, count)``
    buckets — how ``metis-tpu top`` turns a /metrics scrape back into
    p50/p99 without process access.  None when the histogram is empty."""
    if not buckets:
        return None
    buckets = sorted(buckets, key=lambda b: b[0])
    total = buckets[-1][1]
    if total <= 0:
        return None
    target = max(1.0, math.ceil(q * total))
    prev_bound = None
    for le, cum in buckets:
        if cum >= target:
            if not math.isfinite(le):
                return prev_bound  # overflow bucket: best we can say
            if prev_bound and prev_bound > 0 and le > 0:
                return math.sqrt(prev_bound * le)
            return le
        prev_bound = le if math.isfinite(le) else prev_bound
    return prev_bound
