"""metis_tpu — TPU-native automatic distributed-training planner and
execution layer.

Capabilities of SamsungLabs/Metis (USENIX ATC'24) rebuilt TPU-first:
profile-driven search over DP×TP×PP(×SP/CP) plans for homogeneous and
heterogeneous TPU fleets, an ICI/DCN-aware cost model, and a JAX execution
layer that lowers chosen plans onto jax.sharding.Mesh.
"""

__version__ = "0.1.0"
