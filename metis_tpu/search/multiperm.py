"""Distinct permutations of a multiset.

The reference vendors Williams' loopless algorithm from ekg/multipermute
(``search_space/utils.py``, see its NOTICE).  We use a counting backtracker
instead: simpler, allocation-light, and yields in lexicographic order (the
reference's emission order differs, but every consumer treats the result as a
set).
"""
from __future__ import annotations

from collections import Counter
from typing import Hashable, Iterable, Iterator, Sequence, TypeVar

T = TypeVar("T", bound=Hashable)


def multiset_permutations(items: Sequence[T]) -> Iterator[tuple[T, ...]]:
    """Yield each distinct ordering of ``items`` exactly once."""
    counts = Counter(items)
    keys = sorted(counts)
    n = len(items)
    path: list[T] = []

    def rec() -> Iterator[tuple[T, ...]]:
        if len(path) == n:
            yield tuple(path)
            return
        for k in keys:
            if counts[k]:
                counts[k] -= 1
                path.append(k)
                yield from rec()
                path.pop()
                counts[k] += 1

    return rec()


def count_multiset_permutations(items: Iterable[T]) -> int:
    """n! / prod(m_i!) without enumerating."""
    import math

    counts = Counter(items)
    n = sum(counts.values())
    total = math.factorial(n)
    for m in counts.values():
        total //= math.factorial(m)
    return total
