"""Sharded parallel plan search — the multiprocess executor behind
``SearchConfig.workers``.

The search hot loop (``planner/api.plan_hetero``) is a single-process pure
Python walk, exactly like the reference it reproduces — and "planner search
time" is a north-star metric (BASELINE.md).  This module makes it scale with
cores without changing a single answer:

- **Index-stride sharding.**  Every worker enumerates the SAME flat
  inter-stage candidate stream (``search/inter_stage.inter_stage_plans``)
  and processes only candidates whose global index ``idx`` satisfies
  ``idx % num_workers == worker_id``.  The shard assignment depends only on
  the enumeration order — which is deterministic — so the union of shards
  is exactly the serial candidate set for ANY worker count, including 1.
- **Stable tie-break merge.**  The serial path appends costed plans in
  (global candidate index, per-candidate yield sequence) order and then
  STABLE-sorts by ``cost.total_ms`` — so its final order is exactly the
  order of the key ``(total_ms, idx, seq)``.  Workers tag each plan with
  that key; the parent sorts the concatenation by it, reproducing the
  serial ranking byte-for-byte (``dump_ranked_plans`` equality is asserted
  in-bench and in tests/test_parallel_search.py).
- **Counter reconciliation.**  Each worker runs its own ``Counters`` and
  ``SearchPruner``; the parent folds the dicts together
  (``Counters.merge``) and sums ``num_costed``/``num_pruned``/
  ``num_bound_pruned``.  The doom fast-path is stateless per candidate, so
  with the bound/beam prunes off (``prune_to_top_k`` unset — always the
  case under ``strict_compat``) every merged count equals the serial run's.
  With ``prune_to_top_k`` set the workers keep their exactness guarantee
  (a worker-local kth-best is never better than the global one, so a
  bound-pruned candidate is provably outside the global top-K) but prune
  *later* than the serial composition-level walk — the top-K set matches
  serial, while prune counters and the tail beyond K may not.  Per-worker
  cache-utilization counters (``bw_cache_*``) naturally differ from a
  one-process run.
- **Graceful fallback.**  ``try_parallel_plan_hetero`` returns None — and
  emits a ``parallel_fallback`` event with the reason — when no
  multiprocessing start method is available or the search inputs don't
  pickle (e.g. ``plan_tpu``'s closure-based bandwidth factory under
  spawn-only platforms); ``plan_hetero`` then runs its serial loop.

``CandidateEvaluator`` is the factored-out per-candidate cost loop itself,
shared verbatim by the serial path and the workers — one implementation,
two drivers.
"""
from __future__ import annotations

import dataclasses
import math
import multiprocessing as mp
import pickle
import queue as _queue
import time
from itertools import product

from metis_tpu.core.events import EventLog, NULL_LOG
from metis_tpu.core.trace import NULL_SPAN, Counters, Tracer, timed_iter
from metis_tpu.core.types import RankedPlan
from metis_tpu.balance.layers import LayerBalancer
from metis_tpu.balance.stage_perf import StagePerformanceModel, rank_device_types
from metis_tpu.cost.batch import BatchCostEstimator
from metis_tpu.cost.context_parallel import cp_candidates
from metis_tpu.cost.estimator import EstimatorOptions, HeteroCostEstimator
from metis_tpu.cost.expert_parallel import ep_candidates
from metis_tpu.cost.volume import TransformerVolume
from metis_tpu.cost.zero import zero_candidates
from metis_tpu.search.device_groups import type_equivalence_classes
from metis_tpu.search.inter_stage import inter_stage_plans
from metis_tpu.search.intra_stage import intra_stage_plans, schedule_intra_plans
from metis_tpu.search.prune import SearchPruner

# Symmetry-class event memo: one entry per canonical (sequence class,
# device_groups, batches) candidate.  Node-tag memo: one entry per
# (node_sequence, device_groups) layout.  Both are bounded PR-6 style —
# wholesale clear past the cap, traffic observable via
# ``memo.{symmetry,node_tags}.{hit,miss,evict}``.
_SYM_MEMO_MAX = 16384
_NODE_TAG_MEMO_MAX = 8192


class CandidateEvaluator:
    """The per-candidate cost loop of ``plan_hetero``, factored out so the
    serial path and the sharded workers run literally the same code.

    Construction mirrors ``plan_hetero``'s setup span: estimator, stage
    evaluator, layer balancer, and the cp/ep/zero/sp and pipeline-schedule
    family grids.  ``evaluate(inter, pruner)`` is a generator yielding, in
    the exact serial insertion order::

        ("plan", RankedPlan)   # costed candidate; ``pruner.record`` and the
                               # ``costed`` counter already applied
        ("miss", True)         # per-intra profile miss (counts as a
                               # heartbeat tick, like the serial loop)
        ("miss", False)        # family-level profile miss (no tick)

    so drivers only do bookkeeping: pruned tallies, heartbeats, and result
    collection.  ``inter_filter``/``pruner.admit``/``begin_candidate``/
    ``end_candidate`` remain the driver's job.
    """

    def __init__(self, cluster, profiles, model, config,
                 bandwidth_factory=None, counters=None, node_ids=None):
        self.cluster = cluster
        self.model = model
        self.config = config
        self.counters = counters
        # Stable node identities for incremental replanning: position i of
        # ``cluster.nodes`` is known to the OWNER of this evaluator (the
        # serving daemon) as ``node_ids[i]`` in some enclosing topology —
        # a tenant carve's nodes keep their full-fleet ids.  Every costed
        # candidate gets tagged with the ids its placement touches
        # (``touched_nodes``) so a ClusterDelta can re-cost only the
        # intersecting warm state.
        if node_ids is None:
            node_ids = tuple(range(len(cluster.nodes)))
        else:
            node_ids = tuple(node_ids)
            if len(node_ids) != len(cluster.nodes):
                raise ValueError(
                    f"node_ids has {len(node_ids)} entries for "
                    f"{len(cluster.nodes)} cluster nodes")
        self.node_ids = node_ids
        self.touched_nodes: set = set()
        self.tagged_candidates = 0
        self._node_tags: dict[tuple, frozenset] = {}
        # Symmetry collapse (AMP-style, arXiv 2210.07297): when two device
        # types are cost-indistinguishable (see ``type_equivalence_classes``)
        # every candidate whose node_sequence canonicalizes to an
        # already-costed one is REPLAYED from the memo instead of re-priced —
        # bit-identical by construction, since nothing the cost model reads
        # differs.  Gated off when a bandwidth_factory is live (plan_tpu's
        # ICI/DCN topology model reads link structure the DeviceSpec
        # signature cannot see, so the collapse would be unsound there).
        self._symmetry = None
        if (getattr(config, "symmetry_collapse", True)
                and bandwidth_factory is None):
            cmap = type_equivalence_classes(cluster, profiles)
            if any(rep != t for t, rep in cmap.items()):
                self._symmetry = cmap
        self._sym_memo: dict[tuple, list] = {}
        self.sym_hits = 0
        self.sym_misses = 0
        volume = TransformerVolume(model, profiles.model.params_per_layer_bytes)
        options = EstimatorOptions.from_config(config)
        self.estimator = HeteroCostEstimator(
            cluster, profiles, volume, options, bandwidth_factory,
            counters=counters)
        self.evaluator = StagePerformanceModel(cluster, profiles,
                                               counters=counters)
        self.balancer = LayerBalancer(cluster, profiles, config, model=model,
                                      counters=counters)
        # GQA: the a2a head split must divide BOTH head counts — their gcd
        self.a2a_head_limit = math.gcd(
            model.num_heads, model.num_kv_heads or model.num_heads)
        # cp composes with the DENSE families only (execution/hetero.py has
        # no cp+MoE path); every degree > 1 searches ring K/V rotation plus
        # the Ulysses a2a mode where the head count splits evenly.
        cp_families: list[tuple[int, str]] = [(1, "ring")]
        if (config.enable_cp and not config.strict_compat
                and model.num_experts == 0):
            for d in cp_candidates(config.max_cp_degree,
                                   model.sequence_length):
                cp_families.append((d, "ring"))
                if self.a2a_head_limit % d == 0:
                    cp_families.append((d, "a2a"))
        self.cp_families = cp_families
        ep_degrees: list[int] = [1]
        if config.enable_ep and not config.strict_compat:
            ep_degrees += ep_candidates(config.max_ep_degree,
                                        model.num_experts)
        zero_stages = zero_candidates(
            config.enable_zero and not config.strict_compat)
        sp_variants = ((False, True)
                       if config.enable_sp and not config.strict_compat
                       else (False,))
        self.families = list(
            product(cp_families, ep_degrees, zero_stages, sp_variants))
        # 1f1b/interleaved run on the shard_map pipeline executor — dense
        # GPT only (execution/builder.py routing), so MoE models skip them.
        sched_families: list[tuple[str, int]] = []
        if (config.enable_schedule_search and not config.strict_compat
                and model.num_experts == 0):
            sched_families.append(("1f1b", 1))
            for vs in config.virtual_stage_candidates:
                sched_families.append(("interleaved", vs))
        self.sched_families = sched_families
        # Batched table-driven costing (cost/batch.py) prices whole intra
        # candidate lists per inter plan.  It takes over whenever the family
        # grid is exactly the base (cp=1, ep=1, zero=0, sp=False, gpipe)
        # family — the parity and scale workloads, and every strict_compat
        # search; richer family grids keep the per-family scalar loop.
        self._batch_fast = bool(
            getattr(config, "use_batch_eval", True)
            and not sched_families
            and self.families == [((1, "ring"), 1, 0, False)])
        self.batch_estimator = (
            BatchCostEstimator(self.estimator, counters=counters)
            if self._batch_fast else None)
        # serial-path tracing hooks: plan_hetero routes the intra generators
        # through its intra_stage accum span and costing through cost_acc;
        # workers leave them dark (no EventLog crosses the process boundary)
        self.intra_acc = None
        self.cost_acc = NULL_SPAN

    def _inc(self, name: str) -> None:
        if self.counters is not None:
            self.counters.inc(name)

    def evaluate(self, inter, pruner):
        config = self.config
        cp_eligible = None
        types_uniform = True
        if len(self.cp_families) > 1 or self.sched_families:
            # Ring attention needs uniform block timing: only homogeneous
            # stages take the cp axis; the shard_map pipeline (schedule
            # families) needs ONE device type everywhere.  One placement
            # resolve per inter plan, shared by both uses.
            ranks = rank_device_types(self.cluster, inter.node_sequence)
            cp_eligible = [
                len(set(ranks[slice(*inter.stage_rank_range(s))])) == 1
                for s in range(inter.num_stages)
            ]
            types_uniform = len(set(ranks)) == 1
        for sched, vs in self.sched_families:
            try:
                intra_gen = schedule_intra_plans(
                    inter, self.evaluator, self.balancer,
                    max_tp=config.max_profiled_tp,
                    max_bs=config.max_profiled_bs,
                    schedule=sched, virtual_stages=vs,
                    num_blocks=self.model.num_layers - 2,
                    types_uniform=types_uniform,
                )
                if self.intra_acc is not None:
                    intra_gen = timed_iter(intra_gen, self.intra_acc)
                for intra in intra_gen:
                    try:
                        with self.cost_acc:
                            cost = self.estimator.get_cost(
                                inter, intra.strategies,
                                intra.layer_partition,
                                schedule=sched, virtual_stages=vs)
                    except KeyError:
                        self._inc("pruned_profile_miss")
                        yield "miss", True
                        continue
                    pruner.record(cost.total_ms, inter)
                    self._inc("costed")
                    yield "plan", RankedPlan(inter=inter, intra=intra,
                                             cost=cost)
            except KeyError:
                self._inc("pruned_profile_miss")
                yield "miss", False
        # one try-block per (cp, ep, zero, sp) family: a profile miss
        # mid-generation prunes only that family, not its siblings
        for (cp, cp_mode), ep, zero, sp in self.families:
            try:
                intra_gen = intra_stage_plans(
                    inter, self.evaluator, self.balancer,
                    max_tp=config.max_profiled_tp,
                    max_bs=config.max_profiled_bs,
                    cp_degrees=(cp,), cp_eligible=cp_eligible,
                    ep_degrees=(ep,), zero_stages=(zero,),
                    sp_variants=(sp,), cp_modes=(cp_mode,),
                    num_heads=self.a2a_head_limit,
                )
                if self.intra_acc is not None:
                    intra_gen = timed_iter(intra_gen, self.intra_acc)
                for intra in intra_gen:
                    try:
                        with self.cost_acc:
                            cost = self.estimator.get_cost(
                                inter, intra.strategies,
                                intra.layer_partition)
                    except KeyError:
                        self._inc("pruned_profile_miss")
                        yield "miss", True
                        continue
                    pruner.record(cost.total_ms, inter)
                    self._inc("costed")
                    yield "plan", RankedPlan(inter=inter, intra=intra,
                                             cost=cost)
            except KeyError:
                self._inc("pruned_profile_miss")
                yield "miss", False

    def evaluate_batch(self, inters, pruner):
        """Price a buffered run of ADMITTED inter plans, batched.

        Yields ``(inter, events)`` per input in order, where ``events`` is
        the exact ``evaluate`` stream for that inter; ``begin_candidate``/
        ``end_candidate`` are handled here (begin before generation, end
        after the caller consumed the events — generator resumption
        guarantees end(i) runs before begin(i+1), so pruner state evolves
        exactly as in the one-at-a-time loop).  Drivers buffer ONE inter
        when the bound/beam prunes are active — ``pruner.admit`` must see
        each candidate's results before judging the next — and a real batch
        otherwise.

        The fast path collects each inter's intra candidates first (their
        generation never consults costing or the pruner, so collect-then-
        cost reorders nothing), prices them in one ``cost_many`` call, and
        replays the event stream: per-candidate misses tick like the serial
        loop, and a family-level miss lands last — exactly where generation
        aborted.  An empty events list is a valid yield (admitted inter
        with no candidates).

        When symmetry collapse is live, candidates whose canonicalized
        ``node_sequence`` was already costed are replayed from the memo —
        each stored event re-runs ``pruner.record`` and the counters, and
        each plan is re-wrapped with THIS inter — so the pruner state,
        counter totals, and the final stable-sort ranking are byte-identical
        to pricing every permutation from scratch.
        """
        for inter in inters:
            pruner.begin_candidate()
            events = self._candidate_events(inter, pruner)
            n_plans = sum(1 for kind, _ in events if kind == "plan")
            if n_plans:
                self.touched_nodes |= self._tag_nodes(inter)
                self.tagged_candidates += n_plans
            yield inter, events
            pruner.end_candidate(inter)

    def _candidate_events(self, inter, pruner):
        """Events for one admitted inter plan: memo replay when its symmetry
        class was already costed, fresh generation (then memoized) otherwise."""
        sym = self._symmetry
        if sym is None:
            return self._generate_events(inter, pruner)
        key = (tuple(sym[t] for t in inter.node_sequence),
               inter.device_groups, inter.batches)
        cached = self._sym_memo.get(key)
        if cached is not None:
            self.sym_hits += 1
            self._inc("memo.symmetry.hit")
            return self._replay(cached, inter, pruner)
        self.sym_misses += 1
        self._inc("memo.symmetry.miss")
        events = self._generate_events(inter, pruner)
        if len(self._sym_memo) > _SYM_MEMO_MAX:
            self._sym_memo.clear()
            self._inc("memo.symmetry.evict")
        self._sym_memo[key] = events
        return events

    def _replay(self, cached, inter, pruner):
        """Re-emit a memoized event stream for an equivalent inter plan.

        Costs are reused verbatim (bit-identical across the class by
        construction); the pruner heap and the ``costed``/
        ``pruned_profile_miss`` counters are re-driven per event so every
        observable downstream of the evaluator matches a from-scratch run.
        """
        events = []
        for kind, item in cached:
            if kind == "plan":
                pruner.record(item.cost.total_ms, inter)
                self._inc("costed")
                events.append(
                    ("plan", dataclasses.replace(item, inter=inter)))
            else:
                self._inc("pruned_profile_miss")
                events.append((kind, item))
        return events

    def _generate_events(self, inter, pruner):
        if not self._batch_fast:
            return list(self.evaluate(inter, pruner))
        config = self.config
        intras = []
        fam_miss = False
        try:
            intra_gen = intra_stage_plans(
                inter, self.evaluator, self.balancer,
                max_tp=config.max_profiled_tp,
                max_bs=config.max_profiled_bs,
                cp_degrees=(1,), cp_eligible=None,
                ep_degrees=(1,), zero_stages=(0,),
                sp_variants=(False,), cp_modes=("ring",),
                num_heads=self.a2a_head_limit,
            )
            if self.intra_acc is not None:
                intra_gen = timed_iter(intra_gen, self.intra_acc)
            for intra in intra_gen:
                intras.append(intra)
        except KeyError:
            fam_miss = True
        with self.cost_acc:
            costs = self.batch_estimator.cost_many(inter, intras)
        events = []
        for intra, cost in zip(intras, costs):
            if cost is None:
                self._inc("pruned_profile_miss")
                events.append(("miss", True))
            else:
                pruner.record(cost.total_ms, inter)
                self._inc("costed")
                events.append(
                    ("plan", RankedPlan(inter=inter, intra=intra,
                                        cost=cost)))
        if fam_miss:
            self._inc("pruned_profile_miss")
            events.append(("miss", False))
        return events

    def _tag_nodes(self, inter) -> frozenset:
        """Node ids (in the owner's namespace) the placement touches.

        Ranks are laid out over nodes in ``node_sequence`` type order;
        every stage's rank range maps back to the nodes it spans.  Device
        groups always sum to the cluster total, so for a single-job search
        the union covers every node — the granularity that makes
        incremental replanning selective comes from the daemon searching
        per-tenant carves, each tagged with its own slice of fleet ids.
        """
        key = (inter.node_sequence, inter.device_groups)
        cached = self._node_tags.get(key)
        if cached is not None:
            self._inc("memo.node_tags.hit")
            return cached
        self._inc("memo.node_tags.miss")
        # rank spans per node, in sequence order
        spans = []  # (start_rank, end_rank, node_id)
        rank = 0
        for t in inter.node_sequence:
            for i, node in enumerate(self.cluster.nodes):
                if node.device_type != t:
                    continue
                spans.append((rank, rank + node.num_devices,
                              self.node_ids[i]))
                rank += node.num_devices
        touched = set()
        for s in range(inter.num_stages):
            lo, hi = inter.stage_rank_range(s)
            for start, end, nid in spans:
                if start < hi and lo < end:
                    touched.add(nid)
        out = frozenset(touched)
        if len(self._node_tags) > _NODE_TAG_MEMO_MAX:
            self._node_tags.clear()
            self._inc("memo.node_tags.evict")
        self._node_tags[key] = out
        return out


def build_shard_pruner(ctx, profiles):
    """A fresh :class:`SearchPruner` for one shard run of ``ctx`` — the
    same construction the serial driver and every worker use, including
    the tight relaxation bound when the config calls for it (built from
    the evaluator's own tables, so the bound floats match the serial
    run's exactly: pure functions of the shared profiles/config)."""
    config = ctx.config
    bound_fn = None
    if (getattr(config, "tight_bound", True)
            and config.prune_to_top_k is not None
            and not config.strict_compat):
        from metis_tpu.search.exact import RelaxationBound

        bound_fn = RelaxationBound.from_evaluator(ctx)
    return SearchPruner(config, ctx.cluster, profiles, ctx.model,
                        counters=ctx.counters, bound_fn=bound_fn)


def run_worker_shard(ctx, pruner, worker_id, num_workers,
                     inter_filter=None, top_k=None, progress=None):
    """One index-stride shard of the search, in the calling process.

    Enumerates the FULL flat candidate stream (bumping ``inter_enumerated``
    only for owned candidates, so worker sums equal the serial total) and
    runs the shared cost loop on every ``idx % num_workers == worker_id``
    candidate.  ``progress(ticks, elapsed_s, best_ms, n_plans, n_pruned)``
    fires every ``config.progress_every`` heartbeat ticks when given.
    Returns ``(plans, num_costed, pruned, num_bound_pruned)`` where
    ``plans`` is the locally sorted, optionally top-k truncated list of
    ``(total_ms, global_idx, seq, RankedPlan)`` merge tuples.

    Shared verbatim by the one-shot fork-per-search workers here and the
    daemon's persistent pre-warmed pool (``serve/pool.py``) — one
    implementation, so the byte-identical-ranking guarantee cannot drift
    between them.
    """
    config = ctx.config
    counters = ctx.counters
    plans: list[tuple] = []  # (total_ms, global_idx, seq, RankedPlan)
    pruned = 0
    ticks = 0
    best_ms = float("inf")
    t0 = time.perf_counter()
    every = max(int(config.progress_every), 1)
    next_emit = every
    stream = inter_stage_plans(
        ctx.cluster.device_types, ctx.cluster.total_devices, config.gbs,
        ctx.model.num_layers, variance=config.min_group_scale_variance,
        max_permute_len=config.max_permute_len)
    # With the bound/beam prunes active, admit() must see each
    # candidate's recorded costs before judging the next — batching
    # would admit with stale bounds and change the prune counters.
    # Batch size 1 keeps every mode byte-identical to the serial loop.
    batch: list[tuple[int, object]] = []
    bsize = 1 if pruner.active else 64

    def _drain():
        nonlocal ticks, pruned, best_ms, next_emit
        pos = 0
        for _inter, events in ctx.evaluate_batch(
                [rec[1] for rec in batch], pruner):
            idx = batch[pos][0]
            pos += 1
            seq = 0
            for kind, item in events:
                if kind == "plan":
                    if item.cost.total_ms < best_ms:
                        best_ms = item.cost.total_ms
                    plans.append((item.cost.total_ms, idx, seq, item))
                    seq += 1
                    ticks += 1
                else:
                    pruned += 1
                    if item:
                        ticks += 1
                if progress is not None and ticks >= next_emit:
                    next_emit = ticks + every
                    progress(ticks, time.perf_counter() - t0,
                             best_ms if best_ms != float("inf") else None,
                             len(plans), pruned)
        batch.clear()

    for idx, inter in enumerate(stream):
        if idx % num_workers != worker_id:
            continue
        if counters is not None:
            counters.inc("inter_enumerated")
        if inter_filter is not None and not inter_filter(inter):
            pruned += 1
            if counters is not None:
                counters.inc("pruned_inter_filter")
            continue
        if not pruner.admit(inter):
            continue
        batch.append((idx, inter))
        if len(batch) >= bsize:
            _drain()
    if batch:
        _drain()
    num_costed = len(plans)
    # local sort by the global stable-tie-break key; with a top_k the
    # merged top-k is a subset of the union of local top-ks, so the
    # tail never needs to cross the process boundary
    plans.sort(key=lambda rec: rec[:3])
    if top_k is not None:
        plans = plans[:top_k]
    return plans, num_costed, pruned, pruner.num_pruned


def _worker_main(worker_id, num_workers, out_queue, cluster, profiles,
                 model, config, bandwidth_factory, inter_filter, top_k,
                 want_counters):
    """One shard of the search, in a one-shot child process: build the
    evaluator + pruner, run :func:`run_worker_shard`, report
    ``("progress", ...)`` heartbeats and one final ``("result", ...)``
    carrying the tagged plans plus the accounting."""
    try:
        counters = Counters() if want_counters else None
        ctx = CandidateEvaluator(
            cluster, profiles, model, config,
            bandwidth_factory=bandwidth_factory, counters=counters)
        pruner = build_shard_pruner(ctx, profiles)

        def _progress(ticks, elapsed, best, n_plans, n_pruned):
            out_queue.put(("progress", worker_id, ticks, elapsed, best,
                           n_plans, n_pruned))

        plans, num_costed, pruned, bound_pruned = run_worker_shard(
            ctx, pruner, worker_id, num_workers,
            inter_filter=inter_filter, top_k=top_k, progress=_progress)
        out_queue.put((
            "result", worker_id, plans,
            counters.as_dict() if counters is not None else None,
            num_costed, pruned, bound_pruned))
    except BaseException as e:  # noqa: BLE001 — report; parent falls back
        out_queue.put(("error", worker_id, f"{type(e).__name__}: {e}"))


def _mp_context():
    """A usable multiprocessing context, fork preferred (cheap, inherits
    the parent's loaded modules); None when no start method works."""
    for method in ("fork", "spawn"):
        try:
            return mp.get_context(method)
        except (ValueError, RuntimeError):
            continue
    return None


def try_parallel_plan_hetero(
    cluster, profiles, model, config,
    bandwidth_factory=None,
    top_k: int | None = None,
    events: EventLog = NULL_LOG,
    inter_filter=None,
):
    """Run ``plan_hetero``'s search sharded over ``config.workers``
    processes.  Returns the merged PlannerResult — byte-identical ranking
    to the serial loop — or None when parallel execution is unavailable
    (the caller then runs the serial path); every None is preceded by a
    ``parallel_fallback`` event naming the reason."""
    from metis_tpu.planner.api import DEFAULT_EXPLAIN_K, PlannerResult

    workers = int(config.workers)
    if workers <= 1:
        return None
    try:
        pickle.dumps((cluster, profiles, model, config, bandwidth_factory,
                      inter_filter, top_k))
    except Exception as e:
        events.emit("parallel_fallback",
                    reason=f"unpicklable search inputs ({type(e).__name__})")
        return None
    mp_ctx = _mp_context()
    if mp_ctx is None:
        events.emit("parallel_fallback",
                    reason="no multiprocessing start method available")
        return None

    tracer = Tracer(events)
    root = tracer.span("plan_hetero", mode="hetero", model=model.name,
                       devices=cluster.total_devices, workers=workers)
    root.__enter__()
    t0 = time.perf_counter()
    setup_span = tracer.span("setup")
    setup_span.__enter__()
    # parent-side evaluator: family count for search_started + the
    # estimator for the post-ranking explain breakdowns
    ctx = CandidateEvaluator(
        cluster, profiles, model, config,
        bandwidth_factory=bandwidth_factory,
        counters=tracer.counters if tracer.enabled else None)
    setup_span.__exit__(None, None, None)
    events.emit(
        "search_started", mode="hetero", devices=cluster.total_devices,
        device_types=list(cluster.device_types), gbs=config.gbs,
        num_families=len(ctx.families), model=model.name, workers=workers)

    out_queue = mp_ctx.Queue()
    procs = []
    try:
        for wid in range(workers):
            p = mp_ctx.Process(
                target=_worker_main,
                args=(wid, workers, out_queue, cluster, profiles, model,
                      config, bandwidth_factory, inter_filter, top_k,
                      events.enabled),
                daemon=True)
            p.start()
            procs.append(p)
    except OSError as e:
        for p in procs:
            if p.is_alive():
                p.terminate()
        root.__exit__(None, None, None)
        events.emit("parallel_fallback",
                    reason=f"worker start failed ({type(e).__name__})")
        return None

    results_by_wid: dict[int, tuple] = {}
    failed: str | None = None
    strikes = 0
    workers_span = tracer.span("workers", workers=workers)
    workers_span.__enter__()
    # drain while the workers run — the result payloads exceed the pipe
    # buffer, so a put-then-join worker would deadlock against a
    # join-then-get parent
    while len(results_by_wid) < workers and failed is None:
        try:
            msg = out_queue.get(timeout=1.0)
        except _queue.Empty:
            for wid, p in enumerate(procs):
                if (wid not in results_by_wid and not p.is_alive()
                        and p.exitcode not in (0, None)):
                    failed = f"worker {wid} exited with code {p.exitcode}"
                    break
            if failed is None and all(not p.is_alive() for p in procs):
                strikes += 1  # all dead, queue quiet: give the feeder
                if strikes >= 5:  # threads a few grace periods to flush
                    failed = "workers exited without reporting results"
            continue
        kind = msg[0]
        if kind == "progress":
            _, wid, n, elapsed, best, n_costed, n_pruned = msg
            events.emit(
                "search_progress", n=n, elapsed_s=round(elapsed, 3),
                per_s=round(n / elapsed, 1) if elapsed > 0 else None,
                worker=wid, best_cost_ms=best, num_costed=n_costed,
                num_pruned=n_pruned)
        elif kind == "error":
            failed = f"worker {msg[1]} raised: {msg[2]}"
        else:
            results_by_wid[msg[1]] = msg[2:]
    if failed is not None:
        for p in procs:
            if p.is_alive():
                p.terminate()
        for p in procs:
            p.join(timeout=5.0)
        workers_span.__exit__(None, None, None)
        root.__exit__(None, None, None)
        events.emit("parallel_fallback", reason=failed)
        return None
    for p in procs:
        p.join()
    workers_span.__exit__(None, None, None)

    merged: list[tuple] = []
    num_costed = 0
    pruned = 0
    bound_pruned = 0
    for wid in range(workers):
        w_plans, w_counters, w_costed, w_pruned, w_bound = results_by_wid[wid]
        merged.extend(w_plans)
        num_costed += w_costed
        pruned += w_pruned
        bound_pruned += w_bound
        if w_counters:
            tracer.counters.merge(w_counters)
    with tracer.span("ranking", num_plans=len(merged)):
        # (total_ms, global candidate idx, per-candidate yield seq): the
        # serial path's stable sort over its insertion order is exactly a
        # sort by this key, so the merge reproduces it byte-for-byte
        merged.sort(key=lambda rec: rec[:3])
    results = [rec[3] for rec in merged]
    best_cost = results[0].cost.total_ms if results else None
    if top_k is not None:
        results = results[:top_k]
    elapsed = time.perf_counter() - t0

    import dataclasses

    from metis_tpu.obs.ledger import fingerprint_ranked_plan

    explain_k = min(len(results),
                    top_k if top_k is not None else DEFAULT_EXPLAIN_K)
    if explain_k:
        with tracer.span("explain", num_plans=explain_k):
            for i in range(explain_k):
                rp = results[i]
                try:
                    _, bd = ctx.estimator.get_breakdown(
                        rp.inter, rp.intra.strategies,
                        rp.intra.layer_partition,
                        schedule=rp.intra.schedule,
                        virtual_stages=rp.intra.virtual_stages)
                except KeyError:  # pragma: no cover - costed once already
                    continue
                results[i] = dataclasses.replace(rp, breakdown=bd)
                events.emit(
                    "plan_explain", rank=i + 1,
                    fingerprint=fingerprint_ranked_plan(rp),
                    total_ms=round(bd.total_ms, 4),
                    components={k: round(v, 4)
                                for k, v in bd.components.items()},
                    schedule=rp.intra.schedule)
    tracer.emit_counters(scope="plan_hetero")
    events.emit(
        "search_finished", mode="hetero", num_costed=num_costed,
        num_pruned=pruned, seconds=round(elapsed, 4),
        best_cost_ms=best_cost, num_bound_pruned=bound_pruned,
        workers=workers)
    root.__exit__(None, None, None)
    return PlannerResult(
        plans=tuple(results),
        num_costed=num_costed,
        num_pruned=pruned,
        search_seconds=elapsed,
        num_bound_pruned=bound_pruned,
    )
