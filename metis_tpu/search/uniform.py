"""Uniform (homogeneous, Megatron-grid) plan enumeration.

Covers the reference ``UniformPlanGenerator`` space (``search_space/
plan.py:40-97``): every (dp, pp, tp) with dp·pp·tp == num_devices and
tp <= max_tp, crossed with global/micro batch sizes.

Deliberate deviation (documented; see tests/test_search_parity.py): the
reference admits ragged batch splits — it only requires ``mbs·dp <= gbs``, so
``gbs // mbs // dp`` can truncate (``plan.py:84``, ``cost_estimator.py:106``).
We require exact divisibility ``dp·mbs | gbs``: a truncated microbatch count
costs a plan that silently drops samples, which the execution layer could
never run faithfully.
"""
from __future__ import annotations

from typing import Iterator

from metis_tpu.core.types import UniformPlan, divisors


def grid_degrees(num_devices: int, max_tp: int, max_pp: int | None = None) -> Iterator[tuple[int, int, int]]:
    """All (dp, pp, tp) with dp·pp·tp == num_devices, tp <= max_tp."""
    for pp in divisors(num_devices):
        if max_pp is not None and pp > max_pp:
            continue
        per_stage = num_devices // pp
        for tp in divisors(per_stage):
            if tp > max_tp:
                continue
            yield per_stage // tp, pp, tp


def uniform_plans(
    num_devices: int,
    max_tp: int,
    gbs: int,
    max_pp: int | None = None,
    sweep_gbs: bool = False,
    max_gbs: int | None = None,
) -> Iterator[UniformPlan]:
    """Enumerate uniform plans at a fixed global batch size (the reference
    generator sweeps gbs but its driver filters to the requested one,
    ``cost_homo_cluster.py:25`` — we expose the sweep behind ``sweep_gbs``)."""
    gbs_values = (
        [g for g in divisors(max_gbs or gbs) ] if sweep_gbs else [gbs]
    )
    for dp, pp, tp in grid_degrees(num_devices, max_tp, max_pp):
        for g in gbs_values:
            if g % dp:
                continue
            per_replica = g // dp
            for mbs in divisors(per_replica):
                yield UniformPlan(dp=dp, pp=pp, tp=tp, mbs=mbs, gbs=g)
