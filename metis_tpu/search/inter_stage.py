"""Inter-stage (pipeline-level) plan enumeration for heterogeneous clusters.

The reference's outer hot loop (``search_space/plan.py:100-175``): device-type
placement permutations × stage counts × device-group arrangements ×
microbatch counts.  Rewritten as a plain generator — the reference's odometer
object with mutating ``__next__`` state is an implementation detail, not a
behavior; the enumerated *set* is oracle-tested for parity.
"""
from __future__ import annotations

from itertools import permutations
from typing import Iterator, Sequence

from metis_tpu.core.types import InterStagePlan, divisors
from metis_tpu.search.device_groups import enumerate_device_groups


def sequence_symmetry_stats(
    device_types: Sequence[str], class_map: dict[str, str],
) -> tuple[int, int]:
    """(total, distinct) type-permutation counts under an equivalence map.

    ``total`` is the number of node-sequence permutations the search walks;
    ``distinct`` how many remain after canonicalizing each through
    ``class_map`` (device_groups.type_equivalence_classes) — the
    denominator/numerator of the ``symmetry_collapse`` event's
    ``collapse_frac``."""
    types = sorted(set(device_types))
    total = 0
    distinct: set[tuple] = set()
    for perm in permutations(types):
        total += 1
        distinct.add(tuple(class_map.get(t, t) for t in perm))
    return total, len(distinct)


def stage_compositions(
    num_devices: int,
    num_layers: int,
    variance: float = 1.0,
    max_stages: int | None = None,
) -> Iterator[tuple[int, tuple[int, ...]]]:
    """Yield every (stage count, non-decreasing composition) class of the
    search space — the branch nodes shared by the composition-level pruned
    walk (``search/prune.pruned_inter_stage_plans``) and the exact
    branch-and-bound backend (``search/exact.py``).  One definition, so the
    spaces the two backends cover cannot drift: a composition appears here
    iff some arrangement of it appears in the flat walk."""
    from metis_tpu.search.device_groups import (
        nondecreasing_compositions,
        power_of_two_shapes,
    )

    cap = min(num_devices, num_layers)
    if max_stages is not None:
        cap = min(cap, max_stages)
    all_shapes = power_of_two_shapes(num_devices)
    for num_stage in range(1, cap + 1):
        min_group = max(num_devices // num_stage,
                        num_stage // num_devices) * variance
        eligible = [s for s in all_shapes if s >= min_group]
        for comp in nondecreasing_compositions(
                num_stage, num_devices, eligible):
            yield num_stage, comp


def inter_stage_plans(
    device_types: Sequence[str],
    num_devices: int,
    gbs: int,
    num_layers: int,
    variance: float = 1.0,
    max_permute_len: int = 6,
    max_stages: int | None = None,
    counters=None,
) -> Iterator[InterStagePlan]:
    """Yield every inter-stage candidate.

    Stage count is capped at ``min(num_devices, num_layers)`` (a stage needs
    at least one layer and one device, ``plan.py:139,165``); microbatch counts
    sweep the divisors of gbs descending (``plan.py:120-124``).

    ``counters``: optional ``core.trace.Counters`` — every yielded candidate
    bumps ``inter_enumerated`` for the flight recorder's search accounting.
    """
    cap = min(num_devices, num_layers)
    if max_stages is not None:
        cap = min(cap, max_stages)
    batch_options = list(divisors(gbs, descending=True))
    # Group arrangements don't depend on the node sequence — compute once per
    # stage count, not once per device-type permutation.
    groups_by_stage = {
        n: enumerate_device_groups(n, num_devices, variance, max_permute_len,
                                   counters=counters)
        for n in range(1, cap + 1)
    }

    for node_sequence in permutations(sorted(set(device_types))):
        for num_stage in range(1, cap + 1):
            for groups in groups_by_stage[num_stage]:
                for batches in batch_options:
                    if counters is not None:
                        counters.inc("inter_enumerated")
                    yield InterStagePlan(
                        node_sequence=tuple(node_sequence),
                        device_groups=groups,
                        batches=batches,
                        gbs=gbs,
                    )
