"""Exact branch-and-bound planning backend (``SearchConfig.backend="exact"``).

ROADMAP item 1: the beam/prune search (search/prune.py) is fast but
documented INEXACT once ``beam_patience`` is set — at 1024+ devices it
ships "best we found" instead of "within x% of optimal".  This module
closes that gap with a best-first branch-and-bound over the SAME candidate
space the beam backend walks:

- **Branch nodes** are the (stage count, composition, microbatch count)
  classes of ``search/inter_stage.stage_compositions`` — exactly the
  classes the composition-level pruned walk filters, so the two backends
  cover one space by construction.
- **Admissible lower bounds** (``RelaxationBound``) come from the cost
  model's own tables: the ``ExecutionFloor`` W-tables SearchPruner prunes
  with (built over the estimator's post-affine profile view), plus
  per-term minima of the additive ``cost/batch.py`` formula — fb-sync,
  optimizer, and batch-generator floors, the step-overhead intercept
  adjustment, and the EXACT spot multiplier (constant per search: device
  groups always sum to the cluster total, so every candidate carries the
  full-cluster hazard).  dp/pp/migration floor at 0.  Reusing the
  estimator's tables means bound math and costed math can never drift.
- **Leaves** are fully expanded and costed through the shared
  ``CandidateEvaluator`` — the identical code path (and identical floats)
  the beam backend prices with.
- **Certificate.**  The search terminates with a proven lower bound on
  every candidate in the space: run-to-exhaustion proves gap 0; a
  ``SearchConfig.exact_deadline_s`` stop keeps the incumbent and certifies
  the remaining gap (min of the incumbent and the best unexplored node's
  bound).  The certificate is attached to the ``PlannerResult`` and
  emitted as a ``certificate`` event.

Honest contract: the certificate is relative to the candidate space this
config searches (families, max_tp/max_bs, variance, inter_filter) under
this cost model — not a claim about placements outside that space.  With
symmetry collapse live, only canonical type permutations are expanded;
images are cost-identical by construction, so the bound still covers them
(the returned ranking carries one representative per class).

The same ``RelaxationBound`` doubles as the default beam search's
``bound_fn`` (SearchPruner ``prune.bound.tight``): admissible means a
candidate it prunes provably cannot enter the top K, so the beam ranking
stays byte-identical while pricing strictly fewer candidates — gated by
tools/check_search_regression.py like the symmetry collapse was.
"""
from __future__ import annotations

import heapq
import time
from itertools import permutations

from metis_tpu.core.events import EventLog, NULL_LOG
from metis_tpu.core.trace import Tracer
from metis_tpu.core.types import Certificate, RankedPlan, divisors
from metis_tpu.search.inter_stage import stage_compositions
from metis_tpu.search.prune import ExecutionFloor

# The base (cp=1 ring, ep=1, zero=0, sp=False) family signature — when the
# evaluator's grid is exactly this and no schedule families are live, every
# candidate is priced by the additive gpipe formula (cost/batch.py `_fast`
# or its scalar twin) and the per-term floors below are sound.  Richer
# grids (ZeRO shards the optimizer, cp reshapes fb-sync) fall back to the
# execution floor alone.
_BASE_FAMILIES = [((1, "ring"), 1, 0, False)]


class _NullPruner:
    """Pruner protocol stub for ``CandidateEvaluator.evaluate_batch``: the
    branch-and-bound does its own bounding at the node level, so leaves are
    costed unconditionally — record/begin/end are no-ops, exactly like a
    ``SearchPruner`` with ``top_k=None`` (costs stay bit-identical to the
    beam path because the evaluator never branches on the pruner)."""

    def begin_candidate(self) -> None:
        pass

    def record(self, total_ms: float, inter=None) -> None:
        pass

    def end_candidate(self, inter) -> None:
        pass


class RelaxationBound:
    """Admissible per-(composition, stages, batches) lower bound on
    ``PlanCost.total_ms`` over every candidate of the class.

    Callable as ``bound(g_max, num_stages, batches) -> ms`` — the same
    signature as ``SearchPruner._exec_lower_bound``, so the beam path can
    consult it as its ``bound_fn`` after the stock floor passes.

    Term-by-term over the additive formula (cost/batch.py ``_fast``; the
    scalar path is bit-identical):

    - **feasibility cap** — a stage's axes multiply to its group size
      (``dp * tp * cp == g``, search/intra_stage.initial_strategies +
      escalation), ``mbs >= 1`` caps ``dp <= gbs // batches`` and the
      escalation dooms at ``tp > max_tp``, so a class whose largest group
      exceeds ``(gbs // batches) * max_tp * max_cp`` contains NO valid
      plan in any family.  The bound returns +inf for it — vacuously
      admissible over an empty class, and it skips the whole doomed
      dp->tp escalation walk the beam path would otherwise grind through.
    - ``execution``  >= ExecutionFloor.bound(...) + the step-overhead
      floor: the charge is ``max`` over the plan's (type, tp) pairs, once
      for uniform plans and ``max(0, .) * batches`` otherwise, so
      ``min over profiled pairs of so.get(pair, 0.0)`` lower-bounds both
      branches (a negative affine intercept is charged at most once, so
      clamping the floor at zero would be UNSOUND).
    - ``fb_sync``    >= (min profiled fb_sync_ms) * batches   [base only]
    - ``max_opt``    >= (min optimizer rate / max_tp) * ceil(L/S)/L — some
      stage holds at least ceil(L/S) layers                   [base only]
    - ``batch_gen``  == per-batch cost * batches under strict_compat
      (constant across candidates); >= min per-type cost native [base only]
    - ``dp/pp/migration`` >= 0.
    - spot multiplier is EXACT: device groups always sum to the cluster
      total, so every candidate's hazard is the full-cluster hazard.
    """

    def __init__(self, floor: ExecutionFloor, *, base_only: bool,
                 strict: bool, overhead_adjust: float, fb_min: float,
                 opt_floor_rate: float, num_layers: int,
                 bg_strict_per_batch: float, bg_native_min: float,
                 spot_scale: float, gbs: int = 0, max_tp: int = 1,
                 max_cp: int = 1):
        self._floor = floor
        self._base_only = base_only
        self._strict = strict
        self._overhead_adjust = overhead_adjust
        self._fb_min = fb_min
        self._opt_floor_rate = opt_floor_rate
        self._L = num_layers
        self._bg_strict = bg_strict_per_batch
        self._bg_native = bg_native_min
        self._spot_mult = 1.0 + spot_scale
        self._gbs = gbs
        self._axes_cap = max_tp * max_cp

    @classmethod
    def from_evaluator(cls, ctx) -> "RelaxationBound":
        """Build from a ``CandidateEvaluator``'s own estimator tables — the
        floors price with exactly the view (post-affine profiles, optimizer
        factor, spot options) candidates are costed with."""
        config, cluster, model = ctx.config, ctx.cluster, ctx.model
        scalar = ctx.estimator
        profiles = scalar.profiles  # post affine-view when mb_affine is on
        floor = ExecutionFloor(config, cluster, profiles, model)
        base_only = (not ctx.sched_families
                     and ctx.families == _BASE_FAMILIES)
        strict = bool(config.strict_compat)
        so = scalar._step_overhead
        fb_min = float("inf")
        overhead_adjust = float("inf")
        for t in cluster.device_types:
            for (_, tp, bs) in profiles.configs(t):
                if tp <= config.max_profiled_tp:
                    fb = profiles.get(t, tp, bs).fb_sync_ms
                    if fb < fb_min:
                        fb_min = fb
                    oh = so.get((t, tp), 0.0)
                    if oh < overhead_adjust:
                        overhead_adjust = oh
        if fb_min == float("inf"):
            fb_min = 0.0
        if not so or overhead_adjust == float("inf"):
            overhead_adjust = 0.0
        opt_types = (None,) if strict else tuple(cluster.device_types)
        opt_ms = []
        for t in opt_types:
            try:
                opt_ms.append(scalar._optimizer_ms(t))
            except KeyError:
                opt_ms = []
                break
        opt_floor_rate = (min(opt_ms) / config.max_profiled_tp
                          if opt_ms else 0.0)
        bg_strict = profiles.model.batch_generator_ms
        bg_vals = []
        for t in cluster.device_types:
            try:
                bg_vals.append(profiles.type_meta[t].batch_generator_ms)
            except (KeyError, AttributeError):
                bg_vals = []
                break
        bg_native = min(bg_vals) if bg_vals else 0.0
        spot_scale = 0.0
        if scalar.options.spot_active:
            hazard = sum(
                node.num_devices
                * cluster.devices[node.device_type].hazard_per_hr
                for node in cluster.nodes)
            spot_scale = scalar._spot_scale_of(hazard)
        # largest context-parallel degree any family can put on a stage —
        # the same eligibility gate ExecutionFloor's cp divisor uses
        max_cp = (config.max_cp_degree
                  if (config.enable_cp and not config.strict_compat
                      and model.num_experts == 0) else 1)
        return cls(floor, base_only=base_only, strict=strict,
                   overhead_adjust=overhead_adjust, fb_min=fb_min,
                   opt_floor_rate=opt_floor_rate,
                   num_layers=model.num_layers,
                   bg_strict_per_batch=bg_strict, bg_native_min=bg_native,
                   spot_scale=spot_scale, gbs=config.gbs,
                   max_tp=config.max_profiled_tp, max_cp=max_cp)

    def __call__(self, g_max: int, num_stages: int, batches: int) -> float:
        # empty class: no (dp, tp, cp) factorization of g_max can keep
        # mbs >= 1 within the profiled tp range — every candidate's
        # escalation walk is provably fruitless
        if g_max > (self._gbs // batches) * self._axes_cap:
            return float("inf")
        lb = self._floor.bound(g_max, num_stages, batches)
        lb += self._overhead_adjust
        if self._base_only:
            lb += self._fb_min * batches
            L = self._L
            max_layers = -(-L // num_stages)  # ceil: the fullest stage
            lb += self._opt_floor_rate * max_layers / L
            lb += (self._bg_strict * batches if self._strict
                   else self._bg_native)
        return lb * self._spot_mult


def _canonical_type_perms(device_types, symmetry):
    """Type permutations to expand: all of them, or — with a live symmetry
    map — one representative per cost-equivalence class (images are
    bit-identical to their canonical, so skipping them loses nothing the
    certificate covers)."""
    perms = list(permutations(sorted(set(device_types))))
    if symmetry is None:
        return perms
    seen: set[tuple] = set()
    out = []
    for p in perms:
        key = tuple(symmetry.get(t, t) for t in p)
        if key not in seen:
            seen.add(key)
            out.append(p)
    return out


def exact_plan_hetero(
    cluster,
    profiles,
    model,
    config,
    bandwidth_factory=None,
    top_k: int | None = None,
    events: EventLog = NULL_LOG,
    inter_filter=None,
    search_state=None,
    residual_model=None,
):
    """Branch-and-bound heterogeneous search with an optimality certificate.

    Same signature and return shape as ``planner.api.plan_hetero`` (which
    dispatches here on ``config.backend == "exact"``); runs serially —
    ``config.workers`` is ignored.  The returned ``PlannerResult`` carries
    a :class:`~metis_tpu.core.types.Certificate` (None only when the space
    yields no costable plan at all).

    ``residual_model`` (cost/uncertainty.ResidualModel, optional): prices
    each candidate's residual distribution.  With the config's
    ``risk_quantile``/``cvar_alpha`` knobs set, incumbents and the final
    ranking live in SCORE space (point total x tail factor, >= the point
    total, so the point-cost relaxation bounds stay admissible and the
    bound-stop only prunes provably score-worse frontiers).  With a model
    — knobs or not — the Certificate carries ``confidence_p``: the
    probability the incumbent is truly optimal given the residual sigma.
    None keeps everything byte-identical to the point-mode backend."""
    from metis_tpu.core.types import InterStagePlan
    from metis_tpu.planner.api import (
        DEFAULT_EXPLAIN_K,
        PlannerResult,
        make_search_state,
    )
    from metis_tpu.cost.uncertainty import (
        certificate_confidence,
        make_risk_scorer,
    )
    from metis_tpu.search.device_groups import arrangements_of_composition

    scorer = make_risk_scorer(config, residual_model)
    tracer = Tracer(events)
    root = tracer.span("plan_exact", mode="hetero", model=model.name,
                       devices=cluster.total_devices)
    root.__enter__()
    t0 = time.perf_counter()
    with tracer.span("setup"):
        ctx = search_state if search_state is not None else make_search_state(
            cluster, profiles, model, config,
            bandwidth_factory=bandwidth_factory,
            counters=tracer.counters if tracer.enabled else None)
        bound = RelaxationBound.from_evaluator(ctx)
    events.emit(
        "search_started", mode="hetero", devices=cluster.total_devices,
        device_types=list(cluster.device_types), gbs=config.gbs,
        num_families=len(ctx.families), model=model.name, backend="exact")

    # enumerate branch nodes: one per (stage count, composition, batches)
    # class, doom-filtered exactly like the beam walk (a smallest-group
    # microbatch over max_bs stays over under every dp escalation)
    batch_options = list(divisors(config.gbs))
    heap: list[tuple] = []  # (lower bound, enum idx, S, comp, batches)
    idx = 0
    num_doomed = 0
    with tracer.span("enumeration"):
        for num_stage, comp in stage_compositions(
                cluster.total_devices, model.num_layers,
                variance=config.min_group_scale_variance):
            g_min, g_max = comp[0], comp[-1]
            for batches in batch_options:
                if (config.gbs // g_min) // batches > config.max_profiled_bs:
                    num_doomed += 1
                    tracer.inc("prune.doom")
                    continue
                node_lb = bound(g_max, num_stage, batches)
                if node_lb == float("inf"):
                    # provably empty class (feasibility cap): doom-style
                    # exactness prune, no node to explore
                    num_doomed += 1
                    tracer.inc("prune.doom")
                    continue
                heapq.heappush(
                    heap, (node_lb, idx, num_stage, comp, batches))
                idx += 1

    type_perms = _canonical_type_perms(cluster.device_types, ctx._symmetry)
    pruner = _NullPruner()
    ctx.intra_acc = None
    ctx.cost_acc = tracer.accum("costing")
    results: list[RankedPlan] = []
    order: list[tuple] = []  # (total_ms, node idx, yield seq) sort keys
    pruned = 0
    incumbent = float("inf")
    nodes_explored = 0
    nodes_bounded = 0
    complete = True
    proven_lb = float("inf")
    deadline = config.exact_deadline_s

    while heap:
        node_lb, node_idx, num_stage, comp, batches = heapq.heappop(heap)
        if node_lb > incumbent:
            # best-first: every remaining node's bound is >= this one, so
            # the whole frontier is provably outside the incumbent
            nodes_bounded += 1 + len(heap)
            heap.clear()
            break
        if (deadline is not None
                and time.perf_counter() - t0 > deadline):
            complete = False
            proven_lb = min(incumbent, node_lb)
            heap.clear()
            break
        seq = 0
        for node_sequence in type_perms:
            for groups in arrangements_of_composition(
                    comp, config.max_permute_len):
                inter = InterStagePlan(
                    node_sequence=node_sequence, device_groups=groups,
                    batches=batches, gbs=config.gbs)
                if inter_filter is not None and not inter_filter(inter):
                    pruned += 1
                    tracer.inc("pruned_inter_filter")
                    continue
                for _inter, evs in ctx.evaluate_batch([inter], pruner):
                    for kind, item in evs:
                        if kind == "plan":
                            score = (scorer.score(item.cost.total_ms,
                                                  node_sequence)
                                     if scorer is not None
                                     else item.cost.total_ms)
                            if score < incumbent:
                                incumbent = score
                            results.append(item)
                            order.append((score, node_idx, seq))
                            seq += 1
                        else:
                            pruned += 1
        nodes_explored += 1
        if events.enabled:
            events.emit(
                "bnb_progress", nodes_explored=nodes_explored,
                nodes_bounded=nodes_bounded,
                best_ms=incumbent if incumbent != float("inf") else None,
                bound_ms=round(node_lb, 4), frontier=len(heap))

    ctx.cost_acc.close()
    if complete:
        proven_lb = incumbent
    num_costed = len(results)
    with tracer.span("ranking", num_plans=num_costed):
        ranked = [p for _, p in sorted(
            zip(order, results), key=lambda rec: rec[0])]
    best_cost = ranked[0].cost.total_ms if ranked else None
    if top_k is not None:
        ranked = ranked[:top_k]
    elapsed = time.perf_counter() - t0

    certificate = None
    if best_cost is not None:
        # with a scorer the incumbent/proven_lb pair lives in score
        # space, so the whole certificate (best_ms, bound, gap) is
        # certified there too — best_ms >= lower_bound always holds in
        # one space; point mode is unchanged (score == total then,
        # float-identical)
        skeys = sorted(k[0] for k in order)
        best_score = skeys[0]
        gap = ((best_score - proven_lb) / best_score
               if best_score > 0 else 0.0)
        confidence_p = None
        if residual_model is not None and residual_model:
            best_plan = ranked[0]
            sigma = residual_model.sigma_ms(
                best_cost, best_plan.inter.node_sequence)
            margin = skeys[1] - best_score if len(skeys) > 1 else float("inf")
            if not complete:
                # unexplored frontier could hold a plan as low as the
                # proven bound — that hypothetical is the competitor
                margin = min(margin, proven_lb - best_score)
            confidence_p = round(certificate_confidence(
                margin, sigma, scorer.z_q if scorer is not None else 0.0), 6)
        certificate = Certificate(
            best_ms=best_score,
            lower_bound_ms=proven_lb,
            gap_frac=max(0.0, gap),
            nodes_explored=nodes_explored,
            nodes_bounded=nodes_bounded + num_doomed,
            wall_s=elapsed,
            complete=complete,
            confidence_p=confidence_p,
        )
        events.emit("certificate", **certificate.to_json_dict())

    # plan explainability, same contract as the beam path: re-price the
    # top-k through the SAME estimator for per-component breakdowns
    import dataclasses

    from metis_tpu.obs.ledger import fingerprint_ranked_plan

    explain_k = min(len(ranked),
                    top_k if top_k is not None else DEFAULT_EXPLAIN_K)
    if explain_k:
        with tracer.span("explain", num_plans=explain_k):
            for i in range(explain_k):
                rp = ranked[i]
                try:
                    _, bd = ctx.estimator.get_breakdown(
                        rp.inter, rp.intra.strategies,
                        rp.intra.layer_partition,
                        schedule=rp.intra.schedule,
                        virtual_stages=rp.intra.virtual_stages)
                except KeyError:  # pragma: no cover - costed once already
                    continue
                if residual_model is not None and residual_model:
                    from metis_tpu.cost.uncertainty import annotate_breakdown

                    bd = annotate_breakdown(bd, residual_model,
                                            rp.inter.node_sequence)
                ranked[i] = dataclasses.replace(rp, breakdown=bd)
                events.emit(
                    "plan_explain", rank=i + 1,
                    fingerprint=fingerprint_ranked_plan(rp),
                    total_ms=round(bd.total_ms, 4),
                    components={k: round(v, 4)
                                for k, v in bd.components.items()},
                    schedule=rp.intra.schedule)
    tracer.emit_counters(scope="plan_exact")
    events.emit(
        "search_finished", mode="hetero", num_costed=num_costed,
        num_pruned=pruned, seconds=round(elapsed, 4),
        best_cost_ms=best_cost,
        num_bound_pruned=num_doomed + nodes_bounded, backend="exact")
    root.__exit__(None, None, None)
    return PlannerResult(
        plans=tuple(ranked),
        num_costed=num_costed,
        num_pruned=pruned,
        search_seconds=elapsed,
        num_bound_pruned=num_doomed + nodes_bounded,
        certificate=certificate,
    )
