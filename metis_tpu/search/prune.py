"""Search-scalability pruning: exact lower bounds + anytime beam.

VERDICT r2 next-step 7: enumeration at 256 devices with small-group
variance grows to tens of millions of (placement x groups x batches)
candidates; costing each takes minutes-to-hours.  Four prunes, layered:

1. **Doom fast-path (always on, exact).**  A stage's microbatch size only
   GROWS under dp->tp escalation (``mbs = gbs/(dp*B)``, dp only halves), so
   an inter plan whose smallest group already forces ``mbs > max_bs`` at
   full dp can never produce a valid strategy — the intra generator would
   classify every escalation DOOMED.  One integer compare replaces that
   whole walk; observably identical output.

2. **Execution lower bound vs the running top-K (exact given monotone
   profiles, opt-in via ``SearchConfig.prune_to_top_k``).**  Any plan's
   cost >= its GPipe execution term >= ``(B-1)*max_lens + sum_lens``, and
   every partition processes all L layers exactly once, so
   ``sum_lens >= W_min`` (the fastest possible one-microbatch full-model
   time) and ``max_lens >= W_min/S``.  Candidates whose bound already
   exceeds the K-th best cost seen cannot enter the top K and are skipped.
   Exactness assumption: per-layer profile times are non-decreasing in
   batch size (``W_min`` is taken at the smallest profiled bs) — true of
   real measurements and the synthesizer; the returned TOP-K ranking then
   matches exhaustive search, only the tail beyond K is dropped.

3. **Tightened relaxation bound (default on via
   ``SearchConfig.tight_bound``, exact).**  After the stock bound (2)
   passes, the pruner consults the exact backend's admissible
   ``RelaxationBound`` (search/exact.py) — the execution floor plus
   step-overhead / fb-sync / optimizer floors and the mbs-feasibility
   cap — through the ``bound_fn`` hook.  Admissibility gives the same
   top-K guarantee as (2) while skipping strictly more classes
   (``prune.bound.tight`` counter); disabled under ``strict_compat``
   like the stock bound.

4. **Beam patience (opt-in via ``SearchConfig.beam_patience``, INEXACT).**
   Each (node_sequence, stage_count) class stops after N consecutive
   candidates that failed to enter the running top K — an anytime beam
   for scales where even the bounded walk is too slow.  Patience is
   keyed on the RAW (node_sequence, stage_count) pair even under
   symmetry collapse, so collapsed and uncollapsed searches stay
   byte-identical.
"""
from __future__ import annotations

import heapq
from typing import Iterator, Sequence

from metis_tpu.cluster.spec import ClusterSpec
from metis_tpu.core.config import ModelSpec, SearchConfig
from metis_tpu.profiles.store import ProfileStore


def fastest_full_model_ms(
    profiles: ProfileStore,
    device_types: Sequence[str],
    max_tp: int,
    cp_divisor: int = 1,
) -> float:
    """``W_min``: lower bound on one microbatch's full-model fwd+bwd time —
    per layer, the fastest profiled (type, tp) at the smallest profiled
    batch, divided by the largest context-parallel degree in the search."""
    per_layer: list[float] | None = None
    for t in device_types:
        by_tp: dict[int, int] = {}
        for (_, tp, bs) in profiles.configs(t):
            if tp <= max_tp:
                by_tp[tp] = min(by_tp.get(tp, bs), bs)
        for tp, bs in by_tp.items():
            times = profiles.get(t, tp, bs).layer_times_ms
            if per_layer is None:
                per_layer = list(times)
            else:
                per_layer = [min(a, b) for a, b in zip(per_layer, times)]
    if per_layer is None:
        return 0.0
    return sum(per_layer) / max(cp_divisor, 1)


def fastest_full_model_by_bs(
    profiles: ProfileStore,
    device_types: Sequence[str],
    max_tp: int,
    cp_divisor: int = 1,
) -> dict[int, float]:
    """``W[bs]`` per profiled batch size: the fastest one-microbatch
    full-model time when every stage's microbatch is >= ``bs`` — a much
    tighter execution bound than W[1] for plans whose group sizes force
    large microbatches."""
    by_bs: dict[int, list[float]] = {}
    for t in device_types:
        for (_, tp, bs) in profiles.configs(t):
            if tp > max_tp:
                continue
            times = profiles.get(t, tp, bs).layer_times_ms
            cur = by_bs.get(bs)
            if cur is None:
                by_bs[bs] = list(times)
            else:
                by_bs[bs] = [min(a, b) for a, b in zip(cur, times)]
    return {bs: sum(v) / max(cp_divisor, 1) for bs, v in by_bs.items()}


class ExecutionFloor:
    """W tables + the all-schedules execution lower bound, factored out of
    ``SearchPruner`` so the exact backend's relaxation bound
    (search/exact.RelaxationBound) provably shares the same floor
    arithmetic — bound math and prune math can never drift.

    ``profiles`` decides which view the tables read: SearchPruner passes
    the raw store it was built with (its historical behavior);
    RelaxationBound passes the estimator's post-affine view so the floor
    matches what candidates are actually priced with."""

    def __init__(self, config: SearchConfig, cluster: ClusterSpec,
                 profiles: ProfileStore, model: ModelSpec):
        self.gbs = config.gbs
        # schedule search admits interleaved plans whose execution can
        # undercut the gpipe fill-drain — the bound must floor at the
        # interleaved schedule's own minimum or it would prune true top-K
        # members (cost/schedule.py)
        self._schedule_search = (config.enable_schedule_search
                                 and not config.strict_compat
                                 and model.num_experts == 0)
        from metis_tpu.cost.schedule import REMAT_FWD_FRACTION

        # the interleaved-floor bound must use the SAME remat fraction the
        # estimator prices with, or a calibrated r < 1/3 would let true
        # top-K members be pruned
        self._remat = (config.remat_fwd_fraction
                       if config.remat_fwd_fraction is not None
                       else REMAT_FWD_FRACTION)
        cp_div = (config.max_cp_degree
                  if (config.enable_cp and not config.strict_compat
                      and model.num_experts == 0) else 1)
        self.w_min = fastest_full_model_ms(
            profiles, cluster.device_types, config.max_profiled_tp, cp_div)
        self._w_by_bs = fastest_full_model_by_bs(
            profiles, cluster.device_types, config.max_profiled_tp, cp_div)
        self._w_bs_sorted = sorted(self._w_by_bs)

    def w_at(self, mbs: int) -> float:
        """W at the largest profiled bs <= mbs (monotone-time assumption).

        Below the sweep, W[smallest] would be an OVER-estimate (time is
        increasing in bs) and could prune true top-K members; scale it by
        mbs/smallest instead — per-sample time only grows as bs shrinks
        (fixed per-launch overhead), so time(mbs) >= time(smallest) *
        mbs/smallest is a genuine lower bound and the exactness guarantee
        of prune_to_top_k holds even when the sweep starts above bs=1."""
        import bisect

        if not self._w_bs_sorted:
            return self.w_min
        smallest = self._w_bs_sorted[0]
        if mbs < smallest:
            return self._w_by_bs[smallest] * (mbs / smallest)
        i = bisect.bisect_right(self._w_bs_sorted, mbs) - 1
        return self._w_by_bs[self._w_bs_sorted[i]]

    def bound(self, g_max: int, num_stages: int, batches: int) -> float:
        """Execution >= (B-1)*max_lens + sum_lens; every stage's microbatch
        is >= gbs/(group*B) (dp only shrinks under escalation), so the
        full-model pass costs >= W[mbs_floor] where mbs_floor comes from
        the LARGEST group (smallest per-stage microbatch).

        With schedule search on, the interleaved schedule's execution
        (``schedule_execution_ms``) can undercut the gpipe fill-drain —
        its own floor is ``exec > (1+r) * B * max_lens`` (ticks exceed
        vs*S per group, each >= max_lens/vs), so the all-schedules bound
        is the minimum of the two."""
        mbs_floor = max(1, (self.gbs // g_max) // batches)
        # w_at covers every case: w_min when the by-bs table is empty,
        # the scaled-down bound below the sweep, the table lookup above it
        # (w_min <= W[bs] for all bs, so a separate max() floor is dead).
        w = self.w_at(mbs_floor)
        gpipe_lb = (batches - 1) * w / num_stages + w
        if not self._schedule_search:
            return gpipe_lb
        interleaved_floor = (
            (1 + self._remat) * batches * w / num_stages)
        return min(gpipe_lb, interleaved_floor)


class SearchPruner:
    """Running top-K tracker + the candidate filters.

    ``admit(inter)`` is called per inter-stage candidate BEFORE the (much
    more expensive) intra expansion; ``record(total_ms)`` after each costed
    plan; ``composition_batches``/``class_dead`` let the pruned generator
    (``pruned_inter_stage_plans``) filter whole (composition, batches)
    classes before arrangements are even expanded.  The doom fast-path runs
    unconditionally; the bound and beam filters only when configured."""

    def __init__(self, config: SearchConfig, cluster: ClusterSpec,
                 profiles: ProfileStore, model: ModelSpec,
                 counters=None, bound_fn=None, scorer=None):
        # optional core.trace.Counters: prune-family accounting for the
        # flight recorder (``prune.doom``/``prune.bound``/``prune.beam``
        # mirror num_doomed/num_bounded/num_beamed; ``prune.bound.tight``
        # counts the bound_fn's extra catches within num_bounded); None =
        # tracing off, not even a dict add in the hot filters
        self._counters = counters
        # optional tighter admissible lower bound ``(g_max, num_stages,
        # batches) -> ms`` (search/exact.RelaxationBound): consulted AFTER
        # the stock execution bound passes, so it only ever prunes more.
        # Must be admissible — a true lower bound on every plan in the
        # (composition ceiling, stage count, batches) class — or the
        # prune_to_top_k exactness guarantee breaks.
        self._bound_fn = bound_fn
        # optional cost/uncertainty.RiskScorer: when set, ``record``
        # keeps the top-K heap in SCORE space (total * tail factor for
        # the candidate's device types) instead of point space.  Scores
        # are >= the point total by construction (factors clamped at
        # 1.0), so the point-cost lower bounds compared against the
        # score-space kth best prune strictly less than in point mode —
        # never wrongly.  None (the default) is byte-identical to the
        # pre-uncertainty pruner.
        self._scorer = scorer
        self.max_bs = config.max_profiled_bs
        self.gbs = config.gbs
        self.top_k = (config.prune_to_top_k
                      if not config.strict_compat else None)
        self.beam_patience = (config.beam_patience
                              if self.top_k is not None else None)
        self.num_doomed = 0
        self.num_bounded = 0
        self.num_beamed = 0
        self._heap: list[float] = []  # negated costs; [0] = -(kth best)
        self._patience: dict[tuple, int] = {}
        self._improved = False
        self._floor: ExecutionFloor | None = None
        self.w_min = 0.0
        if self.top_k is not None:
            self._floor = ExecutionFloor(config, cluster, profiles, model)
            self.w_min = self._floor.w_min

    def _w_at(self, mbs: int) -> float:
        return self._floor.w_at(mbs) if self._floor is not None else 0.0

    def _exec_lower_bound(self, g_max: int, num_stages: int,
                          batches: int) -> float:
        return self._floor.bound(g_max, num_stages, batches)

    def composition_batches(
        self, composition: Sequence[int], num_stages: int,
        batch_options: Sequence[int],
    ) -> list[int]:
        """Feasible microbatch counts for one (non-decreasing) composition:
        doom-filtered (exact), then bound-filtered against the running kth
        best.  Composition-level — shared by every arrangement and type
        permutation, so the filter runs once per composition, not once per
        candidate."""
        g_min, g_max = composition[0], composition[-1]
        kth = self._kth_best()
        out = []
        for batches in batch_options:
            if (self.gbs // g_min) // batches > self.max_bs:
                # doom: smallest-group stage over max_bs forever
                self.num_doomed += 1  # counts (composition, B) classes
                if self._counters is not None:
                    self._counters.inc("prune.doom")
                continue
            if self.top_k is not None and kth != float("inf"):
                if self._exec_lower_bound(
                        g_max, num_stages, batches) > kth:
                    self.num_bounded += 1  # counts (composition, B) classes
                    if self._counters is not None:
                        self._counters.inc("prune.bound")
                    continue
                if (self._bound_fn is not None
                        and self._bound_fn(
                            g_max, num_stages, batches) > kth):
                    self.num_bounded += 1
                    if self._counters is not None:
                        self._counters.inc("prune.bound.tight")
                    continue
            out.append(batches)
        return out

    def _class_key(self, node_sequence, num_stages: int) -> tuple:
        # keyed on the RAW sequence: symmetry replay drives record() with
        # bit-identical costs per permutation, so per-sequence budgets make
        # the collapsed beam walk byte-identical to the uncollapsed one.
        # (A canonicalized shared budget — tried first — kills classes
        # earlier under collapse and changes the ranking.)
        return (node_sequence, num_stages)

    def class_dead(self, node_sequence, num_stages: int) -> bool:
        """Beam: whether a (placement, stage-count) class exhausted its
        patience (checked inside the pruned generator so dead classes skip
        arrangement expansion entirely)."""
        if self.beam_patience is None:
            return False
        return (self._patience.get(
            self._class_key(node_sequence, num_stages), 0)
            > self.beam_patience)

    @property
    def active(self) -> bool:
        """Whether the opt-in (bound/beam) pruning is on — selects the
        composition-level generator in plan_hetero."""
        return self.top_k is not None

    def _kth_best(self) -> float:
        if self.top_k is None or len(self._heap) < self.top_k:
            return float("inf")
        return -self._heap[0]

    def admit(self, inter) -> bool:
        groups = inter.device_groups
        g_min, g_max = min(groups), max(groups)
        # 1. doom fast-path: smallest-group stage over max_bs at full dp
        #    stays over under every escalation (same floor-division
        #    arithmetic as classify_strategies — dp only shrinks, so this
        #    stage's mbs only grows)
        if (inter.gbs // g_min) // inter.batches > self.max_bs:
            self.num_doomed += 1
            if self._counters is not None:
                self._counters.inc("prune.doom")
            return False
        if self.top_k is None or self.w_min <= 0:
            return True
        # 2. execution lower bound vs the running kth best, then the
        #    optional tighter relaxation bound (only when the cheap stock
        #    bound failed to prune — it strictly adds catches)
        kth = self._kth_best()
        if kth != float("inf"):
            if self._exec_lower_bound(
                    g_max, inter.num_stages, inter.batches) > kth:
                self.num_bounded += 1
                if self._counters is not None:
                    self._counters.inc("prune.bound")
                return False
            if (self._bound_fn is not None
                    and self._bound_fn(
                        g_max, inter.num_stages, inter.batches) > kth):
                self.num_bounded += 1
                if self._counters is not None:
                    self._counters.inc("prune.bound.tight")
                return False
        # 3. anytime beam: stop a (placement, stage-count) class after
        #    beam_patience consecutive non-improving candidates
        if self.beam_patience is not None:
            key = self._class_key(inter.node_sequence, inter.num_stages)
            if self._patience.get(key, 0) > self.beam_patience:
                self.num_beamed += 1
                if self._counters is not None:
                    self._counters.inc("prune.beam")
                return False
        return True

    def begin_candidate(self) -> None:
        self._improved = False

    def record(self, total_ms: float, inter=None) -> None:
        if self.top_k is None:
            return
        if self._scorer is not None and inter is not None:
            total_ms = self._scorer.score(total_ms, inter.node_sequence)
        if len(self._heap) < self.top_k:
            heapq.heappush(self._heap, -total_ms)
            self._improved = True
        elif total_ms < -self._heap[0]:
            heapq.heapreplace(self._heap, -total_ms)
            self._improved = True

    def end_candidate(self, inter) -> None:
        if self.beam_patience is None:
            return
        key = self._class_key(inter.node_sequence, inter.num_stages)
        if self._improved:
            self._patience[key] = 0
        else:
            self._patience[key] = self._patience.get(key, 0) + 1

    @property
    def num_pruned(self) -> int:
        return self.num_doomed + self.num_bounded + self.num_beamed


def pruned_inter_stage_plans(
    device_types: Sequence[str],
    num_devices: int,
    gbs: int,
    num_layers: int,
    pruner: SearchPruner,
    variance: float = 1.0,
    max_permute_len: int = 6,
    counters=None,
) -> Iterator:
    """Inter-stage enumeration with COMPOSITION-level pruning — the flat
    walk (``inter_stage_plans``) materializes placement x arrangement x
    batches candidates before any filter can run (tens of millions at 256
    devices with small-group variance; iteration alone blows the budget).
    Here doom + bound filters run per (composition, batches) — shared by
    every arrangement and type permutation — and beam-dead classes skip
    arrangement expansion entirely.  Same candidate SET as the flat walk
    minus pruner-filtered entries; order differs (stage count outer,
    batches ascending), which is invisible behind the final cost sort."""
    from itertools import permutations as _perms

    from metis_tpu.core.types import InterStagePlan, divisors
    from metis_tpu.search.device_groups import arrangements_of_composition
    from metis_tpu.search.inter_stage import stage_compositions

    batch_options = list(divisors(gbs))  # ascending: low-bubble plans first
    type_perms = list(_perms(sorted(set(device_types))))
    for num_stage, comp in stage_compositions(
            num_devices, num_layers, variance=variance):
        feasible = pruner.composition_batches(
            comp, num_stage, batch_options)
        if not feasible:
            continue
        arrangements = None  # expand lazily, reuse across type perms
        for node_sequence in type_perms:
            if pruner.class_dead(node_sequence, num_stage):
                continue
            if arrangements is None:
                arrangements = list(
                    arrangements_of_composition(comp, max_permute_len))
            for groups in arrangements:
                for batches in feasible:
                    if counters is not None:
                        counters.inc("inter_enumerated")
                    yield InterStagePlan(
                        node_sequence=node_sequence,
                        device_groups=groups,
                        batches=batches,
                        gbs=gbs,
                    )
