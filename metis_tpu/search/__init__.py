from metis_tpu.search.multiperm import multiset_permutations, count_multiset_permutations
from metis_tpu.search.device_groups import (
    power_of_two_shapes,
    nondecreasing_compositions,
    merge_for_permute_cap,
    arrangements_of_composition,
    enumerate_device_groups,
)
from metis_tpu.search.uniform import uniform_plans, grid_degrees
from metis_tpu.search.inter_stage import inter_stage_plans
from metis_tpu.search.intra_stage import (
    PartitionResult,
    StageEvaluator,
    LayerPartitioner,
    initial_strategies,
    strategies_valid,
    escalate_dp_to_tp,
    intra_stage_plans,
)

__all__ = [
    "multiset_permutations",
    "count_multiset_permutations",
    "power_of_two_shapes",
    "nondecreasing_compositions",
    "merge_for_permute_cap",
    "arrangements_of_composition",
    "enumerate_device_groups",
    "uniform_plans",
    "grid_degrees",
    "inter_stage_plans",
    "PartitionResult",
    "StageEvaluator",
    "LayerPartitioner",
    "initial_strategies",
    "strategies_valid",
    "escalate_dp_to_tp",
    "intra_stage_plans",
]
