"""Intra-stage strategy search: per-stage (dp, tp) under memory pressure.

The reference's most intricate control flow (``search_space/plan.py:178-268``,
SURVEY.md §3.3): start every stage fully data-parallel, and when the layer
balancer reports memory pressure, convert the most-pressured stage's dp to tp
(halve dp, double tp) and retry.  Search and feasibility-repair interleave —
escalation order keys on the per-stage memory headroom from the previous
(possibly failed) partition attempt.

Policy parity notes (each mirrors a reference behavior):
- a strategy set is valid iff every stage's microbatch is >= 1, within the
  profiled batch range, and tp within the profiled tp range (``plan.py:238-249``);
- after a partition that succeeded on the first attempt (num_repartition == 1)
  the search stops — good enough, no need to trade dp for tp (``plan.py:193-194``);
- a successful-but-repaired partition (num_repartition > 1) keeps escalating
  in search of a strategy that doesn't need repair (``plan.py:192-226``);
- with no memory feedback yet, stages escalate largest-dp-first
  (default pressure 1/dp, ``plan.py:255``).
"""
from __future__ import annotations

from dataclasses import dataclass
from itertools import product
from typing import Iterator, Protocol, Sequence

from metis_tpu.core.types import InterStagePlan, IntraStagePlan, Strategy


@dataclass(frozen=True)
class PartitionResult:
    """Outcome of one layer-partition attempt."""

    partition: tuple[int, ...] | None  # None => infeasible
    attempts: int                      # 1 = feasible without repair
    memory_state: tuple[float, ...] | None  # per-stage capacity - demand (MB)


class StageEvaluator(Protocol):
    """Per-stage memory capacity and normalized compute performance
    (implemented by metis_tpu.balance.StagePerformanceModel)."""

    def memory_capacity(self, plan: InterStagePlan) -> list[float]: ...

    def compute_performance(
        self, plan: InterStagePlan, strategies: Sequence[Strategy]
    ) -> list[float]: ...


class LayerPartitioner(Protocol):
    """Layer->stage partitioning with memory repair
    (implemented by metis_tpu.balance.LayerBalancer)."""

    def partition(
        self,
        plan: InterStagePlan,
        strategies: Sequence[Strategy],
        compute_performance: Sequence[float],
        memory_capacity: Sequence[float],
    ) -> PartitionResult: ...


def initial_strategies(
    plan: InterStagePlan,
    cp: int = 1,
    cp_eligible: Sequence[bool] | None = None,
    ep: int = 1,
    zero: int = 0,
    sp: bool = False,
) -> tuple[Strategy, ...] | None:
    """Every stage starts fully data-parallel (``plan.py:231-236``).

    With ``cp > 1`` each eligible stage dedicates a cp-sized sub-axis to ring
    attention (dp = group/cp, tp = 1); ineligible stages (heterogeneous device
    mix — ring attention needs uniform block timing) stay cp=1.  With
    ``ep > 1`` each stage whose dp divides evenly shards experts over ep-sized
    sub-groups of its data ranks (Strategy docstring: ep rides inside dp).
    Returns None when no stage can actually take the requested axis
    (degenerate family — identical to a lower-degree search).
    """
    out = []
    any_cp, any_ep, any_zero = False, False, False
    for stage_id, g in enumerate(plan.device_groups):
        eligible = cp_eligible is None or cp_eligible[stage_id]
        stage_cp = cp if (cp > 1 and eligible and g % cp == 0) else 1
        any_cp |= stage_cp > 1
        dp = g // stage_cp
        stage_ep = ep if (ep > 1 and dp % ep == 0) else 1
        any_ep |= stage_ep > 1
        # ZeRO needs >1 data rank to shard over
        stage_zero = zero if dp * stage_cp > 1 else 0
        any_zero |= stage_zero > 0
        out.append(Strategy(dp=dp, tp=1, sp=sp, cp=stage_cp, ep=stage_ep,
                            zero=stage_zero))
    if cp > 1 and not any_cp:
        return None
    if ep > 1 and not any_ep:
        return None
    if zero > 0 and not any_zero:
        return None
    return tuple(out)


def strategies_valid(
    plan: InterStagePlan,
    strategies: Sequence[Strategy],
    max_tp: int,
    max_bs: int,
) -> bool:
    for s in strategies:
        mbs = plan.gbs // s.dp // plan.batches
        if mbs == 0 or mbs > max_bs:
            return False
        if s.tp > max_tp:
            return False
    return True


def escalate_dp_to_tp(
    strategies: Sequence[Strategy],
    memory_state: Sequence[float] | None,
) -> tuple[Strategy, ...] | None:
    """Halve dp / double tp on the most memory-pressured stage that still has
    dp to give.  Returns None when no stage can escalate (search exhausted)."""
    # Truthiness (not `is not None`): an empty memory_state means "no per-stage
    # feedback", same as None — matches the reference guard (plan.py:252-255).
    pressure = (
        list(memory_state) if memory_state else [1.0 / s.dp for s in strategies]
    )
    # search-hot (~1M calls/search): bound __getitem__ beats a lambda key
    order = sorted(range(len(strategies)), key=pressure.__getitem__)
    out = list(strategies)
    for stage_id in order:
        s = out[stage_id]
        # ep must keep dividing dp after the halving (ep rides inside dp)
        if s.dp != 1 and (s.ep <= 1 or (s.dp // 2) % s.ep == 0):
            # zero degenerates to 0 when no data ranks remain to shard over
            new_zero = s.zero if (s.dp // 2) * s.cp > 1 else 0
            out[stage_id] = Strategy(dp=s.dp // 2, tp=s.tp * 2, sp=s.sp,
                                     cp=s.cp, ep=s.ep, zero=new_zero)
            return tuple(out)
    return None


def intra_stage_plans(
    plan: InterStagePlan,
    evaluator: StageEvaluator,
    partitioner: LayerPartitioner,
    max_tp: int,
    max_bs: int,
    cp_degrees: Sequence[int] = (1,),
    cp_eligible: Sequence[bool] | None = None,
    ep_degrees: Sequence[int] = (1,),
    zero_stages: Sequence[int] = (0,),
    sp_variants: Sequence[bool] = (False,),
) -> Iterator[IntraStagePlan]:
    """Yield feasible intra-stage plans for one inter-stage candidate.

    ``cp_degrees`` x ``ep_degrees`` x ``zero_stages`` x ``sp_variants``
    extend the reference's (dp, tp) space with context-parallel,
    expert-parallel, ZeRO, and sequence-parallel families (net-new,
    SURVEY.md §5): for each combination the same escalation runs with the
    extra axes carved out of every eligible stage.  The cost estimator ranks
    the families against each other.  sp is a no-op at tp=1, so the sp=True
    family suppresses tp=1 yields (duplicates of the sp=False family) and
    keeps escalating toward tp>1 shapes where sp actually pays.
    """
    capacity: list[float] | None = None  # strategy-independent; resolve once
    for cp, ep, zero, sp in product(cp_degrees, ep_degrees, zero_stages,
                                    sp_variants):
        strategies = initial_strategies(plan, cp, cp_eligible, ep, zero, sp)
        memory_state: tuple[float, ...] | None = None

        while strategies is not None:
            if strategies_valid(plan, strategies, max_tp, max_bs):
                if capacity is None:
                    capacity = evaluator.memory_capacity(plan)
                performance = evaluator.compute_performance(plan, strategies)
                result = partitioner.partition(plan, strategies, performance, capacity)
                memory_state = result.memory_state
                degenerate_sp = sp and all(s.tp == 1 for s in strategies)
                if result.partition is not None and not degenerate_sp:
                    yield IntraStagePlan(
                        strategies=strategies,
                        layer_partition=result.partition,
                        memory_state=result.memory_state or (),
                        num_repartition=result.attempts,
                    )
                    if result.attempts == 1:
                        break  # this family is satisfied; next
            strategies = escalate_dp_to_tp(strategies, memory_state)
