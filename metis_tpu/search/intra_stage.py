"""Intra-stage strategy search: per-stage (dp, tp) under memory pressure.

The reference's most intricate control flow (``search_space/plan.py:178-268``,
SURVEY.md §3.3): start every stage fully data-parallel, and when the layer
balancer reports memory pressure, convert the most-pressured stage's dp to tp
(halve dp, double tp) and retry.  Search and feasibility-repair interleave —
escalation order keys on the per-stage memory headroom from the previous
(possibly failed) partition attempt.

Policy parity notes (each mirrors a reference behavior):
- a strategy set is valid iff every stage's microbatch is >= 1, within the
  profiled batch range, and tp within the profiled tp range (``plan.py:238-249``);
- after a partition that succeeded on the first attempt (num_repartition == 1)
  the search stops — good enough, no need to trade dp for tp (``plan.py:193-194``);
- a successful-but-repaired partition (num_repartition > 1) keeps escalating
  in search of a strategy that doesn't need repair (``plan.py:192-226``);
- with no memory feedback yet, stages escalate largest-dp-first
  (default pressure 1/dp, ``plan.py:255``).
"""
from __future__ import annotations

from dataclasses import dataclass
from functools import lru_cache
from itertools import product
from typing import Iterator, Protocol, Sequence

from metis_tpu.core.types import InterStagePlan, IntraStagePlan, Strategy


@dataclass(frozen=True)
class PartitionResult:
    """Outcome of one layer-partition attempt."""

    partition: tuple[int, ...] | None  # None => infeasible
    attempts: int                      # 1 = feasible without repair
    memory_state: tuple[float, ...] | None  # per-stage capacity - demand (MB)


class StageEvaluator(Protocol):
    """Per-stage memory capacity and normalized compute performance
    (implemented by metis_tpu.balance.StagePerformanceModel)."""

    def memory_capacity(self, plan: InterStagePlan) -> list[float]: ...

    def compute_performance(
        self, plan: InterStagePlan, strategies: Sequence[Strategy]
    ) -> list[float]: ...


class LayerPartitioner(Protocol):
    """Layer->stage partitioning with memory repair
    (implemented by metis_tpu.balance.LayerBalancer)."""

    def partition(
        self,
        plan: InterStagePlan,
        strategies: Sequence[Strategy],
        compute_performance: Sequence[float],
        memory_capacity: Sequence[float],
    ) -> PartitionResult: ...


def initial_strategies(
    plan: InterStagePlan,
    cp: int = 1,
    cp_eligible: Sequence[bool] | None = None,
    ep: int = 1,
    zero: int = 0,
    sp: bool = False,
    cp_mode: str = "ring",
) -> tuple[Strategy, ...] | None:
    """Every stage starts fully data-parallel (``plan.py:231-236``).

    With ``cp > 1`` each eligible stage dedicates a cp-sized sub-axis to ring
    attention (dp = group/cp, tp = 1); ineligible stages (heterogeneous device
    mix — ring attention needs uniform block timing) stay cp=1.  With
    ``ep > 1`` each stage whose dp divides evenly shards experts over ep-sized
    sub-groups of its data ranks (Strategy docstring: ep rides inside dp).
    Returns None when no stage can actually take the requested axis
    (degenerate family — identical to a lower-degree search).
    """
    # search-hot: the result depends only on the group sizes + axis degrees,
    # which repeat across the thousands of inter-stage plans sharing a
    # device-group composition — memoize on exactly those
    return _initial_strategies(
        plan.device_groups, cp,
        None if cp_eligible is None else tuple(cp_eligible), ep, zero, sp,
        cp_mode)


@lru_cache(maxsize=65536)
def _initial_strategies(
    device_groups: tuple[int, ...],
    cp: int,
    cp_eligible: tuple[bool, ...] | None,
    ep: int,
    zero: int,
    sp: bool,
    cp_mode: str = "ring",
) -> tuple[Strategy, ...] | None:
    out = []
    any_cp, any_ep, any_zero = False, False, False
    for stage_id, g in enumerate(device_groups):
        eligible = cp_eligible is None or cp_eligible[stage_id]
        stage_cp = cp if (cp > 1 and eligible and g % cp == 0) else 1
        any_cp |= stage_cp > 1
        dp = g // stage_cp
        stage_ep = ep if (ep > 1 and dp % ep == 0) else 1
        any_ep |= stage_ep > 1
        # ZeRO needs >1 data rank to shard over
        stage_zero = zero if dp * stage_cp > 1 else 0
        any_zero |= stage_zero > 0
        out.append(Strategy(dp=dp, tp=1, sp=sp, cp=stage_cp, ep=stage_ep,
                            zero=stage_zero,
                            cp_mode=cp_mode if stage_cp > 1 else "ring"))
    if cp > 1 and not any_cp:
        return None
    if ep > 1 and not any_ep:
        return None
    if zero > 0 and not any_zero:
        return None
    return tuple(out)


VALID, RETRY, DOOMED = "valid", "retry", "doomed"


class SchedulePartitioner(Protocol):
    """Even-split + schedule-aware memory feasibility
    (implemented by metis_tpu.balance.LayerBalancer.schedule_partition)."""

    def schedule_partition(
        self,
        plan: InterStagePlan,
        strategies: Sequence[Strategy],
        memory_capacity: Sequence[float],
        schedule: str,
        virtual_stages: int,
    ) -> PartitionResult: ...


def schedule_intra_plans(
    plan: InterStagePlan,
    evaluator: StageEvaluator,
    partitioner: SchedulePartitioner,
    max_tp: int,
    max_bs: int,
    schedule: str,
    virtual_stages: int = 1,
    num_blocks: int | None = None,
    types_uniform: bool = True,
) -> Iterator[IntraStagePlan]:
    """Yield intra plans for one pipeline-SCHEDULE family (1f1b /
    interleaved) of an inter-stage candidate — a searched axis beyond the
    reference's GPipe-only pricing (cost/schedule.py).

    These schedules run on the shard_map pipeline executor
    (``execution/builder.py``), which demands a rectangular plan: equal
    device groups, ONE strategy shape, the canonical even block split, and
    a single device type (SPMD lockstep — mixed chip speeds would idle the
    faster type every tick, and the mesh admits no per-stage profiles).
    Escalation is therefore uniform: all stages trade dp for tp together.
    Memory feasibility uses the schedule's true activation peak
    (``LayerBalancer.schedule_partition``) — the whole point of the 1f1b
    family is admitting memory-tight plans the gpipe footprint rejects.
    """
    from metis_tpu.cost.schedule import schedule_valid

    if len(set(plan.device_groups)) != 1 or not types_uniform:
        return
    if not schedule_valid(schedule, plan.num_stages, plan.batches,
                          virtual_stages, num_blocks):
        return
    group = plan.device_groups[0]
    strategies: tuple[Strategy, ...] | None = tuple(
        Strategy(dp=group, tp=1) for _ in plan.device_groups)
    capacity: list[float] | None = None
    while strategies is not None:
        verdict = classify_strategies(plan, strategies, max_tp, max_bs)
        if verdict is DOOMED:
            break
        if verdict is VALID:
            if capacity is None:
                capacity = evaluator.memory_capacity(plan)
            result = partitioner.schedule_partition(
                plan, strategies, capacity, schedule, virtual_stages)
            if result.partition is not None:
                yield IntraStagePlan(
                    strategies=strategies,
                    layer_partition=result.partition,
                    memory_state=result.memory_state or (),
                    num_repartition=result.attempts,
                    schedule=schedule,
                    virtual_stages=virtual_stages,
                )
                break  # feasible at this dp — higher tp never cheaper here
        s0 = strategies[0]
        strategies = (
            tuple(Strategy(dp=s0.dp // 2, tp=s0.tp * 2) for _ in strategies)
            if s0.dp > 1 else None)


def classify_strategies(
    plan: InterStagePlan,
    strategies: Sequence[Strategy],
    max_tp: int,
    max_bs: int,
    num_heads: int | None = None,
) -> str:
    """One scan, three outcomes for the search-hot escalation loop:

    - ``VALID`` — every stage's microbatch is in [1, max_bs] and tp within
      the profiled range (the reference validity rule, ``plan.py:238-249``);
    - ``DOOMED`` — NO amount of further dp->tp escalation can reach
      validity, so the family can stop early (observably identical to
      escalating to exhaustion — the reference loop grinds on regardless,
      ``plan.py:192-226``, but yields nothing on the way).  Escalation only
      shrinks a stage's dp (growing its microbatch) and only grows its tp,
      so a stage whose mbs already exceeds ``max_bs`` or whose tp exceeds
      ``max_tp`` is unrecoverable.  With ``num_heads`` given (callers pass
      the binding head count — for GQA the gcd of Q and KV heads, since the
      a2a split must divide both), an a2a cp stage whose heads don't split
      evenly over ``tp * cp`` is also doom: both factors are powers of two,
      so once ``2^k`` stops dividing the head count no further doubling
      recovers — and the a2a cost/execution path assumes even head splits
      (no padding term, ``ops/ulysses.py``);
    - ``RETRY`` — invalid but recoverable (some stage's mbs == 0: halving
      its dp grows the microbatch).
    """
    verdict = VALID
    for s in strategies:
        mbs = plan.gbs // s.dp // plan.batches
        if mbs > max_bs or s.tp > max_tp:
            return DOOMED
        if (num_heads is not None and s.cp > 1 and s.cp_mode == "a2a"
                and num_heads % (s.tp * s.cp) != 0):
            return DOOMED
        if mbs == 0:
            verdict = RETRY
    return verdict


def strategies_valid(
    plan: InterStagePlan,
    strategies: Sequence[Strategy],
    max_tp: int,
    max_bs: int,
) -> bool:
    return classify_strategies(plan, strategies, max_tp, max_bs) == VALID


def escalate_dp_to_tp(
    strategies: Sequence[Strategy],
    memory_state: Sequence[float] | None,
) -> tuple[Strategy, ...] | None:
    """Halve dp / double tp on the most memory-pressured stage that still has
    dp to give.  Returns None when no stage can escalate (search exhausted)."""
    # search-hot (~1M calls/search): the full pressure ordering is only used
    # to take the FIRST escalatable stage, so an O(n) stable argmin over the
    # escalatable stages replaces the sort (+ its list allocations).
    # Truthiness (not `is not None`): an empty memory_state means "no per-stage
    # feedback", same as None — matches the reference guard (plan.py:252-255).
    best_id, best_p = -1, None
    for stage_id, s in enumerate(strategies):
        # ep must keep dividing dp after the halving (ep rides inside dp)
        if s.dp == 1 or (s.ep > 1 and (s.dp // 2) % s.ep != 0):
            continue
        p = memory_state[stage_id] if memory_state else 1.0 / s.dp
        if best_p is None or p < best_p:  # strict <: stable ties by index
            best_id, best_p = stage_id, p
    if best_id < 0:
        return None
    out = list(strategies)
    s = out[best_id]
    # zero degenerates to 0 when no data ranks remain to shard over
    new_zero = s.zero if (s.dp // 2) * s.cp > 1 else 0
    out[best_id] = Strategy(dp=s.dp // 2, tp=s.tp * 2, sp=s.sp,
                            cp=s.cp, ep=s.ep, zero=new_zero,
                            cp_mode=s.cp_mode)
    return tuple(out)


# Escalation-prefix memo for the base (cp=1, ep=1, zero=0, sp=False) family:
# until the first non-RETRY verdict no partition has run, so memory_state is
# None and the walk — classify, escalate on 1/dp pressure, repeat — is a pure
# function of (device_groups, gbs, batches, max_tp, max_bs).  Thousands of
# inter plans share the same few compositions, so the leading RETRY
# iterations (mbs == 0 shapes) collapse to one dict hit.  The cached tuple
# is exactly what the uncached walk would hold when it first leaves RETRY
# (or None if it exhausts first), so downstream behavior is identical.
_BASE_WALK_MEMO: dict[tuple, tuple[Strategy, ...] | None] = {}
_BASE_WALK_MAX = 200_000


def intra_stage_plans(
    plan: InterStagePlan,
    evaluator: StageEvaluator,
    partitioner: LayerPartitioner,
    max_tp: int,
    max_bs: int,
    cp_degrees: Sequence[int] = (1,),
    cp_eligible: Sequence[bool] | None = None,
    ep_degrees: Sequence[int] = (1,),
    zero_stages: Sequence[int] = (0,),
    sp_variants: Sequence[bool] = (False,),
    cp_modes: Sequence[str] = ("ring",),
    num_heads: int | None = None,
) -> Iterator[IntraStagePlan]:
    """Yield feasible intra-stage plans for one inter-stage candidate.

    ``cp_degrees`` x ``ep_degrees`` x ``zero_stages`` x ``sp_variants``
    extend the reference's (dp, tp) space with context-parallel,
    expert-parallel, ZeRO, and sequence-parallel families (net-new,
    SURVEY.md §5): for each combination the same escalation runs with the
    extra axes carved out of every eligible stage.  The cost estimator ranks
    the families against each other.  sp is a no-op at tp=1, so the sp=True
    family suppresses tp=1 yields (duplicates of the sp=False family) and
    keeps escalating toward tp>1 shapes where sp actually pays.
    """
    capacity: list[float] | None = None  # strategy-independent; resolve once
    for cp, ep, zero, sp, cp_mode in product(cp_degrees, ep_degrees,
                                             zero_stages, sp_variants,
                                             cp_modes):
        if cp == 1 and cp_mode != "ring":
            continue  # mode is meaningless without a cp axis; skip duplicates
        strategies = initial_strategies(plan, cp, cp_eligible, ep, zero, sp,
                                        cp_mode)
        memory_state: tuple[float, ...] | None = None
        if cp == 1 and ep == 1 and zero == 0 and not sp:
            # fast-forward the deterministic RETRY prefix (see _BASE_WALK_MEMO;
            # cp_eligible and num_heads are no-ops at cp == 1)
            wkey = (plan.device_groups, plan.gbs, plan.batches, max_tp, max_bs)
            walked = _BASE_WALK_MEMO.get(wkey, _BASE_WALK_MEMO)
            if walked is _BASE_WALK_MEMO:
                walked = strategies
                while walked is not None and classify_strategies(
                        plan, walked, max_tp, max_bs) is RETRY:
                    walked = escalate_dp_to_tp(walked, None)
                if len(_BASE_WALK_MEMO) > _BASE_WALK_MAX:
                    _BASE_WALK_MEMO.clear()
                _BASE_WALK_MEMO[wkey] = walked
            strategies = walked

        while strategies is not None:
            verdict = classify_strategies(plan, strategies, max_tp, max_bs,
                                          num_heads)
            if verdict is DOOMED:
                break
            if verdict is VALID:
                if capacity is None:
                    capacity = evaluator.memory_capacity(plan)
                performance = evaluator.compute_performance(plan, strategies)
                result = partitioner.partition(plan, strategies, performance, capacity)
                memory_state = result.memory_state
                degenerate_sp = sp and all(s.tp == 1 for s in strategies)
                if result.partition is not None and not degenerate_sp:
                    yield IntraStagePlan(
                        strategies=strategies,
                        layer_partition=result.partition,
                        memory_state=result.memory_state or (),
                        num_repartition=result.attempts,
                    )
                    if result.attempts == 1:
                        break  # this family is satisfied; next
            strategies = escalate_dp_to_tp(strategies, memory_state)
