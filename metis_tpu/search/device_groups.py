"""Device-group enumeration: how many chips each pipeline stage gets.

Re-derivation of the reference's three "key ideas" (``search_space/
device_group.py``):

1. group sizes restricted to powers of two (``gen_device_group_shapes:84-90``)
   — on TPU this is also the hardware-true constraint: a power-of-two group
   maps onto a contiguous ICI sub-torus;
2. a variance knob that discards groups much smaller than the even share
   (``gen_dgroups_for_stages_with_variance:93-98``);
3. a permutation-length cap that merges equal-size smallest groups pairwise
   before permuting stage order, bounding the orderings explosion
   (``permute:7-55``).

The composition enumerator and the merge cap reproduce the reference's
*observable* outputs (oracle-tested against the upstream module in
tests/test_search_parity.py); the implementation is our own.
"""
from __future__ import annotations

from itertools import chain
from typing import Iterator, Sequence

from metis_tpu.search.multiperm import multiset_permutations


def type_equivalence_classes(cluster, profiles) -> dict[str, str]:
    """Map each device type to its class representative under cost symmetry.

    Two types are interchangeable for the planner (AMP-style placement
    symmetry, arXiv 2210.07297) iff NOTHING the cost model reads can tell
    them apart: identical ``DeviceSpec`` cost fields (everything but the
    name), identical per-type node-width sequences (node order is rank
    order, so widths must match position-for-position), identical profiled
    configs with bit-equal ``LayerProfile`` data, and identical
    ``type_meta`` timings.  Swapping two such types inside a
    ``node_sequence`` then reprices to bit-identical floats, which is what
    lets the evaluator cost one representative per class and replay the
    result stream for the equivalent permutations (search/parallel.py).

    The representative is the lexicographically smallest name in the
    class, so the canonical form of a sequence is deterministic.  Clusters
    with no equivalent pair map every type to itself.
    """
    sigs: dict[tuple, list[str]] = {}
    for t in cluster.device_types:
        spec = cluster.devices[t]
        widths = tuple(n.num_devices for n in cluster.nodes
                       if n.device_type == t)
        meta = profiles.type_meta.get(t)
        profile_sig = []
        for (_, tp, bs) in sorted(profiles.configs(t)):
            prof = profiles.get(t, tp, bs)
            profile_sig.append((tp, bs, tuple(prof.layer_times_ms),
                                tuple(prof.layer_memory_mb),
                                prof.fb_sync_ms))
        sig = (
            spec.memory_gb, spec.intra_bw_gbps, spec.inter_bw_gbps,
            spec.hbm_gbps, spec.tier, spec.preemption_rate_per_hr,
            widths,
            None if meta is None else (meta.optimizer_time_ms,
                                       meta.batch_generator_ms),
            tuple(profile_sig),
        )
        sigs.setdefault(sig, []).append(t)
    out: dict[str, str] = {}
    for members in sigs.values():
        rep = min(members)
        for t in members:
            out[t] = rep
    return out


def power_of_two_shapes(num_devices: int) -> list[int]:
    """Allowed per-stage group sizes: 1, 2, 4, ... <= num_devices."""
    shapes = []
    p = 1
    while p <= num_devices:
        shapes.append(p)
        p *= 2
    return shapes


def nondecreasing_compositions(
    num_stages: int, total: int, shapes: Sequence[int]
) -> Iterator[tuple[int, ...]]:
    """All non-decreasing ways to write ``total`` as a sum of ``num_stages``
    values drawn (with repetition) from ``shapes``."""
    shapes = sorted(shapes)
    if not shapes:
        return

    def rec(remaining: int, stages_left: int, min_idx: int) -> Iterator[tuple[int, ...]]:
        if stages_left == 0:
            if remaining == 0:
                yield ()
            return
        for i in range(min_idx, len(shapes)):
            s = shapes[i]
            if s > remaining or s * stages_left > remaining:
                break  # shapes ascending + non-decreasing suffix ⇒ no fit
            if shapes[-1] * (stages_left - 1) < remaining - s:
                continue  # even the largest shape can't absorb the rest
            for rest in rec(remaining - s, stages_left - 1, i):
                yield (s, *rest)

    yield from rec(total, num_stages, 0)


def merge_for_permute_cap(
    composition: Sequence[int], max_permute_len: int
) -> list[tuple[int, ...]]:
    """Bound permutation count by fusing equal-size smallest groups pairwise.

    Takes a non-decreasing composition; returns "super-groups" (tuples of
    original group sizes) whose count is at most ``max_permute_len`` when
    achievable.  Behavioral parity with the reference's ``permute`` merge
    phase, including its two quirks we keep deliberately (oracle-tested):
    it may over-merge (half the smallest groups fuse even when fewer merges
    would do), and after a partial merge the leading group may no longer be
    the smallest.
    """
    groups: list[tuple[int, ...]] = [(g,) for g in composition]
    reduce_target = len(groups) - max_permute_len
    while reduce_target > 0:
        lead = groups[0]
        lead_sum = sum(lead)
        lead_count = 0
        for g in groups:
            if g != lead:
                break
            lead_count += 1
        # Reference's find_num_min (device_group.py:8-12) returns the index of
        # the first non-equal group plus one — i.e. leading-run + 1 unless the
        # whole list is equal.  The over-merge decision keys on that value, so
        # we reproduce it exactly (oracle-tested).
        min_run = lead_count if lead_count == len(groups) else lead_count + 1
        reduce_target = max(reduce_target, min_run // 2)

        merged: list[tuple[int, ...]] = []
        for i in range(0, len(groups), 2):
            if reduce_target <= i // 2:
                merged.extend(groups[i:])
                break
            if i + 1 >= len(groups):
                merged.append(groups[i])
            elif sum(groups[i]) == lead_sum and sum(groups[i + 1]) == lead_sum:
                merged.append(groups[i] + groups[i + 1])
            else:
                merged.append(groups[i])
                merged.append(groups[i + 1])

        groups = merged
        if reduce_target == len(groups) - max_permute_len:
            break  # no further reduction possible
        reduce_target = len(groups) - max_permute_len
    return groups


def arrangements_of_composition(
    composition: Sequence[int], max_permute_len: int
) -> Iterator[tuple[int, ...]]:
    """All stage orderings of one composition, under the permutation cap.

    Super-groups permute as units and are then flattened back to per-stage
    sizes (≅ reference ``permute`` + ``chain`` at ``device_group.py:102-105``).
    """
    groups = merge_for_permute_cap(composition, max_permute_len)
    for perm in multiset_permutations(groups):
        yield tuple(chain.from_iterable(perm))


# Arrangement-space memo: explicit bounded dict (was an lru_cache) so the
# hit/miss/evict traffic is observable through the flight recorder's
# counters like every other PR-4 memo layer.  Wholesale clear past the
# bound — the space count per key is small, the values are what's big.
_MEMO_MAX = 4096
_memo: dict[tuple, tuple[tuple[int, ...], ...]] = {}


def enumerate_device_groups(
    num_stages: int,
    num_devices: int,
    variance: float = 1.0,
    max_permute_len: int = 6,
    shapes: Sequence[int] | None = None,
    counters=None,
) -> Sequence[tuple[int, ...]]:
    """Every candidate per-stage device-count arrangement for a stage count.

    ``variance`` filters shapes below ``max(num_devices // num_stages,
    num_stages // num_devices) * variance`` — the reference's "key idea 1"
    (small-group pruning).

    Memoized across calls: the arrangement space depends only on the
    arguments, and both replanning (``planner/replan.replan_on_drift``) and
    the sharded parallel workers re-enumerate the identical space.  Callers
    receive a shared immutable tuple — iterate, don't mutate.

    ``counters``: optional ``core.trace.Counters`` — bumps
    ``memo.device_groups.{hit,miss,evict}``.
    """
    key = (num_stages, num_devices, variance, max_permute_len,
           None if shapes is None else tuple(shapes))
    cached = _memo.get(key)
    if cached is not None:
        if counters is not None:
            counters.inc("memo.device_groups.hit")
        return cached
    if counters is not None:
        counters.inc("memo.device_groups.miss")
    out = _enumerate_device_groups(*key)
    if len(_memo) > _MEMO_MAX:
        _memo.clear()
        if counters is not None:
            counters.inc("memo.device_groups.evict")
    _memo[key] = out
    return out


def _enumerate_device_groups(
    num_stages: int,
    num_devices: int,
    variance: float,
    max_permute_len: int,
    shapes: tuple[int, ...] | None,
) -> tuple[tuple[int, ...], ...]:
    all_shapes = list(shapes) if shapes is not None else power_of_two_shapes(num_devices)
    min_group = max(num_devices // num_stages, num_stages // num_devices) * variance
    eligible = [s for s in all_shapes if s >= min_group]

    out: list[tuple[int, ...]] = []
    for comp in nondecreasing_compositions(num_stages, num_devices, eligible):
        out.extend(arrangements_of_composition(comp, max_permute_len))
    return tuple(out)
