"""Jit-compiled cost tensor — the optional jax backend for batched costing.

``JaxCostBackend`` prices the fast family (gpipe, virtual_stages=1,
cp=ep=1, zero=0) of a candidate batch through one ``jax.jit``-compiled
f64 kernel instead of ``BatchCostEstimator._fast``'s per-candidate Python
loop.  The host side gathers exactly the same memoized tables ``_fast``
reads (stage-time slice-sum matrices, activation volumes, dp ring
factors, parameter bytes, optimizer rates, latency floors) into dense
``[B, S]`` arrays; the kernel then replays the per-stage assembly with
the same operations in the same association order, statically unrolled
over the stage axis.

Exactness contract (same as the numpy path's, extended): every float the
kernel produces is either the result of the identical IEEE-754 double
operation sequence ``_fast`` performs, or of an exact identity
(``x + 0.0`` for ``x >= 0`` — how the per-candidate migration and
step-overhead adds become unconditional).  Candidate selection, profile
misses, and the non-fast-family scalar fallback are decided on the host
with byte-for-byte the code ``_cost_one`` runs, so a batch returns the
same ``PlanCost | None`` list in the same order — the regression gate
(``tools/check_search_regression.py``) asserts ranked-dump byte-identity
against the numpy backend on the parity workload.

Specialization: the kernel re-traces per ``(num_stages, overlap,
latency-floor, spot, migration, dp share, num_layers, padded batch)``
combination; the batch axis is padded to the next power of two (pad rows
are copies of row 0, sliced off after) so compile count stays
logarithmic in batch size.  ``memo.jax_kernel.{hit,miss}`` counters
report cache behavior.  f64 is forced per call via the scoped
``jax.experimental.enable_x64`` context, so the process-global x64 flag
is never touched.
"""
from __future__ import annotations

import numpy as np

from metis_tpu.core.errors import MetisError
from metis_tpu.core.types import PlanCost
from metis_tpu.cost.batch import _MISS

try:  # lazy, optional: the numpy backend must work without jax installed
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.experimental import enable_x64
except ImportError:  # pragma: no cover - exercised on jax-free hosts
    jax = None
    jnp = None
    lax = None
    enable_x64 = None


def available() -> bool:
    """Whether the jax cost backend can be constructed on this host."""
    return jax is not None


def _rounded(x):
    """Force a product to round to f64 before it feeds an add or subtract.

    XLA:CPU contracts ``a * b + c`` into a fused multiply-add during
    codegen (the product keeps infinite precision), which breaks
    bit-identity with the numpy path's separately-rounded multiply.
    Neither ``--xla_cpu_enable_fast_math=false``,
    ``--xla_allow_excess_precision=false``, nor
    ``lax.optimization_barrier`` suppresses it, and a double bitcast gets
    simplified away — all verified empirically.  ``abs`` does block it
    (LLVM only contracts an fmul feeding an fadd/fsub directly) and is an
    exact identity here: every guarded product is a product of
    nonnegative factors (times, byte volumes, ring factors, hazard
    scales), so ``abs(x) == x`` bit-for-bit.  The byte-identity
    regression-gate leg re-verifies this on every run.
    """
    return jnp.abs(x)


def _kernel(stage_ms, act, q, params, lat, o, span, pp_den, fb_sync,
            batch_gen, migration, extra_once, extra_pb, batches_f,
            spot_scale_f, layers_f, share_f, *, S, ov, has_lat, has_spot,
            has_mig):
    """The batched per-stage assembly, statically unrolled over stages.

    Mirrors ``BatchCostEstimator._fast`` line for line: chained adds in
    stage order (never a tree ``sum``), ``jnp.maximum`` chains seeded
    from the first stage's value, and the left-associated component sum.
    ``batches`` and ``num_layers`` travel as runtime scalars, not trace
    constants: a compile-time divisor gets strength-reduced to a
    multiply-by-reciprocal (verified: ``x / 10`` compiled to ``x * 0.1``),
    which is inexact for non-power-of-two divisors.
    """
    sum_l = max_l = None
    pp_cost = pp_exposed = None
    max_dp = max_opt = max_dpe = None
    for s in range(S):
        t = stage_ms[:, s]
        sum_l = t if sum_l is None else sum_l + t
        max_l = t if max_l is None else jnp.maximum(max_l, t)
        if s < S - 1:
            t_pp = act[:, s] / pp_den[s]
            pp_cost = t_pp if pp_cost is None else pp_cost + t_pp
            if ov:
                e = jnp.maximum(0.0, t_pp - t)
                pp_exposed = e if pp_exposed is None else pp_exposed + e
        dpv = _rounded((q[:, s] * params[:, s]) * share_f)
        if has_lat:
            dpv = dpv + lat[:, s]
        max_dp = dpv if max_dp is None else jnp.maximum(max_dp, dpv)
        opt = (o[:, s] * span[:, s]) / layers_f
        max_opt = opt if max_opt is None else jnp.maximum(max_opt, opt)
        if ov:
            dpe = jnp.maximum(0.0, dpv - opt)
            max_dpe = dpe if max_dpe is None else jnp.maximum(max_dpe, dpe)
    execution = _rounded((batches_f - 1.0) * max_l) + sum_l
    # step overhead: host pre-splits into a once-per-step and a
    # per-microbatch term (exactly one is nonzero); adding both keeps the
    # op unconditional and exact (x + 0.0 == x for x >= 0)
    execution = (execution + extra_once) + _rounded(extra_pb * batches_f)
    zero = jnp.zeros_like(execution)
    dp_charge = max_dpe if ov else max_dp
    pp_charge = pp_exposed if ov else pp_cost
    if pp_charge is None:  # single-stage placement: no pp boundary at all
        pp_charge = zero
    total = (((((execution + fb_sync) + max_opt) + dp_charge)
              + pp_charge) + batch_gen)
    if has_spot:
        recovery = _rounded(total * spot_scale_f)
        total = total + recovery
    else:
        recovery = zero
    if has_mig:
        total = total + migration
    return total, execution, max_opt, dp_charge, pp_charge, recovery


_STATIC_ARGS = ("S", "ov", "has_lat", "has_spot", "has_mig")


class JaxCostBackend:
    """Batch-cost evaluation via the jit kernel, over a host
    ``BatchCostEstimator`` that owns every table and memo."""

    def __init__(self, host):
        if jax is None:
            raise MetisError(
                "cost_backend='jax' requested but jax is not importable "
                "on this host; use cost_backend='numpy'")
        self.host = host
        self._jit = jax.jit(_kernel, static_argnames=_STATIC_ARGS)
        self._specs_seen: set = set()

    # -- public API --------------------------------------------------------
    def cost_many(self, P, inter, intras):
        """Price one inter plan's intra batch; same contract as the host's
        ``cost_many`` (entry per candidate, None on profile miss)."""
        host = self.host
        results: list = [None] * len(intras)
        rows = []
        rows_idx = []
        for i, intra in enumerate(intras):
            strategies = intra.strategies
            if (intra.schedule != "gpipe" or intra.virtual_stages != 1
                    or any(s.cp != 1 or s.ep != 1 or s.zero != 0
                           for s in strategies)):
                # non-fast family: scalar path, verbatim from _cost_one
                try:
                    results[i] = host.scalar.get_cost(
                        inter, strategies, intra.layer_partition,
                        schedule=intra.schedule,
                        virtual_stages=intra.virtual_stages)
                except KeyError:
                    results[i] = None
                continue
            g = self._gather(P, inter, strategies, intra.layer_partition)
            if g is None:
                results[i] = None
                continue
            rows.append(g)
            rows_idx.append(i)
        if not rows:
            return results
        self._price(P, inter, rows, rows_idx, results)
        return results

    # -- host-side gather --------------------------------------------------
    def _gather(self, P, inter, strategies, partition):
        """One candidate's kernel inputs — the same memoized lookups, in
        the same order, as ``_fast``; None at the same miss points."""
        host = self.host
        batches = inter.batches
        g2 = inter.gbs // batches
        stages = P.stages
        S = P.num_stages
        last = S - 1
        dpfac = P.dpfac
        lat_fn = P.lat_fn
        actmap = host._actmap
        pmap = host._pmap
        omap = host._omap
        stage_row = [0.0] * S
        act_row = [0.0] * last
        q_row = [0.0] * S
        params_row = [0.0] * S
        lat_row = [0.0] * S
        o_row = [0.0] * S
        span_row = [0.0] * S
        fb_sync = 0.0
        for s in range(S):
            strat = strategies[s]
            dp = strat.dp
            tp = strat.tp
            start = partition[s]
            end = partition[s + 1]
            meta = stages[s]
            mbs = g2 // dp
            if meta.homo:
                E = meta.etabs.get((tp, mbs))
                if E is None:
                    E = host._build_etab(meta, tp, mbs)
                if E is _MISS:
                    return None
                stage_row[s] = E[start][end]
            else:
                try:
                    stage_row[s] = host.scalar._stage_execution_ms(
                        inter, strat, meta.types, start, end)
                except KeyError:
                    return None
            if s == last:
                fb = meta.fbtabs.get((tp, mbs))
                if fb is None:
                    fb = host._build_fb(meta, tp, mbs)
                if fb is _MISS:
                    return None
                fb_sync = fb * batches
            else:
                akey = (end, mbs, tp)
                act = actmap.get(akey)
                if act is None:
                    act = host.scalar._activation(end, mbs, tp)
                    actmap[akey] = act
                if strat.sp:
                    act = act / tp
                act_row[s] = act
            dkey = (s, dp)
            q = dpfac.get(dkey)
            if q is None:
                q = host._build_dpfac(P, s, strat)
                dpfac[dkey] = q
            q_row[s] = q
            pkey = (tp, start, end)
            params = pmap.get(pkey)
            if params is None:
                params = host.volume.stage_parameter_bytes(tp, start, end)
                pmap[pkey] = params
            params_row[s] = params
            if lat_fn is not None:
                lat = P.latmap.get(dp)
                if lat is None:
                    lat = lat_fn("all_reduce", dp)
                    P.latmap[dp] = lat
                lat_row[s] = lat
            okey = (meta.opt_type, tp)
            o = omap.get(okey)
            if o is None:
                o = host.scalar._optimizer_ms(meta.opt_type) / tp
                omap[okey] = o
            o_row[s] = o
            span_row[s] = float(end - start)
        extra_once = extra_pb = 0.0
        so = host._so
        if so:
            st0 = strategies[0]
            d0, t0 = st0.dp, st0.tp
            uniform = True
            pairs = set()
            for s in range(S):
                strat = strategies[s]
                if strat.dp != d0 or strat.tp != t0:
                    uniform = False
                stp = strat.tp
                for t in stages[s].typeset:
                    pairs.add((t, stp))
            overhead = max((so.get(p, 0.0) for p in pairs), default=0.0)
            if uniform and P.ranks_uniform:
                extra_once = overhead
            else:
                extra_pb = max(overhead, 0.0)
        if host.options.strict_compat or P.first_type is None:
            batch_gen = host._bg_per * batches
        else:
            batch_gen = P.batch_gen
        migration = 0.0
        if host._mig_active:
            migration = host.scalar._migration_ms(
                tuple(s.tp for s in strategies), tuple(partition))
        return (stage_row, act_row, q_row, params_row, lat_row, o_row,
                span_row, fb_sync, batch_gen, migration, extra_once,
                extra_pb)

    # -- kernel dispatch ---------------------------------------------------
    def _price(self, P, inter, rows, rows_idx, results):
        host = self.host
        S = P.num_stages
        ov = host._overlap
        has_lat = P.lat_fn is not None
        spot_scale = P.spot_scale
        has_spot = bool(spot_scale)
        has_mig = host._mig_active
        share = host._share
        L = host._L
        B = len(rows)
        bpad = 1
        while bpad < B:
            bpad *= 2
        spec = (S, ov, has_lat, has_spot, has_mig, share, L, bpad)
        c = host.counters
        if c is not None:
            if spec in self._specs_seen:
                c.inc("memo.jax_kernel.hit")
            else:
                self._specs_seen.add(spec)
                c.inc("memo.jax_kernel.miss")
        padded = rows + [rows[0]] * (bpad - B)

        def mat(j, width):
            return np.array([g[j] for g in padded],
                            dtype=np.float64).reshape(bpad, width)

        def vec(j):
            return np.array([g[j] for g in padded], dtype=np.float64)

        with enable_x64():
            out = self._jit(
                mat(0, S), mat(1, S - 1), mat(2, S), mat(3, S), mat(4, S),
                mat(5, S), mat(6, S),
                np.asarray(P.pp_den[:S - 1], dtype=np.float64),
                vec(7), vec(8), vec(9), vec(10), vec(11),
                np.float64(inter.batches), np.float64(spot_scale),
                np.float64(L), np.float64(share),
                S=S, ov=ov, has_lat=has_lat, has_spot=has_spot,
                has_mig=has_mig)
            total, execution, max_opt, dp_charge, pp_charge, recovery = (
                np.asarray(a) for a in out)
        for r, i in enumerate(rows_idx):
            g = rows[r]
            results[i] = PlanCost(
                total_ms=float(total[r]),
                execution_ms=float(execution[r]),
                fb_sync_ms=g[7],
                optimizer_ms=float(max_opt[r]),
                dp_comm_ms=float(dp_charge[r]),
                pp_comm_ms=float(pp_charge[r]),
                batch_gen_ms=g[8],
                cp_comm_ms=0.0,
                ep_comm_ms=0.0,
                expected_recovery_ms=float(recovery[r]),
                migration_ms=g[9],
            )
