"""ICI/DCN-aware collective cost model — the TPU-native bandwidth layer.

Replaces the reference's two-scalars-per-node convention and slowest-link
scans (SURVEY.md §2.3 "TPU-native equivalent") with per-collective analytic
costs over the slice torus: ring all-reduce/all-gather/reduce-scatter along
mesh axes at ICI ring bandwidth, hop-aware point-to-point for pipeline
neighbors, DCN for anything crossing a slice boundary.

Bandwidths convert as GB/s -> 1e6 bytes/ms (decimal, the physical unit; the
reference's 1024*1024 factor is a compat-mode quirk confined to the
estimator).
"""
from __future__ import annotations

from metis_tpu.cluster.tpu import TpuClusterSpec, TpuSliceSpec
from metis_tpu.core.types import InterStagePlan, Strategy
from metis_tpu.cost.bandwidth import cp_ring_groups


def _bytes_per_ms(bw_gbps: float) -> float:
    return bw_gbps * 1e6


def ring_all_reduce_ms(nbytes: float, group_size: int, bw_gbps: float) -> float:
    """Bandwidth-optimal ring all-reduce: 2(n-1)/n of the payload crosses the
    slowest link (reduce-scatter + all-gather)."""
    if group_size <= 1:
        return 0.0
    return 2 * (group_size - 1) / group_size * nbytes / _bytes_per_ms(bw_gbps)


def all_gather_ms(nbytes: float, group_size: int, bw_gbps: float) -> float:
    """Ring all-gather of a full ``nbytes`` result: (n-1)/n crosses each link."""
    if group_size <= 1:
        return 0.0
    return (group_size - 1) / group_size * nbytes / _bytes_per_ms(bw_gbps)


reduce_scatter_ms = all_gather_ms  # same wire volume, opposite direction


def all_to_all_ms(nbytes: float, group_size: int, bw_gbps: float) -> float:
    """All-to-all moves (n-1)/n of the payload, but a torus routes it across
    the bisection; per-chip cost approximated by payload/(n·bw) per peer."""
    if group_size <= 1:
        return 0.0
    return (group_size - 1) / group_size * nbytes / _bytes_per_ms(bw_gbps)


def p2p_ms(nbytes: float, bw_gbps: float, hops: int = 1) -> float:
    """Point-to-point send: store-and-forward hops pipeline, so extra hops add
    latency, not bandwidth division — modeled as pure bandwidth for large
    transfers."""
    del hops  # large activations are bandwidth-bound; hop latency negligible
    return nbytes / _bytes_per_ms(bw_gbps)


class IciDcnBandwidth:
    """StageBandwidthModel over a TPU slice collection.

    Ranks follow the plan's node-sequence placement (all chips of
    ``node_sequence[0]``'s generation take the lowest ranks, and so on —
    the same convention as ``balance.rank_device_types``), so permuted
    placements cost against the correct hardware.
    """

    def __init__(self, tpu_cluster: TpuClusterSpec, plan: InterStagePlan):
        self.tpu_cluster = tpu_cluster
        self.plan = plan
        # rank -> slice index, in node-sequence order (stable within a
        # generation: slices keep their declaration order).
        self._rank_slice: list[int] = []
        for generation in plan.node_sequence:
            for idx, s in enumerate(tpu_cluster.slices):
                if s.generation == generation:
                    self._rank_slice.extend([idx] * s.num_chips)

    def _slice_of(self, rank: int) -> int:
        return self._rank_slice[rank]

    def _slice_ring_bw(self, slice_idx: int) -> float:
        s: TpuSliceSpec = self.tpu_cluster.slices[slice_idx]
        return min(s.axis_ring_bw_gbps(a) for a in range(len(s.topology)))

    def _group_bandwidth(self, ranks: list[int]) -> float:
        slices = {self._slice_of(r) for r in ranks}
        if len(slices) == 1:
            return self._slice_ring_bw(next(iter(slices)))
        # Crossing slices: DCN, shared by the chips of the slowest side.
        return min(
            self.tpu_cluster.slices[i].gen.dcn_bw_gbps for i in slices)

    def pp_bandwidth(self, stage_id: int) -> float:
        """Boundary p2p: ICI if both stages live in one slice, else DCN."""
        start, _ = self.plan.stage_rank_range(stage_id)
        groups = self.plan.device_groups
        end = start + groups[stage_id] + (
            groups[stage_id + 1] if stage_id + 1 < len(groups) else 0)
        slices = {self._slice_of(r) for r in range(start, end)}
        if len(slices) == 1:
            s = self.tpu_cluster.slices[next(iter(slices))]
            return s.gen.ici_bw_gbps
        return min(self.tpu_cluster.slices[i].gen.dcn_bw_gbps for i in slices)

    def dp_bandwidth(self, stage_id: int, strategy: Strategy) -> float:
        start, end = self.plan.stage_rank_range(stage_id)
        ranks = list(range(start, end))
        slowest = float("inf")
        for d in range(strategy.dp):
            slowest = min(slowest, self._group_bandwidth(ranks[d::strategy.dp]))
        return slowest

    def cp_bandwidth(self, stage_id: int, strategy: Strategy) -> float:
        """Ring-attention ring bandwidth (rank layout: cp_ring_groups)."""
        start, _ = self.plan.stage_rank_range(stage_id)
        return min(
            self._group_bandwidth(ring)
            for ring in cp_ring_groups(start, strategy))
