"""ICI/DCN-aware collective cost model — the TPU-native bandwidth layer.

Replaces the reference's two-scalars-per-node convention and slowest-link
scans (SURVEY.md §2.3 "TPU-native equivalent") with per-collective analytic
costs over the slice torus: ring all-reduce/all-gather/reduce-scatter along
mesh axes at ICI ring bandwidth, hop-aware point-to-point for pipeline
neighbors, DCN for anything crossing a slice boundary.

Fidelity layers (SURVEY.md §7 hard part #1):

1. **Analytic formulas** (``ring_all_reduce_ms`` & co.) with published
   per-generation link constants (``cluster/tpu.py``) — the zero-TPU default.
2. **Torus placement** — :class:`IciDcnBandwidth` maps each communication
   group to slice-local torus *coordinates* (row-major over the slice
   topology, matching how ``PlanArtifact.build_mesh`` lays ranks out) and
   derives an effective bandwidth from the axes the group actually spans:
   a collective over a sub-grid decomposes into sequential per-axis ring
   phases (the standard multi-axis decomposition XLA performs), strided
   groups share links with their interleaved siblings, and only a full
   wrapped axis gets both ring directions.
3. **Measured calibration** — a :class:`~metis_tpu.cost.calibration.
   CollectiveCalibration` (microbenchmarked with
   ``microbenchmark_collectives`` on the deployment's own mesh) overrides
   the published link constant with the measured wire bandwidth and adds the
   measured latency floor whenever its platform matches the slice costed.

Bandwidths convert as GB/s -> 1e6 bytes/ms (decimal, the physical unit; the
reference's 1024*1024 factor is a compat-mode quirk confined to the
estimator).
"""
from __future__ import annotations

import math
from typing import Sequence

from metis_tpu.cluster.tpu import TpuClusterSpec, TpuSliceSpec
from metis_tpu.core.types import InterStagePlan, Strategy
from metis_tpu.cost.bandwidth import cp_ring_groups
from metis_tpu.cost.calibration import CollectiveCalibration


def _bytes_per_ms(bw_gbps: float) -> float:
    return bw_gbps * 1e6


def ring_all_reduce_ms(nbytes: float, group_size: int, bw_gbps: float,
                       latency_ms: float = 0.0) -> float:
    """Bandwidth-optimal ring all-reduce: 2(n-1)/n of the payload crosses the
    slowest link (reduce-scatter + all-gather); 2(n-1) latency steps."""
    if group_size <= 1:
        return 0.0
    return (2 * (group_size - 1) / group_size * nbytes
            / _bytes_per_ms(bw_gbps)) + 2 * (group_size - 1) * latency_ms


def all_gather_ms(nbytes: float, group_size: int, bw_gbps: float,
                  latency_ms: float = 0.0) -> float:
    """Ring all-gather of a full ``nbytes`` result: (n-1)/n crosses each
    link, n-1 latency steps."""
    if group_size <= 1:
        return 0.0
    return ((group_size - 1) / group_size * nbytes
            / _bytes_per_ms(bw_gbps)) + (group_size - 1) * latency_ms


reduce_scatter_ms = all_gather_ms  # same wire volume, opposite direction


def all_to_all_ms(nbytes: float, group_size: int, bw_gbps: float,
                  latency_ms: float = 0.0, wrap: bool = True) -> float:
    """All-to-all on a ring: each chip sends ``nbytes/n`` to every peer over
    shortest paths; the per-direction link traffic sums to ``n*nbytes/8``
    on a bidirectional ring (mean hop distance n/4, both directions used),
    double on a line.  This replaces the r1 placeholder that reused the
    all-gather formula — all-to-all is ~4x cheaper than an all-gather of the
    same buffer at n=8 and, unlike all-gather, *grows* with n (bisection
    limited), which is exactly the regime MoE dispatch planning cares about.
    """
    if group_size <= 1:
        return 0.0
    factor = 8.0 if wrap else 4.0
    return (group_size * nbytes / factor / _bytes_per_ms(bw_gbps)
            + (group_size - 1) * latency_ms)


def p2p_ms(nbytes: float, bw_gbps: float, hops: int = 1,
           hop_latency_ms: float = 0.0) -> float:
    """Point-to-point send: store-and-forward hops pipeline, so extra hops
    add per-hop latency, not bandwidth division."""
    return nbytes / _bytes_per_ms(bw_gbps) + hops * hop_latency_ms


# ---------------------------------------------------------------------------
# torus placement
# ---------------------------------------------------------------------------


def sub_torus_eff_bw_gbps(slice_spec: TpuSliceSpec,
                          offsets: Sequence[int],
                          link_bw_gbps: float | None = None) -> float:
    """Effective per-chip ring bandwidth for a collective over the chips at
    slice-local ``offsets`` (row-major coordinates over the slice topology).

    Model: the collective decomposes into sequential ring phases, one per
    torus axis the group spans (extent ``e_a`` along axis ``a``), so

        t/V = sum_a  2(e_a - 1)/e_a / bw_a

    and the effective bandwidth is the value that makes the flat ring
    formula over the full group size reproduce that time.  Per-axis
    ``bw_a``: the link constant, x2 when the phase traverses a full wrapped
    axis contiguously (both ring directions usable), /stride when the
    group's coordinates along the axis are strided (interleaved sibling
    groups share the same physical links).

    Groups that do not form a sub-grid (coordinate-product != group size)
    fall back to the slowest-axis scalar — the r1 behavior.
    """
    n = len(offsets)
    if n <= 1:
        return float("inf")
    link = link_bw_gbps if link_bw_gbps is not None else slice_spec.gen.ici_bw_gbps
    topo = slice_spec.topology
    coords = [[] for _ in topo]
    for off in offsets:
        for a in range(len(topo) - 1, -1, -1):
            coords[a].append(off % topo[a])
            off //= topo[a]
    slowest = min(slice_spec.axis_ring_bw_gbps(a) for a in range(len(topo)))
    scale = slowest / slice_spec.gen.ici_bw_gbps
    phases: list[tuple[int, float]] = []
    grid = 1
    for a, extent in enumerate(topo):
        vals = sorted(set(coords[a]))
        e = len(vals)
        grid *= e
        if e == 1:
            continue
        strides = {vals[i + 1] - vals[i] for i in range(e - 1)}
        stride = vals[1] - vals[0] if len(strides) == 1 else None
        if stride is None:
            phases.append((e, link))  # irregular spacing: single direction
            continue
        full_ring = (stride == 1 and e == extent and slice_spec.wrap[a]
                     and e > 2)
        bw = link * (2 if full_ring else 1) / max(stride, 1)
        phases.append((e, bw))
    if grid != n or not phases:
        return link * scale
    denom = sum(2 * (e - 1) / e / bw for e, bw in phases)
    return 2 * (n - 1) / n / denom


_KIND_TO_GENERATION = {
    "v4": "tpu_v4", "v5 lite": "tpu_v5e", "v5e": "tpu_v5e",
    "v5p": "tpu_v5p", "v5": "tpu_v5p", "v6 lite": "tpu_v6e", "v6e": "tpu_v6e",
}


def generation_of_device_kind(device_kind: str) -> str | None:
    """Map a jax ``device_kind`` string (e.g. "TPU v5 lite") to a
    ``TPU_GENERATIONS`` key; None when unrecognized."""
    kind = device_kind.lower()
    best = None
    for sub, gen in _KIND_TO_GENERATION.items():
        if sub in kind and (best is None or len(sub) > len(best[0])):
            best = (sub, gen)
    return best[1] if best else None


class IciDcnBandwidth:
    """StageBandwidthModel over a TPU slice collection.

    Ranks follow the plan's node-sequence placement (all chips of
    ``node_sequence[0]``'s generation take the lowest ranks, and so on —
    the same convention as ``balance.rank_device_types``), so permuted
    placements cost against the correct hardware.  Within a slice, the
    slice-local rank offset is the row-major torus coordinate (matching
    ``PlanArtifact.build_mesh``'s device order).

    ``calibration``: measured collective constants; applied when the
    calibration's platform matches the slice (TPU generation matched via
    device_kind, or a CPU calibration against a CPU-mesh deployment).
    """

    def __init__(self, tpu_cluster: TpuClusterSpec, plan: InterStagePlan,
                 calibration: CollectiveCalibration | None = None):
        from metis_tpu.cluster.tpu import rank_slice_placement

        self.tpu_cluster = tpu_cluster
        self.plan = plan
        self.calibration = calibration
        # rank -> (slice index, slice-local offset), node-sequence order
        self._rank_slice = rank_slice_placement(
            tpu_cluster, plan.node_sequence)

    # -- calibration hooks -------------------------------------------------
    def _cal_matches(self, slice_spec: TpuSliceSpec) -> bool:
        cal = self.calibration
        if cal is None:
            return False
        if cal.platform == "cpu":
            # A CPU-mesh calibration describes the CPU fake backend, never
            # real ICI: it applies only when this process is actually
            # planning for the CPU backend (e.g. the predicted-vs-measured
            # validator on the virtual mesh), not to TPU hardware.
            import jax

            return jax.default_backend() == "cpu"
        gen = generation_of_device_kind(cal.device_kind)
        return gen == slice_spec.generation

    def collective_latency_ms(self, collective: str, group_size: int) -> float:
        """Measured per-collective latency floor, rescaled from the
        calibration's ring-step count to ``group_size``'s (consumed by the
        estimator as an additive term; 0 without a matching calibration)."""
        cal = self.calibration
        if cal is None or group_size <= 1:
            return 0.0
        if not any(self._cal_matches(s) for s in self.tpu_cluster.slices):
            return 0.0
        steps_of = lambda n: (2 * (n - 1) if collective == "all_reduce"  # noqa: E731
                              else n - 1)
        cal_steps = max(steps_of(max(cal.group_size, 2)), 1)
        return cal.latency_ms(collective) / cal_steps * steps_of(group_size)

    def _link_bw(self, slice_spec: TpuSliceSpec, collective: str) -> float:
        """Per-link bandwidth, measured when calibrated: the fit's effective
        bandwidth is per logical payload, so invert the collective's wire
        factor at the calibration's group size to recover the link rate."""
        if not self._cal_matches(slice_spec):
            return slice_spec.gen.ici_bw_gbps
        cal = self.calibration
        eff = cal.bw_gbps(collective)
        if eff is None or not math.isfinite(eff):
            return slice_spec.gen.ici_bw_gbps
        n = max(cal.group_size, 2)
        wire_factor = {
            "all_reduce": 2 * (n - 1) / n,
            "all_gather": (n - 1) / n,
            "reduce_scatter": (n - 1) / n,
            "all_to_all": n / 8.0,
            "ppermute": 1.0,
        }.get(collective, 1.0)
        return eff * wire_factor

    # -- placement ---------------------------------------------------------
    def _slice_of(self, rank: int) -> int:
        return self._rank_slice[rank][0]

    def _group_bandwidth(self, ranks: Sequence[int],
                         collective: str = "all_reduce") -> float:
        located = [self._rank_slice[r] for r in ranks]
        slices = {s for s, _ in located}
        if len(slices) == 1:
            idx = next(iter(slices))
            spec = self.tpu_cluster.slices[idx]
            return sub_torus_eff_bw_gbps(
                spec, [off for _, off in located],
                link_bw_gbps=self._link_bw(spec, collective))
        # Crossing slices: DCN, shared by the chips of the slowest side.
        return min(
            self.tpu_cluster.slices[i].gen.dcn_bw_gbps for i in slices)

    def pp_bandwidth(self, stage_id: int) -> float:
        """Boundary p2p: ICI if both stages live in one slice, else DCN."""
        start, _ = self.plan.stage_rank_range(stage_id)
        groups = self.plan.device_groups
        end = start + groups[stage_id] + (
            groups[stage_id + 1] if stage_id + 1 < len(groups) else 0)
        slices = {self._slice_of(r) for r in range(start, end)}
        if len(slices) == 1:
            s = self.tpu_cluster.slices[next(iter(slices))]
            return self._link_bw(s, "ppermute")
        return min(self.tpu_cluster.slices[i].gen.dcn_bw_gbps for i in slices)

    def dp_bandwidth(self, stage_id: int, strategy: Strategy) -> float:
        """Slowest gradient-sync ring.  Stage ranks lay out (dp, cp, tp)
        row-major, so the sync group of model-shard slot (c, t) is
        ``{start + d*cp*tp + c*tp + t : d}`` — the groups that actually
        all-reduce gradients together (the r1 ``ranks[d::dp]`` stride scan
        grouped *by replica*, which is the transpose of the sync layout)."""
        start, end = self.plan.stage_rank_range(stage_id)
        width = strategy.cp * strategy.tp
        slowest = float("inf")
        for slot in range(width):
            group = [start + d * width + slot for d in range(strategy.dp)]
            if group[-1] >= end:
                group = [r for r in group if r < end]
            if len(group) > 1:
                slowest = min(
                    slowest, self._group_bandwidth(group, "all_reduce"))
        return slowest if math.isfinite(slowest) else self._group_bandwidth(
            list(range(start, end)), "all_reduce")

    def cp_bandwidth(self, stage_id: int, strategy: Strategy) -> float:
        """Ring-attention ring bandwidth (rank layout: cp_ring_groups)."""
        start, _ = self.plan.stage_rank_range(stage_id)
        return min(
            self._group_bandwidth(ring, "ppermute")
            for ring in cp_ring_groups(start, strategy))
