"""Context-parallel (ring attention) planning model — net-new TPU capability.

The reference has **no** long-context support: sequence length is a scalar in
its activation math and no CP/ring/Ulysses variant exists anywhere
(SURVEY.md §5 "Long-context / sequence parallelism").  This module adds the
cost and memory model for a context-parallel plan axis: each stage may shard
the *sequence* dimension over ``Strategy.cp`` devices running ring attention
(execution counterpart: :mod:`metis_tpu.ops.ring_attention`).

Modeling assumptions (validated against the execution layer, documented here
because the planner must predict what the executed plan does):

- **Compute** scales ~1/cp.  FFN/projection FLOPs are linear in local sequence
  length; ring attention computes the full causal attention in ``cp`` block
  steps of (S/cp x S/cp) scores, so per-device attention FLOPs are also S²/cp.
- **Ring traffic**: each device rotates its K/V block (2 tensors of
  ``mbs x S/cp x hidden/tp``) ``cp-1`` times forward; backward re-runs the ring
  carrying K/V plus accumulated dK/dV — 2 rotations' worth.  Total per layer
  per microbatch = ``(cp-1) * 3 * kv_block_bytes``.  We charge it un-overlapped
  (conservative; on real slices XLA/pallas overlap most of it with the block
  matmuls — the validator's predicted-vs-measured loop is where this constant
  gets calibrated).
- **GQA**: the RING path carries grouped K/V natively (``make_ring_attention
  .supports_gqa``; models/llama passes unexpanded [b, kv_heads, s, d]), so
  ring K/V rotation bytes scale by ``num_kv_heads / num_heads``.  The
  Ulysses path still expands K/V to the query head count before its
  all-to-alls (its head-split logic assumes matched counts), so a2a bytes
  stay at full ``hidden_size`` — each formula prices what its executor
  moves.
- **Memory**: sequence sharding divides *activation* memory by cp but leaves
  weights/optimizer state whole.  Profiles report one per-layer total, so we
  recover the split from the store's batch-size sweep: per-layer memory is
  affine in bs (``mem(bs) ~ static + bs * act_slope``) because activations are
  the only bs-dependent term.  A least-squares fit over the profiled bs points
  gives (static, slope) per layer; cp memory = ``static + bs * slope / cp``.
  With fewer than two bs points the split is unidentifiable and we
  conservatively model **no** memory relief (cp=1 memory), never an optimistic
  guess.
"""
from __future__ import annotations

from typing import Sequence

from metis_tpu.core.config import ModelSpec
from metis_tpu.profiles.store import ProfileStore, affine_fit

# Ring rotations of the K/V block: 1 forward + 1 backward at the model
# dtype, plus the backward's dK/dV accumulator rotation at float32 (the
# ring VJP carries fp32 accumulators — _ring_flash_bwd) — kept as explicit
# terms in ring_comm_bytes_per_layer, not a flat rotation count.
RING_ROTATIONS = 3  # structural count (fwd K/V, bwd K/V, bwd dK/dV)
_GRAD_BYTES = 4     # dK/dV rotate as float32 accumulators


def ring_comm_bytes_per_layer(
    model: ModelSpec, mbs: int, cp: int, tp: int
) -> float:
    """Un-overlapped ring-attention wire bytes one device moves per
    transformer layer per microbatch — priced per rotating tensor: what the
    executor actually moves (``ops/ring_attention.py``)."""
    if cp <= 1:
        return 0.0
    # GQA: the ring rotates grouped K/V (kv_heads/num_heads of the hidden
    # width) — see the module docstring and ops/ring_attention.py
    kv_frac = (model.num_kv_heads / model.num_heads
               if getattr(model, "num_kv_heads", 0) else 1.0)
    kv_elems = (
        2  # K and V
        * mbs
        * (model.sequence_length // cp)
        * (model.hidden_size // tp)
        * kv_frac
    )
    # 2 rotations at the model dtype (fwd K/V + bwd K/V) + 1 at fp32
    # (bwd dK/dV accumulators)
    return (cp - 1) * kv_elems * (2 * model.dtype_bytes + _GRAD_BYTES)


def cp_ring_ms(
    model: ModelSpec,
    mbs: int,
    cp: int,
    tp: int,
    num_attn_layers: int,
    bw_gbps: float,
) -> float:
    """Ring-attention comm time (ms) for one microbatch across a stage's
    attention layers at ``bw_gbps`` per-link ring bandwidth."""
    if cp <= 1 or num_attn_layers <= 0:
        return 0.0
    nbytes = ring_comm_bytes_per_layer(model, mbs, cp, tp) * num_attn_layers
    return nbytes / (bw_gbps * 1e6)


def a2a_comm_bytes_per_layer(
    model: ModelSpec, mbs: int, cp: int, tp: int
) -> float:
    """Un-overlapped Ulysses (all-to-all) wire bytes one device moves per
    transformer layer per microbatch: 4 tensors re-shard each direction of
    the forward (q, k, v in; context out) and their 4 gradients on the
    backward; an all-to-all moves ``(cp-1)/cp`` of each local tensor of
    ``mbs x S/cp x hidden/tp``.  Asymptotically ~cp x less traffic than the
    ring's K/V rotation (``ring_comm_bytes_per_layer``) — the planner prices
    both and picks per stage (``Strategy.cp_mode``)."""
    if cp <= 1:
        return 0.0
    local = (
        mbs
        * (model.sequence_length // cp)
        * (model.hidden_size // tp)
        * model.dtype_bytes
    )
    return 8 * local * (cp - 1) / cp


def cp_comm_ms(
    model: ModelSpec,
    mbs: int,
    cp: int,
    tp: int,
    num_attn_layers: int,
    bw_gbps: float,
    mode: str = "ring",
) -> float:
    """Context-parallel comm time (ms) for one microbatch across a stage's
    attention layers, for either cp mode ("ring" or "a2a")."""
    if cp <= 1 or num_attn_layers <= 0:
        return 0.0
    per_layer = (
        a2a_comm_bytes_per_layer(model, mbs, cp, tp) if mode == "a2a"
        else ring_comm_bytes_per_layer(model, mbs, cp, tp))
    return per_layer * num_attn_layers / (bw_gbps * 1e6)


def attention_layer_range(model: ModelSpec, start: int, end: int) -> int:
    """How many layers in [start, end) are transformer blocks (ring attention
    runs only there; the embed (0) and head (L-1) pseudo-layers carry none)."""
    lo = max(start, 1)
    hi = min(end, model.num_layers - 1)
    return max(0, hi - lo)


class ActivationSplitModel:
    """Per-layer (static, bs-slope) memory decomposition fit from a profile
    store's batch-size sweep, cached per (device_type, tp)."""

    def __init__(self, profiles: ProfileStore):
        self.profiles = profiles
        self._cache: dict[tuple[str, int], tuple[tuple[float, ...], tuple[float, ...]] | None] = {}

    def split(
        self, device_type: str, tp: int
    ) -> tuple[tuple[float, ...], tuple[float, ...]] | None:
        """(static_mb, act_slope_mb_per_bs) per layer, or None when the store
        has <2 batch points for this (type, tp) and the split is
        unidentifiable."""
        key = (device_type, tp)
        if key not in self._cache:
            self._cache[key] = self._fit(device_type, tp)
        return self._cache[key]

    def _fit(self, device_type: str, tp: int):
        points = sorted(
            (bs, self.profiles.get(device_type, tp, bs).layer_memory_mb)
            for (t, p, bs) in self.profiles.configs(device_type)
            if t == device_type and p == tp
        )
        if len(points) < 2:
            return None
        xs = [float(bs) for bs, _ in points]
        if len(set(xs)) < 2:
            return None
        num_layers = len(points[0][1])
        static: list[float] = []
        slope: list[float] = []
        for layer in range(num_layers):
            ys = [mem[layer] for _, mem in points]
            a, b = affine_fit(xs, ys)
            # Physical clamps: activations can't be negative; static memory
            # can't exceed the smallest observed total.
            b = max(b, 0.0)
            a = max(min(a, min(ys)), 0.0)
            static.append(a)
            slope.append(b)
        return tuple(static), tuple(slope)

    def layer_memory(
        self,
        device_type: str,
        tp: int,
        bs: int,
        act_divisor: float = 1.0,
        static_scale: Sequence[float] | None = None,
        static_reduction_mb: Sequence[float] | None = None,
        act_scale: Sequence[float] | None = None,
    ) -> tuple[float, ...]:
        """Per-layer memory row (MB) with the activation component divided by
        ``act_divisor`` (sequence/context sharding) and scaled per layer by
        ``act_scale`` (partial activation sharding, e.g. Megatron sp), the
        static component scaled per layer by ``static_scale`` (weight
        sharding, e.g. expert parallelism), then reduced by
        ``static_reduction_mb`` (absolute sharded-state relief, e.g. ZeRO;
        clamped at zero).  Falls back to the measured full row (no relief)
        when the static/activation split cannot be identified — conservative,
        never optimistic."""
        base = self.profiles.get(device_type, tp, bs).layer_memory_mb
        if (act_divisor <= 1 and static_scale is None
                and static_reduction_mb is None and act_scale is None):
            return base
        fitted = self.split(device_type, tp)
        if fitted is None:
            return base
        n = len(base)
        static, slope = fitted
        scales = static_scale if static_scale is not None else [1.0] * n
        cuts = (static_reduction_mb if static_reduction_mb is not None
                else [0.0] * n)
        ascales = act_scale if act_scale is not None else [1.0] * n
        return tuple(
            min(max(s * sc - cut, 0.0) + bs * m * asc / act_divisor, full)
            for s, m, sc, cut, asc, full
            in zip(static, slope, scales, cuts, ascales, base)
        )

    def layer_memory_with_cp(
        self, device_type: str, tp: int, bs: int, cp: int
    ) -> tuple[float, ...]:
        """Per-layer memory row (MB) under sequence sharding by ``cp``."""
        return self.layer_memory(device_type, tp, bs, act_divisor=cp)


def cp_candidates(max_cp_degree: int, sequence_length: int) -> list[int]:
    """Power-of-two cp degrees to search: cp must divide the sequence."""
    out = []
    cp = 2
    while cp <= max_cp_degree:
        if sequence_length % cp == 0:
            out.append(cp)
        cp *= 2
    return out
