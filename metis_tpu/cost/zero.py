"""ZeRO / FSDP sharded-state planning model — net-new TPU capability.

The reference has no sharded-optimizer support (SURVEY.md §2.2 "ZeRO/FSDP —
Absent"; its optimizer cost just divides profiled time, ``cost_estimator.py:
88-89``).  This module adds a ZeRO stage to the plan space:

- **stage 1** shards optimizer state (fp32 master + Adam moments) over the
  stage's data ranks;
- **stage 2** additionally shards gradients;
- **stage 3** (FSDP) additionally shards parameters.

Execution counterpart: on TPU, ZeRO-3 is just a ``NamedSharding`` that puts
parameters (and therefore optax state, which mirrors the param pytree) on the
dp axis — GSPMD inserts the forward/backward all-gathers over ICI
(``execution.train.fsdp_wrap_specs``).

Cost model:

- **Memory**: per-layer static relief = shardable bytes x (1 - 1/d), where d
  is the stage's data-rank count (dp*cp).  Shardable bytes are analytic from
  the profile's per-layer parameter bytes: grads mirror the param dtype, Adam
  fp32 state is master + 2 moments (12 bytes per parameter).  The relief is
  subtracted from the *fitted static component* (never below zero, never
  above the measured row — same conservative stance as the cp/ep models).
- **Gradient comm**: stages 1-2 replace the ring all-reduce (volume
  ``2(d-1)/d x P``) with reduce-scatter + all-gather of the same total volume
  — cost unchanged.  Stage 3 adds the backward parameter all-gather:
  ``3(d-1)/d x P`` total, a 1.5x factor on the dp term.  (The forward
  all-gather overlaps with layer compute on real hardware and profiles would
  absorb it; we charge only the exposed backward gather — calibrate via the
  validator.)
- **Optimizer step**: with state sharded, each rank updates 1/d of the
  parameters — profiled optimizer time divides by d.
"""
from __future__ import annotations

_MB = 1024 * 1024
# Adam fp32 state bytes per parameter: master copy + first + second moment.
_ADAM_BYTES_PER_PARAM = 12


def zero_candidates(enabled: bool) -> list[int]:
    return [0, 1, 2, 3] if enabled else [0]


def zero_dp_factor(zero_stage: int) -> float:
    """Multiplier on the ring all-reduce gradient cost: stage 3 adds the
    backward parameter all-gather (2(d-1)/d -> 3(d-1)/d)."""
    return 1.5 if zero_stage >= 3 else 1.0


def shardable_bytes_per_param_byte(dtype_bytes: int, zero_stage: int) -> float:
    """How many bytes of per-rank state become shardable per byte of stored
    parameters, by ZeRO stage (``dtype_bytes`` is the stored-parameter
    width)."""
    if zero_stage < 1:
        return 0.0
    params_per_byte = 1.0 / dtype_bytes
    out = _ADAM_BYTES_PER_PARAM * params_per_byte      # stage 1: optimizer
    if zero_stage >= 2:
        out += 1.0                                     # stage 2: + gradients
    if zero_stage >= 3:
        out += 1.0                                     # stage 3: + parameters
    return out


def zero_static_reduction_mb(
    params_per_layer_bytes: tuple[int, ...],
    zero_stage: int,
    data_ranks: int,
    tp: int = 1,
    dtype_bytes: int = 2,
    expert_frac: float = 0.0,
    ep: int = 1,
) -> tuple[float, ...] | None:
    """Per-layer static-memory reduction (MB) from sharding ZeRO state over
    ``data_ranks``, or None when nothing shards.  ``params_per_layer_bytes``
    is the profile's whole-model figure; each rank stores 1/tp of it.

    With expert parallelism (``expert_frac`` of block-layer parameters
    sharded ``ep``-ways), each expert shard is replicated over only
    ``data_ranks/ep`` ranks, so ZeRO recovers ``1 - ep/data_ranks`` of the
    per-rank expert state (zero when data_ranks == ep), not ``1 - 1/d`` —
    never credit relief the sharding cannot deliver."""
    if zero_stage < 1 or data_ranks <= 1:
        return None
    per_byte = shardable_bytes_per_param_byte(dtype_bytes, zero_stage)
    dense_f = 1.0 - 1.0 / data_ranks
    n = len(params_per_layer_bytes)
    out = []
    for layer, p in enumerate(params_per_layer_bytes):
        stored_mb = p / tp * per_byte / _MB
        is_block = 1 <= layer < n - 1
        if ep > 1 and is_block and expert_frac > 0.0:
            expert_ranks = data_ranks // ep
            exp_f = (1.0 - 1.0 / expert_ranks) if expert_ranks > 1 else 0.0
            out.append(stored_mb * ((1 - expert_frac) * dense_f
                                    + expert_frac / ep * exp_f))
        else:
            out.append(stored_mb * dense_f)
    return tuple(out)
