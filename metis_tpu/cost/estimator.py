"""Step-time cost estimators for uniform and heterogeneous plans.

≅ reference ``model/cost_estimator.py`` (C12 in SURVEY.md §2.1), with every
formula preserved under ``strict_compat`` and differential-tested against the
upstream implementation:

- GPipe fill-drain: ``(num_microbatches - 1) * max_stage + sum(stages)``
- ring all-reduce DP gradient cost ``2(d-1)/(d*B) * stage_params``
- point-to-point PP cost ``activation / B``
- fb_sync looked up at the stage microbatch, maxed over member device types
- optimizer cost scaled by profiled time / tp (and layer share for hetero),
  **max** over stages; DP cost likewise max over stages (hetero)

Unit quirks reproduced only under strict_compat (SURVEY.md §2.3):
bandwidth GB/s -> bytes/ms via 1024*1024 (≈2.4% off), activation volumes in
element counts.  Native mode uses bytes and decimal GB/s, real inter-node
bandwidth, and per-device-type optimizer/batch-generator timings.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Sequence

import numpy as np

from metis_tpu.cluster.spec import ClusterSpec
from metis_tpu.core.config import SearchConfig
from metis_tpu.core.errors import ProfileMissError
from metis_tpu.core.types import (
    CostBreakdown,
    InterStagePlan,
    PlanCost,
    Strategy,
    UniformPlan,
)
from metis_tpu.profiles.store import ProfileStore
from metis_tpu.balance.data import DataBalancer, power_of_two_chunks, replica_chunks
from metis_tpu.balance.stage_perf import rank_device_types
from metis_tpu.cost.bandwidth import (
    HeteroScalarBandwidth,
    HomoScalarBandwidth,
    StageBandwidthModel,
)
from metis_tpu.cost.context_parallel import attention_layer_range, cp_comm_ms
from metis_tpu.cost.expert_parallel import (
    ep_a2a_ms,
    expert_param_fraction,
    moe_layer_range,
)
from metis_tpu.cost.schedule import (
    schedule_execution_ms,
    schedule_pp_send_factor,
)
from metis_tpu.cost.zero import zero_dp_factor
from metis_tpu.cost.volume import TransformerVolume


@dataclass(frozen=True)
class EstimatorOptions:
    strict_compat: bool = False
    # None = auto: 2.0 strict_compat (ref data_loader.py:19), 1.0 native
    # (the executors run the adamw update once per step — see
    # SearchConfig.optimizer_factor)
    optimizer_factor: float | None = None
    max_profiled_bs: int = 16       # ref cost_estimator.py:166 cap
    dp_over_pp_rows: bool = True    # homo: whole pp-row treated as one dp group
    # Measured fraction of the dp gradient all-reduce hidden under backward
    # compute (cost/calibration.measure_dp_overlap).  0.0 = fully serial —
    # the reference's model (cost_estimator.py:37-43 charged on the critical
    # path) and the only behavior under strict_compat.  Native mode charges
    # only the exposed (1 - fraction) share; the latency floor stays fully
    # charged (a ring's alpha cost cannot be hidden by more compute).
    dp_overlap_fraction: float = 0.0
    # measured fwd share of a fwd+bwd stage time for remat-schedule pricing
    # (cost/schedule.schedule_execution_ms); None = analytic default
    remat_fwd_fraction: float | None = None
    # Overlap-aware comm pricing (SearchConfig.use_overlap_model): charge
    # only the exposed share of each collective — per pp boundary
    # ``max(0, send - sender stage compute)``, per stage
    # ``max(0, dp sync - optimizer)`` — matching the executor's
    # double-buffered ppermute and chunked gradient all-reduce
    # (execution/pipeline.py).  Never active under strict_compat: the
    # reference prices every collective fully exposed.
    use_overlap_model: bool = True
    # Native mode: affine-smooth the profile's bs axis and charge the fitted
    # per-program fixed cost once per step instead of once per microbatch
    # (ProfileStore.affine_view — the executors scan microbatches inside one
    # jit).  Ignored under strict_compat (the reference charges the raw
    # profiled time per microbatch).
    mb_affine: bool = True
    # Availability-aware pricing (SearchConfig.use_spot_model): charge the
    # expected preemption-recovery cost per step — step time x the plan's
    # summed spot hazard (DeviceSpec.hazard_per_hr) x measured recover
    # seconds / 3600 — as an additive ``expected_recovery`` term.  Never
    # active under strict_compat; a reserved-only fleet prices a hazard of
    # exactly 0 and every cost stays bit-identical to the flag being off.
    use_spot_model: bool = True
    spot_recover_s: float = 30.0
    # Migration-aware pricing (SearchConfig.use_migration_model): when a
    # replan carries the incumbent plan's layout (``migrate_from`` — a tuple
    # of (tp, layer_start, layer_end) per old stage), charge each candidate
    # the parameter bytes it must reshard away from that layout, amortized
    # over ``migration_amortize_steps`` — so the planner can trade a
    # slightly worse plan for a much cheaper live switch
    # (execution/reshard.py prices the same delta for the actual transfer).
    # An empty ``migrate_from`` prices exactly 0.0; never active under
    # strict_compat.
    use_migration_model: bool = True
    migrate_from: tuple = ()
    migration_bw_gbps: float = 100.0
    migration_amortize_steps: int = 1000
    # Batched cost-tensor backend (SearchConfig.cost_backend): "numpy" is
    # the scalar-float oracle; "jax" jit-compiles the same per-stage table
    # product (cost/jax_backend.py) with byte-identical results.
    cost_backend: str = "numpy"

    @staticmethod
    def from_config(cfg: SearchConfig) -> "EstimatorOptions":
        return EstimatorOptions(
            strict_compat=cfg.strict_compat,
            optimizer_factor=cfg.optimizer_factor,
            max_profiled_bs=cfg.max_profiled_bs,
            dp_overlap_fraction=cfg.dp_overlap_fraction,
            remat_fwd_fraction=cfg.remat_fwd_fraction,
            use_overlap_model=cfg.use_overlap_model,
            use_spot_model=cfg.use_spot_model,
            spot_recover_s=cfg.spot_recover_s,
            use_migration_model=cfg.use_migration_model,
            migrate_from=tuple(
                tuple(int(x) for x in t) for t in cfg.migrate_from),
            migration_bw_gbps=cfg.migration_bw_gbps,
            migration_amortize_steps=cfg.migration_amortize_steps,
            cost_backend=getattr(cfg, "cost_backend", "numpy"),
        )

    @property
    def overlap_active(self) -> bool:
        """Whether the exposed-vs-hidden comm split applies."""
        return self.use_overlap_model and not self.strict_compat

    @property
    def spot_active(self) -> bool:
        """Whether the expected-recovery availability term applies."""
        return self.use_spot_model and not self.strict_compat

    @property
    def migration_active(self) -> bool:
        """Whether the amortized plan-switch term applies."""
        return (self.use_migration_model and not self.strict_compat
                and bool(self.migrate_from))

    @property
    def dp_exposed_share(self) -> float:
        """Share of dp gradient-sync volume charged on the critical path."""
        if self.strict_compat:
            return 1.0
        return 1.0 - min(max(self.dp_overlap_fraction, 0.0), 1.0)

    def bw_to_bytes_per_ms(self, bw_gbps: float) -> float:
        # Reference converts GB/s with 1024*1024 (cost_estimator.py:40,46);
        # natively GB/s = 1e6 bytes/ms.
        return bw_gbps * (1024 * 1024 if self.strict_compat else 1e6)


def kv_bytes_per_token(model, kv_dtype_bytes: int = 2, tp: int = 1) -> float:
    """KV-cache bytes one sequence adds per token per transformer block.

    ``2 ×`` is K and V; GQA/MQA shrink the footprint through
    ``num_kv_heads`` (0 on the spec means full multi-head attention).
    Tensor parallelism shards heads, so a tp-way stage holds ``1/tp`` of the
    cache per rank — the per-rank figure is what the HBM check needs."""
    kv_heads = model.num_kv_heads or model.num_heads
    return 2.0 * kv_heads * model.head_dim * kv_dtype_bytes / tp


def kv_stage_bytes(
    model,
    batch: int,
    context_len: int,
    start: int,
    end: int,
    kv_dtype_bytes: int = 2,
    tp: int = 1,
) -> float:
    """Per-rank KV footprint for ``batch`` sequences of ``context_len`` tokens
    on a stage holding layers ``[start, end)``.

    Only transformer blocks hold KV — the embed (layer 0) and head (layer
    ``num_layers-1``) pseudo-layers the partition convention carries are
    clamped out, so a stage that owns only those prices to zero."""
    blocks = max(0, min(end, model.num_layers - 1) - max(start, 1))
    return batch * context_len * blocks * kv_bytes_per_token(
        model, kv_dtype_bytes=kv_dtype_bytes, tp=tp)


def paged_tokens(tokens: int, page_tokens: int) -> int:
    """Token count rounded UP to whole KV pages (``page_tokens`` tokens per
    page per layer, vLLM-style block allocation).  ``page_tokens <= 0`` means
    exact (unpaged) accounting — the PR-9 model."""
    if page_tokens <= 0 or tokens <= 0:
        return max(tokens, 0)
    return -(-tokens // page_tokens) * page_tokens


def paged_kv_seq_bytes(
    model,
    context_len: int,
    start: int,
    end: int,
    kv_dtype_bytes: int = 2,
    tp: int = 1,
    *,
    page_tokens: int = 0,
    prefix_len: int = 0,
    prefix_share_frac: float = 0.0,
) -> float:
    """Expected per-rank KV bytes ONE sequence uniquely holds on a stage
    under paged prefix sharing.

    ``prefix_share_frac`` of sequences share one common prompt prefix of
    ``prefix_len`` tokens whose pages are stored once per lane (see
    :func:`shared_prefix_stage_bytes`), so a sharing sequence only allocates
    pages for its ``context_len - prefix_len`` unique tail.  The remaining
    ``1 - prefix_share_frac`` carry their full context.  With sharing off and
    paging off this is EXACTLY ``kv_stage_bytes(model, 1, context_len, ...)``
    — the short-circuit keeps the frozen PR-9 golden byte-identical."""
    if prefix_share_frac <= 0.0 or prefix_len <= 0:
        return kv_stage_bytes(model, 1, paged_tokens(context_len, page_tokens),
                              start, end, kv_dtype_bytes, tp)
    pfx = min(prefix_len, context_len)
    full = kv_stage_bytes(model, 1, paged_tokens(context_len, page_tokens),
                          start, end, kv_dtype_bytes, tp)
    uniq = kv_stage_bytes(model, 1,
                          paged_tokens(context_len - pfx, page_tokens),
                          start, end, kv_dtype_bytes, tp)
    return prefix_share_frac * uniq + (1.0 - prefix_share_frac) * full


def shared_prefix_stage_bytes(
    model,
    prefix_len: int,
    context_len: int,
    start: int,
    end: int,
    kv_dtype_bytes: int = 2,
    tp: int = 1,
    *,
    page_tokens: int = 0,
    prefix_share_frac: float = 0.0,
) -> float:
    """Per-rank bytes of the ONE shared-prefix page set a stage keeps
    resident (counted once per lane, not once per sequence).  Zero when
    sharing is off."""
    if prefix_share_frac <= 0.0 or prefix_len <= 0:
        return 0.0
    pfx = min(prefix_len, context_len)
    return kv_stage_bytes(model, 1, paged_tokens(pfx, page_tokens),
                          start, end, kv_dtype_bytes, tp)


# Memo bounds (entries) for the PR-4 costing caches: wholesale clear beyond
# these, so a long-lived daemon sweeping many clusters cannot grow them
# unboundedly.  Evictions are visible as ``memo.*.evict`` counters.
_BW_CACHE_MAX = 200_000
_STAGE_MS_CACHE_MAX = 200_000


def uniform_layer_split(total_layers: int, num_stages: int) -> list[int]:
    """Even layer counts per stage; first/last get +1 for embed/head
    (≅ ``model/utils.py:5-31``)."""
    base = (total_layers - 2) // num_stages
    rem = (total_layers - 2) % num_stages
    counts = [base] * num_stages
    for i in range(1, rem + 1):
        counts[i % num_stages] += 1
    counts[0] += 1
    counts[-1] += 1
    return counts


class _EstimatorBase:
    def __init__(
        self,
        cluster: ClusterSpec,
        profiles: ProfileStore,
        volume: TransformerVolume,
        options: EstimatorOptions,
        counters=None,
    ):
        self.cluster = cluster
        self.volume = volume
        self.options = options
        # optional core.trace.Counters — estimator-level accounting for the
        # flight recorder: ``profile_miss`` (ProfileMissError raised while
        # pricing a stage) and the bandwidth-model cache hits/misses below.
        # None (tracing off) skips even the dict adds.
        self.counters = counters
        self._step_overhead: dict[tuple[str, int], float] = {}
        if options.mb_affine and not options.strict_compat:
            profiles, self._step_overhead = profiles.affine_view()
        self.profiles = profiles
        # migration term memo: a pure function of (per-stage tp tuple,
        # layer partition) given frozen options — shared verbatim by the
        # batch path so both stay bit-identical
        self._migration_cache: dict = {}
        self._migrate_from_tp: dict[int, int] | None = None

    def _step_overhead_ms(
            self, pairs: Sequence[tuple[str, int]]) -> float:
        """The fitted per-program fixed cost, charged once per step, maxed
        over the (device_type, tp) configurations the plan ACTUALLY runs
        (the slowest participant bounds the critical path).  May be
        negative: a superlinear-in-bs profile fits a negative intercept,
        and the affine extrapolation — not the \"fixed overhead\" story —
        is the contract (it is what makes the predicted step flat in the
        microbatch count, matching the on-chip measurement)."""
        if not self._step_overhead:
            return 0.0
        return max((self._step_overhead.get(p, 0.0) for p in set(pairs)),
                   default=0.0)

    def _dp_cost_ms(self, param_bytes: float, bw_gbps: float, dp: int) -> float:
        if dp <= 1:
            return 0.0
        return 2 * (dp - 1) / (dp * self.options.bw_to_bytes_per_ms(bw_gbps)) * param_bytes

    def _pp_cost_ms(self, activation: float, bw_gbps: float) -> float:
        return activation / self.options.bw_to_bytes_per_ms(bw_gbps)

    def _activation(self, boundary: int, mbs: int, tp: int) -> float:
        return self.volume.boundary_activation(
            boundary, mbs, tp, elements=self.options.strict_compat)

    def _fb_sync_ms(self, device_types: Sequence[str], tp: int, bs: int) -> float:
        return max(
            self.profiles.get(t, tp, bs).fb_sync_ms for t in set(device_types))

    def _optimizer_ms(self, device_type: str | None = None) -> float:
        if self.options.strict_compat or device_type is None:
            raw = self.profiles.model.optimizer_time_ms
        else:
            raw = self.profiles.type_meta[device_type].optimizer_time_ms
        factor = self.options.optimizer_factor
        if factor is None:
            factor = 2.0 if self.options.strict_compat else 1.0
        return raw * factor

    def _spot_scale_of(self, hazard_per_hr: float) -> float:
        """Dimensionless expected-recovery multiplier for a device set with
        the given summed preemption hazard: a step of T ms sees
        ``hazard * T / 3.6e6`` expected evictions, each costing
        ``spot_recover_s * 1000`` ms of recovery, so the charge is
        ``T * hazard * spot_recover_s / 3600`` — exactly 0.0 when the spot
        model is inactive or the fleet is reserved-only."""
        if not self.options.spot_active or hazard_per_hr == 0.0:
            return 0.0
        return hazard_per_hr * self.options.spot_recover_s / 3600.0

    def _migration_ms(self, tps: tuple, partition: tuple) -> float:
        """Amortized cost of resharding the incumbent layout
        (``options.migrate_from``) into a candidate's (per-stage tp,
        layer partition): every layer NOT already held at the candidate's
        tp by some old stage must move its parameter bytes over the
        migration fabric, spread over ``migration_amortize_steps`` so the
        one-time transfer is comparable to per-step terms.  Depends only
        on (tps, partition) + the frozen options — placement-free, so the
        batch path calls this same memoized helper and stays
        bit-identical.  Exactly 0.0 when the model is inactive."""
        if not self.options.migration_active:
            return 0.0
        key = (tps, partition)
        cached = self._migration_cache.get(key)
        if cached is not None:
            return cached
        old_tp = self._migrate_from_tp
        if old_tp is None:
            old_tp = {}
            for tp, start, end in self.options.migrate_from:
                for layer in range(start, end):
                    old_tp[layer] = tp
            self._migrate_from_tp = old_tp
        moved = 0.0
        for s, tp in enumerate(tps):
            per = self.volume.parameter_bytes_per_layer(tp)
            for layer in range(partition[s], partition[s + 1]):
                if old_tp.get(layer) != tp:
                    moved += per[layer]
        ms = (moved
              / self.options.bw_to_bytes_per_ms(self.options.migration_bw_gbps)
              / self.options.migration_amortize_steps)
        if len(self._migration_cache) > _STAGE_MS_CACHE_MAX:
            self._migration_cache.clear()
        self._migration_cache[key] = ms
        return ms

    def _batch_gen_ms(self, count: int, device_type: str | None = None) -> float:
        """Input-pipeline cost; native mode reads the feeding stage's device
        type (the host attached to stage 0's chips generates batches).

        Strict-compat charges it per microbatch (``count``x), matching the
        reference (``cost_estimator.py:34-35``).  Native mode charges it ONCE
        per step: our executors build the global batch on host and
        microbatch-split on device (``execution.microbatch_split`` feeding a
        ``lax.scan``), so the pipeline does not re-run per microbatch.  The
        on-chip validation sweep pinned this: measured step time is flat in
        the microbatch count while per-microbatch charging bent predictions
        up at small mbs (calibration/tpu_validation_sweep.json)."""
        if self.options.strict_compat or device_type is None:
            per = self.profiles.model.batch_generator_ms
            return per * count
        return self.profiles.type_meta[device_type].batch_generator_ms


def _assemble_breakdown(
    cost: PlanCost,
    detail: dict,
    schedule: str,
    batches: int,
    virtual_stages: int,
    remat_fraction: float | None,
) -> CostBreakdown:
    """CostBreakdown from a PlanCost plus the estimator's ``_detail`` dump.

    Parity-preserving by construction: ``compute`` is the schedule priced
    with every stage leveled at the comm-free mean, ``imbalance`` the delta
    to the comm-free actual lens, and cp/ep/overhead are the exact terms
    ``get_cost`` added — so compute + imbalance + cp + ep + overhead ==
    ``PlanCost.execution_ms`` and the component sum == ``total_ms`` up to
    float association.
    """
    lens_nocomm = detail["lens_nocomm"]
    mean_l = sum(lens_nocomm) / len(lens_nocomm)
    balanced = schedule_execution_ms(
        schedule, [mean_l] * len(lens_nocomm), batches, virtual_stages,
        remat_fraction=remat_fraction)
    actual = schedule_execution_ms(
        schedule, lens_nocomm, batches, virtual_stages,
        remat_fraction=remat_fraction)
    # Overlap model: the PlanCost comm fields carry the EXPOSED (charged)
    # values, so the additive component keys switch to *_exposed and the
    # hidden remainder rides the side-channel ``hidden`` dict.
    hidden = detail.get("overlap_hidden")
    pp_key, dp_key = (
        ("pp_comm_exposed", "dp_comm_exposed") if hidden is not None
        else ("pp_comm", "dp_comm"))
    components = {
        "compute": balanced,
        "imbalance": actual - balanced,
        "cp_comm": cost.cp_comm_ms,
        "ep_comm": cost.ep_comm_ms,
        "step_overhead": detail["overhead_ms"],
        pp_key: cost.pp_comm_ms,
        dp_key: cost.dp_comm_ms,
        "fb_sync": cost.fb_sync_ms,
        "optimizer": cost.optimizer_ms,
        "batch_gen": cost.batch_gen_ms,
    }
    # spot model: the expected-recovery charge joins the additive sum only
    # when it is real (reserved-only breakdowns stay byte-identical)
    if detail.get("spot_recovery") is not None:
        components["expected_recovery"] = cost.expected_recovery_ms
    # migration model: same omission contract — fresh searches stay
    # byte-identical to pre-migration breakdowns
    if detail.get("migration") is not None:
        components["migration"] = cost.migration_ms
    return CostBreakdown(
        total_ms=cost.total_ms,
        components=components,
        stage_execution_ms=detail["sched_lens"],
        stage_comm_ms=detail.get("comm_by_stage", ()),
        stage_dp_comm_ms=detail.get("dp_costs", ()),
        stage_optimizer_ms=detail.get("opt_costs", ()),
        schedule=schedule,
        hidden=dict(hidden) if hidden else {},
    )


class UniformCostEstimator(_EstimatorBase):
    """Cost of a uniform Megatron-grid plan on a (nominally) homogeneous
    cluster (≅ ``HomoCostEstimator.get_cost``, ``cost_estimator.py:98-138``)."""

    def __init__(self, cluster, profiles, volume, options, counters=None):
        super().__init__(cluster, profiles, volume, options, counters)
        self.bandwidth = HomoScalarBandwidth(cluster, options.strict_compat)

    def get_breakdown(
        self, plan: UniformPlan, device_type: str,
    ) -> tuple[PlanCost, CostBreakdown]:
        """(cost, per-component breakdown) — same math path as ``get_cost``,
        so the scalar is bit-identical; run post-ranking on top-k plans."""
        detail: dict = {}
        cost = self.get_cost(plan, device_type, _detail=detail)
        num_mbs = plan.gbs // plan.mbs // plan.dp
        return cost, _assemble_breakdown(
            cost, detail, "gpipe", num_mbs, 1, None)

    def get_cost(self, plan: UniformPlan, device_type: str,
                 _detail: dict | None = None) -> PlanCost:
        L = self.volume.num_layers
        counts = uniform_layer_split(L, plan.pp)
        prof = self.profiles.get(device_type, plan.tp, plan.mbs)
        params = self.volume.parameter_bytes_per_layer(plan.tp)
        num_mbs = plan.gbs // plan.mbs // plan.dp

        overlap = self.options.overlap_active
        lens: list[float] = []
        stage_params: list[float] = []
        stage_memory: list[float] = []
        fb_sync = pp_cost = pp_exposed = 0.0
        for s in range(plan.pp):
            start = sum(counts[:s])
            end = start + counts[s]
            lens.append(prof.time_slice(start, end))
            stage_params.append(sum(params[start:end]))
            stage_memory.append(prof.memory_slice(start, end))
            if s == plan.pp - 1:
                fb_sync = self._fb_sync_ms([device_type], plan.tp, plan.mbs) * num_mbs
            else:
                bw = self.bandwidth.pp_bandwidth(plan.pp, plan.tp, s)
                t_pp = self._pp_cost_ms(
                    self._activation(end, plan.mbs, plan.tp), bw)
                pp_cost += t_pp
                if overlap:
                    # double-buffered send: only what outlasts the sender
                    # stage's per-microbatch compute stays exposed
                    pp_exposed += max(0.0, t_pp - lens[-1])

        # Per-device capacity of the profiled type (the reference reads node
        # 0's memory regardless of the device type being costed,
        # cost_estimator.py:31-32 — that's only right when they coincide).
        cap_type = (
            self.cluster.nodes[0].device_type if self.options.strict_compat
            else device_type)
        oom = self.cluster.memory_mb(cap_type) < max(stage_memory)
        overhead = self._step_overhead_ms([(device_type, plan.tp)])
        execution = (num_mbs - 1) * max(lens) + sum(lens) + overhead
        optimizer = self._optimizer_ms(device_type) / plan.pp / plan.tp
        # only the measured exposed share of the gradient sync rides the
        # critical path (overlap calibration; serial under strict_compat)
        dp_cost = self._dp_cost_ms(
            max(stage_params), self.bandwidth.dp_bandwidth(plan.pp, plan.tp),
            plan.dp) * self.options.dp_exposed_share
        batch_gen = self._batch_gen_ms(num_mbs, device_type)

        # Overlap model: the chunked gradient all-reduce hides under the
        # optimizer step, the double-buffered send under stage compute —
        # PlanCost charges the exposed remainders (additivity preserved).
        if overlap:
            dp_charge = max(0.0, dp_cost - optimizer)
            pp_charge = pp_exposed
        else:
            dp_charge = dp_cost
            pp_charge = pp_cost

        total = execution + fb_sync + optimizer + dp_charge + pp_charge + batch_gen
        recovery = 0.0
        spot_scale = self._spot_scale_of(
            plan.dp * plan.pp * plan.tp
            * self.cluster.devices[device_type].hazard_per_hr)
        if spot_scale:
            recovery = total * spot_scale
            total = total + recovery
        migration = 0.0
        if self.options.migration_active:
            bounds = [0]
            for c in counts:
                bounds.append(bounds[-1] + c)
            migration = self._migration_ms(
                (plan.tp,) * plan.pp, tuple(bounds))
            if migration:
                total = total + migration

        if _detail is not None:
            _detail.update(
                sched_lens=tuple(lens), lens_nocomm=tuple(lens),
                comm_by_stage=(0.0,) * plan.pp, overhead_ms=overhead)
            if overlap:
                _detail["overlap_hidden"] = {
                    "pp_comm": pp_cost - pp_charge,
                    "dp_comm": dp_cost - dp_charge,
                }
            if recovery:
                _detail["spot_recovery"] = recovery
            if migration:
                _detail["migration"] = migration
        return PlanCost(
            total_ms=total,
            execution_ms=execution,
            fb_sync_ms=fb_sync,
            optimizer_ms=optimizer,
            dp_comm_ms=dp_charge,
            pp_comm_ms=pp_charge,
            batch_gen_ms=batch_gen,
            expected_recovery_ms=recovery,
            migration_ms=migration,
            oom=oom,
        )


BandwidthFactory = Callable[[InterStagePlan], StageBandwidthModel]


class HeteroCostEstimator(_EstimatorBase):
    """Cost of a heterogeneous inter+intra stage plan
    (≅ ``HeteroCostEstimator.get_cost``, ``cost_estimator.py:199-244``)."""

    def __init__(self, cluster, profiles, volume, options,
                 bandwidth_factory: BandwidthFactory | None = None,
                 counters=None):
        super().__init__(cluster, profiles, volume, options, counters)
        self.data_balancer = DataBalancer(profiles)
        # CONTRACT: factories must depend on the plan's placement only
        # (node_sequence + device_groups) — the memo below reuses one model
        # across plans that share a placement but differ in batches/gbs.
        # Both in-repo models (HeteroScalarBandwidth, IciDcnBandwidth)
        # satisfy this; a batches-sensitive custom factory must not be
        # passed here.
        self.bandwidth_factory = bandwidth_factory or (
            lambda plan: HeteroScalarBandwidth(cluster, plan, options.strict_compat))
        # search-hot: bandwidth depends on the plan's *placement* only —
        # (node_sequence, device_groups) — which the enumeration shares
        # across every microbatch count and intra candidate; memoize the
        # model and its per-stage scans on that key (pure functions of it)
        self._bw_key = None
        self._bw_model = None
        self._bw_cache: dict = {}
        # Cross-candidate stage-time memo: many (inter, intra) candidates
        # share (stage composition, layer range, strategy) sub-problems.
        # Values are the SCALAR path's floats verbatim, so cached pricing is
        # bit-identical to uncached (tests/test_ledger.py pins exact
        # re-price equality).  Bounded like _bw_cache.
        self._stage_ms_cache: dict = {}
        # stage_time_grid prefix matrices per (device_type, tp)
        self._time_grid_cache: dict = {}
        # spot-hazard scale per placement — a pure function of
        # (node_sequence, device_groups); the batch path stores the SAME
        # float in its placement tables so both paths stay bit-identical
        self._spot_cache: dict = {}

    def _bandwidth_for(self, plan: InterStagePlan):
        key = (plan.node_sequence, plan.device_groups)
        if key != self._bw_key:
            self._bw_key = key
            self._bw_model = self.bandwidth_factory(plan)
            if self.counters is not None:
                self.counters.inc("bw_model_built")
            if len(self._bw_cache) > _BW_CACHE_MAX:
                self._bw_cache.clear()
                if self.counters is not None:
                    self.counters.inc("memo.bw.evict")
        return self._bw_model

    def _cache_key(self, kind: str, stage_id: int, *rest):
        return (kind, self._bw_key, stage_id, *rest)

    def _count_cache(self, hit: bool) -> None:
        if self.counters is not None:
            self.counters.inc("bw_cache_hit" if hit else "bw_cache_miss")

    def _profile_miss(self, t: str, tp: int, c: int) -> ProfileMissError:
        if self.counters is not None:
            self.counters.inc("profile_miss")
        return ProfileMissError(t, tp, c)

    def _dp_bw(self, bandwidth, stage_id: int, strat: Strategy) -> float:
        key = self._cache_key("dp", stage_id, strat.dp, strat.cp, strat.tp)
        if key not in self._bw_cache:
            self._bw_cache[key] = bandwidth.dp_bandwidth(stage_id, strat)
            self._count_cache(hit=False)
        else:
            self._count_cache(hit=True)
        return self._bw_cache[key]

    def _pp_bw(self, bandwidth, stage_id: int) -> float:
        key = self._cache_key("pp", stage_id)
        if key not in self._bw_cache:
            self._bw_cache[key] = bandwidth.pp_bandwidth(stage_id)
            self._count_cache(hit=False)
        else:
            self._count_cache(hit=True)
        return self._bw_cache[key]

    def _cp_bw(self, bandwidth, stage_id: int, strat: Strategy) -> float:
        key = self._cache_key("cp", stage_id, strat.dp, strat.cp, strat.tp)
        if key not in self._bw_cache:
            cp_bw_fn = getattr(bandwidth, "cp_bandwidth", None)
            self._bw_cache[key] = (
                cp_bw_fn(stage_id, strat) if cp_bw_fn is not None
                else bandwidth.dp_bandwidth(stage_id, strat))
            self._count_cache(hit=False)
        else:
            self._count_cache(hit=True)
        return self._bw_cache[key]

    def _spot_scale(self, plan: InterStagePlan) -> float:
        """The plan's expected-recovery multiplier (``_spot_scale_of`` over
        the per-rank hazards of the placement's device set), memoized per
        (node_sequence, device_groups)."""
        if not self.options.spot_active:
            return 0.0
        key = (plan.node_sequence, plan.device_groups)
        scale = self._spot_cache.get(key)
        if scale is None:
            ranks = rank_device_types(self.cluster, plan.node_sequence)
            hazard = 0.0
            for t in ranks[:sum(plan.device_groups)]:
                hazard += self.cluster.devices[t].hazard_per_hr
            scale = self._spot_scale_of(hazard)
            if len(self._spot_cache) > _BW_CACHE_MAX:
                self._spot_cache.clear()
            self._spot_cache[key] = scale
        return scale

    def stage_time_grid(
        self, device_type: str, tp: int, start: int, end: int,
    ) -> tuple[tuple[int, ...], np.ndarray]:
        """Vectorized batch costing of one stage's intra-strategy grid:
        ``(batch_sizes, times_ms)`` pricing layers ``[start, end)`` at EVERY
        profiled batch size of the ``(device_type, tp)`` configuration in one
        numpy subtraction of cached per-layer prefix sums.

        The scalar ``get_cost`` path and its ``CostBreakdown`` decomposition
        stay the oracle — prefix-sum association differs from the sequential
        ``time_slice`` sum at the last ulp, so this grid is for batch
        consumers (sweeps, regression tooling) and is oracle-tested against
        the scalar path at rtol 1e-9 (tools/check_search_regression.py)."""
        key = (device_type, tp)
        entry = self._time_grid_cache.get(key)
        if entry is None:
            bss = sorted(b for (_, t, b) in self.profiles.configs(device_type)
                         if t == tp)
            if not bss:
                raise ProfileMissError(device_type, tp, 1)
            mat = np.stack([
                np.asarray(self.profiles.get(device_type, tp, b).layer_times_ms,
                           dtype=np.float64)
                for b in bss])
            prefix = np.concatenate(
                [np.zeros((len(bss), 1)), np.cumsum(mat, axis=1)], axis=1)
            entry = (tuple(bss), prefix)
            self._time_grid_cache[key] = entry
        bss, prefix = entry
        return bss, prefix[:, end] - prefix[:, start]

    def _stage_execution_ms(
        self,
        plan: InterStagePlan,
        strategy: Strategy,
        stage_types: Sequence[str],
        start: int,
        end: int,
    ) -> float:
        # homo stages collapse dp/batches into the microbatch size, so plans
        # differing only in that split hit one entry; mixed stages key on the
        # microbatch total (two-step floor division is exact).  Successes
        # only: a profile miss re-runs so the raise and its ``profile_miss``
        # accounting replay identically on every repeat.
        if len(set(stage_types)) == 1:
            key = ("h", stage_types[0], strategy.tp,
                   plan.gbs // strategy.dp // plan.batches, strategy.cp,
                   start, end)
        else:
            key = ("m", tuple(stage_types), strategy.dp, strategy.tp,
                   strategy.cp, strategy.ep, strategy.zero,
                   plan.gbs // plan.batches, start, end)
        cached = self._stage_ms_cache.get(key)
        if cached is not None:
            if self.counters is not None:
                self.counters.inc("memo.stage_ms.hit")
            return cached
        if self.counters is not None:
            self.counters.inc("memo.stage_ms.miss")
        out = self._stage_execution_ms_uncached(
            plan, strategy, stage_types, start, end)
        if len(self._stage_ms_cache) > _STAGE_MS_CACHE_MAX:
            self._stage_ms_cache.clear()
            if self.counters is not None:
                self.counters.inc("memo.stage_ms.evict")
        self._stage_ms_cache[key] = out
        return out

    def _stage_execution_ms_uncached(
        self,
        plan: InterStagePlan,
        strategy: Strategy,
        stage_types: Sequence[str],
        start: int,
        end: int,
    ) -> float:
        dp, tp = strategy.dp, strategy.tp
        if len(set(stage_types)) == 1:
            bs = plan.gbs // dp // plan.batches
            # cp shards the sequence: per-device compute scales ~1/cp (ring
            # comm is charged separately in get_cost).
            return (self.profiles.get(stage_types[0], tp, bs)
                    .time_slice(start, end) / strategy.cp)
        if (self.volume.model.num_experts > 0
                and (strategy.ep > 1 or strategy.zero > 0
                     or strategy.cp > 1)):
            # MoE mixed-type stages carrying ep/zero/cp run the pad/mask
            # SINGLE program (the per-type group split supports none of
            # those axes — execution.hetero.plan_replica_groups), where
            # capacity-shaped expert compute pays the PADDED batch on
            # every replica: price the slowest type at max(split).
            split = self.data_balancer.partition(
                stage_types, dp, tp, plan.gbs // plan.batches)
            bs = max(split)
            slowest = 0.0
            for t in set(stage_types):
                total = 0.0
                for c in power_of_two_chunks(bs):
                    if c > self.options.max_profiled_bs:
                        raise self._profile_miss(t, tp, c)
                    total += self.profiles.get(t, tp, c).time_slice(start, end)
                slowest = max(slowest, total)
            return slowest / strategy.cp
        # Mixed-type stages (dense AND MoE without ep/zero/cp) execute as
        # per-type sub-mesh groups, each computing only its data-balancer
        # share — no padded rows, and an MoE group's expert capacity
        # derives from its own token count
        # (execution.hetero.StageSpec.replica_groups).  Price each replica
        # at its own type and real batch; the stage finishes with its
        # slowest replica.  (Until round 4 MoE stages priced the PADDED
        # batch on every replica — sound for the pad/mask executor but
        # structurally erasing the uneven-split advantage.)
        split = self.data_balancer.partition(
            stage_types, dp, tp, plan.gbs // plan.batches)
        chunks = replica_chunks(stage_types, dp)
        costs = []
        for replica_id, h_bs in enumerate(split):
            if h_bs == 0:
                continue
            rep_type = chunks[replica_id][0]
            total = 0.0
            for c in power_of_two_chunks(h_bs):
                if c > self.options.max_profiled_bs:
                    raise self._profile_miss(rep_type, tp, c)
                total += self.profiles.get(rep_type, tp, c).time_slice(start, end)
            costs.append(total)
        return max(costs)

    def get_breakdown(
        self,
        plan: InterStagePlan,
        strategies: Sequence[Strategy],
        layer_partition: Sequence[int],
        rank_types: Sequence[str] | None = None,
        schedule: str = "gpipe",
        virtual_stages: int = 1,
    ) -> tuple[PlanCost, CostBreakdown]:
        """(cost, per-component breakdown) — same math path as ``get_cost``,
        so the ranked scalar is bit-identical and the components sum to it;
        run post-ranking on top-k plans, never in the search hot loop."""
        detail: dict = {}
        cost = self.get_cost(plan, strategies, layer_partition, rank_types,
                             schedule, virtual_stages, _detail=detail)
        return cost, _assemble_breakdown(
            cost, detail, schedule, plan.batches, virtual_stages,
            self.options.remat_fwd_fraction)

    def get_cost(
        self,
        plan: InterStagePlan,
        strategies: Sequence[Strategy],
        layer_partition: Sequence[int],
        rank_types: Sequence[str] | None = None,
        schedule: str = "gpipe",
        virtual_stages: int = 1,
        _detail: dict | None = None,
    ) -> PlanCost:
        ranks = (
            list(rank_types) if rank_types is not None
            else rank_device_types(self.cluster, plan.node_sequence)
        )
        bandwidth = self._bandwidth_for(plan)
        L = self.volume.num_layers

        overlap = self.options.overlap_active
        lens: list[float] = []
        comm_by_stage: list[float] = []  # cp + ep, for breakdown reconcile
        cp_total = a2a_total = 0.0
        dp_costs: list[float] = []
        dp_exposed_costs: list[float] = []  # overlap model: max(0, dp - opt)
        opt_costs: list[float] = []
        fb_sync = pp_cost = pp_exposed = 0.0
        for stage_id, strat in enumerate(strategies):
            start_l, end_l = layer_partition[stage_id], layer_partition[stage_id + 1]
            r0, r1 = plan.stage_rank_range(stage_id)
            stage_types = ranks[r0:r1]

            stage_ms = self._stage_execution_ms(
                plan, strat, stage_types, start_l, end_l)
            # overlap window for the double-buffered boundary send: the
            # sender's compute-only per-microbatch time (cp/ep comm extends
            # the critical path and cannot hide another collective)
            compute_window = stage_ms
            mbs = plan.gbs // strat.dp // plan.batches
            cp_bw = None
            cp_ms = a2a_ms = 0.0
            if strat.cp > 1:
                # Context-parallel comm extends the stage's critical path
                # (un-overlapped model, cost/context_parallel.py): the ring
                # K/V rotation, or the Ulysses all-to-alls when the
                # strategy's cp_mode is "a2a" — cp_ms is mode-neutral, it is
                # whatever the priced cp_mode's traffic costs.
                cp_bw = self._cp_bw(bandwidth, stage_id, strat)
                cp_ms = cp_comm_ms(
                    self.volume.model, mbs, strat.cp, strat.tp,
                    attention_layer_range(self.volume.model, start_l, end_l),
                    cp_bw, mode=strat.cp_mode)
                stage_ms += cp_ms
            if strat.ep > 1:
                # MoE token all-to-all rides the links of the dp sub-group
                # the ep axis is carved from (un-overlapped model,
                # cost/expert_parallel.py).
                a2a_ms = ep_a2a_ms(
                    self.volume.model, mbs, strat.ep,
                    moe_layer_range(self.volume.model, start_l, end_l),
                    self._dp_bw(bandwidth, stage_id, strat), cp=strat.cp)
                stage_ms += a2a_ms
            comm_by_stage.append(cp_ms + a2a_ms)
            cp_total += cp_ms
            a2a_total += a2a_ms
            lens.append(stage_ms)

            if stage_id == plan.num_stages - 1:
                fb_sync = self._fb_sync_ms(stage_types, strat.tp, mbs) * plan.batches
            else:
                # cp shards the boundary activation by sequence; Megatron sp
                # additionally sequence-shards it over the tp group, so each
                # rank's p2p volume divides by tp too.
                sp_div = strat.tp if strat.sp else 1
                t_pp = self._pp_cost_ms(
                    self._activation(end_l, mbs, strat.tp) / strat.cp / sp_div,
                    self._pp_bw(bandwidth, stage_id))
                pp_cost += t_pp
                if overlap:
                    pp_exposed += max(0.0, t_pp - compute_window)

            stage_params = self.volume.stage_parameter_bytes(strat.tp, start_l, end_l)
            # Weights are replicated across cp (ring attention shards only the
            # sequence), so the gradient all-reduce spans dp*cp ranks; its ring
            # crosses both the dp and cp group links.
            sync_degree = strat.dp * strat.cp
            dp_bw = self._dp_bw(bandwidth, stage_id, strat)
            if cp_bw is not None:
                dp_bw = min(dp_bw, cp_bw)
            # Measured latency floor (calibrated bandwidth models only):
            # additive per gradient-sync ring, rescaled to this ring's steps.
            lat_fn = getattr(bandwidth, "collective_latency_ms", None)
            dp_latency = (lat_fn("all_reduce", sync_degree)
                          if lat_fn is not None else 0.0)
            # ZeRO-3 adds the backward parameter all-gather to the gradient
            # sync volume (cost/zero.py).
            zfac = zero_dp_factor(strat.zero)
            if strat.ep > 1:
                # Expert weights shard 1/ep: each shard all-reduces over the
                # dp*cp/ep replicas that hold it; dense weights over dp*cp.
                block_params = self.volume.stage_parameter_bytes(
                    strat.tp, max(start_l, 1), min(end_l, L - 1))
                expert_bytes = (block_params
                                * expert_param_fraction(self.volume.model)
                                / strat.ep)
                # two rings, two latency floors: the dense ring over all
                # sync_degree ranks, the expert ring over its 1/ep subgroup.
                # Volume terms charge only the measured exposed share
                # (overlap calibration); the alpha/latency floors stay fully
                # charged — a ring's startup cost cannot hide under compute.
                ep_latency = (lat_fn("all_reduce", sync_degree // strat.ep)
                              if lat_fn is not None else 0.0)
                dp_costs.append(zfac * (
                    self._dp_cost_ms(stage_params - expert_bytes * strat.ep,
                                     dp_bw, sync_degree)
                    + self._dp_cost_ms(expert_bytes, dp_bw,
                                       sync_degree // strat.ep))
                    * self.options.dp_exposed_share
                    + dp_latency + ep_latency)
            else:
                dp_costs.append(
                    zfac * self._dp_cost_ms(stage_params, dp_bw, sync_degree)
                    * self.options.dp_exposed_share
                    + dp_latency)

            opt_type = None if self.options.strict_compat else stage_types[0]
            # ZeRO >=1 shards the optimizer step itself over the data ranks.
            opt_shard = strat.data_ranks if strat.zero >= 1 else 1
            opt_costs.append(
                self._optimizer_ms(opt_type) / strat.tp / opt_shard
                * (end_l - start_l) / L)
            if overlap:
                # chunked gradient all-reduce overlaps the optimizer step:
                # only what outlasts this stage's optimizer stays exposed
                # (the latency floors inside dp_costs are charged within it)
                dp_exposed_costs.append(
                    max(0.0, dp_costs[-1] - opt_costs[-1]))

        # the schedule is a plan axis (cost/schedule.py): gpipe reproduces
        # the reference fill-drain verbatim; 1f1b adds the remat factor;
        # interleaved prices the implemented group-drain bubble and its
        # vs-times-more pp boundary crossings.
        # UNEVEN 1f1b partitions run on the LOCKSTEP shard_map executor
        # with every stage padded to the largest stage's block count
        # (execution.pipeline) — each ppermute-barriered tick costs the max
        # stage's time on EVERY device, so pricing must level the lens to
        # max(lens) or uneven plans come out systematically under-priced
        # (for even splits leveling is an identity: the fill-drain formula
        # already reduces to ticks * max).
        sched_lens = lens
        if schedule == "1f1b" and len(set(lens)) > 1:
            sched_lens = [max(lens)] * len(lens)
        execution = schedule_execution_ms(
            schedule, sched_lens, plan.batches, virtual_stages,
            remat_fraction=self.options.remat_fwd_fraction)
        send_factor = schedule_pp_send_factor(
            schedule, plan.num_stages, virtual_stages)
        pp_cost *= send_factor
        if overlap:
            pp_exposed *= send_factor
        # cp_comm_ms / ep_comm_ms report exactly the cp (ring or a2a) /
        # MoE all-to-all traffic's contribution to the schedule's execution
        # total (the with-comm minus without-comm delta, split pro rata), so
        # the breakdown fields reconcile for the validator.
        lens_nocomm = [l - c for l, c in zip(sched_lens, comm_by_stage)]
        comm_delta = execution - schedule_execution_ms(
            schedule, lens_nocomm, plan.batches, virtual_stages,
            remat_fraction=self.options.remat_fwd_fraction)
        comm_total = cp_total + a2a_total
        cp_cost = comm_delta * cp_total / comm_total if comm_total else 0.0
        ep_cost = comm_delta * a2a_total / comm_total if comm_total else 0.0
        # fitted per-program fixed cost (after comm_delta so the cp/ep
        # breakdown split excludes it); pairs limited to the (type, tp)
        # configurations the stages actually run.  Charged once per step
        # for RECTANGULAR plans (builder routes them to the gspmd /
        # shard_map-pipeline executors, which scan microbatches inside one
        # jit) but once per MICROBATCH for non-rectangular plans — the
        # multi-mesh executor dispatches each stage's program per
        # microbatch from a Python loop (execution/hetero.py), so its
        # per-program cost recurs plan.batches times.
        overhead_pairs: list[tuple[str, int]] = []
        for stage_id, strat in enumerate(strategies):
            r0, r1 = plan.stage_rank_range(stage_id)
            overhead_pairs.extend((t, strat.tp) for t in set(ranks[r0:r1]))
        rectangular = (
            len({(s.dp, s.tp, s.cp, s.ep) for s in strategies}) == 1
            and len(set(ranks)) <= 1)
        overhead = self._step_overhead_ms(overhead_pairs)
        if rectangular:
            overhead_term = overhead  # signed: the affine extrapolation
        else:
            # a real dispatch cannot cost negative time — a noise-negative
            # intercept must not get amplified by the microbatch count
            overhead_term = max(overhead, 0.0) * plan.batches
        execution += overhead_term
        first_stage_type = ranks[0] if ranks else None
        batch_gen = self._batch_gen_ms(plan.batches, first_stage_type)

        # Overlap model: charge only the exposed remainders — the per-stage
        # max of the dp sync that outlasts its optimizer, and the boundary
        # sends that outlast their sender's compute.  PlanCost stays
        # additive; the hidden share is reported through ``_detail``.
        if overlap:
            dp_charge = max(dp_exposed_costs)
            pp_charge = pp_exposed
        else:
            dp_charge = max(dp_costs)
            pp_charge = pp_cost

        total = (execution + fb_sync + max(opt_costs) + dp_charge
                 + pp_charge + batch_gen)
        recovery = 0.0
        spot_scale = self._spot_scale(plan)
        if spot_scale:
            recovery = total * spot_scale
            total = total + recovery
        migration = self._migration_ms(
            tuple(s.tp for s in strategies), tuple(layer_partition))
        if migration:
            total = total + migration

        if _detail is not None:
            # explainability dump (get_breakdown): the exact intermediates
            # the total was assembled from, so the component decomposition
            # reconciles with the ranked scalar by construction
            _detail.update(
                sched_lens=tuple(sched_lens),
                lens_nocomm=tuple(lens_nocomm),
                comm_by_stage=tuple(comm_by_stage),
                dp_costs=tuple(dp_exposed_costs if overlap else dp_costs),
                opt_costs=tuple(opt_costs),
                overhead_ms=overhead_term)
            if overlap:
                _detail["overlap_hidden"] = {
                    "pp_comm": pp_cost - pp_charge,
                    "dp_comm": max(dp_costs) - dp_charge,
                }
            if recovery:
                _detail["spot_recovery"] = recovery
            if migration:
                _detail["migration"] = migration

        return PlanCost(
            total_ms=total,
            execution_ms=execution,
            fb_sync_ms=fb_sync,
            optimizer_ms=max(opt_costs),
            dp_comm_ms=dp_charge,
            pp_comm_ms=pp_charge,
            batch_gen_ms=batch_gen,
            cp_comm_ms=cp_cost,
            ep_comm_ms=ep_cost,
            expected_recovery_ms=recovery,
            migration_ms=migration,
        )
