"""Expert-parallel (MoE) planning model — net-new TPU capability.

The reference has no MoE/EP support anywhere (SURVEY.md §2.2: "EP — Absent").
This module adds the cost and memory model for an expert-parallel plan axis:
a stage's experts may be sharded over ``Strategy.ep`` devices, with tokens
exchanged by all-to-all over the ICI mesh (execution counterpart:
:mod:`metis_tpu.models.moe` + the ``ep`` mesh axis).

Semantics (Megatron-style, encoded in ``core.types.Strategy``): **ep rides
inside dp** — an ep group is a sub-group of the stage's dp*cp data ranks, so
``ep`` must divide ``dp`` and consumes no extra devices.  Consequences the
model captures:

- **Compute** is unchanged by ep: every rank still processes its own
  microbatch; the all-to-all redistributes tokens to expert owners and back,
  and with balanced routing each rank computes the same token count it sent.
  (Imbalance shows up in measured profiles, not the analytic model.)
- **All-to-all traffic**: per MoE layer per microbatch, a rank dispatches
  ``mbs * seq * top_k`` token activations of ``hidden`` features, of which the
  fraction ``(ep-1)/ep`` crosses the wire, twice forward (dispatch + combine)
  and twice backward — 4 passes.  Charged un-overlapped (conservative;
  calibrate via the predicted-vs-measured validator).
- **Memory**: expert weights (and their optimizer state) shard 1/ep while
  everything else replicates.  Profiles report one per-layer total; the
  bs-sweep affine fit (``cost.context_parallel.ActivationSplitModel``) gives
  the static (weights+optimizer) vs activation split, and the analytic
  expert-parameter fraction of a block then scales only the expert share of
  the static part.
- **Gradient sync**: expert parameters all-reduce over ``dp*cp/ep`` ranks
  (the replicas of each expert shard); non-expert parameters over ``dp*cp``.
- **Dispatch/combine activation memory**: ``models.moe`` routes tokens in
  fixed-size groups (``MoEConfig.route_group_size``), so the one-hot
  dispatch/combine tensors are *linear* in tokens — which is exactly the
  affine-in-bs activation model the profile bs-sweep fit
  (``ActivationSplitModel``) assumes.  (With global routing they were
  O(T^2·top_k) and the fit under-predicted large batches — ADVICE r1.)
"""
from __future__ import annotations

from metis_tpu.core.config import ModelSpec
from metis_tpu.cost.context_parallel import ActivationSplitModel

# All-to-all passes per MoE layer per microbatch: dispatch + combine, forward
# and backward.
A2A_PASSES = 4


def ep_candidates(max_ep_degree: int, num_experts: int) -> list[int]:
    """Power-of-two ep degrees to search: ep must divide the expert count."""
    out = []
    ep = 2
    while ep <= max_ep_degree:
        if num_experts > 0 and num_experts % ep == 0:
            out.append(ep)
        ep *= 2
    return out


def moe_layer_range(model: ModelSpec, start: int, end: int) -> int:
    """How many layers in [start, end) carry experts (all transformer blocks
    of an MoE model; the embed/head pseudo-layers carry none)."""
    if model.num_experts <= 1:
        return 0
    lo = max(start, 1)
    hi = min(end, model.num_layers - 1)
    return max(0, hi - lo)


def a2a_buffer_bytes(model: ModelSpec, mbs: int, cp: int = 1) -> float:
    """One rank's all-to-all send buffer per MoE layer per pass: every
    routed token copy (``top_k`` per token) with ``hidden`` features.  With
    context parallelism each rank holds only seq/cp tokens, so combined
    (cp, ep) families dispatch proportionally less."""
    return (
        mbs
        * (model.sequence_length // cp)
        * model.expert_top_k
        * model.hidden_size
        * model.dtype_bytes
    )


def a2a_bytes_per_layer(model: ModelSpec, mbs: int, ep: int, cp: int = 1) -> float:
    """Un-overlapped all-to-all wire bytes one rank moves per MoE layer per
    microbatch (4 passes, cross-rank fraction (ep-1)/ep) — the *volume*
    view; the *time* model (``ep_a2a_ms``) prices the ring routing of that
    volume via ``cost.ici.all_to_all_ms``."""
    if ep <= 1:
        return 0.0
    return A2A_PASSES * a2a_buffer_bytes(model, mbs, cp) * (ep - 1) / ep


def ep_a2a_ms(
    model: ModelSpec, mbs: int, ep: int, num_moe_layers: int, bw_gbps: float,
    cp: int = 1,
) -> float:
    """All-to-all time (ms) for one microbatch across a stage's MoE layers:
    4 passes (dispatch + combine, forward + backward) of the per-rank send
    buffer through the bidirectional-ring all-to-all model
    (``ici.all_to_all_ms`` — per-link traffic ``n*V/8``, which *grows* with
    ep; the flat (ep-1)/ep volume model under-charged large ep by >2x)."""
    from metis_tpu.cost.ici import all_to_all_ms

    if ep <= 1 or num_moe_layers <= 0:
        return 0.0
    per_pass = all_to_all_ms(a2a_buffer_bytes(model, mbs, cp), ep, bw_gbps)
    return A2A_PASSES * per_pass * num_moe_layers


def expert_param_fraction(model: ModelSpec) -> float:
    """Analytic fraction of a transformer block's parameters that are expert
    weights (the part ep shards).  MoE blocks replace the dense FFN with
    ``num_experts`` expert FFNs plus a router."""
    if model.num_experts <= 1:
        return 0.0
    h = model.hidden_size
    f = h * model.ffn_multiplier
    expert = model.num_experts * 2 * h * f
    router = h * model.num_experts
    attn = 4 * h * h  # qkv + proj
    return expert / (expert + router + attn)


def expert_static_scale(
    model: ModelSpec, n_layers: int, ep: int
) -> list[float] | None:
    """Per-layer multiplier on static memory under ep-way expert sharding
    (None when nothing shards).  Block layers keep the dense fraction plus
    1/ep of the expert fraction; the embed/head pseudo-layers carry no
    experts."""
    if ep <= 1 or model.num_experts <= 1:
        return None
    frac = expert_param_fraction(model)
    block_scale = (1 - frac) + frac / ep
    return [1.0] + [block_scale] * (n_layers - 2) + [1.0]


def layer_memory_with_ep(
    split_model: ActivationSplitModel,
    model: ModelSpec,
    device_type: str,
    tp: int,
    bs: int,
    ep: int,
    cp: int = 1,
) -> tuple[float, ...]:
    """Per-layer memory row (MB) under expert sharding by ``ep`` (and,
    combined, sequence sharding by ``cp``).

    Expert relief applies the analytic expert fraction to the *static*
    component of block layers only (delegating to
    ``ActivationSplitModel.layer_memory`` for the split/fallback/clamp
    mechanics, which the cp path shares).
    """
    n = len(split_model.profiles.get(device_type, tp, bs).layer_memory_mb)
    return split_model.layer_memory(
        device_type, tp, bs, act_divisor=cp,
        static_scale=expert_static_scale(model, n, ep))
