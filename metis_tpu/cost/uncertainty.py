"""Distributional cost modeling: residual fits, risk scoring, variance.

The planner's estimators emit point costs; the accuracy ledger
(obs/ledger.py) measures how wrong those points are, per cost component
and per device type.  This module closes the loop (ROADMAP item 4): it
fits the ledger's *relative* residuals into a :class:`ResidualModel`
(lognormal when the ratio samples support it, empirical quantiles
otherwise — the "lognormal-or-empirical" rule, per device type), and
exposes the three consumers the uncertainty layer needs:

* a :class:`RiskScorer` — multiplicative tail factor per device-type
  set, used by planner/api.py, search/prune.py and search/exact.py to
  rank by a tail quantile or CVaR-alpha instead of the mean.  Factors
  are clamped at >= 1.0 and risk knobs at quantile >= 0.5, so a risk
  score is never below the point estimate — the exact backend's
  point-cost relaxation bounds stay admissible against score-space
  incumbents (prune strictly less than before, never wrongly);
* per-component ``(mean, variance)`` annotation for a
  :class:`~..core.types.CostBreakdown` — analytic propagation through
  the additive components, deterministic-seed Monte-Carlo for the
  pipeline-schedule max over stage times;
* :func:`certificate_confidence` — the honest "optimal at confidence
  p" for the exact backend's :class:`~..core.types.Certificate`:
  p -> 1 as residual variance -> 0, and degrades toward the coin-flip
  regime as variance grows.

Everything here is OPTIONAL: with no ResidualModel supplied every
search/ranking path takes the pre-existing point-estimate code and is
byte-identical to it (the frozen-golden contract).
"""

from __future__ import annotations

import math
import random
from dataclasses import dataclass, field, replace
from statistics import NormalDist
from typing import TYPE_CHECKING, Iterable, Mapping, Sequence

from ..core.events import NULL_LOG, EventLog

if TYPE_CHECKING:  # pragma: no cover - typing only
    from ..core.types import CostBreakdown
    from ..obs.ledger import AccuracyLedger

_NORMAL = NormalDist()

# Minimum matched samples before a per-device fit exists at all; below
# this the aggregate ("" device type) fit answers for everyone.
MIN_FIT_SAMPLES = 2
# Minimum samples for a parametric (lognormal) fit; fewer fall back to
# empirical quantiles of the observed ratios.
MIN_LOGNORMAL_SAMPLES = 4

_MC_DRAWS = 256
_MC_SEED = 0xC0FFEE


def z_score(q: float) -> float:
    """Standard-normal quantile (inverse CDF) of ``q`` in (0, 1)."""
    return _NORMAL.inv_cdf(min(max(q, 1e-9), 1.0 - 1e-9))


def normal_cdf(x: float) -> float:
    return _NORMAL.cdf(x)


def _percentile(sorted_vals: Sequence[float], q: float) -> float:
    """Linear-interpolated percentile of an ascending-sorted sequence."""
    if not sorted_vals:
        return 0.0
    if len(sorted_vals) == 1:
        return sorted_vals[0]
    pos = q * (len(sorted_vals) - 1)
    lo = int(pos)
    hi = min(lo + 1, len(sorted_vals) - 1)
    frac = pos - lo
    return sorted_vals[lo] * (1 - frac) + sorted_vals[hi] * frac


# ---------------------------------------------------------------------------
# per-device residual fits
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class ResidualFit:
    """Distribution of measured/predicted step-time ratios for one
    device type ('' = all samples pooled).

    ``kind`` is ``"lognormal"`` (``mu``/``sigma`` are the log-ratio
    moments) when at least :data:`MIN_LOGNORMAL_SAMPLES` strictly
    positive ratios exist, else ``"empirical"`` (``ratios`` holds the
    sorted observations).  ``rel_sigma`` is the plain standard
    deviation of the ratios — the relative residual scale used for
    sigma_ms and confidence-p."""

    device_type: str
    n: int
    kind: str
    mu: float = 0.0
    sigma: float = 0.0
    ratios: tuple[float, ...] = ()
    rel_sigma: float = 0.0

    def quantile_factor(self, q: float) -> float:
        """Multiplicative tail factor: the q-quantile of the ratio
        distribution, clamped at >= 1.0 (see module docstring on
        admissibility)."""
        if self.kind == "lognormal":
            f = math.exp(self.mu + z_score(q) * self.sigma)
        else:
            f = _percentile(self.ratios, q)
        return max(f, 1.0)

    def cvar_factor(self, alpha: float) -> float:
        """CVaR-alpha of the ratio distribution (mean of the worst
        ``1 - alpha`` tail), clamped at >= 1.0."""
        if self.kind == "lognormal":
            # E[X | X > x_alpha] for X ~ LogNormal(mu, sigma):
            # exp(mu + sigma^2/2) * Phi(sigma - z_alpha) / (1 - alpha)
            z = z_score(alpha)
            tail = _NORMAL.cdf(self.sigma - z)
            f = math.exp(self.mu + 0.5 * self.sigma * self.sigma)
            f *= tail / max(1.0 - alpha, 1e-9)
        else:
            cut = _percentile(self.ratios, alpha)
            tail_vals = [r for r in self.ratios if r >= cut] or [cut]
            f = sum(tail_vals) / len(tail_vals)
        return max(f, 1.0)

    def to_json_dict(self) -> dict:
        return {"device_type": self.device_type, "n": self.n,
                "kind": self.kind, "mu": round(self.mu, 6),
                "sigma": round(self.sigma, 6),
                "rel_sigma": round(self.rel_sigma, 6)}


def _fit_ratios(device_type: str, ratios: list[float]) -> ResidualFit:
    n = len(ratios)
    mean = sum(ratios) / n
    var = max(sum(r * r for r in ratios) / n - mean * mean, 0.0)
    rel_sigma = math.sqrt(var)
    if n >= MIN_LOGNORMAL_SAMPLES and all(r > 0 for r in ratios):
        logs = [math.log(r) for r in ratios]
        mu = sum(logs) / n
        lvar = max(sum(x * x for x in logs) / n - mu * mu, 0.0)
        return ResidualFit(device_type=device_type, n=n, kind="lognormal",
                           mu=mu, sigma=math.sqrt(lvar),
                           rel_sigma=rel_sigma)
    return ResidualFit(device_type=device_type, n=n, kind="empirical",
                       ratios=tuple(sorted(ratios)), rel_sigma=rel_sigma)


# ---------------------------------------------------------------------------
# the model
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class ResidualModel:
    """Per-device-type residual distributions fit from an AccuracyLedger.

    ``fits`` maps device type -> :class:`ResidualFit`; the pooled fit
    under ``""`` always exists when any fit does and answers for device
    types never measured.  ``component_stats`` carries the ledger's
    per-component residual moments (ms at ledger scale) keyed by device
    type first — the input for CostBreakdown variance annotation —
    and ``mean_predicted_ms`` anchors those ms-scale variances so they
    can be rescaled to a candidate plan's magnitude."""

    fits: dict[str, ResidualFit] = field(default_factory=dict)
    component_stats: dict[str, dict[str, dict]] = field(default_factory=dict)
    mean_predicted_ms: float = 0.0
    n_samples: int = 0

    def __bool__(self) -> bool:
        return bool(self.fits)

    def fit_for(self, device_types: Iterable[str] = ()) -> ResidualFit | None:
        """The riskiest (largest rel_sigma) fit among the given device
        types, falling back to the pooled fit for types never measured."""
        best: ResidualFit | None = None
        for t in device_types:
            f = self.fits.get(t)
            if f is not None and (best is None or f.rel_sigma > best.rel_sigma):
                best = f
        return best if best is not None else self.fits.get("")

    def rel_sigma(self, device_types: Iterable[str] = ()) -> float:
        f = self.fit_for(device_types)
        return f.rel_sigma if f else 0.0

    def sigma_ms(self, total_ms: float,
                 device_types: Iterable[str] = ()) -> float:
        """Residual standard deviation of a plan's total, in ms."""
        return abs(total_ms) * self.rel_sigma(device_types)

    def quantile_factor(self, q: float,
                        device_types: Iterable[str] = ()) -> float:
        f = self.fit_for(device_types)
        return f.quantile_factor(q) if f else 1.0

    def cvar_factor(self, alpha: float,
                    device_types: Iterable[str] = ()) -> float:
        f = self.fit_for(device_types)
        return f.cvar_factor(alpha) if f else 1.0

    # -- per-component variance -------------------------------------------

    def component_relvar(self, component: str,
                         device_types: Iterable[str] = ()) -> float:
        """Relative residual variance of one CostBreakdown component:
        ledger var_ms scaled by the ledger-scale mean predicted total,
        worst over the given device types (pooled stats fallback)."""
        if self.mean_predicted_ms <= 0:
            return 0.0
        worst = 0.0
        seen = False
        for t in device_types:
            stats = self.component_stats.get(t, {}).get(component)
            if stats:
                seen = True
                worst = max(worst, stats.get("var_ms", 0.0))
        if not seen:
            stats = self.component_stats.get("", {}).get(component)
            worst = stats.get("var_ms", 0.0) if stats else 0.0
        return worst / (self.mean_predicted_ms ** 2)

    def to_summary(self) -> dict:
        return {"n_samples": self.n_samples,
                "mean_predicted_ms": round(self.mean_predicted_ms, 4),
                "device_types": sorted(t for t in self.fits if t),
                "fits": {t: f.to_json_dict()
                         for t, f in sorted(self.fits.items())}}


def fit_residual_model(ledger: "AccuracyLedger", *,
                       min_samples: int = MIN_FIT_SAMPLES,
                       events: EventLog = NULL_LOG) -> ResidualModel | None:
    """Fit a :class:`ResidualModel` from a ledger's matched samples.

    Returns None when fewer than ``min_samples`` matched (predicted AND
    measured, both finite and positive) samples exist — callers treat
    None as "stay in point mode".  Emits one ``residual_fit`` event on
    success."""
    by_dev: dict[str, list[float]] = {}
    pooled: list[float] = []
    total_pred = 0.0
    for s in ledger.samples:
        p, m = s.predicted_ms, s.measured_ms
        if (p is None or not math.isfinite(p) or p <= 0
                or not math.isfinite(m) or m <= 0):
            continue
        ratio = m / p
        pooled.append(ratio)
        total_pred += p
        dev = s.device_type or ""
        if dev:
            by_dev.setdefault(dev, []).append(ratio)
    if len(pooled) < max(min_samples, 1):
        return None
    fits = {"": _fit_ratios("", pooled)}
    for dev, ratios in sorted(by_dev.items()):
        if len(ratios) >= max(min_samples, 1):
            fits[dev] = _fit_ratios(dev, ratios)
    model = ResidualModel(
        fits=fits,
        component_stats=dict(ledger.component_residuals(by_device=True)),
        mean_predicted_ms=total_pred / len(pooled),
        n_samples=len(pooled),
    )
    events.emit("residual_fit", n_samples=model.n_samples,
                n_device_types=len(fits) - 1,
                rel_sigma=round(fits[""].rel_sigma, 6),
                kind=fits[""].kind)
    return model


# ---------------------------------------------------------------------------
# risk scoring (the search-hot piece)
# ---------------------------------------------------------------------------


class RiskScorer:
    """Turns a point total into a tail-risk score for ranking.

    ``score(total_ms, node_sequence)`` = total * factor(device types),
    where the factor is the configured quantile (or CVaR-alpha) of the
    residual ratio distribution, worst-case over the plan's device
    types, clamped >= 1.0 and cached per type-set.  With uniform
    per-type variance the factor is a constant, so the score is a
    monotone transform of the point total and the ranking is unchanged
    — the satellite-3 invariant."""

    __slots__ = ("model", "mode", "param", "_cache")

    def __init__(self, model: ResidualModel, *, quantile: float = 0.0,
                 cvar_alpha: float = 0.0):
        if cvar_alpha:
            self.mode, self.param = "cvar", float(cvar_alpha)
        else:
            self.mode, self.param = "quantile", float(quantile or 0.5)
        self.model = model
        self._cache: dict[tuple[str, ...], float] = {}

    def factor(self, device_types: Iterable[str] = ()) -> float:
        key = tuple(sorted(set(device_types)))
        f = self._cache.get(key)
        if f is None:
            if self.mode == "cvar":
                f = self.model.cvar_factor(self.param, key)
            else:
                f = self.model.quantile_factor(self.param, key)
            self._cache[key] = f
        return f

    def score(self, total_ms: float,
              device_types: Iterable[str] = ()) -> float:
        return total_ms * self.factor(device_types)

    @property
    def z_q(self) -> float:
        """The standard-normal z of the configured tail point (the
        quantile, or the CVaR threshold alpha) — >= 0 by the knob
        validation, used to center confidence-p."""
        return max(z_score(self.param), 0.0)

    def describe(self) -> dict:
        """Risk-posture annotation for decision records / why."""
        if self.mode == "cvar":
            return {"ranking": "cvar", "cvar_alpha": self.param}
        return {"ranking": "quantile", "risk_quantile": self.param}


def make_risk_scorer(config, model: ResidualModel | None) -> RiskScorer | None:
    """Build the scorer a SearchConfig's risk knobs ask for, or None in
    point mode (no knobs set, or no/empty residual model)."""
    if model is None or not model:
        return None
    q = getattr(config, "risk_quantile", 0.0) or 0.0
    a = getattr(config, "cvar_alpha", 0.0) or 0.0
    if not q and not a:
        return None
    return RiskScorer(model, quantile=q, cvar_alpha=a)


# ---------------------------------------------------------------------------
# variance propagation
# ---------------------------------------------------------------------------


def propagate_sum_variance(variances: Iterable[float]) -> float:
    """Variance of a sum of independent components: the analytic rule."""
    return sum(max(v, 0.0) for v in variances)


def mc_max_moments(means: Sequence[float], sigmas: Sequence[float],
                   draws: int = _MC_DRAWS,
                   seed: int = _MC_SEED) -> tuple[float, float]:
    """(mean, variance) of ``max_i N(means[i], sigmas[i]^2)`` by
    deterministic-seed Monte-Carlo — the fallback for pipeline-schedule
    maxes, where no closed form exists.  Fixed seed keeps repeated
    explains byte-identical."""
    if not means:
        return 0.0, 0.0
    if all(s <= 0 for s in sigmas):
        m = max(means)
        return m, 0.0
    rng = random.Random(seed)
    acc = acc2 = 0.0
    for _ in range(draws):
        m = max(mu + sig * rng.gauss(0.0, 1.0)
                for mu, sig in zip(means, sigmas))
        acc += m
        acc2 += m * m
    mean = acc / draws
    return mean, max(acc2 / draws - mean * mean, 0.0)


def annotate_breakdown(breakdown: "CostBreakdown", model: ResidualModel,
                       device_types: Iterable[str] = ()) -> "CostBreakdown":
    """Attach per-component variances (ms^2) to a CostBreakdown.

    Additive components get the analytic rule: var_c = relvar_c *
    value_c^2, scaled from the ledger's per-component residual moments.
    The schedule's max over per-stage execution times (the ``compute``
    + ``imbalance`` pair) gets the Monte-Carlo fallback over the stage
    vector when it is present.  The input is returned unchanged (no
    ``component_variance``) when the model has no component stats."""
    types = tuple(device_types)
    variances: dict[str, float] = {}
    for comp, value in breakdown.components.items():
        rv = model.component_relvar(comp, types)
        if rv > 0:
            variances[comp] = rv * value * value
    if breakdown.stage_execution_ms:
        rel = model.rel_sigma(types)
        if rel > 0:
            stages = breakdown.stage_execution_ms
            mc_mean, mc_var = mc_max_moments(
                list(stages), [s * rel for s in stages])
            if mc_var > 0:
                # the schedule max rides the compute+imbalance pair;
                # fold the MC variance onto ``compute`` (the larger of
                # the two by construction) rather than double-charging
                variances["compute"] = max(
                    variances.get("compute", 0.0), mc_var)
            del mc_mean
    if not variances:
        return breakdown
    return replace(breakdown, component_variance={
        k: round(v, 6) for k, v in sorted(variances.items())})


def breakdown_sigma_ms(breakdown: "CostBreakdown") -> float:
    """Std-dev of the total implied by an annotated breakdown (sum
    rule over the per-component variances)."""
    return math.sqrt(propagate_sum_variance(
        breakdown.component_variance.values()))


# ---------------------------------------------------------------------------
# probabilistic certificates
# ---------------------------------------------------------------------------


def certificate_confidence(margin_ms: float, sigma_ms: float,
                           z_q: float = 0.0) -> float:
    """Honest confidence that the certified plan is truly optimal.

    ``margin_ms`` is the proven point-cost headroom between the
    incumbent and its nearest competitor (runner-up total when the
    search completed; the bound gap — possibly negative — when it
    stopped at the deadline).  Treating both true costs as independent
    normals around their point estimates with the residual sigma,
    p = Phi((margin + z_q * sigma) / (sigma * sqrt(2))).  sigma -> 0
    gives p -> 1 (a point certificate is certain of itself); sigma ->
    infinity decays p toward Phi(z_q / sqrt(2)) < 1 — confidence
    degrades honestly as residual variance grows."""
    if sigma_ms <= 0 or math.isinf(margin_ms):
        return 1.0
    return _NORMAL.cdf((margin_ms + z_q * sigma_ms)
                       / (sigma_ms * math.sqrt(2.0)))
