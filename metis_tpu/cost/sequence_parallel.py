"""Megatron-style sequence parallelism (sp) planning model.

Absent from the reference (SURVEY.md §2.2 "SP — Absent").  SP rides the tp
axis: the non-matmul regions of a block (layernorms, residual stream,
dropout) shard their activations along the *sequence* dimension over the tp
group, and the two TP all-reduces per block become reduce-scatter +
all-gather pairs.  Consequences the model captures:

- **Time**: unchanged.  A ring reduce-scatter plus all-gather moves the same
  wire bytes as the ring all-reduce it replaces, and FLOPs don't move; the
  profiled tp times remain valid for sp variants.
- **Pipeline boundary**: the activation crossing a stage boundary is
  sequence-sharded, so each rank's p2p volume divides by tp.
- **Memory**: only the *replicated* share of activation memory divides by
  tp — the matmul-region activations inside attention/MLP are already
  tp-sharded in the measured profiles.  The split is recovered from data, not
  assumed: the per-layer activation slope (from the bs sweep) as a function
  of tp fits ``slope(tp) = A + B/tp`` — A is the replicated share SP can
  shard, B the already-sharded share.  With fewer than two tp points the
  split is unidentifiable and sp gets **no** memory relief (conservative,
  like the cp/ep fallbacks).
"""
from __future__ import annotations

from metis_tpu.cost.context_parallel import ActivationSplitModel


class SequenceParallelModel:
    """Per-layer replicated-activation share fit over the profile store's tp
    sweep, cached per device type."""

    def __init__(self, split_model: ActivationSplitModel):
        self.split_model = split_model
        self._cache: dict[str, tuple[tuple[float, ...], tuple[float, ...]] | None] = {}

    def _fit(self, device_type: str):
        """Least squares of slope(tp) = A + B * (1/tp) per layer, from the
        activation slopes the bs-sweep fit produced at each profiled tp."""
        profiles = self.split_model.profiles
        tps = sorted({t for (d, t, _) in profiles.configs(device_type)})
        points = []  # (1/tp, slopes_per_layer)
        for tp in tps:
            fitted = self.split_model.split(device_type, tp)
            if fitted is not None:
                points.append((1.0 / tp, fitted[1]))
        if len(points) < 2:
            return None
        xs = [x for x, _ in points]
        n = len(xs)
        mean_x = sum(xs) / n
        var_x = sum((x - mean_x) ** 2 for x in xs)
        if var_x == 0:
            return None
        num_layers = len(points[0][1])
        rep: list[float] = []   # A: replicated share (MB per bs unit)
        shd: list[float] = []   # B: tp-sharded share
        for layer in range(num_layers):
            ys = [slopes[layer] for _, slopes in points]
            mean_y = sum(ys) / n
            b = sum((x - mean_x) * (y - mean_y)
                    for x, y in zip(xs, ys)) / var_x
            a = mean_y - b * mean_x
            rep.append(max(a, 0.0))
            shd.append(max(b, 0.0))
        return tuple(rep), tuple(shd)

    def replicated_share(self, device_type: str):
        if device_type not in self._cache:
            self._cache[device_type] = self._fit(device_type)
        return self._cache[device_type]

    def act_scale(self, device_type: str, tp: int) -> tuple[float, ...] | None:
        """Per-layer multiplier on the activation component under sp: the
        replicated share divides by tp, the rest is already sharded.  None
        (no relief) when tp <= 1 or the split is unidentifiable."""
        if tp <= 1:
            return None
        fitted = self.replicated_share(device_type)
        if fitted is None:
            return None
        rep, shd = fitted
        out = []
        for a, b in zip(rep, shd):
            total = a + b / tp          # measured slope at this tp (by fit)
            with_sp = a / tp + b / tp
            out.append(with_sp / total if total > 0 else 1.0)
        return tuple(out)
