"""Batched plan costing — the array-native primary pricing path.

``BatchCostEstimator`` prices whole batches of (inter, intra) candidates
against precomputed tables instead of re-walking the scalar estimator's
per-stage Python for every candidate:

- **Stage-time matrices** ``E[(type, tp, bs)][start][end]`` — every layer
  slice of every profiled configuration, built once by the exact sequential
  left-to-right accumulation ``LayerProfile.time_slice`` performs, so a
  table lookup returns the scalar path's float VERBATIM (the numpy
  prefix-subtraction ``stage_time_grid`` stays a side API: its association
  differs at the last ulp, which is why it is the rtol-1e-9 oracle and not
  the primary path).
- **Per-placement tables** keyed on ``(node_sequence, device_groups)`` —
  pp-link denominators, dp ring factors, collective-latency floors, and
  per-stage type metadata — shared by every microbatch count and intra
  candidate of a placement.
- **Cross-candidate memos** for boundary-activation volumes, stage
  parameter bytes, optimizer rates, and fb-sync maxima.

Exactness contract: for every candidate the fast path handles (gpipe,
virtual_stages=1, cp=ep=1, zero=0 — the base search family), the returned
``PlanCost`` is bit-identical to ``HeteroCostEstimator.get_cost``: every
float is either produced by the same calls in the same order or by an
IEEE-exact algebraic identity (x/1, x*1.0, x+0.0 for x >= 0, and the
left-associated factoring of the dp ring term).  Candidates outside the
fast family fall through to the scalar estimator wholesale.  The scalar
path is the parity oracle: ``tools/check_search_regression.py`` asserts
ranked-plan byte-identity between the two on the frozen parity workload.

Profile misses replay exactly: tables negative-cache the miss, and
``cost_many`` returns None for a candidate at the same first-missing-stage
point where the scalar path would raise ``ProfileMissError``.
"""
from __future__ import annotations

from typing import Sequence

from metis_tpu.balance.stage_perf import rank_device_types
from metis_tpu.core.types import InterStagePlan, IntraStagePlan, PlanCost
from metis_tpu.cost.bandwidth import HeteroScalarBandwidth

# Negative-cache sentinel: the scalar path raises ProfileMissError here.
_MISS = object()

# Placement-table memo bound (entries): one entry per distinct
# (node_sequence, device_groups); wholesale clear beyond this, so a
# long-lived daemon sweeping many clusters cannot grow it unboundedly.
_PLACEMENT_MEMO_MAX = 8192

# Per-node-sequence memo bound (dp factors, pp denominators, stage metas).
_SEQ_MEMO_MAX = 200_000


class _StageMeta:
    """Per-stage placement facts resolved once per (node_sequence, rank
    range) and SHARED across placements: none of these fields read the
    device grouping beyond the stage's own rank slice, so every placement
    of a node sequence that puts some stage on ranks [r0, r1) reuses the
    same meta — and with it the stage-time and fb-sync tables."""

    __slots__ = ("homo", "types", "typeset", "opt_type", "etabs", "fbtabs")


class _PlacementTables:
    __slots__ = ("bw", "num_stages", "stages", "pp_den", "lat_fn", "latmap",
                 "dpfac", "ranks_uniform", "first_type", "batch_gen",
                 "seq_key", "ranges", "spot_scale")


class BatchCostEstimator:
    """Table-driven batch pricing over a ``HeteroCostEstimator``.

    The scalar estimator supplies the profiles (post affine-view), the
    volume model, the options, and the bandwidth model/memos — this class
    adds the candidate-batch evaluation on top and never diverges from the
    scalar math (see module docstring for the exactness contract).
    """

    def __init__(self, scalar, counters=None):
        self.scalar = scalar
        self.counters = counters
        self.options = scalar.options
        self.profiles = scalar.profiles
        self.volume = scalar.volume
        self._L = scalar.volume.num_layers
        # hoisted invariants of the per-stage assembly
        self._share = scalar.options.dp_exposed_share
        self._overlap = scalar.options.overlap_active
        self._mig_active = scalar.options.migration_active
        self._so = scalar._step_overhead
        self._bg_per = scalar.profiles.model.batch_generator_ms
        # cross-placement memos
        self._pcache: dict = {}   # placement -> _PlacementTables
        self._etabs: dict = {}    # (type, tp, bs) -> slice-sum matrix | _MISS
        self._actmap: dict = {}   # (boundary, mbs, tp) -> activation volume
        self._pmap: dict = {}     # (tp, start, end) -> stage parameter bytes
        self._omap: dict = {}     # (opt_type, tp) -> optimizer ms / tp
        # per-node-sequence memos (see _StageMeta / _build_dpfac): the
        # scalar bandwidth model's dp/pp values are pure functions of the
        # node sequence and explicit rank ranges, so placements share them
        self._seq_meta: dict = {}   # (node_sequence, r0, r1) -> _StageMeta
        self._seq_dpfac: dict = {}  # (node_sequence, r0, r1, dp) -> factor
        self._seq_ppden: dict = {}  # (node_sequence, r0, end2) -> denominator
        # optional jit backend (SearchConfig.cost_backend="jax"): shares
        # every memo above through the host reference and stays
        # byte-identical to the numpy loop (cost/jax_backend.py docstring);
        # construction raises MetisError when jax is unavailable
        self._jax = None
        if getattr(scalar.options, "cost_backend", "numpy") == "jax":
            from metis_tpu.cost.jax_backend import JaxCostBackend
            self._jax = JaxCostBackend(self)

    # -- public API --------------------------------------------------------
    def cost_many(
        self, inter: InterStagePlan, intras: Sequence[IntraStagePlan],
    ) -> list[PlanCost | None]:
        """Price a batch of intra candidates of one inter plan.

        Returns one entry per candidate, aligned with ``intras``:
        a ``PlanCost`` bit-identical to the scalar path's, or None where
        the scalar path would raise ``ProfileMissError``.  An empty batch
        returns an empty list (no tables are touched).
        """
        if not intras:
            return []
        P = self._placement(inter)
        if self._jax is not None:
            return self._jax.cost_many(P, inter, intras)
        return [self._cost_one(P, inter, intra) for intra in intras]

    def _cost_one(self, P, inter, intra):
        strategies = intra.strategies
        if (intra.schedule != "gpipe" or intra.virtual_stages != 1
                or any(s.cp != 1 or s.ep != 1 or s.zero != 0
                       for s in strategies)):
            # outside the fast family (cp/ep/zero/schedule axes): the scalar
            # path prices it — these are a vanishing share of the search
            try:
                return self.scalar.get_cost(
                    inter, strategies, intra.layer_partition,
                    schedule=intra.schedule,
                    virtual_stages=intra.virtual_stages)
            except KeyError:
                return None
        return self._fast(P, inter, strategies, intra.layer_partition)

    # -- fast path ---------------------------------------------------------
    # Term structure (execution + pp/dp exposure + overhead + fb-sync +
    # optimizer + spot/migration) is mirrored by the admissible per-class
    # floors in search/exact.RelaxationBound — a new additive term here
    # needs a matching floor there (or 0, which stays admissible) or the
    # exact backend's certificates go stale.
    def _fast(self, P, inter, strategies, partition):
        batches = inter.batches
        # gbs // dp // batches == (gbs // batches) // dp for positive ints
        g2 = inter.gbs // batches
        stages = P.stages
        S = P.num_stages
        last = S - 1
        pp_den = P.pp_den
        dpfac = P.dpfac
        lat_fn = P.lat_fn
        actmap = self._actmap
        pmap = self._pmap
        omap = self._omap
        share = self._share
        ov = self._overlap
        L = self._L
        sum_l = 0.0
        max_l = max_opt = max_dp = max_dpe = None
        pp_cost = pp_exposed = 0.0
        fb_sync = 0.0
        for s in range(S):
            strat = strategies[s]
            dp = strat.dp
            tp = strat.tp
            start = partition[s]
            end = partition[s + 1]
            meta = stages[s]
            mbs = g2 // dp
            if meta.homo:
                E = meta.etabs.get((tp, mbs))
                if E is None:
                    E = self._build_etab(meta, tp, mbs)
                if E is _MISS:
                    return None
                stage_ms = E[start][end]
            else:
                try:
                    stage_ms = self.scalar._stage_execution_ms(
                        inter, strat, meta.types, start, end)
                except KeyError:
                    return None
            sum_l += stage_ms
            if max_l is None or stage_ms > max_l:
                max_l = stage_ms
            if s == last:
                fb = meta.fbtabs.get((tp, mbs))
                if fb is None:
                    fb = self._build_fb(meta, tp, mbs)
                if fb is _MISS:
                    return None
                fb_sync = fb * batches
            else:
                akey = (end, mbs, tp)
                act = actmap.get(akey)
                if act is None:
                    act = self.scalar._activation(end, mbs, tp)
                    actmap[akey] = act
                if strat.sp:
                    # the scalar divides by cp (==1 here, exact) then tp
                    act = act / tp
                t_pp = act / pp_den[s]
                pp_cost += t_pp
                if ov:
                    # overlap model: the same floats, same max(0, send -
                    # sender compute) as the scalar path (gpipe send
                    # factor is 1.0 so the post-loop scaling is skipped
                    # exactly, like pp_cost itself)
                    pp_exposed += max(0.0, t_pp - stage_ms)
            # the ring factor is tp-independent (dp_bandwidth never reads tp)
            dkey = (s, dp)
            q = dpfac.get(dkey)
            if q is None:
                q = self._build_dpfac(P, s, strat)
                dpfac[dkey] = q
            pkey = (tp, start, end)
            params = pmap.get(pkey)
            if params is None:
                params = self.volume.stage_parameter_bytes(tp, start, end)
                pmap[pkey] = params
            if lat_fn is None:
                dpv = q * params * share
            else:
                lat = P.latmap.get(dp)
                if lat is None:
                    lat = lat_fn("all_reduce", dp)
                    P.latmap[dp] = lat
                dpv = q * params * share + lat
            if max_dp is None or dpv > max_dp:
                max_dp = dpv
            okey = (meta.opt_type, tp)
            o = omap.get(okey)
            if o is None:
                o = self.scalar._optimizer_ms(meta.opt_type) / tp
                omap[okey] = o
            opt = o * (end - start) / L
            if max_opt is None or opt > max_opt:
                max_opt = opt
            if ov:
                # chunked dp sync hides under the optimizer: same dpv/opt
                # floats as the scalar, so the exposed max is bit-identical
                dpe = max(0.0, dpv - opt)
                if max_dpe is None or dpe > max_dpe:
                    max_dpe = dpe

        # gpipe fill-drain (cost/schedule.py) inlined; pp send factor is 1.0
        # and the cp/ep comm delta is exactly 0.0 in this family
        execution = (batches - 1) * max_l + sum_l
        so = self._so
        if so:
            st0 = strategies[0]
            d0, t0 = st0.dp, st0.tp
            uniform = True
            pairs = set()
            for s in range(S):
                strat = strategies[s]
                if strat.dp != d0 or strat.tp != t0:
                    uniform = False
                stp = strat.tp
                for t in stages[s].typeset:
                    pairs.add((t, stp))
            overhead = max((so.get(p, 0.0) for p in pairs), default=0.0)
            if uniform and P.ranks_uniform:
                execution = execution + overhead
            else:
                execution = execution + max(overhead, 0.0) * batches
        if self.options.strict_compat or P.first_type is None:
            batch_gen = self._bg_per * batches
        else:
            batch_gen = P.batch_gen
        dp_charge = max_dpe if ov else max_dp
        pp_charge = pp_exposed if ov else pp_cost
        total = (execution + fb_sync + max_opt + dp_charge + pp_charge
                 + batch_gen)
        # spot model: the scalar's placement-memoized scale verbatim
        # (_placement stores the same float), so recovery and total stay
        # bit-identical to HeteroCostEstimator.get_cost
        recovery = 0.0
        spot_scale = P.spot_scale
        if spot_scale:
            recovery = total * spot_scale
            total = total + recovery
        # migration model: the scalar's memoized helper verbatim — it is a
        # pure function of (tps, partition), so the float here IS the
        # scalar path's
        migration = 0.0
        if self._mig_active:
            migration = self.scalar._migration_ms(
                tuple(s.tp for s in strategies), tuple(partition))
            if migration:
                total = total + migration
        return PlanCost(
            total_ms=total,
            execution_ms=execution,
            fb_sync_ms=fb_sync,
            optimizer_ms=max_opt,
            dp_comm_ms=dp_charge,
            pp_comm_ms=pp_charge,
            batch_gen_ms=batch_gen,
            cp_comm_ms=0.0,
            ep_comm_ms=0.0,
            expected_recovery_ms=recovery,
            migration_ms=migration,
        )

    # -- table builders ----------------------------------------------------
    def _placement(self, plan: InterStagePlan) -> _PlacementTables:
        key = (plan.node_sequence, plan.device_groups)
        P = self._pcache.get(key)
        if P is not None:
            return P
        scalar = self.scalar
        opts = self.options
        bw = scalar._bandwidth_for(plan)
        ranks = rank_device_types(scalar.cluster, plan.node_sequence)
        S = plan.num_stages
        P = _PlacementTables()
        P.bw = bw
        P.num_stages = S
        P.ranks_uniform = len(set(ranks)) <= 1
        P.first_type = ranks[0] if ranks else None
        P.lat_fn = getattr(bw, "collective_latency_ms", None)
        P.latmap = {}
        P.dpfac = {}
        # The scalar bandwidth model's dp/pp values depend only on the node
        # sequence and explicit rank ranges (bandwidth.py: _rank_node and
        # node_types are built from node_sequence alone), so they memo
        # globally per sequence.  Other factories (e.g. plan_tpu's ici/dcn
        # closure) stay per-placement and go through the model's methods.
        P.seq_key = (plan.node_sequence
                     if isinstance(bw, HeteroScalarBandwidth) else None)
        strict = opts.strict_compat
        seq_meta = self._seq_meta
        seq_ppden = self._seq_ppden
        groups = plan.device_groups
        stages = []
        pp_den = []
        ranges = []
        for s in range(S):
            r0, r1 = plan.stage_rank_range(s)
            ranges.append((r0, r1))
            mkey = (plan.node_sequence, r0, r1)
            meta = seq_meta.get(mkey)
            if meta is None:
                types = ranks[r0:r1]
                meta = _StageMeta()
                meta.types = types
                meta.typeset = tuple(set(types))
                meta.homo = len(meta.typeset) == 1
                meta.opt_type = None if strict else types[0]
                meta.etabs = {}
                meta.fbtabs = {}
                if len(seq_meta) > _SEQ_MEMO_MAX:
                    seq_meta.clear()
                seq_meta[mkey] = meta
            stages.append(meta)
            # pp denominator of the s -> s+1 boundary (unused for the last)
            if s >= S - 1:
                pp_den.append(0.0)
            elif P.seq_key is not None:
                end2 = r1 + groups[s + 1]
                gkey = (P.seq_key, r0, end2)
                den = seq_ppden.get(gkey)
                if den is None:
                    # == bw.pp_bandwidth(s): _group_bandwidth over the two
                    # adjacent stages' combined rank range, verbatim
                    den = opts.bw_to_bytes_per_ms(
                        bw._group_bandwidth(range(r0, end2)))
                    if len(seq_ppden) > _SEQ_MEMO_MAX:
                        seq_ppden.clear()
                    seq_ppden[gkey] = den
                pp_den.append(den)
            else:
                pp_den.append(opts.bw_to_bytes_per_ms(bw.pp_bandwidth(s)))
        P.stages = stages
        P.pp_den = pp_den
        P.ranges = ranges
        P.batch_gen = (
            scalar.profiles.type_meta[P.first_type].batch_generator_ms
            if (not strict and P.first_type is not None) else 0.0)
        P.spot_scale = scalar._spot_scale(plan)
        if len(self._pcache) >= _PLACEMENT_MEMO_MAX:
            self._pcache.clear()
            if self.counters is not None:
                self.counters.inc("memo.placement.evict")
        if self.counters is not None:
            self.counters.inc("memo.placement.built")
        self._pcache[key] = P
        return P

    def _build_etab(self, meta, tp, bs):
        """Slice-sum matrix of one (type, tp, bs) profile: entry [i][j] is
        the SEQUENTIAL sum of layer times [i, j) — bit-identical to
        ``LayerProfile.time_slice`` (and to the /cp==1 scalar stage time)."""
        key = (meta.types[0], tp, bs)
        tab = self._etabs.get(key)
        if tab is None:
            try:
                times = self.profiles.get(*key).layer_times_ms
            except KeyError:
                tab = _MISS
            else:
                n = len(times)
                tab = []
                for start in range(n + 1):
                    row = [0.0] * (n + 1)
                    acc = 0
                    for end in range(start, n):
                        acc = acc + times[end]
                        row[end + 1] = acc
                    tab.append(row)
            self._etabs[key] = tab
        meta.etabs[(tp, bs)] = tab
        return tab

    def _build_fb(self, meta, tp, bs):
        try:
            fb = max(self.profiles.get(t, tp, bs).fb_sync_ms
                     for t in meta.typeset)
        except KeyError:
            fb = _MISS
        meta.fbtabs[(tp, bs)] = fb
        return fb

    def _build_dpfac(self, P, s, strat):
        """The dp ring term's candidate-independent factor: the scalar's
        ``2*(dp-1) / (dp*B)`` sub-expression (its own left-associated
        grouping), so ``factor * param_bytes`` reproduces ``_dp_cost_ms``
        bitwise.  For the scalar bandwidth model the ring bandwidth depends
        only on (node_sequence, rank range, dp), so the factor memos
        globally per sequence — the big win at scale, where each placement
        sees only a handful of candidates but thousands of placements share
        the same few stage rank ranges."""
        dp = strat.dp
        if dp <= 1:
            return 0.0
        if P.seq_key is not None:
            r0, r1 = P.ranges[s]
            gkey = (P.seq_key, r0, r1, dp)
            q = self._seq_dpfac.get(gkey)
            if q is None:
                # == P.bw.dp_bandwidth(s, strat): slowest strided dp ring
                # over the stage's ranks, min-chained in the same order
                bw_model = P.bw
                ranks = list(range(r0, r1))
                slowest = float("inf")
                for d in range(dp):
                    slowest = min(
                        slowest, bw_model._group_bandwidth(ranks[d::dp]))
                q = 2 * (dp - 1) / (
                    dp * self.options.bw_to_bytes_per_ms(slowest))
                if len(self._seq_dpfac) > _SEQ_MEMO_MAX:
                    self._seq_dpfac.clear()
                self._seq_dpfac[gkey] = q
            return q
        bw = P.bw.dp_bandwidth(s, strat)
        return 2 * (dp - 1) / (dp * self.options.bw_to_bytes_per_ms(bw))
