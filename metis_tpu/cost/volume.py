"""Communication-volume model: activation and parameter sizes per plan.

≅ reference ``GPTActivationAndParam`` (``model/activation_parameter.py:5-51``)
with the unit quirk fixed natively: the reference counts activation *elements*
and never multiplies by dtype width (SURVEY.md §2.3), so its PP costs are off
by the dtype factor.  ``elements=True`` reproduces that for strict-compat
costing; the native path returns bytes.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Sequence

from metis_tpu.core.config import ModelSpec


@dataclass(frozen=True)
class TransformerVolume:
    """Analytic sizes for an embed + blocks + head transformer stack."""

    model: ModelSpec
    params_per_layer_bytes: tuple[int, ...]

    @property
    def num_layers(self) -> int:
        return self.model.num_layers

    def boundary_activation(
        self, boundary: int, batch_size: int, tp: int, elements: bool = False
    ) -> float:
        """Tensor volume crossing the stage boundary after layer
        ``boundary - 1``.

        Compat quirk preserved under ``elements=True``: the reference sizes
        the boundary *before its final layer* at vocab/tp
        (``activation_parameter.py:29-32``) even though the hidden-sized
        tensor is what actually crosses; natively every inter-stage boundary
        carries bs*seq*hidden activations in ``dtype_bytes``.
        """
        m = self.model
        if elements:
            if boundary == m.num_layers - 1:
                return batch_size * m.sequence_length * m.vocab_size / tp
            return float(batch_size * m.sequence_length * m.hidden_size)
        return float(
            batch_size * m.sequence_length * m.hidden_size * m.dtype_bytes)

    def parameter_bytes_per_layer(self, tp: int) -> list[float]:
        """Per-layer parameter bytes under tp sharding (first/middle/last
        pattern, ≅ ``get_parameter_size``)."""
        p = self.params_per_layer_bytes
        first, mid, last = float(p[0]), float(p[1]), float(p[-1])
        return (
            [first / tp]
            + [mid / tp] * (self.num_layers - 2)
            + [last / tp]
        )

    def stage_parameter_bytes(self, tp: int, start: int, end: int) -> float:
        """Parameter bytes held by a stage covering layers [start, end)
        (≅ ``get_parameter_size_by_stage``)."""
        p = self.params_per_layer_bytes
        count = end - start
        total = 0.0
        if start == 0:
            total += p[0] / tp
            count -= 1
        if end == self.num_layers:
            total += p[-1] / tp
            count -= 1
        total += p[1] / tp * count
        return total
