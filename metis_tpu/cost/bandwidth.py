"""Scalar bandwidth models — compat projections of the reference's
slowest-link scans (``model/cluster_bandwidth.py``).

These exist for (a) golden/differential parity with the reference cost model
and (b) clusters genuinely described by per-type scalars.  The TPU-native
ICI/DCN model lives in :mod:`metis_tpu.cost.ici`.

Reference semantics reproduced exactly (differential-tested):

- a process group confined to ONE node gets that node type's intra bandwidth;
  any group spanning nodes gets the "inter" bandwidth, which — via the
  reference's swapped getter (``gpu_cluster.py:56-58``) — is the minimum
  *intra* bandwidth among spanned node types under ``strict_compat``;
- "one node" is literal: two same-type nodes still count as spanning
  (``cluster_bandwidth.py:172-177`` keys on distinct node ids);
- hetero DP groups are built round-robin, tp-major (``:148-156``), i.e. group
  d holds stage ranks ``d::dp`` — note this is the *reference's* grouping
  quirk reproduced for differential parity: it scans by replica, the
  transpose of the (dp, cp, tp) gradient-sync layout that
  ``cp_ring_groups`` declares and the ICI model costs
  (``ici.IciDcnBandwidth.dp_bandwidth``).  For the scalar model both scans
  touch the same node set in almost all layouts, so parity wins here.
"""
from __future__ import annotations

from typing import Protocol, Sequence

import numpy as np

from metis_tpu.cluster.spec import ClusterSpec
from metis_tpu.core.types import InterStagePlan, Strategy
from metis_tpu.balance.stage_perf import node_device_types


def cp_ring_groups(start: int, strategy: Strategy) -> list[list[int]]:
    """Rank groups of every context-parallel ring in a stage whose ranks
    begin at ``start``, laid out (dp, cp, tp) row-major — the single source of
    truth for the planner's cp rank layout (shared by all bandwidth models
    and, once cp meshes are emitted, the execution layer)."""
    width = strategy.cp * strategy.tp
    return [
        [start + d * width + c * strategy.tp + t for c in range(strategy.cp)]
        for d in range(strategy.dp)
        for t in range(strategy.tp)
    ]


class StageBandwidthModel(Protocol):
    """What the hetero estimator needs: slowest link for a stage's pipeline
    boundary and for its DP rings, in GB/s."""

    def pp_bandwidth(self, stage_id: int) -> float: ...

    def dp_bandwidth(self, stage_id: int, strategy: Strategy) -> float: ...

    def cp_bandwidth(self, stage_id: int, strategy: Strategy) -> float:
        """Slowest link of any ring-attention (context-parallel) ring.  Stage
        rank layout is (dp, cp, tp) row-major: replica d's cp ring at tp slot t
        is ranks ``start + d*cp*tp + c*tp + t``."""
        ...


class HeteroScalarBandwidth:
    """≅ reference ``HetClusterBandwidth`` (``cluster_bandwidth.py:135-195``)."""

    def __init__(self, cluster: ClusterSpec, plan: InterStagePlan,
                 strict_compat: bool = True):
        self.cluster = cluster
        self.plan = plan
        self.strict_compat = strict_compat
        self.node_types = node_device_types(cluster, plan.node_sequence)
        # rank -> node index under the node-sequence placement: nodes are
        # reordered type-first (stable within a type) to match
        # rank_device_types, so ragged node widths classify correctly.
        self._rank_node: list[int] = []
        node_id = 0
        for device_type in plan.node_sequence:
            for n in cluster.nodes:
                if n.device_type == device_type:
                    self._rank_node.extend([node_id] * n.num_devices)
                    node_id += 1

    def _group_bandwidth(self, ranks: Sequence[int]) -> float:
        nodes = {self._rank_node[r] for r in ranks}
        types = [self.node_types[n] for n in nodes]
        if len(nodes) == 1:
            return self.cluster.intra_bw_for_type(types[0])
        return self.cluster.inter_bw_for_types(types, self.strict_compat)

    def pp_bandwidth(self, stage_id: int) -> float:
        """Slowest link among the ranks of stage_id ∪ stage_id+1
        (≅ ``:143-146,169-177``)."""
        start, _ = self.plan.stage_rank_range(stage_id)
        groups = self.plan.device_groups
        end = start + groups[stage_id] + (
            groups[stage_id + 1] if stage_id + 1 < len(groups) else 0)
        return self._group_bandwidth(range(start, end))

    def dp_bandwidth(self, stage_id: int, strategy: Strategy) -> float:
        start, end = self.plan.stage_rank_range(stage_id)
        ranks = list(range(start, end))
        slowest = float("inf")
        for d in range(strategy.dp):
            slowest = min(slowest, self._group_bandwidth(ranks[d::strategy.dp]))
        return slowest

    def cp_bandwidth(self, stage_id: int, strategy: Strategy) -> float:
        start, _ = self.plan.stage_rank_range(stage_id)
        return min(
            self._group_bandwidth(ring)
            for ring in cp_ring_groups(start, strategy))


class HomoScalarBandwidth:
    """≅ reference ``HomoClusterBandwidth`` (``cluster_bandwidth.py:71-132``)
    for uniform Megatron grids."""

    def __init__(self, cluster: ClusterSpec, strict_compat: bool = True):
        self.cluster = cluster
        first_type = cluster.nodes[0].device_type
        self.intra = cluster.intra_bw_for_type(first_type)
        self.inter = (
            self.intra if strict_compat
            else cluster.spec(first_type).inter_bw_gbps
        )

    def _within_one_node(self, ranks: Sequence[int]) -> bool:
        return len({self.cluster.node_of_rank(r) for r in ranks}) == 1

    def pp_bandwidth(self, pp: int, tp: int, stage_id: int) -> float:
        """Slowest stage->stage+1 peer link over the rank grid
        (≅ ``:83-100,111-123``)."""
        total = self.cluster.total_devices
        grid = np.arange(total).reshape(pp, -1, tp)
        model_groups = np.concatenate(list(grid), axis=1)  # (dp, pp*tp)
        slowest = self.intra
        for row in model_groups:
            for t in range(tp):
                pair = (int(row[stage_id * tp + t]), int(row[(stage_id + 1) * tp + t]))
                if not self._within_one_node(pair):
                    slowest = self.inter
        return slowest

    def dp_bandwidth(self, pp: int, tp: int) -> float:
        """Slowest DP-row link (≅ ``:102-109,125-132``; the reference treats
        each whole pp-row — dp*tp ranks — as one group)."""
        total = self.cluster.total_devices
        grid = np.arange(total).reshape(pp, -1, tp)
        slowest = self.intra
        for row in range(pp):
            if not self._within_one_node([int(r) for r in grid[row].flatten()]):
                slowest = self.inter
        return slowest
