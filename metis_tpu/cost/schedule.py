"""Pipeline-schedule pricing: GPipe vs 1F1B vs interleaved as a PLAN axis.

The reference prices exactly one schedule — the GPipe fill-drain
``(M - 1) * max_stage + sum(stages)`` (``model/cost_estimator.py:129``) — and
has no schedule concept in its plan space.  Our execution layer ships three
schedules (``execution/pipeline.py``); this module makes the *planner* choose
between them by pricing what each implemented schedule actually does:

- **gpipe** — forward scan + autodiff backward.  No recomputation (XLA stores
  every microbatch's residuals), so step time is the reference formula
  unchanged, but peak activation memory grows with the microbatch count M.
- **1f1b** — memory-bounded one-forward-one-backward with stage-granular
  rematerialization.  The fill-drain shape is identical, but every
  microbatch-stage recomputes its forward from the saved boundary input, so
  stage times scale by ``1 + REMAT_FWD_FRACTION``.  Peak activation memory is
  one microbatch's residuals plus ``min(M, 2(S-1)+1)`` boundary buffers —
  independent of M.  1F1B therefore never wins on predicted time; it wins by
  making memory-tight plans *feasible* (exactly how the executor behaves).
- **interleaved** — ``vs`` virtual chunks per device, microbatches in groups
  of S with drain between groups (the implemented schedule —
  ``_pipeline_interleaved_local`` — not Megatron's steady-state overlap; the
  model prices the implementation, VERDICT r2 weak #6).  Per group the
  pipeline exposes chunk units (1/vs of a stage), so the bubble term shrinks
  by ~vs while the same remat factor applies, and each microbatch crosses
  ``vs*S - 1`` chunk boundaries instead of ``S - 1`` (more, smaller sends on
  the same pp links).

All formulas use per-microbatch whole-stage times ``lens`` (profiled fwd+bwd
ms, as the reference's) so gpipe reproduces the reference exactly.
"""
from __future__ import annotations

from typing import Sequence

PIPELINE_SCHEDULES = ("gpipe", "1f1b", "interleaved")

# Fraction of a profiled fwd+bwd stage time that is the forward pass — the
# work a rematerializing schedule (1f1b, interleaved) runs twice.  The
# canonical 1:2 fwd:bwd FLOP ratio for transformer training is the default;
# ``profiles.profiler.measure_remat_fraction`` measures the real split on a
# backend (XLA's fused backward rarely hits the exact FLOP ratio) and feeds
# it here via ``SearchConfig.remat_fwd_fraction`` (VERDICT r3 #3).
REMAT_FWD_FRACTION = 1.0 / 3.0


def schedule_valid(schedule: str, num_stages: int, batches: int,
                   virtual_stages: int, num_blocks: int | None = None) -> bool:
    """Whether the schedule can run this plan shape on the shard_map pipeline
    executor (mirrors ``make_pipeline_train_step``'s checks so the planner
    never emits a schedule the builder would reject)."""
    if schedule not in PIPELINE_SCHEDULES:
        return False
    if schedule == "gpipe":
        return True
    if num_stages < 2:
        return False  # no pipeline; 1f1b/interleaved degenerate to gpipe
    if schedule == "1f1b":
        # uneven chunking is fine — the executor pads stages to the largest
        # stage's block count with masked identity layers
        # (execution.pipeline.pad_blocks_for_partition); each stage just
        # needs at least one block
        return num_blocks is None or num_blocks >= num_stages
    if num_blocks is not None and num_blocks % num_stages:
        return False  # interleaved: the chunk permutation needs even stages
    if virtual_stages < 2:
        return False  # vs=1 is plain 1f1b-shaped; search it as such
    if batches % num_stages:
        return False  # microbatches run in groups of S
    if num_blocks is not None and num_blocks % (num_stages * virtual_stages):
        return False
    return True


def schedule_execution_ms(
    schedule: str,
    lens: Sequence[float],
    batches: int,
    virtual_stages: int = 1,
    remat_fraction: float | None = None,
) -> float:
    """Pipeline execution time (ms) for per-microbatch stage times ``lens``
    under ``schedule``.

    gpipe: the reference fill-drain ``(M-1)*max + sum`` verbatim.
    1f1b: same shape with every stage time scaled by the remat factor.
    interleaved: ``G * (vs*S + S - 1) * (1+r) * max(lens) / vs`` — G = M/S
    groups, each running ``vs*S + S - 1`` lockstep ticks (ppermute barriers)
    of one chunk-unit (``max(lens)/vs`` compute) per device, forward and
    backward phases together costing ``(1+r)`` of the combined fwd+bwd time.

    ``remat_fraction``: measured fwd share of a profiled fwd+bwd stage time
    (``measure_remat_fraction``); None uses the analytic default.
    """
    M = batches
    S = len(lens)
    if schedule == "gpipe":
        return (M - 1) * max(lens) + sum(lens)
    r = REMAT_FWD_FRACTION if remat_fraction is None else remat_fraction
    if schedule == "1f1b":
        return (1 + r) * ((M - 1) * max(lens) + sum(lens))
    if schedule == "interleaved":
        vs = virtual_stages
        groups = M // S
        ticks = vs * S + S - 1
        return groups * ticks * (1 + r) * max(lens) / vs
    raise ValueError(f"unknown schedule {schedule!r}")


def schedule_pp_send_factor(schedule: str, num_stages: int,
                            virtual_stages: int = 1) -> float:
    """Multiplier on the plan's pp boundary-transfer cost: the interleaved
    schedule crosses ``vs*S - 1`` chunk boundaries per microbatch (including
    ring wraps) where gpipe/1f1b cross ``S - 1``."""
    if schedule != "interleaved" or num_stages < 2:
        return 1.0
    return (virtual_stages * num_stages - 1) / (num_stages - 1)


def schedule_activation_factor(schedule: str, batches: int,
                               virtual_stages: int = 1) -> float:
    """How many microbatches' worth of per-stage residual activations are
    live at the schedule's peak, as a multiple of one profiled microbatch:

    - gpipe stores every microbatch's residuals until its backward: M;
    - 1f1b rematerializes — only the one unit under vjp holds residuals: 1;
    - interleaved rematerializes per chunk unit (1/vs of the stage): 1/vs.
    """
    if schedule == "gpipe":
        return float(batches)
    if schedule == "1f1b":
        return 1.0
    if schedule == "interleaved":
        return 1.0 / virtual_stages
    raise ValueError(f"unknown schedule {schedule!r}")


def schedule_boundary_buffers(schedule: str, num_stages: int, batches: int,
                              virtual_stages: int = 1) -> int:
    """Saved boundary-input buffers ([mbs, seq, hidden] each) the schedule
    keeps per device at peak (the remat schedules' rings; gpipe's boundaries
    are part of its stored residuals)."""
    if schedule == "1f1b":
        return min(batches, 2 * (num_stages - 1) + 1)
    if schedule == "interleaved":
        return virtual_stages * num_stages
    return 0


def boundary_buffer_mb(mbs: int, sequence_length: int, hidden_size: int,
                       dtype_bytes: int) -> float:
    """MB of one saved boundary activation (per device: the full hidden, the
    stage's per-replica microbatch)."""
    return mbs * sequence_length * hidden_size * dtype_bytes / 1e6
