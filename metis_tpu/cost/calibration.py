"""Collective microbenchmarks + calibration for the ICI/DCN cost model.

SURVEY.md §7 hard part #1: the torus cost model must predict XLA collective
latencies, which "needs microbenchmark calibration (own profiler)".  The
reference has nothing comparable (its bandwidth layer is two scalars per
node, ``README.md:203-230``); this module closes the loop the TPU-native
way:

1. ``microbenchmark_collectives`` times the real XLA collectives — psum,
   all_gather, psum_scatter, all_to_all, ppermute — under ``shard_map`` over
   a 1-D device mesh at several payload sizes;
2. ``fit_samples`` fits each collective to the two-parameter wire model
   ``time_ms = latency_ms + nbytes * ms_per_byte`` by least squares —
   exactly the alpha/beta decomposition the analytic formulas in
   :mod:`metis_tpu.cost.ici` assume;
3. the resulting :class:`CollectiveCalibration` is a JSON artifact
   (committed per deployment under ``calibration/``) that
   :class:`metis_tpu.cost.ici.IciDcnBandwidth` consumes: measured effective
   bandwidth replaces the published per-generation link constants whenever
   the calibration's platform matches the slice being costed.

The harness runs identically on the CPU fake backend (the 8-device virtual
mesh used across the test suite) and on real TPU slices — the planner core
stays runnable with zero TPUs (SURVEY.md §4) while a deployment with a real
slice gets real constants from the same entry point.
"""
from __future__ import annotations

import json
import time
import warnings
from dataclasses import dataclass, field
from pathlib import Path
from typing import Sequence

COLLECTIVES = ("all_reduce", "all_gather", "reduce_scatter", "all_to_all",
               "ppermute")


class CalibrationError(ValueError):
    """A calibration/transfer fit cannot be computed from the given
    samples (empty ledger, degenerate probe measurements, ...).

    Subclasses ValueError so pre-existing ``except ValueError`` /
    ``pytest.raises(ValueError)`` call sites keep working; new callers
    can catch the typed error and degrade (e.g. to an identity fit)."""


@dataclass(frozen=True)
class CollectiveSample:
    """One timed collective: ``nbytes`` is the logical payload the analytic
    formula charges (the full gradient/buffer size, not the wire volume)."""

    collective: str
    group_size: int
    nbytes: int
    time_ms: float


@dataclass(frozen=True)
class LinearFit:
    """``time_ms = latency_ms + nbytes * ms_per_byte`` (alpha-beta model)."""

    latency_ms: float
    ms_per_byte: float
    r2: float
    n_samples: int

    def predict_ms(self, nbytes: float) -> float:
        return self.latency_ms + nbytes * self.ms_per_byte

    @property
    def effective_bw_gbps(self) -> float:
        """Asymptotic (large-payload) bandwidth in GB/s (1 GB/s = 1e6 B/ms)."""
        if self.ms_per_byte <= 0:
            return float("inf")
        return 1.0 / (self.ms_per_byte * 1e6)


@dataclass(frozen=True)
class CollectiveCalibration:
    """Fitted wire model per collective for one (platform, group size)."""

    platform: str
    device_kind: str
    group_size: int
    fits: dict[str, LinearFit]
    samples: tuple[CollectiveSample, ...] = field(default=(), repr=False)

    # -- persistence -------------------------------------------------------
    def to_json_dict(self) -> dict:
        return {
            "platform": self.platform,
            "device_kind": self.device_kind,
            "group_size": self.group_size,
            "fits": {
                name: {"latency_ms": f.latency_ms,
                       "ms_per_byte": f.ms_per_byte,
                       "r2": f.r2, "n_samples": f.n_samples,
                       "effective_bw_gbps": f.effective_bw_gbps}
                for name, f in self.fits.items()
            },
            "samples": [
                {"collective": s.collective, "group_size": s.group_size,
                 "nbytes": s.nbytes, "time_ms": s.time_ms}
                for s in self.samples
            ],
        }

    def dump(self, path: str | Path) -> None:
        Path(path).write_text(json.dumps(self.to_json_dict(), indent=1))

    @classmethod
    def from_json_dict(cls, d: dict) -> "CollectiveCalibration":
        fits = {
            name: LinearFit(f["latency_ms"], f["ms_per_byte"], f["r2"],
                            f["n_samples"])
            for name, f in d["fits"].items()
        }
        samples = tuple(
            CollectiveSample(s["collective"], s["group_size"], s["nbytes"],
                             s["time_ms"])
            for s in d.get("samples", ()))
        return cls(d["platform"], d["device_kind"], d["group_size"], fits,
                   samples)

    @classmethod
    def load(cls, path: str | Path) -> "CollectiveCalibration":
        return cls.from_json_dict(json.loads(Path(path).read_text()))

    # -- application -------------------------------------------------------
    def bw_gbps(self, collective: str) -> float | None:
        fit = self.fits.get(collective)
        return None if fit is None else fit.effective_bw_gbps

    def latency_ms(self, collective: str) -> float:
        fit = self.fits.get(collective)
        return 0.0 if fit is None else max(fit.latency_ms, 0.0)

    def with_correction(self, scale: float) -> "CollectiveCalibration":
        """A new calibration with every fit's ``predict_ms`` scaled by a
        ledger-derived correction factor (``fit_ledger_correction``):
        latency and per-byte slope scale together, so the alpha/beta shape
        is preserved while the absolute prediction tracks what the
        accuracy ledger measured."""
        if scale <= 0:
            raise ValueError(f"correction scale must be > 0, got {scale}")
        fits = {
            name: LinearFit(f.latency_ms * scale, f.ms_per_byte * scale,
                            f.r2, f.n_samples)
            for name, f in self.fits.items()
        }
        return CollectiveCalibration(
            platform=self.platform, device_kind=self.device_kind,
            group_size=self.group_size, fits=fits, samples=self.samples)


def fit_samples(samples: Sequence[CollectiveSample]) -> dict[str, LinearFit]:
    """Least-squares alpha-beta fit per collective (clamped to latency >= 0:
    a tiny negative intercept is measurement noise, not physics)."""
    import numpy as np

    by_name: dict[str, list[CollectiveSample]] = {}
    for s in samples:
        by_name.setdefault(s.collective, []).append(s)

    fits = {}
    for name, group in by_name.items():
        x = np.array([s.nbytes for s in group], dtype=np.float64)
        y = np.array([s.time_ms for s in group], dtype=np.float64)
        if len(group) >= 2 and np.ptp(x) > 0:
            slope, intercept = np.polyfit(x, y, 1)
            slope = max(float(slope), 0.0)
            intercept = max(float(intercept), 0.0)
            pred = intercept + slope * x
            ss_res = float(((y - pred) ** 2).sum())
            ss_tot = float(((y - y.mean()) ** 2).sum())
            r2 = 1.0 - ss_res / ss_tot if ss_tot > 0 else 1.0
        else:
            slope, intercept, r2 = 0.0, float(y.mean()), 1.0
        fits[name] = LinearFit(intercept, slope, r2, len(group))
    return fits


def _collective_fns(axis: str):
    """name -> (local_fn, logical_payload_fn(local_shape_bytes, n)).

    Local arrays are [rows, cols] sharded over rows; the payload reported is
    the quantity the analytic formulas charge:

    - all_reduce: the full reduced buffer (every device ends with it);
    - all_gather: the full gathered result;
    - reduce_scatter: the full pre-reduction buffer;
    - all_to_all: each device's full send buffer;
    - ppermute: the block one neighbor sends.
    """
    import jax

    def all_reduce(x):
        return jax.lax.psum(x, axis)

    def all_gather(x):
        return jax.lax.all_gather(x, axis, tiled=True)

    def reduce_scatter(x):
        return jax.lax.psum_scatter(x, axis, tiled=True)

    def all_to_all(x):
        return jax.lax.all_to_all(x, axis, split_axis=0, concat_axis=0,
                                  tiled=True)

    def make_ppermute(n):
        perm = [(i, (i + 1) % n) for i in range(n)]

        def ppermute(x):
            return jax.lax.ppermute(x, axis, perm)
        return ppermute

    return {
        "all_reduce": (all_reduce, lambda local, n: local),
        "all_gather": (all_gather, lambda local, n: local * n),
        "reduce_scatter": (reduce_scatter, lambda local, n: local),
        "all_to_all": (all_to_all, lambda local, n: local),
        "ppermute": (None, lambda local, n: local),  # built per-n below
        "_make_ppermute": make_ppermute,
    }


def microbenchmark_collectives(
    devices: Sequence | None = None,
    payload_kb: Sequence[int] = (64, 256, 1024, 4096),
    iters: int = 10,
    warmup: int = 2,
    collectives: Sequence[str] = COLLECTIVES,
) -> CollectiveCalibration:
    """Time XLA collectives over a 1-D mesh of ``devices`` and fit the wire
    model.  ``payload_kb`` are *local shard* sizes; logical payloads are
    derived per collective (see ``_collective_fns``)."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    from metis_tpu.core.compat import shard_map
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devs = list(devices if devices is not None else jax.devices())
    n = len(devs)
    if n < 2:
        raise ValueError("collective microbenchmark needs >= 2 devices")
    mesh = Mesh(np.array(devs), ("x",))
    fns = _collective_fns("x")

    samples: list[CollectiveSample] = []
    # local shard rows: a multiple of n (all_to_all's tiled split of axis 0
    # requires n | local rows on any mesh size) that is also >= 8
    rows = n * max(8 // n, 1)
    for kb in payload_kb:
        cols = max(kb * 1024 // 4 // rows, 8)  # fp32
        local_bytes = rows * cols * 4
        host = np.zeros((n * rows, cols), np.float32)
        x = jax.device_put(
            host, NamedSharding(mesh, P("x", None)))
        for name in collectives:
            fn = fns[name][0] if name != "ppermute" else fns["_make_ppermute"](n)
            payload = fns[name][1](local_bytes, n)
            # out_specs are P("x", None) for every collective: all_gather's
            # per-device copy is emitted as a varying value (global shape
            # n*rows) rather than asking shard_map to prove replication.
            shard = shard_map(
                fn, mesh=mesh, in_specs=P("x", None), out_specs=P("x", None))
            jitted = jax.jit(shard)
            try:
                out = jitted(x)
                jax.block_until_ready(out)
                for _ in range(warmup - 1):
                    jax.block_until_ready(jitted(x))
                t0 = time.perf_counter()
                for _ in range(iters):
                    out = jitted(x)
                jax.block_until_ready(out)
                ms = (time.perf_counter() - t0) / iters * 1e3
            except Exception as e:  # pragma: no cover - backend-specific
                warnings.warn(
                    f"collective microbenchmark skipped {name} at "
                    f"{kb} KB: {type(e).__name__}: {e}", stacklevel=2)
                continue
            samples.append(CollectiveSample(name, n, payload, ms))

    dev0 = devs[0]
    return CollectiveCalibration(
        platform=dev0.platform,
        device_kind=getattr(dev0, "device_kind", dev0.platform),
        group_size=n,
        fits=fit_samples(samples),
        samples=tuple(samples),
    )


# ---------------------------------------------------------------------------
# accuracy-ledger residual refit
# ---------------------------------------------------------------------------


def fit_ledger_correction(samples) -> dict:
    """Fit a multiplicative ``predict_ms`` correction from accuracy-ledger
    residuals (``obs/ledger.py``): the closing of the drift loop — once the
    ledger shows the estimator systematically off, its residuals refit the
    prediction instead of being merely alarmed about.

    ``samples``: an iterable of ``(predicted_ms, measured_ms)`` pairs OR
    ledger ``AccuracySample`` objects (matched ones; unpredicted samples
    are skipped).  The scale is the least-squares through-origin fit
    ``measured ≈ scale * predicted`` — a single factor, because a ranking
    model only needs its *level* corrected (a uniform scale preserves every
    plan ordering while fixing the absolute step-time estimate the drift
    band is judged against).

    Returns ``{"scale", "n", "mape_before_pct", "mape_after_pct"}``; apply
    with ``CollectiveCalibration.with_correction(scale)`` or by scaling any
    ``predict_ms`` output directly.

    Degrades gracefully on thin ledgers: an empty/unmatched sample set
    raises the typed :class:`CalibrationError` (a ValueError subclass —
    existing handlers keep working); a single matched sample fits the
    exact one-point scale; non-finite (NaN/inf) pairs are skipped like
    unmatched ones rather than poisoning the fit.
    """
    import math

    pairs: list[tuple[float, float]] = []
    for s in samples:
        if hasattr(s, "predicted_ms"):
            p, m = s.predicted_ms, s.measured_ms
        else:
            p, m = s
        if p is None or m is None:
            continue
        p, m = float(p), float(m)
        if not math.isfinite(p) or not math.isfinite(m) or m <= 0:
            continue
        pairs.append((p, m))
    if not pairs:
        raise CalibrationError(
            "no matched (predicted, measured) samples to fit")
    sxx = sum(p * p for p, _ in pairs)
    sxy = sum(p * m for p, m in pairs)
    scale = sxy / sxx if sxx > 0 else 1.0

    def mape(factor: float) -> float:
        return sum(abs(p * factor - m) / m for p, m in pairs) / len(pairs) * 100

    return {
        "scale": round(scale, 6),
        "n": len(pairs),
        "mape_before_pct": round(mape(1.0), 3),
        "mape_after_pct": round(mape(scale), 3),
    }


def fit_recovery_seconds(samples, kinds: Sequence[str] | None = None) -> dict:
    """Refit ``SearchConfig.spot_recover_s`` from measured recoveries.

    The spot-availability cost term charges ``hazard_per_hr x
    spot_recover_s`` of expected recovery time per plan
    (``cost/estimator.py``); the seed value comes from the bench
    ``resilience`` headline, and THIS closes the loop from production:
    ``samples`` is an iterable of recovery durations in seconds — floats,
    ``(kind, recover_s)`` pairs, supervisor ``RecoveryRecord`` objects, or
    their ``to_json_dict`` rows.  ``kinds`` (default: the replan-bearing
    ones — ``device_loss``/``spot_preemption``/``spot_return``) filters
    records that carry a kind; anomaly rollbacks re-jit nothing and would
    drag the estimate down.

    Returns ``{"spot_recover_s", "n", "mean_s", "p50_s", "p90_s"}`` —
    ``spot_recover_s`` is the MEDIAN (one straggler recovery must not
    dominate the prior every future plan is ranked with)."""
    if kinds is None:
        kinds = ("device_loss", "spot_preemption", "spot_return")
    vals: list[float] = []
    for s in samples:
        kind = None
        if hasattr(s, "recover_s"):
            kind, sec = getattr(s, "kind", None), s.recover_s
        elif isinstance(s, dict):
            kind, sec = s.get("kind"), s.get("recover_s")
        elif isinstance(s, tuple):
            kind, sec = s
        else:
            sec = s
        if sec is None or float(sec) <= 0:
            continue
        if kind is not None and kind not in kinds:
            continue
        vals.append(float(sec))
    if not vals:
        raise ValueError("no usable recovery samples to fit")
    vals.sort()
    n = len(vals)
    p50 = vals[(n - 1) // 2]
    p90 = vals[min(int(n * 0.9), n - 1)]
    return {
        "spot_recover_s": round(p50, 4),
        "n": n,
        "mean_s": round(sum(vals) / n, 4),
        "p50_s": round(p50, 4),
        "p90_s": round(p90, 4),
    }


# ---------------------------------------------------------------------------
# dp gradient-sync overlap calibration
# ---------------------------------------------------------------------------


def measure_dp_overlap(
    devices: Sequence | None = None,
    hidden: int = 512,
    layers: int = 8,
    batch_per_device: int = 32,
    iters: int = 8,
    warmup: int = 2,
) -> dict:
    """Measure how much of the dp gradient all-reduce XLA hides under
    backward compute on THIS backend (VERDICT r2 weak #4: the serial comm
    model systematically over-predicts comm-heavy plans).

    Three timed variants of a layered matmul train-ish step over a 1-D "dp"
    mesh: (a) value_and_grad + per-leaf gradient pmean (XLA's latency-hiding
    scheduler may overlap the reductions with earlier layers' backward),
    (b) the same without any gradient reduction, (c) a bare all-reduce of
    the same total gradient payload.  Then

        exposed_ms          = (a) - (b)     — comm actually on the critical path
        overlap_fraction    = 1 - exposed_ms / (c), clamped to [0, 1]

    The fraction feeds ``EstimatorOptions.dp_overlap_fraction`` (native cost
    mode only; strict_compat stays serial like the reference) — a measured
    calibration field, not a guess."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    from metis_tpu.core.compat import shard_map
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devs = list(devices if devices is not None else jax.devices())
    n = len(devs)
    if n < 2:
        raise ValueError("dp overlap calibration needs >= 2 devices")
    mesh = Mesh(np.array(devs), ("dp",))
    params = [jnp.full((hidden, hidden), 0.01, jnp.float32)
              for _ in range(layers)]
    x_host = np.ones((n * batch_per_device, hidden), np.float32)
    x = jax.device_put(x_host, NamedSharding(mesh, P("dp", None)))

    def loss_fn(ps, xb):
        for w in ps:
            xb = jnp.tanh(xb @ w)
        return (xb * xb).mean()

    def make_step(reduce_grads: bool):
        def local(ps, xb):
            loss, grads = jax.value_and_grad(loss_fn)(ps, xb)
            if reduce_grads:
                grads = [jax.lax.pmean(g, "dp") for g in grads]
            # consume every gradient so XLA cannot dead-code the reductions;
            # rank-1 output so the dp-varying value concatenates over "dp"
            return (loss + sum(jnp.sum(g) for g in grads) * 1e-9)[None]

        return jax.jit(shard_map(
            local, mesh=mesh, in_specs=(P(), P("dp", None)),
            out_specs=P("dp")))

    grad_bytes = layers * hidden * hidden * 4

    def bare_allreduce():
        # each device's local shard must hold the FULL grad payload — the
        # gradient pmean above all-reduces grad_bytes per device (params
        # are replicated), so the comparator must move the same volume
        buf = jax.device_put(
            np.ones((n * max(grad_bytes // 4 // hidden, 1), hidden),
                    np.float32),
            NamedSharding(mesh, P("dp", None)))
        fn = jax.jit(shard_map(
            lambda b: jax.lax.psum(b, "dp"), mesh=mesh,
            in_specs=P("dp", None), out_specs=P("dp", None)))
        return fn, buf

    def timed(fn, *args) -> tuple[float, float]:
        """(median_ms, spread_ms) — spread is the interquartile range, the
        caller's noise yardstick for rejecting implausible fits."""
        out = fn(*args)
        jax.block_until_ready(out)
        for _ in range(warmup - 1):
            jax.block_until_ready(fn(*args))
        samples = []
        for _ in range(iters):
            t0 = time.perf_counter()
            jax.block_until_ready(fn(*args))
            samples.append((time.perf_counter() - t0) * 1e3)
        import statistics

        srt = sorted(samples)
        q1 = srt[len(srt) // 4]
        q3 = srt[(3 * len(srt)) // 4]
        return statistics.median(samples), q3 - q1

    with_ms, with_iqr = timed(make_step(True), params, x)
    without_ms, without_iqr = timed(make_step(False), params, x)
    ar_fn, ar_buf = bare_allreduce()
    bare_ms, _ = timed(ar_fn, ar_buf)

    exposed_ms = max(with_ms - without_ms, 0.0)
    overlap = 1.0 - exposed_ms / bare_ms if bare_ms > 0 else 0.0
    # Noise guard: on a loaded host with_ms <= without_ms happens from
    # jitter alone, which would read as overlap 1.0 (perfect hiding) and
    # zero out the dp comm term in native cost mode — a noise artifact
    # presented as measurement.  When the measured exposure doesn't stand
    # above the run-to-run spread, cap the fraction so some comm cost
    # always survives, and flag the fit so callers can reject it.
    noise_ms = max(with_iqr, without_iqr)
    noise_limited = bool(noise_ms > 0.0 and exposed_ms <= noise_ms)
    if noise_limited:
        overlap = min(overlap, 0.9)
    dev0 = devs[0]
    return {
        "platform": dev0.platform,
        "device_kind": getattr(dev0, "device_kind", dev0.platform),
        "group_size": n,
        "grad_bytes": grad_bytes,
        "with_reduce_ms": round(with_ms, 4),
        "without_reduce_ms": round(without_ms, 4),
        "with_reduce_iqr_ms": round(with_iqr, 4),
        "without_reduce_iqr_ms": round(without_iqr, 4),
        "exposed_comm_ms": round(exposed_ms, 4),
        "bare_allreduce_ms": round(bare_ms, 4),
        "noise_limited": noise_limited,
        "overlap_fraction": round(min(max(overlap, 0.0), 1.0), 4),
    }


def measure_pipeline_overlap(
    devices: Sequence | None = None,
    pp: int = 2,
    dp: int = 2,
    microbatches: int = 4,
    hidden: int = 64,
    blocks: int = 4,
    seq: int = 32,
    vocab: int = 256,
    schedule: str = "1f1b",
    iters: int = 5,
    warmup: int = 2,
    events=None,
) -> dict:
    """Measure what the overlap schedule actually buys on THIS backend:
    the SAME pipeline train step built lockstep vs overlapped
    (``execution.pipeline.make_pipeline_train_step(overlap=...)``), plus a
    bare ppermute ring of the boundary activation as the comm yardstick —

        saved_ms            = lockstep_ms - overlapped_ms
        overlap_hidden_frac = clamp(saved_ms / bare_comm_ms, 0, 1)

    the measured analogue of the cost model's exposed-vs-hidden split
    (``SearchConfig.use_overlap_model``).  Emits one ``overlap_measured``
    event.  Same noise discipline as :func:`measure_dp_overlap`: when the
    saving doesn't stand above the run-to-run spread the result is flagged
    ``noise_limited`` — single-host CPU meshes route the "transfer"
    through memcpy, so a near-zero (even negative-before-clamp) saving
    there is expected, not a failed measurement."""
    import statistics

    import numpy as np

    import jax
    import jax.numpy as jnp

    from jax.sharding import Mesh

    from metis_tpu.core.compat import shard_map
    from metis_tpu.core.events import NULL_LOG
    from metis_tpu.execution import (
        DP, PP, TP, make_pipeline_train_step, microbatch_split)
    from metis_tpu.models import GPTConfig

    events = events if events is not None else NULL_LOG
    devs = list(devices if devices is not None else jax.devices())
    if len(devs) < pp * dp:
        raise ValueError(
            f"pipeline overlap calibration needs >= {pp * dp} devices, "
            f"have {len(devs)}")
    mesh = Mesh(np.array(devs[: pp * dp]).reshape(pp, dp, 1), (PP, DP, TP))
    cfg = GPTConfig(vocab_size=vocab, seq_len=seq, hidden=hidden,
                    num_heads=max(hidden // 16, 1), num_blocks=blocks,
                    ffn_multiplier=2, dtype=jnp.float32)
    batch = microbatches * dp
    key = jax.random.PRNGKey(0)
    tokens = jax.random.randint(key, (batch, seq), 0, vocab)
    tok_mbs = microbatch_split(tokens, microbatches)

    def timed(fn, *args) -> tuple[float, float]:
        jax.block_until_ready(fn(*args))
        for _ in range(warmup - 1):
            jax.block_until_ready(fn(*args))
        samples = []
        for _ in range(iters):
            t0 = time.perf_counter()
            jax.block_until_ready(fn(*args))
            samples.append((time.perf_counter() - t0) * 1e3)
        srt = sorted(samples)
        return (statistics.median(samples),
                srt[(3 * len(srt)) // 4] - srt[len(srt) // 4])

    def step_ms(overlap: bool) -> tuple[float, float]:
        init_fn, step = make_pipeline_train_step(
            cfg, mesh, microbatches, schedule=schedule, overlap=overlap)
        state = list(init_fn(jax.random.PRNGKey(1)))

        def run():
            # the step donates params/opt_state — re-thread them each call
            state[0], state[1], loss = step(state[0], state[1],
                                            tok_mbs, tok_mbs)
            return loss

        return timed(run)

    lockstep_ms, lockstep_iqr = step_ms(False)
    overlapped_ms, overlapped_iqr = step_ms(True)

    # comm yardstick: the boundary activation around the pp ring for every
    # tick's forward+backward send (what the schedule tries to hide)
    perm = [(i, (i + 1) % pp) for i in range(pp)]
    ticks = microbatches + pp - 1

    def bare(buf):
        def body(b, _):
            return jax.lax.ppermute(b, PP, perm), None
        out, _ = jax.lax.scan(body, buf, None, length=2 * ticks)
        return out

    mbs_local = batch // microbatches // dp
    buf = jnp.ones((pp * mbs_local, seq, hidden), jnp.float32)
    from jax.sharding import PartitionSpec as P
    bare_fn = jax.jit(shard_map(
        bare, mesh=mesh, in_specs=P(PP), out_specs=P(PP)))
    bare_ms, _ = timed(bare_fn, buf)

    saved_ms = lockstep_ms - overlapped_ms
    frac = saved_ms / bare_ms if bare_ms > 0 else 0.0
    frac = min(max(frac, 0.0), 1.0)
    noise_ms = max(lockstep_iqr, overlapped_iqr)
    noise_limited = bool(noise_ms > 0.0 and abs(saved_ms) <= noise_ms)
    dev0 = devs[0]
    out = {
        "platform": dev0.platform,
        "device_kind": getattr(dev0, "device_kind", dev0.platform),
        "pp": pp,
        "dp": dp,
        "microbatches": microbatches,
        "schedule": schedule,
        "lockstep_ms": round(lockstep_ms, 4),
        "overlapped_ms": round(overlapped_ms, 4),
        "lockstep_iqr_ms": round(lockstep_iqr, 4),
        "overlapped_iqr_ms": round(overlapped_iqr, 4),
        "bare_comm_ms": round(bare_ms, 4),
        "saved_ms": round(saved_ms, 4),
        "noise_limited": noise_limited,
        "overlap_hidden_frac": round(frac, 4),
    }
    events.emit("overlap_measured", lockstep_ms=out["lockstep_ms"],
                overlapped_ms=out["overlapped_ms"],
                overlap_hidden_frac=out["overlap_hidden_frac"],
                noise_limited=noise_limited, schedule=schedule)
    return out


# ---------------------------------------------------------------------------
# single-chip roofline calibration (compute side)
# ---------------------------------------------------------------------------


def microbenchmark_chip(device=None, iters: int = 10) -> dict:
    """Measure one chip's achievable matmul TFLOP/s and HBM read bandwidth —
    the two roofline constants the synthetic profile generator
    (``profiles/synthetic.py``) and MFU accounting key on.  Returns a plain
    dict artifact (committed next to the collective calibration)."""
    import jax
    import jax.numpy as jnp

    dev = device if device is not None else jax.devices()[0]
    out: dict = {"platform": dev.platform,
                 "device_kind": getattr(dev, "device_kind", dev.platform)}

    from metis_tpu.core.timing import two_point_queue_ms

    def timed(fn, *args) -> float:
        """Seconds per chained iteration.  The chain runs inside ONE jitted
        fori_loop with a data dependency between iterations (so XLA cannot
        overlap them); the shared two-point fence cancels the fixed
        dispatch/transfer overhead of the remote-TPU tunnel."""
        jitted = jax.jit(fn, static_argnums=(0,))
        return two_point_queue_ms(
            lambda n: jitted(n, *args), iters) / 1e3

    with jax.default_device(dev):
        # matmul peak: bf16 k^3 keeps the MXU busy ~ms per iteration; each
        # loop step feeds the previous product back in (scaled back to ~1)
        k = 2048 if dev.platform == "cpu" else 8192
        a = jnp.ones((k, k), jnp.bfloat16)
        b = jnp.ones((k, k), jnp.bfloat16)

        def mm_chain(n, a, b):
            body = lambda _, x: ((x @ b) * (1.0 / k)).astype(x.dtype)  # noqa: E731
            return jax.lax.fori_loop(0, n, body, a)

        dt = timed(mm_chain, a, b)
        out["matmul_tflops"] = round(2 * k**3 / dt / 1e12, 1)

        # HBM streaming bandwidth: each iteration reads + writes the buffer
        # (2x volume), dependent on the previous iteration's output
        m = (64 if dev.platform == "cpu" else 256) * 1024 * 1024 // 4
        big = jnp.ones((m,), jnp.float32)

        def scale_chain(n, x):
            body = lambda _, v: v * 1.0000001  # noqa: E731
            return jax.lax.fori_loop(0, n, body, x)

        dt = timed(scale_chain, big)
        out["hbm_stream_gbps"] = round(2 * m * 4 / dt / 1e9, 1)
    return out


# ---------------------------------------------------------------------------
# cross-device profile transfer (AMP-style roofline scaling)
# ---------------------------------------------------------------------------


# Default compute share of a transformer layer's step time for the
# roofline mix: large-matmul transformer layers are mostly MXU-bound,
# the remainder streams activations/weights from HBM.
TRANSFER_COMPUTE_MIX = 0.7


def fit_transfer_scale(source_bench: dict, target_bench: dict,
                       compute_mix: float = TRANSFER_COMPUTE_MIX) -> dict:
    """Fit roofline scale factors between a profiled and an unprofiled
    chip from two ``microbenchmark_chip`` artifacts.

    AMP-style cross-type generalization (arXiv 2210.07297): a layer's
    step time splits into a compute-bound share (scales with achievable
    matmul TFLOP/s) and a memory-bound share (scales with HBM stream
    bandwidth), so

    ``time_target = time_source * (mix / compute_scale
                                   + (1 - mix) / mem_scale)``

    where ``compute_scale = target_tflops / source_tflops`` and
    ``mem_scale = target_gbps / source_gbps``.  Returns ``{"compute_scale",
    "mem_scale", "time_scale", "compute_mix", "source_kind",
    "target_kind"}``; raises :class:`CalibrationError` when either probe
    artifact is missing or degenerate (non-positive roofline numbers)."""
    if not 0.0 <= compute_mix <= 1.0:
        raise CalibrationError(
            f"compute_mix must be in [0, 1], got {compute_mix!r}")
    vals = {}
    for name, bench in (("source", source_bench), ("target", target_bench)):
        try:
            tflops = float(bench["matmul_tflops"])
            gbps = float(bench["hbm_stream_gbps"])
        except (KeyError, TypeError, ValueError) as e:
            raise CalibrationError(
                f"{name} probe artifact lacks roofline numbers: {e}") from None
        if tflops <= 0 or gbps <= 0:
            raise CalibrationError(
                f"{name} probe artifact has non-positive roofline numbers")
        vals[name] = (tflops, gbps)
    compute_scale = vals["target"][0] / vals["source"][0]
    mem_scale = vals["target"][1] / vals["source"][1]
    time_scale = compute_mix / compute_scale + (1.0 - compute_mix) / mem_scale
    return {
        "compute_scale": round(compute_scale, 6),
        "mem_scale": round(mem_scale, 6),
        "time_scale": round(time_scale, 6),
        "compute_mix": compute_mix,
        "source_kind": source_bench.get("device_kind", ""),
        "target_kind": target_bench.get("device_kind", ""),
    }


def transfer_profiles(store, source_type: str, target_type: str,
                      scales: dict, events=None) -> "object":
    """Synthesize profiles for an unprofiled device type by roofline-
    scaling a profiled one (:func:`fit_transfer_scale` output).

    Every (``source_type``, tp, bs) entry is copied to ``target_type``
    with layer/decode times and fb_sync multiplied by
    ``scales["time_scale"]`` (memory rows are model- not chip-shaped and
    pass through); the per-type optimizer/batch-generator metas scale
    the same way.  The returned merged store carries the provenance tag
    ``store.transferred[target_type] = {"source": ..., **scales,
    "transferred": True}`` — planner decision records pick it up so a
    plan built on transferred profiles is auditable as such.  Emits one
    ``transfer_fit`` event when an event log is passed."""
    from metis_tpu.profiles.store import (
        DeviceTypeMeta,
        LayerProfile,
        ProfileStore,
    )

    src_keys = store.configs(source_type)
    if not src_keys:
        raise CalibrationError(
            f"no profiled entries for source type {source_type!r}")
    if store.configs(target_type):
        raise CalibrationError(
            f"target type {target_type!r} is already profiled")
    ts = float(scales["time_scale"])
    if not ts > 0:
        raise CalibrationError(f"time_scale must be > 0, got {ts!r}")
    entries = {}
    for (t, tp, bs) in src_keys:
        prof = store.get(t, tp, bs)
        entries[(target_type, tp, bs)] = LayerProfile(
            layer_times_ms=tuple(x * ts for x in prof.layer_times_ms),
            layer_memory_mb=prof.layer_memory_mb,
            fb_sync_ms=prof.fb_sync_ms * ts,
            decode_layer_times_ms=(
                tuple(x * ts for x in prof.decode_layer_times_ms)
                if prof.decode_layer_times_ms is not None else None),
            decode_context_len=prof.decode_context_len,
        )
    src_meta = store.type_meta[source_type]
    extra = ProfileStore(
        entries, store.model,
        {target_type: DeviceTypeMeta(
            optimizer_time_ms=src_meta.optimizer_time_ms * ts,
            batch_generator_ms=src_meta.batch_generator_ms * ts)})
    extra.attn = store.attn
    merged = store.merged_with(extra)
    merged.transferred = dict(getattr(store, "transferred", {}) or {})
    merged.transferred[target_type] = {
        "source": source_type, "transferred": True, **scales}
    if events is not None:
        events.emit("transfer_fit", source_type=source_type,
                    target_type=target_type,
                    time_scale=scales.get("time_scale"),
                    compute_scale=scales.get("compute_scale"),
                    mem_scale=scales.get("mem_scale"),
                    n_entries=len(entries))
    return merged
