from metis_tpu.cost.volume import TransformerVolume
from metis_tpu.cost.bandwidth import (
    StageBandwidthModel,
    HeteroScalarBandwidth,
    HomoScalarBandwidth,
)
from metis_tpu.cost.ici import (
    IciDcnBandwidth,
    ring_all_reduce_ms,
    all_gather_ms,
    reduce_scatter_ms,
    all_to_all_ms,
    p2p_ms,
    sub_torus_eff_bw_gbps,
)
from metis_tpu.cost.calibration import (
    CollectiveCalibration,
    LinearFit,
    fit_ledger_correction,
    fit_samples,
    measure_dp_overlap,
    measure_pipeline_overlap,
    microbenchmark_collectives,
    microbenchmark_chip,
)
from metis_tpu.cost.estimator import (
    EstimatorOptions,
    UniformCostEstimator,
    HeteroCostEstimator,
    uniform_layer_split,
)

__all__ = [
    "TransformerVolume",
    "StageBandwidthModel",
    "HeteroScalarBandwidth",
    "HomoScalarBandwidth",
    "IciDcnBandwidth",
    "ring_all_reduce_ms",
    "all_gather_ms",
    "reduce_scatter_ms",
    "all_to_all_ms",
    "p2p_ms",
    "sub_torus_eff_bw_gbps",
    "CollectiveCalibration",
    "LinearFit",
    "fit_ledger_correction",
    "fit_samples",
    "measure_dp_overlap",
    "measure_pipeline_overlap",
    "microbenchmark_collectives",
    "microbenchmark_chip",
    "EstimatorOptions",
    "UniformCostEstimator",
    "HeteroCostEstimator",
    "uniform_layer_split",
]
