"""Tier-1 wiring for the closed-loop serve load generator
(tools/serve_load.py): a short multi-process storm must complete with
zero errors and zero byte-identity mismatches, and the qps baseline gate
must either pass or skip with an honest reason — never crash, never
silently pass on a host that cannot support the comparison."""
from __future__ import annotations

import json
import os

import pytest

from tools.serve_load import (
    BASELINE_PATH,
    GATE_FRACTION,
    MIN_GATE_CORES,
    gate_against_baseline,
    run_load,
)


@pytest.fixture(scope="module")
def load_result(tmp_path_factory):
    try:
        return run_load(procs=2, duration_s=1.5,
                        work_dir=tmp_path_factory.mktemp("serve-load"))
    except RuntimeError as e:  # no mp start method on this platform
        pytest.skip(str(e))


def test_load_generator_correctness(load_result):
    out = load_result
    assert out["requests"] > 0, "closed loop made no requests"
    assert out["errors"] == 0, f"{out['errors']} request errors under load"
    assert out["mismatches"] == 0, (
        f"{out['mismatches']} byte-identity mismatches under load")
    assert out["qps"] > 0


def test_load_generator_keepalive(load_result):
    # the storm must actually ride keep-alive connections: the client
    # pools report reuse AND the server's reuse counter agrees
    assert load_result["connections_reused"] > 0
    assert load_result["server_keepalive_reuse"] > 0
    # closed loop over pooled connections opens ~1 socket per worker,
    # not one per request
    assert load_result["connections_opened"] < \
        load_result["requests"] / 2


def test_qps_gate_is_honest(load_result):
    gate = gate_against_baseline(load_result, BASELINE_PATH)
    cores = os.cpu_count() or 1
    if cores < MIN_GATE_CORES:
        assert "skipped_reason" in gate
        assert str(cores) in gate["skipped_reason"]
    else:
        baseline = json.loads(BASELINE_PATH.read_text())
        if baseline.get("cores", 0) < MIN_GATE_CORES:
            # baseline from a small host: comparison must refuse itself
            assert "skipped_reason" in gate
        else:
            assert gate["floor_qps"] == round(
                GATE_FRACTION * baseline["qps"], 1)
            assert gate["ok"], (
                f"serve qps {gate['qps']} below {gate['floor_qps']} "
                f"(80% of baseline {gate['baseline_qps']})")


def test_gate_skips_without_baseline(tmp_path):
    gate = gate_against_baseline(
        {"qps": 1.0, "cores": 64}, tmp_path / "missing.json")
    assert "skipped_reason" in gate


def test_gate_fails_on_regression(tmp_path):
    path = tmp_path / "baseline.json"
    path.write_text(json.dumps({"qps": 10000.0, "cores": 8}))
    gate = gate_against_baseline({"qps": 7000.0, "cores": 8}, path)
    assert gate == {"ok": False, "qps": 7000.0, "baseline_qps": 10000.0,
                    "floor_qps": 8000.0, "baseline_cores": 8}
    gate = gate_against_baseline({"qps": 9500.0, "cores": 8}, path)
    assert gate["ok"] is True
