"""Planning under uncertainty (cost/uncertainty.py + the risk plumbing
through config, search, exact backend and calibration transfer).

The load-bearing contracts:

- point mode is byte-identical: no residual model (or risk knobs off)
  must reproduce the pre-uncertainty rankings exactly (the frozen-golden
  contract lives in test_cost_parity_frozen; here the scorer-off path);
- uniform per-type variance is a monotone transform, so quantile order
  == point order with equal variances (satellite-3 invariant);
- the exact backend's ``confidence_p`` is honest: -> 1 as variance -> 0,
  degrades as variance grows;
- ``fit_ledger_correction`` and the transfer fitters degrade with a
  typed :class:`CalibrationError`, never an IndexError.
"""
import dataclasses
import math
import sys
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "tools"))

import check_decisions_schema  # noqa: E402

from metis_tpu.cluster.spec import ClusterSpec, DeviceSpec, NodeSpec
from metis_tpu.core.config import ModelSpec, SearchConfig
from metis_tpu.core.events import EventLog, read_events
from metis_tpu.core.types import CostBreakdown, dump_ranked_plans
from metis_tpu.cost.calibration import (
    CalibrationError,
    fit_ledger_correction,
    fit_transfer_scale,
    transfer_profiles,
)
from metis_tpu.cost.uncertainty import (
    MIN_LOGNORMAL_SAMPLES,
    ResidualModel,
    annotate_breakdown,
    breakdown_sigma_ms,
    certificate_confidence,
    fit_residual_model,
    make_risk_scorer,
    mc_max_moments,
    propagate_sum_variance,
    z_score,
)
from metis_tpu.obs.ledger import AccuracyLedger
from metis_tpu.planner import plan_hetero
from metis_tpu.profiles import synthesize_profiles


def make_ledger(per_type_ratios: dict[str, list[float]],
                predicted_ms: float = 100.0) -> AccuracyLedger:
    """In-memory ledger with the given measured/predicted ratios."""
    led = AccuracyLedger(None)
    for dev, ratios in per_type_ratios.items():
        fp = f"fp-{dev or 'pooled'}"
        led.record_prediction(fp, predicted_ms)
        for r in ratios:
            led.record_measurement(fp, measured_ms=predicted_ms * r,
                                   device_type=dev)
    return led


def _workload(types=("A100", "T4")):
    model = ModelSpec(name="unc-wl", num_layers=8, hidden_size=256,
                      sequence_length=256, vocab_size=8192, num_heads=8)
    store = synthesize_profiles(model, list(types), tps=[1, 2, 4],
                                bss=[1, 2, 4, 8, 16])
    specs = {"A100": DeviceSpec("A100", 80, 100, 25),
             "T4": DeviceSpec("T4", 15, 50, 10)}
    cluster = ClusterSpec(
        nodes=tuple(NodeSpec(t, 4) for t in types),
        devices={t: specs[t] for t in types})
    return model, store, cluster


# ---------------------------------------------------------------------------
# residual fits: lognormal-or-empirical, clamping, tail ordering
# ---------------------------------------------------------------------------


def test_fit_is_lognormal_with_enough_positive_samples():
    ratios = [0.9, 1.0, 1.1, 1.2, 1.05]
    model = fit_residual_model(make_ledger({"A100": ratios}))
    fit = model.fits["A100"]
    assert fit.kind == "lognormal" and fit.n == len(ratios)
    assert fit.sigma > 0
    # pooled fit always present alongside per-type fits
    assert "" in model.fits


def test_fit_falls_back_to_empirical_below_min_samples():
    ratios = [1.0, 1.3, 0.8][:MIN_LOGNORMAL_SAMPLES - 1]
    model = fit_residual_model(make_ledger({"A100": ratios}))
    fit = model.fits["A100"]
    assert fit.kind == "empirical"
    assert fit.ratios == tuple(sorted(ratios))


def test_quantile_factor_clamped_at_one():
    # every ratio < 1: an over-predicting estimator must not DISCOUNT
    # risk scores below the point estimate (bound admissibility)
    model = fit_residual_model(make_ledger({"A100": [0.5, 0.6, 0.7, 0.8]}))
    assert model.quantile_factor(0.95, ("A100",)) == 1.0
    assert model.cvar_factor(0.9, ("A100",)) == 1.0


def test_quantile_factor_monotone_and_cvar_dominates_var():
    ratios = [0.9, 1.0, 1.1, 1.25, 1.4, 1.6]
    model = fit_residual_model(make_ledger({"A100": ratios}))
    q50 = model.quantile_factor(0.5, ("A100",))
    q90 = model.quantile_factor(0.9, ("A100",))
    q99 = model.quantile_factor(0.99, ("A100",))
    assert q50 <= q90 <= q99
    # CVaR-alpha (tail mean) >= the alpha-quantile (tail floor)
    assert model.cvar_factor(0.9, ("A100",)) >= q90


def test_single_sample_fit_p50_equals_p95():
    # one ratio: the empirical distribution is a point mass, every
    # quantile answers the same factor (satellite-3 edge case)
    model = fit_residual_model(
        make_ledger({"A100": [1.2]}), min_samples=1)
    assert model.quantile_factor(0.5, ("A100",)) == pytest.approx(
        model.quantile_factor(0.95, ("A100",)))


def test_fit_for_picks_riskiest_type_and_pools_unknown():
    model = fit_residual_model(make_ledger({
        "A100": [1.0, 1.01, 0.99, 1.0],
        "T4": [0.7, 1.5, 0.9, 1.3]}))
    assert model.fit_for(("A100", "T4")).device_type == "T4"
    # a never-measured type answers from the pooled fit
    assert model.fit_for(("H100",)).device_type == ""


def test_fit_returns_none_below_min_samples_and_skips_bad_pairs():
    led = AccuracyLedger(None)
    led.record_measurement("unmatched", 100.0)   # no prediction
    assert fit_residual_model(led) is None
    led2 = make_ledger({"A100": [1.0]})
    assert fit_residual_model(led2, min_samples=2) is None


def test_fit_emits_residual_fit_event(tmp_path):
    ev_path = tmp_path / "events.jsonl"
    fit_residual_model(make_ledger({"A100": [1.0, 1.1, 0.9, 1.2]}),
                       events=EventLog(ev_path))
    (ev,) = [e for e in read_events(ev_path)
             if e["event"] == "residual_fit"]
    assert ev["n_samples"] == 4 and ev["n_device_types"] == 1
    assert ev["kind"] == "lognormal" and ev["rel_sigma"] > 0


# ---------------------------------------------------------------------------
# risk scorer + config validation
# ---------------------------------------------------------------------------


def test_scorer_score_is_total_times_factor():
    model = fit_residual_model(
        make_ledger({"A100": [1.0, 1.2, 1.1, 1.3]}))
    cfg = SearchConfig(gbs=64, risk_quantile=0.9)
    scorer = make_risk_scorer(cfg, model)
    expected = 100.0 * model.quantile_factor(0.9, ("A100",))
    assert scorer.score(100.0, ("A100",)) == pytest.approx(expected)
    assert scorer.describe() == {"ranking": "quantile",
                                 "risk_quantile": 0.9}


def test_scorer_none_when_knobs_off_or_model_empty():
    model = fit_residual_model(make_ledger({"A100": [1.0, 1.2]}))
    assert make_risk_scorer(SearchConfig(gbs=64), model) is None
    assert make_risk_scorer(
        SearchConfig(gbs=64, risk_quantile=0.9), None) is None
    assert make_risk_scorer(
        SearchConfig(gbs=64, risk_quantile=0.9), ResidualModel()) is None


def test_cvar_mode_describe():
    model = fit_residual_model(make_ledger({"A100": [1.0, 1.2, 0.9, 1.4]}))
    scorer = make_risk_scorer(SearchConfig(gbs=64, cvar_alpha=0.9), model)
    assert scorer.describe() == {"ranking": "cvar", "cvar_alpha": 0.9}
    assert scorer.score(50.0, ("A100",)) >= 50.0


@pytest.mark.parametrize("knobs", [
    {"risk_quantile": 0.3}, {"risk_quantile": 1.0},
    {"cvar_alpha": 0.2}, {"cvar_alpha": 1.5},
    {"risk_quantile": 0.9, "cvar_alpha": 0.9},
])
def test_config_rejects_bad_risk_knobs(knobs):
    with pytest.raises(ValueError):
        SearchConfig(gbs=64, **knobs)


# ---------------------------------------------------------------------------
# variance propagation edge cases (satellite 3)
# ---------------------------------------------------------------------------


def test_propagate_sum_variance_zero_and_negative_guard():
    assert propagate_sum_variance([]) == 0.0
    assert propagate_sum_variance([0.0, 0.0]) == 0.0
    assert propagate_sum_variance([4.0, -1.0, 5.0]) == 9.0


def test_mc_max_moments_deterministic_and_zero_variance_exact():
    m1 = mc_max_moments([10.0, 12.0], [1.0, 1.5])
    m2 = mc_max_moments([10.0, 12.0], [1.0, 1.5])
    assert m1 == m2  # fixed seed: byte-identical repeats
    # all-zero sigmas: the max is deterministic, variance exactly 0
    mean, var = mc_max_moments([10.0, 12.0], [0.0, 0.0])
    assert (mean, var) == (12.0, 0.0)
    assert mc_max_moments([], []) == (0.0, 0.0)


def test_annotate_breakdown_roundtrip_and_passthrough():
    bd = CostBreakdown(total_ms=100.0,
                       components={"compute": 80.0, "pp_comm": 20.0},
                       stage_execution_ms=(40.0, 40.0))
    # no stats at all: input returned unchanged -> JSON omits the field
    empty = ResidualModel(fits={}, component_stats={})
    assert annotate_breakdown(bd, empty, ("A100",)) is bd
    assert "component_variance" not in bd.to_json_dict()

    model = fit_residual_model(make_ledger(
        {"A100": [1.0, 1.2, 0.9, 1.3]}))
    out = annotate_breakdown(bd, model, ("A100",))
    if out.component_variance:
        assert breakdown_sigma_ms(out) > 0
        # round-trips through JSON with the variances intact
        again = CostBreakdown.from_json_dict(out.to_json_dict())
        assert again.component_variance == out.component_variance
    # point-mode breakdown sigma is 0
    assert breakdown_sigma_ms(bd) == 0.0


# ---------------------------------------------------------------------------
# confidence: honest degradation
# ---------------------------------------------------------------------------


def test_certificate_confidence_limits():
    assert certificate_confidence(5.0, 0.0) == 1.0          # no variance
    assert certificate_confidence(math.inf, 10.0) == 1.0    # sole plan
    p = certificate_confidence(5.0, 1.0)
    assert 0.5 < p < 1.0


def test_certificate_confidence_monotone():
    # degrades as sigma grows, at fixed margin
    ps = [certificate_confidence(10.0, s) for s in (0.1, 1.0, 10.0, 100.0)]
    assert ps == sorted(ps, reverse=True)
    # grows with margin, at fixed sigma
    pm = [certificate_confidence(m, 5.0) for m in (0.0, 5.0, 50.0)]
    assert pm == sorted(pm)
    # z_q > 0 (risk-ranked incumbent) only raises confidence
    assert certificate_confidence(5.0, 5.0, z_q=z_score(0.95)) >= \
        certificate_confidence(5.0, 5.0)


# ---------------------------------------------------------------------------
# planner integration: ordering invariance + honest exact certificates
# ---------------------------------------------------------------------------


def test_quantile_ranking_equals_point_ranking_with_uniform_variance():
    # equal per-type variance => uniform factor => monotone transform
    model_wl, store, cluster = _workload()
    ratios = [0.9, 1.0, 1.1, 1.2]
    rmodel = fit_residual_model(
        make_ledger({"A100": ratios, "T4": ratios}))
    point = plan_hetero(cluster, store, model_wl,
                        SearchConfig(gbs=64), top_k=5)
    risky = plan_hetero(cluster, store, model_wl,
                        SearchConfig(gbs=64, risk_quantile=0.95),
                        residual_model=rmodel, top_k=5)
    assert [r.inter for r in risky.plans] == [r.inter for r in point.plans]
    assert [r.cost.total_ms for r in risky.plans] == \
        [r.cost.total_ms for r in point.plans]


def test_risk_knob_without_model_is_byte_identical_to_point():
    model_wl, store, cluster = _workload()
    point = plan_hetero(cluster, store, model_wl,
                        SearchConfig(gbs=64), top_k=5)
    risky = plan_hetero(cluster, store, model_wl,
                        SearchConfig(gbs=64, risk_quantile=0.95),
                        residual_model=None, top_k=5)
    assert dump_ranked_plans(risky.plans) == dump_ranked_plans(point.plans)


def test_exact_confidence_p_degrades_with_variance():
    model_wl, store, cluster = _workload(types=("A100",))
    cfg = SearchConfig(gbs=64, backend="exact")
    tight = fit_residual_model(make_ledger(
        {"A100": [1.0, 1.001, 0.999, 1.0]}))
    noisy = fit_residual_model(make_ledger(
        {"A100": [0.5, 1.0, 1.6, 2.2]}))

    point = plan_hetero(cluster, store, model_wl, cfg, top_k=3)
    assert point.certificate is not None
    assert point.certificate.confidence_p is None  # point mode: omitted

    p_tight = plan_hetero(cluster, store, model_wl, cfg,
                          residual_model=tight,
                          top_k=3).certificate.confidence_p
    p_noisy = plan_hetero(cluster, store, model_wl, cfg,
                          residual_model=noisy,
                          top_k=3).certificate.confidence_p
    assert p_tight is not None and p_noisy is not None
    assert p_noisy < p_tight <= 1.0
    # same certified plan either way — confidence changes, optimum not
    assert point.certificate.best_ms == pytest.approx(
        plan_hetero(cluster, store, model_wl, cfg, residual_model=noisy,
                    top_k=3).certificate.best_ms)


def test_exact_risk_ranking_never_below_point_cost():
    model_wl, store, cluster = _workload()
    rmodel = fit_residual_model(make_ledger(
        {"A100": [1.0, 1.3, 0.9, 1.5], "T4": [1.0, 1.05, 0.95, 1.1]}))
    cfg = SearchConfig(gbs=64, backend="exact", risk_quantile=0.95)
    res = plan_hetero(cluster, store, model_wl, cfg,
                      residual_model=rmodel, top_k=3)
    cert = res.certificate
    assert cert is not None and res.plans
    # the certificate lives in score space: best >= bound in one space,
    # and the score is never below the point total (clamped factor)
    assert cert.best_ms >= cert.lower_bound_ms - 1e-6
    assert cert.best_ms >= res.plans[0].cost.total_ms - 1e-6


# ---------------------------------------------------------------------------
# calibration: typed degradation + roofline transfer
# ---------------------------------------------------------------------------


def test_fit_ledger_correction_empty_raises_typed_error():
    with pytest.raises(CalibrationError):
        fit_ledger_correction([])
    led = AccuracyLedger(None)
    led.record_measurement("never-predicted", 100.0)
    with pytest.raises(CalibrationError):
        fit_ledger_correction(led.samples)


def test_fit_ledger_correction_single_sample_ok():
    led = make_ledger({"A100": [1.25]})
    fit = fit_ledger_correction(led.samples)
    assert fit["n"] == 1
    assert fit["scale"] == pytest.approx(1.25)
    assert fit["mape_after_pct"] == pytest.approx(0.0, abs=1e-3)


def test_fit_transfer_scale_roofline_math():
    src = {"matmul_tflops": 312.0, "hbm_stream_gbps": 2039.0}
    tgt = {"matmul_tflops": 65.0, "hbm_stream_gbps": 320.0}
    s = fit_transfer_scale(src, tgt, compute_mix=0.7)
    assert s["compute_scale"] == pytest.approx(65.0 / 312.0, rel=1e-4)
    assert s["mem_scale"] == pytest.approx(320.0 / 2039.0, rel=1e-4)
    assert s["time_scale"] == pytest.approx(
        0.7 / s["compute_scale"] + 0.3 / s["mem_scale"], rel=1e-4)
    # identical chips: unit scale
    assert fit_transfer_scale(src, dict(src))["time_scale"] == \
        pytest.approx(1.0)


@pytest.mark.parametrize("bad", [
    {},  # missing roofline keys
    {"matmul_tflops": 0.0, "hbm_stream_gbps": 100.0},   # degenerate
    {"matmul_tflops": 100.0, "hbm_stream_gbps": -1.0},
])
def test_fit_transfer_scale_rejects_bad_artifacts(bad):
    good = {"matmul_tflops": 312.0, "hbm_stream_gbps": 2039.0}
    with pytest.raises(CalibrationError):
        fit_transfer_scale(good, bad)
    with pytest.raises(CalibrationError):
        fit_transfer_scale(good, good, compute_mix=1.5)


def test_transfer_profiles_scales_times_not_memory(tmp_path):
    model_wl, store, _ = _workload(types=("A100",))
    scales = {"time_scale": 2.0, "compute_scale": 0.5, "mem_scale": 0.5}
    ev_path = tmp_path / "events.jsonl"
    merged = transfer_profiles(store, "A100", "H100", scales,
                               events=EventLog(ev_path))
    assert set(merged.device_types) == {"A100", "H100"}
    src = store.get("A100", 1, 1)
    out = merged.get("H100", 1, 1)
    assert out.layer_times_ms == pytest.approx(
        tuple(t * 2.0 for t in src.layer_times_ms))
    assert out.layer_memory_mb == src.layer_memory_mb  # model-shaped
    assert out.fb_sync_ms == pytest.approx(src.fb_sync_ms * 2.0)
    # provenance tag + event
    assert merged.transferred["H100"]["source"] == "A100"
    assert merged.transferred["H100"]["transferred"] is True
    (ev,) = [e for e in read_events(ev_path)
             if e["event"] == "transfer_fit"]
    assert ev["target_type"] == "H100" and ev["n_entries"] == \
        len(store.configs("A100"))
    # the source store itself is untouched
    assert not store.transferred


def test_transfer_profiles_typed_errors():
    _, store, _ = _workload(types=("A100", "T4"))
    scales = {"time_scale": 2.0}
    with pytest.raises(CalibrationError):
        transfer_profiles(store, "H100", "B200", scales)  # unprofiled src
    with pytest.raises(CalibrationError):
        transfer_profiles(store, "A100", "T4", scales)    # already profiled
    with pytest.raises(CalibrationError):
        transfer_profiles(store, "A100", "H100", {"time_scale": 0.0})


def test_transferred_plan_posture_reaches_decision_detail():
    model_wl, store, cluster = _workload(types=("A100", "T4"))
    reduced_entries = {k: store.get(*k) for k in store.configs("A100")}
    from metis_tpu.profiles.store import ProfileStore
    reduced = ProfileStore(reduced_entries, store.model,
                           {"A100": store.type_meta["A100"]})
    scales = fit_transfer_scale(
        {"matmul_tflops": 312.0, "hbm_stream_gbps": 2039.0},
        {"matmul_tflops": 65.0, "hbm_stream_gbps": 320.0})
    merged = transfer_profiles(reduced, "A100", "T4", scales)

    from metis_tpu.obs.provenance import DecisionLog
    dlog = DecisionLog(None)
    res = plan_hetero(cluster, merged, model_wl, SearchConfig(gbs=64),
                      top_k=3, decisions=dlog)
    assert res.plans
    rec = dlog.records()[-1]
    assert rec.detail.get("transferred_profiles") == ["T4"]
    # and the schema checker accepts the posture vocabulary
    assert not check_decisions_schema.validate_decisions(
        [r.to_json_dict() for r in dlog.records()])


# ---------------------------------------------------------------------------
# decisions-schema detail validation (satellite 5)
# ---------------------------------------------------------------------------


def test_decisions_schema_rejects_bad_risk_posture():
    base = {"seq": 1, "ts": 1.0, "kind": "cold_search"}
    ok = dict(base, detail={"ranking": "quantile", "risk_quantile": 0.95})
    assert not check_decisions_schema.validate_decisions([ok])
    bad_rank = dict(base, detail={"ranking": "vibes"})
    assert check_decisions_schema.validate_decisions([bad_rank])
    bad_knob = dict(base, seq=1,
                    detail={"ranking": "cvar", "cvar_alpha": 1.2})
    assert check_decisions_schema.validate_decisions([bad_knob])
