"""Deviceless Mosaic compilation of the pallas kernels (VERDICT r3 #4).

Interpret-mode tests (test_flash_attention.py, test_ring_attention.py) pin
the MATH; these pin that the TPU pallas compiler ACCEPTS the kernels —
tiling/layout/scratch rules differ from interpret mode, and every prior
round shipped kernels Mosaic had never seen.  Uses a compile-only v5e
topology from libtpu (no chip needed); skips when libtpu can't provide one.
"""
import pytest

# minutes-scale Mosaic compiles — excluded from the tier-1 "-m 'not slow'"
# run (pyproject.toml markers) so the suite fits its wall-clock budget
pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def topo():
    try:
        from jax.experimental import topologies

        return topologies.get_topology_desc("v5e:2x2", platform="tpu")
    except Exception as e:  # noqa: BLE001 — env-dependent
        pytest.skip(f"no compile-only TPU topology: {e}")


def test_all_kernels_mosaic_compile(topo, tmp_path):
    """The tool's full sweep: flash fwd (causal + stats), blockwise bwd,
    and ring attention over a 4-device sp mesh."""
    import json
    import sys
    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "tools"))
    import mosaic_aot_check

    out = tmp_path / "aot.json"
    rc = mosaic_aot_check.main(["--out", str(out)])
    record = json.loads(out.read_text())
    assert rc == 0, record
    assert record["status"] == "all kernels Mosaic-compiled"
    assert set(record["kernels"]) >= {
        "flash_fwd_causal", "flash_fwd_stats", "flash_bwd",
        "flash_fwd_gqa4", "flash_bwd_gqa4", "ring_attention_sp4"}
    assert all(v["ok"] for v in record["kernels"].values())
