"""Search-scalability prunes (search/prune.py — VERDICT r2 next-step 7).

The always-on doom fast-path must be observably invisible; the
lower-bound prune must return the SAME top-K ranking as exhaustive search;
the beam is inexact but must still find a best plan close to exhaustive.
"""
import pytest

from metis_tpu.cluster.spec import ClusterSpec, DeviceSpec, NodeSpec
from metis_tpu.core.config import ModelSpec, SearchConfig
from metis_tpu.planner import plan_hetero
from metis_tpu.profiles import synthesize_profiles


def _plan_key(r):
    return (r.inter.node_sequence, r.inter.device_groups, r.inter.batches,
            tuple((s.dp, s.tp) for s in r.intra.strategies),
            r.intra.layer_partition, r.intra.schedule)


@pytest.fixture(scope="module")
def workload():
    model = ModelSpec(name="prune-wl", num_layers=10, hidden_size=512,
                      sequence_length=256, vocab_size=8192, num_heads=8)
    store = synthesize_profiles(model, ["A100", "T4"], tps=[1, 2, 4],
                                bss=[1, 2, 4, 8, 16, 32, 64, 128])
    cluster = ClusterSpec(
        nodes=(NodeSpec("A100", 4), NodeSpec("A100", 4),
               NodeSpec("T4", 4), NodeSpec("T4", 4)),
        devices={"A100": DeviceSpec("A100", 80, 100, 25),
                 "T4": DeviceSpec("T4", 15, 50, 10)})
    return model, store, cluster


def test_exhaustive_unchanged_by_doom_fast_path(workload):
    """With no prune config the only active filter is the doom fast-path,
    which must skip exactly the candidates that yield nothing — pinned by
    the costed-plan count being identical to the pre-prune baseline (the
    search parity suite pins the actual plan set)."""
    model, store, cluster = workload
    res = plan_hetero(cluster, store, model, SearchConfig(gbs=128))
    assert res.num_costed > 100
    # doomed inter candidates were skipped without changing results
    assert res.num_bound_pruned > 0


def test_topk_parity_with_bound_prune(workload):
    model, store, cluster = workload
    K = 20
    full = plan_hetero(cluster, store, model, SearchConfig(gbs=128))
    pruned = plan_hetero(cluster, store, model,
                         SearchConfig(gbs=128, prune_to_top_k=K))
    # composition-level counting: doomed/bounded CLASSES, not candidates
    assert pruned.num_bound_pruned > 0
    assert pruned.num_costed <= full.num_costed
    full_top = [(_plan_key(r), round(r.cost.total_ms, 9))
                for r in full.plans[:K]]
    pruned_top = [(_plan_key(r), round(r.cost.total_ms, 9))
                  for r in pruned.plans[:K]]
    assert pruned_top == full_top


def test_topk_parity_when_sweep_excludes_small_bs(workload):
    """Exactness must survive a profile sweep that starts ABOVE bs=1: a
    plan whose mbs floor is below the sweep must get a scaled-down bound
    (time(mbs) >= time(smallest)*mbs/smallest), not W[smallest] verbatim
    (an over-estimate that can prune true top-K members — ADVICE r3)."""
    model, _, cluster = workload
    store = synthesize_profiles(model, ["A100", "T4"], tps=[1, 2, 4],
                                bss=[4, 8, 16, 32, 64, 128])
    K = 20
    full = plan_hetero(cluster, store, model, SearchConfig(gbs=128))
    pruned = plan_hetero(cluster, store, model,
                         SearchConfig(gbs=128, prune_to_top_k=K))
    full_top = [(_plan_key(r), round(r.cost.total_ms, 9))
                for r in full.plans[:K]]
    pruned_top = [(_plan_key(r), round(r.cost.total_ms, 9))
                  for r in pruned.plans[:K]]
    assert pruned_top == full_top


def test_w_at_scales_below_profiled_sweep(workload):
    """Direct bound check: below the sweep, _w_at returns W[smallest]
    scaled by mbs/smallest — strictly less than W[smallest]."""
    from metis_tpu.search.prune import SearchPruner

    model, _, cluster = workload
    store = synthesize_profiles(model, ["A100"], tps=[1],
                                bss=[4, 8, 16])
    pruner = SearchPruner(SearchConfig(gbs=128, prune_to_top_k=5),
                          cluster, store, model)
    w4 = pruner._w_at(4)
    assert pruner._w_at(1) == pytest.approx(w4 / 4)
    assert pruner._w_at(2) == pytest.approx(w4 / 2)
    assert pruner._w_at(8) >= w4  # at/above sweep: unchanged lookup


def test_beam_finds_near_optimal_best(workload):
    model, store, cluster = workload
    full = plan_hetero(cluster, store, model, SearchConfig(gbs=128))
    beam = plan_hetero(
        cluster, store, model,
        SearchConfig(gbs=128, prune_to_top_k=10, beam_patience=50))
    assert beam.best is not None
    # inexact, but the best plan must be the true optimum here (patience
    # 50 on this small space should not lose it) — and never better than
    # exhaustive (sanity: the beam searches a subset)
    assert beam.best.cost.total_ms >= full.best.cost.total_ms - 1e-9
    assert beam.best.cost.total_ms == pytest.approx(
        full.best.cost.total_ms, rel=0.05)


def test_strict_compat_disables_bound_prune(workload):
    model, store, cluster = workload
    res = plan_hetero(
        cluster, store, model,
        SearchConfig(gbs=128, strict_compat=True, prune_to_top_k=5))
    full = plan_hetero(cluster, store, model,
                       SearchConfig(gbs=128, strict_compat=True))
    # same plan set: the bound prune must not run under strict_compat
    assert res.num_costed == full.num_costed


def test_beam_symmetry_byte_identity_and_counter_reconciliation():
    """Beam patience under symmetry_collapse must key its patience
    counters on the RAW class key, so the collapsed search stays
    byte-identical to the uncollapsed one — and the prune counters must
    reconcile exactly: every bound-pruned class is attributed to exactly
    one of doom / stock bound / tight bound / beam patience."""
    import dataclasses
    import io
    import json

    from metis_tpu.core.events import EventLog
    from metis_tpu.core.types import dump_ranked_plans
    from metis_tpu.testing import symmetric_scale_workload

    cluster, profiles, model, config = symmetric_scale_workload(
        total_devices=128, gbs=512)
    config = dataclasses.replace(config, strict_compat=False,
                                 prune_to_top_k=10, beam_patience=2)
    from metis_tpu.planner import plan_hetero

    stream = io.StringIO()
    sym = plan_hetero(cluster, profiles, model, config, top_k=10,
                      events=EventLog(stream=stream))
    plain = plan_hetero(
        cluster, profiles, model,
        dataclasses.replace(config, symmetry_collapse=False), top_k=10)
    assert dump_ranked_plans(sym.plans) == dump_ranked_plans(plain.plans)

    counters = [json.loads(l) for l in stream.getvalue().splitlines()
                if json.loads(l)["event"] == "counters"][-1]["counters"]
    attributed = (counters.get("prune.doom", 0)
                  + counters.get("prune.bound", 0)
                  + counters.get("prune.bound.tight", 0)
                  + counters.get("prune.beam", 0))
    assert attributed == sym.num_bound_pruned
    assert sym.num_bound_pruned > 0


def test_fastest_full_model_ms_is_lower_bound(workload):
    """W_min must lower-bound every costed plan's execution sum."""
    from metis_tpu.search.prune import fastest_full_model_ms

    model, store, cluster = workload
    w_min = fastest_full_model_ms(store, cluster.device_types, max_tp=4)
    assert w_min > 0
    res = plan_hetero(cluster, store, model,
                      SearchConfig(gbs=128), top_k=50)
    for r in res.plans:
        # execution >= (B-1)*max+sum >= (B-1)*W/S + W
        lb = ((r.inter.batches - 1) * w_min / r.inter.num_stages + w_min)
        assert r.cost.execution_ms >= lb - 1e-9
