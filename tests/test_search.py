import math

import pytest

from metis_tpu.core.types import InterStagePlan, Strategy
from metis_tpu.search import (
    arrangements_of_composition,
    count_multiset_permutations,
    enumerate_device_groups,
    escalate_dp_to_tp,
    initial_strategies,
    inter_stage_plans,
    intra_stage_plans,
    merge_for_permute_cap,
    multiset_permutations,
    nondecreasing_compositions,
    power_of_two_shapes,
    strategies_valid,
    uniform_plans,
    PartitionResult,
)


class TestMultiperm:
    def test_distinct_and_complete(self):
        perms = list(multiset_permutations((1, 1, 2)))
        assert len(perms) == len(set(perms)) == 3
        assert set(perms) == {(1, 1, 2), (1, 2, 1), (2, 1, 1)}

    def test_count_matches_enumeration(self):
        items = (1, 1, 2, 2, 4)
        assert count_multiset_permutations(items) == len(list(multiset_permutations(items)))
        assert count_multiset_permutations(items) == math.factorial(5) // 4


class TestDeviceGroups:
    def test_shapes(self):
        assert power_of_two_shapes(16) == [1, 2, 4, 8, 16]
        assert power_of_two_shapes(6) == [1, 2, 4]

    def test_compositions_sum_and_order(self):
        comps = list(nondecreasing_compositions(3, 16, [1, 2, 4, 8, 16]))
        for c in comps:
            assert sum(c) == 16
            assert list(c) == sorted(c)
        assert (4, 4, 8) in comps
        assert (2, 2, 4) not in comps  # wrong sum

    def test_merge_cap_reduces_count(self):
        groups = merge_for_permute_cap([1] * 16, 6)
        assert len(groups) <= 6
        assert sum(sum(g) for g in groups) == 16

    def test_arrangements_flatten(self):
        arrs = set(arrangements_of_composition((4, 4, 8), 6))
        assert (8, 4, 4) in arrs and (4, 8, 4) in arrs and (4, 4, 8) in arrs
        assert all(sum(a) == 16 for a in arrs)

    def test_variance_filters_small_groups(self):
        loose = enumerate_device_groups(4, 16, variance=0.0)
        tight = enumerate_device_groups(4, 16, variance=1.0)
        assert len(tight) < len(loose)
        # with variance=1 and 4 stages of 16 devices, min group = 16//4 = 4
        assert all(min(g) >= 4 for g in tight)

    def test_every_group_sums_to_cluster(self):
        for stages in (1, 2, 3, 4):
            for g in enumerate_device_groups(stages, 16, variance=0.5):
                assert sum(g) == 16 and len(g) == stages


class TestUniformPlans:
    def test_valid_grids(self):
        plans = list(uniform_plans(num_devices=8, max_tp=4, gbs=32))
        assert plans
        for p in plans:
            assert p.dp * p.pp * p.tp == 8
            assert p.tp <= 4
            assert p.gbs % (p.dp * p.mbs) == 0
            assert p.num_microbatches >= 1

    def test_no_duplicates(self):
        plans = list(uniform_plans(num_devices=8, max_tp=4, gbs=32))
        assert len(plans) == len(set(plans))


class TestInterStagePlans:
    def test_structure(self):
        plans = list(inter_stage_plans(
            ["A100", "T4"], num_devices=16, gbs=128, num_layers=10,
            variance=1.0, max_permute_len=6))
        assert plans
        for p in plans:
            assert sum(p.device_groups) == 16
            assert p.gbs % p.batches == 0
            assert 1 <= p.num_stages <= 10
            assert p.node_sequence in {("A100", "T4"), ("T4", "A100")}

    def test_stage_cap_respects_layers(self):
        plans = list(inter_stage_plans(["A100"], 16, 16, num_layers=3,
                                       variance=0.5))
        assert max(p.num_stages for p in plans) == 3


class _FakeEvaluator:
    def __init__(self, capacity):
        self._cap = capacity

    def memory_capacity(self, plan):
        return list(self._cap)

    def compute_performance(self, plan, strategies):
        n = len(plan.device_groups)
        return [1.0 / n] * n


class _FakePartitioner:
    """Feasible only when every stage runs tp >= min_tp (simulates memory
    pressure that dp->tp escalation relieves)."""

    def __init__(self, min_tp=1, attempts=1):
        self.min_tp = min_tp
        self.attempts = attempts
        self.calls = 0

    def partition(self, plan, strategies, perf, cap):
        self.calls += 1
        n = len(strategies)
        if all(s.tp >= self.min_tp for s in strategies):
            bounds = tuple(round(i * 10 / n) for i in range(n + 1))
            return PartitionResult(bounds, self.attempts, tuple(1.0 for _ in strategies))
        return PartitionResult(None, -1, tuple(-1.0 if s.tp < self.min_tp else 1.0
                                               for s in strategies))


class TestIntraStagePlans:
    def _plan(self, groups=(8, 8), batches=8, gbs=128):
        return InterStagePlan(("T4", "A100"), tuple(groups), batches, gbs)

    def test_initial_strategies_full_dp(self):
        s = initial_strategies(self._plan())
        assert s == (Strategy(8, 1), Strategy(8, 1))

    def test_validity_bounds(self):
        p = self._plan(batches=8)
        assert strategies_valid(p, (Strategy(2, 1), Strategy(2, 1)), max_tp=4, max_bs=16)
        # mbs = 128/8/8 = 2 ok; tp above profiled cap invalid
        assert not strategies_valid(p, (Strategy(1, 8), Strategy(8, 1)), max_tp=4, max_bs=16)
        # dp too big => mbs 0
        assert not strategies_valid(
            InterStagePlan(("T4",), (256,), 1, 128), (Strategy(256, 1),), 4, 16)

    def test_escalation_order_prefers_pressured_stage(self):
        s = (Strategy(4, 1), Strategy(4, 1))
        out = escalate_dp_to_tp(s, memory_state=(5.0, -3.0))
        assert out == (Strategy(4, 1), Strategy(2, 2))  # stage 1 most pressured

    def test_escalation_exhausts(self):
        assert escalate_dp_to_tp((Strategy(1, 4),), None) is None

    def test_first_attempt_success_stops_search(self):
        ev = _FakeEvaluator([1e9, 1e9])
        part = _FakePartitioner(min_tp=1, attempts=1)
        plans = list(intra_stage_plans(self._plan(), ev, part, max_tp=4, max_bs=16))
        assert len(plans) == 1
        assert plans[0].strategies == (Strategy(8, 1), Strategy(8, 1))
        assert part.calls == 1

    def test_escalates_until_feasible(self):
        ev = _FakeEvaluator([1e9, 1e9])
        part = _FakePartitioner(min_tp=2, attempts=1)
        plans = list(intra_stage_plans(self._plan(), ev, part, max_tp=4, max_bs=16))
        assert len(plans) == 1
        assert all(s.tp >= 2 for s in plans[0].strategies)

    def test_repaired_partition_keeps_searching(self):
        ev = _FakeEvaluator([1e9, 1e9])
        part = _FakePartitioner(min_tp=1, attempts=2)  # always needs repair
        plans = list(intra_stage_plans(self._plan(), ev, part, max_tp=4, max_bs=16))
        assert len(plans) > 1  # kept yielding while escalating
