"""Event-JSONL schema validation (tools/check_events_schema.py) wired into
tier-1: a freshly generated planner run must validate clean, so schema
drift between emitters and the documented contract breaks the build."""
import json
import sys
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "tools"))

import check_events_schema  # noqa: E402

from metis_tpu.core.events import EventLog, read_events  # noqa: E402


@pytest.fixture(scope="module")
def planner_events(tmp_path_factory):
    """A fresh planner run's event file — the real emitters, not fixtures."""
    from metis_tpu.cluster import ClusterSpec
    from metis_tpu.core.config import SearchConfig
    from metis_tpu.planner import plan_hetero
    from metis_tpu.profiles import synthesize_profiles, tiny_test_model

    model = tiny_test_model()
    store = synthesize_profiles(model, ["A100", "T4"], tps=[1, 2, 4],
                                bss=[1, 2, 4, 8, 16])
    cluster = ClusterSpec.of(("A100", 2, 4), ("T4", 1, 4))
    path = tmp_path_factory.mktemp("schema") / "events.jsonl"
    with EventLog(path) as log:
        plan_hetero(cluster, store, model,
                    SearchConfig(gbs=64, progress_every=200), events=log)
    return path


def test_fresh_planner_run_validates_clean(planner_events):
    n, problems = check_events_schema.validate_file(planner_events)
    assert problems == []
    assert n >= 6  # spans + started/finished + counters at minimum


def test_fresh_planner_run_emits_plan_explain(planner_events):
    """The planner attaches top-k breakdowns and their plan_explain events
    ride in the same (schema-clean) file."""
    explains = [e for e in read_events(planner_events)
                if e["event"] == "plan_explain"]
    assert explains
    for e in explains:
        assert sum(e["components"].values()) == pytest.approx(
            e["total_ms"], abs=0.01)


def test_accuracy_and_drift_events_validate(tmp_path):
    """The obs/ledger emitters (accuracy_sample, drift_alarm) conform to
    the documented schema."""
    from metis_tpu.obs.ledger import AccuracyLedger, AccuracyMonitor

    path = tmp_path / "acc.jsonl"
    with EventLog(path) as log:
        led = AccuracyLedger(None)
        led.record_prediction("fp01", 100.0)
        mon = AccuracyMonitor(led, "fp01", events=log, band_pct=10.0,
                              min_samples=2, skip_steps=0)
        for i in range(4):
            mon.observe(150.0, step=i)
    events = read_events(path)
    names = [e["event"] for e in events]
    assert "accuracy_sample" in names and names.count("drift_alarm") == 1
    assert check_events_schema.validate_events(events) == []


def test_every_emitted_event_name_is_documented(planner_events):
    names = {e["event"] for e in read_events(planner_events)}
    assert names <= set(check_events_schema.EVENT_SCHEMA)


def test_unknown_event_name_is_flagged():
    problems = check_events_schema.validate_events(
        [{"ts": 1.0, "event": "not_a_real_event"}])
    assert len(problems) == 1 and "unknown event name" in problems[0]


def test_missing_ts_and_event_flagged():
    problems = check_events_schema.validate_events(
        [{"event": "search_started"}, {"ts": 1.0}])
    assert any("'ts'" in p for p in problems)
    assert any("'event'" in p for p in problems)


def test_missing_required_fields_flagged():
    problems = check_events_schema.validate_events(
        [{"ts": 1.0, "event": "span_end", "name": "x"}])
    assert len(problems) == 1
    assert "missing fields" in problems[0]
    assert "span_id" in problems[0]


def test_invalid_json_line_is_a_problem_not_a_crash(tmp_path):
    p = tmp_path / "bad.jsonl"
    p.write_text('{"ts": 1.0, "event": "search_progress", "n": 1, '
                 '"elapsed_s": 0.1}\n{not json\n')
    n, problems = check_events_schema.validate_file(p)
    assert n == 1
    assert any("invalid JSON" in x for x in problems)


def test_cli_main_exit_codes(planner_events, tmp_path, capsys):
    assert check_events_schema.main([str(planner_events)]) == 0
    bad = tmp_path / "bad.jsonl"
    bad.write_text(json.dumps({"ts": 1.0, "event": "mystery"}) + "\n")
    assert check_events_schema.main([str(bad)]) == 1
    out = capsys.readouterr().out
    assert "schema OK" in out and "unknown event name" in out


def test_profiler_and_train_events_validate(tmp_path):
    """The non-planner emitters (profiler measurement events, train-step
    telemetry) also conform to the documented schema."""
    from metis_tpu.core.config import ModelSpec
    from metis_tpu.execution.train import StepTimer
    from metis_tpu.profiles.profiler import ProfilerConfig, profile_model

    path = tmp_path / "mixed.jsonl"
    with EventLog(path) as log:
        model = ModelSpec(name="t", num_layers=4, hidden_size=64,
                          sequence_length=32, vocab_size=128, num_heads=4)
        profile_model(model, tps=(1, 16), bss=(1,),
                      config=ProfilerConfig(warmup=1, iters=1),
                      events=log)
        timer = StepTimer(log, tokens_per_step=64 * 32)
        for i in range(3):
            timer.record(loss=3.0 - i)
    events = read_events(path)
    names = [e["event"] for e in events]
    assert "profile_started" in names and "profile_measured" in names
    assert "profile_skipped" in names  # tp=16 > local devices
    assert "profile_finished" in names
    steps = [e for e in events if e["event"] == "train_step"]
    assert [s["step"] for s in steps] == [1, 2, 3]
    assert all("tokens_per_s" in s and "step_ms" in s for s in steps)
    assert check_events_schema.validate_events(events) == []
