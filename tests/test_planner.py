import json

import pytest

from metis_tpu.cluster import ClusterSpec, DeviceSpec, TpuClusterSpec, slice_from_name
from metis_tpu.core.config import SearchConfig
from metis_tpu.planner import plan_hetero, plan_tpu, plan_uniform
from metis_tpu.planner.cli import main as cli_main
from metis_tpu.profiles import synthesize_profiles, tiny_test_model


@pytest.fixture(scope="module")
def small_cluster():
    return ClusterSpec.of(
        ("T4", 1, 4), ("A100", 1, 4),
        overrides={
            "T4": DeviceSpec("T4", 15, 50, 10),
            "A100": DeviceSpec("A100", 80, 46, 10),
        })


@pytest.fixture(scope="module")
def profiles():
    return synthesize_profiles(
        tiny_test_model(), ["A100", "T4"], tps=[1, 2, 4], bss=[1, 2, 4, 8, 16])


class TestPlanHetero:
    def test_end_to_end(self, small_cluster, profiles):
        result = plan_hetero(
            small_cluster, profiles, tiny_test_model(),
            SearchConfig(gbs=32, strict_compat=True))
        assert result.num_costed > 10
        best = result.best
        assert best is not None
        # ranked ascending
        costs = [p.cost.total_ms for p in result.plans]
        assert costs == sorted(costs)
        # plan internally consistent
        assert sum(best.inter.device_groups) == 8
        assert best.intra.layer_partition[0] == 0
        assert best.intra.layer_partition[-1] == 10
        for s, g in zip(best.intra.strategies, best.inter.device_groups):
            assert s.dp * s.tp == g

    def test_top_k(self, small_cluster, profiles):
        result = plan_hetero(
            small_cluster, profiles, tiny_test_model(),
            SearchConfig(gbs=32, strict_compat=True), top_k=5)
        assert len(result.plans) == 5


class TestPlanUniform:
    def test_end_to_end(self, small_cluster, profiles):
        result = plan_uniform(
            small_cluster, profiles, tiny_test_model(),
            SearchConfig(gbs=32, strict_compat=True), device_type="A100",
            include_oom=True)
        assert result.num_costed > 5
        for r in result.plans:
            assert r.plan.dp * r.plan.pp * r.plan.tp == 8


class TestPlanTpu:
    def test_north_star_topology(self):
        tc = TpuClusterSpec((slice_from_name("v4-32"), slice_from_name("v5e-16")))
        profiles = synthesize_profiles(
            tiny_test_model(), ["tpu_v4", "tpu_v5e"], tps=[1, 2, 4],
            bss=[1, 2, 4, 8, 16])
        result = plan_tpu(
            tc, profiles, tiny_test_model(),
            SearchConfig(gbs=64, min_group_scale_variance=1.0), top_k=10)
        assert result.best is not None
        assert sum(result.best.inter.device_groups) == 48
        # faster chips should end up with more than proportional work or the
        # plan should at least be feasible and costed
        assert result.best.cost.total_ms > 0


class TestCli:
    def test_hetero_cli_json(self, tmp_path, profiles, capsys):
        profiles.dump_to_dir(tmp_path / "profiles")
        (tmp_path / "hostfile").write_text("h1 slots=4\nh2 slots=4\n")
        (tmp_path / "cluster.json").write_text(json.dumps({
            "h1": {"instance_type": "T4", "inter_bandwidth": 10,
                   "intra_bandwidth": 50, "memory": 15},
            "h2": {"instance_type": "A100", "inter_bandwidth": 10,
                   "intra_bandwidth": 46, "memory": 80}}))
        out = tmp_path / "plans.json"
        rc = cli_main([
            "hetero",
            "--hostfile", str(tmp_path / "hostfile"),
            "--clusterfile", str(tmp_path / "cluster.json"),
            "--profile-dir", str(tmp_path / "profiles"),
            "--gbs", "32", "--num-layers", "10", "--hidden-size", "4096",
            "--seq-len", "1024", "--vocab-size", "51200", "--num-heads", "32",
            "--strict-compat", "--top-k", "3",
            "--output", str(out),
        ])
        assert rc == 0
        plans = json.loads(out.read_text())
        assert len(plans) == 3
        assert plans[0]["rank"] == 1
        assert plans[0]["cost_ms"] <= plans[1]["cost_ms"]
        assert "strategies" in plans[0] and "layer_partition" in plans[0]

    def test_tpu_cli(self, tmp_path, capsys):
        profiles = synthesize_profiles(
            tiny_test_model(), ["tpu_v5e"], tps=[1, 2, 4], bss=[1, 2, 4, 8])
        profiles.dump_to_dir(tmp_path / "profiles")
        rc = cli_main([
            "tpu", "--slices", "v5e-16",
            "--profile-dir", str(tmp_path / "profiles"),
            "--gbs", "16", "--num-layers", "10", "--hidden-size", "4096",
            "--seq-len", "1024", "--vocab-size", "51200", "--num-heads", "32",
            "--top-k", "2",
        ])
        assert rc == 0
        out = capsys.readouterr().out
        assert json.loads(out)[0]["rank"] == 1


class TestPlannerBeatsReferenceBalancer:
    """Our DP balancer must never lose to the reference's greedy under the
    identical (strict-compat) cost semantics."""

    def test_best_cost_not_worse_than_reference(self, reference_run, parity_fixture_dir):
        from metis_tpu.profiles import ProfileStore

        # Two upstream artifacts are excluded from the comparison:
        # 1. loop-recorded costs hit by the num_stage corruption — use DIRECT
        #    evaluations instead (see conftest reference_run docstring);
        # 2. INVALID partitions from the greedy balancer: its majority-vote
        #    collapse (load_balancer.py:290-308) can emit empty stages and
        #    even drop layers entirely (e.g. partition [0,1,...,1,8] on a
        #    10-layer model — layers 8-9 never costed), producing
        #    artificially low totals.  Our DP balancer guarantees full
        #    coverage with non-empty stages, so only structurally valid
        #    reference candidates are comparable.
        num_layers = tiny_test_model().num_layers

        def partition_valid(part):
            return (part[0] == 0 and part[-1] == num_layers
                    and all(a < b for a, b in zip(part, part[1:])))

        ref_best = min(
            direct
            for rec, direct in zip(reference_run["costs"],
                                   reference_run["direct_costs"])
            if partition_valid(rec[4]))

        cluster = ClusterSpec.from_files(
            parity_fixture_dir / "hostfile", parity_fixture_dir / "clusterfile.json")
        store = ProfileStore.from_dir(parity_fixture_dir / "profiles")
        ours = plan_hetero(
            cluster, store, tiny_test_model(),
            SearchConfig(gbs=128, strict_compat=True))
        assert ours.best is not None
        # identical cost semantics + optimal balancer => never worse
        assert ours.best.cost.total_ms <= ref_best * (1 + 1e-9)
