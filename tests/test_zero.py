"""ZeRO/FSDP plan axis: cost/memory model, planner families, and the
FSDP-sharded execution path."""
from dataclasses import replace

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from metis_tpu.cost.zero import (
    shardable_bytes_per_param_byte,
    zero_candidates,
    zero_dp_factor,
    zero_static_reduction_mb,
)


class TestZeroCostModel:
    def test_candidates(self):
        assert zero_candidates(False) == [0]
        assert zero_candidates(True) == [0, 1, 2, 3]

    def test_dp_factor(self):
        assert zero_dp_factor(0) == 1.0
        assert zero_dp_factor(1) == 1.0
        assert zero_dp_factor(2) == 1.0
        assert zero_dp_factor(3) == 1.5

    def test_shardable_bytes_progression(self):
        # bf16 params: Adam fp32 state = 12B per 2B stored -> 6x at stage 1
        assert shardable_bytes_per_param_byte(2, 0) == 0.0
        assert shardable_bytes_per_param_byte(2, 1) == 6.0
        assert shardable_bytes_per_param_byte(2, 2) == 7.0
        assert shardable_bytes_per_param_byte(2, 3) == 8.0

    def test_reduction_scaling(self):
        params = (1024 * 1024, 2 * 1024 * 1024)
        # stage 3, 4 ranks, tp=1: 8x param bytes, 3/4 sharded away
        got = zero_static_reduction_mb(params, 3, 4, tp=1, dtype_bytes=2)
        assert got == pytest.approx((8 * 0.75, 16 * 0.75))
        # tp halves the per-rank stored params
        got_tp2 = zero_static_reduction_mb(params, 3, 4, tp=2, dtype_bytes=2)
        assert got_tp2 == pytest.approx((4 * 0.75, 8 * 0.75))
        assert zero_static_reduction_mb(params, 0, 4) is None
        assert zero_static_reduction_mb(params, 3, 1) is None

    def test_reduction_with_experts_never_optimistic(self):
        """Expert state replicates over only d/ep ranks — ZeRO recovers
        (1 - ep/d) of it, and nothing when d == ep."""
        params = (1024 * 1024, 1024 * 1024, 1024 * 1024)  # embed/block/head
        d, ep, frac = 8, 4, 0.5
        got = zero_static_reduction_mb(params, 1, d, dtype_bytes=2,
                                       expert_frac=frac, ep=ep)
        per_byte = 6.0
        dense_f, exp_f = 1 - 1 / d, 1 - 1 / (d // ep)
        want_block = per_byte * ((1 - frac) * dense_f + frac / ep * exp_f)
        assert got[1] == pytest.approx(want_block)
        # embed/head are expert-free: plain dense relief
        assert got[0] == got[2] == pytest.approx(per_byte * dense_f)
        # d == ep: expert state cannot shard further
        got_eq = zero_static_reduction_mb(params, 1, 4, dtype_bytes=2,
                                          expert_frac=frac, ep=4)
        assert got_eq[1] == pytest.approx(
            per_byte * (1 - frac) * (1 - 1 / 4))

    def test_escalation_drops_degenerate_zero(self):
        from metis_tpu.core.types import Strategy
        from metis_tpu.search.intra_stage import escalate_dp_to_tp

        s = (Strategy(dp=2, tp=2, zero=3),)
        out = escalate_dp_to_tp(s, None)
        assert out[0].dp == 1 and out[0].zero == 0


@pytest.fixture(scope="module")
def planner_setup():
    from metis_tpu.cluster import ClusterSpec
    from metis_tpu.profiles import synthesize_profiles, tiny_test_model

    model = tiny_test_model()
    store = synthesize_profiles(model, ["A100"], tps=[1, 2, 4],
                                bss=[1, 2, 4, 8, 16])
    cluster = ClusterSpec.homogeneous("A100", num_nodes=2, devices_per_node=4)
    return model, store, cluster


class TestPlannerZeroFamilies:
    def test_zero_families_searched(self, planner_setup):
        from metis_tpu.core.config import SearchConfig
        from metis_tpu.planner import plan_hetero

        model, store, cluster = planner_setup
        result = plan_hetero(cluster, store, model,
                             SearchConfig(gbs=64, enable_zero=True))
        zeros = {s.zero for r in result.plans for s in r.intra.strategies}
        assert zeros == {0, 1, 2, 3}, f"zero stages missing: {zeros}"

    def test_zero_cuts_optimizer_cost(self, planner_setup):
        from metis_tpu.core.config import SearchConfig
        from metis_tpu.planner import plan_hetero

        model, store, cluster = planner_setup
        result = plan_hetero(cluster, store, model,
                             SearchConfig(gbs=64, enable_zero=True))

        def best(pred):
            ms = [r for r in result.plans
                  if all(pred(s) for s in r.intra.strategies)
                  and all(s.dp * s.cp > 1 for s in r.intra.strategies)]
            return ms[0] if ms else None

        z0 = best(lambda s: s.zero == 0)
        z1 = best(lambda s: s.zero == 1)
        assert z0 is not None and z1 is not None
        # same-shape plans exist in both families; the zero-1 family's best
        # optimizer cost must undercut the replicated one
        assert z1.cost.optimizer_ms < z0.cost.optimizer_ms

    def test_zero3_charges_gather_traffic(self, planner_setup):
        """Same (inter, strategies) plan at zero 2 vs 3: dp comm is 1.5x.

        Serial pricing: the 1.5x is a raw-traffic ratio; the overlap
        model's ``max(0, comm - optimizer)`` window would break it
        (test_overlap.py covers that pricing)."""
        from metis_tpu.core.config import SearchConfig
        from metis_tpu.planner import plan_hetero

        model, store, cluster = planner_setup
        result = plan_hetero(cluster, store, model,
                             SearchConfig(gbs=64, enable_zero=True,
                                          use_overlap_model=False))
        by_key = {}
        for r in result.plans:
            zset = {s.zero for s in r.intra.strategies}
            if len(zset) != 1:
                continue
            key = (r.inter, tuple((s.dp, s.tp, s.cp) for s in r.intra.strategies),
                   r.intra.layer_partition)
            by_key.setdefault(key, {})[zset.pop()] = r
        pairs = [v for v in by_key.values() if 2 in v and 3 in v
                 and v[2].cost.dp_comm_ms > 0]
        assert pairs
        for v in pairs[:5]:
            assert v[3].cost.dp_comm_ms == pytest.approx(
                1.5 * v[2].cost.dp_comm_ms)


class TestZeroMemoryRelief:
    def test_memory_row_monotone_in_stage(self, planner_setup):
        from metis_tpu.balance.layers import LayerBalancer
        from metis_tpu.core.config import SearchConfig
        from metis_tpu.core.types import Strategy

        model, store, cluster = planner_setup
        bal = LayerBalancer(cluster, store, SearchConfig(gbs=64), model=model)
        rows = [
            bal._sharded_memory_row("A100", 4, Strategy(dp=4, tp=1, zero=z))
            for z in (0, 1, 2, 3)
        ]
        totals = [sum(r) for r in rows]
        assert totals[0] > totals[1] > totals[2] > totals[3]


class TestFsdpExecution:
    def test_fsdp_specs_shard_large_params(self):
        from jax.sharding import PartitionSpec as P
        from metis_tpu.execution import fsdp_wrap_specs, param_specs_for
        from metis_tpu.models import GPTConfig, init_params

        cfg = GPTConfig(vocab_size=128, seq_len=16, hidden=32, num_heads=2,
                        num_blocks=2, dtype=jnp.float32)
        params = init_params(jax.random.PRNGKey(0), cfg)
        specs = fsdp_wrap_specs(
            param_specs_for(cfg, tp_axis=None), params, dp_axis="dp")
        # vocab dim (largest) of the embedding shards over dp
        assert specs["embed"]["tok"] == P("dp", None)
        # stacked qkv [L, 3, h, h]: one h dim takes dp
        assert "dp" in tuple(specs["blocks"]["qkv"])
        # truly-1D leaves (unstacked head norms) stay replicated
        assert specs["head"]["ln_scale"] == P()

    def test_fsdp_specs_respect_divisibility(self):
        """Dims not divisible by the dp axis size fall to the next largest
        divisible dim, or stay replicated."""
        from jax.sharding import PartitionSpec as P
        from metis_tpu.execution import fsdp_wrap_specs, param_specs_for
        from metis_tpu.models import GPTConfig, init_params

        cfg = GPTConfig(vocab_size=131, seq_len=16, hidden=32, num_heads=2,
                        num_blocks=2, dtype=jnp.float32)  # prime vocab
        params = init_params(jax.random.PRNGKey(0), cfg)
        specs = fsdp_wrap_specs(param_specs_for(cfg, tp_axis=None), params,
                                dp_axis="dp", axis_size=8)
        # vocab 131 % 8 != 0: embedding shards its hidden dim instead
        assert specs["embed"]["tok"] == P(None, "dp")
        # head out [h=32, v=131]: hidden shards
        assert specs["head"]["out"] == P("dp", None)

    def test_fsdp_step_matches_unsharded(self):
        import numpy as onp
        from jax.sharding import Mesh
        from metis_tpu.execution import (
            DP, build_train_state, make_train_step)
        from metis_tpu.models import GPTConfig, init_params
        from metis_tpu.models.gpt import next_token_loss

        cfg = GPTConfig(vocab_size=128, seq_len=16, hidden=32, num_heads=2,
                        num_blocks=2, dtype=jnp.float32)
        mesh = Mesh(onp.array(jax.devices()[:8]).reshape(8), (DP,))
        state, specs = build_train_state(
            jax.random.PRNGKey(0), cfg, mesh, tp_axis=None, fsdp_axis=DP)
        step = make_train_step(cfg, mesh)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 16), 0, 128)
        _, loss = step(state, tokens, tokens)

        params = init_params(jax.random.PRNGKey(0), cfg)
        want = next_token_loss(params, tokens, tokens, cfg)
        np.testing.assert_allclose(float(loss), float(want), rtol=1e-5)

    def test_fsdp_opt_state_is_sharded(self):
        import numpy as onp
        from jax.sharding import Mesh
        from metis_tpu.execution import DP, build_train_state
        from metis_tpu.models import GPTConfig

        cfg = GPTConfig(vocab_size=128, seq_len=16, hidden=32, num_heads=2,
                        num_blocks=2, dtype=jnp.float32)
        mesh = Mesh(onp.array(jax.devices()[:8]).reshape(8), (DP,))
        state, _ = build_train_state(
            jax.random.PRNGKey(0), cfg, mesh, tp_axis=None, fsdp_axis=DP)
        # Adam mu for the embedding must carry the dp sharding
        mu_tok = state.opt_state[0].mu["embed"]["tok"]
        assert "dp" in str(mu_tok.sharding.spec)