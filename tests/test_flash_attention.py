"""Pallas flash attention vs the dense reference (interpret mode on CPU)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

# interpreter-mode pallas numerics run the kernel grid step-by-step on CPU
# (~160 s with test_ring_attention per VERDICT r5) — excluded from the
# tier-1 "-m 'not slow'" run so the suite fits its wall-clock budget
pytestmark = pytest.mark.slow

from metis_tpu.models.gpt import causal_attention
from metis_tpu.ops.flash_attention import (
    dense_causal_attention,
    finalize_stats,
    flash_attention,
    flash_attention_stats,
    merge_stats,
    _pick_block,
)


def _qkv(key, b=2, h=2, s=128, d=16, dtype=jnp.float32):
    ks = jax.random.split(key, 3)
    shape = (b, h, s, d)
    return tuple(jax.random.normal(k, shape, dtype) for k in ks)


def test_pick_block():
    assert _pick_block(256, 128) == 128
    assert _pick_block(96, 128) == 96
    assert _pick_block(40, 32) == 8
    assert _pick_block(7, 128) is None


def test_forward_matches_dense():
    q, k, v = _qkv(jax.random.PRNGKey(0), s=128, d=16)
    got = flash_attention(q, k, v, block_q=32, block_kv=32, interpret=True)
    want = causal_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_forward_uneven_blocks():
    # block_q != block_kv exercises the causal block-skip boundary logic
    q, k, v = _qkv(jax.random.PRNGKey(1), s=96, d=8)
    got = flash_attention(q, k, v, block_q=48, block_kv=16, interpret=True)
    want = causal_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_forward_wide_kv_blocks():
    # block_kv > block_q — the ORIENTATION of the shipped default tiling
    # (DEFAULT_BLOCK_Q=512 < DEFAULT_BLOCK_KV=1024): a KV block then spans
    # multiple Q blocks, so the causal skip predicate must keep diagonal
    # blocks that are only PARTIALLY in the future, and the element mask
    # must zero exactly the upper-triangular remainder.  fwd AND grad.
    q, k, v = _qkv(jax.random.PRNGKey(5), s=64, d=16)
    got = flash_attention(q, k, v, block_q=16, block_kv=32, interpret=True)
    want = causal_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)

    def loss_f(fn):
        return lambda q: fn(q).sum()

    gf = jax.grad(loss_f(lambda q: flash_attention(
        q, k, v, block_q=16, block_kv=32, interpret=True)))(q)
    gd = jax.grad(loss_f(lambda q: causal_attention(q, k, v)))(q)
    np.testing.assert_allclose(np.asarray(gf), np.asarray(gd),
                               rtol=2e-4, atol=2e-4)


def test_forward_noncausal():
    q, k, v = _qkv(jax.random.PRNGKey(2), s=64, d=16)
    got = flash_attention(q, k, v, causal=False, block_q=32, block_kv=32,
                          interpret=True)
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) / jnp.sqrt(16.0)
    want = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(scores, -1), v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_fallback_untileable_shapes():
    # seq=7 has no multiple-of-8 divisor: must silently use the dense path
    q, k, v = _qkv(jax.random.PRNGKey(3), s=7, d=16)
    got = flash_attention(q, k, v, interpret=True)
    want = causal_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_grad_matches_dense():
    q, k, v = _qkv(jax.random.PRNGKey(4), s=64, d=8)

    def loss_flash(q, k, v):
        return flash_attention(q, k, v, block_q=32, block_kv=32,
                               interpret=True).sum()

    def loss_dense(q, k, v):
        return dense_causal_attention(q, k, v).sum()

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for gf, gd in zip(g_flash, g_dense):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gd),
                                   rtol=1e-4, atol=1e-4)


def test_bf16_inputs():
    q, k, v = _qkv(jax.random.PRNGKey(5), s=64, d=16, dtype=jnp.bfloat16)
    got = flash_attention(q, k, v, block_q=32, block_kv=32, interpret=True)
    want = causal_attention(q, k, v)
    assert got.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32),
        rtol=3e-2, atol=3e-2)


def test_stats_merge_equals_full_attention():
    """Two disjoint KV shards folded with merge_stats == full attention —
    the algebra a pallas ring attention composes over."""
    q, k, v = _qkv(jax.random.PRNGKey(6), s=64, d=16)
    half = 32
    sa = flash_attention_stats(q, k[:, :, :half], v[:, :, :half],
                               block_q=32, block_kv=16, interpret=True)
    sb = flash_attention_stats(q, k[:, :, half:], v[:, :, half:],
                               block_q=32, block_kv=16, interpret=True)
    got = finalize_stats(merge_stats(sa, sb))

    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) / jnp.sqrt(16.0)
    want = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(scores, -1), v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_model_forward_with_flash():
    """GPTConfig(attn="flash") end-to-end forward parity with dense."""
    from metis_tpu.models import GPTConfig, forward, init_params
    from metis_tpu.ops.flash_attention import flash_attn_fn

    cfg = GPTConfig(vocab_size=128, seq_len=32, hidden=32, num_heads=2,
                    num_blocks=2, dtype=jnp.float32)
    params = init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, 128)
    dense = forward(params, tokens, cfg)
    flash = forward(params, tokens, cfg,
                    attn_impl=flash_attn_fn(interpret=True, block_q=16,
                                            block_kv=16))
    np.testing.assert_allclose(np.asarray(flash), np.asarray(dense),
                               rtol=2e-4, atol=2e-4)


def test_gqa_flash_matches_expanded_dense():
    """GQA-native kernel path: K/V at kv_heads < q_heads, parity (fwd +
    all grads incl. the grouped dK/dV scratch accumulation) vs the dense
    reference on repeat-expanded K/V.  The expansion never touches HBM in
    the kernel path — models.llama skips its jnp.repeat for GQA-capable
    impls (flash_attn_fn.supports_gqa)."""
    b, nh, kvh, s, d = 2, 8, 2, 64, 16
    key = jax.random.PRNGKey(3)
    q = jax.random.normal(jax.random.fold_in(key, 0), (b, nh, s, d))
    k = jax.random.normal(jax.random.fold_in(key, 1), (b, kvh, s, d))
    v = jax.random.normal(jax.random.fold_in(key, 2), (b, kvh, s, d))
    rep = nh // kvh
    want = dense_causal_attention(
        q, jnp.repeat(k, rep, axis=1), jnp.repeat(v, rep, axis=1))
    got = flash_attention(q, k, v, block_q=32, block_kv=32, interpret=True)
    np.testing.assert_allclose(got, want, atol=2e-5)

    def loss_flash(q, k, v):
        return (flash_attention(q, k, v, block_q=32, block_kv=32,
                                interpret=True) ** 2).sum()

    def loss_dense(q, k, v):
        kf = jnp.repeat(k, rep, axis=1)
        vf = jnp.repeat(v, rep, axis=1)
        return (dense_causal_attention(q, kf, vf) ** 2).sum()

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    assert gf[1].shape == (b, kvh, s, d)  # grads stay in the GQA layout
    for a, b_ in zip(gf, gd):
        np.testing.assert_allclose(a, b_, atol=3e-5)


def test_gqa_untileable_falls_back_to_dense():
    """Non-tileable GQA shapes still work: the fallback expands K/V."""
    b, nh, kvh, s, d = 1, 4, 2, 12, 16  # s=12 has no /8 divisor
    key = jax.random.PRNGKey(4)
    q = jax.random.normal(jax.random.fold_in(key, 0), (b, nh, s, d))
    k = jax.random.normal(jax.random.fold_in(key, 1), (b, kvh, s, d))
    v = jax.random.normal(jax.random.fold_in(key, 2), (b, kvh, s, d))
    want = dense_causal_attention(
        q, jnp.repeat(k, 2, axis=1), jnp.repeat(v, 2, axis=1))
    got = flash_attention(q, k, v, interpret=True)
    np.testing.assert_allclose(got, want, atol=2e-5)


def test_llama_gqa_flash_forward_parity():
    """LLaMA block with GQA + flash == the same block with GQA + dense
    (the repeat path) — end to end through llama_forward."""
    from metis_tpu.models.llama import LlamaConfig, init_llama_params, llama_forward

    kw = dict(vocab_size=128, seq_len=32, hidden=64, num_heads=4,
              num_blocks=2, num_kv_heads=2, dtype=jnp.float32)
    cfg_d = LlamaConfig(attn="dense", **kw)
    cfg_f = LlamaConfig(attn="flash", **kw)
    params = init_llama_params(jax.random.PRNGKey(0), cfg_d)
    toks = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, 128)
    out_d = llama_forward(params, toks, cfg_d)
    out_f = llama_forward(params, toks, cfg_f)
    np.testing.assert_allclose(out_f, out_d, atol=2e-4, rtol=2e-4)


def test_gqa_stats_match_expanded_reference():
    """flash_attention_stats with grouped K/V (the ring-attention building
    block) matches the expanded-dense softmax state — review r5 finding:
    it previously admitted GQA shapes but indexed K/V out of bounds."""
    b, nh, kvh, s, d = 1, 4, 2, 32, 16
    key = jax.random.PRNGKey(5)
    q = jax.random.normal(jax.random.fold_in(key, 0), (b, nh, s, d))
    k = jax.random.normal(jax.random.fold_in(key, 1), (b, kvh, s, d))
    v = jax.random.normal(jax.random.fold_in(key, 2), (b, kvh, s, d))
    state = flash_attention_stats(q, k, v, causal=True, block_q=16,
                                  block_kv=16, interpret=True)
    got = finalize_stats(state).astype(jnp.float32)
    want = dense_causal_attention(
        q, jnp.repeat(k, 2, axis=1), jnp.repeat(v, 2, axis=1))
    np.testing.assert_allclose(got, want, atol=2e-5)
