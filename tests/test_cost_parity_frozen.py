"""Cost-model parity against a FROZEN reference run — no upstream needed.

The live differential oracle (tests/test_cost_parity.py) skips when
``/root/reference`` is absent; this file replays one frozen run of it
(``tests/fixtures/parity_reference_costs.json``, captured by
``tools/freeze_parity_fixture.py``) so a standalone checkout keeps its
cost-parity regression net — the role the reference's committed ranked-output
logs play (``results/hetero_cost_model:48-60``), but machine-checked per plan.

The parity workload is deterministic (seedless roofline synthesizer +
``metis_tpu.testing.write_parity_fixture``), so regenerated profiles pair
exactly with the frozen costs.  If the workload definition changes, re-run
the freezer — the assertions here will fail loudly, not silently drift.
"""
import json
import pathlib

import pytest

from metis_tpu.cluster import ClusterSpec
from metis_tpu.core.types import InterStagePlan, Strategy, UniformPlan
from metis_tpu.cost import (
    EstimatorOptions,
    HeteroCostEstimator,
    TransformerVolume,
    UniformCostEstimator,
)
from metis_tpu.profiles import ProfileStore, tiny_test_model

FIXTURE = pathlib.Path(__file__).parent / "fixtures" / "parity_reference_costs.json"


@pytest.fixture(scope="module")
def frozen():
    return json.loads(FIXTURE.read_text())


@pytest.fixture(scope="module")
def ours(parity_fixture_dir, frozen):
    cluster = ClusterSpec.from_files(
        parity_fixture_dir / "hostfile", parity_fixture_dir / "clusterfile.json")
    profiles = ProfileStore.from_dir(parity_fixture_dir / "profiles")
    volume = TransformerVolume(
        tiny_test_model(), profiles.model.params_per_layer_bytes)
    options = EstimatorOptions(
        strict_compat=True, max_profiled_bs=frozen["workload"]["max_bs"])
    return {
        "hetero": HeteroCostEstimator(cluster, profiles, volume, options),
        "uniform": UniformCostEstimator(cluster, profiles, volume, options),
    }


def test_fixture_is_nontrivial(frozen):
    assert len(frozen["hetero"]) > 100
    assert len(frozen["uniform"]) > 20


def test_hetero_parity_vs_frozen(frozen, ours):
    gbs = frozen["workload"]["gbs"]
    mismatches = []
    for rec in frozen["hetero"]:
        plan = InterStagePlan(
            node_sequence=tuple(rec["node_sequence"]),
            device_groups=tuple(rec["device_groups"]),
            batches=rec["batches"], gbs=gbs)
        cost = ours["hetero"].get_cost(
            plan,
            tuple(Strategy(dp=s[0], tp=s[1]) for s in rec["strategies"]),
            tuple(rec["partition"]))
        if cost.total_ms != pytest.approx(rec["cost_ms"], rel=1e-9):
            mismatches.append((rec, cost.total_ms))
    assert not mismatches, (
        f"{len(mismatches)}/{len(frozen['hetero'])} mismatches; "
        f"first: {mismatches[0]}")


def test_uniform_parity_vs_frozen(frozen, ours):
    dtype = frozen["workload"]["device_type"]
    for rec in frozen["uniform"]:
        plan = UniformPlan(dp=rec["dp"], pp=rec["pp"], tp=rec["tp"],
                           mbs=rec["mbs"], gbs=rec["gbs"])
        cost = ours["uniform"].get_cost(plan, dtype)
        assert cost.total_ms == pytest.approx(rec["cost_ms"], rel=1e-9), rec
        assert cost.oom == rec["oom"], rec
