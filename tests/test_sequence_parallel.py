"""Megatron sequence parallelism: memory model fit, planner families, and
the sharded execution path."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from metis_tpu.cluster import ClusterSpec
from metis_tpu.core.config import SearchConfig
from metis_tpu.cost.context_parallel import ActivationSplitModel
from metis_tpu.cost.sequence_parallel import SequenceParallelModel
from metis_tpu.planner import plan_hetero
from metis_tpu.profiles import synthesize_profiles, tiny_test_model


@pytest.fixture(scope="module")
def store():
    return synthesize_profiles(tiny_test_model(), ["A100"], tps=[1, 2, 4],
                               bss=[1, 2, 4, 8, 16])


class TestSpMemoryModel:
    def test_replicated_share_identified(self, store):
        sp = SequenceParallelModel(ActivationSplitModel(store))
        fitted = sp.replicated_share("A100")
        assert fitted is not None
        rep, shd = fitted
        # the synthetic act model has a replicated component (act/tp plus
        # constant parts), so both shares are non-negative and finite
        assert all(r >= 0 for r in rep) and all(b >= 0 for b in shd)

    def test_act_scale_bounds_and_monotonicity(self, store):
        sp = SequenceParallelModel(ActivationSplitModel(store))
        s2 = sp.act_scale("A100", 2)
        s4 = sp.act_scale("A100", 4)
        assert s2 is not None and s4 is not None
        for a2, a4 in zip(s2, s4):
            assert 0.0 < a2 <= 1.0 and 0.0 < a4 <= 1.0
            assert a4 <= a2 + 1e-12  # more tp => at least as much relief

    def test_no_relief_without_tp_sweep(self):
        store1 = synthesize_profiles(tiny_test_model(), ["A100"], tps=[1],
                                     bss=[1, 2, 4])
        sp = SequenceParallelModel(ActivationSplitModel(store1))
        assert sp.act_scale("A100", 2) is None

    def test_no_relief_at_tp1(self, store):
        sp = SequenceParallelModel(ActivationSplitModel(store))
        assert sp.act_scale("A100", 1) is None


class TestPlannerSpFamilies:
    @pytest.fixture(scope="class")
    def result(self, ):
        model = tiny_test_model()
        store = synthesize_profiles(model, ["A100"], tps=[1, 2, 4],
                                    bss=[1, 2, 4, 8, 16])
        cluster = ClusterSpec.homogeneous("A100", 2, 4)
        return plan_hetero(cluster, store, model,
                           SearchConfig(gbs=64, enable_sp=True))

    def test_sp_plans_only_at_tp_above_one(self, result):
        sp_plans = [r for r in result.plans
                    if any(s.sp for s in r.intra.strategies)]
        assert sp_plans, "no sp plans searched"
        for r in sp_plans:
            assert any(s.tp > 1 for s in r.intra.strategies), (
                "degenerate sp plan (all tp=1) leaked into the ranking")

    def test_sp_memory_headroom_not_worse(self, result):
        """An sp plan's memory state is >= its non-sp twin's (same shapes)."""
        by_shape = {}
        for r in result.plans:
            key = (r.inter, tuple((s.dp, s.tp, s.cp, s.ep, s.zero)
                                  for s in r.intra.strategies),
                   r.intra.layer_partition)
            by_shape.setdefault(key, {})[
                any(s.sp for s in r.intra.strategies)] = r
        pairs = [v for v in by_shape.values() if True in v and False in v]
        assert pairs, "no sp/non-sp twin plans to compare"
        for v in pairs:
            sp_state = v[True].intra.memory_state
            base_state = v[False].intra.memory_state
            if sp_state and base_state:
                assert min(sp_state) >= min(base_state) - 1e-9

    def test_sp_pp_comm_discount(self, result):
        """Multi-stage sp twins pay <= the non-sp pp boundary cost."""
        for r in result.plans:
            if (r.inter.num_stages > 1
                    and all(s.sp and s.tp > 1 for s in r.intra.strategies)):
                twin = next(
                    (o for o in result.plans
                     if o.inter == r.inter
                     and not any(s.sp for s in o.intra.strategies)
                     and tuple(s.as_tuple() for s in o.intra.strategies)
                     == tuple(s.as_tuple() for s in r.intra.strategies)
                     and o.intra.layer_partition == r.intra.layer_partition),
                    None)
                if twin is not None and twin.cost.pp_comm_ms > 0:
                    assert r.cost.pp_comm_ms < twin.cost.pp_comm_ms
                    return
        pytest.skip("no comparable multi-stage sp twin found")


class TestSpExecution:
    def test_megatron_sp_step_matches_unsharded(self):
        import numpy as onp
        from jax.sharding import Mesh
        from metis_tpu.execution import (
            DP, TP, build_train_state, make_train_step)
        from metis_tpu.models import GPTConfig, init_params
        from metis_tpu.models.gpt import next_token_loss

        cfg = GPTConfig(vocab_size=128, seq_len=16, hidden=32, num_heads=4,
                        num_blocks=2, dtype=jnp.float32)
        mesh = Mesh(onp.array(jax.devices()[:8]).reshape(2, 4), (DP, TP))
        state, _ = build_train_state(jax.random.PRNGKey(0), cfg, mesh)
        step = make_train_step(cfg, mesh, megatron_sp=True)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, 128)
        _, loss = step(state, tokens, tokens)

        params = init_params(jax.random.PRNGKey(0), cfg)
        want = next_token_loss(params, tokens, tokens, cfg)
        np.testing.assert_allclose(float(loss), float(want), rtol=1e-5)
