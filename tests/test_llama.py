"""LLaMA model family: RMSNorm/RoPE/GQA/SwiGLU correctness, sharded-execution
parity (SURVEY.md §5 race-detection equivalent), and planner integration."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from metis_tpu.core.config import ModelSpec
from metis_tpu.execution import (
    DP, TP,
    build_train_state,
    make_train_step,
    param_specs_for,
    shard_params,
)
from metis_tpu.models import LlamaConfig, config_for_model_spec
from metis_tpu.models.llama import (
    init_llama_params,
    llama_forward,
    llama_next_token_loss,
    rms_norm,
    rope,
)

CFG = LlamaConfig(vocab_size=256, seq_len=32, hidden=64, num_heads=4,
                  num_blocks=4, ffn_multiplier=2, dtype=jnp.float32)


def _mesh(shape, axes):
    devs = np.array(jax.devices()[: int(np.prod(shape))]).reshape(shape)
    return Mesh(devs, axes)


@pytest.fixture(scope="module")
def data():
    key = jax.random.PRNGKey(0)
    tokens = jax.random.randint(jax.random.fold_in(key, 1), (8, CFG.seq_len),
                                0, CFG.vocab_size)
    params = init_llama_params(jax.random.PRNGKey(42), CFG)
    return params, tokens


class TestOps:
    def test_rms_norm_unit_scale(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 8, 16)) * 5.0
        y = rms_norm(x, jnp.ones((16,)))
        rms = np.sqrt(np.mean(np.asarray(y) ** 2, -1))
        np.testing.assert_allclose(rms, 1.0, rtol=1e-3)

    def test_rope_preserves_norm(self):
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 4, 8, 16))
        y = rope(x, 10000.0)
        np.testing.assert_allclose(
            np.linalg.norm(np.asarray(y), axis=-1),
            np.linalg.norm(np.asarray(x), axis=-1), rtol=1e-5)

    def test_rope_relative_position_invariance(self):
        """q_i . k_j after RoPE depends only on (i - j): shifting both
        positions by a common offset leaves the score unchanged."""
        q = jax.random.normal(jax.random.PRNGKey(2), (1, 1, 8, 16))
        k = jax.random.normal(jax.random.PRNGKey(3), (1, 1, 8, 16))
        s0 = np.einsum("bhqd,bhkd->bhqk", np.asarray(rope(q, 1e4, 0)),
                       np.asarray(rope(k, 1e4, 0)))
        s7 = np.einsum("bhqd,bhkd->bhqk", np.asarray(rope(q, 1e4, 7)),
                       np.asarray(rope(k, 1e4, 7)))
        np.testing.assert_allclose(s0, s7, rtol=1e-4, atol=1e-4)

    def test_gqa_head_count_validation(self):
        with pytest.raises(ValueError):
            LlamaConfig(vocab_size=8, seq_len=4, hidden=12, num_heads=4,
                        num_blocks=1, num_kv_heads=3)


class TestModel:
    def test_forward_shapes_and_finite(self, data):
        params, tokens = data
        logits = llama_forward(params, tokens, CFG)
        assert logits.shape == (8, CFG.seq_len, CFG.vocab_size)
        assert np.isfinite(np.asarray(logits)).all()

    def test_gqa_forward(self):
        cfg = LlamaConfig(vocab_size=128, seq_len=16, hidden=64, num_heads=4,
                          num_blocks=2, num_kv_heads=2, ffn_multiplier=2,
                          dtype=jnp.float32)
        params = init_llama_params(jax.random.PRNGKey(0), cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 128)
        logits = llama_forward(params, tokens, cfg)
        assert np.isfinite(np.asarray(logits)).all()
        # GQA halves the KV projection parameter count
        assert params["blocks"]["wkv"].shape == (2, 2, 64, 2 * 16)

    def test_loss_decreases_under_sgd(self, data):
        params, tokens = data

        @jax.jit
        def step(p):
            loss, g = jax.value_and_grad(llama_next_token_loss)(
                p, tokens, tokens, CFG)
            return loss, jax.tree.map(lambda w, gw: w - 0.1 * gw, p, g)

        losses = []
        for _ in range(8):
            loss, params = step(params)
            losses.append(float(loss))
        assert losses[-1] < losses[0]


class TestShardedExecution:
    def test_sharded_forward_matches_single_device(self, data):
        params, tokens = data
        expected = llama_forward(params, tokens, CFG)
        mesh = _mesh((2, 2), (DP, TP))
        specs = param_specs_for(CFG, tp_size=2)
        sharded = shard_params(params, mesh, specs)
        with mesh:
            got = jax.jit(lambda p, t: llama_forward(p, t, CFG))(sharded, tokens)
        np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                                   rtol=1e-4, atol=1e-4)

    def test_train_step_reduces_loss(self, data):
        _, tokens = data
        mesh = _mesh((2, 2), (DP, TP))
        state, _ = build_train_state(jax.random.PRNGKey(0), CFG, mesh)
        step = make_train_step(CFG, mesh)
        state, loss0 = step(state, tokens, tokens)
        for _ in range(3):
            state, loss = step(state, tokens, tokens)
        assert float(loss) < float(loss0)

    def test_gqa_replicated_kv_under_tp(self):
        """KV heads not divisible by tp: the KV projection replicates and the
        forward still matches single-device."""
        cfg = LlamaConfig(vocab_size=128, seq_len=16, hidden=64, num_heads=4,
                          num_blocks=2, num_kv_heads=1, ffn_multiplier=2,
                          dtype=jnp.float32)
        params = init_llama_params(jax.random.PRNGKey(0), cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, 128)
        expected = llama_forward(params, tokens, cfg)
        mesh = _mesh((2, 2), (DP, TP))
        specs = param_specs_for(cfg, tp_size=2)
        from jax.sharding import PartitionSpec as P

        assert specs["blocks"]["wkv"] == P(None, None, None, None)
        sharded = shard_params(params, mesh, specs)
        with mesh:
            got = jax.jit(lambda p, t: llama_forward(p, t, cfg))(sharded, tokens)
        np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                                   rtol=1e-4, atol=1e-4)

    def test_ring_attention_cp_step(self, data):
        """Context parallelism: RoPE positions are global under GSPMD, so the
        sp-sharded step must agree with the unsharded loss."""
        params, tokens = data
        mesh = _mesh((2, 4), (DP, "sp"))
        state, _ = build_train_state(jax.random.PRNGKey(0), CFG, mesh,
                                     tp_axis=None)
        step = make_train_step(CFG, mesh, seq_axis="sp")
        state, loss = step(state, tokens, tokens)
        assert np.isfinite(float(loss))


class TestHeteroPath:
    def test_llama_hetero_stage_parity(self, data):
        """The per-stage multi-mesh executor runs the llama family: 2-stage
        non-uniform plan, loss matches the single-device model."""
        from metis_tpu.execution.hetero import (
            make_hetero_train_step,
            stage_specs_from_plan,
        )
        from metis_tpu.core.types import Strategy

        params, tokens = data
        stages = stage_specs_from_plan(
            [0, 2, CFG.num_profile_layers],
            [Strategy(dp=2, tp=2), Strategy(dp=2, tp=1)], CFG)
        init_fn, step_fn = make_hetero_train_step(
            CFG, stages, devices=jax.devices()[:6])
        state = init_fn(jax.random.PRNGKey(42))
        tok_mbs = tokens.reshape(2, 4, -1)
        expected = float(llama_next_token_loss(params, tokens, tokens, CFG))
        state, loss = step_fn(state, tok_mbs, tok_mbs)
        assert loss == pytest.approx(expected, rel=1e-4)


class TestPlannerIntegration:
    def test_model_spec_dispatch(self):
        spec = ModelSpec(name="llama-test", num_layers=6, hidden_size=64,
                         sequence_length=32, vocab_size=256, num_heads=4,
                         family="llama", num_kv_heads=2)
        cfg = config_for_model_spec(spec, dtype=jnp.float32)
        assert isinstance(cfg, LlamaConfig)
        assert cfg.kv_heads == 2
        assert cfg.num_blocks == 4

    def test_profiler_measures_llama(self):
        from metis_tpu.profiles.profiler import ProfilerConfig, profile_model

        spec = ModelSpec(name="llama-prof", num_layers=4, hidden_size=32,
                         sequence_length=16, vocab_size=64, num_heads=2,
                         family="llama")
        store = profile_model(spec, tps=(1,), bss=(1,),
                              config=ProfilerConfig(warmup=1, iters=2),
                              devices=jax.devices()[:1])
        prof = store.get(store.device_types[0], 1, 1)
        assert prof.num_layers == 4
        assert all(t >= 0 for t in prof.layer_times_ms)
