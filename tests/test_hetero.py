"""Non-uniform hetero plan execution: multi-mesh per-stage GSPMD programs.

The planner's flagship output — non-uniform layer partitions with per-stage
(dp, tp) strategies (reference plan tuple ``cost_het_cluster.py:43-45``) and
uneven hetero-DP microbatches (reference ``load_balancer.py:155-179``) — must
*train identically* to the single-device model (SURVEY.md §5 race detection:
numeric parity is the correctness oracle).
"""
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from metis_tpu.execution import PlanArtifact
from metis_tpu.execution.hetero import (
    StageSpec,
    make_hetero_train_step,
    make_hetero_train_step_from_artifact,
    plan_replica_rows,
    stage_specs_from_plan,
)
from metis_tpu.models.gpt import GPTConfig, init_params, next_token_loss

CFG = GPTConfig(vocab_size=256, seq_len=16, hidden=64, num_heads=4,
                num_blocks=4, ffn_multiplier=2, dtype=jnp.float32)


def _data(gbs: int, seed: int = 1):
    key = jax.random.PRNGKey(seed)
    toks = jax.random.randint(key, (gbs, CFG.seq_len), 0, CFG.vocab_size)
    return toks


def _reference_losses(tokens, steps: int, cfg=CFG, seed: int = 0):
    """Single-device full-batch adamw training — the parity oracle."""
    params = init_params(jax.random.PRNGKey(seed), cfg)
    opt = optax.adamw(1e-4, weight_decay=0.01)
    opt_state = opt.init(params)

    @jax.jit
    def step(params, opt_state, t):
        loss, grads = jax.value_and_grad(next_token_loss)(params, t, t, cfg)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    losses = []
    for _ in range(steps):
        params, opt_state, loss = step(params, opt_state, tokens)
        losses.append(float(loss))
    return losses


def _hetero_losses(stages, tokens, microbatches: int, cfg=CFG, seed: int = 0,
                   steps: int = 2):
    init_fn, step = make_hetero_train_step(cfg, stages)
    state = init_fn(jax.random.PRNGKey(seed))
    gbs = tokens.shape[0]
    mbs = tokens.reshape(microbatches, gbs // microbatches, cfg.seq_len)
    losses = []
    for _ in range(steps):
        state, loss = step(state, mbs, mbs)
        losses.append(loss)
    return losses


class TestStageSpecConversion:
    def test_profile_layer_to_block_mapping(self):
        # 4 blocks -> 6 profile layers; [0, 2, 6] = (embed + block0 | blocks
        # 1..3 + head), the reference's partition convention
        specs = stage_specs_from_plan(
            [0, 2, 6], [{"dp": 2, "tp": 2}, {"dp": 4, "tp": 1}], CFG)
        assert specs[0] == StageSpec(blocks=(0, 1), has_embed=True,
                                     has_head=False, dp=2, tp=2)
        assert specs[1] == StageSpec(blocks=(1, 4), has_embed=False,
                                     has_head=True, dp=4, tp=1)

    def test_embed_only_stage(self):
        specs = stage_specs_from_plan(
            [0, 1, 6], [{"dp": 1, "tp": 1}, {"dp": 1, "tp": 1}], CFG)
        assert specs[0].blocks == (0, 0)  # no transformer blocks
        assert specs[0].has_embed and not specs[0].has_head

    def test_bad_span_raises(self):
        with pytest.raises(ValueError, match="span"):
            stage_specs_from_plan([0, 5], [{"dp": 1, "tp": 1}], CFG)

    def test_strategy_count_mismatch_raises(self):
        with pytest.raises(ValueError, match="boundaries"):
            stage_specs_from_plan([0, 6], [{"dp": 1, "tp": 1}] * 2, CFG)

    def test_replica_rows_arity_checked(self):
        with pytest.raises(ValueError, match="replica rows"):
            stage_specs_from_plan(
                [0, 6], [{"dp": 2, "tp": 1}], CFG,
                stage_replica_rows=[(1, 2, 3)])

    def test_cp_moe_combination_rejected(self):
        from metis_tpu.models.moe import MoEConfig

        moe = MoEConfig(vocab_size=128, seq_len=16, hidden=32, num_heads=2,
                        num_blocks=4, ffn_multiplier=2, num_experts=2,
                        top_k=1, dtype=jnp.float32)
        with pytest.raises(NotImplementedError, match="cp"):
            stage_specs_from_plan([0, 6], [{"dp": 2, "tp": 1, "cp": 2}], moe)


class TestNonUniformParity:
    def test_two_stage_nonuniform_matches_single_device(self):
        tokens = _data(8)
        stages = [
            StageSpec(blocks=(0, 1), has_embed=True, has_head=False, dp=2, tp=2),
            StageSpec(blocks=(1, 4), has_embed=False, has_head=True, dp=4, tp=1),
        ]
        got = _hetero_losses(stages, tokens, microbatches=2)
        want = _reference_losses(tokens, steps=2)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    def test_three_stage_nonuniform_matches_single_device(self):
        tokens = _data(8)
        # partitions [0,2,3,6]: 1 block | 1 block | 2 blocks + head
        stages = stage_specs_from_plan(
            [0, 2, 3, 6],
            [{"dp": 2, "tp": 1}, {"dp": 1, "tp": 2}, {"dp": 2, "tp": 2}],
            CFG)
        got = _hetero_losses(stages, tokens, microbatches=2)
        want = _reference_losses(tokens, steps=2)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    def test_single_stage_plan(self):
        tokens = _data(8)
        stages = stage_specs_from_plan([0, 6], [{"dp": 4, "tp": 2}], CFG)
        got = _hetero_losses(stages, tokens, microbatches=2)
        want = _reference_losses(tokens, steps=2)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


class TestUnevenHeteroDP:
    def test_uneven_replica_rows_match_single_device(self):
        """The data balancer's uneven per-replica split (Metis's signature
        feature) executes via pad/gather and changes nothing numerically."""
        tokens = _data(16)
        stages = [
            StageSpec(blocks=(0, 2), has_embed=True, has_head=False,
                      dp=4, tp=1, replica_rows=(3, 2, 2, 1)),
            StageSpec(blocks=(2, 4), has_embed=False, has_head=True,
                      dp=2, tp=2, replica_rows=(5, 3)),
        ]
        got = _hetero_losses(stages, tokens, microbatches=2)
        want = _reference_losses(tokens, steps=2)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    def test_replica_rows_must_sum_to_microbatch(self):
        tokens = _data(8)
        stages = [StageSpec(blocks=(0, 4), has_embed=True, has_head=True,
                            dp=2, tp=1, replica_rows=(3, 2))]
        init_fn, step = make_hetero_train_step(CFG, stages)
        state = init_fn(jax.random.PRNGKey(0))
        mbs = tokens.reshape(2, 4, CFG.seq_len)
        with pytest.raises(ValueError, match="sum"):
            step(state, mbs, mbs)

    def test_zero3_stage_sharding_preserves_parity(self):
        tokens = _data(8)
        stages = [
            StageSpec(blocks=(0, 2), has_embed=True, has_head=False,
                      dp=4, tp=1, zero=3),
            StageSpec(blocks=(2, 4), has_embed=False, has_head=True,
                      dp=2, tp=2),
        ]
        got = _hetero_losses(stages, tokens, microbatches=2)
        want = _reference_losses(tokens, steps=2)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


class TestArtifactBridge:
    def _nonuniform_artifact(self):
        return PlanArtifact(
            mesh_axes=(), mesh_shape=(),
            layer_partition=(0, 2, 6),
            strategies=({"dp": 2, "tp": 2}, {"dp": 4, "tp": 1}),
            gbs=8, microbatches=2,
            node_sequence=("A100", "T4"), device_groups=(4, 4))

    def test_artifact_executes(self):
        art = self._nonuniform_artifact()
        init_fn, step = make_hetero_train_step_from_artifact(CFG, art)
        state = init_fn(jax.random.PRNGKey(0))
        tokens = _data(art.gbs)
        mbs = tokens.reshape(art.microbatches, -1, CFG.seq_len)
        state, first = step(state, mbs, mbs)
        state, second = step(state, mbs, mbs)
        assert np.isfinite(first) and second < first

    def test_device_group_mismatch_raises(self):
        art = PlanArtifact(
            mesh_axes=(), mesh_shape=(), layer_partition=(0, 2, 6),
            strategies=({"dp": 2, "tp": 2}, {"dp": 4, "tp": 1}),
            gbs=8, microbatches=2, device_groups=(2, 6))
        with pytest.raises(ValueError, match="disagree"):
            make_hetero_train_step_from_artifact(CFG, art)

    def test_planner_rows_glue(self, reference_profiles):
        """plan_replica_rows reproduces the DataBalancer split for a mixed
        stage and None for homogeneous ones."""
        from metis_tpu.balance.data import DataBalancer
        from metis_tpu.cluster import ClusterSpec
        from metis_tpu.core.types import InterStagePlan, Strategy

        # synthetic 2-type cluster where both types have A100 profiles is not
        # needed: a homogeneous stage exercises the None path, a mixed-rank
        # plan on one type cannot arise — so fabricate a 2-type placement
        # whose types both resolve to the A100 profile store entry.
        cluster = ClusterSpec.homogeneous("A100", 2, 4)
        inter = InterStagePlan(node_sequence=("A100",), device_groups=(4, 4),
                               batches=2, gbs=32)
        rows = plan_replica_rows(
            inter, (Strategy(dp=4, tp=1), Strategy(dp=2, tp=2)),
            cluster, reference_profiles)
        assert rows == [None, None]


class TestMoEStages:
    """MoE stages in the per-stage executor: (x, aux) boundaries, ep-sharded
    expert weights, loss parity vs the single-program moe loss."""

    def _cfg(self, **kw):
        from metis_tpu.models.moe import MoEConfig

        base = dict(vocab_size=128, seq_len=16, hidden=32, num_heads=2,
                    num_blocks=4, ffn_multiplier=2, num_experts=2, top_k=1,
                    capacity_factor=8.0, dtype=jnp.float32)
        base.update(kw)
        return MoEConfig(**base)

    def test_two_stage_moe_matches_single_program(self):
        from metis_tpu.models.moe import init_moe_params, moe_next_token_loss

        cfg = self._cfg()
        toks = jax.random.randint(
            jax.random.PRNGKey(1), (4, cfg.seq_len), 0, cfg.vocab_size)
        expected = float(moe_next_token_loss(
            init_moe_params(jax.random.PRNGKey(0), cfg), toks, toks, cfg))

        stages = stage_specs_from_plan(
            [0, 3, cfg.num_profile_layers],
            [{"dp": 2, "tp": 1}, {"dp": 2, "tp": 2}], cfg)
        init_fn, step_fn = make_hetero_train_step(
            cfg, stages, devices=jax.devices()[:6])
        state = init_fn(jax.random.PRNGKey(0))
        mbs = toks.reshape(2, 2, -1)
        _, loss = step_fn(state, mbs, mbs)
        assert loss == pytest.approx(expected, rel=1e-4)

    def test_ep_stage_trains(self):
        cfg = self._cfg()
        stages = stage_specs_from_plan(
            [0, 3, cfg.num_profile_layers],
            [{"dp": 4, "tp": 1, "ep": 2}, {"dp": 2, "tp": 2}], cfg)
        assert stages[0].ep == 2
        init_fn, step_fn = make_hetero_train_step(
            cfg, stages, devices=jax.devices()[:8])
        state = init_fn(jax.random.PRNGKey(0))
        toks = jax.random.randint(
            jax.random.PRNGKey(1), (4, cfg.seq_len), 0, cfg.vocab_size)
        mbs = toks.reshape(1, 4, -1)
        losses = []
        for _ in range(3):
            state, loss = step_fn(state, mbs, mbs)
            losses.append(loss)
        assert losses[-1] < losses[0]

    def test_ep_must_divide(self):
        cfg = self._cfg()
        with pytest.raises(ValueError, match="divide"):
            stage_specs_from_plan(
                [0, cfg.num_profile_layers], [{"dp": 3, "tp": 1, "ep": 2}],
                cfg)

    def test_moe_uneven_padding_matches_single_program(self):
        """Uneven hetero-DP rows on an MoE stage: the router's pad mask
        (models/moe.moe_ffn valid_mask) keeps duplicate pad rows out of
        expert-capacity competition, so the padded run reproduces the
        single-program loss exactly — ample capacity here, since capacity
        DROPS are the only grouping-order-dependent behavior."""
        from metis_tpu.models.moe import init_moe_params, moe_next_token_loss

        cfg = self._cfg()
        toks = jax.random.randint(
            jax.random.PRNGKey(1), (4, cfg.seq_len), 0, cfg.vocab_size)
        expected = float(moe_next_token_loss(
            init_moe_params(jax.random.PRNGKey(0), cfg), toks, toks, cfg))

        stages = stage_specs_from_plan(
            [0, cfg.num_profile_layers], [{"dp": 2, "tp": 1}], cfg,
            stage_replica_rows=[(3, 1)])
        init_fn, step_fn = make_hetero_train_step(
            cfg, stages, devices=jax.devices()[:2])
        state = init_fn(jax.random.PRNGKey(0))
        mbs = toks.reshape(1, 4, -1)
        _, loss = step_fn(state, mbs, mbs)
        assert loss == pytest.approx(expected, rel=1e-4)

    def test_moe_uneven_two_stage_matches_single_program(self):
        from metis_tpu.models.moe import init_moe_params, moe_next_token_loss

        cfg = self._cfg()
        toks = jax.random.randint(
            jax.random.PRNGKey(1), (4, cfg.seq_len), 0, cfg.vocab_size)
        expected = float(moe_next_token_loss(
            init_moe_params(jax.random.PRNGKey(0), cfg), toks, toks, cfg))

        stages = stage_specs_from_plan(
            [0, 3, cfg.num_profile_layers],
            [{"dp": 2, "tp": 1}, {"dp": 2, "tp": 2}], cfg,
            stage_replica_rows=[(3, 1), None])
        init_fn, step_fn = make_hetero_train_step(
            cfg, stages, devices=jax.devices()[:6])
        state = init_fn(jax.random.PRNGKey(0))
        mbs = toks.reshape(1, 4, -1)
        _, loss = step_fn(state, mbs, mbs)
        assert loss == pytest.approx(expected, rel=1e-4)


class TestReplicaGroups:
    """Per-type sub-mesh groups (StageSpec.replica_groups — VERDICT r3
    next-step 7): a mixed-type stage splits into one GSPMD program per
    type group, each computing ONLY its real rows; gradients sum across
    groups on the primary mesh.  Numerically identical to the
    single-program run."""

    def test_grouped_dense_stage_matches_single_device(self):
        tokens = _data(16)
        stages = [
            StageSpec(blocks=(0, 2), has_embed=True, has_head=False,
                      dp=4, tp=1, replica_rows=(3, 3, 1, 1),
                      replica_groups=(2, 2)),
            StageSpec(blocks=(2, 4), has_embed=False, has_head=True,
                      dp=2, tp=2),
        ]
        got = _hetero_losses(stages, tokens, microbatches=2)
        want = _reference_losses(tokens, steps=2)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    def test_grouped_last_stage_matches_single_device(self):
        """Groups on the LOSS stage: per-group losses/cotangents are scaled
        by row share so the summed loss is the global batch mean."""
        tokens = _data(16)
        stages = [
            StageSpec(blocks=(0, 2), has_embed=True, has_head=False,
                      dp=2, tp=2),
            StageSpec(blocks=(2, 4), has_embed=False, has_head=True,
                      dp=4, tp=1, replica_rows=(3, 3, 1, 1),
                      replica_groups=(2, 2)),
        ]
        got = _hetero_losses(stages, tokens, microbatches=2)
        want = _reference_losses(tokens, steps=2)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    def test_grouped_uneven_within_group_falls_back_to_pad(self):
        """Rows uneven WITHIN a group compose with the in-group pad/mask
        mechanism (sub-spec keeps replica_rows)."""
        tokens = _data(16)
        stages = [
            StageSpec(blocks=(0, 4), has_embed=True, has_head=True,
                      dp=4, tp=1, replica_rows=(4, 2, 1, 1),
                      replica_groups=(2, 2)),
        ]
        got = _hetero_losses(stages, tokens, microbatches=2)
        want = _reference_losses(tokens, steps=2)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    def test_grouped_moe_stage_matches_single_program(self):
        """Grouped MoE stage: each group's expert capacity derives from its
        OWN token count (no pad rows at all) — loss parity below capacity
        pressure with the single-program moe loss."""
        from metis_tpu.models.moe import (
            MoEConfig,
            init_moe_params,
            moe_next_token_loss,
        )

        cfg = MoEConfig(vocab_size=128, seq_len=16, hidden=32, num_heads=2,
                        num_blocks=4, ffn_multiplier=2, num_experts=2,
                        top_k=1, capacity_factor=8.0, dtype=jnp.float32)
        toks = jax.random.randint(
            jax.random.PRNGKey(1), (4, cfg.seq_len), 0, cfg.vocab_size)
        expected = float(moe_next_token_loss(
            init_moe_params(jax.random.PRNGKey(0), cfg), toks, toks, cfg))

        stages = stage_specs_from_plan(
            [0, 3, cfg.num_profile_layers],
            [{"dp": 2, "tp": 1}, {"dp": 2, "tp": 2}], cfg,
            stage_replica_rows=[(3, 1), None],
            stage_replica_groups=[(1, 1), None])
        assert stages[0].replica_groups == (1, 1)
        init_fn, step_fn = make_hetero_train_step(
            cfg, stages, devices=jax.devices()[:6])
        state = init_fn(jax.random.PRNGKey(0))
        mbs = toks.reshape(1, 4, -1)
        _, loss = step_fn(state, mbs, mbs)
        assert loss == pytest.approx(expected, rel=1e-4)

    def test_grouped_stage_trains(self):
        tokens = _data(16)
        stages = [
            StageSpec(blocks=(0, 4), has_embed=True, has_head=True,
                      dp=4, tp=1, replica_rows=(3, 3, 1, 1),
                      replica_groups=(2, 2)),
        ]
        init_fn, step = make_hetero_train_step(CFG, stages)
        state = init_fn(jax.random.PRNGKey(0))
        mbs = tokens.reshape(2, 8, CFG.seq_len)
        losses = []
        for _ in range(4):
            state, loss = step(state, mbs, mbs)
            losses.append(loss)
        assert losses[-1] < losses[0]

    def test_grouped_zero_row_group_is_skipped(self):
        """A type group the data balancer gives ZERO rows contributes no
        loss and no gradients — an empty-batch mean would be NaN and poison
        the step (found driving the train CLI on a small gbs)."""
        tokens = _data(8)
        stages = [
            StageSpec(blocks=(0, 4), has_embed=True, has_head=True,
                      dp=8, tp=1, replica_rows=(1, 1, 1, 1, 0, 0, 0, 0),
                      replica_groups=(4, 4)),
        ]
        got = _hetero_losses(stages, tokens, microbatches=2)
        want = _reference_losses(tokens, steps=2)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    def test_plan_replica_groups_detection(self):
        from metis_tpu.cluster.spec import ClusterSpec, DeviceSpec, NodeSpec
        from metis_tpu.core.types import InterStagePlan, Strategy
        from metis_tpu.execution.hetero import plan_replica_groups

        cluster = ClusterSpec(
            nodes=(NodeSpec("A", 4), NodeSpec("B", 4)),
            devices={"A": DeviceSpec("A", 80, 100, 25),
                     "B": DeviceSpec("B", 15, 50, 10)})
        inter = InterStagePlan(node_sequence=("A", "B"),
                               device_groups=(8,), batches=2, gbs=16)
        # one mixed stage of 8 devices: 4 A-replicas then 4 B-replicas
        groups = plan_replica_groups(inter, [Strategy(dp=8, tp=1)], cluster)
        assert groups == [(4, 4)]
        # homogeneous stages and zero/cp/ep stages stay single-program
        inter2 = InterStagePlan(node_sequence=("A", "B"),
                                device_groups=(4, 4), batches=2, gbs=16)
        assert plan_replica_groups(
            inter2, [Strategy(dp=4, tp=1), Strategy(dp=4, tp=1)],
            cluster) == [None, None]
        assert plan_replica_groups(
            inter, [Strategy(dp=8, tp=1, zero=1)], cluster) == [None]


class TestCpStages:
    """cp (ring attention) stages under pipelining: a stage's mesh carries a
    dedicated sp axis and its attention runs the K/V-rotating ring."""

    def test_cp_stage_matches_single_device(self):
        toks = _data(4)
        stages = stage_specs_from_plan(
            [0, 3, CFG.num_profile_layers],
            [{"dp": 2, "tp": 1, "cp": 2}, {"dp": 2, "tp": 1}], CFG)
        assert stages[0].cp == 2 and stages[0].devices == 4
        got = _hetero_losses(stages, toks, microbatches=2, steps=2)
        want = _reference_losses(toks, steps=2)
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)

    def test_cp_seq_divisibility_checked(self):
        with pytest.raises(ValueError, match="divide seq_len"):
            stage_specs_from_plan(
                [0, CFG.num_profile_layers], [{"dp": 1, "tp": 1, "cp": 3}],
                CFG)
