"""Plan provenance (obs/provenance.py): the append-only decision log,
attributed plan diffs, causal-chain reconstruction, the decision-schema
checker, ledger component-residual analytics, and the rotated-event-log
regression."""
import json
import sys
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "tools"))

import check_decisions_schema  # noqa: E402
import check_events_schema  # noqa: E402

from metis_tpu.cluster import ClusterSpec
from metis_tpu.core.config import SearchConfig
from metis_tpu.core.events import EventLog, read_events
from metis_tpu.obs.ledger import AccuracyLedger
from metis_tpu.obs.provenance import (
    DECISION_KINDS,
    DecisionLog,
    DecisionRecord,
    artifact_digest,
    causal_chain,
    chain_json,
    diff_plans,
    fingerprint_plan_dict,
    plan_axes,
    planner_decision_fields,
    record_planner_decision,
    render_chain,
)
from metis_tpu.planner import plan_hetero
from metis_tpu.profiles import synthesize_profiles, tiny_test_model


@pytest.fixture(scope="module")
def workload():
    model = tiny_test_model()
    store = synthesize_profiles(model, ["A100", "T4"], tps=[1, 2, 4],
                                bss=[1, 2, 4, 8, 16])
    cluster = ClusterSpec.of(("A100", 2, 4), ("T4", 1, 4))
    return model, store, cluster


@pytest.fixture(scope="module")
def search_result(workload):
    model, store, cluster = workload
    return plan_hetero(cluster, store, model, SearchConfig(gbs=64), top_k=4)


# ---------------------------------------------------------------------------
# DecisionLog: append-only, seq-numbered, restart-safe
# ---------------------------------------------------------------------------


class TestDecisionLog:
    def test_seq_assignment_and_queries(self):
        log = DecisionLog(None)
        a = log.record("cold_search", plan_fingerprint="fpA",
                       query_fingerprint="qA")
        b = log.record("cache_hit", plan_fingerprint="fpA", parent_seq=a.seq)
        c = log.record("drift_replan", plan_fingerprint="fpB",
                       parent_seq=a.seq, cause="drift_alarm")
        assert (a.seq, b.seq, c.seq) == (1, 2, 3)
        assert log.last_seq == 3
        assert len(log) == 3
        assert [r.seq for r in log.records(since=1)] == [2, 3]
        assert log.get(2) is b
        # find returns the LATEST match per criterion
        assert log.find(plan_fingerprint="fpA") is b
        assert log.find(kind="cold_search") is a
        assert log.find(plan_fingerprint="nope") is None

    def test_restart_resumes_sequence(self, tmp_path):
        path = tmp_path / "decisions.jsonl"
        with DecisionLog(path) as log:
            log.record("cold_search", plan_fingerprint="fp1")
            log.record("cache_hit", plan_fingerprint="fp1", parent_seq=1)
        # reopen: the prior records load and the seq continues — a daemon
        # restart must never reset the audit trail's numbering
        with DecisionLog(path) as log2:
            assert log2.last_seq == 2
            assert len(log2) == 2
            rec = log2.record("drift_replan", plan_fingerprint="fp2",
                              parent_seq=2)
            assert rec.seq == 3
        lines = [json.loads(ln) for ln
                 in path.read_text().splitlines() if ln.strip()]
        assert [r["seq"] for r in lines] == [1, 2, 3]
        n, problems = check_decisions_schema.validate_file(path)
        assert n == 3 and not problems

    def test_record_emits_decision_record_event(self, tmp_path):
        ev_path = tmp_path / "events.jsonl"
        with EventLog(ev_path) as events:
            log = DecisionLog(None, events=events)
            log.record("cold_search", plan_fingerprint="fpX",
                       trace_id="trace-1")
            log.record("fleet_repartition")
        evs = read_events(ev_path)
        assert [e["event"] for e in evs] == ["decision_record"] * 2
        assert evs[0]["seq"] == 1 and evs[0]["kind"] == "cold_search"
        assert evs[0]["trace_id"] == "trace-1"
        assert "trace_id" not in evs[1]

    def test_roundtrip_preserves_fields(self, tmp_path):
        path = tmp_path / "d.jsonl"
        with DecisionLog(path) as log:
            log.record("cold_search", plan_fingerprint="fp",
                       query_fingerprint="q", cause="boot", tenant="t0",
                       total_ms=12.5,
                       breakdown={"total_ms": 12.5,
                                  "components": {"compute": 12.5}},
                       runner_up={"plan_fingerprint": "fp2",
                                  "total_ms": 13.0},
                       margin_ms=0.5,
                       confidence={"compute": {"n": 3, "p95_abs_ms": 0.2}},
                       digests={"config": "abc"},
                       detail={"k": 1})
        rec = DecisionLog(path).get(1)
        assert rec.tenant == "t0" and rec.cause == "boot"
        assert rec.breakdown["components"] == {"compute": 12.5}
        assert rec.runner_up["plan_fingerprint"] == "fp2"
        assert rec.margin_ms == 0.5
        assert rec.confidence["compute"]["p95_abs_ms"] == 0.2
        assert rec.digests == {"config": "abc"}
        assert rec.detail == {"k": 1}


# ---------------------------------------------------------------------------
# causal chains
# ---------------------------------------------------------------------------


def _chaos_log() -> DecisionLog:
    """A preemption fan-out: cluster_delta -> fleet_repartition ->
    tenant_replan -> migration_decision, plus an unrelated root."""
    log = DecisionLog(None)
    log.record("cold_search", plan_fingerprint="fp0")          # seq 1
    root = log.record("cluster_delta", cause="preemption")     # seq 2
    rep = log.record("fleet_repartition", parent_seq=root.seq,
                     cause="preemption")                       # seq 3
    ten = log.record("tenant_replan", plan_fingerprint="fpT",
                     parent_seq=rep.seq, tenant="serve-web",
                     cause="preemption")                       # seq 4
    log.record("migration_decision", plan_fingerprint="fpT",
               parent_seq=ten.seq, cause="preemption",
               detail={"path": "migrate"})                     # seq 5
    return log


class TestCausalChain:
    def test_walks_to_root(self):
        log = _chaos_log()
        chain = log.chain(5)
        assert [r.seq for r in chain] == [2, 3, 4, 5]
        assert chain[0].kind == "cluster_delta"
        assert chain[0].cause == "preemption"

    def test_root_is_its_own_chain(self):
        log = _chaos_log()
        assert [r.seq for r in log.chain(1)] == [1]

    def test_dangling_parent_ends_walk(self):
        recs = [DecisionRecord(seq=7, ts=0.0, kind="tenant_replan",
                               parent_seq=99)]
        assert [r.seq for r in causal_chain(recs, 7)] == [7]

    def test_missing_leaf_is_empty(self):
        assert causal_chain([], 1) == []

    def test_render_and_json(self):
        log = _chaos_log()
        chain = log.chain(5)
        text = render_chain(chain)
        assert "cluster_delta" in text and "preemption" in text
        assert "tenant=serve-web" in text
        payload = chain_json(chain)
        assert payload["depth"] == 4
        assert payload["root_cause"] == "preemption"
        assert [h["record"]["seq"] for h in payload["hops"]] == [2, 3, 4, 5]


# ---------------------------------------------------------------------------
# plan diff: attribution sums exactly
# ---------------------------------------------------------------------------


class TestDiffPlans:
    def test_component_deltas_sum_exactly(self, search_result):
        plans = search_result.plans
        assert len(plans) >= 2 and plans[0].breakdown is not None
        diff = diff_plans(plans[0], plans[1])
        assert diff.total_delta_ms == pytest.approx(
            plans[1].cost.total_ms - plans[0].cost.total_ms)
        # the additive contract: per-component deltas decompose the total
        assert diff.component_delta_sum_ms == pytest.approx(
            diff.total_delta_ms, abs=1e-9)

    def test_axis_changes_detected(self, search_result):
        a = search_result.plans[0].to_json_dict()
        b = dict(a)
        b["node_sequence"] = list(reversed(a["node_sequence"]))
        diff = diff_plans(a, b)
        assert "placement" in diff.axis_changes
        assert diff.axis_changes["placement"]["b"] == b["node_sequence"]

    def test_identical_plans_diff_to_zero(self, search_result):
        p = search_result.plans[0]
        diff = diff_plans(p, p)
        assert diff.total_delta_ms == 0.0
        assert all(d == 0.0 for d in diff.component_deltas.values())
        assert not diff.axis_changes

    def test_decision_records_diff(self):
        a = DecisionRecord(
            seq=1, ts=0.0, kind="cold_search", plan_fingerprint="fpA",
            total_ms=10.0,
            breakdown={"total_ms": 10.0,
                       "components": {"compute": 7.0, "optimizer": 3.0}})
        b = DecisionRecord(
            seq=2, ts=0.0, kind="drift_replan", plan_fingerprint="fpB",
            total_ms=12.0,
            breakdown={"total_ms": 12.0,
                       "components": {"compute": 8.5, "optimizer": 3.5}})
        diff = diff_plans(a, b)
        assert diff.total_delta_ms == pytest.approx(2.0)
        assert diff.component_deltas["compute"] == pytest.approx(1.5)
        assert diff.decisive == ("compute", pytest.approx(1.5))
        assert diff.component_delta_sum_ms == pytest.approx(
            diff.total_delta_ms, abs=1e-9)
        assert "compute" in diff.render()

    def test_plan_axes_and_fingerprint_roundtrip(self, search_result):
        d = search_result.plans[0].to_json_dict()
        axes = plan_axes(d)
        assert axes["stages"] == d["num_stages"]
        assert axes["layer_cut"] == list(d["layer_partition"])
        from metis_tpu.obs.ledger import fingerprint_ranked_plan

        assert fingerprint_plan_dict(d) == fingerprint_ranked_plan(
            search_result.plans[0])


# ---------------------------------------------------------------------------
# planner-result extraction
# ---------------------------------------------------------------------------


class TestPlannerDecisionFields:
    def test_fields_from_result(self, search_result):
        fields = planner_decision_fields(search_result)
        best = search_result.plans[0]
        assert fields["total_ms"] == best.cost.total_ms
        assert fields["breakdown"]["total_ms"] == pytest.approx(
            best.cost.total_ms)
        assert fields["margin_ms"] == pytest.approx(
            search_result.plans[1].cost.total_ms - best.cost.total_ms)
        assert fields["runner_up"]["total_ms"] == \
            search_result.plans[1].cost.total_ms

    def test_record_planner_decision(self, search_result):
        log = DecisionLog(None)
        rec = record_planner_decision(log, search_result, cause="boot",
                                      tenant="t1")
        assert rec is not None and rec.seq == 1
        assert rec.kind == "cold_search" and rec.tenant == "t1"
        assert rec.breakdown is not None
        assert record_planner_decision(None, search_result) is None

    def test_artifact_digest_is_canonical(self):
        assert artifact_digest({"a": 1, "b": 2}) == \
            artifact_digest({"b": 2, "a": 1})
        assert artifact_digest({"a": 1}) != artifact_digest({"a": 2})
        assert len(artifact_digest([1, 2, 3])) == 12


# ---------------------------------------------------------------------------
# the decision-schema checker
# ---------------------------------------------------------------------------


class TestDecisionsSchemaChecker:
    def _valid(self):
        return [
            {"seq": 1, "ts": 1.0, "kind": "cold_search"},
            {"seq": 2, "ts": 2.0, "kind": "cache_hit", "parent_seq": 1},
            {"seq": 5, "ts": 3.0, "kind": "drift_replan", "parent_seq": 1,
             "breakdown": {"total_ms": 10.0,
                           "components": {"compute": 6.0, "optimizer": 4.0}}},
        ]

    def test_valid_log_passes(self):
        assert check_decisions_schema.validate_decisions(self._valid()) == []

    def test_unknown_kind_flagged(self):
        recs = self._valid()
        recs[0]["kind"] = "vibes"
        assert any("unknown decision kind" in p for p in
                   check_decisions_schema.validate_decisions(recs))

    def test_non_monotonic_seq_flagged(self):
        recs = self._valid()
        recs[2]["seq"] = 2
        assert any("strictly increasing" in p for p in
                   check_decisions_schema.validate_decisions(recs))

    def test_dangling_parent_flagged(self):
        recs = self._valid()
        recs[1]["parent_seq"] = 42
        assert any("does not resolve" in p for p in
                   check_decisions_schema.validate_decisions(recs))

    def test_forward_parent_flagged(self):
        # a parent_seq pointing FORWARD cannot be causal
        recs = self._valid()
        recs[0]["parent_seq"] = 5
        assert any("does not resolve" in p for p in
                   check_decisions_schema.validate_decisions(recs))

    def test_breakdown_additivity_enforced(self):
        recs = self._valid()
        recs[2]["breakdown"]["components"]["compute"] = 99.0
        assert any("additivity violated" in p for p in
                   check_decisions_schema.validate_decisions(recs))

    def test_kinds_stay_in_sync(self):
        # the checker's fallback literal must track the real vocabulary
        assert tuple(check_decisions_schema.DECISION_KINDS) == DECISION_KINDS

    def test_cli_flags_bad_file(self, tmp_path):
        bad = tmp_path / "bad.jsonl"
        bad.write_text('{"seq": 1, "ts": 1.0, "kind": "cold_search"}\n'
                       'not json\n')
        assert check_decisions_schema.main([str(bad)]) == 1


# ---------------------------------------------------------------------------
# ledger component residuals (the confidence context) — edge cases
# ---------------------------------------------------------------------------


class TestComponentResiduals:
    def test_empty_ledger(self):
        assert AccuracyLedger(None).component_residuals() == {}

    def test_measurement_without_component_prediction(self):
        led = AccuracyLedger(None)
        led.record_prediction("fp", 10.0)  # no components -> nothing to split
        led.record_measurement("fp", 11.0)
        assert led.component_residuals() == {}

    def test_single_sample_degenerate_percentiles(self):
        led = AccuracyLedger(None)
        led.record_prediction("fp", 10.0,
                              components={"compute": 6.0, "optimizer": 4.0})
        led.record_measurement("fp", 11.0,
                               components={"compute": 6.5, "optimizer": 4.5})
        out = led.component_residuals()
        for comp, pred, meas in (("compute", 6.0, 6.5),
                                 ("optimizer", 4.0, 4.5)):
            st = out[comp]
            assert st["n"] == 1
            assert st["mean_ms"] == pytest.approx(pred - meas)
            # one sample: p50 == p95 == |residual|, zero variance
            assert st["p50_abs_ms"] == st["p95_abs_ms"] == \
                pytest.approx(abs(pred - meas))
            assert st["var_ms"] == 0.0

    def test_identical_residuals_zero_variance(self):
        led = AccuracyLedger(None)
        led.record_prediction("fp", 10.0, components={"compute": 10.0})
        for step in range(4):
            led.record_measurement("fp", 9.0, step=step,
                                   components={"compute": 9.0})
        st = led.component_residuals()["compute"]
        assert st["n"] == 4
        assert st["mean_ms"] == pytest.approx(1.0)
        assert st["var_ms"] == 0.0
        assert st["p50_abs_ms"] == st["p95_abs_ms"] == pytest.approx(1.0)

    def test_component_absent_from_some_samples(self):
        # `migration` only appears on migrated steps: its n must count
        # only the samples that carry it, not every sample
        led = AccuracyLedger(None)
        led.record_prediction("fp", 12.0,
                              components={"compute": 10.0, "migration": 2.0})
        led.record_measurement("fp", 12.5, step=0,
                               components={"compute": 10.5, "migration": 2.0})
        led.record_measurement("fp", 10.4, step=1,
                               components={"compute": 10.4})
        out = led.component_residuals()
        assert out["compute"]["n"] == 2
        assert out["migration"]["n"] == 1
        assert out["migration"]["mean_ms"] == pytest.approx(0.0)

    def test_proportional_attribution_sums_to_total(self):
        # unresolved measurements split the total residual by predicted
        # shares, so per-component residuals still sum to the total
        led = AccuracyLedger(None)
        led.record_prediction("fp", 10.0,
                              components={"compute": 6.0, "optimizer": 4.0})
        led.record_measurement("fp", 12.0)
        out = led.component_residuals()
        total = out["compute"]["mean_ms"] + out["optimizer"]["mean_ms"]
        assert total == pytest.approx(-2.0)
        assert out["compute"]["mean_ms"] == pytest.approx(-1.2)

    def test_by_device_split(self):
        led = AccuracyLedger(None)
        led.record_prediction("fp", 10.0, components={"compute": 10.0},
                              device_type="A100")
        led.record_measurement("fp", 9.0, device_type="A100")
        led.record_measurement("fp", 11.0, device_type="T4")
        out = led.component_residuals(by_device=True)
        assert set(out) == {"A100", "T4"}
        assert out["A100"]["compute"]["n"] == 1
        assert out["A100"]["compute"]["mean_ms"] == pytest.approx(1.0)
        assert out["T4"]["compute"]["mean_ms"] == pytest.approx(-1.0)


# ---------------------------------------------------------------------------
# the why/diff CLI over a written decision log
# ---------------------------------------------------------------------------


class TestProvenanceCli:
    @pytest.fixture()
    def decisions_file(self, tmp_path):
        path = tmp_path / "decisions.jsonl"
        with DecisionLog(path) as log:
            log.record(
                "cold_search", plan_fingerprint="fpA",
                query_fingerprint="qfpA", total_ms=10.0,
                breakdown={"total_ms": 10.0,
                           "components": {"compute": 7.0, "optimizer": 3.0}})
            root = log.record("cluster_delta", cause="preemption")
            log.record(
                "delta_replan", plan_fingerprint="fpB",
                parent_seq=root.seq, cause="preemption", tenant="web",
                total_ms=12.0,
                breakdown={"total_ms": 12.0,
                           "components": {"compute": 8.0, "optimizer": 4.0}})
        return path

    def test_why_by_fingerprint(self, decisions_file, tmp_path, capsys):
        from metis_tpu.planner.cli import main as cli_main

        out = tmp_path / "why.json"
        rc = cli_main(["why", "fpB", "--decisions", str(decisions_file),
                       "--json", "--output", str(out)])
        assert rc == 0
        payload = json.loads(out.read_text())
        assert payload["depth"] == 2
        assert payload["root_cause"] == "preemption"
        assert payload["hops"][0]["record"]["kind"] == "cluster_delta"

    def test_why_by_query_fingerprint_and_tenant(self, decisions_file,
                                                 tmp_path):
        from metis_tpu.planner.cli import main as cli_main

        out = tmp_path / "why.json"
        # the /plan response echoes the QUERY fingerprint — it must match
        rc = cli_main(["why", "qfpA", "--decisions", str(decisions_file),
                       "--json", "--output", str(out)])
        assert rc == 0
        assert json.loads(out.read_text())["hops"][0]["record"][
            "plan_fingerprint"] == "fpA"
        rc = cli_main(["why", "--tenant", "web",
                       "--decisions", str(decisions_file),
                       "--json", "--output", str(out)])
        assert rc == 0
        assert json.loads(out.read_text())["depth"] == 2

    def test_why_unknown_fingerprint_fails(self, decisions_file, capsys):
        from metis_tpu.planner.cli import main as cli_main

        rc = cli_main(["why", "nope", "--decisions", str(decisions_file)])
        assert rc == 1
        assert "no decision matching" in capsys.readouterr().err

    def test_diff_from_decision_log(self, decisions_file, tmp_path):
        from metis_tpu.planner.cli import main as cli_main

        out = tmp_path / "diff.json"
        rc = cli_main(["diff", "fpA", "fpB",
                       "--decisions", str(decisions_file),
                       "--json", "--output", str(out)])
        assert rc == 0
        payload = json.loads(out.read_text())
        assert payload["total_delta_ms"] == pytest.approx(2.0)
        assert sum(payload["component_deltas"].values()) == pytest.approx(
            payload["total_delta_ms"], abs=1e-9)

    def test_diff_from_plan_dump(self, search_result, tmp_path):
        from metis_tpu.core.types import dump_ranked_plans
        from metis_tpu.planner.cli import main as cli_main

        dump = tmp_path / "plans.json"
        dump.write_text(dump_ranked_plans(search_result.plans))
        fps = [fingerprint_plan_dict(p)
               for p in json.loads(dump.read_text())[:2]]
        out = tmp_path / "diff.json"
        rc = cli_main(["diff", fps[0], fps[1], "--plans", str(dump),
                       "--json", "--output", str(out)])
        assert rc == 0
        payload = json.loads(out.read_text())
        assert payload["fingerprint_a"] == fps[0]
        assert sum(payload["component_deltas"].values()) == pytest.approx(
            payload["total_delta_ms"] or 0.0, abs=1e-9)

    def test_diff_unknown_fingerprint_fails(self, decisions_file, capsys):
        from metis_tpu.planner.cli import main as cli_main

        rc = cli_main(["diff", "fpA", "ghost",
                       "--decisions", str(decisions_file)])
        assert rc == 1
        assert "not found" in capsys.readouterr().err


# ---------------------------------------------------------------------------
# rotation regression: the audit trail survives an event-log roll
# ---------------------------------------------------------------------------


class TestRotationRegression:
    def test_fleet_drill_with_midrun_rotation(self, tmp_path):
        """A fleet drill sized to roll its event log to <name>.1 exactly
        once mid-run: the drill's own causality checks (which read the
        roll) and the schema checker's rotated read must both pass."""
        from tools.fleet_drill import run_fleet_drill

        rep = run_fleet_drill(tmp_path, ticks=12, seed=2,
                              spot_rate_per_hr=0.15,
                              events_max_bytes=60_000)
        assert rep["provenance_chains_verified"] == rep["replan_pushes"] > 0
        ev = tmp_path / "fleet_events.jsonl"
        roll = tmp_path / "fleet_events.jsonl.1"
        assert roll.exists(), "the drill never rotated"
        # the checker spans the roll: cross-event invariants (span pairs,
        # seq continuity) hold over roll + live, not just the live file
        n, problems = check_events_schema.validate_file(ev)
        assert not problems, "\n".join(problems)
        n_live, _ = check_events_schema.validate_file(
            ev, include_rotated=False)
        assert n > n_live > 0

    def test_report_cli_reads_rotated_log(self, tmp_path):
        """`metis-tpu report` over a rotated log sees spans from BOTH
        files (the roll's records come first), so a span tree that
        straddles the roll still reconstructs."""
        from metis_tpu.core.events import read_events_rotated
        from metis_tpu.core.trace import Tracer
        from metis_tpu.planner.cli import main as cli_main

        def write_spans(events):
            tracer = Tracer(events)
            for i in range(12):
                with tracer.span(f"step_{i:02d}"):
                    with tracer.span("inner"):
                        pass

        # size the cap off an unrotated probe run so the real log rolls
        # exactly once (a second roll would overwrite .1 and LOSE the
        # earliest events — then the regression would prove nothing)
        probe = tmp_path / "probe.jsonl"
        with EventLog(probe) as events:
            write_spans(events)
        n_probe = len(read_events(probe))
        path = tmp_path / "ev.jsonl"
        with EventLog(path, max_bytes=int(probe.stat().st_size * 0.6)) \
                as events:
            write_spans(events)
        assert (tmp_path / "ev.jsonl.1").exists()
        merged = read_events_rotated(path)
        markers = [e for e in merged if e["event"] == "event_log_rotated"]
        assert len(markers) == 1, "expected exactly one rotation"
        n_total = len(merged)
        assert n_total == n_probe + 1 and len(read_events(path)) < n_total
        out = tmp_path / "report.json"
        rc = cli_main(["report", str(path), "--json",
                       "--output", str(out)])
        assert rc == 0
        payload = json.loads(out.read_text())
        names = {s["name"] for s in payload.get("spans", [])}
        # spans from the ROLLED half of the log made it into the report
        assert "step_00" in names
