"""Cold-search worker pool tests (metis_tpu/serve/pool.py).

The contracts that make the pool safe to put behind the daemon:
- ranking byte-identity: a pool search's merged ranking is exactly the
  serial search's (same stable tie-break key, same truncation), proven
  at the daemon level — plan_query responses from a pooled service are
  byte-identical to a serial service AND to offline plan_hetero;
- warm reuse: repeat searches for the same query fingerprint answer from
  warm per-worker evaluators (outcome.warm flips true);
- incremental-replan bridge: workers ship touched_nodes /
  tagged_candidates home and the daemon merges them into its parent
  state, so apply_cluster_delta's keep/drop pivot still works;
- fallback: any pool failure degrades to the serial path with a
  parallel_fallback event — never an error to the client, and the
  response is byte-identical either way.
"""
from __future__ import annotations

import sys
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from metis_tpu.cluster import ClusterSpec
from metis_tpu.core.config import SearchConfig
from metis_tpu.serve.pool import SearchPoolError, SearchWorkerPool

pytestmark = pytest.mark.skipif(
    __import__("metis_tpu.search.parallel", fromlist=["_mp_context"])
    ._mp_context() is None,
    reason="no multiprocessing start method available")


@pytest.fixture(scope="module")
def workload():
    from metis_tpu.profiles import synthesize_profiles, tiny_test_model

    model = tiny_test_model(num_layers=4)
    profiles = synthesize_profiles(model, ["A100", "T4"], tps=[1, 2],
                                   bss=[1, 2, 4])
    cluster = ClusterSpec.of(("A100", 1, 4), ("T4", 1, 4))
    config = SearchConfig(gbs=16, max_profiled_tp=2, max_profiled_bs=4)
    return cluster, profiles, model, config


@pytest.fixture(scope="module")
def pool(workload):
    cluster, profiles, _model, _config = workload
    p = SearchWorkerPool(cluster, profiles, 2)
    yield p
    p.close()


class TestSearchWorkerPool:
    def test_merged_ranking_matches_serial(self, workload, pool):
        from metis_tpu.obs.ledger import (fingerprint_ranked_plan,
                                          query_fingerprint)
        from metis_tpu.planner.api import plan_hetero

        cluster, profiles, model, config = workload
        serial = plan_hetero(cluster, profiles, model, config, top_k=5)
        qfp = query_fingerprint(model, cluster, config)
        out = pool.search(qfp, cluster, model, config, 5,
                          range(len(cluster.nodes)))
        assert [fingerprint_ranked_plan(p) for p in out.plans] == \
            [fingerprint_ranked_plan(p) for p in serial.plans]
        assert [p.cost.total_ms for p in out.plans] == \
            [p.cost.total_ms for p in serial.plans]
        assert out.num_costed == serial.num_costed
        assert out.num_pruned == serial.num_pruned
        assert out.num_bound_pruned == serial.num_bound_pruned

    def test_warm_reuse_and_identical_repeat(self, workload, pool):
        from metis_tpu.obs.ledger import (fingerprint_ranked_plan,
                                          query_fingerprint)

        cluster, profiles, model, config = workload
        qfp = query_fingerprint(model, cluster, config)
        first = pool.search(qfp, cluster, model, config, 5,
                            range(len(cluster.nodes)))
        again = pool.search(qfp, cluster, model, config, 5,
                            range(len(cluster.nodes)))
        assert again.warm is True
        assert [fingerprint_ranked_plan(p) for p in again.plans] == \
            [fingerprint_ranked_plan(p) for p in first.plans]

    def test_ships_incremental_replan_state_home(self, workload, pool):
        from metis_tpu.obs.ledger import query_fingerprint

        cluster, profiles, model, config = workload
        qfp = query_fingerprint(model, cluster, config)
        out = pool.search(qfp, cluster, model, config, 5,
                          range(len(cluster.nodes)))
        assert out.touched_nodes, "workers shipped no touched_nodes"
        assert out.tagged_candidates > 0
        assert out.counters, "workers shipped no counter deltas"

    def test_prewarm(self, workload):
        from metis_tpu.obs.ledger import query_fingerprint

        cluster, profiles, model, config = workload
        p = SearchWorkerPool(cluster, profiles, 2)
        try:
            qfp = query_fingerprint(model, cluster, config)
            p.prewarm(qfp, cluster, model, config,
                      range(len(cluster.nodes)))
            out = p.search(qfp, cluster, model, config, 5,
                           range(len(cluster.nodes)))
            assert out.warm is True
        finally:
            p.close()

    def test_close_is_idempotent_and_rejects_searches(self, workload):
        cluster, profiles, model, config = workload
        p = SearchWorkerPool(cluster, profiles, 1)
        p.close()
        p.close()
        with pytest.raises(SearchPoolError):
            p.search("qfp", cluster, model, config, 5, (0, 1))

    def test_rejects_zero_workers(self, workload):
        cluster, profiles, _model, _config = workload
        with pytest.raises(ValueError):
            SearchWorkerPool(cluster, profiles, 0)


class TestDaemonPoolIntegration:
    def test_pooled_daemon_byte_identical_to_serial_and_offline(
            self, workload):
        from metis_tpu.core.types import dump_ranked_plans
        from metis_tpu.planner.api import plan_hetero
        from metis_tpu.serve.daemon import PlanService

        cluster, profiles, model, config = workload
        offline = dump_ranked_plans(
            plan_hetero(cluster, profiles, model, config, top_k=5).plans)
        serial_svc = PlanService(cluster, profiles)
        pooled_svc = PlanService(cluster, profiles, search_pool=2)
        try:
            assert pooled_svc.search_pool is not None, \
                "pool failed to boot"
            serial = serial_svc.plan_query(model, config, top_k=5)
            pooled = pooled_svc.plan_query(model, config, top_k=5)
            assert pooled["plans"] == serial["plans"] == offline
            assert pooled["num_costed"] == serial["num_costed"]
            assert pooled["num_pruned"] == serial["num_pruned"]
            assert pooled["plan_fingerprint"] == \
                serial["plan_fingerprint"]
            assert pooled_svc.counters.get("serve.pool_search") == 1
            # encoded path over the pool: still canonical dumps bytes
            body = pooled_svc.plan_query_encoded(model, config, top_k=5)
            import json as _json
            assert _json.dumps(_json.loads(body)).encode() == body
        finally:
            pooled_svc.close()
            serial_svc.close()

    def test_pool_search_primes_parent_state_for_replan(self, workload):
        from metis_tpu.serve.daemon import PlanService

        cluster, profiles, model, config = workload
        svc = PlanService(cluster, profiles, search_pool=2)
        try:
            assert svc.search_pool is not None
            svc.plan_query(model, config, top_k=5)
            assert svc.stats()["warm_states"] == 1
            state = next(iter(svc._states.values()))
            # the workers' touch tags landed in the parent state, so the
            # incremental-replan keep/drop pivot sees this query
            assert state.touched_nodes
            assert state.tagged_candidates > 0
            out = svc.apply_cluster_delta({"T4": 4})
            assert out["invalidated"] == 1
            # shrunk topology still answers (pool handles the new
            # fingerprint; ranking contract re-checked by byte-identity
            # tests above)
            shrunk = svc.plan_query(model, config, top_k=5)
            assert shrunk["cached"] is False
        finally:
            svc.close()

    def test_pool_failure_falls_back_to_serial(self, workload, tmp_path):
        from metis_tpu.core.events import EventLog
        from metis_tpu.core.types import dump_ranked_plans
        from metis_tpu.planner.api import plan_hetero
        from metis_tpu.serve.daemon import PlanService

        cluster, profiles, model, config = workload
        events_path = tmp_path / "events.jsonl"
        events = EventLog(events_path)
        svc = PlanService(cluster, profiles, search_pool=2,
                          events=events)
        try:
            assert svc.search_pool is not None
            # kill the pool out from under the daemon: the next cold
            # query must fall back to the serial path, not error
            svc.search_pool.close()
            out = svc.plan_query(model, config, top_k=5)
            offline = dump_ranked_plans(
                plan_hetero(cluster, profiles, model, config,
                            top_k=5).plans)
            assert out["plans"] == offline
            assert svc.counters.get("serve.pool_fallback") == 1
        finally:
            svc.close()
            events.close()
        import json as _json
        evs = [_json.loads(ln)
               for ln in events_path.read_text().splitlines()]
        falls = [e for e in evs if e["event"] == "parallel_fallback"]
        assert falls and "search pool" in falls[0]["reason"]

    def test_standby_never_boots_a_pool(self, workload):
        from metis_tpu.serve.daemon import PlanService

        cluster, profiles, _model, _config = workload
        svc = PlanService(cluster, profiles, search_pool=2,
                          read_only=True)
        try:
            assert svc.search_pool is None
            assert svc.stats()["search_pool_workers"] == 0
        finally:
            svc.close()
