"""Planner-as-a-service tests: plan cache, query fingerprint, daemon.

Covers the serve-layer contracts:
- PlanCache: LRU eviction order, capacity bound, serve.cache.* counters,
  invalidation.
- query_fingerprint: inequality across every cost-relevant SearchConfig
  toggle (the stale-cache regression), equality across processes,
  neutrality of result-neutral fields.
- PlanService in-process: hit/miss semantics, byte-identity with the
  offline path, warm-state reuse, drift-alarm replan + notification,
  ClusterDelta invalidation.
- tools/serve_smoke.py wired in as the tier-1 end-to-end gate (HTTP
  transport, 64-thread concurrency, p50 budget, schema-valid events).
"""
from __future__ import annotations

import dataclasses
import json
import os
import subprocess
import sys
import time
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from metis_tpu.cluster import ClusterSpec
from metis_tpu.core.config import ModelSpec, SearchConfig
from metis_tpu.core.trace import Counters
from metis_tpu.obs.ledger import calibration_fingerprint, query_fingerprint
from metis_tpu.serve.cache import PlanCache


# ---------------------------------------------------------------------------
# PlanCache
# ---------------------------------------------------------------------------


class TestPlanCache:
    def test_hit_miss_and_counters(self):
        c = Counters()
        cache = PlanCache(capacity=4, counters=c)
        assert cache.get("a") is None
        cache.put("a", {"v": 1})
        assert cache.get("a") == {"v": 1}
        assert c.get("serve.cache.miss") == 1
        assert c.get("serve.cache.hit") == 1

    def test_lru_eviction_order(self):
        c = Counters()
        cache = PlanCache(capacity=2, counters=c)
        cache.put("a", {"v": 1})
        cache.put("b", {"v": 2})
        cache.get("a")  # refresh a: b becomes LRU
        cache.put("c", {"v": 3})  # evicts b
        assert "b" not in cache
        assert cache.get("a") == {"v": 1}
        assert cache.get("c") == {"v": 3}
        assert c.get("serve.cache.evict") == 1

    def test_capacity_bound(self):
        cache = PlanCache(capacity=3)
        for i in range(10):
            cache.put(f"k{i}", {"v": i})
        assert len(cache) == 3
        assert cache.keys() == ["k7", "k8", "k9"]

    def test_put_refreshes_recency(self):
        cache = PlanCache(capacity=2)
        cache.put("a", {"v": 1})
        cache.put("b", {"v": 2})
        cache.put("a", {"v": 10})  # refresh: b is now LRU
        cache.put("c", {"v": 3})
        assert "b" not in cache
        assert cache.get("a") == {"v": 10}

    def test_invalidate_single_and_counters(self):
        c = Counters()
        cache = PlanCache(capacity=4, counters=c)
        cache.put("a", {"v": 1})
        assert cache.invalidate("a") is True
        assert cache.invalidate("a") is False  # already gone: no counter
        assert c.get("serve.cache.invalidate") == 1
        assert cache.get("a") is None

    def test_invalidate_where_and_all(self):
        c = Counters()
        cache = PlanCache(capacity=8, counters=c)
        for i in range(4):
            cache.put(f"k{i}", {"fingerprint": "x" if i < 2 else "y"})
        dropped = cache.invalidate_where(
            lambda _k, v: v["fingerprint"] == "x")
        assert sorted(dropped) == ["k0", "k1"]
        assert len(cache) == 2
        assert cache.invalidate_all() == 2
        assert len(cache) == 0
        assert c.get("serve.cache.invalidate") == 4

    def test_rejects_nonpositive_capacity(self):
        with pytest.raises(ValueError):
            PlanCache(capacity=0)

    def test_stats_shape(self):
        cache = PlanCache(capacity=4, counters=Counters())
        cache.put("a", {})
        cache.get("a")
        cache.get("zz")
        s = cache.stats()
        assert s["size"] == 1 and s["capacity"] == 4
        assert s["hits"] == 1 and s["misses"] == 1


# ---------------------------------------------------------------------------
# query_fingerprint
# ---------------------------------------------------------------------------


def _mini_model(**over) -> ModelSpec:
    base = dict(name="m", num_layers=4, hidden_size=256,
                sequence_length=128, vocab_size=1000, num_heads=4)
    base.update(over)
    return ModelSpec(**base)


def _mini_cluster() -> ClusterSpec:
    return ClusterSpec.of(("A100", 1, 4))


class TestQueryFingerprint:
    def test_stable_for_identical_inputs(self):
        a = query_fingerprint(_mini_model(), _mini_cluster(),
                              SearchConfig(gbs=16))
        b = query_fingerprint(_mini_model(), _mini_cluster(),
                              SearchConfig(gbs=16))
        assert a == b
        assert len(a) == 12

    @pytest.mark.parametrize("flip", [
        {"use_overlap_model": False},
        {"use_batch_eval": False},
        {"strict_compat": True},
        {"gbs": 32},
        {"max_profiled_tp": 2},
        {"max_profiled_bs": 8},
        {"mem_coef": 1.0},
        {"enable_sp": True},
        {"enable_zero": True},
        {"enable_schedule_search": True},
        {"dp_overlap_fraction": 0.5},
        {"prune_to_top_k": 10},
    ])
    def test_cost_relevant_toggle_changes_fingerprint(self, flip):
        """The stale-cache regression: flipping ANY cost-relevant config
        field must produce a different cache key."""
        base = SearchConfig(gbs=16)
        flipped = dataclasses.replace(base, **flip)
        assert (query_fingerprint(_mini_model(), _mini_cluster(), base)
                != query_fingerprint(_mini_model(), _mini_cluster(),
                                     flipped))

    @pytest.mark.parametrize("flip", [
        {"workers": 4},
        {"progress_every": 17},
    ])
    def test_result_neutral_fields_do_not_change_fingerprint(self, flip):
        """Fields that by construction cannot change the ranked result
        (serial/parallel byte-identity, heartbeat cadence) share a key."""
        base = SearchConfig(gbs=16)
        flipped = dataclasses.replace(base, **flip)
        assert (query_fingerprint(_mini_model(), _mini_cluster(), base)
                == query_fingerprint(_mini_model(), _mini_cluster(),
                                     flipped))

    def test_model_and_cluster_change_fingerprint(self):
        cfg = SearchConfig(gbs=16)
        base = query_fingerprint(_mini_model(), _mini_cluster(), cfg)
        assert query_fingerprint(_mini_model(num_layers=8),
                                 _mini_cluster(), cfg) != base
        bigger = ClusterSpec.of(("A100", 2, 4))
        assert query_fingerprint(_mini_model(), bigger, cfg) != base

    def test_calibration_identity(self):
        cfg = SearchConfig(gbs=16)
        none = query_fingerprint(_mini_model(), _mini_cluster(), cfg)
        cal = {"platform": "tpu", "device_kind": "v5e", "group_size": 8,
               "fits": {"all_reduce": [1.0, 2.0]},
               "samples": [[1, 2, 3]]}
        with_cal = query_fingerprint(_mini_model(), _mini_cluster(), cfg,
                                     calibration=cal)
        assert with_cal != none
        # samples are measurement noise, not pricing: excluded
        cal2 = dict(cal, samples=[[9, 9, 9]])
        assert query_fingerprint(_mini_model(), _mini_cluster(), cfg,
                                 calibration=cal2) == with_cal
        cal3 = dict(cal, fits={"all_reduce": [9.0, 9.0]})
        assert query_fingerprint(_mini_model(), _mini_cluster(), cfg,
                                 calibration=cal3) != with_cal
        assert calibration_fingerprint(None) is None

    def test_equal_across_processes(self):
        """sha1-of-canonical-JSON, not hash(): a daemon restart (new
        PYTHONHASHSEED) must produce the same cache keys."""
        local = query_fingerprint(_mini_model(), _mini_cluster(),
                                  SearchConfig(gbs=16))
        script = (
            "from metis_tpu.cluster import ClusterSpec\n"
            "from metis_tpu.core.config import ModelSpec, SearchConfig\n"
            "from metis_tpu.obs.ledger import query_fingerprint\n"
            "m = ModelSpec(name='m', num_layers=4, hidden_size=256,\n"
            "              sequence_length=128, vocab_size=1000,\n"
            "              num_heads=4)\n"
            "print(query_fingerprint(m, ClusterSpec.of(('A100', 1, 4)),\n"
            "                        SearchConfig(gbs=16)))\n"
        )
        out = subprocess.run(
            [sys.executable, "-c", script],
            capture_output=True, text=True, check=True,
            cwd=Path(__file__).resolve().parent.parent,
            env={**os.environ, "PYTHONHASHSEED": "12345",
                 "JAX_PLATFORMS": "cpu"},
        )
        assert out.stdout.strip() == local


# ---------------------------------------------------------------------------
# PlanService (in-process, no HTTP — transport is covered by the smoke)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def small_workload():
    from metis_tpu.profiles import synthesize_profiles, tiny_test_model

    model = tiny_test_model(num_layers=4)
    profiles = synthesize_profiles(model, ["A100", "T4"], tps=[1, 2],
                                   bss=[1, 2, 4])
    cluster = ClusterSpec.of(("A100", 1, 4), ("T4", 1, 4))
    config = SearchConfig(gbs=16, max_profiled_tp=2, max_profiled_bs=4)
    return cluster, profiles, model, config


@pytest.fixture()
def service(small_workload):
    from metis_tpu.serve.daemon import PlanService

    cluster, profiles, model, config = small_workload
    return PlanService(cluster, profiles, drift_min_samples=5)


class TestPlanService:
    def test_miss_then_hit_byte_identical_to_offline(self, small_workload,
                                                     service):
        from metis_tpu.core.types import dump_ranked_plans
        from metis_tpu.planner.api import plan_hetero

        cluster, profiles, model, config = small_workload
        offline = dump_ranked_plans(
            plan_hetero(cluster, profiles, model, config, top_k=5).plans)
        cold = service.plan_query(model, config, top_k=5)
        assert cold["cached"] is False
        assert cold["plans"] == offline
        hit = service.plan_query(model, config, top_k=5)
        assert hit["cached"] is True
        assert hit["plans"] == offline
        assert service.counters.get("serve.cache.hit") == 1
        assert service.counters.get("serve.cache.miss") == 1

    def test_distinct_config_distinct_entry(self, small_workload, service):
        _, _, model, config = small_workload
        a = service.plan_query(model, config, top_k=5)
        flipped = dataclasses.replace(config, use_overlap_model=False)
        b = service.plan_query(model, flipped, top_k=5)
        assert b["cached"] is False
        assert a["fingerprint"] != b["fingerprint"]
        assert len(service.cache) == 2

    def test_warm_state_reuse_is_byte_identical(self, small_workload,
                                                service):
        _, _, model, config = small_workload
        first = service.plan_query(model, config, top_k=5)
        service.invalidate()  # drop cache, KEEP warm state
        assert service.stats()["warm_states"] == 1
        again = service.plan_query(model, config, top_k=5)
        assert again["cached"] is False
        assert again["plans"] == first["plans"]

    def test_drift_alarm_replans_and_notifies(self, small_workload,
                                              service):
        _, _, model, config = small_workload
        cold = service.plan_query(model, config, top_k=5)
        fp = cold["plan_fingerprint"]
        status = None
        for step in range(8):
            status = service.post_accuracy_sample(
                fp, measured_ms=cold["best_cost_ms"] * 2.0, step=step)
        assert status["in_drift"] is True
        assert status["alarms"] == 1
        notes = service.notifications(since=0, timeout_s=30.0)
        pushes = [n for n in notes if n["kind"] == "replan_push"]
        assert len(pushes) == 1
        assert pushes[0]["fingerprint"] == fp
        # same topology: identical ranking re-primed under the same key
        refreshed = service.plan_query(model, config, top_k=5)
        assert refreshed["cached"] is True
        assert refreshed["plans"] == cold["plans"]
        # hysteresis: more bad samples fire no second alarm/replan
        before = service.stats()["note_seq"]
        for step in range(8, 12):
            service.post_accuracy_sample(
                fp, measured_ms=cold["best_cost_ms"] * 2.0, step=step)
        assert service.notifications(since=before) == []

    def test_in_band_samples_do_not_replan(self, small_workload, service):
        _, _, model, config = small_workload
        cold = service.plan_query(model, config, top_k=5)
        fp = cold["plan_fingerprint"]
        for step in range(10):
            out = service.post_accuracy_sample(
                fp, measured_ms=cold["best_cost_ms"] * 1.01, step=step)
            assert out["replanning"] is False
        assert service.notifications(since=0) == []

    def test_cluster_delta_invalidates_and_rekeys(self, small_workload,
                                                  service):
        _, _, model, config = small_workload
        cold = service.plan_query(model, config, top_k=5)
        out = service.apply_cluster_delta({"T4": 4})
        assert out["invalidated"] == 1
        assert out["devices"] == 4
        assert service.stats()["warm_states"] == 0
        notes = service.notifications(since=0)
        assert notes and notes[-1]["kind"] == "cluster_delta"
        shrunk = service.plan_query(model, config, top_k=5)
        assert shrunk["cached"] is False
        assert shrunk["fingerprint"] != cold["fingerprint"]
        assert shrunk["plans"] != cold["plans"]

    def test_empty_cluster_delta_is_a_cheap_noop(self, small_workload,
                                                 service):
        """A delta that changes nothing (no args, or a remove cancelled by
        an add in the same call) must keep warm search state and the plan
        cache, and push no note — regression for the path that used to
        clear both on EMPTY deltas."""
        _, _, model, config = small_workload
        cold = service.plan_query(model, config, top_k=5)
        before = service.stats()
        service.apply_cluster_delta()
        out = service.apply_cluster_delta(removed={"T4": 2},
                                          added={"T4": 2}, replan=True)
        assert out["invalidated"] == 0
        assert out["removed"] == {} and out["added"] == {}
        assert out["replanning"] is False
        after = service.stats()
        assert after["warm_states"] == before["warm_states"] == 1
        assert after["cache"]["size"] == before["cache"]["size"] == 1
        assert after["note_seq"] == before["note_seq"] == out["seq"]
        assert service.notifications(since=0) == []
        warm = service.plan_query(model, config, top_k=5)
        assert warm["cached"] is True
        assert warm["plans"] == cold["plans"]

    def test_cluster_delta_rejects_overdraw(self, service):
        from metis_tpu.core.errors import ClusterSpecError

        with pytest.raises(ClusterSpecError):
            service.apply_cluster_delta({"T4": 99})

    def test_close_wakes_blocked_long_poll(self, service):
        """Regression: daemon shutdown must wake a client blocked in the
        notifications long-poll immediately instead of holding it until
        its timeout expires."""
        import threading
        import time

        out = {}

        def poll():
            t0 = time.monotonic()
            out["notes"] = service.notifications(since=0, timeout_s=30.0)
            out["waited_s"] = time.monotonic() - t0

        t = threading.Thread(target=poll)
        t.start()
        time.sleep(0.05)  # let the poller block on the condition
        service.close()
        t.join(timeout=5.0)
        assert not t.is_alive(), "close() left the long-poll blocked"
        assert out["notes"] == []
        assert out["waited_s"] < 5.0
        # closed service answers further polls immediately, and close()
        # is idempotent
        t0 = time.monotonic()
        assert service.notifications(since=0, timeout_s=30.0) == []
        assert time.monotonic() - t0 < 1.0
        service.close()

    def test_stats_shape(self, small_workload, service):
        _, _, model, config = small_workload
        service.plan_query(model, config, top_k=5)
        s = service.stats()
        assert s["cluster_devices"] == 8
        assert s["cache"]["size"] == 1
        assert s["warm_states"] == 1
        assert s["queries"] == 1
        assert json.dumps(s)  # JSON-serializable end to end


# ---------------------------------------------------------------------------
# incremental replanning (ClusterDelta keep/drop of warm state + cache)
# ---------------------------------------------------------------------------


class TestIncrementalReplan:
    def test_changed_node_ids_partial_and_full(self, service):
        """Shrinks peel from the END of a type's node run; a partial loss
        narrows the last matching node — only that node id changes."""
        from metis_tpu.planner.replan import grow_cluster, shrink_cluster

        full = service.cluster  # A100 node 0, T4 node 1 (4 devices each)
        half = shrink_cluster(full, {"T4": 2})
        assert service._full_node_ids(half) == (0, 1)
        assert service._changed_node_ids(full, half) == frozenset({1})
        gone = shrink_cluster(full, {"T4": 4})
        assert service._full_node_ids(gone) == (0,)
        assert service._changed_node_ids(full, gone) == frozenset({1})
        assert service._changed_node_ids(full, full) == frozenset()
        back = grow_cluster(gone, full, {"T4": 4})
        assert service._changed_node_ids(gone, back) == frozenset({1})

    def test_full_cluster_queries_are_recosted_not_reused(
            self, small_workload, service):
        """A single-job search lays stages over every node, so a delta
        touching any node drops its warm state — and the reused/recosted
        counters reconcile exactly with the pre-delta candidate tags."""
        _, _, model, config = small_workload
        service.plan_query(model, config, top_k=5)
        flipped = dataclasses.replace(config, use_overlap_model=False)
        service.plan_query(model, flipped, top_k=5)
        tagged = sum(s.tagged_candidates for s in service._states.values())
        assert tagged > 0
        out = service.apply_cluster_delta({"T4": 4})
        assert service.stats()["warm_states"] == 0
        c = service.counters
        assert c.get("replan.incremental.reused") == 0
        assert c.get("replan.incremental.recosted") == tagged
        assert out["invalidated"] == 2

    def test_shrink_grow_round_trip_byte_identical(self, small_workload,
                                                   service):
        """After a delta the daemon's answer must equal a cold full search
        on the new topology, and the grow-back must reproduce the original
        full-fleet ranking byte-identically under the original key."""
        from metis_tpu.core.types import dump_ranked_plans
        from metis_tpu.planner.api import plan_hetero

        _, profiles, model, config = small_workload
        cold = service.plan_query(model, config, top_k=5)
        service.apply_cluster_delta(removed={"T4": 4})
        shrunk = service.plan_query(model, config, top_k=5)
        assert shrunk["cached"] is False
        assert shrunk["fingerprint"] != cold["fingerprint"]
        offline = dump_ranked_plans(
            plan_hetero(service.cluster, profiles, model, config,
                        top_k=5).plans)
        assert shrunk["plans"] == offline
        service.apply_cluster_delta(added={"T4": 4})
        restored = service.plan_query(model, config, top_k=5)
        assert restored["fingerprint"] == cold["fingerprint"]
        assert restored["plans"] == cold["plans"]

    def test_tenant_carve_untouched_by_delta_stays_warm(self,
                                                        small_workload,
                                                        service):
        """The satellite-1 regression: a delta that misses a tenant's
        carve must keep that tenant's warm search state AND its cached
        answer; only intersecting states are re-costed."""
        from metis_tpu.sched.tenant import TenantSpec

        _, _, model, config = small_workload
        service.tenant_register(
            TenantSpec("a", model, config, priority=1, quota_ceiling=4))
        service.tenant_register(
            TenantSpec("b", model, config, quota_ceiling=4))
        a0 = service.tenant_plan("a")
        b0 = service.tenant_plan("b")
        assert a0["node_indices"] == [0]  # A100 node: lowest hazard first
        assert b0["node_indices"] == [1]
        tagged = {k: s.tagged_candidates
                  for k, s in service._states.items()}
        assert tagged
        service.apply_cluster_delta(removed={"T4": 4})
        # tenant a's carve (node 0) missed the delta (node 1): cached
        # answer survives, warm state survives
        a1 = service.tenant_plan("a")
        assert a1["cached"] is True
        assert a1["plans"] == a0["plans"]
        kept = [k for k in tagged if k in service._states]
        assert kept, "delta dropped the untouched tenant's warm state"
        assert all(service._states[k].touched_nodes == {0} for k in kept)
        c = service.counters
        reused = c.get("replan.incremental.reused")
        recosted = c.get("replan.incremental.recosted")
        assert reused == sum(tagged[k] for k in kept) > 0
        assert recosted > 0
        assert reused + recosted == sum(tagged.values())
        # grow back: tenant b's carve recurs with a byte-identical ranking
        service.apply_cluster_delta(added={"T4": 4})
        b2 = service.tenant_plan("b")
        assert b2["node_indices"] == [1]
        assert b2["plans"] == b0["plans"]

    def test_incremental_replan_event_schema(self, small_workload,
                                             tmp_path):
        from metis_tpu.core.events import EventLog
        from metis_tpu.serve.daemon import PlanService
        from tools.check_events_schema import validate_events

        cluster, profiles, model, config = small_workload
        path = tmp_path / "events.jsonl"
        with EventLog(path) as log:
            svc = PlanService(cluster, profiles, events=log)
            svc.plan_query(model, config, top_k=5)
            tagged = sum(s.tagged_candidates
                         for s in svc._states.values())
            svc.apply_cluster_delta(removed={"T4": 2})
            svc.close()
        evs = [json.loads(line)
               for line in path.read_text().splitlines()]
        assert validate_events(evs) == []
        ir = [e for e in evs if e["event"] == "incremental_replan"]
        assert len(ir) == 1
        ev = ir[0]
        assert ev["changed_nodes"] == [1]
        assert ev["states_kept"] == 0 and ev["states_dropped"] == 1
        assert ev["reused"] == 0
        assert ev["recosted"] == tagged
        assert ev["invalidated"] == 1


# ---------------------------------------------------------------------------
# end-to-end smoke (HTTP transport, concurrency, p50, event schema)
# ---------------------------------------------------------------------------


def test_serve_smoke_tier1(tmp_path):
    """The acceptance gate: byte-identical daemon responses, cached p50
    under budget, >= 64 clean concurrent queries, valid event JSONL."""
    from tools.serve_smoke import run_smoke

    out = run_smoke(threads=64, per_thread=2, cached_queries=50,
                    work_dir=tmp_path)
    assert out["ok"] is True
    assert out["serve_cache_hit_p50_ms"] < 10.0
    assert out["concurrent_queries"] >= 64


def test_serve_smoke_unix_socket(tmp_path):
    """Same contract over AF_UNIX — the deployment mode the CLI's
    --socket flag uses."""
    from tools.serve_smoke import run_smoke

    out = run_smoke(threads=16, per_thread=1, cached_queries=20,
                    unix_socket=True, work_dir=tmp_path)
    assert out["ok"] is True
    assert out["address"].startswith("unix:")


# ---------------------------------------------------------------------------
# sharded cache invariants
# ---------------------------------------------------------------------------


class TestPlanCacheSharding:
    def test_shard_counters_reconcile_with_global(self):
        """sum(per-shard hits/misses) == the serve.cache.* counters —
        the reconciliation invariant /stats consumers depend on."""
        c = Counters()
        cache = PlanCache(capacity=64, counters=c, shards=4)
        for i in range(32):
            cache.put(f"k{i}", {"v": i})
        for i in range(32):
            assert cache.get(f"k{i}") == {"v": i}
        for i in range(10):
            assert cache.get(f"absent{i}") is None
        stats = cache.shard_stats()
        assert len(stats) == 4
        assert sum(s["hits"] for s in stats) == c.get("serve.cache.hit")
        assert sum(s["misses"] for s in stats) == \
            c.get("serve.cache.miss")
        assert sum(s["size"] for s in stats) == len(cache) == 32
        # keys spread over more than one shard (crc32 on this keyset)
        assert sum(1 for s in stats if s["size"]) > 1

    def test_global_lru_bound_under_concurrent_fill(self):
        """8 threads racing puts through different shards must never
        leave the cache over its GLOBAL capacity."""
        import threading as _threading

        c = Counters()
        cache = PlanCache(capacity=32, counters=c, shards=4)

        def _fill(tid: int) -> None:
            for i in range(100):
                cache.put(f"t{tid}-k{i}", {"t": tid, "i": i})
                cache.get(f"t{tid}-k{i % 7}")

        threads = [_threading.Thread(target=_fill, args=(t,))
                   for t in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(cache) <= 32
        assert sum(s["size"] for s in cache.shard_stats()) == len(cache)
        # accounting reconciles after the race: inserts - evictions -
        # invalidations == residents
        assert 8 * 100 - c.get("serve.cache.evict") == len(cache)

    def test_invalidate_where_visits_every_shard(self):
        cache = PlanCache(capacity=256, counters=Counters(), shards=8)
        for i in range(64):
            cache.put(f"k{i}", {"doomed": i % 2 == 0})
        populated = sum(1 for s in cache.shard_stats() if s["size"])
        assert populated == 8  # every shard holds keys on this keyset
        dropped = cache.invalidate_where(lambda _k, v: v["doomed"])
        assert len(dropped) == 32
        assert len(cache) == 32
        for k in dropped:
            assert k not in cache

    def test_single_shard_export_matches_pre_shard_semantics(self):
        """shards=1 must dump byte-identically to the pre-shard cache:
        items()/keys() in exact LRU order for the same op sequence, and
        any shard count reproduces the same global order."""
        def _ops(cache):
            for i in range(6):
                cache.put(f"k{i}", {"v": i})
            cache.get("k1")
            cache.put("k2", {"v": 22})  # refresh
            cache.get("k0")
            cache.invalidate("k3")
            return cache

        # the pre-shard implementation was one OrderedDict with
        # move_to_end on access — its export order for this op sequence:
        expected_keys = ["k4", "k5", "k1", "k2", "k0"]
        one = _ops(PlanCache(capacity=16, shards=1))
        assert one.keys() == expected_keys
        dump_one = json.dumps(one.items())
        many = _ops(PlanCache(capacity=16, shards=4))
        assert json.dumps(many.items()) == dump_one
        # restore round-trip: re-putting the export into a different
        # shard count reproduces contents AND eviction order
        restored = PlanCache(capacity=16, shards=2)
        for k, payload in one.items():
            restored.put(k, payload)
        assert restored.keys() == expected_keys
        assert json.dumps(restored.items()) == dump_one

    def test_get_with_body_pre_encoded_bytes(self):
        cache = PlanCache(capacity=4, counters=Counters())
        payload = {"plans": "x" * 50, "best_cost_ms": 1.25}
        cache.put("a", payload)
        got, body = cache.get_with_body("a")
        assert got == payload
        assert body == json.dumps(payload).encode("utf-8")
        # unserializable payloads carry no body; the parsed form works
        cache.put("b", {"bad": object()})
        got_b, body_b = cache.get_with_body("b")
        assert body_b is None and got_b["bad"] is not None

    def test_rejects_nonpositive_shards(self):
        with pytest.raises(ValueError):
            PlanCache(capacity=4, shards=0)


# ---------------------------------------------------------------------------
# zero-copy encoded responses
# ---------------------------------------------------------------------------


class TestEncodedResponses:
    def test_encoded_hit_byte_identical_to_dumps_of_plan_query(
            self, small_workload, service):
        """The spliced cache-hit bytes must be EXACTLY what
        ``json.dumps(plan_query(...))`` would have produced — proven by
        round-tripping: parse the bytes, re-dumps, compare bytes (key
        order and float repr both survive), then compare the parsed dict
        field-by-field against the classic path modulo serve_ms."""
        _, _, model, config = small_workload
        cold_bytes = service.plan_query_encoded(model, config, top_k=5)
        cold = json.loads(cold_bytes)
        assert cold["cached"] is False
        assert json.dumps(cold).encode("utf-8") == cold_bytes

        hit_bytes = service.plan_query_encoded(model, config, top_k=5)
        hit = json.loads(hit_bytes)
        assert hit["cached"] is True
        assert json.dumps(hit).encode("utf-8") == hit_bytes

        plain = service.plan_query(model, config, top_k=5)
        assert set(plain) == set(hit)
        for k in plain:
            if k != "serve_ms":
                assert plain[k] == hit[k], f"field {k} differs"

    def test_encoded_with_trace_id_and_tail_order(self, small_workload,
                                                  service):
        _, _, model, config = small_workload
        service.plan_query(model, config, top_k=5)  # prime
        body = service.plan_query_encoded(model, config, top_k=5,
                                          trace_id="t-123")
        parsed = json.loads(body)
        assert parsed["trace_id"] == "t-123"
        assert json.dumps(parsed).encode("utf-8") == body
        # tail keys land last, in insertion order, like _respond's dict
        assert list(parsed)[-3:] == ["cached", "serve_ms", "trace_id"]

    def test_tail_keys_never_collide_with_entries(self, small_workload,
                                                  service):
        """The splice is only sound while cache entries never contain the
        tail keys — pin that invariant on a real entry."""
        _, _, model, config = small_workload
        service.plan_query(model, config, top_k=5)
        key = next(iter(service.cache.keys()))
        entry = service.cache.get(key)
        assert not {"cached", "serve_ms", "trace_id"} & set(entry)


# ---------------------------------------------------------------------------
# keep-alive transport + bounded worker pool
# ---------------------------------------------------------------------------


@pytest.fixture()
def http_service(small_workload):
    """Service + live TCP server; yields (service, server, host, port)."""
    from metis_tpu.serve.daemon import PlanService, serve_in_thread

    cluster, profiles, _model, _config = small_workload
    service = PlanService(cluster, profiles, drift_min_samples=5)
    server, thread, address = serve_in_thread(service)
    host, port = address[len("http://"):].rsplit(":", 1)
    yield service, server, host, int(port)
    server.shutdown()
    thread.join(10)
    server.server_close()


class TestKeepAliveTransport:
    def test_connection_reuse_over_one_socket(self, http_service):
        import http.client as hc

        service, _server, host, port = http_service
        conn = hc.HTTPConnection(host, port, timeout=10)
        try:
            for i in range(3):
                conn.request("GET", "/stats")
                resp = conn.getresponse()
                body = resp.read()
                assert resp.status == 200
                assert not resp.will_close, (
                    f"server closed the keep-alive connection on "
                    f"request {i + 1}")
                assert json.loads(body)["cluster_devices"] == 8
        finally:
            conn.close()
        reuse = service.metrics.counter(
            "metis_serve_keepalive_reuse_total").value
        assert reuse >= 2

    def test_pool_metrics_exported(self, http_service):
        service, server, _host, _port = http_service
        text = service.render_metrics()
        assert f"metis_serve_pool_threads {server.pool_threads}" in text
        assert "metis_serve_pool_backlog" in text

    def test_overload_sheds_with_503_retry_after(self, small_workload):
        """threads=1 + backlog=1: with the lone worker parked on a
        long-poll, the next connection queues and the one after that
        must get an immediate 503 + Retry-After + Connection: close."""
        import http.client as hc

        from metis_tpu.serve.daemon import (_Handler, _TCPServer,
                                            PlanService)

        cluster, profiles, _model, _config = small_workload
        service = PlanService(cluster, profiles)
        server = _TCPServer(("127.0.0.1", 0), _Handler)
        server.service = service
        server.pool_backlog = 1
        server.init_pool(threads=1)
        host, port = server.server_address[:2]
        import threading as _threading
        thread = _threading.Thread(target=server.serve_forever,
                                   daemon=True)
        thread.start()
        conns = []
        try:
            # park the only worker on a long-poll
            busy = hc.HTTPConnection(host, port, timeout=30)
            conns.append(busy)
            busy.request("GET", "/notifications?timeout=8")
            time.sleep(0.3)  # let the worker pick it up
            # fill the backlog (accepted, never served while parked)
            filler = hc.HTTPConnection(host, port, timeout=30)
            conns.append(filler)
            filler.request("GET", "/stats")
            time.sleep(0.2)
            # overload: must be shed, not queued
            shed = hc.HTTPConnection(host, port, timeout=10)
            conns.append(shed)
            shed.request("GET", "/stats")
            resp = shed.getresponse()
            assert resp.status == 503
            assert resp.getheader("Retry-After") == "1"
            assert resp.getheader("Connection") == "close"
            body = json.loads(resp.read())
            assert "overloaded" in body["error"]
            assert service.counters.get("serve.overload") >= 1
            assert service.metrics.counter(
                "metis_serve_overload_total").value >= 1
        finally:
            for c in conns:
                c.close()
            server.shutdown()
            thread.join(10)
            server.server_close()


class TestClientConnectionPool:
    def test_pool_reuses_sockets(self, http_service):
        from metis_tpu.serve.client import PlanServiceClient

        _service, _server, host, port = http_service
        with PlanServiceClient(f"http://{host}:{port}") as client:
            for _ in range(4):
                client.stats()
            ps = client.pool_stats()
            assert ps["opened"] == 1
            assert ps["reused"] == 3
            assert ps["idle"] == 1

    def test_reconnects_when_pooled_socket_dies(self, http_service):
        """A pooled socket the daemon closed between requests must be
        retried transparently on a fresh connection (idempotent
        endpoints), not surfaced as an error."""
        import socket as _socket

        from metis_tpu.serve.client import PlanServiceClient

        _service, _server, host, port = http_service
        with PlanServiceClient(f"http://{host}:{port}") as client:
            client.stats()
            # simulate a server-side idle close of the pooled socket
            with client._pool_lock:
                stale = client._idle[0][0]
            stale.sock.shutdown(_socket.SHUT_RDWR)
            out = client.stats()
            assert out["cluster_devices"] == 8
            assert client.pool_stats()["opened"] == 2

    def test_long_poll_and_monitoring_get_dedicated_sockets(
            self, http_service):
        from metis_tpu.serve.client import PlanServiceClient

        _service, _server, host, port = http_service
        with PlanServiceClient(f"http://{host}:{port}") as client:
            assert client.healthz()["live"] is True
            assert "metis_serve_requests_total" in client.metrics()
            client.notifications(since=0, timeout_s=0.0)
            # none of those went through (or into) the pool
            ps = client.pool_stats()
            assert ps == {"opened": 0, "reused": 0, "idle": 0}

    def test_pooling_can_be_disabled(self, http_service):
        from metis_tpu.serve.client import PlanServiceClient

        _service, _server, host, port = http_service
        client = PlanServiceClient(f"http://{host}:{port}",
                                   pool_connections=False)
        client.stats()
        client.stats()
        assert client.pool_stats() == {"opened": 0, "reused": 0,
                                       "idle": 0}
