"""PlanArtifact -> executable routing: every plan an artifact can describe
must reach an execution path that realizes it (ADVICE r1 medium: ZeRO plans
previously existed only in the cost model)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from metis_tpu.core.types import UniformPlan
from metis_tpu.execution import PlanArtifact, build_train_state
from metis_tpu.execution.builder import build_executable
from metis_tpu.execution.mesh import DP, PP, SP, TP, mesh_dp_tp
from metis_tpu.models.gpt import GPTConfig

CFG = GPTConfig(vocab_size=256, seq_len=16, hidden=64, num_heads=4,
                num_blocks=4, ffn_multiplier=2, dtype=jnp.float32)


def _train_two_steps(exe, gbs: int):
    state = exe.init(jax.random.PRNGKey(0))
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (gbs, CFG.seq_len), 0, CFG.vocab_size)
    state, first = exe.step(state, tokens, tokens)
    state, second = exe.step(state, tokens, tokens)
    return float(first), float(second)


class TestRouting:
    def test_pp1_routes_gspmd(self):
        art = PlanArtifact.from_uniform_plan(
            UniformPlan(dp=4, pp=1, tp=2, mbs=2, gbs=8))
        exe = build_executable(CFG, art)
        assert exe.kind == "gspmd"
        first, second = _train_two_steps(exe, art.gbs)
        assert np.isfinite(first) and second < first

    def test_pp2_uniform_routes_pipeline(self):
        art = PlanArtifact.from_uniform_plan(
            UniformPlan(dp=2, pp=2, tp=2, mbs=2, gbs=8))
        exe = build_executable(CFG, art)
        assert exe.kind == "pipeline"
        first, second = _train_two_steps(exe, art.gbs)
        assert np.isfinite(first) and second < first

    def test_pp2_1f1b_schedule_trains(self):
        """schedule="1f1b" rides the same pipeline route and trains."""
        art = PlanArtifact.from_uniform_plan(
            UniformPlan(dp=2, pp=2, tp=2, mbs=2, gbs=8))
        exe = build_executable(CFG, art, schedule="1f1b")
        assert exe.kind == "pipeline"
        first, second = _train_two_steps(exe, art.gbs)
        assert np.isfinite(first) and second < first

    def test_uneven_1f1b_partition_routes_pipeline(self):
        """A 1f1b artifact whose layer partition gives stages UNEVEN block
        counts still routes to the shard_map pipeline (padded masked
        layers), not the hetero executor — the hetero path would silently
        run a gpipe-shaped schedule instead of the priced 1f1b."""
        cfg = GPTConfig(vocab_size=256, seq_len=16, hidden=64, num_heads=4,
                        num_blocks=3, ffn_multiplier=2, dtype=jnp.float32)
        # profile layers: embed + 3 blocks + head = 5; bounds (0, 3, 5)
        # give stage0 [embed, b0, b1] and stage1 [b2, head]: blocks (2, 1)
        art = PlanArtifact(
            mesh_axes=("pp", "dp", "tp"), mesh_shape=(2, 2, 1),
            layer_partition=(0, 3, 5),
            strategies=({"dp": 2, "tp": 1},),
            gbs=8, microbatches=2, schedule="1f1b")
        exe = build_executable(cfg, art)
        assert exe.kind == "pipeline"
        state = exe.init(jax.random.PRNGKey(0))
        tokens = jax.random.randint(
            jax.random.PRNGKey(1), (8, cfg.seq_len), 0, cfg.vocab_size)
        state, first = exe.step(state, tokens, tokens)
        state, second = exe.step(state, tokens, tokens)
        assert np.isfinite(float(first)) and float(second) < float(first)

    def test_uneven_gpipe_partition_still_routes_hetero(self):
        """The same uneven partition WITHOUT the 1f1b tag keeps its
        existing multi-mesh route (per-stage programs realize it natively)."""
        cfg = GPTConfig(vocab_size=256, seq_len=16, hidden=64, num_heads=4,
                        num_blocks=3, ffn_multiplier=2, dtype=jnp.float32)
        art = PlanArtifact(
            mesh_axes=("pp", "dp", "tp"), mesh_shape=(2, 2, 1),
            layer_partition=(0, 3, 5),
            strategies=({"dp": 2, "tp": 1},),
            gbs=8, microbatches=2)
        exe = build_executable(cfg, art)
        assert exe.kind == "hetero"

    def test_pp2_interleaved_schedule_trains(self):
        """schedule="interleaved" rides the pipeline route (CFG: 4 blocks =
        2 stages x 2 virtual chunks) and trains."""
        art = PlanArtifact.from_uniform_plan(
            UniformPlan(dp=2, pp=2, tp=2, mbs=2, gbs=8))
        exe = build_executable(CFG, art, schedule="interleaved",
                               virtual_stages=2)
        assert exe.kind == "pipeline"
        first, second = _train_two_steps(exe, art.gbs)
        assert np.isfinite(first) and second < first

    def test_pp2_with_zero_routes_hetero(self):
        """ZeRO under pipelining: the per-stage GSPMD executor delivers the
        state sharding the cost model credits (ADVICE r1 medium)."""
        art = PlanArtifact(
            mesh_axes=(PP, DP, TP), mesh_shape=(2, 2, 2),
            layer_partition=(), strategies=({"dp": 2, "tp": 2, "zero": 1},),
            gbs=8, microbatches=2)
        exe = build_executable(CFG, art)
        assert exe.kind == "hetero"
        first, second = _train_two_steps(exe, art.gbs)
        assert np.isfinite(first) and second < first

    def test_nonuniform_routes_hetero(self):
        art = PlanArtifact(
            mesh_axes=(), mesh_shape=(), layer_partition=(0, 2, 6),
            strategies=({"dp": 2, "tp": 2}, {"dp": 4, "tp": 1}),
            gbs=8, microbatches=2)
        exe = build_executable(CFG, art)
        assert exe.kind == "hetero"
        first, second = _train_two_steps(exe, art.gbs)
        assert np.isfinite(first) and second < first

    def test_cp_under_pp_routes_hetero(self):
        art = PlanArtifact(
            mesh_axes=(), mesh_shape=(), layer_partition=(0, 2, 6),
            strategies=({"dp": 2, "tp": 1, "cp": 2}, {"dp": 4, "tp": 1}),
            gbs=8, microbatches=2)
        exe = build_executable(CFG, art)
        assert exe.kind == "hetero"
        state = exe.init(jax.random.PRNGKey(0))
        toks = jax.random.randint(
            jax.random.PRNGKey(1), (8, CFG.seq_len), 0, CFG.vocab_size)
        _, loss = exe.step(state, toks, toks)
        assert np.isfinite(loss)

    def test_cp_plan_routes_gspmd_with_ring_attention(self):
        art = PlanArtifact(
            mesh_axes=(PP, DP, "ep", SP, TP), mesh_shape=(1, 2, 1, 2, 2),
            layer_partition=(),
            strategies=({"dp": 2, "tp": 2, "cp": 2, "ep": 1},),
            gbs=4, microbatches=1)
        exe = build_executable(CFG, art)
        assert exe.kind == "gspmd"
        first, second = _train_two_steps(exe, art.gbs)
        assert np.isfinite(first) and second < first


    def test_a2a_cp_plan_routes_gspmd_with_ulysses(self):
        """cp_mode="a2a" in the artifact reaches the Ulysses attention path
        and still trains."""
        art = PlanArtifact(
            mesh_axes=(PP, DP, "ep", SP, TP), mesh_shape=(1, 2, 1, 2, 2),
            layer_partition=(),
            strategies=(
                {"dp": 2, "tp": 2, "cp": 2, "ep": 1, "cp_mode": "a2a"},),
            gbs=4, microbatches=1)
        exe = build_executable(CFG, art)
        assert exe.kind == "gspmd"
        first, second = _train_two_steps(exe, art.gbs)
        assert np.isfinite(first) and second < first


class TestZeroStateSharding:
    def test_zero1_shards_opt_state_not_params(self):
        mesh = mesh_dp_tp(4, 2, jax.devices()[:8])
        state, _ = build_train_state(
            jax.random.PRNGKey(0), CFG, mesh, zero=1)
        # params replicated over dp (tp sharding only)
        tok_sharding = state.params["embed"]["tok"].sharding.spec
        assert DP not in jax.tree.leaves(tuple(tok_sharding))
        # adam moments shard over dp
        mu = state.opt_state[0].mu
        mu_specs = jax.tree.leaves(
            jax.tree.map(lambda x: x.sharding.spec, mu),
            is_leaf=lambda x: hasattr(x, "index") or x is None)
        flat = [ax for spec in mu_specs if spec is not None
                for ax in spec if ax is not None]
        assert DP in flat, f"no dp sharding in opt state: {mu_specs}"

    def test_zero3_shards_params_too(self):
        mesh = mesh_dp_tp(4, 2, jax.devices()[:8])
        state, specs = build_train_state(
            jax.random.PRNGKey(0), CFG, mesh, zero=3)
        flat = [ax for spec in jax.tree.leaves(specs)
                for ax in spec if ax is not None]
        assert DP in flat

    def test_zero1_training_matches_zero0(self):
        mesh = mesh_dp_tp(4, 2, jax.devices()[:8])
        tokens = jax.random.randint(
            jax.random.PRNGKey(1), (8, CFG.seq_len), 0, CFG.vocab_size)

        losses = {}
        for zero in (0, 1):
            from metis_tpu.execution import make_train_step

            state, _ = build_train_state(
                jax.random.PRNGKey(0), CFG, mesh, zero=zero)
            step = make_train_step(CFG, mesh)
            out = []
            for _ in range(2):
                state, loss = step(state, tokens, tokens)
                out.append(float(loss))
            losses[zero] = out
        np.testing.assert_allclose(losses[0], losses[1], rtol=1e-5, atol=1e-5)
