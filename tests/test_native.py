"""Differential tests: the C++ minmax-partition kernel against the pure
Python DP it replaces (metis_tpu.balance.layers.minmax_partition)."""
import numpy as np
import pytest

from metis_tpu.balance.layers import minmax_partition
from metis_tpu.native import minmax_partition_native, native_available

pytestmark = pytest.mark.skipif(
    not native_available(), reason="no C++ toolchain")


def _prefix(w):
    return np.concatenate(([0.0], np.cumsum(np.asarray(w, np.float64))))


def test_unconstrained_matches_python_randomized():
    rng = np.random.default_rng(0)
    for trial in range(200):
        L = int(rng.integers(2, 14))
        S = int(rng.integers(1, min(L, 8) + 1))
        w = rng.uniform(0.1, 5.0, L)
        perf = rng.uniform(0.2, 4.0, S)
        want = minmax_partition(tuple(w), tuple(perf))
        got = minmax_partition_native(_prefix(w), perf)
        assert got == want, f"trial {trial}: {got} != {want}"


def test_constrained_matches_python_randomized():
    rng = np.random.default_rng(1)
    for trial in range(200):
        L = int(rng.integers(2, 12))
        S = int(rng.integers(1, min(L, 6) + 1))
        w = rng.uniform(0.1, 5.0, L)
        perf = rng.uniform(0.2, 4.0, S)
        mem = rng.uniform(0.5, 3.0, (S, L))
        cap = rng.uniform(1.0, 2.5, S) * L / S
        coef = 5.0
        mem_prefix = np.concatenate(
            [np.zeros((S, 1)), np.cumsum(mem, axis=1)], axis=1)
        demand_mat = 0.001 + coef * (
            mem_prefix[:, None, :] - mem_prefix[:, :, None])
        feasible = demand_mat <= cap[:, None, None]
        want = minmax_partition(tuple(w), tuple(perf), feasible)
        got = minmax_partition_native(_prefix(w), perf, mem_prefix, cap,
                                      coef=coef)
        assert got == want, f"trial {trial}: {got} != {want}"


def test_zero_performance_stage():
    w = [1.0, 1.0, 1.0, 1.0]
    assert minmax_partition_native(_prefix(w), [1.0, 0.0]) == \
        minmax_partition(w, [1.0, 0.0])


def test_more_stages_than_layers():
    assert minmax_partition_native(_prefix([1.0]), [1.0, 1.0]) is None


def test_planner_end_to_end_native_vs_python(monkeypatch, tmp_path):
    """Full hetero search result must be identical with the native DP off."""
    from metis_tpu.cluster import ClusterSpec
    from metis_tpu.core.config import SearchConfig
    from metis_tpu.planner import plan_hetero
    from metis_tpu.profiles import synthesize_profiles, tiny_test_model
    import metis_tpu.balance.layers as layers_mod

    model = tiny_test_model()
    store = synthesize_profiles(model, ["A100", "T4"], tps=[1, 2],
                                bss=[1, 2, 4, 8])
    cluster = ClusterSpec.of(("A100", 1, 4), ("T4", 1, 4))
    cfg = SearchConfig(gbs=32)

    with_native = plan_hetero(cluster, store, model, cfg)
    monkeypatch.setattr(layers_mod, "native_available", lambda: False)
    without = plan_hetero(cluster, store, model, cfg)

    assert with_native.num_costed == without.num_costed
    for a, b in zip(with_native.plans, without.plans):
        assert a.inter == b.inter
        assert a.intra.strategies == b.intra.strategies
        assert a.intra.layer_partition == b.intra.layer_partition
        assert a.cost.total_ms == pytest.approx(b.cost.total_ms)
