"""Exact branch-and-bound backend (search/exact.py, backend="exact").

Property tests: the exact backend's certified best must never be worse
than the beam's on any workload (it explores a superset of the beam's
candidate space through an admissible relaxation); a complete certificate
must have gap 0 and prove the beam optimal whenever the beam found the
same cost; the tightened relaxation bound handed to the default beam must
leave its ranking byte-identical (serial AND parallel); and the backend
must compose with the spot/migration pricing models and symmetry
collapse.
"""
import dataclasses
import io
import json

import pytest

from metis_tpu.cluster.spec import ClusterSpec, DeviceSpec, NodeSpec
from metis_tpu.core.config import ModelSpec, SearchConfig
from metis_tpu.core.events import EventLog
from metis_tpu.core.types import dump_ranked_plans
from metis_tpu.planner import plan_hetero
from metis_tpu.profiles import synthesize_profiles


def _make_workload(num_layers, hidden, types, nodes_per_type, per_node):
    model = ModelSpec(name=f"exact-wl-{num_layers}-{hidden}",
                      num_layers=num_layers, hidden_size=hidden,
                      sequence_length=256, vocab_size=8192, num_heads=8)
    store = synthesize_profiles(model, types, tps=[1, 2, 4],
                                bss=[1, 2, 4, 8, 16, 32])
    specs = {"A100": DeviceSpec("A100", 80, 100, 25),
             "T4": DeviceSpec("T4", 15, 50, 10)}
    node_list = []
    for t in types:
        node_list.extend(NodeSpec(t, per_node) for _ in range(nodes_per_type))
    cluster = ClusterSpec(nodes=tuple(node_list),
                          devices={t: specs[t] for t in types})
    return model, store, cluster


# uniform and hetero shapes with varied model/batch geometry — the
# property (exact <= beam, certified gap 0 on completion) must hold on
# all of them, not just the frozen parity fixture
WORKLOADS = [
    pytest.param((6, 256, ["A100"], 2, 4), 64, id="uniform-6L"),
    pytest.param((10, 512, ["A100"], 2, 4), 128, id="uniform-10L"),
    pytest.param((8, 256, ["A100", "T4"], 1, 4), 64, id="hetero-8L"),
    pytest.param((10, 512, ["A100", "T4"], 2, 4), 128, id="hetero-10L"),
]


@pytest.mark.parametrize("shape,gbs", WORKLOADS)
def test_exact_never_worse_than_beam(shape, gbs):
    model, store, cluster = _make_workload(*shape)
    beam = plan_hetero(cluster, store, model,
                       SearchConfig(gbs=gbs, prune_to_top_k=10), top_k=5)
    exact = plan_hetero(cluster, store, model,
                        SearchConfig(gbs=gbs, backend="exact"), top_k=5)
    cert = exact.certificate
    assert cert is not None
    assert exact.best is not None
    assert exact.best.cost.total_ms <= beam.best.cost.total_ms + 1e-9
    # a certificate's bound must never exceed the cost it certifies
    assert cert.lower_bound_ms <= cert.best_ms + 1e-9
    assert cert.best_ms == pytest.approx(exact.best.cost.total_ms)


@pytest.mark.parametrize("shape,gbs", WORKLOADS)
def test_complete_certificate_has_zero_gap(shape, gbs):
    """No deadline => the branch-and-bound runs to completion, and a
    complete certificate is by definition gap 0: the incumbent IS the
    proven optimum.  When the beam lands on the same cost, the
    certificate proves the beam optimal on that workload."""
    model, store, cluster = _make_workload(*shape)
    exact = plan_hetero(cluster, store, model,
                        SearchConfig(gbs=gbs, backend="exact"))
    cert = exact.certificate
    assert cert.complete
    assert cert.gap_frac == 0.0
    assert cert.lower_bound_ms == pytest.approx(cert.best_ms)
    beam = plan_hetero(cluster, store, model,
                       SearchConfig(gbs=gbs, prune_to_top_k=10))
    if beam.best.cost.total_ms == pytest.approx(cert.best_ms):
        # beam found the certified optimum: gap between them is exactly 0
        assert abs(beam.best.cost.total_ms - cert.lower_bound_ms) < 1e-6


@pytest.mark.parametrize("workers", [1, 2])
def test_tight_bound_keeps_beam_ranking_byte_identical(workers):
    """The exact backend's relaxation bound rides the default beam as an
    extra admit filter (SearchConfig.tight_bound) — admissibility means
    it may only drop candidates that provably cannot reach the top-K, so
    the ranking must stay byte-for-byte what the stock bound produced,
    serial and parallel alike."""
    model, store, cluster = _make_workload(10, 512, ["A100", "T4"], 2, 4)
    base = SearchConfig(gbs=128, prune_to_top_k=10, workers=workers)
    stock = plan_hetero(cluster, store, model,
                        dataclasses.replace(base, tight_bound=False),
                        top_k=10)
    tight = plan_hetero(cluster, store, model, base, top_k=10)
    assert dump_ranked_plans(tight.plans) == dump_ranked_plans(stock.plans)
    # the tight bound only ever ADDS prunes on top of the stock bound
    assert tight.num_bound_pruned >= stock.num_bound_pruned


def test_exact_composes_with_spot_and_migration_models():
    model, store, cluster = _make_workload(8, 256, ["A100", "T4"], 1, 4)
    for extra in ({"use_spot_model": True},
                  {"migrate_from": ((1, 0, 4),)}):
        beam = plan_hetero(cluster, store, model,
                           SearchConfig(gbs=64, **extra))
        exact = plan_hetero(
            cluster, store, model,
            SearchConfig(gbs=64, backend="exact", **extra))
        cert = exact.certificate
        assert cert is not None and cert.complete
        # availability/migration pricing is part of the objective the
        # certificate covers — the certified best must match exhaustive
        assert exact.best.cost.total_ms == pytest.approx(
            beam.best.cost.total_ms)


def test_exact_composes_with_symmetry_collapse():
    """symmetry_collapse touches the BEAM's candidate replay, not the
    exact enumeration — backend="exact" must return the same certificate
    either way."""
    model, store, cluster = _make_workload(8, 256, ["A100", "T4"], 1, 4)
    on = plan_hetero(cluster, store, model,
                     SearchConfig(gbs=64, backend="exact",
                                  symmetry_collapse=True))
    off = plan_hetero(cluster, store, model,
                      SearchConfig(gbs=64, backend="exact",
                                   symmetry_collapse=False))
    assert on.certificate.best_ms == pytest.approx(off.certificate.best_ms)
    assert on.certificate.complete and off.certificate.complete


def test_exact_emits_certificate_event():
    model, store, cluster = _make_workload(6, 256, ["A100"], 2, 4)
    stream = io.StringIO()
    res = plan_hetero(cluster, store, model,
                      SearchConfig(gbs=64, backend="exact"),
                      events=EventLog(stream=stream))
    events = [json.loads(l) for l in stream.getvalue().splitlines()]
    certs = [e for e in events if e["event"] == "certificate"]
    assert len(certs) == 1
    assert certs[0]["best_ms"] == pytest.approx(res.certificate.best_ms)
    assert certs[0]["gap_frac"] == res.certificate.gap_frac
    assert any(e["event"] == "bnb_progress" for e in events)


def test_deadline_stop_is_honest():
    """An exhausted deadline must yield complete=False with a gap bound
    derived from the unexplored frontier — never a fake gap-0 claim."""
    model, store, cluster = _make_workload(10, 512, ["A100", "T4"], 2, 4)
    res = plan_hetero(cluster, store, model,
                      SearchConfig(gbs=128, backend="exact",
                                   exact_deadline_s=0.0))
    cert = res.certificate
    if cert is None:
        # zero budget can stop before the first node is costed: no
        # incumbent means no certificate — and no plans, not a fake one
        assert res.best is None
        return
    if not cert.complete:
        assert cert.gap_frac >= 0.0
        assert cert.lower_bound_ms <= cert.best_ms + 1e-9


def test_unknown_backend_raises():
    with pytest.raises(ValueError, match="backend"):
        SearchConfig(gbs=64, backend="bogus")


def test_negative_deadline_raises():
    with pytest.raises(ValueError, match="exact_deadline_s"):
        SearchConfig(gbs=64, backend="exact", exact_deadline_s=-1.0)
