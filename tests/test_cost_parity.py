"""Differential cost-model parity against the upstream reference.

The reference's shipped golden logs (``results/hetero_cost_model``) were
produced with T4 profiles that were never released (only A100 fixtures ship),
so exact golden-number reproduction is impossible from shipped data.  Instead
we run the reference planner **in-process** on our synthetic two-type profile
set and assert that our strict-compat estimator reproduces every cost the
reference computes, plan by plan.  This is strictly stronger than a static
golden file: it covers the full plan set, with our fixtures, on every run.
"""
import argparse
import contextlib
import io
import sys

import pytest

from metis_tpu.cluster import ClusterSpec
from metis_tpu.core.config import SearchConfig
from metis_tpu.core.types import InterStagePlan, Strategy
from metis_tpu.cost import (
    EstimatorOptions,
    HeteroCostEstimator,
    TransformerVolume,
    UniformCostEstimator,
)
from metis_tpu.core.types import UniformPlan
from metis_tpu.profiles import ProfileStore, synthesize_profiles, tiny_test_model

GBS = 128
MAX_TP = 4
MAX_BS = 16


@pytest.fixture(scope="module")
def fixture_dir(tmp_path_factory):
    """Synthetic A100+T4 profiles dumped in reference schema + cluster files
    mirroring the golden-run topology (8xA100 + 8xT4, 4 per node)."""
    d = tmp_path_factory.mktemp("parity")
    profiles = synthesize_profiles(
        tiny_test_model(), ["A100", "T4"], tps=[1, 2, 4], bss=[1, 2, 4, 8, 16])
    profiles.dump_to_dir(d / "profiles")
    (d / "hostfile").write_text(
        "0.0.0.3 slots=4\n0.0.0.3 slots=4\n0.0.0.4 slots=4\n0.0.0.4 slots=4\n"
        .replace("0.0.0.3 slots=4\n0.0.0.3", "0.0.0.3 slots=4\n0.0.0.5"))
    # two T4 nodes (distinct ips share a type), two A100 nodes
    (d / "hostfile").write_text(
        "0.0.0.3 slots=4\n0.0.0.5 slots=4\n0.0.0.4 slots=4\n0.0.0.6 slots=4\n")
    (d / "clusterfile.json").write_text("""{
        "0.0.0.3": {"instance_type": "T4", "inter_bandwidth": 10,
                    "intra_bandwidth": 50, "memory": 15},
        "0.0.0.5": {"instance_type": "T4", "inter_bandwidth": 10,
                    "intra_bandwidth": 50, "memory": 15},
        "0.0.0.4": {"instance_type": "A100", "inter_bandwidth": 10,
                    "intra_bandwidth": 46, "memory": 80},
        "0.0.0.6": {"instance_type": "A100", "inter_bandwidth": 10,
                    "intra_bandwidth": 46, "memory": 80}
    }""")
    return d


@pytest.fixture(scope="module")
def reference_run(reference_root, fixture_dir):
    """Run the reference hetero planner end-to-end, in-process, capturing
    every costed (plan, strategies, partition, cost)."""
    sys.path.insert(0, str(reference_root))
    argv_backup = sys.argv
    # the reference re-parses argv deep inside the cost loop
    # (cost_estimator.py:154) — feed it the knobs it expects
    sys.argv = ["prog", "--max_profiled_batch_size", str(MAX_BS),
                "--max_profiled_tp_degree", str(MAX_TP)]
    try:
        import cost_het_cluster as ref_main
        from data_loader import ProfileDataLoader
        from gpu_cluster import GPUCluster
        from model.cost_estimator import HeteroCostEstimator as RefHetero
        from model.activation_parameter import GPTActivationAndParam
        from model.load_balancer import LayerLoadBalancer
        from utils import ModelConfig as RefModelConfig

        gpu_cluster = GPUCluster(
            hostfile_path=str(fixture_dir / "hostfile"),
            clusterfile_path=str(fixture_dir / "clusterfile.json"))
        profile_data, _ = ProfileDataLoader(str(fixture_dir / "profiles")).load_profile_data_all()
        m = tiny_test_model()
        model_config = RefModelConfig(
            model_name=m.name, num_layers=m.num_layers,
            sequence_length=m.sequence_length, vocab_size=m.vocab_size,
            hidden_size=m.hidden_size, attention_head_size=m.num_heads)
        model_volume = GPTActivationAndParam(
            model_config, profile_data["model"]["parameters"])
        estimator = RefHetero(profile_data, model_config, model_volume, gpu_cluster)
        balancer = LayerLoadBalancer(gpu_cluster, profile_data, model_config, GBS)
        args = argparse.Namespace(
            gbs=GBS, num_layers=m.num_layers,
            max_profiled_tp_degree=MAX_TP, max_profiled_batch_size=MAX_BS,
            min_group_scale_variance=1, max_permute_len=6)
        with contextlib.redirect_stdout(io.StringIO()):
            costs = ref_main.cost_het_cluster(
                args, gpu_cluster, profile_data, model_config, estimator, balancer)
        return {
            "costs": costs,
            "profile_data": profile_data,
            "model_volume": model_volume,
            "model_config": model_config,
            "gpu_cluster": gpu_cluster,
            "estimator": estimator,
        }
    finally:
        sys.argv = argv_backup
        sys.path.remove(str(reference_root))


@pytest.fixture(scope="module")
def ours(fixture_dir):
    cluster = ClusterSpec.from_files(
        fixture_dir / "hostfile", fixture_dir / "clusterfile.json")
    profiles = ProfileStore.from_dir(fixture_dir / "profiles")
    volume = TransformerVolume(tiny_test_model(), profiles.model.params_per_layer_bytes)
    options = EstimatorOptions(strict_compat=True, max_profiled_bs=MAX_BS)
    return {
        "cluster": cluster,
        "profiles": profiles,
        "volume": volume,
        "hetero": HeteroCostEstimator(cluster, profiles, volume, options),
        "uniform": UniformCostEstimator(cluster, profiles, volume, options),
    }


def test_reference_run_is_nontrivial(reference_run):
    assert len(reference_run["costs"]) > 100


def test_hetero_estimator_full_parity(reference_run, ours, reference_root):
    """Every candidate the reference's search visited, our strict-compat
    estimator must cost identically (rel tol 1e-9) to a *direct* reference
    evaluation of that candidate.

    Direct evaluation, not the loop-recorded cost, because of an upstream
    state-corruption bug: after a node-sequence advance the reference's
    generator leaves ``curr.num_stage`` at 1 while ``device_groups`` already
    holds multi-stage arrangements (``_find_next_node_sequence`` discards the
    stage count, ``plan.py:144-148``), so the first few recorded costs after
    each advance were computed over stage 0 only.  Direct evaluation with a
    consistent plan object is the reference's intended semantics.
    """
    sys.path.insert(0, str(reference_root))
    argv_backup = sys.argv
    # the reference re-parses argv inside its hetero execution path
    # (cost_estimator.py:154)
    sys.argv = ["prog", "--max_profiled_batch_size", str(MAX_BS),
                "--max_profiled_tp_degree", str(MAX_TP)]
    try:
        from search_space.plan import InterStagePlan as RefISP
        from model.device_group import StagePerformance

        est = ours["hetero"]
        ref_est = reference_run["estimator"]
        mc = reference_run["model_config"]
        gpu_cluster = reference_run["gpu_cluster"]
        profile_data = reference_run["profile_data"]
        mismatches = []
        corrupted = 0
        for (node_seq, device_groups, strategies, batches, partition,
             _nrep, recorded_cost) in reference_run["costs"]:
            ref_plan = RefISP(
                ns_idx=0, node_sequence=list(node_seq), dg_idx=0,
                device_groups=list(device_groups),
                num_stage=len(device_groups), batches=batches, gbs=GBS)
            sp = StagePerformance(mc, profile_data, gpu_cluster, ref_plan)
            with contextlib.redirect_stdout(io.StringIO()):
                ref_cost = ref_est.get_cost(
                    ref_plan, [tuple(s) for s in strategies], list(partition),
                    sp.get_device_placement())
            if abs(ref_cost - recorded_cost) > 1e-6:
                corrupted += 1

            plan = InterStagePlan(
                node_sequence=tuple(dt.name for dt in node_seq),
                device_groups=tuple(device_groups),
                batches=batches, gbs=GBS)
            ours_cost = est.get_cost(
                plan,
                tuple(Strategy(dp=s[0], tp=s[1]) for s in strategies),
                tuple(partition))
            if ours_cost.total_ms != pytest.approx(ref_cost, rel=1e-9):
                mismatches.append((plan, strategies, partition, ref_cost,
                                   ours_cost.total_ms))
        assert not mismatches, (
            f"{len(mismatches)}/{len(reference_run['costs'])} cost mismatches; "
            f"first: {mismatches[0]}")
        # the upstream corruption is real but rare; pin its presence so this
        # comment stays honest if the fixture changes
        assert corrupted < len(reference_run["costs"]) * 0.02
    finally:
        sys.argv = argv_backup
        sys.path.remove(str(reference_root))


def test_uniform_estimator_parity(reference_run, ours, reference_root, fixture_dir):
    """Differential parity for the uniform (homo) estimator on the same
    fixtures across the whole valid (dp, pp, tp, mbs) grid."""
    sys.path.insert(0, str(reference_root))
    try:
        from model.cost_estimator import HomoCostEstimator as RefHomo
        from search_space.plan import UniformPlan as RefUniformPlan
        from gpu_cluster import GPUCluster
        from model.activation_parameter import GPTActivationAndParam
        from utils import ModelConfig as RefModelConfig

        gpu_cluster = GPUCluster(
            hostfile_path=str(fixture_dir / "hostfile"),
            clusterfile_path=str(fixture_dir / "clusterfile.json"))
        profile_data = reference_run["profile_data"]
        m = tiny_test_model()
        model_config = RefModelConfig(
            model_name=m.name, num_layers=m.num_layers,
            sequence_length=m.sequence_length, vocab_size=m.vocab_size,
            hidden_size=m.hidden_size, attention_head_size=m.num_heads)
        ref_est = RefHomo(profile_data, model_config,
                          reference_run["model_volume"], gpu_cluster)

        from metis_tpu.search import uniform_plans
        checked = 0
        with contextlib.redirect_stdout(io.StringIO()):
            for plan in uniform_plans(num_devices=16, max_tp=4, gbs=64):
                if plan.mbs > MAX_BS or not ours["profiles"].has("T4", plan.tp, plan.mbs):
                    continue
                ref_cost, _mem, ref_oom = ref_est.get_cost(
                    RefUniformPlan(dp=plan.dp, pp=plan.pp, tp=plan.tp,
                                   mbs=plan.mbs, gbs=plan.gbs),
                    "T4")
                ours_cost = ours["uniform"].get_cost(plan, "T4")
                assert ours_cost.total_ms == pytest.approx(ref_cost, rel=1e-9), plan
                assert ours_cost.oom == ref_oom, plan
                checked += 1
        assert checked > 20
    finally:
        sys.path.remove(str(reference_root))
