"""Differential cost-model parity against the upstream reference.

The reference's shipped golden logs (``results/hetero_cost_model``) were
produced with T4 profiles that were never released (only A100 fixtures ship),
so exact golden-number reproduction is impossible from shipped data.  Instead
the ``reference_run`` conftest fixture runs the reference planner
**in-process** on our synthetic two-type profile set; here we assert that our
strict-compat estimator reproduces every cost the reference computes, plan by
plan.  This is strictly stronger than a static golden file: it covers the
full plan set, with our fixtures, on every run.
"""
import contextlib
import io
import sys

import pytest

from metis_tpu.cluster import ClusterSpec
from metis_tpu.core.types import InterStagePlan, Strategy
from metis_tpu.cost import (
    EstimatorOptions,
    HeteroCostEstimator,
    TransformerVolume,
    UniformCostEstimator,
)
from metis_tpu.profiles import ProfileStore, tiny_test_model
from metis_tpu.testing import (
    PARITY_GBS as GBS,
    PARITY_MAX_BS as MAX_BS,
    PARITY_MAX_TP as MAX_TP,
)


@pytest.fixture(scope="module")
def ours(parity_fixture_dir):
    cluster = ClusterSpec.from_files(
        parity_fixture_dir / "hostfile", parity_fixture_dir / "clusterfile.json")
    profiles = ProfileStore.from_dir(parity_fixture_dir / "profiles")
    volume = TransformerVolume(tiny_test_model(), profiles.model.params_per_layer_bytes)
    options = EstimatorOptions(strict_compat=True, max_profiled_bs=MAX_BS)
    return {
        "cluster": cluster,
        "profiles": profiles,
        "volume": volume,
        "hetero": HeteroCostEstimator(cluster, profiles, volume, options),
        "uniform": UniformCostEstimator(cluster, profiles, volume, options),
    }


def test_reference_run_is_nontrivial(reference_run):
    assert len(reference_run["costs"]) > 100


def test_upstream_recording_corruption_is_present_but_rare(reference_run):
    """Pins the documented upstream num_stage corruption (see the
    reference_run fixture docstring): a few loop-recorded costs differ from
    direct evaluation of the same candidate."""
    diffs = sum(
        1 for rec, direct in zip(reference_run["costs"], reference_run["direct_costs"])
        if abs(rec[6] - direct) > 1e-6)
    assert 0 < diffs < len(reference_run["costs"]) * 0.02


def test_upstream_balancer_emits_invalid_partitions(reference_run):
    """Pins a second upstream bug: the greedy balancer's majority-vote
    collapse (``load_balancer.py:290-308``) can emit partitions with empty
    stages or dropped layers (boundaries not reaching num_layers), which then
    get artificially low costs.  Our DP balancer structurally cannot."""
    from metis_tpu.profiles import tiny_test_model

    L = tiny_test_model().num_layers
    invalid = [
        rec[4] for rec in reference_run["costs"]
        if not (rec[4][0] == 0 and rec[4][-1] == L
                and all(a < b for a, b in zip(rec[4], rec[4][1:])))
    ]
    assert invalid  # present on these fixtures; estimator parity still holds


def test_hetero_estimator_full_parity(reference_run, ours):
    """Every candidate the reference's search visited, our strict-compat
    estimator must cost identically (rel tol 1e-9) to the reference's own
    direct evaluation."""
    est = ours["hetero"]
    mismatches = []
    for (node_seq, device_groups, strategies, batches, partition,
         _nrep, _recorded), ref_cost in zip(
            reference_run["costs"], reference_run["direct_costs"]):
        plan = InterStagePlan(
            node_sequence=tuple(dt.name for dt in node_seq),
            device_groups=tuple(device_groups),
            batches=batches, gbs=GBS)
        ours_cost = est.get_cost(
            plan,
            tuple(Strategy(dp=s[0], tp=s[1]) for s in strategies),
            tuple(partition))
        if ours_cost.total_ms != pytest.approx(ref_cost, rel=1e-9):
            mismatches.append((plan, strategies, partition, ref_cost,
                               ours_cost.total_ms))
    assert not mismatches, (
        f"{len(mismatches)}/{len(reference_run['costs'])} cost mismatches; "
        f"first: {mismatches[0]}")


def test_uniform_estimator_parity(reference_run, ours, reference_root):
    """Differential parity for the uniform (homo) estimator on the same
    fixtures across the whole valid (dp, pp, tp, mbs) grid."""
    sys.path.insert(0, str(reference_root))
    try:
        from model.cost_estimator import HomoCostEstimator as RefHomo
        from search_space.plan import UniformPlan as RefUniformPlan

        ref_est = RefHomo(
            reference_run["profile_data"], reference_run["model_config"],
            reference_run["model_volume"], reference_run["gpu_cluster"])

        from metis_tpu.search import uniform_plans
        checked = 0
        with contextlib.redirect_stdout(io.StringIO()):
            for plan in uniform_plans(num_devices=16, max_tp=MAX_TP, gbs=64):
                if plan.mbs > MAX_BS or not ours["profiles"].has("T4", plan.tp, plan.mbs):
                    continue
                ref_cost, _mem, ref_oom = ref_est.get_cost(
                    RefUniformPlan(dp=plan.dp, pp=plan.pp, tp=plan.tp,
                                   mbs=plan.mbs, gbs=plan.gbs),
                    "T4")
                ours_cost = ours["uniform"].get_cost(plan, "T4")
                assert ours_cost.total_ms == pytest.approx(ref_cost, rel=1e-9), plan
                assert ours_cost.oom == ref_oom, plan
                checked += 1
        assert checked > 20
    finally:
        sys.path.remove(str(reference_root))
