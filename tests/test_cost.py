import pytest

from metis_tpu.cluster import ClusterSpec, DeviceSpec, TpuClusterSpec, slice_from_name
from metis_tpu.core.types import InterStagePlan, Strategy, UniformPlan
from metis_tpu.cost import (
    EstimatorOptions,
    HeteroCostEstimator,
    HeteroScalarBandwidth,
    HomoScalarBandwidth,
    IciDcnBandwidth,
    TransformerVolume,
    UniformCostEstimator,
    all_gather_ms,
    p2p_ms,
    ring_all_reduce_ms,
    uniform_layer_split,
)
from metis_tpu.profiles import synthesize_profiles, tiny_test_model


@pytest.fixture(scope="module")
def model():
    return tiny_test_model()


@pytest.fixture(scope="module")
def profiles(model):
    return synthesize_profiles(model, ["A100", "T4"], tps=[1, 2, 4], bss=[1, 2, 4, 8, 16])


@pytest.fixture(scope="module")
def volume(model, profiles):
    return TransformerVolume(model, profiles.model.params_per_layer_bytes)


@pytest.fixture(scope="module")
def cluster():
    return ClusterSpec.of(
        ("T4", 2, 4), ("A100", 2, 4),
        overrides={
            "T4": DeviceSpec("T4", 15, 50, 10),
            "A100": DeviceSpec("A100", 80, 46, 10),
        })


class TestVolume:
    def test_boundary_activation_native_is_bytes(self, volume, model):
        native = volume.boundary_activation(4, 2, 2)
        assert native == 2 * model.sequence_length * model.hidden_size * 2

    def test_boundary_activation_compat_quirk(self, volume, model):
        # reference sizes the boundary before the LAST layer at vocab/tp elements
        compat = volume.boundary_activation(model.num_layers - 1, 2, 4, elements=True)
        assert compat == 2 * model.sequence_length * model.vocab_size / 4
        plain = volume.boundary_activation(4, 2, 4, elements=True)
        assert plain == 2 * model.sequence_length * model.hidden_size

    def test_stage_parameter_accounting(self, volume, profiles):
        p = profiles.model.params_per_layer_bytes
        full = volume.stage_parameter_bytes(1, 0, volume.num_layers)
        assert full == pytest.approx(sum(p))
        mid = volume.stage_parameter_bytes(2, 3, 6)
        assert mid == pytest.approx(3 * p[1] / 2)


class TestCollectiveMath:
    def test_all_reduce_scaling(self):
        one_gb = 1e9
        t = ring_all_reduce_ms(one_gb, 8, 100)
        # 2*(7/8) GB over 100 GB/s = 17.5 ms
        assert t == pytest.approx(17.5)
        assert ring_all_reduce_ms(one_gb, 1, 100) == 0.0

    def test_all_gather_is_half_all_reduce(self):
        assert all_gather_ms(1e9, 8, 100) == pytest.approx(
            ring_all_reduce_ms(1e9, 8, 100) / 2)

    def test_p2p(self):
        assert p2p_ms(1e9, 100) == pytest.approx(10.0)


class TestUniformSplit:
    def test_reference_example(self):
        # model/utils.py docstring: 10 layers, 4 stages -> [3, 2, 2, 3]
        assert uniform_layer_split(10, 4) == [3, 2, 2, 3]

    def test_single_stage(self):
        assert uniform_layer_split(10, 1) == [10]

    def test_conservation(self):
        for stages in range(1, 8):
            assert sum(uniform_layer_split(10, stages)) == 10


class TestScalarBandwidth:
    def test_hetero_pp_spans_types(self, cluster):
        plan = InterStagePlan(("T4", "A100"), (8, 8), 8, 128)
        bw = HeteroScalarBandwidth(cluster, plan, strict_compat=True)
        # stage0∪stage1 spans T4+A100 nodes; compat inter = min intra = 46
        assert bw.pp_bandwidth(0) == 46

    def test_hetero_dp_same_type_nodes(self, cluster):
        plan = InterStagePlan(("T4", "A100"), (8, 8), 8, 128)
        bw = HeteroScalarBandwidth(cluster, plan, strict_compat=True)
        # stage 0 = 8 T4 ranks over 2 nodes; dp groups span both T4 nodes
        assert bw.dp_bandwidth(0, Strategy(4, 2)) == 50
        assert bw.dp_bandwidth(1, Strategy(4, 2)) == 46

    def test_native_mode_uses_real_inter(self, cluster):
        plan = InterStagePlan(("T4", "A100"), (8, 8), 8, 128)
        bw = HeteroScalarBandwidth(cluster, plan, strict_compat=False)
        assert bw.pp_bandwidth(0) == 10

    def test_homo_within_node(self, cluster):
        bw = HomoScalarBandwidth(cluster, strict_compat=True)
        # pp=4, tp=2, dp=2: each model row spans nodes -> inter(=intra compat)
        assert bw.pp_bandwidth(4, 2, 0) in (46, 50)


class TestIciBandwidth:
    def test_within_and_across_slices(self):
        tc = TpuClusterSpec((slice_from_name("v4-32"), slice_from_name("v5e-16")))
        plan = InterStagePlan(("tpu_v4", "tpu_v5e"), (32, 16), 8, 128)
        bw = IciDcnBandwidth(tc, plan)
        assert bw.pp_bandwidth(0) == 25  # boundary crosses slices: DCN
        # v4 4x4x2 stage, dp=8/tp=4 sync groups stride the torus: x-axis full
        # ring (2x45) + y-axis stride-2 phase (45/2) -> phase-sum eff bw
        assert bw.dp_bandwidth(0, Strategy(8, 4)) == pytest.approx(28.64, abs=0.01)
        assert bw.dp_bandwidth(1, Strategy(4, 4)) == 90  # v5e 4x4 wrapped ring


class TestEstimators:
    def _options(self, compat=True):
        return EstimatorOptions(strict_compat=compat)

    def test_uniform_cost_structure(self, cluster, profiles, volume):
        est = UniformCostEstimator(cluster, profiles, volume, self._options())
        cost = est.get_cost(UniformPlan(dp=4, pp=1, tp=2, mbs=8, gbs=128), "A100")
        assert cost.total_ms > 0
        assert cost.total_ms == pytest.approx(
            cost.execution_ms + cost.fb_sync_ms + cost.optimizer_ms
            + cost.dp_comm_ms + cost.pp_comm_ms + cost.batch_gen_ms)
        assert cost.pp_comm_ms == 0.0  # pp=1: no boundary

    def test_uniform_pp_adds_comm(self, cluster, profiles, volume):
        est = UniformCostEstimator(cluster, profiles, volume, self._options())
        c2 = est.get_cost(UniformPlan(dp=2, pp=2, tp=2, mbs=8, gbs=128), "A100")
        assert c2.pp_comm_ms > 0

    def test_hetero_cost_known_plan(self, cluster, profiles, volume):
        est = HeteroCostEstimator(cluster, profiles, volume, self._options())
        plan = InterStagePlan(("T4", "A100"), (8, 8), 8, 128)
        cost = est.get_cost(plan, (Strategy(4, 2), Strategy(4, 2)), (0, 4, 10))
        assert cost.total_ms > 0
        assert cost.dp_comm_ms > 0 and cost.pp_comm_ms > 0

    def test_more_tp_less_dp_comm(self, cluster, profiles, volume):
        est = HeteroCostEstimator(cluster, profiles, volume, self._options())
        plan = InterStagePlan(("T4", "A100"), (8, 8), 8, 128)
        c_dp4 = est.get_cost(plan, (Strategy(4, 2), Strategy(4, 2)), (0, 4, 10))
        c_dp2 = est.get_cost(plan, (Strategy(2, 4), Strategy(2, 4)), (0, 4, 10))
        assert c_dp2.dp_comm_ms < c_dp4.dp_comm_ms

    def test_ici_factory_pluggable(self, profiles, volume):
        tc = TpuClusterSpec((slice_from_name("v4-32"), slice_from_name("v5e-16")))
        cluster = tc.as_cluster_spec()
        tpu_profiles = synthesize_profiles(
            tiny_test_model(), ["tpu_v4", "tpu_v5e"], tps=[1, 2, 4],
            bss=[1, 2, 4, 8, 16])
        vol = TransformerVolume(tiny_test_model(), tpu_profiles.model.params_per_layer_bytes)
        est = HeteroCostEstimator(
            cluster, tpu_profiles, vol, EstimatorOptions(strict_compat=False),
            bandwidth_factory=lambda plan: IciDcnBandwidth(tc, plan))
        plan = InterStagePlan(("tpu_v4", "tpu_v5e"), (32, 16), 8, 128)
        cost = est.get_cost(plan, (Strategy(8, 4), Strategy(4, 4)), (0, 6, 10))
        assert cost.total_ms > 0


class TestBatchGenCharging:
    """Native mode charges the input pipeline once per step; strict-compat
    keeps the reference's per-microbatch charge (``cost_estimator.py:34-35``).
    Pinned by the on-chip validation sweep: measured step time is flat in the
    microbatch count (calibration/tpu_validation_sweep.json)."""

    def test_strict_scales_with_microbatches(self, cluster, profiles, volume):
        est = UniformCostEstimator(
            cluster, profiles, volume, EstimatorOptions(strict_compat=True))
        c_mbs4 = est.get_cost(UniformPlan(dp=4, pp=1, tp=2, mbs=4, gbs=128), "A100")
        c_mbs8 = est.get_cost(UniformPlan(dp=4, pp=1, tp=2, mbs=8, gbs=128), "A100")
        # 8 microbatches vs 4 -> 2x the charge
        assert c_mbs4.batch_gen_ms == pytest.approx(2 * c_mbs8.batch_gen_ms)
        assert c_mbs8.batch_gen_ms > 0

    def test_native_charges_once_per_step(self, cluster, profiles, volume):
        est = UniformCostEstimator(
            cluster, profiles, volume, EstimatorOptions(strict_compat=False))
        costs = [
            est.get_cost(UniformPlan(dp=4, pp=1, tp=2, mbs=m, gbs=128), "A100")
            for m in (2, 4, 8)]
        assert costs[0].batch_gen_ms > 0
        for c in costs[1:]:
            assert c.batch_gen_ms == pytest.approx(costs[0].batch_gen_ms)

    def test_native_hetero_charges_once(self, cluster, profiles, volume):
        est = HeteroCostEstimator(
            cluster, profiles, volume, EstimatorOptions(strict_compat=False))
        plan_b8 = InterStagePlan(("T4", "A100"), (8, 8), 8, 128)
        plan_b4 = InterStagePlan(("T4", "A100"), (8, 8), 4, 128)
        c8 = est.get_cost(plan_b8, (Strategy(4, 2), Strategy(4, 2)), (0, 4, 10))
        c4 = est.get_cost(plan_b4, (Strategy(4, 2), Strategy(4, 2)), (0, 4, 10))
        assert c8.batch_gen_ms == pytest.approx(c4.batch_gen_ms)


class TestMbAffine:
    """Affine smoothing of the profile bs axis (ProfileStore.affine_view):
    native mode prices a step as ``num_mbs * slope * mbs + intercept`` with
    the fitted per-program fixed cost charged once per step — the executors
    scan microbatches inside one jit, so a per-microbatch charge of the
    isolated-closure profile time bends predictions with the microbatch
    count (on-chip sweep: +12.8% at 1 microbatch, −6% at 2, +8.6% at 8 —
    calibration/tpu_validation_sweep.json, round 4)."""

    def test_affine_view_is_linear_in_bs(self, profiles):
        smoothed, overhead = profiles.affine_view()
        for (t, tp, bs) in smoothed.configs():
            if bs == 1:
                base = smoothed.get(t, tp, 1).layer_times_ms
                for b2 in (2, 4, 8):
                    if smoothed.has(t, tp, b2):
                        got = smoothed.get(t, tp, b2).layer_times_ms
                        for x1, x2 in zip(base, got):
                            assert x2 == pytest.approx(b2 * x1, rel=1e-9)
        assert set(overhead) == {(t, tp) for (t, tp, _) in profiles.configs()}

    def test_affine_view_preserves_memory(self, profiles):
        smoothed, _ = profiles.affine_view()
        for key in profiles.configs():
            assert (smoothed.get(*key).layer_memory_mb
                    == profiles.get(*key).layer_memory_mb)

    def test_affine_fit_recovers_linear_profile(self):
        """On synthetic exactly-affine data the fit is exact: slope*bs entries
        and the intercept sum per (type, tp)."""
        from metis_tpu.profiles.store import (
            DeviceTypeMeta, LayerProfile, ModelProfileMeta, ProfileStore)

        a, b = 3.0, 2.0
        entries = {
            ("X", 1, bs): LayerProfile(
                layer_times_ms=(a + b * bs,) * 4,
                layer_memory_mb=(1.0,) * 4, fb_sync_ms=0.0)
            for bs in (1, 2, 4, 8)
        }
        meta = ModelProfileMeta(4, 1.0, 1.0, (10,) * 4)
        store = ProfileStore(entries, meta, {"X": DeviceTypeMeta(1.0, 1.0)})
        smoothed, overhead = store.affine_view()
        assert overhead[("X", 1)] == pytest.approx(4 * a)
        assert smoothed.get("X", 1, 4).layer_times_ms == pytest.approx((b * 4,) * 4)

    def test_native_step_flat_in_mbs_for_linear_profiles(self, cluster, volume, model):
        """With affine profiles, a pp=1 plan's predicted total is flat across
        the microbatch size — matching the measured on-chip behavior."""
        from metis_tpu.profiles import synthesize_profiles

        profs = synthesize_profiles(model, ["A100"], tps=[1], bss=[1, 2, 4, 8])
        est = UniformCostEstimator(
            cluster, profs, volume, EstimatorOptions(strict_compat=False))
        totals = [
            est.get_cost(UniformPlan(dp=1, pp=1, tp=1, mbs=m, gbs=8), "A100").total_ms
            for m in (1, 2, 4, 8)]
        for t in totals[1:]:
            assert t == pytest.approx(totals[0], rel=0.02)

    def test_affine_fallback_on_noise_negative_slope(self):
        """A layer whose profiled time DECREASES with bs (pure noise) falls
        back to the mean per-sample rate with zero intercept (regression:
        round-5 refactor broke this branch with a NameError)."""
        from metis_tpu.profiles.store import (
            DeviceTypeMeta, LayerProfile, ModelProfileMeta, ProfileStore)

        entries = {
            ("X", 1, bs): LayerProfile(
                layer_times_ms=(t,) * 3, layer_memory_mb=(1.0,) * 3,
                fb_sync_ms=0.0)
            for bs, t in [(1, 8.0), (2, 6.0), (4, 4.0)]  # negative slope
        }
        meta = ModelProfileMeta(3, 1.0, 1.0, (10,) * 3)
        store = ProfileStore(entries, meta, {"X": DeviceTypeMeta(1.0, 1.0)})
        smoothed, overhead = store.affine_view()
        assert overhead[("X", 1)] == 0.0
        rate = (8.0 / 1 + 6.0 / 2 + 4.0 / 4) / 3
        assert smoothed.get("X", 1, 2).layer_times_ms == pytest.approx(
            (rate * 2,) * 3)

    def test_strict_compat_unaffected(self, cluster, profiles, volume):
        """Strict-compat never smooths — reference per-microbatch parity."""
        est = UniformCostEstimator(
            cluster, profiles, volume, EstimatorOptions(strict_compat=True))
        assert est._step_overhead == {}
        assert est.profiles is profiles

    def test_optimizer_factor_auto(self, cluster, profiles, volume):
        """None = auto: 2.0 strict (ref data_loader.py:19 doubling), 1.0
        native (executors run adamw once per step); explicit value wins."""
        plan = UniformPlan(dp=1, pp=1, tp=1, mbs=4, gbs=4)
        strict = UniformCostEstimator(
            cluster, profiles, volume,
            EstimatorOptions(strict_compat=True)).get_cost(plan, "A100")
        native = UniformCostEstimator(
            cluster, profiles, volume,
            EstimatorOptions(strict_compat=False)).get_cost(plan, "A100")
        forced = UniformCostEstimator(
            cluster, profiles, volume,
            EstimatorOptions(strict_compat=False, optimizer_factor=2.0),
        ).get_cost(plan, "A100")
        assert strict.optimizer_ms == pytest.approx(
            2 * profiles.model.optimizer_time_ms)
        assert native.optimizer_ms == pytest.approx(
            profiles.type_meta["A100"].optimizer_time_ms)
        assert forced.optimizer_ms == pytest.approx(2 * native.optimizer_ms)
