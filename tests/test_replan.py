"""Elastic re-plan on topology delta + structured event log."""
import json

import pytest

from metis_tpu.cluster import ClusterSpec
from metis_tpu.core.config import SearchConfig
from metis_tpu.core.events import EventLog, read_events
from metis_tpu.planner import ClusterDelta, plan_hetero, replan
from metis_tpu.profiles import synthesize_profiles, tiny_test_model


@pytest.fixture(scope="module")
def setup():
    model = tiny_test_model()
    store = synthesize_profiles(model, ["A100", "T4"], tps=[1, 2, 4],
                                bss=[1, 2, 4, 8, 16])
    return model, store


class TestClusterDelta:
    def test_between(self):
        old = ClusterSpec.of(("A100", 2, 4), ("T4", 2, 4))
        new = ClusterSpec.of(("A100", 2, 4), ("T4", 1, 4))
        d = ClusterDelta.between(old, new)
        assert d.removed == {"T4": 4}
        assert d.added == {}
        assert not d.is_empty

    def test_empty(self):
        c = ClusterSpec.of(("A100", 2, 4))
        assert ClusterDelta.between(c, c).is_empty

    def test_device_added_back_after_loss(self):
        """A lost-then-replaced node is a no-op delta, not an add+remove."""
        old = ClusterSpec.of(("A100", 2, 4))
        lost = ClusterSpec.of(("A100", 1, 4))
        healed = ClusterSpec.of(("A100", 2, 4))
        assert ClusterDelta.between(old, lost).removed == {"A100": 4}
        assert ClusterDelta.between(lost, healed).added == {"A100": 4}
        assert ClusterDelta.between(old, healed).is_empty

    def test_type_count_changes_both_ways(self):
        """One type shrinking while another grows lands in both maps."""
        old = ClusterSpec.of(("A100", 2, 4), ("T4", 1, 4))
        new = ClusterSpec.of(("A100", 1, 4), ("T4", 2, 4))
        d = ClusterDelta.between(old, new)
        assert d.removed == {"A100": 4}
        assert d.added == {"T4": 4}
        assert not d.is_empty

    def test_type_swap(self):
        """A type disappearing entirely while a new one appears."""
        old = ClusterSpec.of(("A100", 1, 4))
        new = ClusterSpec.of(("T4", 1, 8))
        d = ClusterDelta.between(old, new)
        assert d.removed == {"A100": 4}
        assert d.added == {"T4": 8}

    def test_empty_delta_short_circuit(self):
        """Same topology spelled with different node granularity is still
        an empty delta (counts per type, not node lists)."""
        a = ClusterSpec.of(("A100", 2, 4))
        b = ClusterSpec.of(("A100", 4, 2))
        assert ClusterDelta.between(a, b).is_empty

    def test_device_count_totals(self):
        """num_added/num_removed aggregate across device types."""
        old = ClusterSpec.of(("A100", 2, 4), ("T4", 1, 4))
        new = ClusterSpec.of(("A100", 1, 4), ("T4", 3, 4))
        d = ClusterDelta.between(old, new)
        assert d.num_removed == 4
        assert d.num_added == 8
        empty = ClusterDelta.between(old, old)
        assert empty.num_added == 0 and empty.num_removed == 0


class TestShrinkCluster:
    def test_whole_node_removed_from_end(self):
        from metis_tpu.planner import shrink_cluster

        c = ClusterSpec.of(("A100", 3, 4))
        s = shrink_cluster(c, {"A100": 4})
        assert s.num_nodes == 2
        assert s.total_devices == 8
        assert ClusterDelta.between(c, s).removed == {"A100": 4}

    def test_partial_node_narrows(self):
        from metis_tpu.cluster.spec import NodeSpec
        from metis_tpu.planner import shrink_cluster

        c = ClusterSpec.of(("A100", 2, 4))
        s = shrink_cluster(c, {"A100": 2})
        assert s.nodes == (NodeSpec("A100", 4), NodeSpec("A100", 2))

    def test_mixed_types_only_named_type_shrinks(self):
        from metis_tpu.planner import shrink_cluster

        c = ClusterSpec.of(("A100", 2, 4), ("T4", 2, 4))
        s = shrink_cluster(c, {"T4": 8})
        assert s.num_devices_by_type("T4") == 0
        assert s.num_devices_by_type("A100") == 8
        # the surviving spec still knows the T4 DeviceSpec (profiles may
        # reference it)
        assert "T4" in s.devices

    def test_removing_too_many_raises(self):
        from metis_tpu.core.errors import ClusterSpecError
        from metis_tpu.planner import shrink_cluster

        c = ClusterSpec.of(("A100", 1, 4))
        with pytest.raises(ClusterSpecError):
            shrink_cluster(c, {"A100": 5})
        with pytest.raises(ClusterSpecError):
            shrink_cluster(c, {"T4": 1})

    def test_nothing_survives_raises(self):
        from metis_tpu.core.errors import ClusterSpecError
        from metis_tpu.planner import shrink_cluster

        c = ClusterSpec.of(("A100", 1, 4))
        with pytest.raises(ClusterSpecError):
            shrink_cluster(c, {"A100": 4})


class TestGrowCluster:
    def test_whole_node_restored(self):
        from metis_tpu.planner import grow_cluster, shrink_cluster

        full = ClusterSpec.of(("A100", 3, 4))
        shrunk = shrink_cluster(full, {"A100": 4})
        g = grow_cluster(shrunk, full, {"A100": 4})
        assert g.nodes == full.nodes
        assert g.devices == full.devices

    def test_partial_node_widens_back(self):
        from metis_tpu.planner import grow_cluster, shrink_cluster

        full = ClusterSpec.of(("A100", 2, 4))
        shrunk = shrink_cluster(full, {"A100": 2})
        g = grow_cluster(shrunk, full, {"A100": 2})
        assert g.nodes == full.nodes

    def test_partial_return_still_missing_some(self):
        from metis_tpu.cluster.spec import NodeSpec
        from metis_tpu.planner import grow_cluster, shrink_cluster

        full = ClusterSpec.of(("A100", 3, 4))
        shrunk = shrink_cluster(full, {"A100": 8})
        g = grow_cluster(shrunk, full, {"A100": 4})
        # rebuilt as full shrunk by the 4 still missing: node order matches
        # the reference topology
        assert g.nodes == (NodeSpec("A100", 4), NodeSpec("A100", 4))

    def test_mixed_types_only_named_type_grows(self):
        from metis_tpu.planner import grow_cluster, shrink_cluster

        full = ClusterSpec.of(("A100", 2, 4), ("T4", 2, 4))
        shrunk = shrink_cluster(full, {"T4": 8})
        g = grow_cluster(shrunk, full, {"T4": 4})
        assert g.num_devices_by_type("T4") == 4
        assert g.num_devices_by_type("A100") == 8

    def test_growing_past_reference_raises(self):
        from metis_tpu.core.errors import ClusterSpecError
        from metis_tpu.planner import grow_cluster

        full = ClusterSpec.of(("A100", 2, 4))
        with pytest.raises(ClusterSpecError):
            grow_cluster(full, full, {"A100": 4})

    def test_unknown_type_raises_typed(self):
        from metis_tpu.core.errors import ClusterSpecError
        from metis_tpu.planner import grow_cluster, shrink_cluster

        full = ClusterSpec.of(("A100", 2, 4))
        shrunk = shrink_cluster(full, {"A100": 4})
        with pytest.raises(ClusterSpecError, match="unknown to"):
            grow_cluster(shrunk, full, {"H100": 4})

    def test_shrink_grow_round_trip(self):
        from metis_tpu.planner import grow_cluster, shrink_cluster

        full = ClusterSpec.of(("A100", 2, 4), ("T4", 2, 4))
        shrunk = shrink_cluster(full, {"A100": 2, "T4": 4})
        g = grow_cluster(shrunk, full, {"A100": 2, "T4": 4})
        assert g.nodes == full.nodes
        assert ClusterDelta.between(full, g).is_empty

    def test_delta_apply_round_trip(self):
        """between/apply symmetry: between(old, d.apply(old)) == d."""
        old = ClusterSpec.of(("A100", 2, 4), ("T4", 2, 4))
        for d in (ClusterDelta(added={}, removed={"T4": 4}),
                  ClusterDelta(added={}, removed={"A100": 2, "T4": 8}),
                  ClusterDelta(added={"V100": 4}, removed={})):
            new = d.apply(old)
            assert ClusterDelta.between(old, new) == d

    def test_delta_apply_toward_full(self):
        from metis_tpu.planner import shrink_cluster

        full = ClusterSpec.of(("A100", 2, 4))
        shrunk = shrink_cluster(full, {"A100": 4})
        d = ClusterDelta.between(shrunk, full)
        assert d.added == {"A100": 4}
        assert d.apply(shrunk, full=full).nodes == full.nodes


class TestReplan:
    def test_lost_node_replans_slower(self, setup):
        """Dropping half the cluster re-plans successfully at higher cost."""
        model, store = setup
        old = ClusterSpec.of(("A100", 2, 4))
        new = ClusterSpec.of(("A100", 1, 4))
        cfg = SearchConfig(gbs=64)
        old_result = plan_hetero(old, store, model, cfg)
        report = replan(old, new, store, model, cfg, old_result=old_result)
        assert report.delta.removed == {"A100": 4}
        assert report.plan_changed
        assert report.result.best is not None
        assert report.cost_ratio is not None and report.cost_ratio > 1.0

    def test_no_change_keeps_plan(self, setup):
        model, store = setup
        c = ClusterSpec.of(("A100", 2, 4))
        cfg = SearchConfig(gbs=64)
        report = replan(c, c, store, model, cfg)
        assert report.delta.is_empty
        assert not report.plan_changed
        assert report.cost_ratio == pytest.approx(1.0)

    def test_added_capacity(self, setup):
        model, store = setup
        old = ClusterSpec.of(("A100", 1, 4))
        new = ClusterSpec.of(("A100", 1, 4), ("T4", 1, 4))
        report = replan(old, new, store, model, SearchConfig(gbs=64))
        assert report.delta.added == {"T4": 4}


class TestMigrationEligibility:
    """Device-set intersection edge cases for live plan migration
    (``execution.reshard``): the gate the supervisor consults before
    attempting an in-memory reshard instead of checkpoint-restore."""

    def test_type_swap_is_disjoint(self):
        """A wholesale fleet swap shares no devices — the delta is a full
        remove + full add, and migration is ineligible."""
        from metis_tpu.execution.reshard import (device_sets_intersect,
                                                 migration_eligible)

        old = ClusterSpec.of(("A100", 2, 4))
        new = ClusterSpec.of(("T4", 2, 4))
        d = ClusterDelta.between(old, new)
        assert d.removed == {"A100": 8} and d.added == {"T4": 8}
        assert not device_sets_intersect(old, new)
        ok, reason = migration_eligible(
            "gspmd", "gspmd", "", "", device_sets_intersect(old, new))
        assert not ok
        assert "disjoint" in reason

    def test_superset_grow_intersects(self):
        """Growing to a superset keeps every old device — intersection
        holds and a same-shape gspmd switch is eligible."""
        from metis_tpu.execution.reshard import (device_sets_intersect,
                                                 migration_eligible)

        old = ClusterSpec.of(("A100", 1, 4))
        new = ClusterSpec.of(("A100", 2, 4), ("T4", 1, 4))
        assert ClusterDelta.between(old, new).removed == {}
        assert device_sets_intersect(old, new)
        assert device_sets_intersect(new, old)
        ok, reason = migration_eligible("gspmd", "gspmd", "", "", True)
        assert ok and reason == "ok"

    def test_same_set_different_plan(self):
        """An unchanged topology (empty delta) still migrates only when
        the state structure matches: same pipeline block layout is
        eligible, a repartition is not."""
        from metis_tpu.execution.reshard import (device_sets_intersect,
                                                 migration_eligible)

        c = ClusterSpec.of(("A100", 2, 4))
        assert ClusterDelta.between(c, c).is_empty
        assert device_sets_intersect(c, c)
        ok, reason = migration_eligible(
            "pipeline", "pipeline", "pp2:(0,2,4)", "pp2:(0,2,4)", True)
        assert ok and reason == "ok"
        ok, reason = migration_eligible(
            "pipeline", "pipeline", "pp2:(0,2,4)", "pp4:(0,1,2,3,4)", True)
        assert not ok
        assert "block layouts differ" in reason
        ok, reason = migration_eligible("pipeline", "gspmd", "", "", True)
        assert not ok
        assert "state shapes differ" in reason

    def test_single_survivor_shrink(self):
        """Shrinking to a single surviving device still intersects; the
        hetero route stays ineligible regardless."""
        from metis_tpu.execution.reshard import (device_sets_intersect,
                                                 migration_eligible)
        from metis_tpu.planner.replan import shrink_cluster

        old = ClusterSpec.of(("A100", 2, 4))
        new = shrink_cluster(old, {"A100": 7})
        assert new.total_devices == 1
        assert ClusterDelta.between(old, new).removed == {"A100": 7}
        assert device_sets_intersect(old, new)
        ok, reason = migration_eligible("gspmd", "gspmd", "", "", True)
        assert ok
        ok, reason = migration_eligible("hetero", "gspmd", "", "", True)
        assert not ok
        assert "hetero" in reason


class TestEventLog:
    def test_planner_emits_events(self, setup, tmp_path):
        model, store = setup
        cluster = ClusterSpec.of(("A100", 2, 4))
        log_path = tmp_path / "events.jsonl"
        plan_hetero(cluster, store, model, SearchConfig(gbs=64),
                    events=EventLog(log_path))
        events = read_events(log_path)
        kinds = [e["event"] for e in events]
        # flight-recorder spans/counters interleave; the start/finish pair
        # stays present and ordered
        assert kinds.index("search_started") < kinds.index("search_finished")
        started = next(e for e in events if e["event"] == "search_started")
        finished = next(e for e in events if e["event"] == "search_finished")
        assert started["devices"] == 8
        assert finished["num_costed"] > 0
        assert finished["best_cost_ms"] > 0
        # the span tree covers the search phases (core/trace.py)
        span_names = {e["name"] for e in events if e["event"] == "span_end"}
        assert {"plan_hetero", "enumeration", "costing",
                "ranking"} <= span_names

    def test_uniform_planner_emits_events(self, setup, tmp_path):
        from metis_tpu.planner import plan_uniform

        model, store = setup
        cluster = ClusterSpec.of(("A100", 2, 4))
        log_path = tmp_path / "uniform.jsonl"
        plan_uniform(cluster, store, model, SearchConfig(gbs=64),
                     events=EventLog(log_path))
        kinds = [e["event"] for e in read_events(log_path)]
        assert kinds.index("search_started") < kinds.index("search_finished")
        assert "counters" in kinds

    def test_disabled_log_is_noop(self, setup):
        log = EventLog()
        assert not log.enabled
        log.emit("anything", x=1)  # must not raise

    def test_stream_sink(self):
        import io

        buf = io.StringIO()
        log = EventLog(stream=buf)
        log.emit("hello", n=2)
        rec = json.loads(buf.getvalue())
        assert rec["event"] == "hello" and rec["n"] == 2 and "ts" in rec

    def test_cli_events_flag(self, setup, tmp_path):
        from metis_tpu.planner.cli import main as cli_main
        from metis_tpu.testing import write_parity_fixture

        write_parity_fixture(tmp_path)
        out = tmp_path / "plans.json"
        ev = tmp_path / "ev.jsonl"
        rc = cli_main([
            "hetero", "--hostfile", str(tmp_path / "hostfile"),
            "--clusterfile", str(tmp_path / "clusterfile.json"),
            "--profile-dir", str(tmp_path / "profiles"),
            "--gbs", "128", "--num-layers", "10", "--hidden-size", "4096",
            "--seq-len", "1024", "--vocab-size", "51200", "--num-heads", "32",
            "--top-k", "1", "--output", str(out), "--events", str(ev),
        ])
        assert rc == 0
        kinds = [e["event"] for e in read_events(ev)]
        assert kinds.index("search_started") < kinds.index("search_finished")
