"""obs/metrics.py correctness: histogram quantiles vs a numpy percentile
oracle, merge algebra (associative + commutative, dict round-trip),
concurrent-record thread safety, counter/gauge/rate semantics, and the
Prometheus render → parse round-trip."""
import math
import random
import threading

import numpy as np
import pytest

from metis_tpu.obs.metrics import (
    DEFAULT_BOUNDS,
    Histogram,
    MetricsRegistry,
    NULL_METRICS,
    RateMeter,
    parse_exposition,
    quantile_from_buckets,
)

# one bucket spans a factor of 10**(1/20); a quantile estimate landing in
# the right bucket is off by at most that ratio plus the nearest-rank
# discretization at small n
_BUCKET_RATIO = 10.0 ** (1.0 / 20.0)


def _oracle(samples, q):
    """Nearest-rank percentile, matching Histogram.quantile's definition."""
    return float(np.quantile(np.asarray(samples), q,
                             method="inverted_cdf"))


class TestHistogramOracle:
    @pytest.mark.parametrize("dist", ["uniform", "lognormal", "bimodal"])
    @pytest.mark.parametrize("q", [0.5, 0.95, 0.99])
    def test_quantiles_within_bucket_resolution(self, dist, q):
        rng = random.Random(hash((dist, q)) & 0xFFFF)
        if dist == "uniform":
            samples = [rng.uniform(0.1, 50.0) for _ in range(5000)]
        elif dist == "lognormal":
            samples = [rng.lognormvariate(0.0, 2.0) for _ in range(5000)]
        else:
            samples = ([rng.gauss(1.0, 0.05) for _ in range(2500)]
                       + [rng.gauss(100.0, 5.0) for _ in range(2500)])
            samples = [max(s, 1e-3) for s in samples]
        h = Histogram()
        for s in samples:
            h.observe(s)
        est = h.quantile(q)
        exact = _oracle(samples, q)
        assert est == pytest.approx(exact, rel=_BUCKET_RATIO - 1.0 + 0.02)

    def test_small_n_exact_extremes(self):
        h = Histogram()
        for v in [3.0, 7.0, 11.0]:
            h.observe(v)
        # estimates are clamped to the observed range
        assert h.quantile(0.0) >= 3.0 - 1e-9
        assert h.quantile(1.0) <= 11.0 + 1e-9
        assert h.count == 3
        assert h.sum == pytest.approx(21.0)
        assert h.min == 3.0 and h.max == 11.0

    def test_empty_quantile_none(self):
        assert Histogram().quantile(0.5) is None

    def test_out_of_range_observations_land_in_edge_buckets(self):
        h = Histogram()
        h.observe(0.0)        # below the lowest bound
        h.observe(1e12)       # above the highest
        assert h.count == 2
        assert h.quantile(0.5) is not None


class TestHistogramMerge:
    def _rand_hist(self, seed, n=400):
        rng = random.Random(seed)
        h = Histogram()
        for _ in range(n):
            h.observe(rng.lognormvariate(1.0, 1.5))
        return h

    def _merged(self, *hists):
        out = Histogram()
        for h in hists:
            out.merge(h)
        return out

    def _state(self, h):
        return (h.count, h.sum, h.min, h.max, h.to_dict()["counts"])

    def test_commutative(self):
        a, b = self._rand_hist(1), self._rand_hist(2)
        assert self._state(self._merged(a, b)) \
            == self._state(self._merged(b, a))

    def test_associative(self):
        a, b, c = (self._rand_hist(s) for s in (3, 4, 5))
        ab_c = self._merged(self._merged(a, b), c)
        a_bc = self._merged(a, self._merged(b, c))
        assert self._state(ab_c) == self._state(a_bc)

    def test_merge_equals_pooled_observation(self):
        rng = random.Random(6)
        samples = [rng.lognormvariate(0.5, 1.0) for _ in range(1000)]
        pooled = Histogram()
        for s in samples:
            pooled.observe(s)
        shards = [Histogram() for _ in range(4)]
        for i, s in enumerate(samples):
            shards[i % 4].observe(s)
        merged = self._merged(*shards)
        assert merged.count == pooled.count
        assert merged.sum == pytest.approx(pooled.sum)  # fp ordering
        assert (merged.min, merged.max) == (pooled.min, pooled.max)
        assert merged.to_dict()["counts"] == pooled.to_dict()["counts"]

    def test_dict_round_trip(self):
        a = self._rand_hist(7)
        b = Histogram()
        b.merge_dict(a.to_dict())
        assert self._state(b) == self._state(a)
        # merging the dict again doubles, like a second worker's report
        b.merge_dict(a.to_dict())
        assert b.count == 2 * a.count
        assert b.sum == pytest.approx(2 * a.sum)

    def test_merge_bounds_mismatch_raises(self):
        a = Histogram()
        b = Histogram(bounds=(1.0, 10.0, 100.0))
        with pytest.raises(ValueError):
            a.merge(b)


class TestConcurrency:
    def test_concurrent_observe_loses_nothing(self):
        h = Histogram()
        reg = MetricsRegistry()
        counter = reg.counter("metis_serve_requests_total", endpoint="t")
        per_thread, threads = 2000, 8

        def work(seed):
            rng = random.Random(seed)
            for _ in range(per_thread):
                h.observe(rng.uniform(0.01, 100.0))
                counter.inc()

        ts = [threading.Thread(target=work, args=(i,)) for i in range(threads)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert h.count == per_thread * threads
        assert counter.value == per_thread * threads
        # bucket mass must reconcile with the count
        assert sum(h.to_dict()["counts"].values()) == h.count

    def test_concurrent_registry_access_single_instrument(self):
        reg = MetricsRegistry()
        seen = []

        def grab():
            seen.append(reg.counter("metis_serve_cache_hits_total"))

        ts = [threading.Thread(target=grab) for _ in range(16)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert all(c is seen[0] for c in seen)


class TestInstruments:
    def test_counter_monotonic(self):
        reg = MetricsRegistry()
        c = reg.counter("metis_serve_requests_total", endpoint="plan")
        c.inc()
        c.inc(2.5)
        assert c.value == pytest.approx(3.5)
        with pytest.raises(ValueError):
            c.inc(-1)

    def test_gauge_set_inc_dec(self):
        g = MetricsRegistry().gauge("metis_serve_inflight_requests")
        g.set(5)
        g.inc()
        g.dec(2)
        assert g.value == pytest.approx(4.0)

    def test_rate_meter_window(self):
        r = RateMeter(window_s=60.0)
        for _ in range(30):
            r.mark()
        assert r.rate() > 0.0
        assert RateMeter().rate() == 0.0

    def test_kind_conflict_raises(self):
        reg = MetricsRegistry()
        reg.counter("metis_serve_cache_hits_total")
        with pytest.raises(ValueError):
            reg.gauge("metis_serve_cache_hits_total")

    def test_label_values_distinguish_series(self):
        reg = MetricsRegistry()
        a = reg.counter("metis_serve_requests_total", endpoint="plan")
        b = reg.counter("metis_serve_requests_total", endpoint="stats")
        a.inc(3)
        b.inc(1)
        assert a.value == 3 and b.value == 1

    def test_invalid_names_rejected(self):
        reg = MetricsRegistry()
        with pytest.raises(ValueError):
            reg.counter("bad name")
        with pytest.raises(ValueError):
            reg.counter("ok_name", **{"bad-label": "x"})


class TestNullRegistry:
    def test_disabled_registry_is_free_and_silent(self):
        reg = MetricsRegistry(enabled=False)
        c = reg.counter("metis_serve_requests_total", endpoint="plan")
        g = reg.gauge("metis_serve_inflight_requests")
        h = reg.histogram("metis_serve_request_latency_ms", endpoint="plan")
        r = reg.rate("metis_serve_qps")
        c.inc()
        g.set(7)
        g.inc()
        g.dec()
        h.observe(1.0)
        r.mark()
        assert h.quantile(0.5) is None
        assert r.rate() == 0.0
        assert c.value == 0.0 and g.value == 0.0
        assert reg.render().strip() == ""

    def test_null_metrics_shared_no_op(self):
        c = NULL_METRICS.counter("anything_goes_here")
        c.inc(1e9)
        assert c.value == 0.0


class TestRenderParse:
    def _populated(self):
        reg = MetricsRegistry()
        reg.counter("metis_serve_requests_total", endpoint="plan").inc(5)
        reg.counter("metis_serve_requests_total", endpoint="stats").inc(2)
        reg.gauge("metis_serve_inflight_requests").set(1)
        h = reg.histogram("metis_serve_request_latency_ms", endpoint="plan")
        for v in (0.5, 1.5, 2.5, 40.0):
            h.observe(v)
        reg.rate("metis_serve_qps").mark(10)
        return reg

    def test_round_trip(self):
        reg = self._populated()
        families = parse_exposition(reg.render())
        reqs = families["metis_serve_requests_total"]
        assert reqs["type"] == "counter"
        by_ep = {dict(labels)["endpoint"]: v
                 for _, labels, v in reqs["samples"]}
        assert by_ep == {"plan": 5.0, "stats": 2.0}
        hist = families["metis_serve_request_latency_ms"]
        assert hist["type"] == "histogram"
        counts = [v for name, labels, v in hist["samples"]
                  if name.endswith("_count")]
        assert counts == [4.0]
        # +Inf bucket equals _count
        inf_bucket = [v for name, labels, v in hist["samples"]
                      if name.endswith("_bucket")
                      and dict(labels).get("le") == "+Inf"]
        assert inf_bucket == [4.0]

    def test_render_passes_exposition_lint(self):
        import sys
        from pathlib import Path
        sys.path.insert(0, str(Path(__file__).resolve().parent.parent
                               / "tools"))
        import check_metrics_names
        assert check_metrics_names.validate_exposition(
            self._populated().render()) == []

    def test_quantile_from_buckets_matches_histogram(self):
        h = Histogram()
        rng = random.Random(11)
        samples = [rng.lognormvariate(0.0, 1.0) for _ in range(2000)]
        for s in samples:
            h.observe(s)
        est = quantile_from_buckets(h.cumulative_buckets(), 0.95)
        # bucket-only estimate lacks the min/max clamp but must still be
        # within one bucket of the oracle
        assert est == pytest.approx(_oracle(samples, 0.95),
                                    rel=_BUCKET_RATIO - 1.0 + 0.02)

    def test_label_escaping(self):
        reg = MetricsRegistry()
        reg.counter("metis_fleet_preemptions_total",
                    tenant='we"ird\\ten\nant').inc()
        families = parse_exposition(reg.render())
        _, labels, v = families["metis_fleet_preemptions_total"]["samples"][0]
        assert dict(labels)["tenant"] == 'we"ird\\ten\nant'
        assert v == 1.0

    def test_parse_rejects_garbage(self):
        with pytest.raises(ValueError):
            parse_exposition("this is { not exposition\n")


class TestRegistryMerge:
    def test_cross_registry_merge(self):
        a, b = MetricsRegistry(), MetricsRegistry()
        a.counter("metis_serve_requests_total", endpoint="plan").inc(3)
        b.counter("metis_serve_requests_total", endpoint="plan").inc(4)
        b.histogram("metis_serve_request_latency_ms",
                    endpoint="plan").observe(1.0)
        a.merge(b)
        assert a.counter("metis_serve_requests_total",
                         endpoint="plan").value == 7.0
        assert a.histogram("metis_serve_request_latency_ms",
                           endpoint="plan").count == 1

    def test_default_bounds_shape(self):
        # 20 per decade over 1e-6..1e9: (9 - -6) * 20 + 1 bounds
        assert len(DEFAULT_BOUNDS) == 15 * 20 + 1
        assert DEFAULT_BOUNDS[0] == pytest.approx(1e-6)
        assert DEFAULT_BOUNDS[-1] == pytest.approx(1e9)
