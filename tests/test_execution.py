"""Execution-layer correctness: every parallelism path must match the
single-device model numerically (SURVEY.md §5 race detection: "correctness
checks = numeric parity tests of sharded vs unsharded forward")."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from metis_tpu.execution import (
    DP, PP, TP,
    PlanArtifact,
    build_train_state,
    gpt_param_specs,
    make_pipeline_train_step,
    make_train_step,
    mesh_for_uniform_plan,
    microbatch_split,
    shard_params,
)
from metis_tpu.core.compat import shard_map
from metis_tpu.core.types import UniformPlan
from metis_tpu.models import GPTConfig, forward, init_params, next_token_loss

CFG = GPTConfig(vocab_size=256, seq_len=32, hidden=64, num_heads=4,
                num_blocks=4, ffn_multiplier=2, dtype=jnp.float32)


@pytest.fixture(scope="module")
def data():
    key = jax.random.PRNGKey(0)
    tokens = jax.random.randint(jax.random.fold_in(key, 1), (8, CFG.seq_len), 0,
                                CFG.vocab_size)
    targets = jax.random.randint(jax.random.fold_in(key, 2), (8, CFG.seq_len), 0,
                                 CFG.vocab_size)
    params = init_params(jax.random.PRNGKey(42), CFG)
    return params, tokens, targets


def _mesh(shape, axes):
    devs = np.array(jax.devices()[: int(np.prod(shape))]).reshape(shape)
    return Mesh(devs, axes)


class TestGsmpdPath:
    def test_sharded_forward_matches_single_device(self, data):
        params, tokens, _ = data
        expected = forward(params, tokens, CFG)

        mesh = _mesh((2, 2), (DP, TP))
        specs = gpt_param_specs(CFG)
        sharded = shard_params(params, mesh, specs)
        with mesh:
            got = jax.jit(lambda p, t: forward(p, t, CFG))(sharded, tokens)
        np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                                   rtol=1e-4, atol=1e-4)

    def test_train_step_runs_and_reduces_loss(self, data):
        _, tokens, targets = data
        mesh = _mesh((2, 2), (DP, TP))
        state, _ = build_train_state(jax.random.PRNGKey(0), CFG, mesh)
        step = make_train_step(CFG, mesh)
        state, loss0 = step(state, tokens, targets)
        for _ in range(3):
            state, loss = step(state, tokens, targets)
        assert float(loss) < float(loss0)
        assert int(state.step) == 4

    def test_ring_attention_train_step(self, data):
        params, tokens, targets = data
        mesh = _mesh((2, 4), (DP, "sp"))
        # loss with ring attention (sequence sharded over 4) must match the
        # full-attention loss
        expected = next_token_loss(params, tokens, targets, CFG)
        from metis_tpu.ops import make_ring_attention

        ring = make_ring_attention(mesh, "sp")
        with mesh:
            got = jax.jit(
                lambda p, t, y: next_token_loss(p, t, y, CFG, ring)
            )(params, tokens, targets)
        np.testing.assert_allclose(float(got), float(expected), rtol=1e-4)


class TestPipelinePath:
    def test_pipeline_loss_matches_single_device(self, data):
        params, tokens, targets = data
        expected = float(next_token_loss(params, tokens, targets, CFG))

        mesh = _mesh((2, 2, 2), (PP, DP, TP))
        specs = gpt_param_specs(CFG, tp_axis=TP, pp_axis=PP)
        sharded = shard_params(params, mesh, specs)

        M = 4
        tok_mbs = microbatch_split(tokens, M)
        tgt_mbs = microbatch_split(targets, M)

        from metis_tpu.execution.pipeline import _pipeline_loss_local
        from functools import partial

        loss_fn = shard_map(
            partial(_pipeline_loss_local, cfg=CFG),
            mesh=mesh,
            in_specs=(specs, P(None, DP, None), P(None, DP, None)),
            out_specs=P(),
            check_vma=False)
        with mesh:
            got = float(jax.jit(loss_fn)(sharded, tok_mbs, tgt_mbs))
        assert got == pytest.approx(expected, rel=1e-4)

    def test_pipeline_grads_match_single_device(self, data):
        """The critical check: GPipe + manual TP collectives must produce the
        SAME gradients as the single-device model, leaf for leaf (loss parity
        alone masks transpose bugs — inflated grads still 'learn')."""
        params, tokens, targets = data
        ref_grads = jax.grad(next_token_loss)(params, tokens, targets, CFG)

        mesh = _mesh((2, 2, 2), (PP, DP, TP))
        M = 4
        init_fn, step = make_pipeline_train_step(CFG, mesh, M)
        del init_fn
        # reach inside: run the sharded grad computation on the same params
        from functools import partial

        from metis_tpu.execution.pipeline import _pipeline_loss_local

        specs = gpt_param_specs(CFG, tp_axis=TP, pp_axis=PP)
        sharded = shard_params(params, mesh, specs)
        grad_fn = shard_map(
            jax.value_and_grad(partial(_pipeline_loss_local, cfg=CFG)),
            mesh=mesh,
            in_specs=(specs, P(None, DP, None), P(None, DP, None)),
            out_specs=(P(), specs))
        with mesh:
            _, grads = jax.jit(grad_fn)(
                sharded, microbatch_split(tokens, M), microbatch_split(targets, M))
        flat_got = jax.tree_util.tree_flatten_with_path(grads)[0]
        flat_ref = jax.tree_util.tree_flatten_with_path(ref_grads)[0]
        for (path, g), (_, rg) in zip(flat_got, flat_ref):
            np.testing.assert_allclose(
                np.asarray(g), np.asarray(rg), rtol=2e-3, atol=2e-5,
                err_msg=jax.tree_util.keystr(path))

    def test_1f1b_loss_and_grads_match_single_device(self, data):
        """The 1F1B schedule must reproduce the single-device loss AND
        gradients exactly like GPipe does — the manual vjp stitching,
        ring-buffer reuse, and pp/dp grad reductions all hide silent
        corruption that loss parity alone would mask."""
        params, tokens, targets = data
        expected = float(next_token_loss(params, tokens, targets, CFG))
        ref_grads = jax.grad(next_token_loss)(params, tokens, targets, CFG)

        from functools import partial

        from metis_tpu.execution.pipeline import _pipeline_1f1b_local

        mesh = _mesh((2, 2, 2), (PP, DP, TP))
        M = 4
        specs = gpt_param_specs(CFG, tp_axis=TP, pp_axis=PP)
        sharded = shard_params(params, mesh, specs)
        fn = shard_map(
            partial(_pipeline_1f1b_local, cfg=CFG),
            mesh=mesh,
            in_specs=(specs, P(None, DP, None), P(None, DP, None)),
            out_specs=(P(), specs))
        tok_mbs = microbatch_split(tokens, M)
        tgt_mbs = microbatch_split(targets, M)
        with mesh:
            loss, grads = jax.jit(fn)(sharded, tok_mbs, tgt_mbs)
        assert float(loss) == pytest.approx(expected, rel=1e-4)
        flat = jax.tree_util.tree_flatten_with_path(grads)[0]
        ref_flat = dict(jax.tree_util.tree_flatten_with_path(ref_grads)[0])
        for path, g in flat:
            np.testing.assert_allclose(
                np.asarray(g), np.asarray(ref_flat[path]),
                rtol=2e-3, atol=2e-5, err_msg=jax.tree_util.keystr(path))

    def test_1f1b_many_microbatches_ring_reuse(self, data):
        """M=8 > R=2(S-1)+1=7 on a 4-stage pipeline exercises ring-slot
        wraparound.  Gradients (not just loss) must match: the loss path
        reads the ring only on the last stage, where the slot is written and
        consumed in the same tick — a clobbered slot on an earlier stage
        corrupts only that stage's gradients."""
        params, tokens, targets = data
        expected = float(next_token_loss(params, tokens, targets, CFG))
        ref_grads = jax.grad(next_token_loss)(params, tokens, targets, CFG)

        from functools import partial

        from metis_tpu.execution.pipeline import _pipeline_1f1b_local

        mesh = _mesh((4, 1, 2), (PP, DP, TP))
        specs = gpt_param_specs(CFG, tp_axis=TP, pp_axis=PP)
        sharded = shard_params(params, mesh, specs)
        fn = shard_map(
            partial(_pipeline_1f1b_local, cfg=CFG),
            mesh=mesh,
            in_specs=(specs, P(None, DP, None), P(None, DP, None)),
            out_specs=(P(), specs))
        with mesh:
            loss, grads = jax.jit(fn)(
                sharded, microbatch_split(tokens, 8),
                microbatch_split(targets, 8))
        assert float(loss) == pytest.approx(expected, rel=1e-4)
        flat = jax.tree_util.tree_flatten_with_path(grads)[0]
        ref_flat = dict(jax.tree_util.tree_flatten_with_path(ref_grads)[0])
        for path, g in flat:
            np.testing.assert_allclose(
                np.asarray(g), np.asarray(ref_flat[path]),
                rtol=2e-3, atol=2e-5, err_msg=jax.tree_util.keystr(path))

    def test_interleaved_loss_and_grads_match_single_device(self, data):
        """Interleaved (virtual-stage) schedule: device-major block
        permutation + grouped schedule + wraparound rings must reproduce the
        single-device loss AND gradients (compared through the layout
        permutation)."""
        params, tokens, targets = data
        expected = float(next_token_loss(params, tokens, targets, CFG))
        ref_grads = jax.grad(next_token_loss)(params, tokens, targets, CFG)

        from functools import partial

        from metis_tpu.execution.pipeline import (
            _pipeline_interleaved_local,
            interleave_block_order,
        )

        mesh = _mesh((2, 2, 2), (PP, DP, TP))
        vs = 2  # CFG has 4 blocks: 2 stages x 2 virtual chunks x 1 block
        order = np.asarray(interleave_block_order(CFG.num_blocks, 2, vs))
        permuted = {**params, "blocks": jax.tree.map(
            lambda a: a[order], params["blocks"])}
        specs = gpt_param_specs(CFG, tp_axis=TP, pp_axis=PP)
        sharded = shard_params(permuted, mesh, specs)
        fn = shard_map(
            partial(_pipeline_interleaved_local, cfg=CFG, vs=vs),
            mesh=mesh,
            in_specs=(specs, P(None, DP, None), P(None, DP, None)),
            out_specs=(P(), specs))
        M = 4  # 2 groups of S=2
        with mesh:
            loss, grads = jax.jit(fn)(
                sharded, microbatch_split(tokens, M),
                microbatch_split(targets, M))
        assert float(loss) == pytest.approx(expected, rel=1e-4)
        # grads come back in the interleaved layout; undo it for comparison
        inv = np.argsort(order)
        grads = {**grads, "blocks": jax.tree.map(
            lambda a: np.asarray(a)[inv], grads["blocks"])}
        flat = jax.tree_util.tree_flatten_with_path(grads)[0]
        ref_flat = dict(jax.tree_util.tree_flatten_with_path(ref_grads)[0])
        for path, g in flat:
            np.testing.assert_allclose(
                np.asarray(g), np.asarray(ref_flat[path]),
                rtol=2e-3, atol=2e-5, err_msg=jax.tree_util.keystr(path))

    def test_interleaved_train_step_learns(self, data):
        _, tokens, targets = data
        mesh = _mesh((2, 2, 2), (PP, DP, TP))
        M = 4
        init_fn, step = make_pipeline_train_step(
            CFG, mesh, M, schedule="interleaved", virtual_stages=2)
        params, opt_state = init_fn(jax.random.PRNGKey(7))
        tok_mbs = microbatch_split(tokens, M)
        tgt_mbs = microbatch_split(targets, M)
        params, opt_state, loss0 = step(params, opt_state, tok_mbs, tgt_mbs)
        for _ in range(3):
            params, opt_state, loss = step(params, opt_state, tok_mbs, tgt_mbs)
        assert float(loss) < float(loss0)

    def test_1f1b_train_step_learns(self, data):
        _, tokens, targets = data
        mesh = _mesh((2, 2, 2), (PP, DP, TP))
        M = 4
        init_fn, step = make_pipeline_train_step(CFG, mesh, M,
                                                 schedule="1f1b")
        params, opt_state = init_fn(jax.random.PRNGKey(7))
        tok_mbs = microbatch_split(tokens, M)
        tgt_mbs = microbatch_split(targets, M)
        params, opt_state, loss0 = step(params, opt_state, tok_mbs, tgt_mbs)
        for _ in range(3):
            params, opt_state, loss = step(params, opt_state, tok_mbs, tgt_mbs)
        assert float(loss) < float(loss0)

    def test_pipeline_train_step_learns(self, data):
        _, tokens, targets = data
        mesh = _mesh((2, 2, 2), (PP, DP, TP))
        M = 4
        init_fn, step = make_pipeline_train_step(CFG, mesh, M)
        params, opt_state = init_fn(jax.random.PRNGKey(7))
        tok_mbs = microbatch_split(tokens, M)
        tgt_mbs = microbatch_split(targets, M)
        params, opt_state, loss0 = step(params, opt_state, tok_mbs, tgt_mbs)
        for _ in range(3):
            params, opt_state, loss = step(params, opt_state, tok_mbs, tgt_mbs)
        assert float(loss) < float(loss0)

    def test_uneven_blocks_rejected(self):
        mesh = _mesh((2, 2, 2), (PP, DP, TP))
        bad = GPTConfig(vocab_size=64, seq_len=8, hidden=32, num_heads=2,
                        num_blocks=3, dtype=jnp.float32)
        with pytest.raises(ValueError, match="divide evenly"):
            make_pipeline_train_step(bad, mesh, 2)

    @pytest.mark.parametrize("schedule", ["gpipe", "1f1b"])
    def test_uneven_block_counts_match_single_device(self, schedule):
        """An UNEVEN layer partition (block_counts) on the shard_map
        pipeline reproduces the single-device loss: the padded zero layers
        are masked to identity and the real blocks keep their global order
        (VERDICT r3 next-step 6 — 1f1b on partitions the even split
        rejects)."""
        cfg = GPTConfig(vocab_size=64, seq_len=8, hidden=32, num_heads=2,
                        num_blocks=3, dtype=jnp.float32)
        mesh = _mesh((2, 2, 1), (PP, DP, TP))
        M = 2
        tokens = jax.random.randint(jax.random.PRNGKey(7), (4, cfg.seq_len),
                                    0, cfg.vocab_size)
        init_fn, step = make_pipeline_train_step(
            cfg, mesh, M, schedule=schedule, block_counts=(2, 1))
        params, opt_state = init_fn(jax.random.PRNGKey(3))
        _, _, loss = step(params, opt_state, microbatch_split(tokens, M),
                          microbatch_split(tokens, M))
        # oracle: same seed, unpadded single-device params
        full = init_params(jax.random.PRNGKey(3), cfg)
        expected = float(next_token_loss(full, tokens, tokens, cfg))
        assert float(loss) == pytest.approx(expected, rel=1e-5)

    def test_uneven_interleaved_rejected(self):
        cfg = GPTConfig(vocab_size=64, seq_len=8, hidden=32, num_heads=2,
                        num_blocks=3, dtype=jnp.float32)
        mesh = _mesh((2, 2, 1), (PP, DP, TP))
        with pytest.raises(ValueError, match="even block split"):
            make_pipeline_train_step(cfg, mesh, 2, schedule="interleaved",
                                     block_counts=(2, 1))

    def test_uneven_1f1b_trains_and_pads_are_inert(self):
        """Training steps under an uneven 1f1b split reduce the loss and
        never move the padded zero layers (their grads are masked out)."""
        cfg = GPTConfig(vocab_size=64, seq_len=8, hidden=32, num_heads=2,
                        num_blocks=3, dtype=jnp.float32)
        mesh = _mesh((2, 2, 1), (PP, DP, TP))
        M = 2
        init_fn, step = make_pipeline_train_step(
            cfg, mesh, M, schedule="1f1b", block_counts=(2, 1))
        params, opt_state = init_fn(jax.random.PRNGKey(0))
        toks = jax.random.randint(jax.random.PRNGKey(1), (4, cfg.seq_len),
                                  0, cfg.vocab_size)
        tok_mbs = microbatch_split(toks, M)
        loss0 = None
        for _ in range(8):
            params, opt_state, loss = step(params, opt_state, tok_mbs,
                                           tok_mbs)
            loss0 = loss0 if loss0 is not None else float(loss)
        assert float(loss) < loss0
        # pad slot (stage 1, second slot = padded index 3) stayed zero
        pad = jax.tree.leaves(
            jax.tree.map(lambda a: np.abs(np.asarray(a[3])).max(),
                         params["blocks"]))
        assert max(pad) == 0.0


class TestPlanArtifact:
    def test_roundtrip(self):
        art = PlanArtifact.from_uniform_plan(
            UniformPlan(dp=2, pp=2, tp=2, mbs=2, gbs=16))
        back = PlanArtifact.from_json(art.to_json())
        assert back == art
        assert back.mesh_shape == (2, 2, 2)
        assert back.microbatches == 4

    def test_mesh_emission(self):
        plan = UniformPlan(dp=2, pp=2, tp=2, mbs=2, gbs=16)
        mesh = mesh_for_uniform_plan(plan)
        assert mesh.shape == {"pp": 2, "dp": 2, "tp": 2}


class TestCommOverlap:
    """The overlap schedule's correctness bar: double-buffered boundary
    sends and the chunked dp all-reduce must reproduce the LOCKSTEP
    schedule's loss and gradients (``pipeline.py`` "Communication
    overlap") — both legs run the same collectives in the same arithmetic
    association, only the issue order moves."""

    def _grads(self, body, data, overlap, mesh_shape=(2, 2, 2), M=4,
               **body_kw):
        from functools import partial

        params, tokens, targets = data
        mesh = _mesh(mesh_shape, (PP, DP, TP))
        specs = gpt_param_specs(CFG, tp_axis=TP, pp_axis=PP)
        sharded = shard_params(params, mesh, specs)
        fn = shard_map(
            partial(body, cfg=CFG, overlap=overlap, **body_kw),
            mesh=mesh,
            in_specs=(specs, P(None, DP, None), P(None, DP, None)),
            out_specs=(P(), specs))
        with mesh:
            loss, grads = jax.jit(fn)(
                sharded, microbatch_split(tokens, M),
                microbatch_split(targets, M))
        return float(loss), jax.tree.map(np.asarray, grads)

    def _assert_parity(self, ref, got):
        assert got[0] == pytest.approx(ref[0], rel=1e-6)
        flat_ref = jax.tree_util.tree_flatten_with_path(ref[1])[0]
        flat_got = dict(jax.tree_util.tree_flatten_with_path(got[1])[0])
        for path, rg in flat_ref:
            np.testing.assert_allclose(
                flat_got[path], rg, rtol=1e-6, atol=1e-8,
                err_msg=jax.tree_util.keystr(path))

    def test_gpipe_overlap_grads_match_lockstep(self, data):
        from functools import partial

        from metis_tpu.execution.pipeline import _pipeline_loss_local

        def body(params, tok, tgt, *, cfg, overlap):
            return jax.value_and_grad(partial(
                _pipeline_loss_local, cfg=cfg, overlap=overlap))(
                    params, tok, tgt)

        ref = self._grads(body, data, overlap=False)
        got = self._grads(body, data, overlap=True)
        self._assert_parity(ref, got)

    def test_1f1b_overlap_grads_match_lockstep(self, data, monkeypatch):
        from metis_tpu.execution import train as _train
        from metis_tpu.execution.pipeline import _pipeline_1f1b_local

        # small chunks so the chunked dp all-reduce actually splits leaves
        monkeypatch.setattr(_train, "DP_CHUNK_ELEMS", 64)
        ref = self._grads(_pipeline_1f1b_local, data, overlap=False)
        got = self._grads(_pipeline_1f1b_local, data, overlap=True)
        self._assert_parity(ref, got)

    @pytest.mark.slow  # redundant leg: 1f1b parity above is the tier-1 pin
    def test_1f1b_overlap_ring_reuse_parity(self, data, monkeypatch):
        """M=8 on a 4-stage pipeline: the hoisted top-of-body permutes must
        stay value-identical through ring-slot wraparound too."""
        from metis_tpu.execution import train as _train
        from metis_tpu.execution.pipeline import _pipeline_1f1b_local

        monkeypatch.setattr(_train, "DP_CHUNK_ELEMS", 64)
        ref = self._grads(_pipeline_1f1b_local, data, overlap=False,
                          mesh_shape=(4, 1, 2), M=8)
        got = self._grads(_pipeline_1f1b_local, data, overlap=True,
                          mesh_shape=(4, 1, 2), M=8)
        self._assert_parity(ref, got)

    @pytest.mark.slow  # redundant leg: gpipe+1f1b parity are the tier-1 pins
    def test_interleaved_overlap_grads_match_lockstep(self, data,
                                                      monkeypatch):
        from metis_tpu.execution import train as _train
        from metis_tpu.execution.pipeline import _pipeline_interleaved_local

        monkeypatch.setattr(_train, "DP_CHUNK_ELEMS", 64)
        ref = self._grads(_pipeline_interleaved_local, data, overlap=False,
                          vs=2)
        got = self._grads(_pipeline_interleaved_local, data, overlap=True,
                          vs=2)
        self._assert_parity(ref, got)

    def test_chunked_pmean_matches_whole_leaf(self):
        from metis_tpu.execution.train import chunked_pmean

        mesh = _mesh((4,), (DP,))
        tree = {"a": jnp.arange(120, dtype=jnp.float32).reshape(8, 15),
                "b": jnp.ones((4,), jnp.float32)}

        def body(t):
            return (chunked_pmean(t, DP, 16),
                    jax.tree.map(lambda g: jax.lax.pmean(g, DP), t))

        with mesh:
            chunked, whole = jax.jit(shard_map(
                body, mesh=mesh, in_specs=(P(DP),),
                out_specs=(P(DP), P(DP))))(tree)
        for k in tree:
            np.testing.assert_array_equal(np.asarray(chunked[k]),
                                          np.asarray(whole[k]))

    def test_pipeline_overlap_event_emitted(self):
        import io
        import json

        from metis_tpu.core.events import EventLog

        cfg = GPTConfig(vocab_size=64, seq_len=8, hidden=16, num_heads=2,
                        num_blocks=2, ffn_multiplier=2, dtype=jnp.float32)
        mesh = _mesh((2, 2, 2), (PP, DP, TP))
        buf = io.StringIO()
        make_pipeline_train_step(cfg, mesh, 4, schedule="1f1b",
                                 events=EventLog(stream=buf))
        events = [json.loads(l) for l in buf.getvalue().splitlines()]
        ov = [e for e in events if e["event"] == "pipeline_overlap"]
        assert len(ov) == 1
        assert ov[0]["schedule"] == "1f1b"
        assert ov[0]["dp_chunk_elems"] > 0

    def test_no_overlap_event_when_lockstep(self):
        import io

        cfg = GPTConfig(vocab_size=64, seq_len=8, hidden=16, num_heads=2,
                        num_blocks=2, ffn_multiplier=2, dtype=jnp.float32)
        from metis_tpu.core.events import EventLog

        mesh = _mesh((2, 2, 2), (PP, DP, TP))
        buf = io.StringIO()
        make_pipeline_train_step(cfg, mesh, 4, overlap=False,
                                 events=EventLog(stream=buf))
        assert "pipeline_overlap" not in buf.getvalue()
