"""Live plan migration units: layout pricing, the additive cost term's
gating, reshard planning/verification, and the serve-cache fingerprint.
The end-to-end supervisor and fleet legs live in tests/test_resilience.py;
the ranking byte-identity invariants live in the search-regression gate
(tools/check_search_regression.py, run by tests/test_parallel_search.py).
"""
import jax.numpy as jnp
import pytest

from metis_tpu.cluster import ClusterSpec
from metis_tpu.core.config import SearchConfig
from metis_tpu.core.errors import MigrationError
from metis_tpu.cost.estimator import EstimatorOptions
from metis_tpu.cost.volume import TransformerVolume
from metis_tpu.execution.reshard import (
    execute_reshard,
    layout_moved_bytes,
    plan_reshard,
    price_migration_ms,
)
from metis_tpu.obs.ledger import query_fingerprint
from metis_tpu.profiles import synthesize_profiles, tiny_test_model
from metis_tpu.resilience.faults import FaultInjector


@pytest.fixture(scope="module")
def volume():
    model = tiny_test_model()
    store = synthesize_profiles(model, ["A100"], tps=[1, 2],
                                bss=[1, 2, 4, 8])
    return TransformerVolume(model, store.model.params_per_layer_bytes)


class TestLayoutPricing:
    def test_identical_layout_moves_nothing(self, volume):
        layout = ((1, 0, 5), (1, 5, 10))
        assert layout_moved_bytes(layout, layout, volume) == 0
        assert price_migration_ms(layout, layout, volume) == 0.0

    def test_repartition_at_same_tp_is_resident(self, volume):
        """A layer stays resident when some old stage held it at the same
        tp — moving the stage boundary alone costs nothing."""
        old = ((1, 0, 5), (1, 5, 10))
        new = ((1, 0, 3), (1, 3, 10))
        assert layout_moved_bytes(old, new, volume) == 0

    def test_tp_change_moves_those_layers(self, volume):
        old = ((1, 0, 5), (1, 5, 10))
        new = ((2, 0, 5), (1, 5, 10))
        expected = sum(volume.parameter_bytes_per_layer(2)[:5])
        assert layout_moved_bytes(old, new, volume) == pytest.approx(
            expected)

    def test_price_scales_inversely_with_bandwidth(self, volume):
        old = ((1, 0, 10),)
        new = ((2, 0, 10),)
        slow = price_migration_ms(old, new, volume, bw_gbps=50.0)
        fast = price_migration_ms(old, new, volume, bw_gbps=100.0)
        assert slow == pytest.approx(2.0 * fast)
        assert fast > 0.0


class TestCostTermGating:
    def test_config_validation(self):
        with pytest.raises(ValueError):
            SearchConfig(gbs=8, migration_bw_gbps=0.0)
        with pytest.raises(ValueError):
            SearchConfig(gbs=8, migration_amortize_steps=0)

    def test_migration_active_gates(self):
        base = dict(gbs=8, migrate_from=((1, 0, 5), (1, 5, 10)))
        on = EstimatorOptions.from_config(SearchConfig(**base))
        assert on.migration_active
        off = EstimatorOptions.from_config(
            SearchConfig(**base, use_migration_model=False))
        assert not off.migration_active
        strict = EstimatorOptions.from_config(
            SearchConfig(**base, strict_compat=True))
        assert not strict.migration_active
        fresh = EstimatorOptions.from_config(SearchConfig(gbs=8))
        assert not fresh.migration_active

    def test_migrate_from_changes_query_fingerprint(self):
        """A replan that carries the incumbent layout must never hit the
        fresh search's cache entry."""
        model = tiny_test_model()
        cluster = ClusterSpec.of(("A100", 2, 4))
        fresh = query_fingerprint(model, cluster, SearchConfig(gbs=8))
        moved = query_fingerprint(
            model, cluster,
            SearchConfig(gbs=8, migrate_from=((1, 0, 5), (1, 5, 10))))
        assert fresh != moved


class TestReshard:
    def _state(self):
        return {"w": jnp.arange(12, dtype=jnp.float32).reshape(3, 4),
                "b": jnp.zeros((4,), dtype=jnp.float32)}

    def test_plan_reshard_identical_state_is_resident(self):
        state = self._state()
        moved, leaves, moved_bytes = plan_reshard(state, self._state())
        assert leaves == 2
        assert moved == [] and moved_bytes == 0

    def test_plan_reshard_rejects_structure_mismatch(self):
        state = self._state()
        with pytest.raises(MigrationError):
            plan_reshard(state, {"w": state["w"]})
        bad_shape = dict(state, w=jnp.zeros((4, 3), dtype=jnp.float32))
        with pytest.raises(MigrationError):
            plan_reshard(state, bad_shape)
        bad_dtype = dict(state, b=jnp.zeros((4,), dtype=jnp.int32))
        with pytest.raises(MigrationError):
            plan_reshard(state, bad_dtype)

    def test_execute_reshard_verifies_bit_identity(self):
        state = self._state()
        new_state, report = execute_reshard(state, self._state())
        assert report.verified and report.leaves == 2
        assert jnp.array_equal(new_state["w"], state["w"])

    def test_injected_verify_fault_raises_migration_error(self):
        """The ``reshard_verify`` injection point surfaces as the typed
        error the supervisor's fallback path catches."""
        state = self._state()
        faults = FaultInjector("reshard_verify@3", seed=0)
        with pytest.raises(MigrationError):
            execute_reshard(state, self._state(), step=3, faults=faults)
        # the budgeted fault is spent: the retry lands on step 4 clean
        new_state, report = execute_reshard(
            state, self._state(), step=3, faults=faults)
        assert report.verified
