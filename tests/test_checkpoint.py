"""Checkpoint/resume: exact resume equivalence, cross-mesh restore, and plan
artifact round-trips."""
import numpy as onp

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from metis_tpu.execution import (
    DP,
    TP,
    PlanArtifact,
    build_train_state,
    load_meta,
    load_plan,
    make_train_step,
    restore_checkpoint,
    save_checkpoint,
)
from metis_tpu.models import GPTConfig


def tiny_cfg():
    return GPTConfig(vocab_size=128, seq_len=16, hidden=32, num_heads=2,
                     num_blocks=2, dtype=jnp.float32)


def dp_tp_mesh(dp, tp):
    return Mesh(onp.array(jax.devices()[:dp * tp]).reshape(dp, tp), (DP, TP))


def batch(key, n=8):
    return jax.random.randint(key, (n, 16), 0, 128)


class TestTrainStateCheckpoint:
    def test_resume_is_bit_identical(self, tmp_path):
        """2 steps + save + restore + 2 steps == 4 uninterrupted steps."""
        cfg = tiny_cfg()
        mesh = dp_tp_mesh(4, 2)
        step = make_train_step(cfg, mesh)
        toks = [batch(jax.random.PRNGKey(i)) for i in range(4)]

        # uninterrupted
        state, _ = build_train_state(jax.random.PRNGKey(0), cfg, mesh)
        for t in toks:
            state, loss_ref = step(state, t, t)

        # interrupted at step 2
        state2, _ = build_train_state(jax.random.PRNGKey(0), cfg, mesh)
        for t in toks[:2]:
            state2, _ = step(state2, t, t)
        save_checkpoint(tmp_path / "ckpt", state2, mesh)

        fresh, _ = build_train_state(jax.random.PRNGKey(1), cfg, mesh)
        resumed = restore_checkpoint(tmp_path / "ckpt", fresh)
        assert int(resumed.step) == 2
        for t in toks[2:]:
            resumed, loss_res = step(resumed, t, t)

        assert int(resumed.step) == int(state.step) == 4
        np.testing.assert_array_equal(np.asarray(loss_res),
                                      np.asarray(loss_ref))
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)),
            state.params, resumed.params)

    def test_async_writer_resume_is_bit_identical(self, tmp_path):
        """AsyncCheckpointWriter: training continues while the write is in
        flight; after close() the checkpoint restores bit-identically."""
        from metis_tpu.execution.checkpoint import AsyncCheckpointWriter

        cfg = tiny_cfg()
        mesh = dp_tp_mesh(4, 2)
        step = make_train_step(cfg, mesh)
        toks = [batch(jax.random.PRNGKey(i)) for i in range(4)]

        state, _ = build_train_state(jax.random.PRNGKey(0), cfg, mesh)
        with AsyncCheckpointWriter() as writer:
            for t in toks[:2]:
                state, _ = step(state, t, t)
            writer.save(tmp_path / "ckpt", state, mesh)
            # keep training while the write drains in the background
            for t in toks[2:]:
                state, _ = step(state, t, t)
            snap_step2 = jax.device_get(state)  # step-4 state, for contrast

        fresh, _ = build_train_state(jax.random.PRNGKey(1), cfg, mesh)
        resumed = restore_checkpoint(tmp_path / "ckpt", fresh)
        assert int(resumed.step) == 2
        assert int(snap_step2.step) == 4
        # resume from the step-2 snapshot reproduces the uninterrupted run
        for t in toks[2:]:
            resumed, _ = step(resumed, t, t)
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)),
            state.params, resumed.params)

    def test_async_writer_back_to_back_saves(self, tmp_path):
        """A second save waits for + swaps the first; the final checkpoint
        wins and .tmp is gone."""
        from metis_tpu.execution.checkpoint import AsyncCheckpointWriter

        cfg = tiny_cfg()
        mesh = dp_tp_mesh(4, 2)
        step = make_train_step(cfg, mesh)
        state, _ = build_train_state(jax.random.PRNGKey(0), cfg, mesh)
        with AsyncCheckpointWriter() as writer:
            state, _ = step(state, batch(jax.random.PRNGKey(0)),
                            batch(jax.random.PRNGKey(0)))
            writer.save(tmp_path / "ckpt", state, mesh)
            state, _ = step(state, batch(jax.random.PRNGKey(1)),
                            batch(jax.random.PRNGKey(1)))
            writer.save(tmp_path / "ckpt", state, mesh)
        assert load_meta(tmp_path / "ckpt").step == 2
        assert not (tmp_path / "ckpt.tmp").exists()
        assert not (tmp_path / "ckpt.prev").exists()

    def test_async_writer_close_surfaces_write_failure(self, tmp_path):
        """Regression: close() must SURFACE a failed in-flight background
        save (as CheckpointWriteError naming the checkpoint path), not
        swallow it — and still release the underlying checkpointer."""
        from metis_tpu.core.errors import CheckpointWriteError
        from metis_tpu.execution.checkpoint import AsyncCheckpointWriter

        cfg = tiny_cfg()
        mesh = dp_tp_mesh(4, 2)
        state, _ = build_train_state(jax.random.PRNGKey(0), cfg, mesh)
        writer = AsyncCheckpointWriter()
        writer.save(tmp_path / "ckpt", state, mesh)

        closed = []
        real_close = writer._ckptr.close

        def tracked_close():
            closed.append(True)
            real_close()

        writer._ckptr.close = tracked_close
        writer._ckptr.wait_until_finished = lambda: (_ for _ in ()).throw(
            RuntimeError("disk on fire"))
        with pytest.raises(CheckpointWriteError) as exc:
            writer.close()
        assert "ckpt" in str(exc.value)
        assert "disk on fire" in str(exc.value)
        assert closed, "underlying checkpointer was not closed"
        # the failed write never swapped: no primary checkpoint appeared
        assert not (tmp_path / "ckpt").exists()

    def test_hetero_state_roundtrip(self, tmp_path):
        """The multi-mesh executor's per-stage state list checkpoints and
        restores bit-identically (2-stage non-uniform plan)."""
        from metis_tpu.execution import PlanArtifact
        from metis_tpu.execution.builder import build_executable
        from metis_tpu.execution.checkpoint import (
            restore_hetero_checkpoint,
            save_hetero_checkpoint,
        )

        cfg = tiny_cfg()
        art = PlanArtifact(
            mesh_axes=(), mesh_shape=(),
            layer_partition=(0, 2, cfg.num_profile_layers),
            strategies=({"dp": 2, "tp": 2}, {"dp": 4, "tp": 1}),
            gbs=8, microbatches=2)
        exe = build_executable(cfg, art)
        state = exe.init(jax.random.PRNGKey(0))
        toks = batch(jax.random.PRNGKey(1))
        state, _ = exe.step(state, toks, toks)
        save_hetero_checkpoint(tmp_path / "hc", state, step=1, plan=art)

        fresh = exe.init(jax.random.PRNGKey(9))
        restored = restore_hetero_checkpoint(tmp_path / "hc", fresh)
        assert load_meta(tmp_path / "hc").step == 1
        for (p, o), (rp, ro) in zip(state, restored):
            jax.tree.map(
                lambda a, b: np.testing.assert_array_equal(
                    np.asarray(a), np.asarray(b)), p, rp)
        # training continues from the restored state
        _, loss_a = exe.step(state, toks, toks)
        _, loss_b = exe.step(restored, toks, toks)
        assert float(loss_a) == pytest.approx(float(loss_b), rel=1e-6)

    def test_restore_onto_different_mesh(self, tmp_path):
        """A checkpoint written on (4, 2) restores onto (2, 4) — the elastic
        re-plan path: orbax reshards onto the target NamedShardings."""
        cfg = tiny_cfg()
        mesh_a = dp_tp_mesh(4, 2)
        state, _ = build_train_state(jax.random.PRNGKey(0), cfg, mesh_a)
        step = make_train_step(cfg, mesh_a)
        t = batch(jax.random.PRNGKey(9))
        state, _ = step(state, t, t)
        save_checkpoint(tmp_path / "ckpt", state, mesh_a)

        mesh_b = dp_tp_mesh(2, 4)
        fresh, _ = build_train_state(jax.random.PRNGKey(1), cfg, mesh_b)
        resumed = restore_checkpoint(tmp_path / "ckpt", fresh)
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)),
            state.params, resumed.params)
        # restored leaves carry mesh_b shardings, ready to step there
        tok_emb = resumed.params["embed"]["tok"]
        assert tok_emb.sharding.mesh.devices.shape == (2, 4)
        step_b = make_train_step(cfg, mesh_b)
        resumed, loss = step_b(resumed, t, t)
        assert np.isfinite(float(loss))

    def test_elastic_restore_onto_fewer_devices(self, tmp_path):
        """A checkpoint written on an 8-device (4, 2) mesh restores in a
        process that only HAS 4 devices (the saved mesh cannot exist) —
        the elastic shrink path after losing a slice (planner/replan.py).
        Runs the restore in a subprocess with
        --xla_force_host_platform_device_count=4; scalar leaves (step,
        optax count) must come back uncommitted, not pinned to the saved
        SingleDeviceSharding, so the next jitted step accepts the state."""
        import os
        import subprocess
        import sys

        cfg = tiny_cfg()
        mesh = dp_tp_mesh(4, 2)
        step = make_train_step(cfg, mesh)
        state, _ = build_train_state(jax.random.PRNGKey(0), cfg, mesh)
        t = batch(jax.random.PRNGKey(3))
        state, _ = step(state, t, t)
        save_checkpoint(tmp_path / "ckpt", state, mesh)

        script = f"""
import os, json
import numpy as onp
import jax, jax.numpy as jnp
from jax.sharding import Mesh
from metis_tpu.execution import (DP, TP, build_train_state, make_train_step,
                                 restore_checkpoint)
from metis_tpu.models import GPTConfig

assert len(jax.devices()) == 4, jax.devices()
cfg = GPTConfig(vocab_size=128, seq_len=16, hidden=32, num_heads=2,
                num_blocks=2, dtype=jnp.float32)
mesh = Mesh(onp.array(jax.devices()).reshape(2, 2), (DP, TP))
fresh, _ = build_train_state(jax.random.PRNGKey(1), cfg, mesh)
resumed = restore_checkpoint({str(tmp_path / "ckpt")!r}, fresh)
toks = jax.random.randint(jax.random.PRNGKey(3), (8, 16), 0, 128)
resumed, loss = make_train_step(cfg, mesh)(resumed, toks, toks)
print(json.dumps({{"step": int(resumed.step), "loss": float(loss)}}))
"""
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env = {**os.environ, "JAX_PLATFORMS": "cpu",
               "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
               "PYTHONPATH": repo}
        out = subprocess.run([sys.executable, "-c", script], env=env,
                             cwd=repo, capture_output=True, text=True,
                             timeout=300)
        assert out.returncode == 0, out.stderr[-2000:]
        import json as _json

        report = _json.loads(out.stdout.strip().splitlines()[-1])
        assert report["step"] == 2  # restored at 1, stepped once
        assert np.isfinite(report["loss"])

    def test_overwrite_cycle_and_prev_fallback(self, tmp_path):
        """Repeated saves to one dir never lose the prior checkpoint: a
        'crash' that leaves only the .prev backup still restores."""
        import shutil

        cfg = tiny_cfg()
        mesh = dp_tp_mesh(4, 2)
        step = make_train_step(cfg, mesh)
        state, _ = build_train_state(jax.random.PRNGKey(0), cfg, mesh)
        t = batch(jax.random.PRNGKey(0))
        save_checkpoint(tmp_path / "ckpt", state, mesh)
        state, _ = step(state, t, t)
        save_checkpoint(tmp_path / "ckpt", state, mesh)  # overwrite
        assert load_meta(tmp_path / "ckpt").step == 1

        # simulate a crash window: primary gone, .prev holds the last good
        (tmp_path / "ckpt").rename(tmp_path / "ckpt.prev")
        assert load_meta(tmp_path / "ckpt").step == 1
        fresh, _ = build_train_state(jax.random.PRNGKey(1), cfg, mesh)
        resumed = restore_checkpoint(tmp_path / "ckpt", fresh)
        assert int(resumed.step) == 1
        shutil.rmtree(tmp_path / "ckpt.prev")

    def test_meta_sidecar(self, tmp_path):
        cfg = tiny_cfg()
        mesh = dp_tp_mesh(4, 2)
        state, _ = build_train_state(jax.random.PRNGKey(0), cfg, mesh)
        save_checkpoint(tmp_path / "ckpt", state, mesh)
        meta = load_meta(tmp_path / "ckpt")
        assert meta.step == 0
        assert meta.mesh_axes == (DP, TP)
        assert meta.mesh_shape == (4, 2)


class TestPlanArtifact:
    def _hetero_result(self):
        from metis_tpu.cluster import ClusterSpec
        from metis_tpu.core.config import SearchConfig
        from metis_tpu.planner import plan_hetero
        from metis_tpu.profiles import synthesize_profiles, tiny_test_model

        model = tiny_test_model()
        store = synthesize_profiles(model, ["A100"], tps=[1, 2, 4],
                                    bss=[1, 2, 4, 8, 16])
        cluster = ClusterSpec.homogeneous("A100", 2, 4)
        return plan_hetero(cluster, store, model, SearchConfig(gbs=64))

    def test_ranked_plan_roundtrip(self, tmp_path):
        result = self._hetero_result()
        art = PlanArtifact.from_ranked_plan(result.best)
        art.save(tmp_path / "plan.json")
        back = PlanArtifact.load(tmp_path / "plan.json")
        assert back == art
        assert back.layer_partition == result.best.intra.layer_partition
        assert back.device_groups == result.best.inter.device_groups

    def test_uniform_stage_artifact_builds_mesh(self):
        result = self._hetero_result()
        # find a plan whose stages share one strategy shape
        for r in result.plans:
            art = PlanArtifact.from_ranked_plan(r)
            if art.mesh_shape:
                break
        else:
            pytest.skip("no rectangular plan found")
        need = int(onp.prod(art.mesh_shape))
        if need <= len(jax.devices()):
            mesh = art.build_mesh()
            assert mesh.axis_names == art.mesh_axes

    def test_artifact_names_every_plan_axis(self):
        """cp/ep plans get dedicated mesh axes — cp must NOT fold into dp
        (a consumer would shard the batch instead of the sequence)."""
        from types import SimpleNamespace
        from metis_tpu.core.types import InterStagePlan, IntraStagePlan, Strategy

        inter = InterStagePlan(node_sequence=("A100",), device_groups=(8,),
                               batches=4, gbs=64)
        intra = IntraStagePlan(
            strategies=(Strategy(dp=2, tp=1, cp=2, ep=2, zero=1),),
            layer_partition=(0, 10), memory_state=(), num_repartition=1)
        art = PlanArtifact.from_ranked_plan(
            SimpleNamespace(inter=inter, intra=intra))
        assert art.mesh_axes == ("pp", "dp", "ep", "sp", "tp")
        assert art.mesh_shape == (1, 1, 2, 2, 1)  # dp/ep=1, ep=2, sp(cp)=2
        assert art.strategies[0]["zero"] == 1

    def test_nonuniform_artifact_refuses_mesh(self):
        art = PlanArtifact(
            mesh_axes=(), mesh_shape=(), layer_partition=(0, 5, 10),
            strategies=({"dp": 4, "tp": 1}, {"dp": 2, "tp": 2}),
            gbs=64, microbatches=4,
            node_sequence=("A100",), device_groups=(4, 4))
        with pytest.raises(ValueError, match="non-uniform"):
            art.build_mesh()

    def test_checkpoint_carries_plan(self, tmp_path):
        cfg = tiny_cfg()
        mesh = dp_tp_mesh(4, 2)
        state, _ = build_train_state(jax.random.PRNGKey(0), cfg, mesh)
        result = self._hetero_result()
        art = PlanArtifact.from_ranked_plan(result.best)
        save_checkpoint(tmp_path / "ckpt", state, mesh, plan=art)
        assert load_plan(tmp_path / "ckpt") == art
        assert load_plan(tmp_path / "no-such-ckpt") is None


def test_block_layouts_compatible_legacy_format():
    """Legacy 'interleaved:<vs>' metas (written before pp was encoded in the
    layout string) are accepted iff vs matches AND the checkpoint's own mesh
    pp extent equals the expected pp — a same-vs/different-pp resume must
    still be refused (the interleave permutation depends on both)."""
    from metis_tpu.execution.checkpoint import (
        CheckpointMeta,
        block_layouts_compatible,
    )

    legacy = CheckpointMeta(step=1, mesh_axes=("pp", "dp"),
                            mesh_shape=(2, 4), block_layout="interleaved:3")
    assert block_layouts_compatible(legacy, "interleaved:2x3")
    assert not block_layouts_compatible(legacy, "interleaved:4x3")  # pp diff
    assert not block_layouts_compatible(legacy, "interleaved:2x2")  # vs diff
    assert not block_layouts_compatible(legacy, "canonical")

    # a legacy meta with no pp axis has pp extent 1
    legacy_nopp = CheckpointMeta(step=1, mesh_axes=("dp",), mesh_shape=(8,),
                                 block_layout="interleaved:2")
    assert block_layouts_compatible(legacy_nopp, "interleaved:1x2")
    assert not block_layouts_compatible(legacy_nopp, "interleaved:2x2")

    # new-format strings compare exactly; canonical matches only canonical
    new = CheckpointMeta(step=1, mesh_axes=("pp", "dp"), mesh_shape=(2, 4),
                         block_layout="interleaved:2x3")
    assert block_layouts_compatible(new, "interleaved:2x3")
    assert not block_layouts_compatible(new, "interleaved:2x2")
    canon = CheckpointMeta(step=1, mesh_axes=("dp",), mesh_shape=(8,),
                           block_layout="canonical")
    assert block_layouts_compatible(canon, "canonical")
    assert not block_layouts_compatible(canon, "interleaved:2x2")
