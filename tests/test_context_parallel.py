"""Context-parallel (ring attention) planning — cost model, memory split,
search integration.  Net-new TPU capability (SURVEY.md §5: the reference has
no long-context support of any kind)."""
import pytest

from metis_tpu.cluster import ClusterSpec, DeviceSpec, TpuClusterSpec, slice_from_name
from metis_tpu.core.config import ModelSpec, SearchConfig
from metis_tpu.core.types import InterStagePlan, Strategy
from metis_tpu.cost.context_parallel import (
    ActivationSplitModel,
    a2a_comm_bytes_per_layer,
    attention_layer_range,
    cp_candidates,
    cp_comm_ms,
    cp_ring_ms,
    ring_comm_bytes_per_layer,
)
from metis_tpu.cost import (
    EstimatorOptions,
    HeteroCostEstimator,
    HeteroScalarBandwidth,
    IciDcnBandwidth,
    TransformerVolume,
)
from metis_tpu.planner import plan_hetero, plan_tpu
from metis_tpu.profiles import synthesize_profiles, tiny_test_model


@pytest.fixture(scope="module")
def model():
    return tiny_test_model()


@pytest.fixture(scope="module")
def profiles(model):
    return synthesize_profiles(
        model, ["tpu_v5e"], tps=[1, 2, 4], bss=[1, 2, 4, 8, 16])


@pytest.fixture(scope="module")
def cluster():
    return ClusterSpec.of(
        ("tpu_v5e", 2, 4),
        overrides={"tpu_v5e": DeviceSpec("tpu_v5e", 16, 90, 25)})


class TestRingCommModel:
    def test_cp1_is_free(self, model):
        assert ring_comm_bytes_per_layer(model, 4, 1, 1) == 0.0
        assert cp_ring_ms(model, 4, 1, 1, 8, 90.0) == 0.0

    def test_volume_formula(self, model):
        # cp=4, tp=2: K/V elems = 2 * mbs * (S/4) * (H/2); per cp-1 step the
        # ring moves 2 rotations at the model dtype (fwd K/V + bwd K/V) plus
        # one at fp32 (the bwd dK/dV accumulators — _ring_flash_bwd).
        got = ring_comm_bytes_per_layer(model, mbs=2, cp=4, tp=2)
        kv_elems = 2 * 2 * (model.sequence_length // 4) * (model.hidden_size // 2)
        assert got == 3 * kv_elems * (2 * model.dtype_bytes + 4)

    def test_volume_formula_gqa(self):
        # grouped K/V: bytes scale by num_kv_heads / num_heads (the ring
        # rotates the unexpanded layout — ops/ring_attention.py)
        from metis_tpu.core.config import ModelSpec

        full = ModelSpec(name="m", num_layers=6, hidden_size=256,
                         sequence_length=128, vocab_size=512, num_heads=8,
                         family="llama")
        gqa = ModelSpec(name="m", num_layers=6, hidden_size=256,
                        sequence_length=128, vocab_size=512, num_heads=8,
                        num_kv_heads=2, family="llama")
        assert ring_comm_bytes_per_layer(gqa, 2, 4, 1) == pytest.approx(
            ring_comm_bytes_per_layer(full, 2, 4, 1) / 4)

    def test_ring_time_scales_inverse_bandwidth(self, model):
        slow = cp_ring_ms(model, 2, 2, 1, 8, 45.0)
        fast = cp_ring_ms(model, 2, 2, 1, 8, 90.0)
        assert slow == pytest.approx(2 * fast)

    def test_attention_layer_range_excludes_embed_head(self, model):
        L = model.num_layers
        assert attention_layer_range(model, 0, L) == L - 2
        assert attention_layer_range(model, 0, 1) == 0     # embed only
        assert attention_layer_range(model, L - 1, L) == 0  # head only
        assert attention_layer_range(model, 1, 3) == 2

    def test_cp_candidates_divide_sequence(self):
        assert cp_candidates(8, 1024) == [2, 4, 8]
        assert cp_candidates(8, 6) == [2]   # 4 does not divide 6
        assert cp_candidates(1, 1024) == []


class TestActivationSplit:
    def test_fit_recovers_affine_memory(self, profiles, model):
        # synthetic profiles are exactly affine in bs at fixed (type, tp)
        split = ActivationSplitModel(profiles).split("tpu_v5e", 1)
        assert split is not None
        static, slope = split
        m1 = profiles.get("tpu_v5e", 1, 1).layer_memory_mb
        m4 = profiles.get("tpu_v5e", 1, 4).layer_memory_mb
        for layer in range(model.num_layers):
            assert static[layer] + slope[layer] == pytest.approx(m1[layer], rel=1e-6)
            assert static[layer] + 4 * slope[layer] == pytest.approx(m4[layer], rel=1e-6)

    def test_cp_memory_between_static_and_full(self, profiles):
        asm = ActivationSplitModel(profiles)
        full = profiles.get("tpu_v5e", 1, 8).layer_memory_mb
        halved = asm.layer_memory_with_cp("tpu_v5e", 1, 8, 2)
        static, slope = asm.split("tpu_v5e", 1)
        for layer in range(len(full)):
            assert halved[layer] <= full[layer] + 1e-9
            assert halved[layer] == pytest.approx(
                static[layer] + 8 * slope[layer] / 2, rel=1e-6)

    def test_single_bs_point_falls_back_to_no_relief(self, model):
        lone = synthesize_profiles(model, ["tpu_v5e"], tps=[1], bss=[4])
        asm = ActivationSplitModel(lone)
        assert asm.split("tpu_v5e", 1) is None
        assert asm.layer_memory_with_cp("tpu_v5e", 1, 4, 4) == \
            lone.get("tpu_v5e", 1, 4).layer_memory_mb


class TestCpCostEstimation:
    def _cost(self, cluster, profiles, model, strategies, bandwidth=None):
        volume = TransformerVolume(model, profiles.model.params_per_layer_bytes)
        # serial collective pricing: this class pins the raw ring formulas;
        # the overlap-window pricing has its own suite (test_overlap.py)
        est = HeteroCostEstimator(
            cluster, profiles, volume,
            EstimatorOptions(use_overlap_model=False), bandwidth)
        plan = InterStagePlan(
            node_sequence=("tpu_v5e",), device_groups=(8,), batches=4, gbs=32)
        return est.get_cost(plan, strategies, (0, model.num_layers))

    def test_cp_shards_compute_adds_ring(self, cluster, profiles, model):
        """At a FIXED device count, trading dp for cp keeps the per-device
        token count (and so the marginal compute) equal and adds the ring
        comm on top — cp buys the MEMORY of long sequences, not speed.
        (Until round 5 this asserted cp2 < base: an artifact of the raw
        profile's per-call intercept making t(2*bs)/2 < t(bs); the affine
        smoothing of the bs axis removed it — ProfileStore.affine_view.)"""
        base = self._cost(cluster, profiles, model, (Strategy(dp=8, tp=1),))
        cp2 = self._cost(cluster, profiles, model, (Strategy(dp=4, tp=1, cp=2),))
        assert cp2.cp_comm_ms > 0
        assert base.cp_comm_ms == 0
        assert cp2.execution_ms >= base.execution_ms
        # exact decomposition: single stage, 4 microbatches => execution =
        # 4 * (smoothed_compute(mbs=2) / cp + ring) + per-program overhead;
        # cp_comm_ms is the ring's share of that total.
        smoothed, ovh = profiles.affine_view()
        compute = smoothed.get("tpu_v5e", 1, 2).total_time_ms
        assert cp2.execution_ms == pytest.approx(
            4 * compute / 2 + cp2.cp_comm_ms + ovh[("tpu_v5e", 1)],
            rel=1e-9)

    def test_cp_gradient_sync_spans_cp_axis(self, cluster, profiles, model):
        # dp=1, cp=8: weights replicated across all 8 ranks => gradient
        # all-reduce must NOT be free.
        cp8 = self._cost(cluster, profiles, model, (Strategy(dp=1, tp=1, cp=8),))
        assert cp8.dp_comm_ms > 0
        # exact: ring all-reduce over 8 ranks at the cp ring's bandwidth
        # (the 8-rank ring spans both 4-chip nodes => inter bw = 25 GB/s)
        volume = TransformerVolume(model, profiles.model.params_per_layer_bytes)
        params = volume.stage_parameter_bytes(1, 0, model.num_layers)
        assert cp8.dp_comm_ms == pytest.approx(
            2 * 7 / 8 * params / (25 * 1e6), rel=1e-9)

    def test_cp_on_tpu_ici_model(self, profiles, model):
        tpu = TpuClusterSpec(slices=(slice_from_name("v5e-8"),))
        plan = InterStagePlan(
            node_sequence=("tpu_v5e",), device_groups=(8,), batches=4, gbs=32)
        bw = IciDcnBandwidth(tpu, plan)
        assert bw.cp_bandwidth(0, Strategy(dp=4, tp=1, cp=2)) > 0
        cluster = tpu.as_cluster_spec(chips_per_node=4)
        cost = self._cost(
            cluster, profiles, model, (Strategy(dp=4, tp=1, cp=2),),
            bandwidth=lambda p: IciDcnBandwidth(tpu, p))
        assert cost.cp_comm_ms > 0


class TestUlyssesMode:
    def test_a2a_moves_less_than_ring(self, model):
        """Ulysses traffic scales (cp-1)/cp vs the ring's (cp-1): a2a must
        be strictly cheaper per layer at every cp > 1, by a growing factor."""
        for cp in (2, 4, 8):
            ring = ring_comm_bytes_per_layer(model, mbs=4, cp=cp, tp=1)
            a2a = a2a_comm_bytes_per_layer(model, mbs=4, cp=cp, tp=1)
            assert 0 < a2a < ring
        # exact: 8 tensors of mbs*(S/cp)*h bytes, (cp-1)/cp wire fraction
        assert a2a_comm_bytes_per_layer(model, 4, 4, 1) == pytest.approx(
            8 * 4 * (model.sequence_length // 4) * model.hidden_size
            * model.dtype_bytes * 3 / 4)

    def test_cp_comm_ms_dispatches_on_mode(self, model):
        ring = cp_comm_ms(model, 4, 4, 1, 8, 100.0, mode="ring")
        a2a = cp_comm_ms(model, 4, 4, 1, 8, 100.0, mode="a2a")
        assert ring == cp_ring_ms(model, 4, 4, 1, 8, 100.0)
        assert 0 < a2a < ring

    def test_estimator_prices_a2a_below_ring(self, cluster, profiles, model):
        volume = TransformerVolume(model, profiles.model.params_per_layer_bytes)
        est = HeteroCostEstimator(
            cluster, profiles, volume, EstimatorOptions(), None)
        plan = InterStagePlan(
            node_sequence=("tpu_v5e",), device_groups=(8,), batches=4, gbs=32)
        part = (0, model.num_layers)
        ring = est.get_cost(plan, (Strategy(dp=4, tp=1, cp=2),), part)
        a2a = est.get_cost(
            plan, (Strategy(dp=4, tp=1, cp=2, cp_mode="a2a"),), part)
        assert 0 < a2a.cp_comm_ms < ring.cp_comm_ms
        assert a2a.total_ms < ring.total_ms

    def test_search_yields_both_modes_and_prefers_a2a(
            self, cluster, profiles, model):
        """With heads % cp == 0 both modes are searched; identical compute +
        cheaper comm must rank the a2a family above its ring twin."""
        cfg = SearchConfig(gbs=32, enable_cp=True, max_cp_degree=4)
        result = plan_hetero(cluster, profiles, model, cfg, top_k=None)
        modes = {(s.cp, s.cp_mode) for p in result.plans
                 for s in p.intra.strategies if s.cp > 1}
        assert any(m == "a2a" for _, m in modes)
        assert any(m == "ring" for _, m in modes)
        by_key = {}
        for p in result.plans:
            s = p.intra.strategies[0]
            if s.cp > 1 and len(p.intra.strategies) == 1:
                key = (p.inter.device_groups, p.inter.batches,
                       s.dp, s.tp, s.cp)
                by_key.setdefault(key, {})[s.cp_mode] = p.cost.total_ms
        paired = [v for v in by_key.values() if len(v) == 2]
        assert paired, "no ring/a2a twin plans found"
        assert all(v["a2a"] < v["ring"] for v in paired)


class TestCpSearch:
    def test_enable_cp_yields_cp_families(self, cluster, profiles, model):
        cfg = SearchConfig(gbs=32, enable_cp=True, max_cp_degree=4)
        result = plan_hetero(cluster, profiles, model, cfg)
        cps = {s.cp for p in result.plans for s in p.intra.strategies}
        assert 1 in cps
        assert any(c > 1 for c in cps), "cp families missing from search"
        # every plan's stage device counts still cover the group
        for p in result.plans:
            for g, s in zip(p.inter.device_groups, p.intra.strategies):
                assert s.dp * s.tp * s.cp == g

    def test_cp_disabled_by_default(self, cluster, profiles, model):
        result = plan_hetero(cluster, profiles, model, SearchConfig(gbs=32))
        assert all(
            s.cp == 1 for p in result.plans for s in p.intra.strategies)

    def test_cp_search_on_tpu_cluster(self, profiles, model):
        tpu = TpuClusterSpec(slices=(slice_from_name("v5e-8"),))
        cfg = SearchConfig(gbs=32, enable_cp=True, max_cp_degree=2)
        result = plan_tpu(tpu, profiles, model, cfg)
        assert result.num_costed > 0
        cps = {s.cp for p in result.plans for s in p.intra.strategies}
        assert any(c > 1 for c in cps)

    def test_hetero_stages_stay_cp1(self, model):
        profiles = synthesize_profiles(
            model, ["tpu_v5e", "tpu_v4"], tps=[1, 2, 4], bss=[1, 2, 4, 8, 16])
        cluster = ClusterSpec.of(
            ("tpu_v5e", 1, 4), ("tpu_v4", 1, 4),
            overrides={
                "tpu_v5e": DeviceSpec("tpu_v5e", 16, 90, 25),
                "tpu_v4": DeviceSpec("tpu_v4", 32, 90, 25),
            })
        cfg = SearchConfig(gbs=32, enable_cp=True, max_cp_degree=4)
        result = plan_hetero(cluster, profiles, model, cfg)
        for p in result.plans:
            for stage_id, strat in enumerate(p.intra.strategies):
                r0, r1 = p.inter.stage_rank_range(stage_id)
                # mixed-type stage => no cp
                types = set()
                acc = 0
                for t in p.inter.node_sequence:
                    n = 4
                    for r in range(acc, acc + n):
                        if r0 <= r < r1:
                            types.add(t)
                    acc += n
                if len(types) > 1:
                    assert strat.cp == 1
