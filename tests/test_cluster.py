import pytest

from metis_tpu.cluster import (
    ClusterSpec,
    DeviceSpec,
    NodeSpec,
    TpuClusterSpec,
    TpuSliceSpec,
    slice_from_name,
)
from metis_tpu.core.errors import ClusterSpecError


def make_hetero_cluster() -> ClusterSpec:
    """8xA100 + 8xT4, 4 per node — the reference golden-run topology
    (results/hetero_cost_model:1-29)."""
    return ClusterSpec.of(
        ("T4", 2, 4),
        ("A100", 2, 4),
        overrides={
            "T4": DeviceSpec("T4", 15, intra_bw_gbps=50, inter_bw_gbps=10),
            "A100": DeviceSpec("A100", 80, intra_bw_gbps=46, inter_bw_gbps=10),
        },
    )


class TestClusterSpec:
    def test_counts(self):
        c = make_hetero_cluster()
        assert c.total_devices == 16
        assert c.num_nodes == 4
        assert c.devices_per_node == 4
        assert c.num_devices_by_type("A100") == 8
        assert set(c.device_types) == {"A100", "T4"}

    def test_memory_mb_convention(self):
        c = make_hetero_cluster()
        assert c.memory_mb("A100") == 80 * 1024

    def test_inter_bw_strict_compat_reads_intra(self):
        # Reference bug: inter getter returns intra field (gpu_cluster.py:56-58).
        c = make_hetero_cluster()
        assert c.inter_bw_for_types(["A100", "T4"], strict_compat=True) == 46
        assert c.inter_bw_for_types(["A100", "T4"], strict_compat=False) == 10

    def test_rank_to_node(self):
        c = make_hetero_cluster()
        assert c.node_of_rank(0) == 0
        assert c.node_of_rank(7) == 1
        assert c.node_of_rank(15) == 3

    def test_from_files(self, tmp_path):
        (tmp_path / "hostfile").write_text(
            "10.0.0.1 slots=16\n10.0.0.2 slots=16\n")
        (tmp_path / "cluster.json").write_text(
            '{"10.0.0.1": {"instance_type": "A100", "inter_bandwidth": 10,'
            ' "intra_bandwidth": 50, "memory": 80},'
            ' "10.0.0.2": {"instance_type": "T4", "inter_bandwidth": 10,'
            ' "intra_bandwidth": 50, "memory": 15}}')
        c = ClusterSpec.from_files(tmp_path / "hostfile", tmp_path / "cluster.json")
        # multi-digit slots parse correctly (reference's [6:7] slice could not)
        assert c.total_devices == 32
        assert c.nodes[0].device_type == "A100"

    def test_empty_cluster_rejected(self):
        with pytest.raises(ClusterSpecError):
            ClusterSpec(nodes=(), devices={})


class TestTpuTopology:
    def test_slice_from_name(self):
        s = slice_from_name("v4-32")
        assert s.generation == "tpu_v4"
        assert s.num_chips == 32
        assert sorted(s.topology, reverse=True) == list(s.topology)

        s16 = slice_from_name("v5e-16")
        assert s16.topology == (4, 4)
        assert s16.wrap == (True, True)

    def test_axis_ring_bandwidth_doubles_on_wrap(self):
        s = TpuSliceSpec("tpu_v4", (4, 4, 2))
        assert s.axis_ring_bw_gbps(0) == 90  # wrapped 4-ring: both directions
        assert s.axis_ring_bw_gbps(2) == 45  # extent-2 axis: single link

    def test_wrong_dims_rejected(self):
        with pytest.raises(ClusterSpecError):
            TpuSliceSpec("tpu_v5e", (4, 4, 2))  # v5e is a 2D torus

    def test_hetero_tpu_cluster_lowering(self):
        # The BASELINE north-star topology: v4-32 + v5e-16 over DCN.
        tc = TpuClusterSpec(slices=(slice_from_name("v4-32"), slice_from_name("v5e-16")))
        assert tc.total_chips == 48
        assert tc.slice_of_rank(0) == 0
        assert tc.slice_of_rank(32) == 1

        c = tc.as_cluster_spec(chips_per_node=4)
        assert c.total_devices == 48
        assert set(c.device_types) == {"tpu_v4", "tpu_v5e"}
        assert c.memory_mb("tpu_v4") == 32 * 1024
        # DCN is the cross-slice bandwidth; ICI the within-slice one.
        assert c.inter_bw_for_types(["tpu_v4", "tpu_v5e"]) == 25
        assert c.intra_bw_for_type("tpu_v4") == 45  # slowest axis: extent-2, unwrapped
