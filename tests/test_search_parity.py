"""Oracle parity: our search-space enumeration vs the upstream reference's,
with the reference modules imported at test time (never vendored).

These tests pin the *observable search space* — same device-group
arrangements, same inter-stage plan set — while the implementations differ
(SURVEY.md §7: algorithms preserved, mechanisms replaced).
"""
import sys

import pytest

from metis_tpu.search import (
    enumerate_device_groups,
    inter_stage_plans,
    uniform_plans,
)


@pytest.fixture(scope="module")
def ref(reference_root):
    sys.path.insert(0, str(reference_root))
    try:
        import search_space.device_group as ref_dg
        import search_space.plan as ref_plan
        yield {"dg": ref_dg, "plan": ref_plan}
    finally:
        sys.path.remove(str(reference_root))


@pytest.mark.parametrize("stages,devices,variance,cap", [
    (1, 16, 1.0, 6),
    (2, 16, 1.0, 6),
    (3, 16, 1.0, 6),
    (4, 16, 1.0, 6),
    (6, 16, 1.0, 6),
    (2, 8, 0.5, 4),
    (3, 8, 0.5, 4),
    (4, 8, 1.0, 2),
    (5, 32, 1.0, 6),
])
def test_device_group_parity(ref, stages, devices, variance, cap):
    shapes = ref["dg"].gen_device_group_shapes(devices)
    theirs = ref["dg"].gen_dgroups_for_stages_with_variance(
        num_stages=stages, num_gpus=devices, group_shapes=shapes,
        variance=variance, max_permute_len=cap)
    ours = enumerate_device_groups(stages, devices, variance, cap)
    assert sorted(map(tuple, theirs)) == sorted(map(tuple, ours))


def test_inter_stage_plan_set_parity(ref):
    """Same (node_sequence, device_groups, num_stage, batches) set on the
    golden-run shape (16 devices, 2 types, gbs=128, 10 layers)."""
    gen = ref["plan"].InterStagePlanGenerator(
        device_types={"T4", "A100"}, num_devices=16, gbs=128, num_layers=10,
        variance=1, max_permute_len=6)
    theirs = set()
    for p in gen:
        theirs.add((tuple(p.node_sequence), tuple(p.device_groups), p.batches))

    ours = set()
    for p in inter_stage_plans(["T4", "A100"], 16, 128, 10,
                               variance=1, max_permute_len=6):
        ours.add((p.node_sequence, p.device_groups, p.batches))

    # Reference bug (documented deviation): advancing the node sequence resets
    # num_stage to 1 and then immediately increments it (plan.py:144-148), so
    # single-stage plans are enumerated for the FIRST node sequence only.  Our
    # space is a strict superset; every extra must be a single-stage plan.
    assert theirs <= ours
    extra = ours - theirs
    assert extra and all(len(groups) == 1 for (_, groups, _) in extra)


# ---------------------------------------------------------------------------
# Batched-vs-scalar costing parity (self-contained — no reference checkout):
# the array-native primary path (cost/batch.py) against the scalar estimator
# it demoted to parity oracle.  Property-style over two workloads and the
# degenerate plan shapes most likely to break table indexing.
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def hetero_eval(parity_fixture_dir):
    """CandidateEvaluator on the hetero parity workload (8xA100 + 8xT4)."""
    return _make_evaluator(parity_fixture_dir)


@pytest.fixture(scope="module")
def uniform_eval(tmp_path_factory):
    """CandidateEvaluator on a single-type (uniform) 8-device workload."""
    import json as _json

    from metis_tpu.profiles import synthesize_profiles, tiny_test_model

    d = tmp_path_factory.mktemp("uniform")
    synthesize_profiles(
        tiny_test_model(), ["A100"], tps=[1, 2, 4],
        bss=[1, 2, 4, 8, 16]).dump_to_dir(d / "profiles")
    (d / "hostfile").write_text("0.0.0.1 slots=4\n0.0.0.2 slots=4\n")
    (d / "clusterfile.json").write_text(_json.dumps({
        ip: {"instance_type": "A100", "inter_bandwidth": 10,
             "intra_bandwidth": 46, "memory": 80}
        for ip in ("0.0.0.1", "0.0.0.2")}))
    return _make_evaluator(d)


def _make_evaluator(fixture_dir, strict_compat=True, use_overlap_model=True):
    from metis_tpu.cluster.spec import ClusterSpec
    from metis_tpu.core.config import SearchConfig
    from metis_tpu.profiles import ProfileStore, tiny_test_model
    from metis_tpu.search.parallel import CandidateEvaluator

    cluster = ClusterSpec.from_files(
        fixture_dir / "hostfile", fixture_dir / "clusterfile.json")
    store = ProfileStore.from_dir(fixture_dir / "profiles")
    return CandidateEvaluator(
        cluster, store, tiny_test_model(),
        SearchConfig(gbs=128, strict_compat=strict_compat,
                     use_overlap_model=use_overlap_model))


def _candidate(node_sequence, device_groups, batches, strategies, partition):
    from metis_tpu.core.types import InterStagePlan, IntraStagePlan, Strategy

    inter = InterStagePlan(node_sequence=node_sequence,
                           device_groups=device_groups,
                           batches=batches, gbs=128)
    intra = IntraStagePlan(
        strategies=tuple(Strategy(dp=d, tp=t) for d, t in strategies),
        layer_partition=tuple(partition),
        memory_state=(), num_repartition=1)
    return inter, intra


_HETERO_SHAPES = [
    # one stage spanning the whole (mixed-type) cluster
    ("one_stage", (16,), 8, [(4, 4)], (0, 10)),
    # tp = full node slice (tp == slots-per-node == 4)
    ("tp_full_slice", (8, 8), 8, [(2, 4), (2, 4)], (0, 5, 10)),
    # a single-layer first stage
    ("one_layer_stage", (8, 8), 8, [(2, 4), (2, 4)], (0, 1, 10)),
    # microbatch above the profiled range -> both paths report a miss
    ("profile_miss", (16,), 1, [(4, 4)], (0, 10)),
]

_UNIFORM_SHAPES = [
    ("one_stage", (8,), 8, [(2, 4)], (0, 10)),
    ("tp_full_slice", (4, 4), 8, [(1, 4), (1, 4)], (0, 5, 10)),
    ("one_layer_stage", (4, 4), 8, [(1, 4), (1, 4)], (0, 1, 10)),
    ("profile_miss", (8,), 1, [(2, 4)], (0, 10)),
]


def _assert_batched_equals_scalar(ev, inter, intra):
    [batched] = ev.batch_estimator.cost_many(inter, [intra])
    try:
        scalar = ev.estimator.get_cost(
            inter, intra.strategies, intra.layer_partition,
            schedule=intra.schedule, virtual_stages=intra.virtual_stages)
    except KeyError:
        scalar = None
    # exact equality, not approx: the batched fast family is bit-identical
    # by contract, and misses must replay at the same candidates
    assert batched == scalar


@pytest.mark.parametrize(
    "shape", _HETERO_SHAPES, ids=[s[0] for s in _HETERO_SHAPES])
def test_batched_equals_scalar_hetero(hetero_eval, shape):
    _, groups, batches, strats, part = shape
    inter, intra = _candidate(("A100", "T4"), groups, batches, strats, part)
    _assert_batched_equals_scalar(hetero_eval, inter, intra)


@pytest.mark.parametrize(
    "shape", _UNIFORM_SHAPES, ids=[s[0] for s in _UNIFORM_SHAPES])
def test_batched_equals_scalar_uniform(uniform_eval, shape):
    _, groups, batches, strats, part = shape
    inter, intra = _candidate(("A100",), groups, batches, strats, part)
    _assert_batched_equals_scalar(uniform_eval, inter, intra)


# --- native mode (strict_compat off): the overlap-aware exposed-comm
# pricing is live, and the batched path must STILL be bit-identical to the
# scalar oracle — the exposed-window max() runs on identical floats.


@pytest.fixture(scope="module")
def hetero_native_eval(parity_fixture_dir):
    """Hetero parity workload with overlap pricing live."""
    return _make_evaluator(parity_fixture_dir, strict_compat=False)


@pytest.mark.parametrize(
    "shape", _HETERO_SHAPES, ids=[s[0] for s in _HETERO_SHAPES])
def test_batched_equals_scalar_hetero_native(hetero_native_eval, shape):
    _, groups, batches, strats, part = shape
    inter, intra = _candidate(("A100", "T4"), groups, batches, strats, part)
    _assert_batched_equals_scalar(hetero_native_eval, inter, intra)


def test_native_overlap_charges_at_most_serial(parity_fixture_dir,
                                               hetero_native_eval):
    """On a multi-stage dp>1 shape the exposed charge never exceeds the
    serial pricing of the same candidate (native mode, overlap on vs off),
    and everything the overlap model cannot touch (execution) is
    unchanged."""
    serial_eval = _make_evaluator(parity_fixture_dir, strict_compat=False,
                                  use_overlap_model=False)
    inter, intra = _candidate(
        ("A100", "T4"), (8, 8), 8, [(2, 4), (2, 4)], (0, 5, 10))
    [serial] = serial_eval.batch_estimator.cost_many(inter, [intra])
    [native] = hetero_native_eval.batch_estimator.cost_many(inter, [intra])
    assert native.execution_ms == serial.execution_ms
    assert native.dp_comm_ms <= serial.dp_comm_ms
    assert native.pp_comm_ms <= serial.pp_comm_ms
    assert native.total_ms <= serial.total_ms


@pytest.mark.parametrize("eval_fixture", ["hetero_eval", "uniform_eval"])
def test_grid_matches_scalar_oracle(eval_fixture, request):
    """The rtol-1e-9 grid-vs-oracle agreement, promoted from the standalone
    gate: every (device_type, tp, layer-range) on both workloads — including
    the empty range start == end the double loop sweeps through."""
    from tools.check_search_regression import _check_grid_oracle

    ev = request.getfixturevalue(eval_fixture)
    assert _check_grid_oracle(ev.cluster, ev.estimator.profiles) == []


def test_empty_candidate_batch(hetero_eval):
    """Empty batches are a no-op at both layers: ``cost_many`` returns []
    without touching tables, ``evaluate_batch`` yields nothing."""
    from metis_tpu.search.prune import SearchPruner

    inter, _ = _candidate(("A100", "T4"), (16,), 8, [(4, 4)], (0, 10))
    assert hetero_eval.batch_estimator.cost_many(inter, []) == []
    pruner = SearchPruner(hetero_eval.config, hetero_eval.cluster,
                          hetero_eval.estimator.profiles,
                          hetero_eval.model)
    assert list(hetero_eval.evaluate_batch([], pruner)) == []


# --- spot availability (use_spot_model): the expected_recovery charge is
# additive, reserved-fleets-invisible, and batched==scalar bit-identical.


@pytest.fixture(scope="module")
def spot_fixture_dir(tmp_path_factory):
    """The parity fixture with the T4 pool marked spot-tier."""
    from metis_tpu.testing import write_spot_parity_fixture

    d = tmp_path_factory.mktemp("spot")
    write_spot_parity_fixture(d)
    return d


@pytest.fixture(scope="module")
def hetero_spot_eval(spot_fixture_dir):
    """Hetero parity workload, T4 spot-tiered, spot pricing live."""
    return _make_evaluator(spot_fixture_dir, strict_compat=False)


@pytest.mark.parametrize(
    "shape", _HETERO_SHAPES, ids=[s[0] for s in _HETERO_SHAPES])
def test_batched_equals_scalar_hetero_spot(hetero_spot_eval, shape):
    """Bit-identity survives the spot term on every degenerate shape."""
    _, groups, batches, strats, part = shape
    inter, intra = _candidate(("A100", "T4"), groups, batches, strats, part)
    _assert_batched_equals_scalar(hetero_spot_eval, inter, intra)


@pytest.mark.parametrize(
    "shape", _HETERO_SHAPES, ids=[s[0] for s in _HETERO_SHAPES])
def test_reserved_only_recovery_is_zero(hetero_native_eval, shape):
    """On an all-reserved fleet the spot model (ON by default) charges
    exactly 0.0 — reserved searches stay byte-identical to pre-spot runs."""
    _, groups, batches, strats, part = shape
    inter, intra = _candidate(("A100", "T4"), groups, batches, strats, part)
    [cost] = hetero_native_eval.batch_estimator.cost_many(inter, [intra])
    if cost is not None:
        assert cost.expected_recovery_ms == 0.0


def test_spot_exposure_prices_recovery(hetero_spot_eval):
    """A plan touching the spot pool is charged; one confined to the
    reserved pool is not — even on the same spot-tiered cluster."""
    inter, intra = _candidate(("A100", "T4"), (16,), 8, [(4, 4)], (0, 10))
    [exposed] = hetero_spot_eval.batch_estimator.cost_many(inter, [intra])
    assert exposed.expected_recovery_ms > 0.0

    inter, intra = _candidate(("A100",), (8,), 8, [(2, 4)], (0, 10))
    [reserved] = hetero_spot_eval.batch_estimator.cost_many(inter, [intra])
    assert reserved.expected_recovery_ms == 0.0


def test_recovery_strictly_increases_with_hazard(spot_fixture_dir):
    """Doubling the spot pool's eviction rate doubles the charge (the term
    is linear in the plan's aggregate hazard)."""
    import dataclasses

    from metis_tpu.cluster.spec import ClusterSpec
    from metis_tpu.core.config import SearchConfig
    from metis_tpu.profiles import ProfileStore, tiny_test_model
    from metis_tpu.search.parallel import CandidateEvaluator

    cluster = ClusterSpec.from_files(
        spot_fixture_dir / "hostfile", spot_fixture_dir / "clusterfile.json")
    store = ProfileStore.from_dir(spot_fixture_dir / "profiles")
    spec = cluster.devices["T4"]
    hot = cluster.with_device_spec(dataclasses.replace(
        spec, preemption_rate_per_hr=2 * spec.preemption_rate_per_hr))
    inter, intra = _candidate(("A100", "T4"), (16,), 8, [(4, 4)], (0, 10))
    costs = []
    for c in (cluster, hot):
        ev = CandidateEvaluator(c, store, tiny_test_model(),
                                SearchConfig(gbs=128))
        [cost] = ev.batch_estimator.cost_many(inter, [intra])
        costs.append(cost)
    assert costs[1].expected_recovery_ms > costs[0].expected_recovery_ms > 0
    assert costs[1].expected_recovery_ms == pytest.approx(
        2 * costs[0].expected_recovery_ms, rel=1e-12)


def test_spot_components_sum_to_total(hetero_spot_eval):
    """The recovery term is additive: every CostBreakdown component,
    expected_recovery included, sums to the ranked total."""
    inter, intra = _candidate(("A100", "T4"), (8, 8), 8,
                              [(2, 4), (2, 4)], (0, 5, 10))
    [cost] = hetero_spot_eval.batch_estimator.cost_many(inter, [intra])
    parts = (cost.execution_ms + cost.fb_sync_ms + cost.optimizer_ms
             + cost.dp_comm_ms + cost.pp_comm_ms + cost.batch_gen_ms
             + cost.cp_comm_ms + cost.ep_comm_ms
             + cost.expected_recovery_ms)
    assert cost.expected_recovery_ms > 0.0
    assert parts == pytest.approx(cost.total_ms, rel=1e-12, abs=0.0)


def test_spot_model_off_matches_reserved(spot_fixture_dir, parity_fixture_dir):
    """use_spot_model=False on the spot-tiered fixture reproduces the
    reserved fixture's costs bit-for-bit (the fixtures differ only in
    availability metadata)."""
    from metis_tpu.cluster.spec import ClusterSpec
    from metis_tpu.core.config import SearchConfig
    from metis_tpu.profiles import ProfileStore, tiny_test_model
    from metis_tpu.search.parallel import CandidateEvaluator

    inter, intra = _candidate(("A100", "T4"), (16,), 8, [(4, 4)], (0, 10))
    costs = []
    for d, use_spot in ((spot_fixture_dir, False), (parity_fixture_dir, True)):
        cluster = ClusterSpec.from_files(
            d / "hostfile", d / "clusterfile.json")
        store = ProfileStore.from_dir(d / "profiles")
        ev = CandidateEvaluator(
            cluster, store, tiny_test_model(),
            SearchConfig(gbs=128, use_spot_model=use_spot))
        [cost] = ev.batch_estimator.cost_many(inter, [intra])
        costs.append(cost)
    assert costs[0] == costs[1]
    assert costs[0].expected_recovery_ms == 0.0


def test_uniform_plan_parity_exact_divisible_subset(ref):
    """Reference uniform plans admit ragged batch splits (gbs not divisible
    by dp*mbs — plan.py:84 truncates); ours require exact divisibility
    (documented deviation, search/uniform.py). Parity holds on the
    exactly-divisible subset at each gbs."""
    gen = ref["plan"].UniformPlanGenerator(num_devices=8, max_tp=4, max_gbs=32)
    theirs = set()
    for p in gen:
        if p.gbs % (p.dp * p.mbs) == 0 and p.gbs == 32:
            theirs.add((p.dp, p.pp, p.tp, p.mbs, p.gbs))

    ours = {
        (p.dp, p.pp, p.tp, p.mbs, p.gbs)
        for p in uniform_plans(num_devices=8, max_tp=4, gbs=32)
    }
    assert theirs <= ours
    extra = ours - theirs
    # anything we add beyond the reference must still be exactly divisible
    for dp, pp, tp, mbs, gbs in extra:
        assert gbs % (dp * mbs) == 0
