"""Oracle parity: our search-space enumeration vs the upstream reference's,
with the reference modules imported at test time (never vendored).

These tests pin the *observable search space* — same device-group
arrangements, same inter-stage plan set — while the implementations differ
(SURVEY.md §7: algorithms preserved, mechanisms replaced).
"""
import sys

import pytest

from metis_tpu.search import (
    enumerate_device_groups,
    inter_stage_plans,
    uniform_plans,
)


@pytest.fixture(scope="module")
def ref(reference_root):
    sys.path.insert(0, str(reference_root))
    try:
        import search_space.device_group as ref_dg
        import search_space.plan as ref_plan
        yield {"dg": ref_dg, "plan": ref_plan}
    finally:
        sys.path.remove(str(reference_root))


@pytest.mark.parametrize("stages,devices,variance,cap", [
    (1, 16, 1.0, 6),
    (2, 16, 1.0, 6),
    (3, 16, 1.0, 6),
    (4, 16, 1.0, 6),
    (6, 16, 1.0, 6),
    (2, 8, 0.5, 4),
    (3, 8, 0.5, 4),
    (4, 8, 1.0, 2),
    (5, 32, 1.0, 6),
])
def test_device_group_parity(ref, stages, devices, variance, cap):
    shapes = ref["dg"].gen_device_group_shapes(devices)
    theirs = ref["dg"].gen_dgroups_for_stages_with_variance(
        num_stages=stages, num_gpus=devices, group_shapes=shapes,
        variance=variance, max_permute_len=cap)
    ours = enumerate_device_groups(stages, devices, variance, cap)
    assert sorted(map(tuple, theirs)) == sorted(map(tuple, ours))


def test_inter_stage_plan_set_parity(ref):
    """Same (node_sequence, device_groups, num_stage, batches) set on the
    golden-run shape (16 devices, 2 types, gbs=128, 10 layers)."""
    gen = ref["plan"].InterStagePlanGenerator(
        device_types={"T4", "A100"}, num_devices=16, gbs=128, num_layers=10,
        variance=1, max_permute_len=6)
    theirs = set()
    for p in gen:
        theirs.add((tuple(p.node_sequence), tuple(p.device_groups), p.batches))

    ours = set()
    for p in inter_stage_plans(["T4", "A100"], 16, 128, 10,
                               variance=1, max_permute_len=6):
        ours.add((p.node_sequence, p.device_groups, p.batches))

    # Reference bug (documented deviation): advancing the node sequence resets
    # num_stage to 1 and then immediately increments it (plan.py:144-148), so
    # single-stage plans are enumerated for the FIRST node sequence only.  Our
    # space is a strict superset; every extra must be a single-stage plan.
    assert theirs <= ours
    extra = ours - theirs
    assert extra and all(len(groups) == 1 for (_, groups, _) in extra)


def test_uniform_plan_parity_exact_divisible_subset(ref):
    """Reference uniform plans admit ragged batch splits (gbs not divisible
    by dp*mbs — plan.py:84 truncates); ours require exact divisibility
    (documented deviation, search/uniform.py). Parity holds on the
    exactly-divisible subset at each gbs."""
    gen = ref["plan"].UniformPlanGenerator(num_devices=8, max_tp=4, max_gbs=32)
    theirs = set()
    for p in gen:
        if p.gbs % (p.dp * p.mbs) == 0 and p.gbs == 32:
            theirs.add((p.dp, p.pp, p.tp, p.mbs, p.gbs))

    ours = {
        (p.dp, p.pp, p.tp, p.mbs, p.gbs)
        for p in uniform_plans(num_devices=8, max_tp=4, gbs=32)
    }
    assert theirs <= ours
    extra = ours - theirs
    # anything we add beyond the reference must still be exactly divisible
    for dp, pp, tp, mbs, gbs in extra:
        assert gbs % (dp * mbs) == 0
