"""Flight-recorder layer (core/trace.py): spans, counters, heartbeats,
the report renderer, and the bench SectionRecorder's crash-proofness."""
import io
import json
import time

import pytest

from metis_tpu.core.events import EventLog, read_events
from metis_tpu.core.trace import (
    Counters,
    Heartbeat,
    NULL_SPAN,
    Tracer,
    build_span_tree,
    render_span_table,
    span_tree_json,
    timed_iter,
)


def _stream_tracer():
    buf = io.StringIO()
    return Tracer(EventLog(stream=buf)), buf


def _events(buf: io.StringIO) -> list[dict]:
    return [json.loads(line) for line in buf.getvalue().splitlines()]


class TestSpans:
    def test_nesting_paths_and_parents(self):
        tracer, buf = _stream_tracer()
        with tracer.span("root"):
            with tracer.span("child"):
                with tracer.span("grand"):
                    pass
            with tracer.span("sibling"):
                pass
        ends = [e for e in _events(buf) if e["event"] == "span_end"]
        by_name = {e["name"]: e for e in ends}
        assert by_name["grand"]["path"] == "root/child/grand"
        assert by_name["grand"]["parent_id"] == by_name["child"]["span_id"]
        assert by_name["child"]["parent_id"] == by_name["root"]["span_id"]
        assert by_name["sibling"]["parent_id"] == by_name["root"]["span_id"]
        assert by_name["root"]["parent_id"] is None
        # children close before parents
        names_in_order = [e["name"] for e in ends]
        assert names_in_order.index("grand") < names_in_order.index("child")
        assert names_in_order.index("child") < names_in_order.index("root")

    def test_durations_monotonic_and_nested(self):
        tracer, buf = _stream_tracer()
        with tracer.span("outer"):
            with tracer.span("inner"):
                time.sleep(0.02)
        ends = {e["name"]: e for e in _events(buf)
                if e["event"] == "span_end"}
        assert ends["inner"]["dur_ms"] >= 15.0
        assert ends["outer"]["dur_ms"] >= ends["inner"]["dur_ms"]

    def test_span_attrs_ride_on_end(self):
        tracer, buf = _stream_tracer()
        with tracer.span("s", model="gpt") as sp:
            sp.set(extra=7)
        end = [e for e in _events(buf) if e["event"] == "span_end"][0]
        assert end["model"] == "gpt" and end["extra"] == 7

    def test_begin_emitted_for_crash_evidence(self):
        """A span entered but never exited (crash) still leaves its
        span_begin in the log and shows up unclosed in the tree."""
        tracer, buf = _stream_tracer()
        span = tracer.span("doomed")
        span.__enter__()  # never exited
        roots, _ = build_span_tree(_events(buf))
        assert roots[0].name == "doomed" and not roots[0].closed
        assert "open" in render_span_table(roots, {})

    def test_disabled_tracer_is_noop(self):
        tracer = Tracer()
        assert tracer.span("x") is NULL_SPAN
        assert tracer.accum("y") is NULL_SPAN
        with tracer.span("x"):
            tracer.inc("n")
        tracer.emit_counters(scope="nowhere")  # must not raise
        assert not tracer.counters.as_dict()


class TestAccumSpans:
    def test_accumulates_across_entries(self):
        tracer, buf = _stream_tracer()
        with tracer.span("root"):
            acc = tracer.accum("phase")
            for _ in range(5):
                with acc:
                    pass
            acc.close()
        end = [e for e in _events(buf)
               if e["event"] == "span_end" and e["name"] == "phase"][0]
        assert end["entries"] == 5
        assert end["dur_ms"] >= 0

    def test_parent_exit_closes_forgotten_accum(self):
        tracer, buf = _stream_tracer()
        with tracer.span("root"):
            with tracer.accum("leaky"):
                pass
            # no close()
        names = [e["name"] for e in _events(buf)
                 if e["event"] == "span_end"]
        assert "leaky" in names

    def test_close_idempotent(self):
        tracer, buf = _stream_tracer()
        acc = tracer.accum("a")
        acc.close()
        acc.close()
        assert sum(1 for e in _events(buf)
                   if e["event"] == "span_end") == 1

    def test_timed_iter_charges_generator_pulls(self):
        tracer, buf = _stream_tracer()
        acc = tracer.accum("gen")
        out = list(timed_iter(iter(range(4)), acc))
        acc.close()
        assert out == [0, 1, 2, 3]
        end = [e for e in _events(buf) if e["event"] == "span_end"][0]
        assert end["entries"] == 5  # 4 items + the exhaustion pull


class TestCounters:
    def test_aggregation(self):
        c = Counters()
        c.inc("a")
        c.inc("a", 4)
        c.inc("b", 2)
        assert c.as_dict() == {"a": 5, "b": 2}
        assert c.get("a") == 5 and c.get("missing") == 0

    def test_emit_counters_event(self):
        tracer, buf = _stream_tracer()
        tracer.inc("costed", 3)
        tracer.emit_counters(scope="test", extra_field=1)
        ev = _events(buf)[0]
        assert ev["event"] == "counters" and ev["scope"] == "test"
        assert ev["counters"] == {"costed": 3}
        assert ev["extra_field"] == 1


class TestHeartbeat:
    def test_cadence_every_n_ticks(self):
        buf = io.StringIO()
        hb = Heartbeat(EventLog(stream=buf), every=10)
        for _ in range(35):
            hb.tick(best=1.0)
        beats = _events(buf)
        assert [b["n"] for b in beats] == [10, 20, 30]
        assert all(b["event"] == "search_progress" for b in beats)
        assert all("elapsed_s" in b and "per_s" in b for b in beats)
        assert all(b["best"] == 1.0 for b in beats)

    def test_bulk_ticks_and_disabled(self):
        buf = io.StringIO()
        hb = Heartbeat(EventLog(stream=buf), every=100)
        hb.tick(250)
        assert [b["n"] for b in _events(buf)] == [250]
        null_hb = Heartbeat(EventLog(), every=1)
        null_hb.tick()  # must not raise, must not count
        assert null_hb.n == 0


class TestEventLogHandle:
    def test_handle_stays_open_and_close_releases(self, tmp_path):
        p = tmp_path / "ev.jsonl"
        log = EventLog(p)
        log.emit("a", n=1)
        fh = log._fh
        assert fh is not None and not fh.closed
        log.emit("b", n=2)
        assert log._fh is fh  # no reopen per emit
        # line-buffered: both records already on disk, tail-able live
        assert [e["event"] for e in read_events(p)] == ["a", "b"]
        log.close()
        assert log._fh is None
        log.emit("c", n=3)  # emit after close reopens
        log.close()
        assert [e["event"] for e in read_events(p)] == ["a", "b", "c"]

    def test_context_manager(self, tmp_path):
        p = tmp_path / "ev.jsonl"
        with EventLog(p) as log:
            log.emit("x")
        assert log._fh is None
        assert read_events(p)[0]["event"] == "x"


class TestReport:
    def _sample_events(self):
        tracer, buf = _stream_tracer()
        with tracer.span("root", mode="test"):
            with tracer.span("setup"):
                pass
            acc = tracer.accum("work")
            for _ in range(3):
                with acc:
                    time.sleep(0.001)
            acc.close()
        tracer.inc("costed", 7)
        tracer.emit_counters(scope="root")
        return _events(buf)

    def test_tree_self_time_and_json(self):
        roots, counters = build_span_tree(self._sample_events())
        assert len(roots) == 1
        root = roots[0]
        assert [c.name for c in root.children] == ["setup", "work"]
        child_sum = sum(c.dur_ms for c in root.children)
        assert root.self_ms == pytest.approx(root.dur_ms - child_sum)
        assert counters == {"root": {"costed": 7}}
        js = span_tree_json(roots, counters)
        assert js["spans"][0]["name"] == "root"
        assert js["spans"][0]["attrs"]["mode"] == "test"
        assert {c["name"] for c in js["spans"][0]["children"]} == \
            {"setup", "work"}
        assert js["counters"]["root"]["costed"] == 7

    def test_render_table(self):
        roots, counters = build_span_tree(self._sample_events())
        table = render_span_table(roots, counters)
        assert "root" in table and "  work" in table
        assert "costed = 7" in table
        assert "100.0" in table  # root percent

    def test_cli_report_round_trip(self, tmp_path):
        from metis_tpu.planner.cli import main as cli_main

        ev_path = tmp_path / "ev.jsonl"
        ev_path.write_text("".join(
            json.dumps(e) + "\n" for e in self._sample_events()))
        out = tmp_path / "report.txt"
        rc = cli_main(["report", str(ev_path), "--output", str(out)])
        assert rc == 0
        assert "root" in out.read_text()
        out_json = tmp_path / "report.json"
        rc = cli_main(["report", str(ev_path), "--json",
                       "--output", str(out_json)])
        assert rc == 0
        parsed = json.loads(out_json.read_text())
        assert parsed["spans"][0]["name"] == "root"
        assert parsed["counters"]["root"]["costed"] == 7

    def test_cli_report_missing_file(self, tmp_path):
        from metis_tpu.planner.cli import main as cli_main

        assert cli_main(["report", str(tmp_path / "nope.jsonl")]) == 1


class TestPlannerIntegration:
    @pytest.fixture(scope="class")
    def run(self, tmp_path_factory):
        from metis_tpu.cluster import ClusterSpec
        from metis_tpu.core.config import SearchConfig
        from metis_tpu.planner import plan_hetero
        from metis_tpu.profiles import synthesize_profiles, tiny_test_model

        model = tiny_test_model()
        store = synthesize_profiles(model, ["A100", "T4"], tps=[1, 2, 4],
                                    bss=[1, 2, 4, 8, 16])
        cluster = ClusterSpec.of(("A100", 2, 4), ("T4", 1, 4))
        path = tmp_path_factory.mktemp("trace") / "events.jsonl"
        with EventLog(path) as log:
            result = plan_hetero(cluster, store, model,
                                 SearchConfig(gbs=64, progress_every=100),
                                 events=log)
        return result, read_events(path)

    def test_span_tree_covers_phases(self, run):
        _, events = run
        roots, _ = build_span_tree(events)
        root = next(r for r in roots if r.name == "plan_hetero")
        names = {c.name for c in root.children}
        assert {"setup", "enumeration", "intra_stage", "costing",
                "ranking"} <= names
        assert all(c.closed for c in root.children)

    def test_counters_reconcile_with_result(self, run):
        """The acceptance criterion: flight-recorder counters sum
        consistently with PlannerResult accounting."""
        result, events = run
        cnt = next(e for e in events if e["event"] == "counters")["counters"]
        assert cnt["costed"] == result.num_costed
        assert (cnt.get("pruned_profile_miss", 0)
                + cnt.get("pruned_inter_filter", 0)) == result.num_pruned
        assert (cnt.get("prune.doom", 0) + cnt.get("prune.bound", 0)
                + cnt.get("prune.beam", 0)) == result.num_bound_pruned
        assert cnt["inter_enumerated"] > 0

    def test_heartbeat_progression(self, run):
        result, events = run
        beats = [e for e in events if e["event"] == "search_progress"]
        assert beats, "a >100-candidate search must emit heartbeats"
        ns = [b["n"] for b in beats]
        assert ns == sorted(ns)
        costed = [b["num_costed"] for b in beats]
        assert costed == sorted(costed)
        # best-cost-so-far only improves
        bests = [b["best_cost_ms"] for b in beats
                 if b["best_cost_ms"] is not None]
        assert bests == sorted(bests, reverse=True)
        assert result.num_costed >= costed[-1]

    def test_uniform_planner_spans(self, tmp_path):
        from metis_tpu.cluster import ClusterSpec
        from metis_tpu.core.config import SearchConfig
        from metis_tpu.planner import plan_uniform
        from metis_tpu.profiles import synthesize_profiles, tiny_test_model

        model = tiny_test_model()
        store = synthesize_profiles(model, ["A100"], tps=[1, 2],
                                    bss=[1, 2, 4, 8, 16])
        cluster = ClusterSpec.of(("A100", 2, 4))
        path = tmp_path / "uniform.jsonl"
        with EventLog(path) as log:
            result = plan_uniform(cluster, store, model,
                                  SearchConfig(gbs=64), events=log)
        events = read_events(path)
        roots, counters = build_span_tree(events)
        root = next(r for r in roots if r.name == "plan_uniform")
        assert {"costing", "ranking"} <= {c.name for c in root.children}
        cnt = counters["plan_uniform"]
        assert cnt["costed"] == result.num_costed


class TestBenchSections:
    """bench.SectionRecorder: a section that raises (or a truncated run)
    still leaves every prior section's JSONL record on disk."""

    @pytest.fixture()
    def recorder(self, tmp_path):
        import bench

        return bench.SectionRecorder(path=tmp_path / "sections.jsonl")

    def test_section_flushed_the_moment_it_completes(self, recorder):
        record = {}
        recorder.run("one", lambda r: r.__setitem__("k", 1), record)
        lines = [json.loads(l) for l in
                 recorder.path.read_text().splitlines()]
        assert lines[-1]["section"] == "one"
        assert lines[-1]["status"] == "ok"
        assert lines[-1]["data"] == {"k": 1}

    def test_raising_section_keeps_prior_records(self, recorder):
        record = {}
        recorder.run("good", lambda r: r.__setitem__("x", 42), record)

        def boom(r):
            raise RuntimeError("section died")

        recorder.run("bad", boom, record)
        lines = [json.loads(l) for l in
                 recorder.path.read_text().splitlines()]
        assert [(l["section"], l["status"]) for l in lines] == [
            ("good", "ok"), ("bad", "error")]
        assert lines[0]["data"] == {"x": 42}  # prior record intact on disk
        assert "RuntimeError" in record["bad"]["error"]

    def test_deadline_skips_with_recorded_reason(self, tmp_path):
        import bench

        rec = bench.SectionRecorder(path=tmp_path / "s.jsonl",
                                    deadline_s=0.0)
        time.sleep(0.01)
        record = {}
        ran = []
        rec.run("late", lambda r: ran.append(1), record)
        assert not ran
        assert "skipped" in record["late"]
        line = json.loads(rec.path.read_text().splitlines()[0])
        assert line["status"] == "skipped"
        assert "BENCH_DEADLINE_S" in line["data"]["skipped"]

    @pytest.mark.slow  # ~90 s (bench subprocess) — the heaviest tier-1 test
    def test_truncated_bench_leaves_startup_record(self, tmp_path):
        """The acceptance criterion: an artificially truncated bench run
        (tiny deadline standing in for `timeout 5`) leaves >= 1
        completed-section record on disk — an empty-tail BENCH_r05-style
        loss is impossible by construction."""
        import os
        import subprocess
        import sys
        from pathlib import Path

        sections = tmp_path / "sections.jsonl"
        env = {**os.environ, "BENCH_DEADLINE_S": "0.01",
               "BENCH_SECTIONS_PATH": str(sections),
               "BENCH_OUT_PATH": str(tmp_path / "bench_out.json"),
               "BENCH_PROBE_LOG": str(tmp_path / "probe.jsonl"),
               "JAX_PLATFORMS": "cpu"}
        repo = Path(__file__).resolve().parent.parent
        proc = subprocess.run(
            [sys.executable, str(repo / "bench.py")], env=env,
            capture_output=True, text=True, timeout=300, cwd=str(repo))
        lines = [json.loads(l) for l in
                 sections.read_text().splitlines()]
        assert lines, "sidecar must exist even for a truncated run"
        assert lines[0]["section"] == "startup"
        assert lines[0]["status"] == "ok"
        # skipped sections carry their reason; the final stdout line is
        # assembled from whatever finished
        statuses = {l["section"]: l["status"] for l in lines}
        assert statuses.get("parity") == "skipped"
        headline = json.loads(proc.stdout.strip().splitlines()[-1])
        assert headline["sections"]["parity"] == "skipped"
